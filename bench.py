"""Round benchmark battery (driver-run on the real TPU chip).

Sections (each emits one JSON line as it completes; the final line is the
headline shallow-water metric with every section's record embedded under
``"metrics"``):

1. shallow-water headline config — reference BASELINE.md: 6.28 s on one
   P100, 111.95 s on one CPU socket (docs/shallow-water.rst there).
2. flash-attention MFU — Pallas ring-flash fwd and fwd+bwd, Mosaic-
   compiled on the chip, vs the chip's 197 TFLOP/s bf16 peak (v5e).
3. pallas kernel census — every Pallas kernel in the tree compiled and
   executed on the chip (no interpret fallbacks): flash fwd/bwd, RDMA
   hop/bidir/multi, direct alltoall (size-1-ring loopback DMAs), fused
   shallow-water step.
4. world tier ON the TPU platform — 1-rank launcher job running every op
   through the ordered host callback under the accelerator runtime
   (tests/world_programs/tpu_world.py).
5. allreduce message sweep, world tier np=8 loopback (native transport).
6. DP ResNet grad-allreduce step (BASELINE config 3).
7. GPT-2-124M train step, bf16 (BASELINE config 4 scale) + tokens/s.
8. spectral 3-D Poisson solve via FFT alltoall transpose (config 5).

NOTE on timing: through the axon tunnel ``block_until_ready`` does NOT
wait for device completion — only a data fetch does.  Every timed region
here therefore ends inside jit with a scalar reduction that is fetched
with ``float(...)``, and multi-iteration loops live inside one jit call
(the tunnel also adds ~100 ms per dispatched call, measured r3).

Artifact contract (round 5 — the battery is un-killable-without-output):
the battery maintains ONE summary line — the headline metric with EVERY
section embedded under ``"metrics"``, sections not yet run appearing as
explicit pending/skip records — and (re)prints it at startup, after every
section, from the SIGTERM/SIGINT handler, from the watchdog, and from a
budget-guard thread that exits the process cleanly 75 s before
``BENCH_TOTAL_BUDGET_S`` runs out.  Whenever and however the process
dies, the last JSON line on stdout is a complete, parseable artifact
(round 3's gate was too short for the device wedge; round 4's was too
long for the driver's own timeout, which killed the battery mid-wait and
left no summary at all — VERDICT r4 weak #1).
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

BASELINE_GPU_SECONDS = 6.28  # reference: 1x P100, docs/shallow-water.rst:81-83
V5E_BF16_PEAK = 197e12       # bf16 TFLOP/s peak of one v5e chip

INIT_TIMEOUT_S = float(os.environ.get("BENCH_INIT_TIMEOUT_S", "600"))
REPO = os.path.dirname(os.path.abspath(__file__))


def _watchdog(flag, battery):
    # guards the init phase only (the world-on-tpu subprocess, then the
    # parent's device claim + first compile inside shallow_water); the
    # deadline is pushed forward as init-phase sections complete, and
    # the thread retires once 'ready' is set
    while True:
        if flag["ready"]:
            return
        now = time.time()
        if now >= flag["deadline"]:
            phase = flag.get("phase", "init")
            note = (
                f"watchdog: init phase {phase!r} did not complete within "
                f"its {flag.get('window_s', INIT_TIMEOUT_S):.0f}s window")
            battery.record(phase, _skip_record(phase, note),
                           reprint_summary=False)
            battery.final_exit(note)
        time.sleep(min(10.0, flag["deadline"] - now + 0.1))


# children launched by battery sections, killed by Battery.final_exit so
# an aborting battery never leaves a rank subprocess holding the device
# claim or a rendezvous port.  The lock covers spawn+register as one
# step so an abort snapshot cannot miss a child mid-launch.
_CHILDREN = set()
_CHILDREN_LOCK = threading.Lock()


def _run_tracked(cmd, timeout=None, **kwargs):
    """``subprocess.run`` equivalent whose child is registered in
    ``_CHILDREN`` for the battery's abort paths."""
    if kwargs.pop("capture_output", False):
        kwargs["stdout"] = subprocess.PIPE
        kwargs["stderr"] = subprocess.PIPE
    with _CHILDREN_LOCK:
        proc = subprocess.Popen(cmd, **kwargs)
        _CHILDREN.add(proc)
    try:
        out, err = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        raise
    finally:
        _CHILDREN.discard(proc)
    return subprocess.CompletedProcess(cmd, proc.returncode, out, err)


def _probe_claim_once():
    """One short-lived subprocess claim attempt.

    Returns the claimed platform string on success, None on failure.
    The probe prints the platform and the gate requires a non-cpu
    answer: the axon plugin can fail fast and leave jax to fall back to
    cpu, which would otherwise report a wedged device as healthy
    (ADVICE r3 #2).
    """
    try:
        res = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "print('claim-ok', d[0].platform)"],
            capture_output=True, text=True, timeout=150,
        )
    except subprocess.TimeoutExpired:
        return None
    for line in res.stdout.splitlines():
        parts = line.split()
        if parts[:1] == ["claim-ok"]:
            # require an explicit non-cpu platform token: a probe that
            # printed no platform (or fell back to cpu) is not healthy
            if (len(parts) == 2 and parts[1] != "cpu"
                    and res.returncode == 0):
                return parts[1]
    return None


def _wait_for_claim(flag, budget_s, label):
    """Block until a fresh subprocess can claim the (non-cpu) device, or
    the budget runs out.

    The axon tunnel wedges its single device claim for ~15-40 min after
    a claim-holding process dies uncleanly (docs/developers.md).  Round
    3's gate capped the wait at 1200 s — shorter than the wedge it was
    built to outlast — and the driver battery recorded every TPU
    section as skipped (VERDICT r3 weak #1).  The caller sizes
    ``budget_s``: capped by ``BENCH_CLAIM_BUDGET_S`` (2700 s ≈ 2x the
    observed wedge) but shrunk to fit inside ``BENCH_TOTAL_BUDGET_S``
    minus the TPU-section reserve — see the trade note at
    ``TOTAL_BUDGET_S``.  ``main()`` runs every CPU section during the
    wait, so the budget costs the battery nothing unless the chip is
    truly gone.

    Probes are sparse (one per ~7 min): a probe killed mid-claim can
    re-poison the wedge, so rapid-fire retries would livelock against
    the re-wedge window.

    Returns ``(ok, record)``; ``record`` is a failure metric when the
    claim never came back (None on success).
    """
    t_end = time.time() + budget_s
    # keep the watchdog off our back for the whole wait
    flag["deadline"] = max(flag["deadline"], t_end + 400)
    flag["window_s"] = max(flag.get("window_s", 0), budget_s + 400)
    while True:
        platform = _probe_claim_once()
        if platform is not None:
            # small settle: the probe's own claim needs to release
            # before the next claimer shows up
            time.sleep(15)
            return True, None
        now = time.time()
        remaining = t_end - now
        if remaining < 230:  # no room for another meaningful probe
            return False, {
                "metric": f"device_claim_before_{label}", "value": 0,
                "unit": "ok", "vs_baseline": None,
                "error": f"device claim still wedged after {budget_s}s",
            }
        time.sleep(min(420.0, remaining - 170.0))


def bench_shallow_water(flag):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi4jax_tpu.models.shallow_water import ShallowWater, SWParams
    from mpi4jax_tpu.parallel.grid import ProcessGrid

    grid = ProcessGrid((1, 1), devices=jax.devices()[:1])
    params = SWParams(dx=5e3, dy=5e3)
    ny, nx = 1800, 3600
    model = ShallowWater(grid, (ny, nx), params)

    days = 0.1
    n_steps = int(days * params.day_seconds / params.dt)  # 432 (timed: 431)

    # ALL steps in ONE jitted call: the tunnel costs ~100 ms per call,
    # which round 2 paid 9 times (VERDICT.md weak #2 traced to this).
    # Timed region matches the reference's "Solution took" exactly: the
    # multistep loop only — initial conditions, the Euler bootstrap
    # step, and compilation all happen before its timer starts
    # (/root/reference/examples/shallow_water.py:423-470).
    state1 = model.step_fn(1, first=True)(model.init())
    run = model.step_fn(n_steps - 1, first=False)

    float(jnp.sum(run(state1).h))  # compile + warmup, fetch-forced
    flag["ready"] = True

    t0 = time.perf_counter()
    state = run(state1)
    float(jnp.sum(state.h))  # drain the queue
    elapsed = time.perf_counter() - t0

    h = model.interior(state.h)
    if not np.all(np.isfinite(np.asarray(h))):
        raise RuntimeError("diverged")
    timed = n_steps - 1
    return {
        "metric": "shallow_water_1800x3600_0.1day_1chip",
        "value": round(elapsed, 3), "unit": "s",
        "vs_baseline": round(BASELINE_GPU_SECONDS / elapsed, 3),
        "steps": timed, "ms_per_step": round(elapsed / timed * 1e3, 3),
        "timed_region": "multistep loop (= reference 'Solution took')",
        "platform": jax.devices()[0].platform,
    }


def _flash_setup(**fa_kwargs):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from functools import partial

    from jax.sharding import Mesh, PartitionSpec as P

    from mpi4jax_tpu.ops.flash import ring_flash_attention

    B, T, H, D = 4, 4096, 16, 128
    ks = [jax.random.PRNGKey(i) for i in range(3)]
    q, k, v = (jax.random.normal(kk, (B, T, H, D), jnp.bfloat16) for kk in ks)
    mesh = Mesh(np.array(jax.devices()[:1]), ("sp",))
    fa = jax.shard_map(
        partial(ring_flash_attention, axis="sp", causal=True,
                interpret=False, **fa_kwargs),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"),
        check_vma=False)
    fwd_flops = 2 * 2 * B * H * T * T * D * 0.5  # causal
    return q, k, v, fa, fwd_flops


def bench_flash_mfu():
    import jax
    import jax.numpy as jnp

    q, k, v, fa, fwd_flops = _flash_setup()
    K = 10

    @jax.jit
    def many_fwd(q, k, v):
        def step(qc, _):
            return fa(qc, k, v).astype(qc.dtype), ()
        out, _ = jax.lax.scan(step, q, None, length=K)
        return jnp.sum(out.astype(jnp.float32))

    def loss(q, k, v):
        return jnp.sum(fa(q, k, v).astype(jnp.float32))

    gfn = jax.grad(loss, argnums=(0, 1, 2))

    @jax.jit
    def many_bwd(q, k, v):
        def step(qc, _):
            dq, _, _ = gfn(qc, k, v)
            return qc + dq.astype(qc.dtype) * 1e-4, ()
        out, _ = jax.lax.scan(step, q, None, length=K)
        return jnp.sum(out.astype(jnp.float32))

    recs = []
    for name, fn, mult in [("fwd", many_fwd, 1.0),
                           ("fwd+bwd", many_bwd, 3.5)]:
        float(fn(q, k, v))  # compile + warmup
        t0 = time.perf_counter()
        float(fn(q, k, v))
        dt = (time.perf_counter() - t0) / K
        tflops = fwd_flops * mult / dt / 1e12
        recs.append({
            "metric": f"flash_attention_{name}_B4_T4096_H16_D128_bf16",
            "value": round(tflops, 1), "unit": "TFLOP/s",
            "vs_baseline": None,  # reference ships no attention kernels
            "pct_of_v5e_bf16_peak": round(tflops * 1e12 / V5E_BF16_PEAK
                                          * 100, 1),
            "ms": round(dt * 1e3, 3),
        })
    recs.extend(bench_flash_experiments())
    return recs


def bench_flash_experiments():
    """Settle the r4 fwd-MFU questions with data (VERDICT r4 #5):
    (a) the q-prescale rewrite A/B (claimed ~5-10%, never measured);
    (b) the VPU-exp roofline probe — identical kernel with the two
    ``exp`` calls swapped for a linear stand-in.  If (b) barely moves,
    the forward is NOT exp-bound; if it jumps, the VPU transcendental
    unit is the ceiling and the measured gap bounds it."""
    import jax
    import jax.numpy as jnp

    from mpi4jax_tpu.ops import flash as flash_mod

    K = 10
    recs = []

    def timed_fwd(fa, q, k, v):
        @jax.jit
        def many(q, k, v):
            def step(qc, _):
                return fa(qc, k, v).astype(qc.dtype), ()
            out, _ = jax.lax.scan(step, q, None, length=K)
            return jnp.sum(out.astype(jnp.float32))

        float(many(q, k, v))
        t0 = time.perf_counter()
        float(many(q, k, v))
        return (time.perf_counter() - t0) / K

    for label, kwargs, patch_exp in [
            ("prescale_off", {"prescale_q": False}, False),
            ("cheap_exp", {}, True)]:
        saved = flash_mod._EXP
        if patch_exp:
            flash_mod._EXP = lambda x: x * 0.25 + 1.0  # linear stand-in
        try:
            q, k, v, fa, fwd_flops = _flash_setup(**kwargs)
            dt = timed_fwd(fa, q, k, v)
        finally:
            flash_mod._EXP = saved
        tflops = fwd_flops / dt / 1e12
        recs.append({
            "metric": f"flash_fwd_experiment_{label}",
            "value": round(tflops, 1), "unit": "TFLOP/s",
            "vs_baseline": None,
            "pct_of_v5e_bf16_peak": round(tflops * 1e12 / V5E_BF16_PEAK
                                          * 100, 1),
            "ms": round(dt * 1e3, 3),
            "note": ("kernel-internal s*scale (pre-r4 behavior)"
                     if label == "prescale_off" else
                     "exp swapped for linear op — NOT valid attention; "
                     "roofline probe only"),
        })
    return recs


def bench_pallas_census():
    """Compile + execute every Pallas kernel on the real chip."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from mpi4jax_tpu.ops.pallas_collectives import (
        ring_shift, ring_shift2, ring_shift_n, _make_alltoall_kernel)

    mesh = Mesh(np.array(jax.devices()[:1]), ("r",))
    x = jnp.arange(8 * 128, dtype=jnp.float32).reshape(8, 128)
    ok, total, failures = 0, 0, []

    def shard(f, nin=1):
        return jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=(P("r"),) * nin, out_specs=P("r"),
            check_vma=False))

    def attempt(fn):
        # a scalar fetch is the only real completion barrier through the
        # tunnel (see module NOTE); partial failures count, not abort
        nonlocal ok, total
        total += 1
        try:
            out = fn()
            float(np.sum(np.asarray(
                jax.tree_util.tree_leaves(out)[0], dtype=np.float32)))
            ok += 1
        except Exception as err:
            failures.append(f"{type(err).__name__}: {err}"[:160])

    # RDMA hop kernels as size-1-ring loopback DMAs
    attempt(lambda: shard(
        lambda v: ring_shift(v, "r", 1, interpret=False))(x))
    attempt(lambda: shard(
        lambda a: sum(ring_shift2(a, a + 1, "r", interpret=False)))(x))
    attempt(lambda: shard(
        lambda a: sum(ring_shift_n((a, a * 2, a * 3), "r", 1,
                                   interpret=False)))(x))

    def direct_a2a(v):
        meta = jnp.stack([jnp.int32(0), jnp.int32(0)])
        return pl.pallas_call(
            _make_alltoall_kernel(1),
            out_shape=jax.ShapeDtypeStruct(v.shape, v.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.MemorySpace.SMEM),
                      pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA((1,)),
                            pltpu.SemaphoreType.DMA((1,))],
            interpret=False,
        )(meta, v)

    attempt(lambda: jax.jit(jax.shard_map(
        direct_a2a, mesh=mesh, in_specs=P(None, "r"),
        out_specs=P(None, "r"), check_vma=False))(x[None]))

    # flash fwd + bwd kernels (fwd/dq/dkv) via value_and_grad
    q, k, v, fa, _ = _flash_setup()
    attempt(lambda: jax.jit(fa)(q, k, v))
    attempt(lambda: jax.jit(jax.grad(
        lambda a, b, c: jnp.sum(fa(a, b, c).astype(jnp.float32)),
        argnums=(0, 1, 2)))(q, k, v))

    # fused shallow-water step kernel (fuse=1 and fuse=2 variants)
    from mpi4jax_tpu.models import _sw_pallas
    from mpi4jax_tpu.models.shallow_water import ShallowWater, SWParams
    from mpi4jax_tpu.parallel.grid import ProcessGrid

    grid = ProcessGrid((1, 1), devices=jax.devices()[:1])
    model = ShallowWater(grid, (256, 512), SWParams(dx=5e3, dy=5e3))
    s0 = model.init()
    shape = s0.h.shape
    for fuse in (1, 2):
        sp = _sw_pallas.pad_rows(s0, tile_rows=128, fuse=fuse)
        attempt(lambda: jax.jit(
            lambda st: jnp.sum(_sw_pallas.fused_step(
                st, model.params, first=False, logical_shape=shape,
                tile_rows=128, fuse=fuse).h))(sp))

    rec = {
        "metric": "pallas_kernels_compiled_on_tpu",
        "value": ok, "unit": f"of {total} kernels",
        "vs_baseline": None,  # reference has no device kernels at all
        "detail": "hop, bidir, multi, direct-alltoall, flash fwd, "
                  "flash bwd (dq+dkv), sw fused (fuse=1, fuse=2)",
    }
    if failures:
        rec["failures"] = failures
    return rec


def bench_world_on_tpu():
    """1-rank world job under the accelerator runtime (staging tier)."""
    # pass the platform explicitly: the launcher pins ranks to cpu when
    # the parent env exports no JAX_PLATFORMS
    platform = os.environ.get("JAX_PLATFORMS") or "tpu,cpu"
    env = dict(os.environ)
    # persistent compile cache: through the tunnel every distinct
    # executable costs 20-40 s in the remote compile helper; cache them
    # across runs (and across rounds when the dir survives)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_compile_cache")
    res = _run_tracked(
        [sys.executable, "-m", "mpi4jax_tpu.runtime.launch", "-n", "1",
         "--port", "46100", "--platform", platform,
         os.path.join(REPO, "tests", "world_programs", "tpu_world.py")],
        # this section runs first, ahead of any device claim by the
        # parent; its budget is a full INIT_TIMEOUT_S window (the
        # watchdog deadline was pushed past it by main())
        capture_output=True, text=True, timeout=INIT_TIMEOUT_S,
        cwd=REPO, env=env,
    )
    ok = res.returncode == 0 and "tpu_world OK" in res.stdout
    rec = {
        "metric": "world_tier_on_tpu_platform",
        "value": 1 if ok else 0, "unit": "ok",
        "vs_baseline": None,
        "rc": res.returncode,
    }
    if not ok:
        rec["stderr_tail"] = res.stderr[-800:]
    return rec


def bench_host_context():
    """Record the host's single-core copy bandwidth next to the loopback
    sweep: with N ranks time-sharing this machine's cores, an N-rank
    16 MB allreduce moves ~2N payloads through one memory system, so
    the sweep's ceiling is a host property — the record makes the
    comparison against multi-socket reference numbers interpretable."""
    import numpy as np

    n = 64 * 1024 * 1024
    a = np.ones(n, np.uint8)
    b = np.empty_like(a)
    np.copyto(b, a)  # warm
    t0 = time.perf_counter()
    for _ in range(4):
        np.copyto(b, a)
    dt = (time.perf_counter() - t0) / 4
    return {
        "metric": "host_context", "value": os.cpu_count(), "unit": "cores",
        "vs_baseline": None,
        "memcpy_GBps": round(n / dt / 1e9, 2),
        "note": "reference CPU table used 2x Xeon E5-2650 v4 (24 cores)",
    }


def _run_world_sweep(n_ranks, port, sizes=None, timeout_s=600):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    cmd = [sys.executable, "-m", "mpi4jax_tpu.runtime.launch",
           "-n", str(n_ranks), "--port", str(port),
           os.path.join(REPO, "benchmarks", "allreduce_sweep.py"),
           "--world", "--max-mb", "17"]
    if sizes:
        cmd += ["--sizes", ",".join(str(s) for s in sizes)]
    res = _run_tracked(cmd, capture_output=True, text=True,
                       timeout=timeout_s, cwd=REPO, env=env)
    rows = []
    for line in res.stdout.splitlines():
        try:
            rows.append(json.loads(line))
        except (json.JSONDecodeError, ValueError):
            continue
    return res, rows


def bench_allreduce_sweep():
    """World-tier loopback allreduce: full np=8 sweep + np=2/np=4
    headline points (native transport, shm arena on this single host).

    Reports both the in-jit time (ops inside a compiled step function —
    the deployment shape) and the transport-level time (native call on
    host buffers) per point, labeled as such.
    """
    res, rows = _run_world_sweep(8, 46150)
    if res.returncode != 0 or not rows:
        return {
            "metric": "allreduce_world_np8_sweep", "value": None,
            "unit": "GB/s", "vs_baseline": None, "rc": res.returncode,
            "stderr_tail": res.stderr[-500:],
        }
    small = min(rows, key=lambda r: r["bytes"])
    big = max(rows, key=lambda r: r["bytes"])
    rec = {
        "metric": "allreduce_world_np8_sweep",
        "value": big["eff_GBps_per_chip"],
        "unit": "GB/s/rank eff (16MiB, in-jit)",
        "vs_baseline": None,  # BASELINE.json published: {} — first capture
        "eff_GBps_transport_16MiB": big.get("raw_eff_GBps_per_chip"),
        "small_msg_1KB_us_injit": round(small["seconds"] * 1e6, 1),
        "small_msg_1KB_us_transport": round(
            small.get("raw_seconds", small["seconds"]) * 1e6, 1),
        "sizes": len(rows), "ranks": big["ranks"],
    }
    out = [rec]
    for n_ranks, port in ((2, 46170), (4, 46180)):
        try:
            res, rows = _run_world_sweep(
                n_ranks, port, sizes=[1024, 16 * 1024 * 1024],
                timeout_s=300)
            big = max(rows, key=lambda r: r["bytes"])
            small = min(rows, key=lambda r: r["bytes"])
            out.append({
                "metric": f"allreduce_world_np{n_ranks}_16MiB",
                "value": big["eff_GBps_per_chip"],
                "unit": "GB/s/rank eff (in-jit)",
                "vs_baseline": None,
                "eff_GBps_transport": big.get("raw_eff_GBps_per_chip"),
                "small_msg_1KB_us_injit": round(
                    small["seconds"] * 1e6, 1),
            })
        except Exception as err:
            out.append({
                "metric": f"allreduce_world_np{n_ranks}_16MiB",
                "value": None, "vs_baseline": None,
                "error": f"{type(err).__name__}: {err}"[:200],
            })
    return out


def bench_dp_resnet():
    import jax
    import jax.numpy as jnp

    import mpi4jax_tpu as m4j
    from mpi4jax_tpu.models import resnet

    def run(cfg, B, K, label):
        mesh = m4j.make_mesh(1)
        params = resnet.init_params(cfg)
        step = resnet.make_dp_train_step(cfg, mesh, lr=0.05)
        x = jnp.ones((B, 224, 224, 3), jnp.float32)
        y = jnp.zeros((B,), jnp.int32)

        @jax.jit
        def many(params, x, y):
            def one(p, _):
                loss, p = step(p, x, y)
                return p, loss
            p, losses = jax.lax.scan(one, params, None, length=K)
            return losses[-1]

        float(many(params, x, y))
        t0 = time.perf_counter()
        loss = float(many(params, x, y))
        dt = (time.perf_counter() - t0) / K
        return {
            "metric": f"dp_{label}_grad_allreduce_step_bf16",
            "value": round(B / dt, 1), "unit": "img/s",
            "vs_baseline": None,  # BASELINE.json published: {}
            "ms_per_step": round(dt * 1e3, 1), "batch": B,
            "loss_finite": bool(loss == loss),
        }

    # BASELINE.md names ResNet-50: bottleneck (3,4,6,3).  B=32 (B=64 at
    # 224^2 overflows the tunnel's remote compile helper — bisected r3).
    try:
        return run(resnet.resnet50_config(dtype="bfloat16"), 32, 5,
                   "resnet50")
    except Exception as err:
        # fall back to the basic-block (3,4,6,3) = ResNet-34 used in r3,
        # recording why (VERDICT r3 weak #6: the substitution must be
        # justified in the record itself)
        rec = run(
            resnet.ResNetConfig(stages=(3, 4, 6, 3), n_classes=1000,
                                dtype="bfloat16", stem="imagenet"),
            32, 5, "resnet34")
        rec["note"] = ("ResNet-50 (bottleneck) failed on this backend: "
                       f"{type(err).__name__}: {err}"[:200])
        return rec


def bench_gpt2_step():
    import jax
    import jax.numpy as jnp

    import mpi4jax_tpu as m4j
    from mpi4jax_tpu.models.transformer import GPT, GPTConfig, init_params

    import numpy as np
    from jax.sharding import Mesh

    cfg = GPTConfig(vocab=50304, d_model=768, n_heads=12, n_layers=12,
                    d_ff=3072, max_seq=1024, dtype="bfloat16")
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1, 1),
                ("dp", "tp", "sp"))
    model = GPT(cfg, mesh)
    params = init_params(cfg, tp=1)
    opt_state = model.init_opt_state(params)
    step = model.train_step_fn(opt_state)
    B, T = 8, 1024
    tokens = jnp.ones((B, T), jnp.int32)
    K = 3

    @jax.jit
    def many(params, opt_state, tokens):
        def one(carry, _):
            p, o = carry
            loss, p, o = step(p, o, tokens)
            return (p, o), loss
        (p, o), losses = jax.lax.scan(
            one, (params, opt_state), None, length=K)
        return losses[-1]

    float(many(params, opt_state, tokens))
    t0 = time.perf_counter()
    loss = float(many(params, opt_state, tokens))
    dt = (time.perf_counter() - t0) / K

    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    flops = 6 * n_params * B * T  # fwd+bwd dense estimate
    tflops = flops / dt / 1e12
    return {
        "metric": "gpt2_124M_train_step_bf16",
        "value": round(B * T / dt, 0), "unit": "tokens/s",
        "vs_baseline": None,  # BASELINE.json published: {} — first capture
        "ms_per_step": round(dt * 1e3, 1),
        "model_TFLOPs": round(tflops, 1),
        "pct_of_v5e_bf16_peak": round(tflops * 1e12 / V5E_BF16_PEAK * 100,
                                      1),
        "params_M": round(n_params / 1e6, 1),
        "loss_finite": bool(loss == loss),
        # BASELINE.md names "PP GPT-2 124M via point-to-point"; pipeline
        # parallelism needs >1 device, so on this single chip the battery
        # measures the same model dense (dp=tp=sp=1) and the PP path
        # (models/pp_transformer.py, ppermute handoffs) executes in
        # dryrun_multichip section 2 on the virtual mesh every round
        "pp_note": "PP path exercised in dryrun_multichip (1 chip here)",
    }


def bench_spectral():
    import jax
    import jax.numpy as jnp

    import mpi4jax_tpu as m4j
    from mpi4jax_tpu.models import spectral

    mesh = m4j.make_mesh(1, axis="x")
    n = 256
    shape = (n, n, n)
    f = jnp.ones((n, n, n), jnp.float32)
    K = 5

    solve = m4j.spmd(
        lambda v: spectral.poisson_solve(v, axis="x", shape=shape),
        mesh=mesh)

    @jax.jit
    def many(f):
        def one(cur, _):
            return solve(cur), ()
        out, _ = jax.lax.scan(one, f, None, length=K)
        return jnp.sum(out)

    float(many(f))
    t0 = time.perf_counter()
    float(many(f))
    dt = (time.perf_counter() - t0) / K
    return {
        "metric": "spectral_poisson_fft_alltoall_256cubed",
        "value": round(dt * 1e3, 2), "unit": "ms/solve",
        "vs_baseline": None,  # BASELINE.json published: {} — first capture
    }


CLAIM_BUDGET_S = float(os.environ.get("BENCH_CLAIM_BUDGET_S", "2700"))

# total wall-clock the battery may use, end to end.  The driver's own
# timeout is outside our control (r4: it fired INSIDE the 2700 s claim
# gate and the battery died summary-less with rc=124 — so the external
# window is <= ~2700 s); the battery now budgets itself to finish — or
# self-terminate with a complete artifact and rc=0 — before an external
# kill can land.  The default sits safely inside that observed window;
# override upward via env when a longer window is known to exist.
# Consequence accepted by design: within a hard external window the
# claim gate can no longer outlast a full 15-40 min device wedge AND
# leave room for the TPU sections — when the device is wedged past the
# sized-down gate, the battery ends early with structured skips instead
# of dying summary-less (the r3 vs r4 trade, resolved in favor of the
# artifact).
TOTAL_BUDGET_S = float(os.environ.get("BENCH_TOTAL_BUDGET_S", "2500"))
T_START = time.time()

# wall-clock reserved for the TPU sections when sizing the claim gate:
# with a healthy tunnel the full device battery fits in ~20 min of
# compile-cached runtime (r3 measurements) plus first-compile slack
TPU_RESERVE_S = float(os.environ.get("BENCH_TPU_RESERVE_S", "1400"))


def _budget_remaining():
    return TOTAL_BUDGET_S - (time.time() - T_START)


class Battery:
    """Holds every section record and owns the output contract.

    ``record()`` prints the per-section line AND the refreshed summary
    line, under one lock — so stdout's last complete line is always the
    full artifact, whatever kills the process next.
    """

    def __init__(self, section_names, headline_metric):
        # RLock: the SIGTERM handler runs on the main thread and may
        # interrupt a record() that already holds the lock
        self._lock = threading.RLock()
        self._names = list(section_names)
        self._done = {}         # section name -> list of records
        self._headline = headline_metric
        self.note = None

    def record(self, name, rec, reprint_summary=True):
        recs = rec if isinstance(rec, list) else [rec]
        with self._lock:
            self._done.setdefault(name, []).extend(recs)
            for r in recs:
                print(json.dumps(r), flush=True)
            if reprint_summary:
                print(json.dumps(self._summary_locked()), flush=True)

    def _summary_locked(self):
        metrics = []
        for name in self._names:
            if name in self._done:
                metrics.extend(self._done[name])
            else:
                metrics.append(_skip_record(
                    name, "pending: section had not run when the "
                          "summary was (re)printed"))
        for name in self._done:           # out-of-plan records (gate etc.)
            if name not in self._names:
                metrics.extend(self._done[name])
        headline = next(
            (m for m in metrics
             if m["metric"] == self._headline and m.get("value") is not None),
            {"metric": self._headline, "value": None, "unit": "s",
             "vs_baseline": None},
        )
        final = dict(headline)
        if self.note:
            final["battery_note"] = self.note
        final["battery_elapsed_s"] = round(time.time() - T_START, 1)
        final["metrics"] = metrics
        return final

    def print_summary(self):
        with self._lock:
            print(json.dumps(self._summary_locked()), flush=True)

    def final_exit(self, note, rc=0):
        """Print the full summary and exit WITHOUT releasing the lock:
        no other thread can start a partial stdout write between the
        final summary line and process death.

        ``os._exit`` here is a deliberate trade: every final_exit path
        fires only when an external kill is already imminent (driver
        timeout, delivered signal, wedged init) — the alternative to an
        abrupt-but-artifact-bearing exit is SIGKILL with no artifact,
        which wedges the claim just the same.  Tracked child processes
        are killed first so they cannot outlive the battery holding
        ports or their own claims."""
        self._lock.acquire()
        try:
            self.note = note
            with _CHILDREN_LOCK:
                children = list(_CHILDREN)
            for proc in children:
                try:
                    proc.kill()
                except Exception:
                    pass
            # leading newline: if the kill interrupted a half-written
            # stdout line, the summary still starts a fresh line
            sys.stdout.write("\n" + json.dumps(self._summary_locked())
                             + "\n")
            sys.stdout.flush()
        finally:
            os._exit(rc)

# sections that never touch the device — they run FIRST, concurrently
# with the claim gate, so a wedged chip costs the battery nothing but
# the gate's own wait (r3 ran only one of these while waiting and lost
# every TPU record to a 1200 s gate shorter than the wedge)
CPU_SECTIONS = [
    ("host_context", bench_host_context),
    ("allreduce_sweep", bench_allreduce_sweep),
]

# device sections, all run from ONE parent process holding ONE claim
# (world_on_tpu is the exception: its rank subprocess needs the claim,
# so it runs before the parent first touches jax — a single-session
# device pool will not grant two concurrent claims)
TPU_SECTIONS = [
    ("world_on_tpu", bench_world_on_tpu),
    ("shallow_water", None),  # bound to flag in main()
    ("flash_mfu", bench_flash_mfu),
    ("pallas_census", bench_pallas_census),
    ("dp_resnet", bench_dp_resnet),
    ("gpt2", bench_gpt2_step),
    ("spectral", bench_spectral),
]

HEADLINE = "shallow_water_1800x3600_0.1day_1chip"


def _skip_record(name, reason="skipped: device claim wedged"):
    metric = {"shallow_water": HEADLINE,
              "world_on_tpu": "world_tier_on_tpu_platform"}.get(name, name)
    return {"metric": metric, "value": None, "unit": None,
            "vs_baseline": None, "error": reason}


def main():
    # persistent compile cache for the parent's own sections as well
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          "/tmp/jax_compile_cache")
    battery = Battery(
        [n for n, _ in CPU_SECTIONS] + [n for n, _ in TPU_SECTIONS],
        HEADLINE)

    # a complete artifact exists from second zero
    battery.print_summary()

    def _on_signal(signum, frame):
        # a second delivery must not re-enter mid-print (the RLock would
        # let the same thread interleave two summaries into one line)
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        signal.signal(signal.SIGINT, signal.SIG_IGN)
        battery.final_exit(f"terminated by signal {signum}")

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    def _budget_guard():
        # exits the battery cleanly — with the full artifact and rc=0 —
        # before the external deadline can deliver an unhandleable kill
        while True:
            rem = _budget_remaining()
            if rem <= 75:
                battery.final_exit(
                    f"total budget {TOTAL_BUDGET_S:.0f}s exhausted; "
                    "remaining sections recorded as pending skips")
            time.sleep(max(1.0, min(30.0, rem - 70.0)))

    threading.Thread(target=_budget_guard, daemon=True).start()

    flag = {"ready": False,
            "deadline": time.time() + CLAIM_BUDGET_S + 2 * INIT_TIMEOUT_S,
            "window_s": CLAIM_BUDGET_S + 2 * INIT_TIMEOUT_S,
            "phase": "cpu+gate"}
    threading.Thread(target=_watchdog, args=(flag, battery),
                     daemon=True).start()

    # claim gate in a side thread; CPU sections run during the wait.
    # Size the gate to leave TPU_RESERVE_S for the device sections.
    gate_budget = max(300.0, min(CLAIM_BUDGET_S,
                                 _budget_remaining() - TPU_RESERVE_S))
    gate_result = {}

    def gate():
        ok, rec = _wait_for_claim(flag, gate_budget, "tpu_battery")
        gate_result["ok"] = ok
        gate_result["rec"] = rec

    gate_thread = threading.Thread(target=gate, daemon=True)
    gate_thread.start()

    for name, fn in CPU_SECTIONS:
        try:
            battery.record(name, fn())
        except Exception as err:
            battery.record(name, {
                "metric": name, "value": None, "vs_baseline": None,
                "error": f"{type(err).__name__}: {err}"[:300]})

    gate_thread.join()
    device_ok = gate_result.get("ok", False)
    if gate_result.get("rec") is not None:
        battery.record("claim_gate", gate_result["rec"])

    for name, fn in TPU_SECTIONS:
        flag["phase"] = name
        if name == "shallow_water":
            fn = lambda: bench_shallow_water(flag)  # noqa: E731
        if not device_ok:
            battery.record(name, _skip_record(name))
            continue
        if _budget_remaining() < 180:
            battery.record(name, _skip_record(
                name, "skipped: total budget exhausted"))
            continue
        if name == "world_on_tpu":
            # bounded by its own subprocess timeout
            flag["deadline"] = time.time() + INIT_TIMEOUT_S + 120
            flag["window_s"] = INIT_TIMEOUT_S + 120
        elif not flag["ready"]:
            # parent's own claim + first compile gets a fresh window
            flag["deadline"] = time.time() + INIT_TIMEOUT_S
            flag["window_s"] = INIT_TIMEOUT_S
        try:
            rec = fn()
        except Exception as err:  # keep going: one broken section
            rec = {"metric": name, "value": None, "vs_baseline": None,
                   "error": f"{type(err).__name__}: {err}"[:300]}
        # commit the record BEFORE any regate wait: a budget-guard kill
        # during the wait must not lose the section's diagnostics
        battery.record(name, rec)
        if name == "world_on_tpu":
            failed = not (isinstance(rec, dict) and rec.get("value"))
            if failed:
                # the rank may have died mid-claim; let the wedge lapse
                # before the parent claims for its own sections
                regate = max(300.0, min(
                    CLAIM_BUDGET_S / 3,
                    _budget_remaining() - TPU_RESERVE_S / 2))
                device_ok, gate_rec = _wait_for_claim(
                    flag, regate, "parent_battery")
                if gate_rec is not None:
                    battery.record("claim_regate", gate_rec)
        else:
            # the watchdog only guards init; once the device has run a
            # section (or raised a real error) it must never kill the
            # rest of the battery
            flag["ready"] = True
    # rc=0 whenever the battery ran to completion (structured skips
    # included): a non-zero rc is reserved for crashes the contract
    # could not absorb.  Plain process exit releases the device claim
    # cleanly so the next battery starts against a healthy pool.
    return 0


if __name__ == "__main__":
    sys.exit(main())
