"""Round benchmark: shallow-water headline config on the available hardware.

Reference baseline (BASELINE.md): the same physical configuration —
(1800, 3600) domain, 0.1 model days, CFL dt — took 6.28 s on one Tesla P100
and 111.95 s on one CPU socket (docs/shallow-water.rst there).  We report
wall seconds on one TPU chip; ``vs_baseline`` is the speedup over the
reference's best single-accelerator number (P100).

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": "s", "vs_baseline": N}
"""

import json
import os
import sys
import threading
import time

BASELINE_GPU_SECONDS = 6.28  # reference: 1x P100, docs/shallow-water.rst:81-83

# Device acquisition can hang indefinitely if the TPU tunnel is wedged;
# emit a structured failure instead of stalling the driver.
INIT_TIMEOUT_S = float(os.environ.get("BENCH_INIT_TIMEOUT_S", "600"))


def _watchdog(flag):
    time.sleep(INIT_TIMEOUT_S)
    if not flag["ready"]:
        print(json.dumps({
            "metric": "shallow_water_1800x3600_0.1day_1chip",
            "value": None, "unit": "s", "vs_baseline": 0.0,
            "error": ("device init / compile / warmup did not complete in "
                      f"{INIT_TIMEOUT_S}s"),
        }), flush=True)
        os._exit(2)


def main():
    flag = {"ready": False}
    threading.Thread(target=_watchdog, args=(flag,), daemon=True).start()

    import jax

    jax.devices()
    import numpy as np

    from mpi4jax_tpu.models.shallow_water import ShallowWater, SWParams
    from mpi4jax_tpu.parallel.grid import ProcessGrid

    ndev = len(jax.devices())
    # single-chip headline config (the driver runs this on one real TPU)
    grid = ProcessGrid((1, 1), devices=jax.devices()[:1])
    params = SWParams(dx=5e3, dy=5e3)
    ny, nx = 1800, 3600
    model = ShallowWater(grid, (ny, nx), params)

    days = 0.1
    n_steps = int(days * params.day_seconds / params.dt)
    multistep = 50

    state = model.init()
    first = model.step_fn(1, first=True)
    # the timed loop never reuses its argument, so donate the state buffers
    step = model.step_fn(multistep, first=False, donate=True)

    # NOTE: on the tunneled TPU, block_until_ready() does NOT wait for
    # device completion — only a data fetch does.  Warmup and the timed
    # region therefore each end with a scalar fetch that drains the queue.
    import jax.numpy as jnp

    state = first(state)
    float(jnp.sum(step(state).h))  # compile + one warmup multistep, forced
    flag["ready"] = True  # compile/execute survived; watchdog disarmed
    state = first(model.init())  # warmup donated the old state's buffers

    t0 = time.perf_counter()
    done = 1
    while done < n_steps:
        state = step(state)
        done += multistep
    float(jnp.sum(state.h))  # force completion of the whole queue
    elapsed = time.perf_counter() - t0

    h = model.interior(state.h)
    if not np.all(np.isfinite(h)):
        print(json.dumps({
            "metric": "shallow_water_1800x3600_0.1day_1chip",
            "value": None, "unit": "s", "vs_baseline": 0.0,
            "error": "diverged",
        }))
        return 1

    print(json.dumps({
        "metric": "shallow_water_1800x3600_0.1day_1chip",
        "value": round(elapsed, 3),
        "unit": "s",
        "vs_baseline": round(BASELINE_GPU_SECONDS / elapsed, 3),
        "steps": done,
        "platform": jax.devices()[0].platform,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
