#!/usr/bin/env python3
"""Large-np verification scale harness (``make verify-scale``).

Proves the analyzer's verdicts survive world sizes far past what the
per-rank concrete machinery was built for, and emits the committed
``BENCH_verifier_scale.json`` evidence:

1. **Corpus at scale** — every committed golden plan
   (tests/world_programs/golden_plans/*.plan.json) is the calibration
   artifact for an np-parametric schedule generator: at the golden's
   own world size the generated schedule must round-trip the golden's
   events AND its schedule cache key bit-for-bit, and the generator's
   peer columns must be reproduced by fitted affine-mod peer forms
   (``_symbolic.fit_peer_form`` at two calibration sizes,
   instantiated at np=512).  Only then is the generator trusted to
   stand in for the corpus program on the np ladder 8 → 512.
2. **Differential ladder** — at every rung both paths run where
   affordable: the concrete O(np²-channel) matcher up to
   ``--concrete-cap``, the symbolic quotient everywhere.  Findings
   must agree byte-for-byte, every plan must PROVE (at np=512 only
   the class-rotation quotient can — the concrete prover's
   interleaving budget caps out near 256 ranks), and per-rung wall
   time / match-sim steps / class counts / peak RSS land in the
   bench file.
3. **Simulator oracles at np=512** — the hierarchical + quantized
   ``topo.simulate_*`` schedule models (numpy, bit-exact twins of the
   native engine) are checked against exact references on a
   512-rank / 8-island world.
4. **Joint-tuner sanity at ranks=512** — ``tune.joint_search`` over
   the full combo space with a deterministic synthetic cost model
   must pick a winner for every (op, size) and never pick an
   ineligible combo.

Everything here is import-light: the analysis stack, the numpy
simulators, and the tuner load standalone, so this gate runs — and
tier-1 wires it in via tests/test_verify_scale.py — on any host,
including containers whose jax predates the package minimum.

Usage:
    python tools/scale_harness.py [--quick] [--out PATH]
                                  [--budget-s 60] [--concrete-cap N]

Exit 0 with every check green and the wall budget respected; exit 1
otherwise (the summary names the failures).
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import resource
import sys
import time
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDENS = os.path.join(REPO, "tests", "world_programs", "golden_plans")

#: plan-shaping knobs cleared so the run compares under documented
#: defaults (mirrors tools/verify_corpus.py)
NORMALIZED_KNOBS = (
    "MPI4JAX_TPU_PROGRESS_THREAD", "MPI4JAX_TPU_COALESCE_BYTES",
    "MPI4JAX_TPU_PLAN_BUCKET_KB", "MPI4JAX_TPU_PLAN",
    "MPI4JAX_TPU_FAULT", "MPI4JAX_TPU_ANALYZE_SYMBOLIC",
)

NP_LADDER = (8, 16, 32, 64, 128, 256, 512)
NP_LADDER_QUICK = (8, 16, 32, 64)
CALIBRATION_NPS = (8, 12)  # peer-form fitting sizes (two, see fit_peer_form)


def _load_standalone():
    """The analysis + tune stacks under a private package name: pure
    stdlib modules, loadable with or without an importable
    ``mpi4jax_tpu`` (old-jax containers)."""
    if "m4j_scale._symbolic" in sys.modules:
        return {n.rsplit(".", 1)[1]: m for n, m in sys.modules.items()
                if n.startswith("m4j_scale.")}
    pkg = types.ModuleType("m4j_scale")
    pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu", "analysis")]
    sys.modules["m4j_scale"] = pkg
    mods = {}
    for name in ("_events", "_match", "_deps", "_plan", "_symbolic"):
        spec = importlib.util.spec_from_file_location(
            f"m4j_scale.{name}",
            os.path.join(REPO, "mpi4jax_tpu", "analysis", f"{name}.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[f"m4j_scale.{name}"] = mod
        spec.loader.exec_module(mod)
        mods[name] = mod
    return mods


_M = _load_standalone()
EV, MT, PL, SY = _M["_events"], _M["_match"], _M["_plan"], _M["_symbolic"]


def _ev(r, i, kind, **kw):
    kw.setdefault("dtype", "float32")
    return EV.CommEvent(r, i, kind, **kw)


# ---------------------------------------------------------------------------
# corpus-family generators, calibrated against the committed goldens


def gen_halo_exchange(n):
    """tests/world_programs/halo_exchange.py at np: the periodic
    two-direction halo, two iterations (tags 20/40 then 21/41)."""
    sch = {}
    for r in range(n):
        sch[r] = [
            _ev(r, 0, "sendrecv", comm=(0,), dest=(r + 1) % n,
                source=(r - 1) % n, sendtag=20, recvtag=20, shape=(1,)),
            _ev(r, 1, "sendrecv", comm=(0,), dest=(r - 1) % n,
                source=(r + 1) % n, sendtag=40, recvtag=40, shape=(1,)),
            _ev(r, 2, "sendrecv", comm=(0,), dest=(r + 1) % n,
                source=(r - 1) % n, sendtag=21, recvtag=21, shape=(1,)),
            _ev(r, 3, "sendrecv", comm=(0,), dest=(r - 1) % n,
                source=(r + 1) % n, sendtag=41, recvtag=41, shape=(1,)),
        ]
    return sch


def gen_independent_pair(n):
    """independent_pair.py at np (even): two deps-free 3-message
    bursts per pair, sends hoisted ahead of the recv posts — the
    planned order the golden records."""
    sch = {}
    for r in range(n):
        p = r + 1 if r % 2 == 0 else r - 1
        evs = []
        for base in (0, 100):
            for t in range(3):
                evs.append(_ev(r, len(evs), "send", comm=(0,), dest=p,
                               tag=base + t, shape=(64,)))
            for t in range(3):
                evs.append(_ev(r, len(evs), "recv", comm=(0,), source=p,
                               tag=base + t, shape=(64,)))
        sch[r] = evs
    return sch


def gen_bucketed_dp_grad(n):
    """bucketed_dp_grad.py at np: twelve 2 KiB gradient buckets plus
    the 24 KiB coalesced tail — rank-invariant collective chain."""
    sch = {}
    for r in range(n):
        evs = [_ev(r, i, "allreduce", comm=(0,), reduce_op="SUM",
                   shape=(512,)) for i in range(12)]
        evs.append(_ev(r, 12, "allreduce", comm=(0,), reduce_op="SUM",
                       shape=(6144,)))
        sch[r] = evs
    return sch


def gen_false_serialization(n):
    """false_serialization.py at np: two token-serialized but
    data-independent ring exchanges (the program the rewrite exists
    for)."""
    sch = {}
    for r in range(n):
        sch[r] = [
            _ev(r, 0, "send", comm=(0,), dest=(r + 1) % n, tag=11,
                shape=(65536,)),
            _ev(r, 1, "recv", comm=(0,), source=(r - 1) % n, tag=11,
                shape=(65536,)),
            _ev(r, 2, "send", comm=(0,), dest=(r + 1) % n, tag=12,
                shape=(65536,)),
            _ev(r, 3, "recv", comm=(0,), source=(r - 1) % n, tag=12,
                shape=(65536,)),
        ]
    return sch


def gen_quant_ops(n):
    """quant_ops.py at np: the quantized-collective accuracy chain
    (codec-eligible f32, bf16, small, and large-payload buckets)."""
    shapes = [("float32", (1030,)), ("float32", (1030,)),
              ("bfloat16", (1030,)), ("float32", (512,)),
              ("float32", (98304,))]
    return {r: [_ev(r, i, "allreduce", comm=(0,), reduce_op="SUM",
                    dtype=dt, shape=sh)
                for i, (dt, sh) in enumerate(shapes)]
            for r in range(n)}


def gen_moe_ops(n):
    """moe_ops.py at np: the MoE dispatch/combine alltoall chain —
    capacity-3 training steps then capacity-1 inference steps.  The
    leading axis is the world size (one chunk per peer), so it scales
    with np."""
    return {r: [_ev(r, i, "alltoall", comm=(0,),
                    shape=(n, 3 if i < 6 else 1, 16))
                for i in range(8)]
            for r in range(n)}


#: family name -> (golden plan file, generator, peer-form period):
#: period is the rank-residue the family's peer columns are affine in
#: (1 = one form for every rank, 2 = even/odd roles) — what the
#: fit_peer_form calibration partitions observations by.
FAMILIES = {
    "halo_exchange": ("halo_exchange.np3.plan.json",
                      gen_halo_exchange, 1),
    "independent_pair": ("independent_pair.np2.plan.json",
                         gen_independent_pair, 2),
    "bucketed_dp_grad": ("bucketed_dp_grad.np2.plan.json",
                         gen_bucketed_dp_grad, 1),
    "false_serialization": ("false_serialization.np3.plan.json",
                            gen_false_serialization, 1),
    "quant_ops": ("quant_ops.np2.plan.json", gen_quant_ops, 1),
    "moe_ops": ("moe_ops.np4.plan.json", gen_moe_ops, 1),
}


def _world(n):
    return {(0,): tuple(range(n))}


def calibrate_family(name, failures):
    """Pin the generator to its committed golden: events and cache key
    round-trip at the golden's np, and the peer columns refit as
    affine-mod forms that reproduce the generator at np=512."""
    fname, gen, period = FAMILIES[name]
    plan = PL.load_plan(os.path.join(GOLDENS, fname))
    ref_events, _ref_comms = PL.events_from_plan(plan)
    np_g = plan.world_size
    got = gen(np_g)
    out = {"np_golden": np_g, "events_per_rank": len(got[0])}

    ref_canon = {r: [EV.canonical_event(e) for e in evs]
                 for r, evs in ref_events.items()}
    got_canon = {r: [EV.canonical_event(e) for e in evs]
                 for r, evs in got.items()}
    # moe's alltoall leading axis is the world size in the golden too,
    # so a straight equality covers it; any drift is a real failure
    out["events_match_golden"] = got_canon == ref_canon
    if not out["events_match_golden"]:
        failures.append(f"{name}: generated events != golden events "
                        f"at np={np_g}")

    key = EV.schedule_cache_key(got, np_g)
    out["cache_key_match"] = key == plan.cache_key
    if not out["cache_key_match"]:
        failures.append(f"{name}: cache key {key} != golden "
                        f"{plan.cache_key}")

    # peer-form refit: observations at two calibration sizes per
    # (event position, peer field, rank residue) must fit one form
    # that reproduces the generator at 512
    ok = True
    cal = {n: gen(n) for n in CALIBRATION_NPS}
    big = gen(512)
    for pos in range(len(got[0])):
        for field in ("dest", "source"):
            if getattr(got[0][pos], field) is None:
                continue
            for res in range(period):
                obs = [(r, n, getattr(cal[n][r][pos], field))
                       for n in CALIBRATION_NPS
                       for r in range(res, n, period)]
                form = SY.fit_peer_form(obs)
                if form is None:
                    ok = False
                    failures.append(f"{name}: ev{pos}.{field} res{res} "
                                    "not affine-mod fittable")
                    continue
                for r in range(res, 512, period):
                    want = getattr(big[r][pos], field)
                    have = SY.instantiate_peer(form, r, 512)
                    if want != have:
                        ok = False
                        failures.append(
                            f"{name}: ev{pos}.{field} form {form} "
                            f"mispredicts rank {r} at np=512 "
                            f"({have} != {want})")
                        break
    out["peer_forms_rescale"] = ok
    return out


def run_ladder(ladder, concrete_cap, failures):
    """The differential ladder: both matchers + the prover per rung."""
    rows = []
    for name in sorted(FAMILIES):
        gen = FAMILIES[name][1]
        for n in ladder:
            sch = gen(n)
            comms = _world(n)
            row = {"family": name, "np": n,
                   "events_per_rank": len(sch[0])}

            cstats, sstats = {}, {}
            conc = None
            if n <= concrete_cap:
                t0 = time.perf_counter()
                conc = MT.match_schedules(sch, comms, stats=cstats)
                row["concrete"] = {
                    "time_s": round(time.perf_counter() - t0, 6),
                    "steps": cstats.get("steps", 0),
                }
            else:
                row["concrete"] = None

            t0 = time.perf_counter()
            part = SY.partition_schedules(sch, comms)
            sym = SY.match_schedules_symbolic(sch, comms, part,
                                              stats=sstats)
            row["symbolic"] = {
                "time_s": round(time.perf_counter() - t0, 6),
                "steps": sstats.get("steps", 0),
                "classes": part.n_classes,
            }

            if conc is not None:
                row["findings_equal"] = (
                    sorted(json.dumps(f.to_json(), sort_keys=True)
                           for f in sym)
                    == sorted(json.dumps(f.to_json(), sort_keys=True)
                              for f in conc))
                if not row["findings_equal"]:
                    failures.append(
                        f"{name} np={n}: symbolic/concrete findings "
                        "drift")
            if sym:
                failures.append(f"{name} np={n}: unexpected findings "
                                f"{[f.kind for f in sym]}")
            row["findings"] = len(sym)

            t0 = time.perf_counter()
            plan = PL.compile_schedules(sch, comms, world_size=n,
                                        symmetry=part)
            row["plan"] = {
                "time_s": round(time.perf_counter() - t0, 6),
                "proved": bool(plan.proved),
                "interleavings": (plan.proof or {}).get(
                    "interleavings"),
                "symmetry_classes": (plan.proof or {}).get(
                    "symmetry_classes"),
            }
            if not plan.proved:
                failures.append(f"{name} np={n}: plan NOT proved: "
                                f"{plan.reasons}")
            rows.append(row)
    return rows


# ---------------------------------------------------------------------------
# simulator oracles + tuner sanity at np=512


def _load_file(tag, *relpath):
    spec = importlib.util.spec_from_file_location(
        tag, os.path.join(REPO, *relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def run_oracles(n, n_islands, failures):
    import numpy as np

    sim = _load_file("m4j_scale_topo_sim",
                     "mpi4jax_tpu", "topo", "_simulate.py")
    topo = _load_file("m4j_scale_topo", "mpi4jax_tpu", "topo",
                      "__init__.py")

    islands, fake_spec = topo.synthetic_islands(n, n_islands)
    # the spec round-trips through the real FAKE_HOSTS parser — the
    # island map tested here is one a live discovery could produce
    labels = topo.parse_fake_hosts(fake_spec, n)
    derived: dict = {}
    for r, lab in enumerate(labels):
        derived.setdefault(lab, []).append(r)
    if sorted(derived.values()) != sorted(islands):
        failures.append("synthetic_islands spec does not round-trip "
                        "parse_fake_hosts")
    out = {"np": n, "islands": len(islands)}

    rng_vals = (np.arange(n * 64, dtype=np.float32).reshape(n, 64)
                % 37 - 18.0) / 7.0
    inputs = [rng_vals[r] for r in range(n)]
    exact = np.sum(np.stack(inputs, 0), axis=0, dtype=np.float64)

    for fn_name in ("simulate_hring_sum", "simulate_htree_sum"):
        got = getattr(sim, fn_name)(inputs, islands)
        err = float(np.max(np.abs(got.astype(np.float64) - exact)))
        rel = err / max(1.0, float(np.max(np.abs(exact))))
        out[fn_name + "_max_rel_err"] = rel
        if rel > 1e-5:
            failures.append(f"{fn_name} drifted from exact sum at "
                            f"np={n}: rel err {rel:.3e}")

    # alltoall: one 2-element chunk per peer; hierarchical must be
    # bit-identical to the flat pairwise exchange
    a2a_in = [(np.arange(n * 2, dtype=np.float32).reshape(n, 2)
               + 1000.0 * r) for r in range(n)]
    flat = [np.stack([a2a_in[src][dst] for src in range(n)])
            for dst in range(n)]
    hier = sim.simulate_halltoall(a2a_in)
    exact_a2a = all(np.array_equal(flat[d], hier[d]) for d in range(n))
    out["simulate_halltoall_exact"] = exact_a2a
    if not exact_a2a:
        failures.append(f"simulate_halltoall not bit-exact at np={n}")

    # quantized leader-leg alltoall: codec error only, bounded
    hq = sim.simulate_hqalltoall(a2a_in, islands)
    errs = [float(np.max(np.abs(hq[d] - flat[d]))) for d in range(n)]
    scale = float(np.max(np.abs(np.stack(flat))))
    out["simulate_hqalltoall_max_rel_err"] = max(errs) / scale
    if max(errs) / scale > 0.05:
        failures.append(f"simulate_hqalltoall codec error too large "
                        f"at np={n}: {max(errs) / scale:.3e}")
    return out


def run_tuner(n, failures):
    jt = _load_file("m4j_scale_tune_joint",
                    "mpi4jax_tpu", "tune", "_joint.py")

    cands = {op: jt.eligible_combos(op, multi_island=True,
                                    quant_mode="allow",
                                    hier_mode="allow", ici_leg=True)
             for op in ("allreduce", "alltoall")}
    sizes = [1 << s for s in range(12, 23, 2)]
    best, measurements, model = jt.joint_search(
        jt.synthetic_measure(n), cands, sizes, ranks=n)
    out = {"ranks": n,
           "ops": {op: len(cands[op]) for op in cands},
           "measurements": len(measurements),
           "winners": {op: {str(s): best[op][s] for s in sorted(best[op])}
                       for op in best}}
    for op, cs in cands.items():
        if op not in best or not best[op]:
            failures.append(f"joint_search found no winner for {op} "
                            f"at ranks={n}")
            continue
        for s, win in best[op].items():
            if win not in cs:
                failures.append(f"joint_search picked ineligible "
                                f"{win} for {op}@{s}")
    if model.world_size != n:
        failures.append("joint_search model lost the world size")
    return out


# ---------------------------------------------------------------------------


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--quick", action="store_true",
                    help="short ladder (to 64 ranks) for the tier-1 "
                         "wall-clock budget; does not write --out "
                         "unless given explicitly")
    ap.add_argument("--out", default=None,
                    help="bench JSON path (default "
                         "BENCH_verifier_scale.json at the repo root; "
                         "'-' to skip writing)")
    ap.add_argument("--budget-s", type=float, default=60.0,
                    help="hard wall budget for the whole run")
    ap.add_argument("--concrete-cap", type=int, default=None,
                    help="largest np the concrete matcher also runs "
                         "at (default 128; 32 under --quick)")
    args = ap.parse_args(argv)

    for knob in NORMALIZED_KNOBS:
        os.environ.pop(knob, None)

    ladder = NP_LADDER_QUICK if args.quick else NP_LADDER
    cap = args.concrete_cap if args.concrete_cap is not None \
        else (32 if args.quick else 128)
    out_path = args.out
    if out_path is None:
        out_path = (None if args.quick
                    else os.path.join(REPO, "BENCH_verifier_scale.json"))
    elif out_path == "-":
        out_path = None

    t_start = time.perf_counter()
    failures: list = []

    print(f"[scale] calibrating {len(FAMILIES)} corpus families "
          "against committed goldens")
    families = {name: calibrate_family(name, failures)
                for name in sorted(FAMILIES)}

    print(f"[scale] ladder {list(ladder)} (concrete to np={cap})")
    rows = run_ladder(ladder, cap, failures)

    top = max(ladder)
    print(f"[scale] simulator oracles at np={top}")
    oracles = run_oracles(top, n_islands=8, failures=failures)

    print(f"[scale] joint-tuner sanity at ranks={top}")
    tuner = run_tuner(top, failures)

    wall = time.perf_counter() - t_start
    peak_rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    if wall > args.budget_s:
        failures.append(f"wall budget blown: {wall:.1f}s > "
                        f"{args.budget_s:.0f}s")

    bench = {
        "schema": "verifier-scale/1",
        "generated_by": "tools/scale_harness.py",
        "analyzer_version": EV.ANALYZER_VERSION,
        "quick": bool(args.quick),
        "np_ladder": list(ladder),
        "concrete_cap": cap,
        "budget_s": args.budget_s,
        "wall_s": round(wall, 3),
        "peak_rss_kb": int(peak_rss_kb),
        "families": families,
        "rows": rows,
        "oracles": oracles,
        "tuner": tuner,
        "failures": failures,
    }
    if out_path:
        with open(out_path, "w") as fh:
            json.dump(bench, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"[scale] wrote {os.path.relpath(out_path, REPO)}")

    # human summary: per-family steps at the ladder ends show the
    # quotient's scaling (class-bound, not np-bound)
    by_family: dict = {}
    for row in rows:
        by_family.setdefault(row["family"], []).append(row)
    for name, frows in sorted(by_family.items()):
        lo, hi = frows[0], frows[-1]
        conc = (f"concrete {lo['concrete']['steps']}→"
                f"{[r for r in frows if r['concrete']][-1]['concrete']['steps']} steps"
                if lo.get("concrete") else "concrete n/a")
        print(f"[scale] {name}: np{lo['np']}→{hi['np']} symbolic "
              f"{lo['symbolic']['steps']}→{hi['symbolic']['steps']} "
              f"steps, {hi['symbolic']['classes']} classes, "
              f"proved={hi['plan']['proved']} ({conc})")
    print(f"[scale] wall {wall:.2f}s, peak RSS "
          f"{peak_rss_kb / 1024:.0f} MiB, failures: {len(failures)}")
    for f in failures:
        print(f"[scale] FAIL: {f}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
