#!/usr/bin/env python
"""Chaos fault matrix for the self-healing transport (``make chaos``).

Runs the deterministic 2-rank traffic program
(``tests/world_programs/heal_ops.py``) under every cell of

    {reset, drop, delay, corrupt} x {URING 0/1} x {shm on/off}
                                  x {engine on/off}

with the retry layer armed, and holds each cell to the chaos contract:

* **HEALED** — the job completes and both ranks' digests are
  bit-identical to the fault-free baseline (reconnect counters show
  the link layer actually worked);
* **CLEAN** — the job completes bit-identical without a reconnect
  (the fault had no wire surface in this cell — e.g. a delay below
  the deadline, or a byte-level fault armed on a thread that never
  writes TCP when the shm arena carries the traffic);
* **ESCALATED** — the job fails LOUDLY: the DEAD-link escalation line
  or a launcher post-mortem is in stderr (mid-collective resets on
  large frames are allowed to escalate — what is never allowed is a
  hang or a silent wrong answer);
* anything else — a hang (cell timeout), a silent failure, or a digest
  mismatch — **fails the matrix**.

``corrupt`` cells additionally require, on the TCP data path (shm
off), that the CRC actually caught the flipped byte: crc_errors >= 1
or the reconnect-forcing "header CRC mismatch" line.  (On shm cells
the corrupted header may land on a heartbeat instead; the digest
check still rules out silent corruption.)

uring=1 columns are skipped (visibly) when the kernel lacks io_uring.

Exit status: 0 iff every non-skipped cell lands in its contract.
"""

import argparse
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAUNCHER = os.path.join(REPO, "mpi4jax_tpu", "runtime", "launch.py")
PROGRAM = os.path.join(REPO, "tests", "world_programs", "heal_ops.py")

FAULTS = {
    "reset": "action=reset",
    "drop": "action=drop,bytes=20",
    "delay": "action=delay,ms=200",
    "corrupt": "action=corrupt",
}

_port = [49500 + (os.getpid() * 11) % 300]

_LINE_RE = re.compile(
    r"heal_ops (\d+) digest (\S+) reconnects (\d+) dup_dropped (\d+) "
    r"crc_errors (\d+) replayed (\d+) epoch (\d+)")


def run_cell(env_extra, timeout):
    _port[0] += 9
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["MPI4JAX_TPU_TIMEOUT_S"] = "30"
    env.update(env_extra)
    try:
        res = subprocess.run(
            [sys.executable, LAUNCHER, "-n", "2",
             "--port", str(_port[0]), PROGRAM],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=REPO)
    except subprocess.TimeoutExpired as e:
        return None, (e.stdout or b"").decode("utf-8", "replace"), \
            (e.stderr or b"").decode("utf-8", "replace")
    return res.returncode, res.stdout, res.stderr


def heal_lines(stdout):
    out = {}
    for m in _LINE_RE.finditer(stdout):
        out[int(m.group(1))] = (m.group(2),) + tuple(
            int(m.group(i)) for i in range(3, 8))
    return out


def cell_env(fault, uring, shm, engine):
    env = {
        "MPI4JAX_TPU_RETRY": "4",
        "MPI4JAX_TPU_RETRY_BACKOFF_MS": "50",
        "MPI4JAX_TPU_URING": uring,
        "MPI4JAX_TPU_DISABLE_SHM": "0" if shm == "on" else "1",
    }
    if engine == "on":
        env["MPI4JAX_TPU_PROGRESS_THREAD"] = "1"
        if shm == "on":
            # shm traffic can't be reset: the fault lands on the idle
            # TCP link underneath, and only the progress thread's
            # heartbeats can find it — give them an idle window
            env["MPI4JAX_TPU_HEARTBEAT_S"] = "0.2"
            env["HEAL_OPS_SLEEP_S"] = "1.5"
    else:
        env["MPI4JAX_TPU_PROGRESS_THREAD"] = "0"
    if fault is not None:
        env["MPI4JAX_TPU_FAULT"] = (
            "rank=0,point=send,after=5," + FAULTS[fault])
    return env


def uring_available():
    code = (
        "import sys, types, os; sys.path.insert(0, %r)\n"
        "pkg = types.ModuleType('mpi4jax_tpu')\n"
        "pkg.__path__ = [os.path.join(%r, 'mpi4jax_tpu')]\n"
        "sys.modules['mpi4jax_tpu'] = pkg\n"
        "from mpi4jax_tpu.runtime import bridge\n"
        "print('status=' + str(bridge.uring_status()))\n" % (REPO, REPO))
    res = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=300, env={**os.environ, "MPI4JAX_TPU_URING": "auto"},
        cwd=REPO)
    return any(line == "status=on" or line.startswith("status=on")
               for line in res.stdout.splitlines())


def classify(fault, shm, rc, stdout, stderr, baseline):
    """(verdict, pass?, note) for one cell run."""
    if rc is None:
        return "HANG", False, "cell timed out"
    lines = heal_lines(stdout)
    if rc == 0:
        if set(lines) != {0, 1}:
            return "NO-REPORT", False, "rank report lines missing"
        got = (lines[0][0], lines[1][0])
        if got != baseline:
            return "CORRUPTED", False, (
                f"digests {got} != fault-free {baseline}")
        healed = any(v[1] >= 1 for v in lines.values())
        if fault == "corrupt" and shm == "off":
            crc_seen = (any(v[3] >= 1 for v in lines.values())
                        or "header CRC mismatch" in stderr)
            if not crc_seen:
                return "UNDETECTED", False, (
                    "corrupt cell completed without a CRC detection")
        counters = "reconnects=%d+%d replayed=%d+%d" % (
            lines[0][1], lines[1][1], lines[0][4], lines[1][4])
        return ("HEALED" if healed else "CLEAN"), True, counters
    loud = ("escalating (poison -> abort -> elastic)" in stderr
            or "post-mortem" in stderr)
    if loud:
        return "ESCALATED", True, "loud failure (no hang, no corruption)"
    return "SILENT-FAIL", False, f"rc={rc} with no escalation evidence"


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--cell-timeout", type=float, default=120.0)
    ap.add_argument("--fault", choices=sorted(FAULTS), action="append",
                    help="restrict to specific fault(s)")
    args = ap.parse_args()
    faults = args.fault or ["reset", "drop", "delay", "corrupt"]

    urings = ["0"]
    if uring_available():
        urings.append("1")
    else:
        print("chaos: io_uring unavailable on this kernel — "
              "URING=1 column SKIPPED (poll column still runs)")

    # one fault-free baseline pins the bit-identical contract; the
    # digests are knob-independent (heal_ops asserts every payload)
    rc, stdout, stderr = run_cell(cell_env(None, "0", "off", "off"),
                                  args.cell_timeout)
    lines = heal_lines(stdout)
    if rc != 0 or set(lines) != {0, 1}:
        print("chaos: fault-free baseline failed:\n" + stderr[-2000:])
        return 2
    baseline = (lines[0][0], lines[1][0])
    print(f"chaos: baseline digests r0={baseline[0]} r1={baseline[1]}")

    failures = 0
    for fault in faults:
        for uring in urings:
            for shm in ("off", "on"):
                for engine in ("off", "on"):
                    rc, stdout, stderr = run_cell(
                        cell_env(fault, uring, shm, engine),
                        args.cell_timeout)
                    verdict, ok, note = classify(
                        fault, shm, rc, stdout, stderr, baseline)
                    tag = "ok  " if ok else "FAIL"
                    print(f"chaos: [{tag}] fault={fault:<7} "
                          f"uring={uring} shm={shm:<3} engine={engine:<3}"
                          f" -> {verdict:<10} {note}")
                    if not ok:
                        failures += 1
                        sys.stdout.write(stderr[-1500:] + "\n")
    # swap-during-reconnect: the live plane's epoch rendezvous must land
    # while the link layer is healing an injected reset — the table swap
    # may neither corrupt results (digests stay baseline: np=2 float64
    # SUM is one addition under every algorithm) nor wedge the heal.
    # Cell 1 fires the reset mid-phase-2 so the replay and the
    # rendezvous genuinely overlap on the TCP data path; cell 2 is the
    # shm/heartbeat variant (the reset lands on the idle TCP link and
    # only the progress thread's heartbeats find it, right before the
    # swap commits).
    if "reset" in faults:
        live_cells = [
            ("off", "off", "rank=0,point=send,after=13,action=reset"),
            ("on", "on", "rank=0,point=send,after=5,action=reset"),
        ]
        for shm, engine, fault_spec in live_cells:
            env = cell_env("reset", "0", shm, engine)
            env["MPI4JAX_TPU_FAULT"] = fault_spec
            env.update({
                "MPI4JAX_TPU_LIVE": "auto",
                "MPI4JAX_TPU_LIVE_COOLDOWN_OPS": "8",
                "HEAL_OPS_LIVE_SWAP": "1",
            })
            rc, stdout, stderr = run_cell(env, args.cell_timeout)
            verdict, ok, note = classify(
                "reset", shm, rc, stdout, stderr, baseline)
            lines = heal_lines(stdout)
            epochs = sorted({v[5] for v in lines.values()})
            if ok and rc == 0 and epochs != [1]:
                ok, note = False, f"swap epoch(s) {epochs} != [1]"
            elif ok and rc == 0:
                note += f" epoch={epochs[0]}"
            tag = "ok  " if ok else "FAIL"
            print(f"chaos: [{tag}] fault=reset+swap uring=0 "
                  f"shm={shm:<3} engine={engine:<3}"
                  f" -> {verdict:<10} {note}")
            if not ok:
                failures += 1
                sys.stdout.write(stderr[-1500:] + "\n")

    if failures:
        print(f"chaos: {failures} cell(s) violated the heal-or-escalate "
              "contract")
        return 1
    print("chaos: matrix green — every cell healed bit-identically or "
          "escalated loudly")
    return 0


if __name__ == "__main__":
    sys.exit(main())
