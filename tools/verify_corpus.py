#!/usr/bin/env python
"""CI gate over the world-program corpus: analyzer + schedule compiler.

    make verify-corpus        (or: python tools/verify_corpus.py)

For every program in ``tests/world_programs/golden_plans/manifest.json``:

- the static verifier (virtual world, no processes) must produce EXACTLY
  the expected finding kinds — any new kind fails the gate;
- the schedule compiler must produce a PROVED plan (the equivalence
  prover replays original and rewritten schedules through the match
  simulator; an unproved plan is a compiler regression);
- programs with a checked-in golden plan must compile to it exactly
  (``analysis.diff_plans``) — plan drift fails the gate with the diff.

Knob-derived thresholds are normalized (progress engine on, default
coalesce/bucket sizes) so the goldens are stable across CI hosts.
Exit code = number of failing programs.  ``--update-goldens`` rewrites
the golden files from the current compiler output (review the diff!).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROGRAMS = os.path.join(REPO, "tests", "world_programs")
GOLDEN_DIR = os.path.join(PROGRAMS, "golden_plans")
MANIFEST = os.path.join(GOLDEN_DIR, "manifest.json")

#: knobs that change plan thresholds — cleared so goldens are stable
NORMALIZED_KNOBS = (
    "MPI4JAX_TPU_PROGRESS_THREAD",
    "MPI4JAX_TPU_COALESCE_BYTES",
    "MPI4JAX_TPU_PLAN_BUCKET_KB",
    "MPI4JAX_TPU_PLAN",
    "MPI4JAX_TPU_FAULT",
)


def run(update_goldens: bool = False) -> int:
    saved = {k: os.environ.pop(k) for k in NORMALIZED_KNOBS
             if k in os.environ}
    try:
        return _run(update_goldens)
    finally:
        os.environ.update(saved)


def _run(update_goldens: bool) -> int:
    sys.path.insert(0, REPO)
    from mpi4jax_tpu import analysis

    with open(MANIFEST) as f:
        manifest = json.load(f)

    failures = 0
    for entry in manifest["programs"]:
        name, np_ = entry["program"], int(entry["np"])
        label = f"{name} --np {np_}"
        problems = []
        report = analysis.check_program(
            os.path.join(PROGRAMS, name), np_, timeout_s=240)
        kinds = sorted({f.kind for f in report.findings})
        if kinds != sorted(entry.get("kinds", [])):
            problems.append(
                f"finding kinds {kinds} != expected "
                f"{sorted(entry.get('kinds', []))}"
            )
        plan = analysis.plan_report(report)
        if not plan.proved:
            problems.append(f"plan NOT proved: {plan.reasons}")
        want_rewritten = entry.get("rewritten")
        if want_rewritten is not None and plan.rewritten != want_rewritten:
            problems.append(
                f"plan rewritten={plan.rewritten}, expected "
                f"{want_rewritten}"
            )
        golden_name = entry.get("golden")
        if golden_name:
            golden_path = os.path.join(GOLDEN_DIR, golden_name)
            if update_goldens:
                analysis.save_plan(plan, golden_path)
            else:
                try:
                    golden = analysis.load_plan(golden_path)
                except Exception as err:
                    golden = None
                    problems.append(f"cannot load golden: {err}")
                if golden is not None:
                    drift = analysis.diff_plans(golden, plan)
                    if drift:
                        problems.append("plan drift:\n" + drift)
        if problems:
            failures += 1
            print(f"FAIL  {label}")
            for p in problems:
                print(f"      {p}")
        else:
            extra = " [golden]" if golden_name else ""
            print(f"PASS  {label}  kinds={kinds} "
                  f"proved={plan.proved} rewritten={plan.rewritten}"
                  f"{extra}")
    total = len(manifest["programs"])
    print(f"verify-corpus: {total - failures}/{total} program(s) clean")
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tools/verify_corpus.py")
    ap.add_argument("--update-goldens", action="store_true",
                    help="rewrite the golden plan files from the current "
                         "compiler output (review the diff before "
                         "committing)")
    args = ap.parse_args(argv)
    return run(update_goldens=args.update_goldens)


if __name__ == "__main__":
    sys.exit(main())
