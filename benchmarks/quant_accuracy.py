"""Accuracy harness for quantized gradient allreduce: DP training steps
with int8-compressed gradient synchronization vs exact SUM.

    python benchmarks/quant_accuracy.py [--steps 20] [--np 4]
                                        [--algo auto|qring|qrd] [--seed 0]

Trains a tiny GPT-2-style causal LM on synthetic data twice from the
same initialization — once with exact data-parallel gradient sums, once
with the gradients synchronized through the NATIVE quantized collective
arithmetic (``ops/quantized.py``'s ``simulate_qring_sum`` /
``simulate_qrd_sum``, bit-identical to what ``qring``/``qrd`` compute
on the wire — test-enforced against the real library) — and reports the
per-step loss deviation.  One JSON line per step plus a summary record.

The documented bound (docs/usage.md § Quantized collectives): with
block-256 int8 quantization the relative loss deviation of a short DP
training run stays under **5e-2**; ``tests/test_quant_accuracy.py``
enforces it in CI.  No transport, no launcher: the harness measures the
QUANTIZATION error in isolation, deterministically.  (For an end-to-end
run over real sockets, launch ``examples/train_gpt.py`` under the
launcher with a quantized tune table — the wire math is the same.)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _load_quantized():
    try:
        from mpi4jax_tpu.ops import quantized

        return quantized
    except ImportError:  # package gate (old jax): load the module alone
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "m4j_quant_accuracy_codec",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "mpi4jax_tpu", "ops",
                "quantized.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod


# ---------------- tiny GPT-2-style causal LM (pure jax) ----------------


def gpt2_init(rng, vocab, d_model, n_layer, n_head, seq):
    """Parameter pytree for a small pre-LN transformer LM."""
    def norm(*shape, scale=0.02):
        return (rng.randn(*shape) * scale).astype(np.float32)

    params = {
        "wte": norm(vocab, d_model),
        "wpe": norm(seq, d_model),
        "ln_f": np.ones(d_model, np.float32),
    }
    for i in range(n_layer):
        params[f"h{i}"] = {
            "ln1": np.ones(d_model, np.float32),
            "attn_qkv": norm(d_model, 3 * d_model),
            "attn_out": norm(d_model, d_model),
            "ln2": np.ones(d_model, np.float32),
            "mlp_in": norm(d_model, 4 * d_model),
            "mlp_out": norm(4 * d_model, d_model),
        }
    return params


def gpt2_logits(params, tokens, n_layer, n_head):
    """Next-token logits of the tiny LM — the forward pass
    :func:`gpt2_loss` trains and ``examples/serve_gpt.py`` serves."""
    import jax.numpy as jnp

    def ln(x, g):
        mu = jnp.mean(x, -1, keepdims=True)
        var = jnp.var(x, -1, keepdims=True)
        return (x - mu) / jnp.sqrt(var + 1e-5) * g

    B, T = tokens.shape
    x = params["wte"][tokens] + params["wpe"][:T]
    mask = jnp.tril(jnp.ones((T, T), bool))
    for i in range(n_layer):
        h = params[f"h{i}"]
        a_in = ln(x, h["ln1"])
        qkv = a_in @ h["attn_qkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        d_head = q.shape[-1] // n_head

        def heads(t):
            return t.reshape(B, T, n_head, d_head).transpose(0, 2, 1, 3)

        att = (heads(q) @ heads(k).transpose(0, 1, 3, 2)) / np.sqrt(d_head)
        att = jnp.where(mask, att, -1e9)
        att = jnp.exp(att - jnp.max(att, -1, keepdims=True))
        att = att / jnp.sum(att, -1, keepdims=True)
        out = (att @ heads(v)).transpose(0, 2, 1, 3).reshape(B, T, -1)
        x = x + out @ h["attn_out"]
        m_in = ln(x, h["ln2"])
        m = jnp.maximum(m_in @ h["mlp_in"], 0.0)
        x = x + m @ h["mlp_out"]
    x = ln(x, params["ln_f"])
    return x @ params["wte"].T


def gpt2_loss(params, tokens, targets, n_layer, n_head):
    import jax.numpy as jnp

    logits = gpt2_logits(params, tokens, n_layer, n_head)
    logits = logits - jnp.max(logits, -1, keepdims=True)
    logp = logits - jnp.log(jnp.sum(jnp.exp(logits), -1, keepdims=True))
    nll = -jnp.take_along_axis(logp, targets[..., None], -1)
    return jnp.mean(nll)


# ---------------- DP training with pluggable gradient sync ----------------


def run_training(steps, nshards, sync, *, seed=0, vocab=64, d_model=32,
                 n_layer=2, n_head=4, seq=24, batch_per_shard=4, lr=0.05):
    """Train from a fixed init; ``sync(leaves) -> summed leaf`` combines
    the per-shard gradient leaves (each a list of ``nshards`` arrays).
    Returns the per-step full-batch losses."""
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(seed)
    params = gpt2_init(rng, vocab, d_model, n_layer, n_head, seq)
    B = nshards * batch_per_shard
    data = rng.randint(0, vocab, size=(steps + 1, B, seq + 1))

    loss_fn = jax.jit(
        lambda p, tok, tgt: gpt2_loss(p, tok, tgt, n_layer, n_head))
    grad_fn = jax.jit(jax.grad(
        lambda p, tok, tgt: gpt2_loss(p, tok, tgt, n_layer, n_head)))

    flat0, treedef = jax.tree_util.tree_flatten(params)
    losses = []
    for step in range(steps):
        tok = data[step][:, :-1]
        tgt = data[step][:, 1:]
        losses.append(float(loss_fn(params, jnp.asarray(tok),
                                    jnp.asarray(tgt))))
        # per-shard gradients (the DP decomposition), then the sync
        shard_flats = []
        for s in range(nshards):
            lo, hi = s * batch_per_shard, (s + 1) * batch_per_shard
            g = grad_fn(params, jnp.asarray(tok[lo:hi]),
                        jnp.asarray(tgt[lo:hi]))
            shard_flats.append([np.asarray(leaf)
                                for leaf in jax.tree_util.tree_flatten(g)[0]])
        synced = []
        for leaf_idx in range(len(flat0)):
            parts = [shard_flats[s][leaf_idx] for s in range(nshards)]
            shape = parts[0].shape
            summed = sync([p.reshape(-1) for p in parts]).reshape(shape)
            synced.append(summed.astype(np.float32) / nshards)
        grads = jax.tree_util.tree_unflatten(treedef, synced)
        params = jax.tree_util.tree_map(
            lambda p, g: np.asarray(p - lr * g, np.float32), params, grads)
    return losses


def exact_sync(parts):
    return np.sum(np.stack(parts), axis=0, dtype=np.float32)


def make_quant_sync(q, algo):
    """Gradient sync through the native quantized arithmetic: qring for
    payloads the engine would carry as the bandwidth twin, qrd for the
    latency sizes (mirroring tune.quantized_algorithm's 64 KB split)."""
    def sync(parts):
        if algo == "qring":
            return q.simulate_qring_sum(parts)
        if algo == "qrd":
            return q.simulate_qrd_sum(parts)
        nbytes = parts[0].size * 4
        fn = (q.simulate_qring_sum if nbytes >= 64 * 1024
              else q.simulate_qrd_sum)
        return fn(parts)

    return sync


def run_harness(steps=20, nshards=4, algo="auto", seed=0, emit=print,
                **model_kw):
    q = _load_quantized()
    exact = run_training(steps, nshards, exact_sync, seed=seed, **model_kw)
    quant = run_training(steps, nshards, make_quant_sync(q, algo),
                         seed=seed, **model_kw)
    rels = []
    for i, (le, lq) in enumerate(zip(exact, quant)):
        rel = abs(lq - le) / max(abs(le), 1e-9)
        rels.append(rel)
        emit(json.dumps({"step": i, "loss_exact": round(le, 6),
                         "loss_quant": round(lq, 6),
                         "rel_diff": round(rel, 6)}))
    summary = {
        "harness": "quant_accuracy",
        "model": "gpt2-tiny",
        "steps": steps,
        "dp_shards": nshards,
        "algo": algo,
        "final_loss_exact": round(exact[-1], 6),
        "final_loss_quant": round(quant[-1], 6),
        "max_rel_diff": round(max(rels), 6),
        "bound": 5e-2,
        "within_bound": max(rels) < 5e-2,
    }
    emit(json.dumps(summary))
    return summary


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--np", type=int, default=4, dest="np_",
                    help="emulated DP shard count")
    ap.add_argument("--algo", default="auto",
                    choices=("auto", "qring", "qrd"))
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    summary = run_harness(steps=args.steps, nshards=args.np_,
                          algo=args.algo, seed=args.seed)
    sys.exit(0 if summary["within_bound"] else 1)
