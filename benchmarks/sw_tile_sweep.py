"""Sweep the fused shallow-water kernel's (tile_rows, fuse) on the real
chip, plus the XLA step as control.  One jitted multi-step call per
config (the tunnel costs ~100 ms per dispatch); prints one JSON line per
config with ms/step.

    python benchmarks/sw_tile_sweep.py [--steps 64] [--size 1800 3600]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=64)
    ap.add_argument("--size", type=int, nargs=2, default=(1800, 3600))
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from mpi4jax_tpu.models.shallow_water import ShallowWater, SWParams
    from mpi4jax_tpu.parallel.grid import ProcessGrid

    grid = ProcessGrid((1, 1), devices=jax.devices()[:1])
    model = ShallowWater(grid, tuple(args.size), SWParams(dx=5e3, dy=5e3))
    n = args.steps

    configs = [("xla", None, None)]
    for fuse in (1, 2):
        for tr in (16, 32, 64, 128, 256):
            configs.append(("pallas", tr, fuse))

    state0 = model.step_fn(1, first=True)(model.init())
    best = None
    for impl, tr, fuse in configs:
        kw = {} if impl == "xla" else {"tile_rows": tr, "fuse": fuse}
        try:
            run = model.step_fn(n, first=False, impl=impl, **kw)
            float(jnp.sum(run(state0).h))  # compile + warmup
            t0 = time.perf_counter()
            float(jnp.sum(run(state0).h))
            dt = time.perf_counter() - t0
        except Exception as err:
            print(json.dumps({"impl": impl, "tile_rows": tr, "fuse": fuse,
                              "error": f"{type(err).__name__}: {err}"[:160]}),
                  flush=True)
            continue
        ms = dt / n * 1e3
        rec = {"impl": impl, "tile_rows": tr, "fuse": fuse,
               "ms_per_step": round(ms, 3),
               "total_s": round(dt, 3)}
        if best is None or ms < best["ms_per_step"]:
            best = rec
        print(json.dumps(rec), flush=True)
    print(json.dumps({"best": best}), flush=True)


if __name__ == "__main__":
    main()
