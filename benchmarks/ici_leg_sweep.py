"""ICI data-plane leg crossover sweep (the BENCH_ici_leg.json generator).

Measures the hierarchical allreduce with its intra-island legs on the
ICI data plane (``topo/_ici_leg.py`` — MPI4JAX_TPU_ICI_LEG, docs/usage.md
§ Transport tiers and topology) against the native intra paths and the
flat ring, per payload size, on a ``--fake-hosts`` virtual partition:

    python benchmarks/ici_leg_sweep.py \
        --shapes 'np4_2island=4:r0,r1|r2,r3;np8_2island=8:r0,r1,r2,r3|r4,r5,r6,r7' \
        --sizes 65536,1048576,4194304,16777216 --out BENCH_ici_leg.json

This is a DRIVER (run it directly, not under the launcher): the knob
under test is process-wide, so each variant — ``ring``, ``hring``,
``hring+ici`` (MPI4JAX_TPU_ICI_LEG=force), ``hring+q``
(MPI4JAX_TPU_COLL_QUANT=force), ``hring+q+ici`` (both) — runs as its
own launched sub-job, and the rank-0 rows are assembled into the
BENCH_hier_crossover-shaped artifact (``{"note", "config", "sweeps"}``;
rows are ``obs.bench_record`` dicts carrying the ``knobs`` stamp).

Bridge-level with the parent-package shim (no jax import in the
ranks), so it runs in ANY container; every row names the leg backend
it actually measured (``leg_backend``: ``"pallas"`` on a TPU slice
with jax >= 0.6, ``"numpy"`` — the bit-identical twin, Python-rate —
elsewhere).  Numbers from the numpy twin bound the SCHEDULE (frames,
phases, association), not the TPU kernel.

Timing is the raw-transport shape of ``allreduce_sweep.py --world``:
barrier-synchronized per-call medians through ``bridge.allreduce_raw``
with the algorithm forced per call, constant input re-fed every call
(no in-place growth), and a correctness check per size (exact
variants bit-equal to ``x * n``, quantized within the documented int8
bound) so a silently-degraded leg cannot produce a labeled curve.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VARIANTS = (
    ("ring", "ring", {}),
    ("hring", "hring", {}),
    ("hring+ici", "hring", {"MPI4JAX_TPU_ICI_LEG": "force"}),
    ("hring+q", "hring", {"MPI4JAX_TPU_COLL_QUANT": "force"}),
    ("hring+q+ici", "hring", {"MPI4JAX_TPU_ICI_LEG": "force",
                              "MPI4JAX_TPU_COLL_QUANT": "force"}),
)


def rank_main():
    sys.path.insert(0, REPO)
    import types

    pkg = types.ModuleType("mpi4jax_tpu")
    pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
    sys.modules["mpi4jax_tpu"] = pkg

    import numpy as np

    from mpi4jax_tpu import obs, topo, tune
    from mpi4jax_tpu.runtime import bridge, transport

    F32, SUM = 11, 0
    label = os.environ["M4J_ICI_SWEEP_LABEL"]
    algo = os.environ["M4J_ICI_SWEEP_ALGO"]
    sizes = [int(s) for s in os.environ["M4J_ICI_SWEEP_SIZES"].split(",")]
    code = tune.ALGO_CODES[algo]
    quant = os.environ.get("MPI4JAX_TPU_COLL_QUANT", "") == "force"

    comm = transport.get_world_comm()
    h, n = comm.handle, comm.size()
    t = comm.topology()
    st = topo.ici_leg_status(h)

    # rows go to a FILE (driver-provided path), not stdout: the
    # launcher multiplexes rank streams and can interleave mid-line,
    # which would corrupt JSON rows
    rows_path = os.environ["M4J_ICI_SWEEP_ROWS"]
    rows = []
    for size in sizes:
        x = np.ones(size // 4, np.float32)
        out = np.empty_like(x)
        bridge.allreduce_raw(h, x, out, F32, SUM, algo=code)  # warm + align
        # the labeled curve must measure what the label says: exact
        # variants are bit-equal to x*n (all-ones payloads sum exactly
        # under EVERY association), the quantized wire stays inside
        # its documented bound
        if quant:
            assert float(np.max(np.abs(out / n - 1.0))) < 5e-2, label
        else:
            assert np.array_equal(out, x * n), label
        calls = max(6, min(30, int(4e8 / max(size, 1))))
        times = []
        for _ in range(calls):
            bridge.barrier(h)
            t0 = time.perf_counter()
            bridge.allreduce_raw(h, x, out, F32, SUM, algo=code)
            times.append(time.perf_counter() - t0)
        dt = obs.percentile(times, 50)
        if comm.rank() == 0:
            extra = {}
            if t is not None and t.multi:
                extra["topology"] = t.fingerprint()
                extra["islands"] = [len(m) for m in t.islands]
            if st["active"]:
                extra["leg_backend"] = st["backend"]
            rows.append(obs.bench_record(
                op="allreduce", nbytes=size, seconds=dt, ranks=n,
                tier="world", algo=label, resolved_algo=algo,
                raw_p95_us=round(obs.percentile(times, 95) * 1e6, 1),
                raw_eff_GBps_per_chip=round(
                    2 * (n - 1) / n * size / dt / 1e9, 3),
                **extra,
            ))
    if comm.rank() == 0:
        with open(rows_path, "w") as f:
            json.dump(rows, f)
    print("ici_leg_sweep OK", comm.rank(), flush=True)


def driver():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--shapes",
        default="np4_2island=4:r0,r1|r2,r3",
        help="semicolon list of label=np:fake_hosts partitions")
    ap.add_argument("--sizes", default="65536,1048576,4194304,16777216")
    ap.add_argument("--port", type=int, default=47810)
    ap.add_argument("--out", default=None,
                    help="write the artifact here (default: stdout)")
    args = ap.parse_args()

    port = [args.port]
    fake_hosts, sweeps = {}, {}
    for shape in args.shapes.split(";"):
        label, spec = shape.split("=", 1)
        np_s, hosts = spec.split(":", 1)
        fake_hosts[label] = hosts
        rows = []
        for vlabel, algo, gates in VARIANTS:
            port[0] += int(np_s) + 5
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)
            env.pop("MPI4JAX_TPU_COLL_ALGO", None)
            for k in ("MPI4JAX_TPU_ICI_LEG", "MPI4JAX_TPU_COLL_QUANT"):
                env.pop(k, None)
            env.update(gates)
            env["JAX_PLATFORMS"] = "cpu"
            env["M4J_ICI_SWEEP_LABEL"] = vlabel
            env["M4J_ICI_SWEEP_ALGO"] = algo
            env["M4J_ICI_SWEEP_SIZES"] = args.sizes
            rows_path = os.path.join(
                tempfile.gettempdir(),
                f"m4j_ici_sweep_{os.getpid()}_{label}_{vlabel}.json")
            env["M4J_ICI_SWEEP_ROWS"] = rows_path
            try:
                res = subprocess.run(
                    [sys.executable,
                     os.path.join(REPO, "mpi4jax_tpu", "runtime",
                                  "launch.py"),
                     "-n", np_s, "--port", str(port[0]),
                     "--fake-hosts", hosts, os.path.abspath(__file__)],
                    capture_output=True, text=True, timeout=900, cwd=REPO,
                    env=env)
                if res.returncode != 0:
                    sys.stderr.write(res.stderr[-3000:] + res.stdout[-500:])
                    raise SystemExit(
                        f"ici_leg_sweep: variant {vlabel} ({label}) failed")
                with open(rows_path) as f:
                    got = json.load(f)
            finally:
                if os.path.exists(rows_path):
                    os.unlink(rows_path)
            rows.extend(got)
            print(f"# {label} {vlabel}: {len(got)} rows", file=sys.stderr,
                  flush=True)
        sweeps[label] = rows

    artifact = {
        "note": (
            "ICI data-plane leg crossover: benchmarks/ici_leg_sweep.py — "
            "forced hring through bridge.allreduce_raw under "
            "launch --fake-hosts virtual partitions, one sub-job per "
            "process-wide variant (ring / hring / hring+ici / hring+q / "
            "hring+q+ici; gates in each row's knobs stamp).  f32 SUM, "
            "barrier-synchronized raw-transport per-call medians, "
            "constant input re-fed per call.  Rows with leg_backend name "
            "the data plane that actually served the intra legs; "
            "'numpy' is the Pallas fused ring's bit-identical twin "
            "running at Python rate — those curves bound the SCHEDULE "
            "(frames, phases, association, wire codec), not the TPU "
            "kernel, and the +ici variants are expected to trail the "
            "native intra paths off-TPU.  Quantized variants are "
            "approximate by design (checked to the int8 bound in-run)."
        ),
        "config": {
            "env": {"JAX_PLATFORMS": "cpu"},
            "fake_hosts": fake_hosts,
            "dtype": "float32",
            "op": "SUM",
            "host_cores": os.cpu_count(),
        },
        "sweeps": sweeps,
    }
    text = json.dumps(artifact, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"# wrote {args.out}", file=sys.stderr)
    else:
        print(text)


if __name__ == "__main__":
    if os.environ.get("M4J_ICI_SWEEP_LABEL"):
        rank_main()
    else:
        driver()
