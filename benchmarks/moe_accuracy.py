"""Accuracy harness for quantized MoE dispatch: expert-parallel
training steps with int8-compressed alltoall exchange vs the exact
wire.

    python benchmarks/moe_accuracy.py [--steps 20] [--np 4] [--seed 0]
                                      [--legs dispatch|combine|both]

Trains a tiny top-1 MoE classifier on synthetic data twice from the
same initialization — once with exact dispatch/combine alltoalls, once
with every off-rank chunk pushed through the NATIVE int8+scales codec
arithmetic (the same per-256-element-block quantization ``qalltoall``
runs on the wire; the in-harness jnp twin is bit-pinned against
``ops/quantized.py``'s reference codec by ``tests/test_moe_accuracy.py``)
— and reports the per-step loss deviation.  One JSON line per step plus
a summary record.

The documented bound (docs/usage.md § MoE expert parallelism): with
block-256 int8 quantization of the routed activations the relative loss
deviation of a short expert-parallel training run stays under **5e-2**;
``tests/test_moe_accuracy.py`` enforces it in CI.  No transport, no
launcher: the harness measures the QUANTIZATION error in isolation,
deterministically — the backward pass sees the quantized values through
a straight-through estimator, matching how a real run trains through
the lossy wire.  (For the live schedules over real sockets, see
``tests/world/test_moe_alltoall.py``.)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

QUANT_BLOCK = 256


# ---------------- the wire codec, as a traced jnp twin ----------------


def qdq_vals(v):
    """Quantize+dequantize along the last axis with the native codec's
    block layout: per-256-element absmax scale, symmetric int8 codes,
    round-half-even — the exact arithmetic ``qalltoall`` runs on every
    off-rank chunk.  Works on numpy and traced jnp arrays alike."""
    import jax.numpy as jnp

    v = jnp.asarray(v, jnp.float32)
    n = v.shape[-1]
    pad = (-n) % QUANT_BLOCK
    vp = jnp.pad(v, [(0, 0)] * (v.ndim - 1) + [(0, pad)])
    b = vp.reshape(v.shape[:-1] + (-1, QUANT_BLOCK))
    amax = jnp.max(jnp.abs(b), axis=-1, keepdims=True)
    scale = amax / jnp.float32(127.0)
    safe = jnp.where(scale > 0, scale, jnp.float32(1.0))
    codes = jnp.clip(jnp.round(b / safe), -127, 127)
    deq = (codes * scale).reshape(vp.shape)
    return deq[..., :n] if pad else deq


def _make_qdq_st():
    """Straight-through wrapper: forward = wire codec, backward =
    identity — gradients flow through the lossy exchange the way a real
    quantized-dispatch training run sees them."""
    import jax

    @jax.custom_vjp
    def qdq_st(x):
        return qdq_vals(x)

    def fwd(x):
        return qdq_vals(x), None

    def bwd(_, g):
        return (g,)

    qdq_st.defvjp(fwd, bwd)
    return qdq_st


# ---------------- tiny expert-parallel MoE classifier ----------------


def moe_init(rng, d_model, d_ff, n_experts, vocab):
    def norm(*shape, scale=0.2):
        return (rng.randn(*shape) * scale).astype(np.float32)

    return {
        "w_gate": norm(d_model, n_experts, scale=0.5),
        "w_in": norm(n_experts, d_model, d_ff),
        "b_in": np.zeros((n_experts, d_ff), np.float32),
        "w_out": norm(n_experts, d_ff, d_model),
        "b_out": np.zeros((n_experts, d_model), np.float32),
        "w_cls": norm(d_model, vocab, scale=0.1),
    }


def moe_loss(params, x, targets, capacity, wire):
    """Full-batch forward of the emulated expert-parallel MoE: ``x`` is
    ``(shards, tokens, d)`` — shard ``s`` owns expert ``s`` — and
    ``wire`` transforms each flattened (src, dst) chunk of the dispatch
    and combine exchanges (identity for the exact run, the int8 codec
    for the quantized one; own-rank chunks are ALWAYS exact, matching
    ``qalltoall``)."""
    import jax
    import jax.numpy as jnp

    S, T, D = x.shape
    E = S  # one expert per shard

    logits = x @ params["w_gate"]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    idx = jnp.argmax(probs, axis=-1).astype(jnp.int32)  # (S, T)
    prob = jnp.take_along_axis(probs, idx[..., None], axis=-1)[..., 0]

    oh = jax.nn.one_hot(idx, E, dtype=jnp.int32)
    pos = jnp.sum(jnp.cumsum(oh, axis=1) * oh, axis=-1) - 1  # (S, T)
    keep = (pos >= 0) & (pos < capacity)
    pos_c = jnp.clip(pos, 0, capacity - 1)

    buf = jnp.zeros((S, E, capacity, D), x.dtype)
    src = jnp.arange(S)[:, None].repeat(T, 1)
    buf = buf.at[src, idx, pos_c].add(
        jnp.where(keep[..., None], x, jnp.zeros_like(x)))

    def exchange(b):
        # wire every off-diagonal (src, dst) chunk; the own chunk never
        # leaves the rank and stays exact
        flat = b.reshape(S, E, capacity * D)
        wired = wire(flat).reshape(b.shape)
        own = jnp.eye(S, E, dtype=bool)[:, :, None, None]
        return jnp.where(own, b, wired)

    sent = exchange(buf)  # dispatch leg
    recv = sent.transpose(1, 0, 2, 3)  # (E, S, cap, D): expert e's view
    h = jnp.maximum(
        jnp.einsum("escd,edf->escf", recv, params["w_in"])
        + params["b_in"][:, None, None], 0.0)
    out = (jnp.einsum("escf,efd->escd", h, params["w_out"])
           + params["b_out"][:, None, None])
    back = exchange(out.transpose(1, 0, 2, 3)).transpose(1, 0, 2, 3)
    # (E, S, cap, D) -> shard s gathers its tokens back
    per_shard = back.transpose(1, 0, 2, 3)  # (S, E, cap, D)
    y = per_shard[src, idx, pos_c]  # (S, T, D)
    y = jnp.where(keep[..., None], y, jnp.zeros_like(y))
    hres = x + y * prob[..., None]

    cls = hres @ params["w_cls"]
    cls = cls - jnp.max(cls, -1, keepdims=True)
    logp = cls - jnp.log(jnp.sum(jnp.exp(cls), -1, keepdims=True))
    nll = -jnp.take_along_axis(logp, targets[..., None], -1)
    return jnp.mean(nll)


def run_training(steps, nshards, quantized, *, seed=0, d_model=16,
                 d_ff=32, vocab=16, tokens_per_shard=8,
                 capacity_factor=1.25, lr=0.1):
    """Train from a fixed init; returns the per-step losses."""
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(seed)
    params = moe_init(rng, d_model, d_ff, nshards, vocab)
    data = rng.randn(steps, nshards, tokens_per_shard,
                     d_model).astype(np.float32)
    targets = rng.randint(0, vocab,
                          size=(steps, nshards, tokens_per_shard))
    capacity = max(1, int(np.ceil(
        tokens_per_shard / nshards * capacity_factor)))

    wire = _make_qdq_st() if quantized else (lambda v: v)
    loss_fn = jax.jit(lambda p, x, t: moe_loss(p, x, t, capacity, wire))
    grad_fn = jax.jit(jax.grad(
        lambda p, x, t: moe_loss(p, x, t, capacity, wire)))

    losses = []
    for step in range(steps):
        x = jnp.asarray(data[step])
        tgt = jnp.asarray(targets[step])
        losses.append(float(loss_fn(params, x, tgt)))
        g = grad_fn(params, x, tgt)
        params = jax.tree_util.tree_map(
            lambda p, gg: np.asarray(p - lr * gg, np.float32), params, g)
    return losses


def run_harness(steps=20, nshards=4, seed=0, emit=print, **model_kw):
    exact = run_training(steps, nshards, False, seed=seed, **model_kw)
    quant = run_training(steps, nshards, True, seed=seed, **model_kw)
    rels = []
    for i, (le, lq) in enumerate(zip(exact, quant)):
        rel = abs(lq - le) / max(abs(le), 1e-9)
        rels.append(rel)
        emit(json.dumps({"step": i, "loss_exact": round(le, 6),
                         "loss_quant": round(lq, 6),
                         "rel_diff": round(rel, 6)}))
    summary = {
        "harness": "moe_accuracy",
        "model": "moe-top1-tiny",
        "steps": steps,
        "experts": nshards,
        "final_loss_exact": round(exact[-1], 6),
        "final_loss_quant": round(quant[-1], 6),
        "max_rel_diff": round(max(rels), 6),
        "bound": 5e-2,
        "within_bound": max(rels) < 5e-2,
    }
    emit(json.dumps(summary))
    return summary


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--np", type=int, default=4, dest="np_",
                    help="emulated expert-parallel shard count")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    summary = run_harness(steps=args.steps, nshards=args.np_,
                          seed=args.seed)
    sys.exit(0 if summary["within_bound"] else 1)
