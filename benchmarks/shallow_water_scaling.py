"""Weak-scaling study for the shallow-water app (BASELINE north star:
≥80% weak-scaling efficiency).

Each rank keeps a fixed local block; the global domain grows with the
grid.  One JSON line per configuration.  On the virtual CPU mesh this
validates the harness; the numbers that matter come from a TPU slice.

    python benchmarks/shallow_water_scaling.py --local 256 256 --steps 50
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run(grid_shape, local, steps):
    import jax

    from mpi4jax_tpu.models.shallow_water import ShallowWater, SWParams
    from mpi4jax_tpu.parallel.grid import ProcessGrid

    gy, gx = grid_shape
    ny, nx = gy * local[0], gx * local[1]
    grid = ProcessGrid(grid_shape)
    model = ShallowWater(grid, (ny, nx), SWParams(dx=5e3, dy=5e3))
    state = model.init()
    state = model.step_fn(1, first=True)(state)
    fn = model.step_fn(steps, first=False)
    jax.block_until_ready(fn(state))  # compile + warmup
    t0 = time.perf_counter()
    out = fn(state)
    jax.block_until_ready(out.h)
    dt = time.perf_counter() - t0
    return steps / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--local", type=int, nargs=2, default=(128, 128))
    ap.add_argument("--steps", type=int, default=30)
    args = ap.parse_args()

    import jax

    ndev = len(jax.devices())
    configs = []
    n = 1
    while n <= ndev:
        gy = 1
        for cand in range(int(np.sqrt(n)), 0, -1):
            if n % cand == 0:
                gy = cand
                break
        configs.append((gy, n // gy))
        n *= 2

    base = None
    for shape in configs:
        sps = run(shape, tuple(args.local), args.steps)
        ndev_used = shape[0] * shape[1]
        if base is None:
            base = sps
        eff = sps / base
        print(json.dumps({
            "bench": "shallow_water_weak_scaling",
            "grid": list(shape), "devices": ndev_used,
            "local_block": list(args.local),
            "steps_per_s": round(sps, 2),
            "weak_scaling_efficiency": round(eff, 3),
            "platform": jax.devices()[0].platform,
        }), flush=True)


if __name__ == "__main__":
    main()
