"""Allreduce microbenchmark: float32 message sweep (BASELINE.json config).

Effective bandwidth is reported ring-style: ``2*(n-1)/n * bytes / time``
per chip.  Runs on whatever devices are visible (real TPUs or the virtual
CPU mesh); one JSON line per message size.

    python benchmarks/allreduce_sweep.py [--max-mb 256] [--world] [--pallas]

``--world`` benchmarks the world tier (native transport) instead, under
the launcher.  ``--algos ring,qring,rd,qrd,tree,hring,htree`` (world
tier) additionally sweeps each FORCED collective algorithm — including
the quantized wire formats and the hierarchical (topology-aware)
schedules — and emits one LOGICAL GB/s curve per algorithm (``"algo"``
field in every record; quantized records add ``wire_bytes`` and
``compression``) — the per-algorithm evidence the BENCH artifact,
the crossover curves in docs/benchmarks.md, and the tune package's
defaults rest on.  When the job discovered a topology every record is
stamped with its fingerprint (``topology`` / ``islands``), and
hierarchical records carry the analytic per-leg byte split
(``intra_bytes`` / ``inter_bytes``); run under
``launch --fake-hosts 'r0,r1|r2,r3'`` (or a real multi-host layout) to
measure them for real — on a flat comm they degrade to their flat
twins.  The raw-transport loop runs IN PLACE
(sendbuf == recvbuf, the donated-buffer steady state) and reports
per-call medians.  ``--pallas`` benchmarks
the Pallas RDMA ring collectives (``ops/pallas_collectives.py``) — on TPU
meshes this times the real inter-chip DMA kernels; off-TPU they run
interpreted and the numbers only establish correctness-path overhead.

``--latency`` (world tier) switches to the small-message mode: a
1 B – 64 KiB sweep reporting p50/p95/p99 microseconds per op — both
in-jit (the serving-traffic shape the async progress engine targets)
and at the raw transport — instead of GB/s, which hides small-message
regressions (the BENCH_r05 72 us figure was invisible in the
bandwidth curves).

``--knob-grid`` (a DRIVER mode — run it directly, not under the
launcher) launches one sub-job per hand-set knob combination
(``MPI4JAX_TPU_COLL_ALGO`` x ``MPI4JAX_TPU_COLL_QUANT`` x, under
``--fake-hosts``, ``MPI4JAX_TPU_HIER``) and emits every record stamped
with the combination it ran under (``grid_env`` + the ``knobs`` stamp
every ``obs.bench_record`` row carries), closing with one
``knob_grid_best`` summary per size — the best any ONE process-wide
hand-set combination achieves, which is exactly the baseline a single
``python -m mpi4jax_tpu.tune --joint`` run has to beat
(docs/benchmarks.md § Joint tuner, BENCH_joint_tuner.json).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def mesh_tier_sweep(max_bytes, pallas=False):
    import jax
    import jax.numpy as jnp
    import numpy as np

    import mpi4jax_tpu as m4j

    ndev = len(jax.devices())
    mesh = m4j.make_mesh(ndev)
    results = []
    size = 1024
    while size <= max_bytes:
        n = size // 4
        x = jnp.ones((ndev * n,), jnp.float32)
        if pallas:
            from mpi4jax_tpu.ops import pallas_collectives as pc

            fn = jax.jit(
                m4j.spmd(lambda v: pc.allreduce_sum(v, "mpi"), mesh=mesh)
            )
        else:
            fn = jax.jit(
                m4j.spmd(lambda v: m4j.allreduce(v, op=m4j.SUM), mesh=mesh)
            )
        jax.block_until_ready(fn(x))  # compile + warmup
        reps = max(3, min(50, int(2e8 / max(size, 1))))
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(x)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / reps
        from mpi4jax_tpu import obs

        # shared benchmark serializer (obs.bench_record): same field
        # names as the world sweep, BENCH artifacts, and profile reports
        rec = obs.bench_record(
            op="allreduce", nbytes=size, seconds=dt, ranks=ndev,
            tier="pallas" if pallas else "mesh", devices=ndev,
            platform=jax.devices()[0].platform,
        )
        print(json.dumps(rec), flush=True)
        results.append(rec)
        size *= 4
    return results


def world_tier_rank(max_bytes, sizes=None, algos=None):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    import mpi4jax_tpu as m4j
    from mpi4jax_tpu import tune
    from mpi4jax_tpu.runtime import bridge

    comm = m4j.get_default_comm()
    import numpy as np

    n = comm.size()
    # normalize up front ("recursive_doubling" -> "rd"): the names key
    # into ALGO_CODES below
    algo_list = [a if a == "auto" else tune._check_algo(a)
                 for a in (algos or ["auto"])]
    topology = comm.topology()
    if any(a != "auto" for a in algo_list):
        active, _, _ = bridge.shm_info(comm.handle)
        if active and comm.rank() == 0:
            print("# WARNING: the shm arena is active — forced algorithms "
                  "are no-ops there (every curve measures the arena); set "
                  "MPI4JAX_TPU_DISABLE_SHM=1 to sweep the TCP algorithms",
                  flush=True)
        if (any(a in tune.HIER_ALGOS for a in algo_list)
                and (topology is None or not topology.multi)
                and comm.rank() == 0):
            print("# WARNING: hring/htree requested on a FLAT comm — they "
                  "degrade to their flat twins (ring/tree); partition the "
                  "job with launch --fake-hosts 'r0,r1|r2,r3' (or run "
                  "multi-host) to measure the hierarchy", flush=True)
    size_list = sizes or []
    if not size_list:
        size = 1024
        while size <= max_bytes:
            size_list.append(size)
            size *= 4
    for size in size_list:
        # Small sizes: K ops inside ONE jit call — a per-call dispatch of
        # an ordered-effects computation goes through JAX's Python path
        # (~300 us, and 8-ranks-on-one-core hosts serialize it rank by
        # rank), which would swamp a microsecond-scale transport.  Real
        # programs amortize it the same way: comm ops live inside jitted
        # step functions.  Large sizes: direct calls (dispatch is noise
        # there, and carrying a multi-MB array through lax.scan makes
        # XLA copy the carry every iteration).  The executables carry
        # nothing algorithm-dependent (the native layer re-reads the
        # decision table per call), so one compile serves every algo.
        if size < 1 << 20:
            K = max(4, min(50, int(2e7 / max(size, 1))))

            @jax.jit
            def many(v):
                def step(c, _):
                    return m4j.allreduce(c, op=m4j.SUM, comm=comm), ()
                out, _ = jax.lax.scan(step, v, None, length=K)
                return out
        else:
            # donated input + operand/result aliasing = true in-place
            # allreduce (the steady-state shape of a training loop that
            # reuses its buffers); without donation XLA must copy the
            # 16 MB operand every call to protect the caller's buffer
            fn = jax.jit(lambda v: m4j.allreduce(v, op=m4j.SUM, comm=comm),
                         donate_argnums=0)
            K = 1

        for algo in algo_list:
            # forced algorithm: an engine override steers the jitted path
            # (no retrace — see above); the raw loop below forces per call
            if algo != "auto":
                tune.set_algorithm("allreduce", algo)
            else:
                tune.clear_overrides()
            x = jnp.ones((size // 4,), jnp.float32)
            if size < 1 << 20:
                # steady state is the deployment shape (comm ops live
                # inside a long-running training loop): the first few
                # executions of a fresh executable run 2-7x slower
                # (allocator warmup, branch/cache training, cross-rank
                # convoy alignment — measured on this host), so warm up
                # past them and report the median of per-call timings
                calls = 8
                for _ in range(4):
                    out = many(x)
                jax.block_until_ready(out)
                times = []
                for _ in range(calls):
                    t0 = time.perf_counter()
                    out = many(x)
                    jax.block_until_ready(out)
                    times.append(time.perf_counter() - t0)
                times.sort()
                dt = times[len(times) // 2] / K
            else:
                calls = max(6, min(24, int(5e8 / size)))
                out = fn(x)  # donates x: re-created per algo above
                jax.block_until_ready(out)
                t0 = time.perf_counter()
                for _ in range(calls):
                    out = fn(out)
                jax.block_until_ready(out)
                dt = (time.perf_counter() - t0) / (calls * K)

            # transport-level latency: the native call with every argument
            # pre-marshalled — no JAX, no numpy wrapper work in the loop —
            # isolates the wire/arena cost itself
            import ctypes

            from mpi4jax_tpu.ops.reduce_ops import ALL_OPS
            from mpi4jax_tpu.utils import dtypes as _dtypes

            # IN-PLACE (sendbuf == recvbuf): the steady-state shape a
            # training loop's donated buffers give the in-jit path —
            # separate in/out buffers would add one 16 MB memcpy per
            # call to EVERY algorithm and dilute their differences
            a = np.ones(size // 4, np.float32)
            lib = bridge.get_lib()
            sum_code = next(i for i, op in enumerate(ALL_OPS)
                            if op.name == "SUM")
            args_native = [
                ctypes.c_int64(comm.handle),
                a.ctypes.data_as(ctypes.c_void_p),
                a.ctypes.data_as(ctypes.c_void_p),
                ctypes.c_int64(a.size),
                ctypes.c_int(_dtypes.wire_code(a.dtype)),
                ctypes.c_int(sum_code),
            ]
            if algo != "auto":
                if not hasattr(lib, "tpucomm_allreduce_algo"):
                    # silently timing the default schedule under a forced
                    # label would fabricate the per-algorithm curves
                    raise RuntimeError(
                        "--algos needs a native library with the algorithm "
                        "engine (tpucomm_allreduce_algo); rebuild native/"
                    )
                # forced per call — independent of the table override above
                fn_native = lib.tpucomm_allreduce_algo
                args_native.append(ctypes.c_int(tune.ALGO_CODES[algo]))
            else:
                fn_native = lib.tpucomm_allreduce
            args_native = tuple(args_native)
            rc = fn_native(*args_native)  # align ranks on the same op count
            raw_times = []
            barrier = lib.tpucomm_barrier
            hc = ctypes.c_int64(comm.handle)
            for _ in range(calls * K):
                # barrier-synchronized start: each sample measures the
                # COLLECTIVE's latency from an all-ranks-ready state
                # (the barrier is outside the timed window, identical
                # for every algorithm) — back-to-back free-running
                # calls accumulate rank drift whose stalls land on
                # whichever algorithm runs second, an artifact of the
                # loop rather than of the schedule being measured
                barrier(hc)
                t0 = time.perf_counter()
                rc |= fn_native(*args_native)
                raw_times.append(time.perf_counter() - t0)
            if rc != 0:
                raise RuntimeError(f"native allreduce failed (rc={rc})")
            from mpi4jax_tpu import obs

            # median per call: robust to preemption outliers on the
            # oversubscribed CI hosts these curves are measured on
            raw_dt = obs.percentile(raw_times, 50)

            if comm.rank() == 0:
                # what actually served the call: "shm" on an arena comm
                # (forced algorithms are no-ops there), else the engine's
                # pick / the forced algorithm
                probed = comm.coll_algo("allreduce", size)
                resolved = (probed if (probed == "shm" or algo == "auto")
                            else algo)
                extra = {}
                if resolved in ("qring", "qrd") and bridge.quant_available():
                    # logical vs on-wire payload: the curves report
                    # LOGICAL GB/s (comparable across wire formats);
                    # the compression ratio names the byte saving
                    wb = bridge.quant_packed_bytes(size // 4)
                    extra = {"wire_bytes": wb,
                             "compression": round(size / wb, 3)}
                if topology is not None and topology.multi:
                    # the shape this curve was measured on: joinable
                    # with the topology-keyed tune cache
                    extra["topology"] = topology.fingerprint()
                    extra["islands"] = [len(m) for m in topology.islands]
                    if resolved in tune.HIER_ALGOS:
                        # analytic per-leg wire-byte split (job total):
                        # the intra/inter asymmetry the hierarchy buys
                        leg = topology.leg_bytes(resolved, size)
                        extra["intra_bytes"] = leg["intra"]
                        extra["inter_bytes"] = leg["inter"]
                # shared serializer (obs.bench_record) keeps this curve
                # field-compatible with BENCH_*.json and profile reports
                print(json.dumps(obs.bench_record(
                    op="allreduce", nbytes=size, seconds=dt, ranks=n,
                    tier="world", algo=algo,
                    resolved_algo=resolved,
                    raw_seconds=round(raw_dt, 9),
                    raw_p95_us=round(obs.percentile(raw_times, 95) * 1e6,
                                     1),
                    ops_per_jit=K,
                    raw_eff_GBps_per_chip=round(
                        2 * (n - 1) / n * size / raw_dt / 1e9, 3
                    ),
                    **extra,
                )), flush=True)
    tune.clear_overrides()


def world_latency_rank(sizes=None):
    """Small-message latency mode: p50/p95/p99 microseconds per op over
    a 1 B – 64 KiB uint8 sweep, in-jit and at the raw transport.

    In-jit per-op samples come from repeated calls of one jitted
    scan-of-K step (per-op = call / K, one sample per call) — the same
    amortized-dispatch shape as the GB/s sweep, but keeping the full
    distribution instead of one median.  Raw samples time every native
    call individually.
    """
    import ctypes

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    import mpi4jax_tpu as m4j
    from mpi4jax_tpu import obs
    from mpi4jax_tpu.runtime import bridge
    from mpi4jax_tpu.utils import dtypes as _dtypes

    comm = m4j.get_default_comm()
    n = comm.size()
    size_list = sizes or [1, 4, 16, 64, 256, 1024, 4096, 16384, 65536]
    for size in size_list:
        K = max(8, min(64, int(4e6 / max(size, 64))))

        @jax.jit
        def many(v):
            def step(c, _):
                return m4j.allreduce(c, op=m4j.SUM, comm=comm), ()
            out, _ = jax.lax.scan(step, v, None, length=K)
            return out

        x = jnp.ones((size,), jnp.uint8)
        calls = 16
        for _ in range(4):  # warmup: allocator/caches/convoy alignment
            out = many(x)
        jax.block_until_ready(out)
        jit_us = []
        for _ in range(calls):
            t0 = time.perf_counter()
            out = many(x)
            jax.block_until_ready(out)
            jit_us.append((time.perf_counter() - t0) / K * 1e6)

        # raw transport: every native call timed individually
        a = np.ones(size, np.uint8)
        o = np.empty_like(a)
        lib = bridge.get_lib()
        fn = lib.tpucomm_allreduce
        args = (ctypes.c_int64(comm.handle),
                a.ctypes.data_as(ctypes.c_void_p),
                o.ctypes.data_as(ctypes.c_void_p),
                ctypes.c_int64(a.size),
                ctypes.c_int(_dtypes.wire_code(a.dtype)),
                ctypes.c_int(0))
        raw_reps = calls * K
        rc = fn(*args)  # align ranks on the same op count
        raw_us = []
        for _ in range(raw_reps):
            t0 = time.perf_counter()
            rc |= fn(*args)
            raw_us.append((time.perf_counter() - t0) * 1e6)
        if rc != 0:
            raise RuntimeError(f"native allreduce failed (rc={rc})")

        # syscalls-per-message (the submit-batching column): a short
        # untimed pass with the obs recorder armed averages the native
        # per-event `syscalls` field; None on a pre-uring .so, which
        # never writes it (the timing loops above stay unperturbed)
        sys_per_msg = None
        from mpi4jax_tpu.obs import _native as _obs_native

        if (_obs_native.available(lib)
                and _obs_native.syscalls_available(lib)):
            obs.reset() if obs.enabled() else obs.start(lib=lib)
            obs.events()  # drain anything stale
            for _ in range(min(100, raw_reps)):
                rc |= fn(*args)
            evs = [e for e in obs.events()
                   if e.get("src") == "native" and e["name"] == "Allreduce"]
            if evs:
                sys_per_msg = round(
                    sum(int(e.get("syscalls", 0)) for e in evs)
                    / len(evs), 3)
            obs.stop()
            if rc != 0:
                raise RuntimeError(f"native allreduce failed (rc={rc})")

        if comm.rank() == 0:
            uring = bridge.uring_status() or "unavailable(pre-uring .so)"
            rec = obs.bench_record(
                op="allreduce", nbytes=size,
                seconds=obs.percentile(jit_us, 50) / 1e6, ranks=n,
                tier="world", mode="latency", ops_per_jit=K, calls=calls,
                p50_us=round(obs.percentile(jit_us, 50), 3),
                p95_us=round(obs.percentile(jit_us, 95), 3),
                p99_us=round(obs.percentile(jit_us, 99), 3),
                raw_p50_us=round(obs.percentile(raw_us, 50), 3),
                raw_p95_us=round(obs.percentile(raw_us, 95), 3),
                raw_p99_us=round(obs.percentile(raw_us, 99), 3),
                resolved_algo=comm.coll_algo("allreduce", size),
                uring=uring,
                syscalls_per_msg=sys_per_msg,
            )
            print(json.dumps(rec), flush=True)


def knob_grid_driver(args):
    """Launch one sub-job per hand-set knob combination and emit every
    record stamped with the combination, plus a best-per-size summary.

    The grid is the space an operator can actually SET process-wide:
    a forced algorithm (or the engine default), the quantization gate,
    and — on a partitioned shape — the hierarchy gate.  Per-size
    mix-and-match is exactly what a single hand-set combination cannot
    do; the joint tuner's cache can, which is the comparison this mode
    exists to anchor."""
    import subprocess
    import tempfile

    np_ = args.np or 4
    sizes = args.sizes or "4194304,16777216"
    here = os.path.abspath(__file__)
    grid_tmp = tempfile.mkdtemp(prefix="m4j_knob_grid_")
    combos = []
    for algo in (None, "ring", "rd", "tree"):
        for quant in (None, "force"):
            base = {}
            if algo:
                base["MPI4JAX_TPU_COLL_ALGO"] = algo
            if quant:
                base["MPI4JAX_TPU_COLL_QUANT"] = quant
            combos.append(base)
    if args.fake_hosts:
        # the hierarchy axis only exists on a multi-island shape (on a
        # flat comm HIER=force is a no-op and would double the grid
        # for identical measurements)
        combos += [dict(c, MPI4JAX_TPU_HIER="force") for c in combos]

    rows = []
    for i, combo in enumerate(combos):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        if args.fake_hosts:
            env["MPI4JAX_TPU_FAKE_HOSTS"] = args.fake_hosts
        else:
            # an inherited partition would give the sub-jobs a
            # multi-island topology while the grid skips the HIER axis
            # and the summary claims a flat shape — the grid's shape is
            # --fake-hosts or nothing
            env.pop("MPI4JAX_TPU_FAKE_HOSTS", None)
            env["MPI4JAX_TPU_DISABLE_SHM"] = "1"
        env.pop("MPI4JAX_TPU_COLL_ALGO", None)
        env.pop("MPI4JAX_TPU_COLL_QUANT", None)
        env.pop("MPI4JAX_TPU_HIER", None)
        # the grid is the HAND-SET baseline: a persistent tune cache
        # (possibly written by the joint tuner itself) auto-loading
        # into the no-ALGO combos would make the comparison circular —
        # point the cache knob at a guaranteed-missing file
        env["MPI4JAX_TPU_TUNE_CACHE"] = os.path.join(
            grid_tmp, "no_tune_cache.json")
        env.update(combo)
        cmd = [sys.executable, "-m", "mpi4jax_tpu.runtime.launch",
               "-n", str(np_)]
        if args.port:
            # a fresh port block per sub-job: the previous job's
            # sockets may still sit in TIME_WAIT on the shared block
            cmd += ["--port", str(args.port + i * (np_ + 2))]
        cmd += [here, "--world", "--sizes", sizes]
        res = subprocess.run(cmd, env=env, capture_output=True, text=True)
        label = ",".join(f"{k.rsplit('_', 1)[-1]}={v}"
                         for k, v in sorted(combo.items())) or "defaults"
        if res.returncode != 0:
            print(json.dumps({"mode": "knob-grid", "grid_env": combo,
                              "error": f"exit {res.returncode}",
                              "stderr_tail": res.stderr[-500:]}),
                  flush=True)
            continue
        for line in res.stdout.splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("op") != "allreduce":
                continue
            rec["mode"] = "knob-grid"
            rec["grid_env"] = combo
            rec["grid_label"] = label
            rows.append(rec)
            print(json.dumps(rec), flush=True)

    best = {}
    for rec in rows:
        key = int(rec["bytes"])
        raw = float(rec.get("raw_seconds") or rec["seconds"])
        if key not in best or raw < best[key]["raw_seconds"]:
            best[key] = {"raw_seconds": raw,
                         "grid_label": rec["grid_label"],
                         "grid_env": rec["grid_env"],
                         "resolved_algo": rec.get("resolved_algo"),
                         "raw_eff_GBps_per_chip":
                             rec.get("raw_eff_GBps_per_chip")}
    print(json.dumps({"mode": "knob-grid-best", "ranks": np_,
                      "fake_hosts": args.fake_hosts or None,
                      "combos_swept": len(combos),
                      "best": {str(k): v
                               for k, v in sorted(best.items())}}),
          flush=True)
    return 0 if rows else 1


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--max-mb", type=float, default=64)
    ap.add_argument("--world", action="store_true")
    ap.add_argument("--pallas", action="store_true")
    ap.add_argument("--sizes", default=None,
                    help="comma-separated byte sizes (world tier only; "
                         "overrides the x4 ladder)")
    ap.add_argument("--algos", default=None,
                    help="comma-separated forced collective algorithms to "
                         "sweep (world tier only; e.g. auto,ring,rd,tree — "
                         "one GB/s curve per algorithm)")
    ap.add_argument("--latency", action="store_true",
                    help="small-message mode (world tier): 1 B - 64 KiB "
                         "sweep emitting p50/p95/p99 us per op instead of "
                         "GB/s")
    ap.add_argument("--knob-grid", action="store_true",
                    help="driver mode: sweep the hand-set knob "
                         "combination grid (one launcher sub-job per "
                         "COLL_ALGO x COLL_QUANT [x HIER] point) and "
                         "emit per-combo records + a best-per-size "
                         "summary — the baseline tune --joint must beat")
    ap.add_argument("--np", type=int, default=None,
                    help="--knob-grid: ranks per sub-job (default 4)")
    ap.add_argument("--port", type=int, default=None,
                    help="--knob-grid: launcher base port")
    ap.add_argument("--fake-hosts", default=None,
                    help="--knob-grid: virtual host partition for the "
                         "sub-jobs (adds the MPI4JAX_TPU_HIER axis)")
    args = ap.parse_args()
    if args.knob_grid:
        if os.environ.get("MPI4JAX_TPU_RANK"):
            ap.error("--knob-grid is a driver mode; run it directly, "
                     "not under the launcher")
        sys.exit(knob_grid_driver(args))
    if args.world and args.pallas:
        ap.error("--pallas applies to the mesh tier; drop --world")
    if args.algos and not args.world:
        ap.error("--algos applies to the world tier; add --world")
    if args.latency and not args.world:
        ap.error("--latency applies to the world tier; add --world")
    if args.latency and args.algos:
        ap.error("--latency sweeps the engine-selected algorithm; drop "
                 "--algos")
    max_bytes = int(args.max_mb * 1e6)
    if args.latency:
        sizes = ([int(s) for s in args.sizes.split(",")]
                 if args.sizes else None)
        world_latency_rank(sizes=sizes)
    elif args.world:
        sizes = ([int(s) for s in args.sizes.split(",")]
                 if args.sizes else None)
        algos = ([a.strip() for a in args.algos.split(",") if a.strip()]
                 if args.algos else None)
        world_tier_rank(max_bytes, sizes=sizes, algos=algos)
    else:
        mesh_tier_sweep(max_bytes, pallas=args.pallas)
