"""Load generator for the serving plane (mpi4jax_tpu/serving): open-loop
arrivals, per-phase latency percentiles before / during / after an
injected rank death, goodput across the recovery, and the KV-cache
speedup over full recomputation.

Two ways to run it:

**Driver mode** (no launcher — spawns its own jobs and writes the
committed artifact)::

    python benchmarks/serving_latency.py --write   # BENCH_serving_v2.json

runs a steady and a fault-injected scenario (np=4, two virtual islands,
forced disaggregation, a decode rank killed mid-stream) plus the
in-process KV-cache-vs-recompute measurement, and enforces the
acceptance gates: zero lost requests, post-recovery goodput >= 80% of
pre-fault, cached decode >= 5x over full recompute at seqlen 512.

**Rank mode** (under the launcher — what the driver spawns; also usable
directly)::

    python -m mpi4jax_tpu.runtime.launch -n 4 --elastic \
        --fake-hosts "r0,r1|r2,r3" benchmarks/serving_latency.py \
        --requests 500 --roles disagg

Open loop means the arrival clock never waits for the server: request
i is submitted when its (seeded, exponential inter-arrival) timestamp
passes, however loaded the plane is — so latency percentiles reflect
queueing, not a closed feedback loop that self-throttles under load.

Phase buckets: ``before`` — completed with no retries before the first
recovery finished; ``during`` — in flight across the recovery (their
latency carries the detection deadline + rebuild + re-prefill, which
is why p99 spikes there); ``after`` — the shrunk world's steady state.
Each bucket row carries request-latency, TTFT (the prefill phase), and
per-token decode percentiles — the same split the obs
``phase=prefill|decode`` spans record — via ``obs.bench_record``, so
rows join the usual benchmark artifacts.
"""

import argparse
import json
import os
import sys
import time
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
if "mpi4jax_tpu" not in sys.modules:
    # parent-package shim: obs + serving + the bridge import without
    # jax, so the benchmark runs wherever the launcher does
    pkg = types.ModuleType("mpi4jax_tpu")
    pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
    sys.modules["mpi4jax_tpu"] = pkg

import numpy as np  # noqa: E402

from mpi4jax_tpu import obs, serving  # noqa: E402

DEFAULT_REQUESTS = 500
DEFAULT_RATE = 250.0  # open-loop arrivals per second
FAKE_HOSTS = "r0,r1|r2,r3"
FAULT = "rank=3,point=send,after=2500,action=exit"  # a decode rank


# ---------------- rank mode ----------------


def _phase_row(bucket, reqs, *, ranks, recoveries, window_s):
    lat = sorted(r.latency_s * 1e6 for r in reqs)
    ttft = sorted(r.ttft_s * 1e6 for r in reqs)
    dtok = sorted((r.completed_at - r.first_token_at) * 1e6
                  / max(len(r.generated) - 1, 1) for r in reqs)
    toks = sum(len(r.generated) for r in reqs)
    return obs.bench_record(
        op="serve_request",
        nbytes=int(np.mean([4 * len(r.tokens) for r in reqs])),
        seconds=obs.percentile(lat, 50) / 1e6, ranks=None,
        tier="serving", reps=len(reqs), phase=bucket,
        p50_us=round(obs.percentile(lat, 50), 1),
        p95_us=round(obs.percentile(lat, 95), 1),
        p99_us=round(obs.percentile(lat, 99), 1),
        ttft_p50_us=round(obs.percentile(ttft, 50), 1),
        ttft_p95_us=round(obs.percentile(ttft, 95), 1),
        ttft_p99_us=round(obs.percentile(ttft, 99), 1),
        decode_tok_p50_us=round(obs.percentile(dtok, 50), 1),
        decode_tok_p95_us=round(obs.percentile(dtok, 95), 1),
        decode_tok_p99_us=round(obs.percentile(dtok, 99), 1),
        completed=len(reqs), tokens=toks,
        goodput_tok_s=(round(toks / window_s, 1) if window_s else None),
        recoveries=recoveries, world_size_end=ranks,
    )


def rank_main(args):
    from mpi4jax_tpu.runtime import transport

    comm = transport.get_world_comm()
    _ = comm.handle
    adapter = serving.ToyAdapter()
    if comm.rank() != 0:
        serving.serve_worker(comm, adapter, roles_mode=args.roles)
        return

    server = serving.Server(comm, adapter, max_batch=args.max_batch,
                            chunk_tokens=args.chunk_tokens,
                            queue_cap=args.requests + 1,
                            roles_mode=args.roles)
    print(f"serving_latency {server.roles.describe()}", flush=True)
    rng = np.random.RandomState(args.seed)
    arrivals = np.cumsum(rng.exponential(1.0 / args.rate,
                                         size=args.requests))
    prompts = [rng.randint(0, 900, size=rng.randint(3, 9)).tolist()
               for _ in range(args.requests)]

    t_start = time.perf_counter()
    submitted = 0
    recovery_at = None  # perf_counter when the first recovery finished
    iters = 0
    while submitted < args.requests or server.active:
        elapsed = time.perf_counter() - t_start
        while (submitted < args.requests
               and arrivals[submitted] <= elapsed):
            v = server.submit(prompts[submitted], max_new=args.max_new)
            assert v.admitted, v.reason
            submitted += 1
        pre = server.recoveries
        if not server.step() and not server.active:
            time.sleep(0.0005)  # idle: the next arrival is in the future
        if server.recoveries > pre and recovery_at is None:
            recovery_at = time.perf_counter()
        iters += 1
        if iters > 500000:
            raise RuntimeError("serving did not drain")
    server.stop()

    done = server.completed
    # zero lost: every admitted request completed, exactly once
    assert len(done) == submitted == args.requests, (
        len(done), submitted, args.requests)
    assert len({r.id for r in done}) == len(done)

    rows = []
    t_end = max(r.completed_at for r in done)
    if server.recoveries == 0:
        rows.append(_phase_row("steady", done, ranks=comm.size(),
                               recoveries=0, window_s=t_end - t_start))
    else:
        before = [r for r in done if r.retries == 0
                  and r.completed_at < recovery_at]
        during = [r for r in done if r.retries > 0]
        after = [r for r in done if r.retries == 0
                 and r.completed_at >= recovery_at]
        windows = {"before": recovery_at - t_start,
                   "during": None,  # spans the recovery, not a rate
                   "after": t_end - recovery_at}
        for bucket, reqs in (("before", before), ("during", during),
                             ("after", after)):
            if reqs:
                rows.append(_phase_row(
                    bucket, reqs, ranks=comm.size(),
                    recoveries=server.recoveries,
                    window_s=windows[bucket]))
    for row in rows:
        print(json.dumps(row), flush=True)
    print(f"serving_latency done submitted={submitted} "
          f"completed={len(done)} recoveries={server.recoveries} "
          f"iters={iters}", flush=True)


# ---------------- KV-cache speedup (in-process, no launcher) ----------------


def kv_speedup(seqlen=512, gen=8):
    """Per-token cost of cached ``decode_step`` vs the toy plane's cost
    model (one full forward per generated token) on the numpy GPT at
    ``seqlen`` — the number that justifies the KV cache existing."""
    a = serving.make_numpy_gpt_adapter(max_seq=seqlen + gen + 1)
    prompt = (np.arange(seqlen, dtype=np.int64) * 7 + 3) % a.vocab

    past, logits = a.prefill(prompt.astype(np.int32))
    cached_toks, cached_us = [], []
    for _ in range(gen):
        nxt = int(np.argmax(logits))
        cached_toks.append(nxt)
        t0 = time.perf_counter()
        entry, logits = a.decode_step(past, nxt)
        cached_us.append((time.perf_counter() - t0) * 1e6)
        past = np.concatenate([past, entry[None]])

    toks = list(prompt)
    logits = a.prefill(np.asarray(toks, np.int32))[1]
    full_toks, full_us = [], []
    for _ in range(gen):
        nxt = int(np.argmax(logits))
        full_toks.append(nxt)
        toks.append(nxt)
        t0 = time.perf_counter()
        logits = a.prefill(np.asarray(toks, np.int32))[1]
        full_us.append((time.perf_counter() - t0) * 1e6)

    assert cached_toks == full_toks, "cached and recompute paths diverged"
    cached = obs.percentile(sorted(cached_us), 50)
    full = obs.percentile(sorted(full_us), 50)
    return {
        "seqlen": seqlen, "generated": gen,
        "cached_us_per_tok": round(cached, 1),
        "recompute_us_per_tok": round(full, 1),
        "speedup": round(full / cached, 1),
        "transcripts_identical": True,
    }


# ---------------- driver mode ----------------


def _spawn(label, np_, port, extra_env, prog_args):
    import subprocess

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra_env)
    res = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "mpi4jax_tpu", "runtime", "launch.py"),
         "-n", str(np_), "--port", str(port), "--elastic",
         "--fake-hosts", FAKE_HOSTS, os.path.abspath(__file__)]
        + prog_args,
        capture_output=True, text=True, timeout=900, env=env, cwd=REPO)
    if res.returncode != 0 or "serving_latency done" not in res.stdout:
        sys.stderr.write(res.stderr + res.stdout)
        raise SystemExit(f"scenario {label} failed")
    rows = [json.loads(ln) for ln in res.stdout.splitlines()
            if ln.startswith("{")]
    tail = [ln for ln in res.stdout.splitlines()
            if ln.startswith("serving_latency done")][0]
    meta = dict(kv.split("=") for kv in tail.split()[2:])
    return {"rows": rows, "submitted": int(meta["submitted"]),
            "completed": int(meta["completed"]),
            "recoveries": int(meta["recoveries"])}


def drive(requests, out_path):
    prog_args = ["--requests", str(requests), "--roles", "disagg"]
    scenarios = {}
    scenarios["steady_np4_disagg"] = _spawn(
        "steady", 4, 47810, {"MPI4JAX_TPU_DISABLE_SHM": "1"}, prog_args)
    scenarios["fault_np4_disagg"] = _spawn(
        "fault", 4, 47840,
        {"MPI4JAX_TPU_DISABLE_SHM": "1", "MPI4JAX_TPU_TIMEOUT_S": "8",
         "MPI4JAX_TPU_FAULT": FAULT}, prog_args)
    kv = kv_speedup()

    fault = scenarios["fault_np4_disagg"]
    buckets = {r["phase"]: r for r in fault["rows"]}
    assert fault["recoveries"] >= 1, "the fault did not fire"
    assert {"before", "during", "after"} <= set(buckets), (
        f"missing phase buckets: {sorted(buckets)}")
    for label, sc in scenarios.items():
        assert sc["completed"] == sc["submitted"] == requests, (
            label, sc["completed"], sc["submitted"])
    goodput_ratio = round(buckets["after"]["goodput_tok_s"]
                          / buckets["before"]["goodput_tok_s"], 3)
    assert goodput_ratio >= 0.8, (
        f"post-recovery goodput ratio {goodput_ratio} < 0.8")
    assert kv["speedup"] >= 5.0, f"KV speedup {kv['speedup']} < 5x"

    artifact = {
        "note": (
            "Serving-plane load test (benchmarks/serving_latency.py): "
            f"{requests} open-loop requests (seeded exponential "
            f"arrivals, ~{DEFAULT_RATE:g}/s) against the disaggregated "
            "prefill/decode plane on a 2-island np=4 virtual mesh "
            f"({FAKE_HOSTS}; frontend=r0, prefill=r1, decode=r2,r3), "
            "toy adapter (exactly prefix-consistent, so retried "
            "transcripts are byte-identical).  The fault scenario "
            f"kills decode rank 3 mid-stream ({FAULT}); the plane "
            "recovers, re-derives roles on the shrunk world, "
            "re-prefills in-flight requests, and completes every "
            "admitted request (zero lost, driver-asserted).  Buckets: "
            "before = completed pre-failure, during = in flight "
            "across the recovery (latency carries detection + rebuild "
            "+ re-prefill), after = the shrunk world.  kv_cache: "
            "per-token cached decode_step vs one full forward per "
            "token (the toy plane's cost model) on the numpy GPT at "
            "seqlen 512, transcripts asserted identical."),
        "config": {
            "requests": requests, "rate_rps": DEFAULT_RATE,
            "max_new": 4, "max_batch": 16, "chunk_tokens": 64,
            "adapter": "ToyAdapter", "roles": "disagg",
            "fake_hosts": FAKE_HOSTS, "fault": FAULT,
            "env": {"JAX_PLATFORMS": "cpu",
                    "MPI4JAX_TPU_DISABLE_SHM": "1"},
        },
        "scenarios": scenarios,
        "kv_cache": kv,
        "findings": {
            "zero_lost": True,
            "goodput_after_over_before": goodput_ratio,
            "kv_cache_speedup_seqlen512": kv["speedup"],
            "during_p99_over_after_p99": round(
                buckets["during"]["p99_us"] / buckets["after"]["p99_us"],
                1),
        },
    }
    text = json.dumps(artifact, indent=1)
    if out_path:
        with open(out_path, "w") as f:
            f.write(text + "\n")
        print(f"wrote {out_path}")
    else:
        print(text)


def _parse_rank_args(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=DEFAULT_REQUESTS)
    ap.add_argument("--rate", type=float, default=DEFAULT_RATE)
    ap.add_argument("--max-new", type=int, default=4)
    ap.add_argument("--max-batch", type=int, default=16)
    ap.add_argument("--chunk-tokens", type=int, default=64)
    ap.add_argument("--roles", default="auto")
    ap.add_argument("--seed", type=int, default=11)
    return ap.parse_args(argv)


if __name__ == "__main__":
    if os.environ.get("MPI4JAX_TPU_RANK"):
        rank_main(_parse_rank_args())
        sys.exit(0)
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=DEFAULT_REQUESTS)
    ap.add_argument("--kv-only", action="store_true",
                    help="only the in-process KV speedup measurement")
    ap.add_argument("--write", action="store_true",
                    help=f"write {os.path.join(REPO, 'BENCH_serving_v2.json')}")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    if args.kv_only:
        print(json.dumps(kv_speedup(), indent=1))
        sys.exit(0)
    out = args.out or (os.path.join(REPO, "BENCH_serving_v2.json")
                       if args.write else None)
    drive(args.requests, out)
