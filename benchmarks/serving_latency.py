"""Request-latency percentiles for the elastic serving harness —
before, during, and after an injected rank failure.

Run as a rank program under the launcher (bridge-level: no jax, works
in any container), rank 0 prints one ``obs.bench_record`` JSON row per
phase:

    # steady-state baseline
    python -m mpi4jax_tpu.runtime.launch -n 3 --elastic \
        benchmarks/serving_latency.py

    # with a worker death mid-stream
    MPI4JAX_TPU_FAULT=rank=1,point=recv,after=40,action=exit \
    MPI4JAX_TPU_TIMEOUT_S=8 MPI4JAX_TPU_DISABLE_SHM=1 \
    python -m mpi4jax_tpu.runtime.launch -n 3 --elastic \
        benchmarks/serving_latency.py

Phases: ``before`` — requests that completed before the failure was
detected; ``during`` — requests that were in flight across the
recovery (their iterations were retried on the shrunk world; their
latency carries the detection deadline + the rebuild, which is why
p99 spikes there); ``after`` — requests submitted after recovery,
i.e. the shrunk world's steady state.  Without a fault everything
lands in one ``steady`` row.  The rows share the benchmark field
names (op/bytes/us/p50_us/p95_us/p99_us), so they join with
``obs.stats`` tables and the ``profile report`` rendering of any
``--trace`` recording taken alongside.
"""

import argparse
import json
import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
if "mpi4jax_tpu" not in sys.modules:
    # parent-package shim: obs + elastic + the bridge import without
    # jax, so the benchmark runs wherever the launcher does
    pkg = types.ModuleType("mpi4jax_tpu")
    pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
    sys.modules["mpi4jax_tpu"] = pkg

import numpy as np  # noqa: E402

from mpi4jax_tpu import obs  # noqa: E402
from mpi4jax_tpu.elastic import serving  # noqa: E402
from mpi4jax_tpu.runtime import transport  # noqa: E402


def decode_fn(toks, lengths, start, stop):
    """Toy next-token function (pure function of the row, so retried
    iterations and shrunk worlds reproduce identical transcripts)."""
    out = np.zeros(stop - start, np.int32)
    for i in range(start, stop):
        n = int(lengths[i])
        row = toks[i, :n].astype(np.int64)
        out[i - start] = int((row.sum() * 31 + n * 7 + int(row[-1])) % 997)
    return out


def _phase_row(phase, reqs, *, ranks, recoveries):
    lat_us = sorted(r.latency_s * 1e6 for r in reqs)
    mean_bytes = int(np.mean([4 * len(r.tokens) for r in reqs]))
    return obs.bench_record(
        op="serve_request", nbytes=mean_bytes,
        seconds=obs.percentile(lat_us, 50) / 1e6, ranks=None,
        tier="serving", reps=len(reqs),
        phase=phase,
        p50_us=round(obs.percentile(lat_us, 50), 1),
        p95_us=round(obs.percentile(lat_us, 95), 1),
        p99_us=round(obs.percentile(lat_us, 99), 1),
        completed=len(reqs), recoveries=recoveries,
        world_size_end=ranks,
    )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=24,
                    help="total requests (half submitted up front, "
                         "half streamed in while serving)")
    ap.add_argument("--max-new", type=int, default=6)
    ap.add_argument("--max-batch", type=int, default=4)
    args = ap.parse_args()

    comm = transport.get_world_comm()
    _ = comm.handle
    if comm.rank() != 0:
        serving.serve_worker(comm, decode_fn)
        return

    server = serving.Server(comm, decode_fn, max_batch=args.max_batch)
    rng = np.random.RandomState(11)

    def submit(n):
        for _ in range(n):
            server.submit(rng.randint(0, 900, size=rng.randint(2, 5)),
                          max_new=args.max_new)

    first = args.requests // 2
    submit(first)
    import time

    recovery_at = None  # perf_counter of the first completed recovery
    streamed = False
    iters = 0
    while server.active or len(server.completed) < args.requests:
        iters += 1
        if iters > 2000:
            raise RuntimeError("serving did not drain")
        pre = server.recoveries
        server.step()
        if server.recoveries > pre and recovery_at is None:
            recovery_at = time.perf_counter()
        # stream the second half in: after recovery when a fault is
        # armed (the "after" phase), else once serving is warm
        if not streamed and (recovery_at is not None or iters == 4):
            submit(args.requests - first)
            streamed = True
    server.stop()

    done = server.completed
    assert len(done) == args.requests, (len(done), args.requests)
    rows = []
    if server.recoveries == 0:
        rows.append(_phase_row("steady", done, ranks=comm.size(),
                               recoveries=0))
    else:
        before = [r for r in done if r.retries == 0
                  and r.completed_at < recovery_at]
        during = [r for r in done if r.retries > 0]
        after = [r for r in done if r.retries == 0
                 and r.completed_at >= recovery_at]
        for phase, reqs in (("before", before), ("during", during),
                            ("after", after)):
            if reqs:
                rows.append(_phase_row(phase, reqs, ranks=comm.size(),
                                       recoveries=server.recoveries))
    for row in rows:
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
