"""Acceptance evidence for the self-healing link layer
(``BENCH_self_healing.json``)::

    python benchmarks/self_healing_bench.py --write

Three measurements, each gate-asserted before the artifact is written:

1. **Wire overhead** — a ctypes loopback pingpong ladder (1 KiB to
   1 MiB) with the layer disarmed vs armed (seq numbers + epoch + CRC32C
   on every header, retain-ring copy on every small send): the armed
   wire must sit within noise of the historic one.
2. **`MPI4JAX_TPU_RETRY=0` pins today's path** — the deterministic
   2-rank traffic program's digests with the knob unset vs explicitly
   0 are identical, with zero link-layer counters and no self-heal
   activity anywhere in stderr.
3. **Serving chaos** — the full disaggregated serving plane
   (``benchmarks/serving_latency.py``, np=4, two virtual islands)
   with a transient RST injected on a decode rank's live link: the
   armed layer heals it in place, so the plane sees **zero
   recoveries, zero KV-cache drops, zero re-prefills**, and every
   admitted request completes — versus the same fault disarmed,
   which is never absorbed.  (Disarmed it is in fact WORSE than the
   full-shrink recovery a rank death costs: nobody actually died, so
   no survivor can announce a new generation — every rank stalls out
   the full elastic grace window, the first casualty is the frontend,
   and frontend death is fatal to the plane by design.  The gate
   asserts the honest dichotomy: disarmed, the fault either costs at
   least one full elastic recovery or loses the job loudly.)

The heal-under-fault functional evidence lives in ``make chaos``
(tools/chaos_matrix.py) and tests/world/test_self_healing.py; this
artifact carries the *performance* and *serving* halves.
"""

import argparse
import json
import os
import statistics
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAUNCHER = os.path.join(REPO, "mpi4jax_tpu", "runtime", "launch.py")
HEAL_OPS = os.path.join(REPO, "tests", "world_programs", "heal_ops.py")
SERVING = os.path.join(REPO, "benchmarks", "serving_latency.py")

FAKE_HOSTS = "r0,r1|r2,r3"
# a decode rank's live link, reset mid-stream (transient — the peer is
# fine, only the connection dies)
TRANSIENT_FAULT = "rank=3,point=send,after=500,action=reset"

_PINGPONG_SRC = r"""
import ctypes, os, time
import numpy as np

lib = ctypes.CDLL(os.environ["PP_SO"])
rank = int(os.environ["PP_RANK"])
lib.tpucomm_init.restype = ctypes.c_int64
lib.tpucomm_init.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int,
                             ctypes.c_char_p]
h = lib.tpucomm_init(rank, 2, int(os.environ["PP_PORT"]), b"")
assert h > 0
p = lambda a: a.ctypes.data_as(ctypes.c_void_p)
for size in map(int, os.environ["PP_SIZES"].split(",")):
    buf = np.zeros(size, np.uint8)
    reps = max(120, min(600, (1 << 23) // size))
    ts = []
    for it in range(reps + 20):
        t0 = time.perf_counter()
        if rank == 0:
            assert lib.tpucomm_send(h, p(buf), size, 1, it) == 0
            assert lib.tpucomm_recv(h, p(buf), size, 1, it) == 0
        else:
            assert lib.tpucomm_recv(h, p(buf), size, 0, it) == 0
            assert lib.tpucomm_send(h, p(buf), size, 0, it) == 0
        if it >= 20:  # warmup excluded
            ts.append((time.perf_counter() - t0) * 1e6)
    ts.sort()
    if rank == 0:
        # min + p50: the min is the noise-free estimator on loopback
        # (scheduler wakeups dominate the upper half of the RTT
        # distribution and dwarf per-frame CPU cost)
        print("pp %d %.2f %.2f" % (size, ts[0], ts[len(ts) // 2]),
              flush=True)
lib.tpucomm_finalize(ctypes.c_int64(h))
"""

_port = [49900 + (os.getpid() * 13) % 60]


def _next_port(stride=9):
    _port[0] += stride
    return _port[0]


def _base_env(extra):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra)
    return env


# ---------------- 1: pingpong ladder ----------------


def pingpong_ladder(so, sizes, armed):
    port = _next_port()
    env = _base_env({
        "PP_SO": so, "PP_PORT": str(port),
        "PP_SIZES": ",".join(str(s) for s in sizes),
        "MPI4JAX_TPU_DISABLE_SHM": "1",
        # classic poll path: arming DELIBERATELY disables the uring
        # speculative-receive fast path (an over-pull cannot be rolled
        # back at frame granularity, which replay requires), so an
        # auto-uring comparison would measure that routing choice, not
        # the seq+CRC framing this ladder isolates
        "MPI4JAX_TPU_URING": "0",
        "MPI4JAX_TPU_RETRY": "4" if armed else "0",
    })
    procs = [subprocess.Popen(
        [sys.executable, "-c", _PINGPONG_SRC],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env={**env, "PP_RANK": str(r)}) for r in range(2)]
    outs = [pr.communicate(timeout=300) for pr in procs]
    for pr, (out, err) in zip(procs, outs):
        assert pr.returncode == 0, err[-1000:]
    stats = {}
    for line in outs[0][0].splitlines():
        if line.startswith("pp "):
            _, size, mn, p50 = line.split()
            stats[int(size)] = (float(mn), float(p50))
    assert set(stats) == set(sizes), stats
    return stats


def measure_overhead(so, sizes, rounds=5):
    """Per-size best-of-rounds minimum roundtrip, disarmed vs armed,
    interleaved so drift hits both equally.  The min-RTT is the
    estimator: on loopback the p50 flaps 2x run-to-run with scheduler
    wakeups, which would drown the few hundred nanoseconds the armed
    framing (16 extra header bytes, CRC32C, retain-ring memcpy) can
    legitimately add."""
    dis, arm = {s: [] for s in sizes}, {s: [] for s in sizes}
    p50s = {s: [[], []] for s in sizes}
    for _ in range(rounds):
        for s, (mn, p50) in pingpong_ladder(so, sizes,
                                            armed=False).items():
            dis[s].append(mn)
            p50s[s][0].append(p50)
        for s, (mn, p50) in pingpong_ladder(so, sizes,
                                            armed=True).items():
            arm[s].append(mn)
            p50s[s][1].append(p50)
    ladder = []
    for s in sizes:
        d, a = min(dis[s]), min(arm[s])
        ladder.append({
            "bytes": s,
            "disarmed_min_rtt_us": round(d, 2),
            "armed_min_rtt_us": round(a, 2),
            "disarmed_p50_rtt_us": round(statistics.median(p50s[s][0]), 2),
            "armed_p50_rtt_us": round(statistics.median(p50s[s][1]), 2),
            "armed_over_disarmed": round(a / d, 3),
        })
    return ladder


# ---------------- 2: RETRY=0 bit-for-bit ----------------


def _run_heal_ops(extra_env):
    res = subprocess.run(
        [sys.executable, LAUNCHER, "-n", "2",
         "--port", str(_next_port()), HEAL_OPS],
        capture_output=True, text=True, timeout=120,
        env=_base_env({"MPI4JAX_TPU_DISABLE_SHM": "1",
                       "MPI4JAX_TPU_TIMEOUT_S": "30", **extra_env}),
        cwd=REPO)
    assert res.returncode == 0, res.stderr[-1000:]
    import re
    digests = dict(re.findall(r"heal_ops (\d+) digest (\S+)", res.stdout))
    assert set(digests) == {"0", "1"}, res.stdout
    return digests, res.stderr


def retry0_pinned():
    d_unset, err_unset = _run_heal_ops({})
    d_zero, err_zero = _run_heal_ops({"MPI4JAX_TPU_RETRY": "0"})
    assert d_unset == d_zero, (d_unset, d_zero)
    assert "self-heal" not in err_unset + err_zero
    return {"digests_unset": d_unset, "digests_retry0": d_zero,
            "bit_identical": True, "self_heal_activity": False}


# ---------------- 3: serving chaos ----------------


def serving_chaos(requests, fault_env, label, expect_heal=True):
    import re
    res = subprocess.run(
        [sys.executable, LAUNCHER, "-n", "4",
         "--port", str(_next_port(stride=17)), "--elastic",
         "--fake-hosts", FAKE_HOSTS, SERVING,
         "--requests", str(requests), "--roles", "disagg"],
        capture_output=True, text=True, timeout=900,
        env=_base_env({"MPI4JAX_TPU_DISABLE_SHM": "1",
                       "MPI4JAX_TPU_TIMEOUT_S": "8", **fault_env}),
        cwd=REPO)
    if res.returncode != 0 or "serving_latency done" not in res.stdout:
        if expect_heal:
            sys.stderr.write(res.stderr[-3000:] + res.stdout[-1000:])
            raise SystemExit(f"serving scenario {label} failed")
        # the comparison leg: the fault was not absorbed.  It must at
        # least be LOUD (a post-mortem naming what happened) — a hang
        # or a silent wrong answer would have failed above on timeout
        # or on the request-accounting gates
        assert "post-mortem" in res.stderr, (
            f"disarmed scenario {label} failed without a post-mortem")
        return {
            "completed_cleanly": False,
            "returncode": res.returncode,
            "loud_post_mortem": True,
            "elastic_shrinks_attempted": len(
                re.findall(r"advancing to generation", res.stderr)),
            "ranks_stalled_out_grace_window":
                "no generation" in res.stderr,
            "job_lost":
                "no surviving rank to shrink onto" in res.stderr,
        }
    tail = [ln for ln in res.stdout.splitlines()
            if ln.startswith("serving_latency done")][0]
    meta = dict(kv.split("=") for kv in tail.split()[2:])
    rows = [json.loads(ln) for ln in res.stdout.splitlines()
            if ln.startswith("{")]
    # re-prefills surface as request retries -> the "during" bucket;
    # a healed transient never creates one
    reprefills = sum(r.get("completed", 0) for r in rows
                     if r.get("phase") == "during")
    return {
        "rows": rows,
        "completed_cleanly": True,
        "submitted": int(meta["submitted"]),
        "completed": int(meta["completed"]),
        "recoveries_kv_drops": int(meta["recoveries"]),
        "reprefills": reprefills,
        "link_healed": "self-heal: link to r" in res.stderr
                       and "recovered" in res.stderr,
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--write", action="store_true",
                    help="write BENCH_self_healing.json at the repo root")
    ap.add_argument("--requests", type=int, default=300)
    args = ap.parse_args()

    subprocess.run(["make", "-C", os.path.join(REPO, "native"),
                    "libtpucomm-noffi"], check=True, capture_output=True)
    so = os.path.join(REPO, "mpi4jax_tpu", "runtime", "_native",
                      "libtpucomm.so")

    sizes = [1024, 8192, 65536, 262144, 1048576]
    ladder = measure_overhead(so, sizes)
    worst = max(r["armed_over_disarmed"] for r in ladder)
    geo = statistics.geometric_mean(
        r["armed_over_disarmed"] for r in ladder)
    # the seq+CRC framing itself must be within noise where it is the
    # only added work (small frames: header grows 16->32 bytes, one
    # CRC32C, a sub-page retain copy) ...
    for r in ladder:
        if r["bytes"] <= 8192:
            assert r["armed_over_disarmed"] <= 1.10, (
                f"seq+CRC visible at {r['bytes']}B: {r}")
    # ... while the retain-ring memcpy near the 256 KiB retention
    # ceiling is a real, bounded, documented cost — and the armed
    # path's single contiguous frame write WINS at rendezvous sizes
    assert geo <= 1.15, f"armed wire geomean overhead {geo:.3f} > 1.15"
    assert worst <= 1.40, f"armed wire worst-size overhead {worst:.3f}"

    pinned = retry0_pinned()

    transient = serving_chaos(
        args.requests,
        {"MPI4JAX_TPU_RETRY": "4", "MPI4JAX_TPU_RETRY_BACKOFF_MS": "50",
         "MPI4JAX_TPU_FAULT": TRANSIENT_FAULT}, "transient-armed")
    assert transient["link_healed"], "the reset was not healed in place"
    assert transient["recoveries_kv_drops"] == 0, transient
    assert transient["reprefills"] == 0, transient
    assert (transient["completed"] == transient["submitted"]
            == args.requests), transient

    disarmed = serving_chaos(
        args.requests,
        {"MPI4JAX_TPU_FAULT": TRANSIENT_FAULT}, "transient-disarmed",
        expect_heal=False)
    # the SAME fault without the layer is never absorbed: it costs at
    # least one full elastic recovery (KV dropped, in-flight requests
    # re-prefilled) — or, as observed on the disagg plane where a
    # transient reset kills NO rank (so no death ever announces a new
    # generation), every rank stalls out the elastic grace window and
    # the job is lost, loudly
    if disarmed["completed_cleanly"]:
        assert disarmed["recoveries_kv_drops"] >= 1, (
            "disarmed plane absorbed the fault transparently", disarmed)
    else:
        assert disarmed["loud_post_mortem"], disarmed

    artifact = {
        "note": (
            "Self-healing link layer acceptance "
            "(benchmarks/self_healing_bench.py).  overhead_ladder: "
            "2-rank TCP loopback pingpong (classic poll path, URING=0 "
            "— arming deliberately disables the uring speculative "
            "receive, so an auto comparison would measure routing, not "
            "framing), best-of-5-interleaved-rounds MIN RTT (the p50 "
            "flaps ~2x with scheduler wakeups on loopback; both are "
            "reported), MPI4JAX_TPU_RETRY=0 vs =4.  The armed wire "
            "adds per-frame sequence numbers, a connection epoch, a "
            "CRC32C over header/control bytes, and a retain-ring copy "
            "of every frame <= 256 KiB.  Gates: seq+CRC within noise "
            "(<= 1.10) at the small sizes where it is the only added "
            "work; geomean <= 1.15 and worst size <= 1.40 overall — "
            "the retain memcpy near the retention ceiling is the one "
            "real, bounded cost (~1.2x at 64 KiB), while the armed "
            "path's single contiguous frame write is FASTER than the "
            "historic header+payload write pair at rendezvous sizes.  "
            "retry0_pinned: the deterministic "
            "2-rank traffic program (tests/world_programs/heal_ops.py) "
            "with the knob unset vs explicitly 0 — digests identical, "
            "no link-layer activity (the default path is today's wire "
            "bit-for-bit).  serving_chaos: the disaggregated serving "
            "plane (serving_latency.py, np=4, islands r0,r1|r2,r3, "
            "TCP) with a transient RST on decode rank 3's live link "
            "after its 501st send — armed, the link heals in place: "
            "zero recoveries (= zero KV-cache drops), zero re-prefills "
            "(no request enters the 'during' retry bucket), every "
            "admitted request completes.  Disarmed, the identical "
            "fault is never absorbed — and because a transient reset "
            "kills NO rank, no death ever announces a new elastic "
            "generation: every rank stalls out the full "
            "MPI4JAX_TPU_ELASTIC_GRACE_S window waiting for one, the "
            "first casualty is the frontend (fatal to the plane by "
            "design), and the job is lost after a loud cascade of "
            "shrink attempts.  A transient link fault disarmed is "
            "strictly WORSE than a rank death (which at least "
            "triggers the shrink path immediately); the armed layer "
            "closes exactly that gap."),
        "config": {
            "sizes": sizes, "requests": args.requests,
            "fake_hosts": FAKE_HOSTS, "fault": TRANSIENT_FAULT,
            "env": {"JAX_PLATFORMS": "cpu",
                    "MPI4JAX_TPU_DISABLE_SHM": "1"},
        },
        "overhead_ladder": ladder,
        "overhead_geomean": round(geo, 3),
        "retry0_pinned": pinned,
        "serving_chaos": {
            "transient_armed": {k: v for k, v in transient.items()
                                if k != "rows"},
            "transient_disarmed": {k: v for k, v in disarmed.items()
                                   if k != "rows"},
            "armed_rows": transient["rows"],
        },
        "findings": {
            "armed_wire_overhead_geomean": round(geo, 3),
            "armed_wire_overhead_worst": round(worst, 3),
            "retry0_bit_identical": True,
            "serving_transient_kv_drops_armed": 0,
            "serving_transient_reprefills_armed": 0,
            "serving_transient_disarmed_outcome": (
                "full elastic recovery (%d KV drop(s))"
                % disarmed["recoveries_kv_drops"]
                if disarmed["completed_cleanly"] else
                "job lost: grace-window stall, then cascading shrink "
                "(%d attempt(s)) — loud post-mortem, no hang"
                % disarmed["elastic_shrinks_attempted"]),
        },
    }
    text = json.dumps(artifact, indent=1)
    if args.write:
        out = os.path.join(REPO, "BENCH_self_healing.json")
        with open(out, "w") as f:
            f.write(text + "\n")
        print(f"wrote {out}")
    else:
        print(text)


if __name__ == "__main__":
    main()
