"""Flash-attention block sweep + canonical-kernel comparison (real TPU).

Times `ops/flash.py::ring_flash_attention` (1-device ring = pure local
flash) across (block_q, block_k) and, when available, jax's own
`pallas.ops.tpu.flash_attention` on the same shape as the reference
point.  One JSON line per config.

    python benchmarks/flash_sweep.py [--shape B T H D]

Measured r3 on the tunneled v5e at (4, 4096, 16, 128) bf16 causal:
ours 26.9 TFLOP/s at blocks 1024/1024 (the default) vs the canonical
jax TPU kernel's 10.6 TFLOP/s — 2.5x.  The reference framework ships
no attention kernels at all (its long-context building block is the
token-ordered sendrecv ring, sendrecv.py:46-125 there).
"""

import argparse
import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shape", type=int, nargs=4, default=(4, 4096, 16, 128),
                    metavar=("B", "T", "H", "D"))
    ap.add_argument("--reps", type=int, default=10)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from mpi4jax_tpu.ops.flash import ring_flash_attention

    B, T, H, D = args.shape
    keys = [jax.random.PRNGKey(i) for i in range(3)]
    q, k, v = (jax.random.normal(kk, (B, T, H, D), jnp.bfloat16)
               for kk in keys)
    mesh = Mesh(np.array(jax.devices()[:1]), ("sp",))
    flops = 2 * 2 * B * H * T * T * D * 0.5  # causal
    K = args.reps

    def timed(fa_call, qq, kk, vv):
        @jax.jit
        def many(q, k, v):
            def step(qc, _):
                return fa_call(qc, k, v).astype(qc.dtype), ()
            out, _ = jax.lax.scan(step, q, None, length=K)
            return jnp.sum(out.astype(jnp.float32))

        float(many(qq, kk, vv))  # compile + warmup
        t0 = time.perf_counter()
        float(many(qq, kk, vv))
        return (time.perf_counter() - t0) / K

    for bq, bk in [(1024, 1024), (2048, 1024), (512, 1024),
                   (1024, 512), (512, 512)]:
        fa = jax.shard_map(
            partial(ring_flash_attention, axis="sp", causal=True,
                    interpret=False, block_q=bq, block_k=bk),
            mesh=mesh, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"), check_vma=False)
        try:
            dt = timed(fa, q, k, v)
            print(json.dumps({"kernel": "ours", "bq": bq, "bk": bk,
                              "ms": round(dt * 1e3, 3),
                              "TFLOPs": round(flops / dt / 1e12, 1)}),
                  flush=True)
        except Exception as err:
            print(json.dumps({"kernel": "ours", "bq": bq, "bk": bk,
                              "error": f"{type(err).__name__}"[:60]}),
                  flush=True)

    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as jax_flash)
    except ImportError:
        return
    qh, kh, vh = (x.transpose(0, 2, 1, 3) for x in (q, k, v))  # (B,H,T,D)

    def canonical(qc, kc, vc):
        return jax_flash(qc, kc, vc, causal=True)

    dt = timed(canonical, qh, kh, vh)
    print(json.dumps({"kernel": "jax.pallas.ops.tpu.flash_attention",
                      "ms": round(dt * 1e3, 3),
                      "TFLOPs": round(flops / dt / 1e12, 1)}), flush=True)


if __name__ == "__main__":
    main()
