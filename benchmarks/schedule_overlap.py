"""Schedule-plan overlap microbenchmarks (bridge level, no JAX dispatch).

Measures the two wins the schedule compiler (docs/analysis.md § "From
verifier to compiler") unlocks, each with the plan ON vs OFF so the
delta is the plan's doing:

1. **sendrecv pipeline** — a CHAIN of ranks (the pipeline-parallel
   stage-boundary stream: rank r sends activations downstream to r+1,
   computes, and receives from r-1).  The chain is acyclic, so blocks
   larger than the kernel's socket buffering are safe — and that is
   exactly where plan-off hurts: the caller's blocking send
   rendezvous-waits until the downstream rank finishes computing and
   reaches its recv.  Plan-on posts the send as a deferred ticket and
   pre-posts the recv at the send's post point, so the progress thread
   moves the wire while the host computes.  (A ring at these sizes
   would rendezvous-deadlock without the plan — the hazard the
   recalibrated ``order_critical_exchange`` describes — so the chain is
   also the shape that keeps the plan-off baseline finishable.)
2. **bucketed allreduce** — a backward-pass-shaped run of many small
   gradient allreduces vs the same bytes fused into buckets
   (``MPI4JAX_TPU_PLAN_BUCKET_KB`` semantics): fewer, larger wire
   messages amortize per-op latency.

Run under the launcher (rank 0 prints one ``obs.bench_record`` JSON row
per configuration):

    python -m mpi4jax_tpu.runtime.launch -n 3 benchmarks/schedule_overlap.py

With ``--trace out.json`` the merged Perfetto timeline shows the
overlap directly: plan-on recv spans start at their POST time (inside
the compute window) with the wait share attributed by the dispatch/
wait/wire split.
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np

try:
    from mpi4jax_tpu import obs
except ImportError:
    # bridge-level bench by design: on hosts where the package's jax
    # version gate blocks the normal import, a parent-package shim
    # exposes the jax-free submodules (obs/analysis/runtime)
    import types

    _pkg = types.ModuleType("mpi4jax_tpu")
    _pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
    sys.modules["mpi4jax_tpu"] = _pkg
    from mpi4jax_tpu import obs

from mpi4jax_tpu.analysis import _events, _plan
from mpi4jax_tpu.runtime import bridge, planrt, transport


def _compute(seconds, spin=False):
    """Stand-in for the work between a send and its paired recv.

    Default: ``time.sleep`` — the host thread idles, which is exactly
    the TPU shape (world-tier comm runs on the HOST; the device computes
    while the host waits on it), and what gives the progress thread the
    core it reads the wire with.  ``--spin`` burns the CPU instead
    (host-bound compute): on machines with spare cores the overlap
    still wins; on oversubscribed CI boxes the progress thread then
    competes with the spin and the delta shrinks — measure both."""
    if not spin:
        time.sleep(seconds)
        return 0.0
    end = time.perf_counter() + seconds
    x = 0.0
    while time.perf_counter() < end:
        x += 1.0
    return x


def _pipeline_schedule(n, rounds, shape):
    """Chain: rank r sends to r+1 (r < n-1) and receives from r-1
    (r > 0), ``rounds`` times."""
    events = {}
    for rank in range(n):
        evs = []
        for k in range(rounds):
            if rank < n - 1:
                evs.append(_events.CommEvent(rank, len(evs), "send",
                                             dest=rank + 1, tag=k,
                                             dtype="float32", shape=shape))
            if rank > 0:
                evs.append(_events.CommEvent(rank, len(evs), "recv",
                                             source=rank - 1, tag=k,
                                             dtype="float32", shape=shape))
        events[rank] = evs
    return events, {(0,): tuple(range(n))}


def bench_pipeline(comm, rounds, shape, compute_s, use_plan, spin=False):
    h, rank, n = comm.handle, comm.rank(), comm.size()
    rt = None
    if use_plan:
        events, comms = _pipeline_schedule(n, rounds, shape)
        plan = _plan.compile_schedules(events, comms)
        assert plan.proved and plan.rewritten, plan.reasons
        assert planrt.install(h, plan, rank)
        rt = planrt.get(comm)
    payload = np.arange(int(np.prod(shape)), dtype=np.float32)
    bridge.barrier(h)
    t0 = time.perf_counter()
    for k in range(rounds):
        if rank < n - 1:
            if rt is not None:
                # owned=True (the MPI_Isend contract): `payload` is this
                # loop's long-lived buffer, valid past the drain point,
                # so the runner skips the safety copy the XLA-callback
                # path needs
                assert rt.run_send(payload, rank + 1, k, owned=True)
            else:
                bridge.send(h, payload, rank + 1, k)
        _compute(compute_s, spin)
        if rank > 0:
            if rt is not None:
                # reuse=True: the payload is consumed inside this loop
                # iteration, so the buffer may recycle at the next op
                got = rt.run_recv(shape, np.float32, rank - 1, k,
                                  reuse=True)
                assert got is not None
            else:
                got = bridge.recv(h, shape, np.float32, rank - 1, k)
    dt = time.perf_counter() - t0
    if rt is not None:
        rt.flush()
        assert rt.stats["mismatches"] == 0, rt.stats
        planrt.detach(h)
    bridge.barrier(h)
    return dt


def bench_bucketed_allreduce(comm, n_grads, grad_elems, bucket_elems):
    """Per-leaf vs bucketed gradient allreduce (same total bytes)."""
    h = comm.handle
    grads = [np.full((grad_elems,), 1.0, np.float32)
             for _ in range(n_grads)]
    bridge.barrier(h)
    t0 = time.perf_counter()
    for g in grads:
        bridge.allreduce(h, g, 0)
    per_leaf = time.perf_counter() - t0

    per_bucket = max(1, bucket_elems // grad_elems)
    bridge.barrier(h)
    t0 = time.perf_counter()
    for i in range(0, n_grads, per_bucket):
        chunk = np.concatenate(grads[i:i + per_bucket])
        bridge.allreduce(h, chunk, 0)
    bucketed = time.perf_counter() - t0
    bridge.barrier(h)
    return per_leaf, bucketed


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=15)
    ap.add_argument("--block-kb", type=int, default=4096,
                    help="pipeline block size per message (KB); sizes "
                         "past the kernel's socket buffering are where "
                         "the blocking send rendezvous-waits and the "
                         "plan's overlap pays")
    ap.add_argument("--compute-ms", type=float, default=3.0,
                    help="compute window between send and recv (ms)")
    ap.add_argument("--spin", action="store_true",
                    help="burn the host CPU during the compute window "
                         "instead of idling (device-compute shape); see "
                         "_compute's docstring")
    ap.add_argument("--grads", type=int, default=64)
    ap.add_argument("--grad-kb", type=int, default=8)
    ap.add_argument("--bucket-kb", type=int, default=512)
    args = ap.parse_args()

    comm = transport.get_world_comm()
    rank, n = comm.rank(), comm.size()
    assert n >= 2, "run at np >= 2"
    shape = (args.block_kb * 256,)  # KB -> f32 elements
    rows = []

    for use_plan, label in ((False, "off"), (True, "on")):
        dt = bench_pipeline(comm, args.rounds, shape,
                            args.compute_ms / 1e3, use_plan,
                            spin=args.spin)
        if rank == 0:
            rows.append(obs.bench_record(
                op="plan_pipeline", nbytes=args.block_kb * 1024,
                seconds=dt / args.rounds, ranks=n, tier="plan",
                reps=args.rounds, plan=label,
                compute_ms=args.compute_ms,
                compute_kind="spin" if args.spin else "idle",
            ))

    per_leaf, bucketed = bench_bucketed_allreduce(
        comm, args.grads, args.grad_kb * 256, args.bucket_kb * 256)
    if rank == 0:
        total = args.grads * args.grad_kb * 1024
        rows.append(obs.bench_record(
            op="plan_bucketed_allreduce", nbytes=total,
            seconds=per_leaf, ranks=n, tier="plan", plan="off",
            n_allreduce=args.grads,
        ))
        n_buckets = -(-args.grads // max(1, args.bucket_kb // args.grad_kb))
        rows.append(obs.bench_record(
            op="plan_bucketed_allreduce", nbytes=total,
            seconds=bucketed, ranks=n, tier="plan", plan="on",
            n_allreduce=n_buckets,
        ))
        for row in rows:
            print(json.dumps(row), flush=True)
        pipe = {r["plan"]: r for r in rows if r["op"] == "plan_pipeline"}
        speedup = pipe["off"]["seconds"] / max(pipe["on"]["seconds"], 1e-9)
        print(f"# pipeline round: plan off {pipe['off']['us']:.0f} us -> "
              f"plan on {pipe['on']['us']:.0f} us  ({speedup:.2f}x)",
              file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
