"""Per-rank shallow-water program for the world-tier scaling study.

The analog of the reference's ``mpirun -n N python examples/shallow_water.py
--benchmark`` runs (its CPU scaling table, docs/shallow-water.rst:56-78).
Launch under the world launcher (or mpirun — the env is adopted):

    python -m mpi4jax_tpu.runtime.launch -n 4 benchmarks/sw_world_rank.py \
        -- --grid 2 2 --size 1800 3600 --days 0.1

Rank 0 prints one JSON line: wall seconds of the timed multistep region
(same region as the reference's "Solution took") plus config.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--grid", type=int, nargs=2, default=None,
                    help="(gy gx); default: 1 x size")
    ap.add_argument("--size", type=int, nargs=2, default=(1800, 3600))
    ap.add_argument("--days", type=float, default=0.1)
    ap.add_argument("--check", action="store_true",
                    help="rank 0 validates against the mesh-tier solver")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import mpi4jax_tpu as m4j
    from mpi4jax_tpu.models.shallow_water import SWParams
    from mpi4jax_tpu.models.shallow_water_world import WorldShallowWater

    comm = m4j.get_default_comm()
    n = comm.size()
    grid = tuple(args.grid) if args.grid else (1, n)
    params = SWParams(dx=5e3, dy=5e3)
    model = WorldShallowWater(comm, grid, tuple(args.size), params)

    n_steps = int(args.days * params.day_seconds / params.dt)
    state = model.step_fn(1, first=True)(model.init())
    run = model.step_fn(n_steps - 1, first=False)
    jax.block_until_ready(run(state))  # compile + warmup

    t0 = time.perf_counter()
    out = run(state)
    jax.block_until_ready(out.h)
    elapsed = time.perf_counter() - t0

    h = np.asarray(model.interior(out.h))
    assert np.all(np.isfinite(h)), "diverged"

    if args.check:
        hg = model.gather_global(out.h)
        if comm.rank() == 0:
            from mpi4jax_tpu.models.shallow_water import ShallowWater
            from mpi4jax_tpu.parallel.grid import ProcessGrid

            ref = ShallowWater(
                ProcessGrid((1, 1), devices=jax.devices()[:1]),
                tuple(args.size), params,
            )
            rs = ref.step_fn(1, first=True)(ref.init())
            rs = ref.step_fn(n_steps - 1, first=False, impl="xla")(rs)
            href = np.asarray(ref.interior(rs.h))
            np.testing.assert_allclose(hg, href, rtol=2e-4, atol=2e-4)
            print("sw_world CHECK OK", flush=True)

    if comm.rank() == 0:
        print(json.dumps({
            "bench": "shallow_water_world", "ranks": n,
            "grid": list(grid), "size": list(args.size),
            "steps": n_steps - 1, "seconds": round(elapsed, 3),
            "steps_per_s": round((n_steps - 1) / elapsed, 2),
        }), flush=True)


if __name__ == "__main__":
    main()
