"""Alltoall-family sweep on a 2-island virtual mesh: the MoE dispatch
ladder that produced ``BENCH_moe_alltoall.json``.

    python benchmarks/moe_alltoall_sweep.py [--write] [--out PATH]
                                            [--sizes 2048,16384,...]

The driver launches bridge-level rank jobs under the launcher with
``--fake-hosts`` two-island partitions (even 4+4 at np=8, uneven 4+2 at
np=6) and sweeps a skewed per-peer chunk ladder — from the many-small-
messages regime MoE routing produces (512 B chunks) up to 1 MiB — over
the four alltoall schedules:

    ring        flat exact pairwise exchange (the AUTO default)
    qalltoall   flat, every off-rank chunk int8+scales on the wire
    halltoall   hierarchical exact: intra-island legs ride the island
                shm arenas, only cross-island blocks cross the leader
                (tcp) tier
    hqalltoall  hierarchical with the leader leg quantized (one codec
                frame per island pair)

Timing is barrier-synchronized per call (median + p95 over the rep
loop), all through ``bridge.alltoall_raw`` with a forced algorithm code
— the exact inner loop the tuner measures.  Each quantized row is
error-checked against the exact exchange of the SAME input (own-rank /
intra-island chunks bitwise, cross chunks inside the documented int8
bound); exact rows are compared bitwise.  Wire-byte splits come from
``Topology.leg_bytes`` and the codec arithmetic, so every row carries
``wire_bytes`` / ``intra_bytes`` / ``inter_bytes`` next to the logical
payload.

Rank side is bridge-level with the parent-package shim (no jax import),
so the sweep runs in any container — the same trick as the world tests.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_SIZES = "512,4096,32768,262144,1048576"  # per-peer chunk bytes
ALGOS = ("ring", "qalltoall", "halltoall", "hqalltoall")
LEG_NAMES = {"ring": "alltoall", "qalltoall": "qalltoall",
             "halltoall": "halltoall", "hqalltoall": "hqalltoall"}
SHAPES = [
    ("np8_2island_4p4", 8, "r0,r1,r2,r3|r4,r5,r6,r7", "0,0,0,0,1,1,1,1"),
    ("np6_2island_4p2", 6, "r0,r1,r2,r3|r4,r5", "0,0,0,0,1,1"),
]


# ----------------------------- rank side -----------------------------


def rank_main():
    sys.path.insert(0, REPO)
    import types

    pkg = types.ModuleType("mpi4jax_tpu")
    pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
    sys.modules["mpi4jax_tpu"] = pkg

    import numpy as np

    from mpi4jax_tpu import obs, tune
    from mpi4jax_tpu.runtime import bridge, transport

    comm = transport.get_world_comm()
    rank, size = comm.rank(), comm.size()
    h = comm.handle
    t = comm.topology()
    assert t is not None and t.multi, "bench needs a multi-island mesh"
    my_island = set(t.islands[t.island_of[rank]])

    sizes = [int(s) for s in os.environ["MOE_A2A_SIZES"].split(",")]
    rng = np.random.RandomState(100 + rank)

    for chunk_bytes in sizes:
        count = max(1, chunk_bytes // 4)
        nbytes = size * count * 4
        x = (rng.randn(size, count) * 3).astype(np.float32)
        reps = int(max(5, min(40, (4 << 20) // max(nbytes, 1) + 5)))
        outs = {}
        for algo in ALGOS:
            code = tune.ALGO_CODES[algo]
            out = np.empty_like(x)
            for _ in range(2):  # warmup (connection setup, codec paths)
                bridge.alltoall_raw(h, x, out, algo=code)
            times = []
            for _ in range(reps):
                bridge.barrier(h)
                t0 = time.perf_counter()
                bridge.alltoall_raw(h, x, out, algo=code)
                times.append(time.perf_counter() - t0)
            outs[algo] = (out.copy(), times)

        ring_out = outs["ring"][0]
        assert np.array_equal(outs["halltoall"][0], ring_out), (
            "halltoall must be a bit-exact permutation")
        for algo in ("qalltoall", "hqalltoall"):
            q = outs[algo][0]
            assert np.array_equal(q[rank], ring_out[rank]), (
                f"{algo}: own-rank chunk must stay exact")
            if algo == "hqalltoall":
                for s in my_island:
                    assert np.array_equal(q[s], ring_out[s]), (
                        "hqalltoall: intra-island chunks must stay exact")
            denom = float(np.max(np.abs(ring_out))) or 1.0
            rel = float(np.max(np.abs(q - ring_out))) / denom
            assert rel < 5e-2, f"{algo}: rel err {rel} out of bound"

        if rank != 0:
            continue
        for algo in ALGOS:
            _, times = outs[algo]
            med = obs.percentile(times, 50)
            legs = t.leg_bytes(LEG_NAMES[algo], nbytes)
            wire = legs["intra"] + legs["inter"]
            row = obs.bench_record(
                op="alltoall", nbytes=nbytes, seconds=med,
                ranks=size, tier="world", algo=algo, reps=reps,
                chunk_bytes=chunk_bytes,
                p95_us=round(obs.percentile(times, 95) * 1e6, 1),
                wire_bytes=wire,
                intra_bytes=legs["intra"],
                inter_bytes=legs["inter"],
                topology=t.fingerprint(),
                islands=[len(m) for m in t.islands],
            )
            if algo in ("qalltoall", "hqalltoall"):
                exact = t.leg_bytes(
                    LEG_NAMES["halltoall" if algo == "hqalltoall"
                              else "ring"], nbytes)
                row["compression"] = round(
                    (exact["intra"] + exact["inter"]) / max(wire, 1), 3)
            print(json.dumps(row), flush=True)
    if rank == 0:
        print("moe_alltoall_sweep done", flush=True)


# ---------------------------- driver side ----------------------------


def _crossovers(rows):
    """Smallest chunk size at which each variant beats the flat exact
    exchange (and hqalltoall beats the exact hierarchy)."""
    by = {}
    for r in rows:
        by.setdefault(r["algo"], {})[r["chunk_bytes"]] = r["seconds"]
    out = {}
    for variant, base in (("qalltoall", "ring"), ("halltoall", "ring"),
                          ("hqalltoall", "ring"),
                          ("hqalltoall_vs_halltoall", "halltoall")):
        name = variant.split("_vs_")[0]
        wins = [c for c, s in sorted(by.get(name, {}).items())
                if s < by.get(base, {}).get(c, float("inf"))]
        out[variant] = wins[0] if wins else None
    return out


def _findings(sweeps):
    """One machine-generated sentence per sweep, straight from the
    crossover table — the human-readable face of the acceptance
    criterion."""
    out = {}
    for label, sw in sweeps.items():
        c = sw["crossovers"]
        bits = []
        if c.get("qalltoall") is not None:
            bits.append("qalltoall beats the flat exact exchange from "
                        f"{c['qalltoall']}-byte chunks")
        if c.get("halltoall") is not None:
            bits.append("halltoall wins the many-small-messages regime "
                        f"from {c['halltoall']}-byte chunks")
        if c.get("hqalltoall") is not None:
            bits.append("hqalltoall beats the flat exact exchange from "
                        f"{c['hqalltoall']}-byte chunks")
        if c.get("hqalltoall_vs_halltoall") is not None:
            bits.append("the quantized leader leg beats the exact "
                        "hierarchy from "
                        f"{c['hqalltoall_vs_halltoall']}-byte chunks")
        out[label] = ("; ".join(bits) if bits
                      else "no crossover on this ladder")
    return out


def drive(sizes, out_path=None):
    port = [47600]
    sweeps = {}
    fake = {}
    for label, np_, hosts, _expect in SHAPES:
        port[0] += np_ + 7
        env = dict(os.environ)
        for k in ("XLA_FLAGS", "MPI4JAX_TPU_COLL_ALGO",
                  "MPI4JAX_TPU_COLL_QUANT", "MPI4JAX_TPU_HIER",
                  "MPI4JAX_TPU_DISABLE_SHM"):
            env.pop(k, None)
        env["JAX_PLATFORMS"] = "cpu"
        env["MPI4JAX_TPU_TIMEOUT_S"] = "240"
        env["MOE_A2A_BENCH_RANK"] = "1"
        env["MOE_A2A_SIZES"] = sizes
        res = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "mpi4jax_tpu", "runtime", "launch.py"),
             "-n", str(np_), "--port", str(port[0]),
             "--fake-hosts", hosts, os.path.abspath(__file__)],
            capture_output=True, text=True, timeout=1800, env=env,
            cwd=REPO)
        if res.returncode != 0 or "moe_alltoall_sweep done" not in res.stdout:
            sys.stderr.write(res.stderr + res.stdout)
            raise SystemExit(f"sweep {label} failed")
        rows = [json.loads(ln) for ln in res.stdout.splitlines()
                if ln.startswith("{")]
        sweeps[label] = {"rows": rows, "crossovers": _crossovers(rows)}
        fake[label] = hosts
    artifact = {
        "note": (
            "Alltoall-family sweep for the MoE expert exchange "
            "(benchmarks/moe_alltoall_sweep.py) on 2-island virtual "
            "meshes (launch.py --fake-hosts): per-peer chunk ladder "
            f"[{sizes}] bytes, f32, forced-algorithm "
            "bridge.alltoall_raw inner loop, barrier-synchronized "
            "median-of-reps.  Islands keep their shm arenas (the world "
            "tier is tcp loopback), so halltoall's intra legs ride shm "
            "while flat schedules push every chunk through tcp.  Every "
            "quantized row is error-checked in-run against the exact "
            "exchange of the same input (own/intra chunks bitwise, "
            "cross chunks < 5e-2 rel); halltoall is compared bitwise.  "
            "crossovers = smallest chunk where the variant's median "
            "beats the flat exact exchange (null = never on this "
            "ladder); wire/intra/inter bytes are the analytic "
            "Topology.leg_bytes splits with the codec arithmetic on "
            "quantized legs."),
        "config": {
            "env": {"JAX_PLATFORMS": "cpu"},
            "fake_hosts": fake,
            "dtype": "float32",
            "op": "alltoall",
            "algos": list(ALGOS),
            "chunk_bytes": [int(s) for s in sizes.split(",")],
        },
        "sweeps": sweeps,
        "findings": _findings(sweeps),
    }
    text = json.dumps(artifact, indent=1)
    if out_path:
        with open(out_path, "w") as f:
            f.write(text + "\n")
        print(f"wrote {out_path}")
    else:
        print(text)


if __name__ == "__main__":
    if os.environ.get("MOE_A2A_BENCH_RANK"):
        rank_main()
        sys.exit(0)
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default=DEFAULT_SIZES,
                    help="comma-separated per-peer chunk bytes")
    ap.add_argument("--write", action="store_true",
                    help=f"write {os.path.join(REPO, 'BENCH_moe_alltoall.json')}")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    out = args.out or (os.path.join(REPO, "BENCH_moe_alltoall.json")
                       if args.write else None)
    drive(args.sizes, out)
