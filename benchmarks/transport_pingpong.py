"""Native-transport latency microbenchmark (raw ctypes, no JAX dispatch).

Reproduces the transport-latency table in ``docs/benchmarks.md``: times
the bridge-level ``sendrecv``/``allreduce`` calls directly against the
C++ transport (``native/tpucomm.cc``), so the numbers isolate framing +
socket + reduction cost from XLA callback overhead.  Run under the
launcher; rank 0 prints one JSON line per row:

    python -m mpi4jax_tpu.runtime.launch -n 2 \
        benchmarks/transport_pingpong.py

The reference has no analog (its transport is libmpi); these rows are
the native tier's answer to an MPI pingpong (osu_latency-style).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from mpi4jax_tpu import obs
from mpi4jax_tpu.runtime import bridge, transport


def timeit(fn, reps):
    """Mean seconds per call plus per-call percentiles, with the warmup
    iterations EXCLUDED from every reported number.

    The previous implementation warmed up with a single call: at small
    rep counts (the 16 MiB rows run reps=5) the first measured
    iterations still carried allocator/page-fault warmup, which
    polluted the reported figures exactly where there were fewest
    samples to absorb them.  Warmup scales with reps (at least 2, at
    most 25) and is reported alongside the measured count.
    """
    warmup = max(2, min(25, reps // 20))
    for _ in range(warmup):
        fn()
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    mean = sum(times) / len(times)
    return mean, times, warmup


def syscalls_per_op(fn, op_name, reps=200):
    """Mean transport syscalls per op, measured OUTSIDE the timing loop
    (recording costs clock reads that would perturb the latency rows):
    a short pass with the obs recorder armed averages the per-event
    ``syscalls`` field (None on a pre-uring native library, which never
    writes it), cross-checked against the process-total counter."""
    lib = bridge.get_lib()
    from mpi4jax_tpu.obs import _native

    if not (_native.available(lib) and _native.syscalls_available(lib)):
        return None, None
    obs.reset() if obs.enabled() else obs.start(lib=lib)
    obs.events()  # drain warmup noise
    t0 = bridge.syscall_count()
    for _ in range(reps):
        fn()
    total = bridge.syscall_count() - t0
    evs = [e for e in obs.events()
           if e.get("src") == "native" and e["name"] == op_name]
    per_event = (sum(int(e.get("syscalls", 0)) for e in evs) / len(evs)
                 if evs else None)
    # disarm before returning: the NEXT row's timeit() loop must run
    # with the recorder off, or its latency figures carry the per-event
    # clock reads this pass just paid
    obs.stop()
    return per_event, round(total / reps, 3)


def main():
    comm = transport.get_world_comm()
    handle, rank, size = comm.handle, comm.rank(), comm.size()
    assert size == 2, "pingpong wants exactly 2 ranks"
    peer = 1 - rank
    rows = []

    def record(op, nbytes, mean, times, warmup, reps, **extra):
        # one serializer for every benchmark artifact (obs.bench_record):
        # BENCH_*.json, sweep curves, and profile reports stay
        # field-compatible on (op, bytes, seconds); reps is the MEASURED
        # iteration count (warmup excluded and noted separately)
        us = [t * 1e6 for t in times]
        return obs.bench_record(
            op=op, nbytes=nbytes, seconds=mean, tier="transport",
            reps=reps, warmup_excluded=warmup,
            p50_us=round(obs.percentile(us, 50), 3),
            p95_us=round(obs.percentile(us, 95), 3),
            p99_us=round(obs.percentile(us, 99), 3),
            **extra,
        )

    # the submit-batching column: uring state + syscalls-per-message
    # (obs `syscalls` field; None on a pre-uring .so) stamped into
    # every row so the BENCH artifacts carry the transport-floor
    # attribution, not just wall time
    uring = bridge.uring_status() or "unavailable(pre-uring .so)"

    # sendrecv round: each rank sends to the peer and receives back —
    # one full round of the persistent-writer (or eager inline) path
    for nbytes in (1024, 65536):
        buf = np.ones(nbytes // 4, np.float32)
        reps = 2000 if nbytes <= 4096 else 300

        def round_trip():
            bridge.sendrecv(handle, buf, buf.shape, buf.dtype,
                            peer, peer, 7)

        mean, times, warmup = timeit(round_trip, reps)
        sys_ev, sys_total = syscalls_per_op(round_trip, "Sendrecv",
                                            min(200, reps))
        rows.append(record("sendrecv_round", nbytes, mean, times, warmup,
                           reps, uring=uring, syscalls_per_msg=sys_ev,
                           syscalls_per_msg_total=sys_total))

    # small-send burst: 32 adjacent sends to one peer — the engine's
    # coalescing/batching shape; syscalls-per-message is the headline
    # submit-batching number here
    for nbytes in (512, 8192):
        buf = np.ones(nbytes // 4, np.float32)
        burst = 32

        def burst_round():
            if rank == 0:
                for i in range(burst):
                    bridge.send(handle, buf, peer, 100 + i)
                for i in range(burst):
                    bridge.recv(handle, buf.shape, buf.dtype, peer, 200 + i)
            else:
                out = [bridge.recv(handle, buf.shape, buf.dtype, peer,
                                   100 + i) for i in range(burst)]
                for i in range(burst):
                    bridge.send(handle, out[i], peer, 200 + i)

        reps = 100
        mean, times, warmup = timeit(burst_round, reps)
        _, sys_total = syscalls_per_op(burst_round, "Send", 50)
        rows.append(record(
            "send_burst32", nbytes, mean, times, warmup, reps, uring=uring,
            burst=burst,
            syscalls_per_msg_total=(round(sys_total / (2 * burst), 4)
                                    if sys_total is not None else None)))

    # allreduce: the doc table's three sizes
    for nbytes, reps in ((1024, 2000), (65536, 300), (16 << 20, 5)):
        buf = np.ones(nbytes // 4, np.float32)

        def reduce_once():
            bridge.allreduce(handle, buf, 0)  # 0 = SUM

        mean, times, warmup = timeit(reduce_once, reps)
        sys_ev, sys_total = syscalls_per_op(reduce_once, "Allreduce",
                                            min(100, reps))
        rows.append(record("allreduce", nbytes, mean, times, warmup, reps,
                           ranks=size, uring=uring, syscalls_per_msg=sys_ev,
                           syscalls_per_msg_total=sys_total))

    if obs.enabled():
        obs.stop()
    bridge.barrier(handle)
    if rank == 0:
        for r in rows:
            print(json.dumps(r), flush=True)
    print("pingpong OK", flush=True)


if __name__ == "__main__":
    main()
