"""Native-transport latency microbenchmark (raw ctypes, no JAX dispatch).

Reproduces the transport-latency table in ``docs/benchmarks.md``: times
the bridge-level ``sendrecv``/``allreduce`` calls directly against the
C++ transport (``native/tpucomm.cc``), so the numbers isolate framing +
socket + reduction cost from XLA callback overhead.  Run under the
launcher; rank 0 prints one JSON line per row:

    python -m mpi4jax_tpu.runtime.launch -n 2 \
        benchmarks/transport_pingpong.py

The reference has no analog (its transport is libmpi); these rows are
the native tier's answer to an MPI pingpong (osu_latency-style).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from mpi4jax_tpu import obs
from mpi4jax_tpu.runtime import bridge, transport


def timeit(fn, reps):
    """Mean seconds per call plus per-call percentiles, with the warmup
    iterations EXCLUDED from every reported number.

    The previous implementation warmed up with a single call: at small
    rep counts (the 16 MiB rows run reps=5) the first measured
    iterations still carried allocator/page-fault warmup, which
    polluted the reported figures exactly where there were fewest
    samples to absorb them.  Warmup scales with reps (at least 2, at
    most 25) and is reported alongside the measured count.
    """
    warmup = max(2, min(25, reps // 20))
    for _ in range(warmup):
        fn()
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    mean = sum(times) / len(times)
    return mean, times, warmup


def main():
    comm = transport.get_world_comm()
    handle, rank, size = comm.handle, comm.rank(), comm.size()
    assert size == 2, "pingpong wants exactly 2 ranks"
    peer = 1 - rank
    rows = []

    def record(op, nbytes, mean, times, warmup, reps, **extra):
        # one serializer for every benchmark artifact (obs.bench_record):
        # BENCH_*.json, sweep curves, and profile reports stay
        # field-compatible on (op, bytes, seconds); reps is the MEASURED
        # iteration count (warmup excluded and noted separately)
        us = [t * 1e6 for t in times]
        return obs.bench_record(
            op=op, nbytes=nbytes, seconds=mean, tier="transport",
            reps=reps, warmup_excluded=warmup,
            p50_us=round(obs.percentile(us, 50), 3),
            p95_us=round(obs.percentile(us, 95), 3),
            p99_us=round(obs.percentile(us, 99), 3),
            **extra,
        )

    # sendrecv round: each rank sends to the peer and receives back —
    # one full round of the persistent-writer (or eager inline) path
    for nbytes in (1024, 65536):
        buf = np.ones(nbytes // 4, np.float32)
        reps = 2000 if nbytes <= 4096 else 300

        def round_trip():
            bridge.sendrecv(handle, buf, buf.shape, buf.dtype,
                            peer, peer, 7)

        mean, times, warmup = timeit(round_trip, reps)
        rows.append(record("sendrecv_round", nbytes, mean, times, warmup,
                           reps))

    # allreduce: the doc table's three sizes
    for nbytes, reps in ((1024, 2000), (65536, 300), (16 << 20, 5)):
        buf = np.ones(nbytes // 4, np.float32)

        def reduce_once():
            bridge.allreduce(handle, buf, 0)  # 0 = SUM

        mean, times, warmup = timeit(reduce_once, reps)
        rows.append(record("allreduce", nbytes, mean, times, warmup, reps,
                           ranks=size))

    bridge.barrier(handle)
    if rank == 0:
        for r in rows:
            print(json.dumps(r), flush=True)
    print("pingpong OK", flush=True)


if __name__ == "__main__":
    main()
