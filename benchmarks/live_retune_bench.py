"""Acceptance evidence for live re-tuning (``BENCH_live_retune.json``)::

    python benchmarks/live_retune_bench.py --write

A bandwidth-burning sidecar fleet (memory-copy loops — on loopback TCP
the "wire" IS memory bandwidth) genuinely flips the 16 MiB allreduce
winner on this host: quiescent, the quantized wire (``qrd``, 4x fewer
bytes) beats full-precision ``ring``; contended, the codec's own
memory passes become the bottleneck and ``ring`` wins.  Four gates,
all asserted in-driver before the artifact is written:

1. **The flip is real** — a pinned-algorithm ladder measures
   ``qrd`` < ``ring`` at 16 MiB quiescent AND ``ring`` < ``qrd`` under
   the sidecar fleet (no synthetic forcing: the cost model fed to the
   live controller is built from THIS phase's measured medians).
2. **Re-pick within the cooldown** — with the static table pinned to
   the quiescent winner (``qrd``) and the sidecars injected mid-run,
   the armed controller detects the drift and the epoch rendezvous
   installs the new table within ``MPI4JAX_TPU_LIVE_COOLDOWN_OPS``
   operations of the contention onset, with the swap report naming
   ``qrd -> ring``.
3. **Throughput recovers** — post-swap per-op medians beat the
   live-off run (same pinned table, same sidecar schedule) over the
   same op range by >= 5%: the static cache stays wrong, the live
   plane does not.
4. **Quiescent = zero swaps** — the armed controller over the same
   model with no sidecars records zero table swaps (no epoch ever
   advances): the brain does nothing when nothing drifts.
"""

import argparse
import json
import os
import re
import statistics
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAUNCHER = os.path.join(REPO, "mpi4jax_tpu", "runtime", "launch.py")
ARTIFACT = os.path.join(REPO, "BENCH_live_retune.json")

NBYTES = 16 * 1024 * 1024          # the contested band
N_SIDECARS = 6
# cooldown budgets the TWO-PHASE detection latency: ~per_key ops to the
# first (mixed-regime) crossing that arms suspicion, a fresh per_key
# window to confirm, then the rendezvous period (cooldown // 4)
WINDOW, DRIFT_PCT, COOLDOWN = 32, 50, 24
OPS, SIDECAR_AT = 70, 20

_port = [48700 + (os.getpid() * 13) % 300]

#: each sidecar ping-pongs two 64 MiB buffers through the memory bus —
#: the same resource loopback TCP and the quantize/dequantize passes
#: contend for
SIDECAR_SRC = (
    "import numpy as np\n"
    "a = np.ones(1 << 26, dtype=np.uint8)\n"
    "b = np.empty_like(a)\n"
    "while True:\n"
    "    np.copyto(b, a)\n"
    "    np.copyto(a, b)\n"
)

_PROBE_SRC = r"""
import os, statistics, sys, time, types
REPO = os.environ["LIVE_BENCH_REPO"]
sys.path.insert(0, REPO)
pkg = types.ModuleType("mpi4jax_tpu")
pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
sys.modules["mpi4jax_tpu"] = pkg
import numpy as np
from mpi4jax_tpu.runtime import bridge, transport
c = transport.get_world_comm()
h = c.handle
x = np.ones(int(os.environ["LIVE_BENCH_NBYTES"]) // 4, dtype=np.float32)
for _ in range(3):
    bridge.allreduce(h, x, 0)
ts = []
for _ in range(10):
    t0 = time.perf_counter()
    bridge.allreduce(h, x, 0)
    ts.append(time.perf_counter() - t0)
if c.rank() == 0:
    print("probe_med_ms %.3f" % (statistics.median(ts) * 1e3), flush=True)
"""

_LIVE_SRC = r"""
import json, os, subprocess, sys, time, types
REPO = os.environ["LIVE_BENCH_REPO"]
sys.path.insert(0, REPO)
pkg = types.ModuleType("mpi4jax_tpu")
pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
sys.modules["mpi4jax_tpu"] = pkg
import numpy as np
from mpi4jax_tpu import live
from mpi4jax_tpu.runtime import bridge, transport

c = transport.get_world_comm()
h = c.handle
rank = c.rank()
ops = int(os.environ["LIVE_BENCH_OPS"])
at = int(os.environ["LIVE_BENCH_SIDECAR_AT"])     # -1 = never
nside = int(os.environ["LIVE_BENCH_SIDECARS"])
side_src = os.environ["LIVE_BENCH_SIDECAR_SRC"]
x = np.ones(int(os.environ["LIVE_BENCH_NBYTES"]) // 4, dtype=np.float32)
side, times, epochs = [], [], []
try:
    for it in range(ops):
        if it == at and rank == 0:
            side = [subprocess.Popen([sys.executable, "-c", side_src])
                    for _ in range(nside)]
            time.sleep(0.3)   # let the fleet saturate before timing
        t0 = time.perf_counter()
        bridge.allreduce(h, x, 0)
        times.append((time.perf_counter() - t0) * 1e3)
        epochs.append(int(live.status().get("epoch", 0)))
finally:
    for p in side:
        p.kill()
st = live.status()
if rank == 0:
    out = {
        "times_ms": [round(t, 3) for t in times],
        "epochs": epochs,
        "errors": int(st.get("errors", 0)),
        "swaps": [{"epoch": s["epoch"], "boundary": s["boundary"],
                   "changes": (s.get("report") or {}).get("changes", [])}
                  for s in st.get("swaps", [])],
    }
    sys.stdout.write("live_bench_json " + json.dumps(out) + "\n")
    sys.stdout.flush()
"""


def _launch(src, env_extra, sidecars_for_whole_run=0, timeout=240):
    _port[0] += 11
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        # TCP path: the same-host shm arena would shadow the table
        "MPI4JAX_TPU_DISABLE_SHM": "1",
        "MPI4JAX_TPU_TIMEOUT_S": "120",
        "LIVE_BENCH_REPO": REPO,
        "LIVE_BENCH_NBYTES": str(NBYTES),
    })
    env.update(env_extra)
    with tempfile.NamedTemporaryFile(
        "w", suffix="_m4j_live_bench.py", delete=False
    ) as f:
        f.write(src)
        prog = f.name
    side = [subprocess.Popen([sys.executable, "-c", SIDECAR_SRC])
            for _ in range(sidecars_for_whole_run)]
    try:
        if side:
            time.sleep(0.5)
        res = subprocess.run(
            [sys.executable, LAUNCHER, "-n", "2",
             "--port", str(_port[0]), prog],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=REPO)
    finally:
        for p in side:
            p.kill()
        os.unlink(prog)
    return res


def probe(algo, sidecars):
    res = _launch(_PROBE_SRC,
                  {"MPI4JAX_TPU_COLL_ALGO": f"allreduce={algo}"},
                  sidecars_for_whole_run=sidecars)
    m = re.search(r"probe_med_ms ([\d.]+)", res.stdout)
    assert res.returncode == 0 and m, (
        f"probe {algo}/side={sidecars} failed:\n"
        + (res.stderr or res.stdout)[-1500:])
    return float(m.group(1))


def live_run(mode, model_path):
    """mode: 'armed' (sidecar mid-run), 'static' (live off, same
    sidecar), 'quiescent' (armed, no sidecar)."""
    env = {
        "MPI4JAX_TPU_COLL_ALGO": "allreduce=qrd",   # the static pick
        "MPI4JAX_TPU_TUNE_MODEL": model_path,
        "MPI4JAX_TPU_LIVE": "off" if mode == "static" else "auto",
        "MPI4JAX_TPU_LIVE_WINDOW": str(WINDOW),
        "MPI4JAX_TPU_LIVE_DRIFT_PCT": str(DRIFT_PCT),
        "MPI4JAX_TPU_LIVE_COOLDOWN_OPS": str(COOLDOWN),
        "LIVE_BENCH_OPS": str(OPS),
        "LIVE_BENCH_SIDECAR_AT":
            "-1" if mode == "quiescent" else str(SIDECAR_AT),
        "LIVE_BENCH_SIDECARS": str(N_SIDECARS),
        "LIVE_BENCH_SIDECAR_SRC": SIDECAR_SRC,
    }
    res = _launch(_LIVE_SRC, env)
    m = re.search(r"live_bench_json (\{.*\})", res.stdout)
    assert res.returncode == 0 and m, (
        f"live run {mode} failed:\n" + (res.stderr or res.stdout)[-1500:])
    return json.loads(m.group(1)), res.stderr


def main(argv=None):
    ap = argparse.ArgumentParser(prog="benchmarks/live_retune_bench.py")
    ap.add_argument("--write", action="store_true",
                    help=f"write {os.path.basename(ARTIFACT)}")
    args = ap.parse_args(argv)

    # ---- phase A: the flip, measured with pinned algorithms ----------
    ladder = {}
    for side in (0, N_SIDECARS):
        for algo in ("ring", "qrd"):
            ladder[(algo, side)] = probe(algo, side)
            print(f"probe: algo={algo:<5} sidecars={side} "
                  f"med={ladder[(algo, side)]:.2f} ms", flush=True)
    q_ring, q_qrd = ladder[("ring", 0)], ladder[("qrd", 0)]
    c_ring, c_qrd = ladder[("ring", N_SIDECARS)], ladder[("qrd", N_SIDECARS)]
    assert q_qrd < q_ring, (
        f"gate 1a: quiescent winner at 16 MiB is not qrd "
        f"(qrd={q_qrd} ring={q_ring} ms) — no crossover on this host")
    assert c_ring < c_qrd, (
        f"gate 1b: sidecar fleet did not flip the 16 MiB winner to ring "
        f"(ring={c_ring} qrd={c_qrd} ms)")
    print(f"gate 1 OK: sidecars flip the 16 MiB winner "
          f"(quiescent qrd {q_qrd:.1f} < ring {q_ring:.1f} ms; "
          f"contended ring {c_ring:.1f} < qrd {c_qrd:.1f} ms)", flush=True)

    # ---- the cost model the controller trusts = phase A's medians ----
    model = {
        "version": 1, "world_size": 2, "topology": None,
        "dtype": "float32", "knobs": {},
        "source": "live_retune_bench quiescent ladder",
        "samples": {
            # small-size anchors keep the interpolation sane; the 16 MiB
            # band carries this host's measured quiescent medians
            "allreduce/ring": {"1024": 30e-6, str(NBYTES): q_ring / 1e3},
            "allreduce/qrd": {"1024": 60e-6, str(NBYTES): q_qrd / 1e3},
        },
        "wire_frac": {}, "dispatch_frac": {},
    }
    with tempfile.NamedTemporaryFile(
        "w", suffix="_m4j_live_bench_model.json", delete=False
    ) as f:
        json.dump(model, f)
        model_path = f.name

    try:
        armed, armed_err = live_run("armed", model_path)
        static, _ = live_run("static", model_path)
        quiet, _ = live_run("quiescent", model_path)
    finally:
        os.unlink(model_path)

    # ---- gate 2: re-pick within the cooldown -------------------------
    swap_ops = [i for i, e in enumerate(armed["epochs"]) if e > 0]
    assert swap_ops, f"armed run never swapped: {armed['swaps']}"
    ops_to_swap = swap_ops[0] - SIDECAR_AT
    changes = ";".join(c for s in armed["swaps"] for c in s["changes"])
    assert armed["errors"] == 0, f"controller errors: {armed['errors']}"
    assert "qrd -> ring" in changes, (
        f"swap report does not name the re-pick: {armed['swaps']}")
    # exactly ONE swap: candidate adoption must stop the controller from
    # ping-ponging back once the new pick also runs slower contended
    assert len(armed["swaps"]) == 1, (
        f"controller thrashed ({len(armed['swaps'])} swaps): "
        f"{armed['swaps']}")
    assert 0 < ops_to_swap <= COOLDOWN, (
        f"gate 2: swap landed {ops_to_swap} ops after contention onset "
        f"(cooldown budget {COOLDOWN})")
    assert "[live] epoch 1 committed" in armed_err, armed_err[-800:]
    print(f"gate 2 OK: drift -> rendezvous -> '{changes}' "
          f"{ops_to_swap} ops after onset (budget {COOLDOWN})", flush=True)

    # ---- gate 3: throughput recovers vs the static cache -------------
    post = slice(swap_ops[0] + 2, OPS)
    armed_post = statistics.median(armed["times_ms"][post])
    static_post = statistics.median(static["times_ms"][post])
    recovery = static_post / armed_post
    assert not any(e > 0 for e in static["epochs"]), static["swaps"]
    assert recovery >= 1.05, (
        f"gate 3: post-swap armed {armed_post:.1f} ms vs static "
        f"{static_post:.1f} ms — recovery {recovery:.2f}x < 1.05x")
    print(f"gate 3 OK: post-swap {armed_post:.1f} ms vs static "
          f"{static_post:.1f} ms ({recovery:.2f}x)", flush=True)

    # ---- gate 4: quiescent armed run swaps nothing -------------------
    assert not quiet["swaps"] and not any(e > 0 for e in quiet["epochs"]), (
        f"gate 4: quiescent run swapped: {quiet['swaps']}")
    assert quiet["errors"] == 0, quiet["errors"]
    print("gate 4 OK: quiescent armed run recorded zero swaps", flush=True)

    artifact = {
        "note": (
            "Live re-tuning acceptance (benchmarks/live_retune_bench.py). "
            "flip_ladder: 2-rank loopback TCP 16 MiB allreduce, pinned "
            "algorithm, median of 10 after 3 warmup, quiescent vs a "
            f"{N_SIDECARS}-process memory-copy sidecar fleet — the fleet "
            "flips the winner (quiescent: qrd's 4x-smaller wire wins; "
            "contended: the codec's own memory passes lose to ring). "
            "armed_run: static table pinned to the quiescent winner "
            "(qrd), cost model = the quiescent ladder's own medians, "
            f"sidecars injected at op {SIDECAR_AT} of {OPS}; the armed "
            "controller detects the drift and the epoch rendezvous "
            "installs ring within the cooldown budget, after which "
            "per-op medians beat the live-off run (same table, same "
            "sidecar schedule) over the same op range.  quiescent_run: "
            "the armed controller over the same model with no sidecars "
            "records ZERO swaps.  All four gates are asserted in-driver "
            "before this file is written."
        ),
        "config": {
            "nbytes": NBYTES, "np": 2, "sidecars": N_SIDECARS,
            "ops": OPS, "sidecar_at": SIDECAR_AT,
            "live_window": WINDOW, "live_drift_pct": DRIFT_PCT,
            "live_cooldown_ops": COOLDOWN,
            "static_pick": "qrd",
            "env": {"JAX_PLATFORMS": "cpu",
                    "MPI4JAX_TPU_DISABLE_SHM": "1"},
        },
        "flip_ladder": {
            "quiescent": {"ring_ms": q_ring, "qrd_ms": q_qrd},
            "contended": {"ring_ms": c_ring, "qrd_ms": c_qrd},
        },
        "armed_run": {
            "swap_op": swap_ops[0],
            "ops_after_onset": ops_to_swap,
            "cooldown_budget": COOLDOWN,
            "swaps": armed["swaps"],
            "post_swap_med_ms": armed_post,
            "times_ms": armed["times_ms"],
            "epochs": armed["epochs"],
        },
        "static_run": {
            "post_swap_range_med_ms": static_post,
            "times_ms": static["times_ms"],
        },
        "quiescent_run": {
            "swaps": quiet["swaps"],
            "med_ms": statistics.median(quiet["times_ms"]),
        },
        "recovery_vs_static": round(recovery, 3),
    }
    if args.write:
        with open(ARTIFACT, "w") as f:
            json.dump(artifact, f, indent=1)
            f.write("\n")
        print(f"wrote {ARTIFACT}")
    else:
        print("all gates green (use --write to commit the artifact)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
