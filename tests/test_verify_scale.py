"""Tier-1 wiring of the verification scale gate (make verify-scale).

Two halves:

- a live ``--quick`` harness run (np ladder to 64, concrete
  differential to 32) under a wall-clock budget — catches symbolic /
  concrete drift, calibration drift against the committed goldens,
  and prover regressions on every CI run, jax or no jax;
- schema + structural checks on the committed
  ``BENCH_verifier_scale.json``: the full 8→512 ladder must show the
  sub-quadratic story (symbolic match steps bounded by classes, not
  np; every plan proved at 512 where the concrete prover's
  interleaving budget cannot reach) and a clean failure list.
"""

import json
import os
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "BENCH_verifier_scale.json")

# the quick ladder does ~100x less matching work than the committed
# run's 60s budget covers; 120s keeps slow CI hosts honest without
# flaking
QUICK_BUDGET_S = 120.0


def _harness():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import scale_harness

    return scale_harness


def test_quick_harness_green_under_budget(capsys):
    sh = _harness()
    t0 = time.perf_counter()
    rc = sh.main(["--quick", "--out", "-",
                  "--budget-s", str(QUICK_BUDGET_S)])
    wall = time.perf_counter() - t0
    out = capsys.readouterr().out
    assert rc == 0, f"scale harness failures:\n{out}"
    assert wall < QUICK_BUDGET_S, f"quick ladder took {wall:.1f}s"
    # the gate has teeth: all six corpus families calibrated and ran
    assert out.count("proved=True") == len(sh.FAMILIES)


def test_bench_file_committed_and_well_formed():
    assert os.path.exists(BENCH), \
        "BENCH_verifier_scale.json missing: run make verify-scale " \
        "and commit the result"
    with open(BENCH) as fh:
        bench = json.load(fh)
    assert bench["schema"] == "verifier-scale/1"
    assert bench["failures"] == []
    assert not bench["quick"], "committed bench must be the full ladder"
    assert bench["np_ladder"][-1] == 512
    assert bench["wall_s"] < bench["budget_s"] == 60.0
    assert bench["peak_rss_kb"] > 0

    sh = _harness()
    families = bench["families"]
    assert set(families) == set(sh.FAMILIES)
    for name, cal in families.items():
        assert cal["events_match_golden"], name
        assert cal["cache_key_match"], name
        assert cal["peer_forms_rescale"], name

    rows = bench["rows"]
    assert {r["family"] for r in rows} == set(sh.FAMILIES)
    by_fam = {}
    for r in rows:
        by_fam.setdefault(r["family"], {})[r["np"]] = r
    for name, by_np in by_fam.items():
        assert set(by_np) == set(bench["np_ladder"]), name
        for n, row in by_np.items():
            assert row["findings"] == 0, (name, n)
            assert row["plan"]["proved"], (name, n)
            if n <= bench["concrete_cap"]:
                assert row["concrete"] is not None
                assert row["findings_equal"], (name, n)
            else:
                assert row["concrete"] is None
        # the sub-quadratic claim, structurally: the quotient's match
        # work is bounded by the class count, not the world size —
        # with a constant class count the step count must not grow
        # with np at all, while the concrete matcher's grows at least
        # linearly for p2p families
        first, last = min(by_np), max(by_np)
        if by_np[first]["symbolic"]["classes"] \
                == by_np[last]["symbolic"]["classes"]:
            assert by_np[first]["symbolic"]["steps"] \
                == by_np[last]["symbolic"]["steps"], name
        # prover budget independence: at np=512 the concrete prover
        # cannot prove (512 service rotations > its 256-interleaving
        # budget); the recorded proof must be the quotient's
        top = by_np[max(by_np)]
        assert top["plan"]["symmetry_classes"] is not None
        assert top["plan"]["interleavings"] \
            <= top["symbolic"]["classes"] + 1

    # oracle + tuner sections ran at the top rung
    assert bench["oracles"]["np"] == 512
    assert bench["oracles"]["simulate_halltoall_exact"] is True
    assert bench["tuner"]["ranks"] == 512
    assert bench["tuner"]["winners"]


def test_synthetic_islands_and_measure_helpers():
    """The harness's topo/tune inputs are real package API: the island
    map round-trips the FAKE_HOSTS parser and the synthetic cost
    table is deterministic with the documented shape."""
    sh = _harness()
    topo = sh._load_file("t_scale_topo", "mpi4jax_tpu", "topo",
                         "__init__.py")
    islands, spec = topo.synthetic_islands(512, 8)
    assert len(islands) == 8
    assert all(len(m) == 64 for m in islands)
    labels = topo.parse_fake_hosts(spec, 512)
    assert labels is not None and None not in labels
    with pytest.raises(ValueError):
        topo.synthetic_islands(10, 3)
    jt = sh._load_file("t_scale_jt", "mpi4jax_tpu", "tune",
                       "_joint.py")
    m = jt.synthetic_measure(512)
    big = 1 << 20
    assert m("allreduce", big, "hring+q") < m("allreduce", big, "ring")
    assert m("allreduce", big, "hring") == m("allreduce", big, "hring")
    assert m("alltoall", big, "hqalltoall") \
        < m("alltoall", big, "ring")


def test_concrete_steps_grow_with_np_symbolic_do_not():
    """The scaling evidence in the committed bench, cross-family: for
    every p2p family the concrete matcher's steps grow ~linearly on
    the measured range while the symbolic steps stay flat."""
    if not os.path.exists(BENCH):
        pytest.skip("bench not committed yet")
    with open(BENCH) as fh:
        bench = json.load(fh)
    p2p = ("halo_exchange", "false_serialization", "independent_pair")
    for name in p2p:
        rows = sorted((r for r in bench["rows"]
                       if r["family"] == name and r["concrete"]),
                      key=lambda r: r["np"])
        assert len(rows) >= 2
        lo, hi = rows[0], rows[-1]
        ratio_np = hi["np"] / lo["np"]
        ratio_conc = hi["concrete"]["steps"] / lo["concrete"]["steps"]
        assert ratio_conc >= ratio_np * 0.9, name
        assert hi["symbolic"]["steps"] == lo["symbolic"]["steps"], name
