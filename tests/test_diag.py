"""The diagnostics CLI: all CPU-tier checks pass in this environment."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_diag_cpu_checks():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.runtime.diag", "--json",
         "--port", "45990"],
        capture_output=True, text=True, timeout=240, cwd=REPO, env=env,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    data = json.loads(res.stdout.strip().splitlines()[-1])
    assert data["failed"] == 0
    names = {r["check"] for r in data["results"]}
    assert names == {"native_build", "ffi_fast_path", "coll_algo_engine",
                     "observability", "static_verify", "schedule_plan",
                     "topology", "transport_loopback", "failure_detection",
                     "self_healing", "elasticity", "serving",
                     "live_retune"}
    # the topology probe renders the island map and the live pick
    topo_check = next(r for r in data["results"] if r["check"] == "topology")
    assert "island0[" in topo_check["detail"]
    assert "algo16mb=" in topo_check["detail"]
    # the algorithm engine reports the alltoall family (MoE exchange)
    # next to the quantized wire formats
    ce = next(r for r in data["results"]
              if r["check"] == "coll_algo_engine")
    assert "quant=qring,qrd" in ce["detail"]
    assert "alltoall=halltoall,hqalltoall,qalltoall" in ce["detail"]
    # the static verifier check proves both verdict directions
    sv = next(r for r in data["results"] if r["check"] == "static_verify")
    assert "tag_mismatch flagged" in sv["detail"]
    assert "clean verified" in sv["detail"]
    # the loopback probe reports the engine's pick from a live comm
    loopback = next(r for r in data["results"]
                    if r["check"] == "transport_loopback")
    assert "algo16mb=" in loopback["detail"]
    # the failure-detection probe reports the resolved knobs and proves
    # an injected hang trips the deadline with the stuck peer named
    fd = next(r for r in data["results"] if r["check"] == "failure_detection")
    assert "timeout_s=" in fd["detail"] and "connect_s=" in fd["detail"]
    assert "detected" in fd["detail"]
    # the observability probe records a loopback op into the event ring
    # and proves the export validates against the trace schema
    ob = next(r for r in data["results"] if r["check"] == "observability")
    assert "events recorded" in ob["detail"]
    assert "trace validates" in ob["detail"]
    # the serving probe proves the disaggregated path (prefill on r1,
    # KV shipped, decode on r2) with the KV bytes visible in stats and
    # an over-cap submit shed instead of admitted
    sv2 = next(r for r in data["results"] if r["check"] == "serving")
    assert "prefill=r1 decode=r2" in sv2["detail"]
    assert "kv tier bytes" in sv2["detail"]
    assert "shed" in sv2["detail"]
    # the self-healing probe proves an injected link reset healed on
    # the first reconnect attempt with the counters visible in stats
    sh = next(r for r in data["results"] if r["check"] == "self_healing")
    assert "healed on attempt 1" in sh["detail"]
    assert "digests bit-identical" in sh["detail"]
    assert "dup_dropped=" in sh["detail"]
    assert "obs.stats()" in sh["detail"]
    # the live-retune probe proves forced drift flows through detection,
    # the epoch rendezvous, and the swap report on both ranks
    lr = next(r for r in data["results"] if r["check"] == "live_retune")
    assert "drift detected" in lr["detail"]
    assert "ring -> rd" in lr["detail"]
    assert "on both ranks" in lr["detail"]
