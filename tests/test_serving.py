"""Serving v2 unit surface (mpi4jax_tpu/serving): the paged KV cache,
the model-adapter contract (prefix consistency, chunked prefill,
incremental decode), role assignment over topologies, admission
control, the SLO feedback loop's pinned adaptation latency, and the
strict SERVE_* knob parsers.

No ranks, no sockets — everything here is the pure-Python half the
world tests (tests/world/test_elastic.py) and the serving diag check
compose into the distributed story.  Where the real package is gated
(old-jax containers) it loads under an ALIAS package name, like
test_schedule_plan.py does — installing the real name in sys.modules
would leak into later-collected tests and un-skip their version gates.
"""

import importlib
import pathlib
import sys
import types

import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

try:
    from mpi4jax_tpu import serving
    from mpi4jax_tpu.serving import _engine, _roles
    from mpi4jax_tpu.utils import config
except ImportError:
    _ALIAS = "m4j_srv"
    if _ALIAS not in sys.modules:
        _pkg = types.ModuleType(_ALIAS)
        _pkg.__path__ = [str(REPO / "mpi4jax_tpu")]
        sys.modules[_ALIAS] = _pkg
    serving = importlib.import_module(_ALIAS + ".serving")
    _engine = importlib.import_module(_ALIAS + ".serving._engine")
    _roles = importlib.import_module(_ALIAS + ".serving._roles")
    config = importlib.import_module(_ALIAS + ".utils.config")


# ---------------- KVCache ----------------


def test_kv_cache_append_view_roundtrip_across_pages():
    kv = serving.KVCache((2, 3), np.float32, page=4)
    entries = np.arange(10 * 6, dtype=np.float32).reshape(10, 2, 3)
    kv.append(7, entries[:1][0])        # single-entry form
    kv.append(7, entries[1:])           # batch form
    assert kv.length(7) == 10
    assert 7 in kv and 8 not in kv
    np.testing.assert_array_equal(kv.view(7), entries)
    # 10 entries over page=4 -> 3 pages, padding not counted as bytes
    assert kv.live_pages == 3
    assert kv.nbytes(7) == 10 * 6 * 4


def test_kv_cache_load_free_drop_all():
    kv = serving.KVCache((1,), np.int64, page=2)
    kv.append(1, np.arange(5, dtype=np.int64)[:, None])
    wire = kv.view(1)
    kv2 = serving.KVCache((1,), np.int64, page=64)
    kv2.load(1, wire)                   # receive side of the KV wire
    np.testing.assert_array_equal(kv2.view(1), wire)
    kv2.load(1, wire[:0])               # empty load keeps the request
    assert 1 in kv2 and kv2.length(1) == 0
    kv.free(1)
    assert 1 not in kv and kv.length(1) == 0 and kv.live_pages == 0
    kv.append(2, np.arange(3, dtype=np.int64)[:, None])
    kv.drop_all()                       # the elastic-recovery reset
    assert kv.live_requests == 0 and kv.length(2) == 0


def test_kv_cache_rejects_wrong_entry_shape():
    kv = serving.KVCache((2, 2), np.float32)
    with pytest.raises(ValueError, match="entry shape"):
        kv.append(0, np.zeros((3, 3), np.float32))


# ---------------- adapters ----------------


def _greedy(adapter, prompt, n, chunk=None):
    """Generate n tokens: chunked prefill (or whole-prompt) + cached
    decode_step chain — the exact call pattern the engine makes."""
    toks = list(prompt)
    past = None
    if chunk is None:
        past, logits = adapter.prefill(np.asarray(toks, np.int32))
    else:
        for lo in range(0, len(toks), chunk):
            entries, logits = adapter.prefill(
                np.asarray(toks[lo:lo + chunk], np.int32), past)
            past = (entries if past is None
                    else np.concatenate([past, entries]))
    out = []
    for _ in range(n):
        nxt = int(np.argmax(logits))
        out.append(nxt)
        entry, logits = adapter.decode_step(past, nxt)
        past = np.concatenate([past, entry[None]])
    return out


def test_toy_adapter_exactly_prefix_consistent():
    a = serving.ToyAdapter()
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]
    whole = _greedy(a, prompt, 8)
    chunked = _greedy(a, prompt, 8, chunk=3)
    assert whole == chunked
    # re-prefilling the full transcript reproduces the cache exactly —
    # the invariant the elastic retry path relies on
    entries, _ = a.prefill(np.asarray(prompt + whole, np.int32))
    past = a.prefill(np.asarray(prompt, np.int32))[0]
    for t in whole:
        e, _ = a.decode_step(past, t)
        past = np.concatenate([past, e[None]])
    np.testing.assert_array_equal(entries, past)


def test_numpy_gpt_incremental_decode_matches_full_prefill():
    a = serving.make_numpy_gpt_adapter(max_seq=64)
    prompt = [5, 17, 3, 42, 8, 11]
    # incremental: prefill prompt once, decode_step the continuation
    past, logits = a.prefill(np.asarray(prompt, np.int32))
    toks = list(prompt)
    for _ in range(6):
        nxt = int(np.argmax(logits))
        toks.append(nxt)
        entry, logits = a.decode_step(past, nxt)
        past = np.concatenate([past, entry[None]])
    # full recompute of the same transcript agrees to float tolerance
    full_entries, full_logits = a.prefill(np.asarray(toks, np.int32))
    np.testing.assert_allclose(full_entries, past, atol=1e-5)
    np.testing.assert_allclose(full_logits, logits, atol=1e-4)
    # and chunked prefill is the same function as whole-prompt prefill
    assert _greedy(a, prompt, 6) == _greedy(a, prompt, 6, chunk=2)


def test_gpt_adapter_rejects_context_overflow():
    a = serving.make_numpy_gpt_adapter(max_seq=8)
    with pytest.raises(ValueError, match="max_seq"):
        a.prefill(np.zeros(9, np.int32))


# ---------------- role assignment ----------------


class _FakeTopo:
    def __init__(self, island_of):
        self.island_of = list(island_of)
        self.multi = len(set(island_of)) > 1


def test_roles_auto_flat_world_colocates():
    plan = serving.assign_roles(4, None, mode="auto")
    assert plan.mode == "colocated"
    assert plan.prefill_ranks == plan.decode_ranks == [0, 1, 2, 3]
    p, d = plan.placement(0)
    assert p == d  # colocated: prefill rank IS the decode rank


def test_roles_auto_multi_island_disaggregates():
    # frontend r0's island holds r0,r1; the other island decodes
    plan = serving.assign_roles(4, _FakeTopo([0, 0, 1, 1]), mode="auto")
    assert plan.mode == "disagg"
    assert plan.prefill_ranks == [1]
    assert plan.decode_ranks == [2, 3]
    assert plan.role_of(0) == "frontend"
    assert plan.role_of(1) == "prefill"
    assert plan.role_of(2) == "decode"
    # round-robin placement over decode ranks, stable per sequence no.
    assert [plan.placement(i) for i in range(3)] == [
        (1, 2), (1, 3), (1, 2)]


def test_roles_forced_disagg_positional_split_and_too_small():
    plan = serving.assign_roles(4, None, mode="disagg")
    assert plan.mode == "disagg"
    assert plan.prefill_ranks == [1] and plan.decode_ranks == [2, 3]
    with pytest.raises(ValueError, match=">= 3 ranks"):
        serving.assign_roles(2, None, mode="disagg")
    # auto on the same too-small world silently colocates instead
    assert serving.assign_roles(
        2, _FakeTopo([0, 1]), mode="auto").mode == "colocated"


def test_roles_same_plan_from_every_rank_and_after_shrink():
    # pure function of (size, topology, mode): every rank derives the
    # identical plan, and a shrink just re-derives from the new inputs
    topo = _FakeTopo([0, 0, 1, 1, 1])
    plans = [serving.assign_roles(5, topo, mode="auto") for _ in range(5)]
    assert len({(tuple(p.prefill_ranks), tuple(p.decode_ranks))
                for p in plans}) == 1
    shrunk = serving.assign_roles(4, _FakeTopo([0, 0, 1, 1]), mode="auto")
    assert shrunk.mode == "disagg" and shrunk.size == 4


def test_recovery_degrades_forced_disagg_on_too_small_world(capsys):
    # a shrink below 3 survivors must not kill a forced-disagg job:
    # the recovery-time derivation degrades to colocated, loudly
    class _TinyComm:
        def size(self):
            return 2

    plan = _engine._derive_roles_after_recovery(_TinyComm(), "disagg")
    assert plan.mode == "colocated" and plan.size == 2
    err = capsys.readouterr().err
    assert "NOTICE" in err and "colocated" in err
    # a world that still fits keeps the forced split
    class _Comm3(_TinyComm):
        def size(self):
            return 3

    assert _engine._derive_roles_after_recovery(
        _Comm3(), "disagg").mode == "disagg"


def test_roles_disagg_island_collapse_falls_back_positional():
    # every survivor in the frontend's island: no inter-island split
    # exists, the forced mode still disaggregates positionally
    plan = _roles.assign_roles(5, _FakeTopo([0, 0, 0, 0, 0]),
                               mode="disagg")
    assert plan.mode == "disagg"
    assert plan.prefill_ranks == [1, 2] and plan.decode_ranks == [3, 4]


# ---------------- admission control ----------------


def test_admission_cap_sheds_and_retire_frees_slots():
    adm = serving.Admission(cap=2)
    assert adm.offer(0, 4).admitted
    assert adm.offer(1, 4).admitted
    v = adm.offer(2, 4)
    assert not v.admitted and "capacity" in v.reason
    assert "SHED" in repr(v)
    assert (adm.pending, adm.admitted, adm.shed) == (2, 2, 1)
    adm.retire()
    assert adm.offer(3, 4).admitted  # the freed slot is reusable
    assert adm.pending == 2


def test_admission_sheds_overlong_prompt_without_consuming_a_slot():
    adm = serving.Admission(cap=8, max_prompt=16)
    v = adm.offer(0, 17)
    assert not v.admitted and "exceeds model context" in v.reason
    assert adm.pending == 0 and adm.shed == 1


# ---------------- SLO feedback loop ----------------


def test_slo_disabled_never_adapts():
    c = serving.SLOController(max_batch=8, chunk_tokens=64, slo_ms=0)
    assert all(c.observe(1e6) is None for _ in range(100))
    assert c.adaptations == 0 and c.max_batch == 8


def test_slo_quiescent_run_makes_zero_adaptations():
    # healthy decode well under the SLO, batch already at the knob:
    # the loop must not touch anything (the acceptance pin)
    c = serving.SLOController(max_batch=8, chunk_tokens=64, slo_ms=100)
    assert all(c.observe(1.0) is None for _ in range(200))
    assert c.adaptations == 0
    assert c.max_batch == 8 and c.chunk_tokens == 64


def test_slo_adapts_to_sustained_overshoot_within_two_windows():
    # synthetic slow decode: the FIRST adaptation must land within
    # 2*WINDOW iterations of the slowdown starting (pinned latency)
    c = serving.SLOController(max_batch=8, chunk_tokens=256, slo_ms=5)
    fired_at = None
    for i in range(2 * serving.SLOController.WINDOW):
        if c.observe(20.0) is not None:
            fired_at = i + 1
            break
    assert fired_at is not None
    assert fired_at <= 2 * serving.SLOController.WINDOW
    assert c.max_batch == 4 and c.chunk_tokens == 128
    assert c.adaptations == 1 and not c.retune_requested


def test_slo_floor_requests_retune_then_stays_quiet():
    c = serving.SLOController(max_batch=1, chunk_tokens=32, slo_ms=5)
    verdicts = [c.observe(50.0) for _ in range(5 * c.WINDOW)]
    fired = [v for v in verdicts if v]
    assert len(fired) == 1 and "re-tune" in fired[0]
    assert c.retune_requested and c.max_batch == 1


def test_slo_regrows_toward_but_never_beyond_initial():
    c = serving.SLOController(max_batch=8, chunk_tokens=256, slo_ms=10)
    while c.max_batch > 2:           # shrink twice under overload
        c.observe(100.0)
    assert c.max_batch == 2
    for _ in range(20 * c.WINDOW):   # then a long healthy stretch
        c.observe(0.5)
    assert c.max_batch == 8 and c.chunk_tokens == 256
    assert c.adaptations == 4        # 2 down + 2 up, then quiet


# ---------------- SERVE_* knob parsers ----------------


@pytest.mark.parametrize("name,fn,default", [
    ("MPI4JAX_TPU_SERVE_MAX_BATCH", config.serve_max_batch, 8),
    ("MPI4JAX_TPU_SERVE_QUEUE_CAP", config.serve_queue_cap, 256),
])
def test_serve_int_knobs_strict(monkeypatch, name, fn, default):
    monkeypatch.delenv(name, raising=False)
    assert fn() == default
    monkeypatch.setenv(name, "12")
    assert fn() == 12
    for bad in ("0", "-3", "eight", "2.5"):
        monkeypatch.setenv(name, bad)
        with pytest.raises(ValueError, match=name):
            fn()


def test_serve_slo_ms_knob_strict(monkeypatch):
    monkeypatch.delenv("MPI4JAX_TPU_SERVE_SLO_MS", raising=False)
    assert config.serve_slo_ms() == 0.0  # unset = loop disabled
    monkeypatch.setenv("MPI4JAX_TPU_SERVE_SLO_MS", "2.5")
    assert config.serve_slo_ms() == 2.5
    for bad in ("-1", "fast"):
        monkeypatch.setenv("MPI4JAX_TPU_SERVE_SLO_MS", bad)
        with pytest.raises(ValueError, match="SERVE_SLO_MS"):
            config.serve_slo_ms()


def test_serve_roles_knob_strict(monkeypatch):
    monkeypatch.delenv("MPI4JAX_TPU_SERVE_ROLES", raising=False)
    assert config.serve_roles() == "auto"
    for good in ("auto", "colocated", "disagg"):
        monkeypatch.setenv("MPI4JAX_TPU_SERVE_ROLES", good)
        assert config.serve_roles() == good
    monkeypatch.setenv("MPI4JAX_TPU_SERVE_ROLES", "split")
    with pytest.raises(ValueError, match="SERVE_ROLES"):
        config.serve_roles()


def test_scheduler_reads_knobs_as_defaults(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_SERVE_MAX_BATCH", "3")
    monkeypatch.setenv("MPI4JAX_TPU_SERVE_QUEUE_CAP", "5")
    monkeypatch.setenv("MPI4JAX_TPU_SERVE_SLO_MS", "7.5")
    c = serving.SLOController()
    assert c.initial_max_batch == 3 and c.slo_ms == 7.5
    assert serving.Admission().cap == 5
