"""Unit tests for the schedule compiler (analysis/_deps.py, _plan.py).

Loaded standalone (no package import, no jax) like test_analysis_match:
the dependence pass, the plan builder, and the equivalence prover are
pure Python by design, so these run — and the rewrite semantics stay
pinned — even on hosts whose jax predates the package minimum.
"""

import importlib.util
import json
import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "mpi4jax_tpu", "analysis")


def _load():
    if "m4j_pl._plan" in sys.modules:
        return tuple(sys.modules[f"m4j_pl.{n}"]
                     for n in ("_events", "_match", "_deps", "_plan"))
    pkg = types.ModuleType("m4j_pl")
    pkg.__path__ = [PKG]
    sys.modules["m4j_pl"] = pkg
    mods = []
    for name in ("_events", "_match", "_deps", "_plan"):
        spec = importlib.util.spec_from_file_location(
            f"m4j_pl.{name}", os.path.join(PKG, f"{name}.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[f"m4j_pl.{name}"] = mod
        spec.loader.exec_module(mod)
        mods.append(mod)
    return tuple(mods)


EV, MT, DP, PL = _load()
WORLD2 = {(0,): (0, 1)}
WORLD3 = {(0,): (0, 1, 2)}
BIG = (64 * 1024,)  # f32: 256 KB, above any detach threshold


def _ev(r, i, kind, shape=(4,), **kw):
    return EV.CommEvent(r, i, kind, dtype="float32", shape=shape,
                        site=f"p.py:{10 + i}", **kw)


def _send(r, i, dest, tag=0, shape=(4,)):
    return _ev(r, i, "send", dest=dest, tag=tag, shape=shape)


def _recv(r, i, source, tag=0, shape=(4,), **kw):
    return _ev(r, i, "recv", source=source, tag=tag, shape=shape, **kw)


# ---- dependence pass ---------------------------------------------------


def test_channel_and_collective_edges():
    evs = [
        _send(0, 0, dest=1, tag=0),
        _send(0, 1, dest=1, tag=1),          # same channel: edge 0->1
        _send(0, 2, dest=2, tag=0),          # other channel: no edge
        _ev(0, 3, "allreduce", reduce_op="SUM"),
        _ev(0, 4, "barrier"),                # collective chain: 3->4
        _recv(0, 5, source=1),
        _recv(0, 6, source=1),               # same channel: edge 5->6
        _recv(0, 7, source=2),               # other channel: no edge
    ]
    g = DP.build_rank_deps(evs)
    assert g.depends(0, 1) and g.kind[(0, 1)] == "channel"
    assert not g.depends(1, 2) and not g.depends(0, 2)
    assert g.depends(3, 4) and g.kind[(3, 4)] == "collective"
    assert g.depends(5, 6) and not g.depends(6, 7)
    # the pairs with no semantic edge are the token-only serialization
    assert g.artificial_pairs() >= 3


def test_wildcard_fences_everything_on_the_comm():
    any_src = EV.ANY_SOURCE
    evs = [
        _send(0, 0, dest=1),
        _recv(0, 1, source=any_src),
        _send(0, 2, dest=2),
        _recv(0, 3, source=1),
    ]
    g = DP.build_rank_deps(evs)
    assert g.depends(0, 1) and g.kind[(0, 1)] == "wildcard"
    assert g.depends(1, 2) and g.depends(1, 3)


def test_status_recv_is_wildcard_like():
    evs = [_send(0, 0, dest=1),
           _recv(0, 1, source=1, status=True),
           _send(0, 2, dest=1, tag=9)]
    g = DP.build_rank_deps(evs)
    assert DP.is_wildcard(evs[1])
    assert g.depends(0, 1) and g.depends(1, 2)


def test_value_deps_become_data_edges():
    evs = [_recv(0, 0, source=1), _send(0, 1, dest=1)]
    g = DP.build_rank_deps(evs, value_deps={(0, 1)})
    assert g.depends(0, 1) and g.kind[(0, 1)] == "data"


def test_concurrency_groups_solo_rules_and_cap():
    evs = ([_send(0, i, dest=1, tag=i) for i in range(6)]
           + [_ev(0, 6, "allreduce", reduce_op="SUM")]
           + [_recv(0, 7, source=EV.ANY_SOURCE)])
    g = DP.build_rank_deps(evs)
    # sends to ONE peer share a channel -> serialized, all solo groups
    groups = DP.concurrency_groups(evs, g)
    assert all(len(grp) == 1 for grp in groups)
    # sends to DIFFERENT peers group, capped at MAX_GROUP
    evs2 = [_send(0, i, dest=i + 1, tag=0) for i in range(6)]
    g2 = DP.build_rank_deps(evs2)
    groups2 = DP.concurrency_groups(evs2, g2)
    assert [len(x) for x in groups2] == [DP.MAX_GROUP, 6 - DP.MAX_GROUP]
    # collectives and wildcards never share a group
    evs3 = [_send(0, 0, dest=1), _ev(0, 1, "barrier"), _send(0, 2, dest=2)]
    g3 = DP.build_rank_deps(evs3)
    assert DP.concurrency_groups(evs3, g3) == [[0], [1], [2]]


def test_recv_post_point_temporal_and_fences():
    evs = [_send(0, 0, dest=1, shape=BIG), _recv(0, 1, source=1, shape=BIG)]
    g = DP.build_rank_deps(evs)
    # temporal hoist: posted inside the previous op's callback
    assert DP.recv_post_point(evs, g, 1) == 0
    # first op cannot hoist; wildcard/status recvs never hoist
    assert DP.recv_post_point([_recv(0, 0, source=1)],
                              DP.build_rank_deps([_recv(0, 0, source=1)]),
                              0) == 0
    evs2 = [_send(0, 0, dest=1),
            _recv(0, 1, source=EV.ANY_SOURCE)]
    g2 = DP.build_rank_deps(evs2)
    assert DP.recv_post_point(evs2, g2, 1) == 1
    # a foreign-engine event between post point and recv is passable
    # (its lineage ROOT differs: separate socket set, separate progress
    # thread); a same-engine event — including any sub-comm, which
    # borrows the parent's sockets — is not (FIFO coupling)
    foreign = (1,)
    evs3 = [_send(0, 0, dest=1),
            EV.CommEvent(0, 1, "send", comm=foreign, dest=1,
                         dtype="float32", shape=(4,)),
            _recv(0, 2, source=1)]
    g3 = DP.build_rank_deps(evs3)
    assert DP.recv_post_point(evs3, g3, 2) == 0
    sub = (0, 1, 0)  # sub-comm: same engine root -> fence
    evs4 = [_send(0, 0, dest=1),
            EV.CommEvent(0, 1, "send", comm=sub, dest=1,
                         dtype="float32", shape=(4,)),
            _recv(0, 2, source=1)]
    g4 = DP.build_rank_deps(evs4)
    assert DP.recv_post_point(evs4, g4, 2) == 1


# ---- plan construction + the equivalence prover ------------------------


def test_pipeline_plan_is_rewritten_and_proved():
    sch = {r: [_send(r, 0, dest=(r + 1) % 3, shape=BIG),
               _recv(r, 1, source=(r - 1) % 3, shape=BIG)]
           for r in range(3)}
    plan = PL.compile_schedules(sch, WORLD3)
    assert plan.proved and plan.rewritten
    assert plan.proof["exhaustive"]
    for r in range(3):
        assert plan.ranks[r].ops[1].hoisted
        assert plan.ranks[r].ops[0].deferred
    # the summary names the cache key and verdict (CLI surface)
    assert "proved" in plan.summary() and plan.cache_key


def test_order_critical_schedule_left_unrewritten():
    # send;recv vs recv;send with blocking payloads: true cross-rank
    # ordering dependence — the plan must demonstrably not rewrite it
    sch = {0: [_send(0, 0, dest=1, shape=BIG),
               _recv(0, 1, source=1, shape=BIG)],
           1: [_recv(1, 0, source=0, shape=BIG),
               _send(1, 1, dest=0, shape=BIG)]}
    findings = MT.match_schedules(sch, WORLD2)
    assert any(f.kind == "order_critical_exchange" for f in findings)
    plan = PL.compile_schedules(sch, WORLD2, findings=findings)
    assert plan.proved and not plan.rewritten
    assert any("unrewritten" in r for r in plan.reasons)


def test_prover_rejects_unsafe_wire_reorder():
    # hand-build a plan whose hoist crosses a same-engine send (the
    # symmetric-exchange deadlock): the prover must reject it, and
    # compile_schedules must fall back to a proved plan
    sch = {r: [_send(r, 0, dest=1 - r, shape=BIG),
               _recv(r, 1, source=1 - r, shape=BIG)] for r in range(2)}
    bad = PL.build_plan(sch, WORLD2)
    for r in range(2):
        bad.ranks[r].ops[1].post_at = -1  # wire-reorder before the send
    assert not PL.prove_plan(sch, WORLD2, bad)
    assert any("new finding kind" in f for f in bad.proof["failures"])


def test_prover_pins_per_channel_delivery_order():
    # two sends to one peer on one channel: any plan permuting them
    # changes delivery order; the simulator must record it
    sch = {0: [_send(0, 0, dest=1, tag=5), _send(0, 1, dest=1, tag=6)],
           1: [_recv(1, 0, source=0, tag=5), _recv(1, 1, source=0, tag=6)]}
    deliv = {}
    assert MT.match_schedules(sch, WORLD2, deliveries=deliv) == []
    chan = deliv["p2p"][((0,), 0, 1)]
    assert [d[1] for d in chan] == [0, 1]  # send idx order preserved
    plan = PL.compile_schedules(sch, WORLD2)
    assert plan.proved  # same-channel sends stay serialized by deps


def test_coalesce_and_bucket_marks():
    sch = {0: [_send(0, 0, dest=1, tag=0), _send(0, 1, dest=1, tag=1),
               _send(0, 2, dest=1, tag=2)],
           1: [_recv(1, 0, source=0, tag=0), _recv(1, 1, source=0, tag=1),
               _recv(1, 2, source=0, tag=2)]}
    plan = PL.compile_schedules(sch, WORLD2, coalesce_bytes=4096,
                                detach_threshold=32 * 1024)
    assert all(op.coalesce for op in plan.ranks[0].ops)
    assert not any(op.coalesce for op in plan.ranks[1].ops)

    ar = [_ev(r, i, "allreduce", reduce_op="SUM", shape=(64,))
          for r in range(2) for i in range(3)]
    sch2 = {0: ar[:3], 1: ar[3:]}
    plan2 = PL.compile_schedules(sch2, WORLD2, bucket_bytes=1 << 20)
    assert [op.bucket for op in plan2.ranks[0].ops] == [0, 0, 0]
    plan3 = PL.compile_schedules(sch2, WORLD2, bucket_bytes=0)
    assert all(op.bucket is None for op in plan3.ranks[0].ops)


def test_plan_json_round_trip_and_diff():
    sch = {r: [_send(r, 0, dest=(r + 1) % 3, shape=BIG),
               _recv(r, 1, source=(r - 1) % 3, shape=BIG)]
           for r in range(3)}
    plan = PL.compile_schedules(sch, WORLD3)
    blob = json.loads(json.dumps(plan.to_json()))
    back = PL.ExecutionPlan.from_json(blob)
    assert PL.diff_plans(plan, back) == ""
    back.ranks[0].ops[1].post_at = 1
    drift = PL.diff_plans(plan, back)
    assert "post_at" in drift
    # format gate: a wrong wire version is rejected, not misread
    blob_bad = dict(blob)
    blob_bad["format"] = 999
    try:
        PL.ExecutionPlan.from_json(blob_bad)
    except ValueError:
        pass
    else:
        raise AssertionError("bad plan format accepted")


def test_cache_key_ignores_sites_but_not_semantics():
    def mk(tag, site):
        return {0: [EV.CommEvent(0, 0, "send", dest=1, tag=tag,
                                 dtype="float32", shape=(4,), site=site)],
                1: [EV.CommEvent(1, 0, "recv", source=0, tag=tag,
                                 dtype="float32", shape=(4,),
                                 site=site)]}

    k1 = EV.schedule_cache_key(mk(0, "a.py:1"), 2)
    k2 = EV.schedule_cache_key(mk(0, "b.py:99"), 2)  # moved lines only
    k3 = EV.schedule_cache_key(mk(1, "a.py:1"), 2)   # semantic change
    assert k1 == k2 and k1 != k3
    assert EV.schedule_cache_key(mk(0, "a.py:1"), 3) != k1  # world size


def test_status_recv_accepts_short_messages():
    out = MT.match_schedules(
        {0: [_send(0, 0, dest=1, shape=(2,))],
         1: [_recv(1, 0, source=0, shape=(8,), status=True)]}, WORLD2)
    assert out == []  # short into a Status recv is the native contract
    out = MT.match_schedules(
        {0: [_send(0, 0, dest=1, shape=(16,))],
         1: [_recv(1, 0, source=0, shape=(8,), status=True)]}, WORLD2)
    assert [f.kind for f in out] == ["shape_mismatch"]  # truncation


def test_event_nbytes_parsing():
    assert EV.event_nbytes("float32", (4,)) == 16
    assert EV.event_nbytes("bfloat16", (8, 2)) == 32
    assert EV.event_nbytes("bool", (5,)) == 5
    assert EV.event_nbytes(None, (4,)) is None
    assert EV.event_nbytes("float32", None) is None
