"""Tier-1 wiring of the verify-corpus gate (make verify-corpus).

Runs the analyzer + schedule compiler + equivalence prover over every
program in ``tests/world_programs/golden_plans/manifest.json`` and
fails on any new finding kind, any unproved plan, or any plan/golden
drift — the CI contract of docs/analysis.md § "From verifier to
compiler".  All in-process: no rank processes, no live communication.
"""

import os
import sys

import pytest

try:
    import mpi4jax_tpu  # noqa: F401  (jax version gate)
except Exception as err:  # pragma: no cover - old-jax containers
    pytest.skip(f"mpi4jax_tpu not importable here: {err}",
                allow_module_level=True)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_verify_corpus_gate(capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import verify_corpus

    failures = verify_corpus.run()
    out = capsys.readouterr().out
    assert failures == 0, f"verify-corpus failures:\n{out}"
    # the golden-diffed programs really ran (the gate has teeth)
    assert "[golden]" in out
    assert "plan drift" not in out
