"""Strict-parser tests for the self-healing link knobs' Python mirrors
(``MPI4JAX_TPU_RETRY`` / ``RETRY_BACKOFF_MS`` / ``HEARTBEAT_S`` /
``WIRE_CRC`` / ``RETRY_REPLAY_SLACK``).

The native parsers exit the process on malformed values; these mirrors
must match that strictness — a mirror that quietly reads a typo'd knob
as its default would report a DIFFERENT configuration than the one the
transport is actually running.  Stdlib-only (config.py is loaded
standalone, the test_config_lint pattern), so this runs even where jax
cannot import.
"""

import importlib.util
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_config():
    spec = importlib.util.spec_from_file_location(
        "m4j_config_heal", os.path.join(REPO, "mpi4jax_tpu", "utils",
                                        "config.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


config = _load_config()


def test_knobs_registered():
    # satellite contract: the self-healing knobs live in the registry
    # (the lint cross-checks reads; this pins the rows themselves)
    for knob in ("MPI4JAX_TPU_RETRY", "MPI4JAX_TPU_RETRY_BACKOFF_MS",
                 "MPI4JAX_TPU_HEARTBEAT_S", "MPI4JAX_TPU_WIRE_CRC",
                 "MPI4JAX_TPU_RETRY_REPLAY_SLACK",
                 "MPI4JAX_TPU_CONNECT_TIMEOUT_S"):
        assert knob in config.KNOBS, knob


def test_retry_budget_default_disarmed(monkeypatch):
    monkeypatch.delenv("MPI4JAX_TPU_RETRY", raising=False)
    assert config.retry_budget() == 0
    assert config.retry_armed() is False
    monkeypatch.setenv("MPI4JAX_TPU_RETRY", "  ")
    assert config.retry_budget() == 0


def test_retry_budget_parses_and_clamps(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_RETRY", "4")
    assert config.retry_budget() == 4
    assert config.retry_armed() is True
    monkeypatch.setenv("MPI4JAX_TPU_RETRY", "0")
    assert config.retry_armed() is False
    # negatives clamp to disarmed rather than arming a nonsense budget
    monkeypatch.setenv("MPI4JAX_TPU_RETRY", "-3")
    assert config.retry_budget() == 0


def test_retry_budget_loud_on_garbage(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_RETRY", "many")
    with pytest.raises(ValueError, match="MPI4JAX_TPU_RETRY"):
        config.retry_budget()
    monkeypatch.setenv("MPI4JAX_TPU_RETRY", "2.5")
    with pytest.raises(ValueError, match="MPI4JAX_TPU_RETRY"):
        config.retry_budget()


def test_retry_backoff_default_and_floor(monkeypatch):
    monkeypatch.delenv("MPI4JAX_TPU_RETRY_BACKOFF_MS", raising=False)
    assert config.retry_backoff_ms() == 100.0
    monkeypatch.setenv("MPI4JAX_TPU_RETRY_BACKOFF_MS", "50")
    assert config.retry_backoff_ms() == 50.0
    # non-positive restores the default (a 0ms backoff would busy-dial)
    monkeypatch.setenv("MPI4JAX_TPU_RETRY_BACKOFF_MS", "0")
    assert config.retry_backoff_ms() == 100.0
    monkeypatch.setenv("MPI4JAX_TPU_RETRY_BACKOFF_MS", "-1")
    assert config.retry_backoff_ms() == 100.0


def test_retry_backoff_loud_on_garbage(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_RETRY_BACKOFF_MS", "fast")
    with pytest.raises(ValueError, match="MPI4JAX_TPU_RETRY_BACKOFF_MS"):
        config.retry_backoff_ms()


def test_heartbeat_default_off(monkeypatch):
    monkeypatch.delenv("MPI4JAX_TPU_HEARTBEAT_S", raising=False)
    assert config.heartbeat_s() == 0.0
    monkeypatch.setenv("MPI4JAX_TPU_HEARTBEAT_S", "0.2")
    assert config.heartbeat_s() == 0.2


def test_heartbeat_loud_on_garbage(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_HEARTBEAT_S", "often")
    with pytest.raises(ValueError, match="MPI4JAX_TPU_HEARTBEAT_S"):
        config.heartbeat_s()


def test_wire_crc_modes(monkeypatch):
    monkeypatch.delenv("MPI4JAX_TPU_WIRE_CRC", raising=False)
    assert config.wire_crc_mode() == "auto"
    for v in ("auto", "0", "1", " 1 "):
        monkeypatch.setenv("MPI4JAX_TPU_WIRE_CRC", v)
        assert config.wire_crc_mode() == v.strip()
    monkeypatch.setenv("MPI4JAX_TPU_WIRE_CRC", "")
    assert config.wire_crc_mode() == "auto"


def test_wire_crc_loud_on_garbage(monkeypatch):
    for v in ("yes", "on", "2", "true"):
        monkeypatch.setenv("MPI4JAX_TPU_WIRE_CRC", v)
        with pytest.raises(ValueError, match="MPI4JAX_TPU_WIRE_CRC"):
            config.wire_crc_mode()


def test_replay_slack_default_and_strict(monkeypatch):
    monkeypatch.delenv("MPI4JAX_TPU_RETRY_REPLAY_SLACK", raising=False)
    assert config.retry_replay_slack() == 0
    monkeypatch.setenv("MPI4JAX_TPU_RETRY_REPLAY_SLACK", "2")
    assert config.retry_replay_slack() == 2
    monkeypatch.setenv("MPI4JAX_TPU_RETRY_REPLAY_SLACK", "-1")
    assert config.retry_replay_slack() == 0
    monkeypatch.setenv("MPI4JAX_TPU_RETRY_REPLAY_SLACK", "lots")
    with pytest.raises(ValueError,
                       match="MPI4JAX_TPU_RETRY_REPLAY_SLACK"):
        config.retry_replay_slack()


def test_connect_timeout_bounded_by_default(monkeypatch):
    # the bootstrap accept side is bounded unless explicitly unbounded
    monkeypatch.delenv("MPI4JAX_TPU_CONNECT_TIMEOUT_S", raising=False)
    assert config.connect_timeout_s() == 30.0
    monkeypatch.setenv("MPI4JAX_TPU_CONNECT_TIMEOUT_S", "0")
    assert config.connect_timeout_s() == 0.0  # 0 = explicitly unbounded
