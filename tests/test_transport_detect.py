"""Foreign-launcher adoption unit tests (runtime/transport.py): the
rank/size env-pair table, the SLURM batch-step guard, native-variable
precedence, and the job-token rendezvous-port derivation that backs
``_default_coord``."""

import importlib.util
import pathlib

import pytest

REPO = pathlib.Path(__file__).resolve().parents[1]


def _load_transport():
    try:
        from mpi4jax_tpu.runtime import transport

        return transport
    except ImportError:
        # the package __init__ gates on the jax version; the detection
        # logic under test is stdlib-only at module level (bridge is a
        # lazy import inside WorldComm), so load it standalone
        spec = importlib.util.spec_from_file_location(
            "m4j_transport_standalone",
            REPO / "mpi4jax_tpu/runtime/transport.py",
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod


transport = _load_transport()

ALL_VARS = (
    "MPI4JAX_TPU_RANK", "MPI4JAX_TPU_SIZE",
    "OMPI_COMM_WORLD_RANK", "OMPI_COMM_WORLD_SIZE",
    "PMI_RANK", "PMI_SIZE",
    "SLURM_PROCID", "SLURM_NTASKS", "SLURM_LAUNCH_NODE_IPADDR",
)

TOKEN_VARS = ("OMPI_MCA_ess_base_jobid", "PMIX_NAMESPACE", "SLURM_JOB_ID",
              "PMI_JOBID", "PBS_JOBID", "LSB_JOBID", "MPI4JAX_TPU_COORD")


@pytest.fixture
def clean_env(monkeypatch):
    for var in ALL_VARS + TOKEN_VARS:
        monkeypatch.delenv(var, raising=False)
    return monkeypatch


def test_no_launcher_env_means_no_world(clean_env):
    assert transport._detect_rank_size() is None
    assert not transport.in_world()


def test_native_vars_adopted(clean_env):
    clean_env.setenv("MPI4JAX_TPU_RANK", "3")
    clean_env.setenv("MPI4JAX_TPU_SIZE", "8")
    assert transport._detect_rank_size() == (3, 8)
    assert transport.in_world()


def test_ompi_pair_adopted(clean_env):
    clean_env.setenv("OMPI_COMM_WORLD_RANK", "1")
    clean_env.setenv("OMPI_COMM_WORLD_SIZE", "4")
    assert transport._detect_rank_size() == (1, 4)


def test_pmi_pair_adopted(clean_env):
    clean_env.setenv("PMI_RANK", "2")
    clean_env.setenv("PMI_SIZE", "6")
    assert transport._detect_rank_size() == (2, 6)


def test_native_vars_beat_foreign_pairs(clean_env):
    # a job relaunched by this framework inside an mpirun allocation
    # must follow the native description, not the outer launcher's
    clean_env.setenv("OMPI_COMM_WORLD_RANK", "1")
    clean_env.setenv("OMPI_COMM_WORLD_SIZE", "4")
    clean_env.setenv("PMI_RANK", "2")
    clean_env.setenv("PMI_SIZE", "6")
    clean_env.setenv("MPI4JAX_TPU_RANK", "0")
    clean_env.setenv("MPI4JAX_TPU_SIZE", "2")
    assert transport._detect_rank_size() == (0, 2)


def test_ompi_beats_pmi_in_table_order(clean_env):
    clean_env.setenv("PMI_RANK", "2")
    clean_env.setenv("PMI_SIZE", "6")
    clean_env.setenv("OMPI_COMM_WORLD_RANK", "1")
    clean_env.setenv("OMPI_COMM_WORLD_SIZE", "4")
    assert transport._detect_rank_size() == (1, 4)


def test_slurm_batch_step_not_adopted(clean_env):
    # every SLURM *batch step* exports PROCID=0/NTASKS=N into plain
    # python invocations; adopting it would hang single-process programs
    # waiting for N-1 phantom peers.  Only srun tasks (which also carry
    # SLURM_LAUNCH_NODE_IPADDR) count.
    clean_env.setenv("SLURM_PROCID", "0")
    clean_env.setenv("SLURM_NTASKS", "16")
    assert transport._detect_rank_size() is None
    assert not transport.in_world()


def test_slurm_srun_task_adopted(clean_env):
    clean_env.setenv("SLURM_PROCID", "5")
    clean_env.setenv("SLURM_NTASKS", "16")
    clean_env.setenv("SLURM_LAUNCH_NODE_IPADDR", "10.0.0.1")
    assert transport._detect_rank_size() == (5, 16)


def test_half_pairs_ignored(clean_env):
    # a rank var without its size var is not a world signal
    clean_env.setenv("OMPI_COMM_WORLD_RANK", "1")
    assert transport._detect_rank_size() is None
    clean_env.setenv("PMI_SIZE", "6")
    assert transport._detect_rank_size() is None


def test_default_coord_without_token_is_fixed(clean_env):
    assert transport._default_coord() == "127.0.0.1:49817"


def test_default_coord_derives_stable_port_from_job_token(clean_env):
    clean_env.setenv("SLURM_JOB_ID", "777123")
    first = transport._default_coord()
    assert first == transport._default_coord()  # stable across ranks
    host, _, port = first.partition(":")
    assert host == "127.0.0.1"
    assert 41000 <= int(port) < 49000


def test_default_coord_distinct_jobs_distinct_ports(clean_env):
    clean_env.setenv("SLURM_JOB_ID", "777123")
    a = transport._default_coord()
    clean_env.setenv("SLURM_JOB_ID", "777124")
    b = transport._default_coord()
    assert a != b


def test_default_coord_token_precedence(clean_env):
    # first token var in table order wins (OMPI jobid over SLURM's)
    clean_env.setenv("SLURM_JOB_ID", "999")
    slurm_only = transport._default_coord()
    clean_env.setenv("OMPI_MCA_ess_base_jobid", "123")
    with_ompi = transport._default_coord()
    clean_env.delenv("SLURM_JOB_ID")
    # the OMPI token decided the port, with or without SLURM's present
    assert transport._default_coord() == with_ompi
    assert slurm_only != with_ompi
