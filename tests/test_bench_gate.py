"""bench.py's device-claim gate: the driver-critical scheduling logic.

Probes and clocks are faked — no device, no real sleeps.
"""

import importlib.util
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def bench(monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)  # top level only defines constants/fns

    class FakeTime:
        def __init__(self):
            self.now = 1000.0
            self.sleeps = []

        def time(self):
            return self.now

        def sleep(self, s):
            self.sleeps.append(s)
            self.now += s

        def perf_counter(self):
            return self.now

    ft = FakeTime()
    monkeypatch.setattr(mod, "time", ft)
    return mod, ft


def _flag():
    return {"ready": False, "deadline": 0.0, "window_s": 0.0}


def test_gate_healthy_claim(bench, monkeypatch):
    mod, ft = bench
    calls = []

    def fake_run(cmd, **kw):
        calls.append(cmd)

        class R:
            returncode = 0
            stdout = "claim-ok tpu\n"
            stderr = ""

        return R()

    monkeypatch.setattr(mod.subprocess, "run", fake_run)
    flag = _flag()
    ok, rec = mod._wait_for_claim(flag, 900, "x")
    assert ok and rec is None
    assert len(calls) == 1
    assert 15 in ft.sleeps  # settle delay for the probe's claim release
    assert flag["deadline"] >= ft.now  # watchdog covered the wait


def test_gate_wedged_claim_bounded(bench, monkeypatch):
    mod, ft = bench

    probes = []

    def fake_run(cmd, **kw):
        probes.append(ft.now)
        ft.now += kw["timeout"]  # the probe hangs for its full timeout
        raise subprocess.TimeoutExpired(cmd, kw["timeout"])

    monkeypatch.setattr(mod.subprocess, "run", fake_run)
    flag = _flag()
    t0 = ft.now
    ok, rec = mod._wait_for_claim(flag, 900, "world_on_tpu")
    assert not ok
    assert rec["metric"] == "device_claim_before_world_on_tpu"
    assert rec["value"] == 0 and "wedged" in rec["error"]
    # sparse probes (>= ~7 min apart): rapid-fire retries would livelock
    # against the re-wedge window a killed probe re-arms
    assert len(probes) == 2, probes
    assert all(b - a >= 300 for a, b in zip(probes, probes[1:])), probes
    # bounded: within the budget plus one final probe timeout
    assert ft.now - t0 <= 900 + 160
    # the watchdog deadline covered the whole wait
    assert flag["deadline"] >= t0 + 900


def test_gate_recovers_on_final_probe(bench, monkeypatch):
    mod, ft = bench
    state = {"n": 0}

    def fake_run(cmd, **kw):
        state["n"] += 1
        if state["n"] == 1:
            ft.now += kw["timeout"]
            raise subprocess.TimeoutExpired(cmd, kw["timeout"])

        class R:
            returncode = 0
            stdout = "claim-ok tpu\n"
            stderr = ""

        return R()

    monkeypatch.setattr(mod.subprocess, "run", fake_run)
    ok, rec = mod._wait_for_claim(_flag(), 900, "x")
    assert ok and rec is None
    assert state["n"] == 2


def test_gate_rejects_cpu_fallback(bench, monkeypatch):
    # a probe whose jax silently fell back to the cpu platform must NOT
    # count as a healthy device claim (ADVICE r3 #2)
    mod, ft = bench

    def fake_run(cmd, **kw):
        class R:
            returncode = 0
            stdout = "claim-ok cpu\n"
            stderr = ""

        return R()

    monkeypatch.setattr(mod.subprocess, "run", fake_run)
    ok, rec = mod._wait_for_claim(_flag(), 500, "x")
    assert not ok
    assert "wedged" in rec["error"]


def test_artifact_contract_under_budget_kill():
    # the r5 output contract, end to end: a battery whose total budget
    # expires almost immediately must still exit rc=0 with a complete
    # parseable summary as the LAST stdout line — every section present
    # as a real record or an explicit pending/skip record (VERDICT r4
    # weak #1: r4's battery died summary-less under the driver timeout)
    import json

    env = dict(os.environ)
    env["BENCH_TOTAL_BUDGET_S"] = "78"  # guard fires ~3 s in
    env["JAX_PLATFORMS"] = "cpu"
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=70, env=env, cwd=REPO,
    )
    assert res.returncode == 0, res.stderr[-500:]
    lines = [ln for ln in res.stdout.splitlines() if ln.strip()]
    final = json.loads(lines[-1])  # last line parses, whatever happened
    assert final["metric"].startswith("shallow_water")
    assert "battery_note" in final and "budget" in final["battery_note"]
    metrics = final["metrics"]
    assert len(metrics) >= 9  # every planned section is represented
    for m in metrics:
        assert "metric" in m
        assert "value" in m  # real value or explicit null + error reason
        if m["value"] is None:
            assert m.get("error"), m


def test_artifact_contract_sigterm():
    # SIGTERM (the driver's timeout signal) must flush the full summary
    import json
    import signal as _signal
    import time as _time

    env = dict(os.environ)
    env["BENCH_TOTAL_BUDGET_S"] = "3000"
    env["JAX_PLATFORMS"] = "cpu"
    import threading

    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "bench.py")],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=REPO,
    )
    try:
        # wait (bounded) for the startup summary — the contract says it
        # exists from second zero — so the signal lands after the
        # handler is installed even on a loaded host
        first_box = []
        reader = threading.Thread(
            target=lambda: first_box.append(proc.stdout.readline()),
            daemon=True)
        reader.start()
        reader.join(timeout=60)
        assert first_box and first_box[0].strip(), "no startup summary"
        _time.sleep(1)
        proc.send_signal(_signal.SIGTERM)
        out, _ = proc.communicate(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    lines = [first_box[0]] + [ln for ln in out.splitlines() if ln.strip()]
    final = json.loads(lines[-1])
    assert final["metric"].startswith("shallow_water")
    assert "signal" in final.get("battery_note", "")
    assert len(final["metrics"]) >= 9
