"""Unit + differential tests for rank-symbolic analysis
(analysis/_symbolic.py).

Loaded standalone (no package import, no jax), like
test_analysis_match.py: the symbolic layer is pure Python by design, so
the differential gate — symbolic verdicts byte-identical to concrete —
stays pinned even on hosts whose jax predates the package minimum.
The corpus-program half of the gate lives in test_symbolic_corpus.py
(skipped where ``import mpi4jax_tpu`` is unavailable).
"""

import importlib.util
import os
import sys
import types

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "mpi4jax_tpu", "analysis")


def _load():
    """Load the analysis stack standalone under a private package."""
    if "m4j_sy._symbolic" in sys.modules:
        return {n: sys.modules[f"m4j_sy.{n}"]
                for n in ("_events", "_match", "_deps", "_plan",
                          "_symbolic")}
    pkg = types.ModuleType("m4j_sy")
    pkg.__path__ = [PKG]
    sys.modules["m4j_sy"] = pkg
    mods = {}
    for name in ("_events", "_match", "_deps", "_plan", "_symbolic"):
        spec = importlib.util.spec_from_file_location(
            f"m4j_sy.{name}", os.path.join(PKG, f"{name}.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[f"m4j_sy.{name}"] = mod
        spec.loader.exec_module(mod)
        mods[name] = mod
    return mods


M = _load()
EV, MT, PL, SY = M["_events"], M["_match"], M["_plan"], M["_symbolic"]


# -- the report pipeline's canonical ordering, mirrored from
#    analysis/__init__._canonical_finding_key (package import needs jax)
def _key(f):
    return (0 if f.severity == "error" else 1, f.kind,
            tuple(f.ranks), str(f.comm), f.message, tuple(f.sites))


def _dedupe(findings):
    out, seen = [], set()
    for f in findings:
        key = (f.kind, f.ranks, f.comm, f.message, f.sites)
        if key in seen:
            continue
        seen.add(key)
        out.append(f)
    out.sort(key=_key)
    return out


def world(n):
    return {(0,): tuple(range(n))}


def ev(r, i, kind, **kw):
    kw.setdefault("dtype", "float32")
    kw.setdefault("shape", (4,))
    kw.setdefault("site", f"prog.py:{10 + i}")
    return EV.CommEvent(r, i, kind, **kw)


# ---------------------------------------------------------------------------
# schedule families (np-parametric, mirroring the verify-corpus
# communication patterns: rings, halos, pairs, collectives, mixes)


def ring(n, tag=0):
    return {r: [ev(r, 0, "sendrecv", dest=(r + 1) % n,
                   source=(r - 1) % n, sendtag=tag, recvtag=tag)]
            for r in range(n)}


def halo_walls(n):
    """Non-periodic shift2 halo: rank 0 and rank n-1 see walls, so
    refinement must keep separating boundary roles — every rank its own
    class (distance to each wall differs)."""
    return {r: [ev(r, 0, "shift2", lo=r - 1,
                   hi=r + 1 if r + 1 < n else -1, tag=3)]
            for r in range(n)}


def colls(n):
    return {r: [ev(r, 0, "allreduce", reduce_op="SUM"),
                ev(r, 1, "bcast", root=0),
                ev(r, 2, "barrier", shape=(), dtype="none")]
            for r in range(n)}


def coll_mismatch(n):
    s = colls(n)
    s[n - 1][0] = ev(n - 1, 0, "allreduce", reduce_op="MAX")
    return s


def pairs(n):
    return {r: [ev(r, 0, "sendrecv",
                   dest=r + 1 if r % 2 == 0 else r - 1,
                   source=r + 1 if r % 2 == 0 else r - 1,
                   sendtag=1, recvtag=1)]
            for r in range(n)}


def tag_mismatch(n):
    s = {}
    for r in range(n):
        p = r + 1 if r % 2 == 0 else r - 1
        s[r] = [ev(r, 0, "send", dest=p, tag=1 if r % 2 == 0 else 2),
                ev(r, 1, "recv", source=p, tag=1)]
    return s


def deadlock_cycle(n):
    return {r: [ev(r, 0, "recv", source=(r - 1) % n, tag=0),
                ev(r, 1, "send", dest=(r + 1) % n, tag=0)]
            for r in range(n)}


def unmatched_send(n):
    return {r: [ev(r, 0, "send", dest=(r + 1) % n, tag=5)]
            for r in range(n)}


def unmatched_recv(n):
    return {r: [ev(r, 0, "recv", source=(r - 1) % n, tag=5)]
            for r in range(n)}


def shape_mismatch(n):
    return {r: [ev(r, 0, "sendrecv",
                   dest=r + 1 if r % 2 == 0 else r - 1,
                   source=r + 1 if r % 2 == 0 else r - 1,
                   sendtag=0, recvtag=0,
                   shape=(4,) if r % 2 == 0 else (8,))]
            for r in range(n)}


def block_ring(n, a=4):
    """Island-local rings (islands of ``a``): the block peer pattern
    the hierarchical tiers produce."""
    s = {}
    for r in range(n):
        base = (r // a) * a
        s[r] = [ev(r, 0, "sendrecv", dest=base + (r - base + 1) % a,
                   source=base + (r - base - 1) % a, sendtag=0,
                   recvtag=0)]
    return s


def uneven_blocks(n):
    """Uneven partition: one island of 3 then islands of 2 — pair
    exchange inside each island, the odd island doing a 3-ring.  The
    refinement has to keep the tail-island roles apart."""
    s = {}
    isl = [list(range(0, 3))] + [list(range(b, min(b + 2, n)))
                                 for b in range(3, n, 2)]
    for members in isl:
        k = len(members)
        for j, r in enumerate(members):
            s[r] = [ev(r, 0, "sendrecv",
                       dest=members[(j + 1) % k],
                       source=members[(j - 1) % k],
                       sendtag=2, recvtag=2)]
    return s


def mixed(n):
    return {r: [ev(r, 0, "sendrecv", dest=(r + 1) % n,
                   source=(r - 1) % n, sendtag=0, recvtag=0),
                ev(r, 1, "allreduce", reduce_op="SUM"),
                ev(r, 2, "sendrecv", dest=(r - 1) % n,
                   source=(r + 1) % n, sendtag=9, recvtag=9)]
            for r in range(n)}


FAMILIES = {
    "ring": ring,
    "halo_walls": halo_walls,
    "colls": colls,
    "coll_mismatch": coll_mismatch,
    "pairs": pairs,
    "tag_mismatch": tag_mismatch,
    "deadlock_cycle": deadlock_cycle,
    "unmatched_send": unmatched_send,
    "unmatched_recv": unmatched_recv,
    "shape_mismatch": shape_mismatch,
    "block_ring": block_ring,
    "uneven_blocks": uneven_blocks,
    "mixed": mixed,
}

# even-np-only families (pair structure) and island-size constraints
_NPS = {"pairs": (2, 4, 6, 8, 12), "tag_mismatch": (2, 4, 6, 8, 12),
        "shape_mismatch": (2, 4, 6, 8, 12), "block_ring": (4, 8, 12),
        "uneven_blocks": (5, 7, 9, 11)}
_DEFAULT_NPS = (2, 3, 4, 5, 8, 12)


def _cases():
    for name, fam in sorted(FAMILIES.items()):
        for n in _NPS.get(name, _DEFAULT_NPS):
            yield name, fam, n


# ---------------------------------------------------------------------------
# the differential gate: symbolic verdicts byte-identical to concrete


@pytest.mark.parametrize("name,fam,n",
                         [pytest.param(*c, id=f"{c[0]}-np{c[2]}")
                          for c in _cases()])
def test_differential_findings(name, fam, n):
    sch = fam(n)
    conc = _dedupe(MT.match_schedules(sch, world(n)))
    part = SY.partition_schedules(sch, world(n))
    sym = _dedupe(SY.match_schedules_symbolic(sch, world(n), part))
    assert [f.to_json() for f in sym] == [f.to_json() for f in conc]


@pytest.mark.parametrize("name,fam,n",
                         [pytest.param(*c, id=f"{c[0]}-np{c[2]}")
                          for c in _cases()])
def test_differential_plans(name, fam, n):
    """compile_schedules with the symmetry partition must produce the
    same plan, the same proved verdict, and the same reasons as the
    concrete prover."""
    sch = fam(n)
    part = SY.partition_schedules(sch, world(n))
    pc = PL.compile_schedules(sch, world(n), world_size=n)
    ps = PL.compile_schedules(sch, world(n), world_size=n,
                              symmetry=part)
    assert ps.proved == pc.proved
    assert ps.reasons == pc.reasons
    assert not PL.diff_plans(pc, ps)
    assert ps.cache_key == pc.cache_key


def test_symbolic_prover_engages():
    """On a provable schedule the symmetry-aware compile records the
    class count in the proof blob — evidence the quotient prover (not
    the concrete one) produced the verdict."""
    n = 12
    sch = ring(n)
    part = SY.partition_schedules(sch, world(n))
    ps = PL.compile_schedules(sch, world(n), world_size=n,
                              symmetry=part)
    assert ps.proved
    assert ps.proof["symmetry_classes"] == part.n_classes == 1
    # budget independent of np: identity + planned + (classes-1)
    # rotations, NOT np rotations
    assert ps.proof["interleavings"] < n


def test_symbolic_prover_beats_concrete_budget():
    """The tentpole's reason to exist: at np past MAX_INTERLEAVINGS the
    concrete prover must reject the plan unproven (budget), while the
    class-rotation quotient proves it."""
    n = PL.MAX_INTERLEAVINGS + 44  # 300 with the default budget of 256
    sch = ring(n)
    pc = PL.compile_schedules(sch, world(n), world_size=n)
    assert not pc.proved
    assert any("interleaving budget exceeded" in r for r in pc.reasons)
    part = SY.partition_schedules(sch, world(n))
    ps = PL.compile_schedules(sch, world(n), world_size=n,
                              symmetry=part)
    assert ps.proved
    assert ps.proof["symmetry_classes"] == 1


# ---------------------------------------------------------------------------
# dispatcher + knob


def test_verify_schedules_small_np_stays_concrete(monkeypatch):
    monkeypatch.delenv("MPI4JAX_TPU_ANALYZE_SYMBOLIC", raising=False)
    n = SY.SYMBOLIC_MIN_NP - 1
    stats = {}
    findings, part = SY.verify_schedules(ring(n), world(n), stats=stats)
    assert stats["mode"] == "concrete"
    assert part is None
    assert findings == []


def test_verify_schedules_large_np_goes_symbolic(monkeypatch):
    monkeypatch.delenv("MPI4JAX_TPU_ANALYZE_SYMBOLIC", raising=False)
    n = SY.SYMBOLIC_MIN_NP
    stats = {}
    findings, part = SY.verify_schedules(ring(n), world(n), stats=stats)
    assert stats["mode"] == "symbolic"
    assert part is not None and part.n_classes == 1
    assert findings == []


def test_knob_off_pins_concrete(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_ANALYZE_SYMBOLIC", "off")
    n = 12
    sch = tag_mismatch(n)
    stats = {}
    findings, part = SY.verify_schedules(sch, world(n), stats=stats)
    assert stats["mode"] == "concrete"
    assert part is None
    ref = _dedupe(MT.match_schedules(sch, world(n)))
    assert ([f.to_json() for f in _dedupe(findings)]
            == [f.to_json() for f in ref])


def test_knob_strict_parser(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_ANALYZE_SYMBOLIC", "fast")
    with pytest.raises(ValueError, match="ANALYZE_SYMBOLIC"):
        SY.symbolic_mode()
    monkeypatch.setenv("MPI4JAX_TPU_ANALYZE_SYMBOLIC", " auto ")
    assert SY.symbolic_mode() == "auto"
    monkeypatch.delenv("MPI4JAX_TPU_ANALYZE_SYMBOLIC")
    assert SY.symbolic_mode() == "auto"


def test_wildcard_falls_back_to_concrete(monkeypatch):
    """ANY_SOURCE receives are outside the symbolic model: the
    dispatcher must fall back and reproduce concrete findings."""
    monkeypatch.delenv("MPI4JAX_TPU_ANALYZE_SYMBOLIC", raising=False)
    n = 12
    sch = {r: ([ev(r, 0, "send", dest=(r + 1) % n, tag=0)]
               if r % 2 else
               [ev(r, 0, "send", dest=(r + 1) % n, tag=0),
                ev(r, 1, "recv", source=EV.ANY_SOURCE, tag=0)])
           for r in range(n)}
    with pytest.raises(SY.Uncanonicalizable):
        SY.partition_schedules(sch, world(n))
    stats = {}
    findings, part = SY.verify_schedules(sch, world(n), stats=stats)
    assert stats["mode"] == "concrete"
    assert part is None
    ref = MT.match_schedules(sch, world(n))
    assert ([f.to_json() for f in _dedupe(findings)]
            == [f.to_json() for f in _dedupe(ref)])


# ---------------------------------------------------------------------------
# canonicalization edge cases


def test_noncontiguous_ranks_uncanonicalizable():
    sch = ring(4)
    del sch[2]
    with pytest.raises(SY.Uncanonicalizable, match="non-contiguous"):
        SY.partition_schedules(sch, None)


def test_subcomm_uncanonicalizable():
    n = 12
    comms = {(0,): tuple(range(n)), (1, 0): (0, 1, 2)}
    with pytest.raises(SY.Uncanonicalizable, match="sub-comm"):
        SY.partition_schedules(ring(n), comms)


def test_peer_outside_world_uncanonicalizable():
    n = 4
    sch = ring(n)
    sch[1] = [ev(1, 0, "sendrecv", dest=99, source=0, sendtag=0,
                 recvtag=0)]
    with pytest.raises(SY.Uncanonicalizable, match="outside the world"):
        SY.partition_schedules(sch, world(n))


def test_partition_halo_separates_boundary_roles():
    """Non-periodic halo: refinement must keep every rank in its own
    class (distance-to-wall differs), not collapse the interior."""
    n = 8
    part = SY.partition_schedules(halo_walls(n), world(n))
    assert part.n_classes == n


def test_partition_uneven_islands():
    """Uneven partition (one 3-island + 2-islands): the 3-ring ranks
    must separate from the pair ranks, and pair ranks must all share
    one class despite living in different (non-contiguous) islands."""
    n = 9
    part = SY.partition_schedules(uneven_blocks(n), world(n))
    # ranks 0..2 (3-ring) are one class: same descriptor, peers in the
    # same class.  Pair ranks split by sendrecv alias order (lower vs
    # upper member), giving 1 + 2 classes.
    c3 = {part.class_of[r] for r in range(3)}
    cp = {part.class_of[r] for r in range(3, n)}
    assert c3.isdisjoint(cp)
    assert len(c3) == 1
    assert part.to_json()["world_size"] == n
    assert sum(c["size"] for c in part.to_json()["classes"]) == n


def test_partition_ring_single_class():
    for n in (2, 3, 8, 64):
        part = SY.partition_schedules(ring(n), world(n))
        assert part.n_classes == 1
        assert part.classes[0] == tuple(range(n))
        assert part.reps == [0]


def test_collapse_findings_symmetry():
    n = 12
    sch = tag_mismatch(n)
    part = SY.partition_schedules(sch, world(n))
    findings = _dedupe(MT.match_schedules(sch, world(n)))
    collapsed = EV.collapse_findings(findings, part.class_of)
    assert len(collapsed) < len(findings)
    assert sum(c["count"] for c in collapsed) == len(findings)
    for c in collapsed:
        assert c["kind"] in EV.FINDING_KINDS
        assert c["affected_ranks"] >= 1
        assert c["representative"]["kind"] == c["kind"]


# ---------------------------------------------------------------------------
# np-rescaling peer forms (the scale harness's cross-size layer)


def test_fit_peer_form_ring():
    obs = [(r, n, (r + 1) % n) for n in (6, 8) for r in range(n)]
    form = SY.fit_peer_form(obs)
    assert form == ("shift", 1)
    assert SY.instantiate_peer(form, 511, 512) == 0


def test_fit_peer_form_const_vs_shift_needs_two_sizes():
    """At one world size rank-0's peer 1 is ambiguous (const 1 vs
    shift +1); a second size disambiguates."""
    one = [(0, 4, 1)]
    assert SY.fit_peer_form(one) == ("const", 1)
    both = [(0, 4, 1), (1, 4, 2), (0, 6, 1), (1, 6, 2), (5, 6, 0)]
    assert SY.fit_peer_form(both) == ("shift", 1)


def test_fit_peer_form_hiconst():
    obs = [(r, n, n - 1) for n in (4, 8) for r in range(n)]
    form = SY.fit_peer_form(obs)
    assert form == ("hiconst", 0)
    assert SY.instantiate_peer(form, 3, 512) == 511


def test_fit_peer_form_walls():
    # non-periodic +1 shift: wall at the top rank
    obs = []
    for n in (4, 6):
        for r in range(n):
            obs.append((r, n, r + 1 if r + 1 < n else -1))
    form = SY.fit_peer_form(obs)
    assert form == ("shiftwall", 1)
    assert SY.instantiate_peer(form, 511, 512) == -1
    assert SY.instantiate_peer(form, 510, 512) == 511
    # all-wall column
    assert SY.fit_peer_form([(r, 4, None) for r in range(4)]) \
        == ("wall",)


def test_fit_peer_form_block():
    obs = [(r, n, (r // 4) * 4) for n in (8, 12) for r in range(n)]
    form = SY.fit_peer_form(obs, block=4)
    assert form == ("block", 4, 0)
    assert SY.instantiate_peer(form, 510, 512) == 508


def test_fit_peer_form_non_affine_is_none():
    # bit-reversal-ish pattern: not affine in rank
    obs = [(0, 4, 0), (1, 4, 2), (2, 4, 1), (3, 4, 3)]
    assert SY.fit_peer_form(obs) is None


def test_instantiate_unknown_form_raises():
    with pytest.raises(ValueError):
        SY.instantiate_peer(("spiral", 3), 0, 8)
