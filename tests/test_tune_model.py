"""Unit tests for the joint tuner's brain (mpi4jax_tpu/tune/_model.py,
_joint.py) and its cache/CLI surfaces: cost-model fit/predict round
trips on synthetic event streams with KNOWN crossovers, the
model-seeded joint search, the v2 combination cache, knob-environment
stamping, the conflicting-knob shadow notice, the --from-trace
world-generation gate, and the schedule compiler's model consultation
plus elastic plan re-derivation.

Pure stdlib + the repo's own jax-free modules, loaded standalone like
test_tune/test_schedule_plan — these run on any host."""

import importlib.util
import json
import os
import pathlib
import sys
import types

import pytest

REPO = pathlib.Path(__file__).resolve().parents[1]


def _load_pkg(name, init_path, search_dir):
    if name in sys.modules:
        return sys.modules[name]
    spec = importlib.util.spec_from_file_location(
        name, str(init_path), submodule_search_locations=[str(search_dir)])
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _load_tune():
    try:
        from mpi4jax_tpu import tune

        return tune
    except ImportError:
        return _load_pkg("m4j_jtune", REPO / "mpi4jax_tpu/tune/__init__.py",
                         REPO / "mpi4jax_tpu/tune")


def _load_obs():
    try:
        from mpi4jax_tpu import obs

        return obs
    except ImportError:
        return _load_pkg("m4j_jtune_obs",
                         REPO / "mpi4jax_tpu/obs/__init__.py",
                         REPO / "mpi4jax_tpu/obs")


def _load_analysis():
    base = REPO / "mpi4jax_tpu/analysis"
    if "m4j_jt_an._plan" in sys.modules:
        return (sys.modules["m4j_jt_an._events"],
                sys.modules["m4j_jt_an._plan"])
    pkg = types.ModuleType("m4j_jt_an")
    pkg.__path__ = [str(base)]
    sys.modules["m4j_jt_an"] = pkg
    for name in ("_events", "_match", "_deps", "_plan"):
        spec = importlib.util.spec_from_file_location(
            f"m4j_jt_an.{name}", str(base / f"{name}.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[f"m4j_jt_an.{name}"] = mod
        spec.loader.exec_module(mod)
    return sys.modules["m4j_jt_an._events"], sys.modules["m4j_jt_an._plan"]


tune = _load_tune()
_model = tune._submodule("_model")
_joint = tune._submodule("_joint")


@pytest.fixture(autouse=True)
def _clean_engine_state(monkeypatch):
    for knob in ("MPI4JAX_TPU_COLL_ALGO", "MPI4JAX_TPU_TUNE_CACHE",
                 "MPI4JAX_TPU_TUNE_MODEL", "MPI4JAX_TPU_COLL_QUANT",
                 "MPI4JAX_TPU_HIER", "MPI4JAX_TPU_PLAN",
                 "MPI4JAX_TPU_PLAN_BUCKET_KB"):
        monkeypatch.delenv(knob, raising=False)
    tune._cache_table = None
    tune._cache_origin = None
    tune._cache_combos = None
    tune._noticed.clear()
    for op in tune.OPS:
        tune._overrides[op].clear()
    yield
    tune._cache_table = None
    tune._cache_origin = None
    tune._cache_combos = None
    for op in tune.OPS:
        tune._overrides[op].clear()


# ---------------- cost model: fit / predict ---------------------------


def _ab_model(specs, sizes=(1 << 10, 64 << 10, 4 << 20)):
    """Model populated from exact alpha-beta curves (no noise)."""
    m = _model.CostModel(world_size=4)
    for combo, (alpha, gbps) in specs.items():
        for b in sizes:
            m.add_sample("allreduce", combo, b, alpha + b / (gbps * 1e9))
    return m


def test_fit_recovers_alpha_beta():
    alpha, beta = _model._fit_alpha_beta(
        {b: 25e-6 + b / 2e9 for b in (1024, 65536, 1 << 20, 16 << 20)})
    assert alpha == pytest.approx(25e-6, rel=0.05)
    assert beta == pytest.approx(1 / 2e9, rel=0.05)


def test_fit_degenerate_inputs():
    assert _model._fit_alpha_beta({}) == (0.0, 0.0)
    a, b = _model._fit_alpha_beta({1 << 20: 1e-3})
    assert a == 0.0 and b == pytest.approx(1e-3 / (1 << 20))
    # clamped: fit never predicts negative time out of range
    a, b = _model._fit_alpha_beta({1024: 5e-3, 2048: 1e-6})
    assert a >= 0.0 and b >= 0.0


def test_predict_exact_interpolated_extrapolated():
    m = _ab_model({"ring": (50e-6, 1.0)})
    # exact sample returns the measurement itself
    assert m.predict("allreduce", 1 << 10, "ring") == \
        pytest.approx(50e-6 + (1 << 10) / 1e9)
    # between samples: log-log interpolation stays between the brackets
    mid = m.predict("allreduce", 256 << 10, "ring")
    assert m.samples[("allreduce", "ring")][64 << 10] < mid \
        < m.samples[("allreduce", "ring")][4 << 20]
    # above the measured range: the fitted line extends
    beyond = m.predict("allreduce", 32 << 20, "ring")
    assert beyond > m.samples[("allreduce", "ring")][4 << 20]
    # unknown combo: None, never a guess
    assert m.predict("allreduce", 1024, "warp") is None


def test_small_extrapolation_never_undercuts_measurements():
    m = _model.CostModel()
    # two large samples whose fitted alpha is ~0: a 1 KB query must not
    # come back near-free — it is clamped between the pure-bandwidth
    # scaling of the smallest measurement (t(b) >= (b/B)*t(B), true for
    # any alpha-beta curve) and the measurement itself
    m.add_sample("allreduce", "ring", 4 << 20, 4e-3)
    m.add_sample("allreduce", "ring", 16 << 20, 16e-3)
    pred = m.predict("allreduce", 1024, "ring")
    assert pred <= 4e-3
    assert pred >= 4e-3 * 1024 / (4 << 20)


def test_model_recovers_known_crossover():
    """The acceptance shape: a latency-cheap algo and a bandwidth-cheap
    algo with a known crossover — the fitted model must rank them
    correctly on BOTH sides, including at unmeasured sizes."""
    # tree: 10us + b/0.5GB/s; qring: 100us + b/4GB/s -> crossover ~51KB
    m = _ab_model({"tree": (10e-6, 0.5), "qring": (100e-6, 4.0)})
    for nbytes, want in ((1 << 10, "tree"), (16 << 10, "tree"),
                         (256 << 10, "qring"), (16 << 20, "qring")):
        ranked = m.rank_combos("allreduce", nbytes, ["tree", "qring"])
        assert ranked[0][0] == want, (nbytes, ranked)


def test_fit_model_from_events_round_trip(tmp_path):
    """Synthetic canonical event stream -> fitted model -> save/load ->
    identical predictions, with the wire/dispatch fractions carried."""
    events = []
    for b, algo, dur in ((1024, "tree", 15.0), (1024, "ring", 60.0),
                         (1 << 20, "tree", 2100.0), (1 << 20, "ring", 1100.0)):
        for rep in range(4):
            events.append({"name": "Allreduce", "src": "native",
                           "ts_us": 0.0, "dur_us": dur + rep,
                           "wait_us": dur * 0.1, "dispatch_us": dur * 0.05,
                           "bytes": b, "peer": -1, "tag": 0, "algo": algo})
    model = tune.fit_model_from_events(events, world_size=4)
    assert model.predict("allreduce", 1024, "tree") < \
        model.predict("allreduce", 1024, "ring")
    assert model.predict("allreduce", 1 << 20, "ring") < \
        model.predict("allreduce", 1 << 20, "tree")
    key = ("allreduce", "tree")
    assert model.wire_frac[key][1024] == pytest.approx(0.85, abs=0.03)
    assert model.dispatch_frac[key][1024] == pytest.approx(0.05, abs=0.01)
    p = tmp_path / "model.json"
    _model.save_model(model, path=str(p))
    loaded = _model.load_model(str(p))
    for b in (1024, 32768, 1 << 20):
        assert loaded.predict("allreduce", b, "tree") == \
            pytest.approx(model.predict("allreduce", b, "tree"))
    assert loaded.world_size == 4
    assert "MPI4JAX_TPU_COLL_QUANT" in loaded.knobs  # stamped


def test_model_version_gate(tmp_path):
    p = tmp_path / "m.json"
    p.write_text(json.dumps({"version": 99, "samples": {}}))
    with pytest.raises(ValueError, match="version"):
        _model.load_model(str(p))
    p.write_text(json.dumps({"hello": 1}))
    with pytest.raises(ValueError, match="cost-model"):
        _model.load_model(str(p))


def test_best_bucket_bytes_prices_the_remainder():
    # huge alpha: one big bucket always beats many small ones
    m = _ab_model({"ring": (500e-6, 1.0)},
                  sizes=tuple(_model.BUCKET_LADDER))
    assert m.best_bucket_bytes(8 << 20) == max(_model.BUCKET_LADDER)
    # tiny alpha: bucket size barely matters; the tie prefers LARGER
    # buckets, so the pick must still not be the smallest rung
    m2 = _ab_model({"ring": (1e-9, 1.0)},
                   sizes=tuple(_model.BUCKET_LADDER))
    assert m2.best_bucket_bytes(8 << 20) > min(_model.BUCKET_LADDER)
    # no data for the op: None (the compiler keeps its static default)
    assert _model.CostModel().best_bucket_bytes(8 << 20) is None


def test_suggested_group_cap_tracks_alpha_share():
    m = _ab_model({"ring": (100e-6, 1.0)})
    # 1 KB: alpha dominates -> deepest groups pay
    assert m.suggested_group_cap(1024, op="allreduce", combo="ring") == \
        _model.MAX_GROUP_CAP
    # 16 MB: wire-bound -> static default
    assert m.suggested_group_cap(16 << 20, op="allreduce",
                                 combo="ring") == 4
    # no data: the caller's default, untouched
    assert _model.CostModel().suggested_group_cap(1024, default=4) == 4


# ---------------- joint search ----------------------------------------


TRUE = {"ring": (50e-6, 1.0), "rd": (20e-6, 0.7), "tree": (10e-6, 0.5),
        "qring": (60e-6, 3.2), "qrd": (30e-6, 2.0)}
SIZES = [1 << 10, 4 << 10, 16 << 10, 64 << 10, 256 << 10, 1 << 20,
         4 << 20, 16 << 20]


def _true_cost(op, nbytes, combo):
    alpha, gbps = TRUE[_joint.combo_algo(combo)]
    return alpha + nbytes / (gbps * 1e9)


def test_joint_search_finds_true_winners_measuring_less():
    cands = {"allreduce": ["ring", "rd", "tree", "qring", "qrd"]}
    best, meas, model = _joint.joint_search(_true_cost, cands, SIZES,
                                            ranks=4)
    for nbytes, combo in best["allreduce"].items():
        truly = min(TRUE, key=lambda a: _true_cost("allreduce", nbytes, a))
        assert combo == truly, (nbytes, combo, truly)
    # the model-seeded refine phase measured strictly less than the
    # full grid (that is the point of having a model)
    assert len(meas) < len(SIZES) * len(cands["allreduce"])
    phases = {m["phase"] for m in meas}
    assert phases == {"anchor", "refine"}


def test_joint_search_gated_combo_measured_none():
    """A combo whose gates are not active in this process returns None
    from measure() — it must be skipped, not crowned or crashed on."""
    def measure(op, nbytes, combo):
        if combo.endswith("+q"):
            return None
        return _true_cost(op, nbytes, combo)

    cands = {"allreduce": ["ring", "qring", "hring+q"]}
    best, meas, _ = _joint.joint_search(measure, cands, SIZES[:3], ranks=4)
    assert all(c in ("ring", "qring") for c in best["allreduce"].values())
    assert not any(m["combo"] == "hring+q" for m in meas)


def test_merge_winners_pools_sub_jobs():
    base = [{"op": "allreduce", "bytes": 1 << 20, "combo": "qring",
             "seconds": 1e-3, "ranks": 8},
            {"op": "allreduce", "bytes": 1 << 10, "combo": "tree",
             "seconds": 2e-5, "ranks": 8}]
    gated = [{"op": "allreduce", "bytes": 1 << 20, "combo": "hring+q",
              "seconds": 5e-4, "ranks": 8}]
    best, rows = _joint.merge_winners([base, gated])
    assert best["allreduce"][1 << 20] == "hring+q"
    assert best["allreduce"][1 << 10] == "tree"
    assert len(rows) == 3


def test_eligible_combos_gating():
    full = _joint.eligible_combos("allreduce", multi_island=True,
                                  quant_mode="allow", hier_mode="allow")
    assert "hring+q" in full and "qring" in full and "hring" in full
    flat = _joint.eligible_combos("allreduce", multi_island=False,
                                  quant_mode="allow", hier_mode="allow")
    assert not any(_joint.combo_algo(c) in ("hring", "htree")
                   for c in flat)
    deny = _joint.eligible_combos("allreduce", multi_island=True,
                                  quant_mode="deny", hier_mode="allow")
    assert not any("q" in c for c in deny)
    hdeny = _joint.eligible_combos("allreduce", multi_island=True,
                                   quant_mode="allow", hier_mode="deny")
    assert not any(_joint.combo_algo(c) in ("hring", "htree")
                   for c in hdeny)
    # allgather has no quantized schedule at all
    ag = _joint.eligible_combos("allgather", multi_island=True,
                                quant_mode="force", hier_mode="allow")
    assert not any("q" in c for c in ag)


def test_combo_vocabulary():
    assert _joint.combo_algo("hring+q") == "hring"
    assert _joint.combo_algo("qring") == "qring"
    assert _joint.combo_gates("hring+q") == \
        {"MPI4JAX_TPU_COLL_QUANT": "force"}
    assert _joint.combo_gates("ring") == {}
    with pytest.raises(ValueError, match="unknown joint combination"):
        _joint.check_combo("warp", "allreduce")
    with pytest.raises(ValueError, match="unknown joint combination"):
        _joint.check_combo("qring", "allgather")


# ---------------- v2 combination cache --------------------------------


def test_cache_from_joint_round_trip(tmp_path):
    p = tmp_path / "tune_4.json"
    best = {"allreduce": {1 << 10: "tree", 64 << 10: "qrd",
                          1 << 20: "hring+q"}}
    meas = [{"op": "allreduce", "bytes": 1 << 10, "combo": "tree",
             "seconds": 1e-5, "ranks": 4, "phase": "anchor"}]
    written = tune.cache_from_joint(4, best, meas, path=str(p))
    assert written == str(p)
    data = json.loads(p.read_text())
    assert data["version"] == tune.CACHE_VERSION
    assert data["combos"]["allreduce"] == [[0, "tree"], [65536, "qrd"],
                                           [1048576, "hring+q"]]
    # the derived table keeps the v1 meaning: per-call-forcible algos
    assert data["table"]["allreduce"] == [[0, "tree"], [65536, "qrd"],
                                          [1048576, "hring"]]
    assert data["transport"] == "tcp:joint"
    assert "MPI4JAX_TPU_COLL_QUANT" in data["knobs"]
    # loading installs both layers
    tune.load_cache(4, path=str(p))
    assert tune.cache_combos()["allreduce"][-1] == (1048576, "hring+q")
    assert tune.get_algorithm("allreduce", 2 << 20) == "hring"
    assert "combos" in tune.describe()


def test_v1_cache_still_loads(tmp_path):
    p = tmp_path / "old.json"
    p.write_text(json.dumps({
        "version": 1, "world_size": 4,
        "table": {"allreduce": [[0, "rd"]]}, "measurements": []}))
    assert tune.load_cache(4, path=str(p)) == {"allreduce": [(0, "rd")]}
    assert tune.cache_combos() is None


def test_malformed_combos_rejected(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text(json.dumps({
        "version": 2, "world_size": 4,
        "table": {"allreduce": [[0, "ring"]]},
        "combos": {"allreduce": [[0, "warp+q"]]}}))
    with pytest.raises(ValueError, match="unknown joint combination"):
        tune.load_cache(4, path=str(p))


def test_sweep_cache_payload_stamps_knobs(monkeypatch, tmp_path):
    monkeypatch.setenv("MPI4JAX_TPU_COLL_QUANT", "force")
    p = tmp_path / "tune_2.json"
    tune.save_cache(2, {"allreduce": [(0, "ring")]}, path=str(p))
    data = json.loads(p.read_text())
    assert data["knobs"]["MPI4JAX_TPU_COLL_QUANT"] == "force"


# ---------------- conflicting-knob shadow notice ----------------------


def _install_cache(tmp_path, table, combos=None):
    p = tmp_path / "tune_4.json"
    tune.save_cache(4, table, path=str(p), combos=combos)
    tune.load_cache(4, path=str(p))
    return p


def test_env_algo_shadow_notice(tmp_path, monkeypatch, capsys):
    _install_cache(tmp_path, {"allreduce": [(0, "tree"), (65536, "qring")]})
    monkeypatch.setenv("MPI4JAX_TPU_COLL_ALGO", "ring")
    tune._notice_shadowed()
    err = capsys.readouterr().err
    assert "[tune] NOTICE" in err
    assert "MPI4JAX_TPU_COLL_ALGO=ring" in err  # the overriding pick
    assert "qring" in err and "'ring'" in err   # both picks named
    # once per distinct conflict: a reinstall must not spam
    tune._notice_shadowed()
    assert "[tune] NOTICE" not in capsys.readouterr().err


def test_quant_deny_degrade_notice(tmp_path, monkeypatch, capsys):
    _install_cache(tmp_path, {"allreduce": [(0, "qring")]})
    monkeypatch.setenv("MPI4JAX_TPU_COLL_QUANT", "deny")
    tune._notice_shadowed()
    err = capsys.readouterr().err
    assert "COLL_QUANT=deny" in err and "'qring'" in err \
        and "'ring'" in err


def test_qleg_combo_needs_force_notice(tmp_path, monkeypatch, capsys):
    _install_cache(tmp_path, {"allreduce": [(0, "hring")]},
                   combos={"allreduce": [(0, "hring+q")]})
    tune._notice_shadowed()
    err = capsys.readouterr().err
    assert "hring+q" in err and "COLL_QUANT=force" in err
    # with the gate actually forced there is nothing to report
    tune._noticed.clear()
    monkeypatch.setenv("MPI4JAX_TPU_COLL_QUANT", "force")
    tune._notice_shadowed()
    assert "hring+q" not in capsys.readouterr().err


def test_hier_deny_degrade_notice(tmp_path, monkeypatch, capsys):
    _install_cache(tmp_path, {"allreduce": [(0, "hring")]})
    monkeypatch.setenv("MPI4JAX_TPU_HIER", "deny")
    tune._notice_shadowed()
    err = capsys.readouterr().err
    assert "HIER=deny" in err and "'hring'" in err and "'ring'" in err


def test_no_notice_without_conflict(tmp_path, capsys):
    _install_cache(tmp_path, {"allreduce": [(0, "tree"), (65536, "ring")]})
    tune._notice_shadowed()
    assert "[tune] NOTICE" not in capsys.readouterr().err


# ---------------- --from-trace world-generation gate ------------------


obs = _load_obs()


def _ev(name, nbytes, dur_us, algo):
    return {"name": name, "src": "native", "ts_us": 0.0,
            "dur_us": dur_us, "wait_us": 0.0, "bytes": nbytes,
            "peer": -1, "tag": 0, "algo": algo}


def test_from_trace_skips_superseded_generations(tmp_path, capsys):
    """An elastic shrink mid-recording: the generation-0 part (a rank
    that dumped before dying) must NOT pool its timings with the
    survivors' generation-1 parts."""
    base = str(tmp_path / "rec.json")
    # gen 0: ring looks great (would flip the winner if pooled)
    obs.write_part(base, rank=2, size=3, generation=0,
                   events=[_ev("Allreduce", 1 << 20, 10.0, "ring")] * 4)
    # gen 1 survivors: rd wins
    for r in (0, 1):
        obs.write_part(base, rank=r, size=2, generation=1, events=[
            _ev("Allreduce", 1 << 20, 900.0, "ring"),
            _ev("Allreduce", 1 << 20, 400.0, "rd")] * 3)
    out = str(tmp_path / "cache.json")
    tune.cache_from_trace(obs.part_paths(base), world_size=2,
                          cache_path_override=out, quantize=False)
    err = capsys.readouterr().err
    assert "superseded world generation" in err
    assert "rec.json.rank2.json (generation 0)" in err
    data = json.loads(open(out).read())
    # the stale 10us ring rows are gone: rd is the winner
    assert data["table"]["allreduce"] == [[0, "rd"]]
    assert not any(m["seconds"] < 1e-4 for m in data["measurements"])


def test_from_trace_rejects_mixed_generation_trace(tmp_path):
    """A merged Chrome trace spanning a recovery cannot attribute its
    spans to one world — it is rejected loudly, not averaged."""
    trace = tmp_path / "merged.json"
    trace.write_text(json.dumps({
        "traceEvents": [], "otherData":
            {"world_size": 3, "generations": {"0": 0, "1": 1}}}))
    with pytest.raises(ValueError, match="generations \\[0, 1\\]"):
        tune.cache_from_trace([str(trace)], world_size=3)


def test_collect_trace_events_shared_gate(tmp_path, capsys):
    """The --joint seed path loads through the SAME gated collector as
    plain --from-trace: stale-generation events never reach the model
    fit (a seed pooling pre- and post-shrink medians would steer the
    top-k refinement from wrong-world timings)."""
    base = str(tmp_path / "rec.json")
    obs.write_part(base, rank=2, size=3, generation=0,
                   events=[_ev("Allreduce", 1 << 20, 10.0, "ring")] * 4)
    obs.write_part(base, rank=0, size=2, generation=1,
                   events=[_ev("Allreduce", 1 << 20, 900.0, "ring")] * 4)
    events, seen = tune.collect_trace_events(obs.part_paths(base))
    assert "superseded world generation" in capsys.readouterr().err
    assert seen == 2
    assert all(e["dur_us"] == 900.0 for e in events)
    model = tune.fit_model_from_events(events, world_size=2)
    assert model.predict("allreduce", 1 << 20, "ring") == \
        pytest.approx(900e-6)


def test_bench_record_survives_malformed_gate(monkeypatch):
    """A typo'd gate aborts loudly where it matters (the native
    parser); the stamp must record the problem, not crash a mesh-tier
    benchmark that never touches the gate."""
    monkeypatch.setenv("MPI4JAX_TPU_COLL_QUANT", "tru")
    rec = obs.bench_record(op="allreduce", nbytes=1024, seconds=1e-4)
    assert "unparseable" in rec["knobs"]
    assert "tru" in rec["knobs"]["unparseable"]


def test_from_trace_single_generation_unaffected(tmp_path):
    base = str(tmp_path / "rec.json")
    obs.write_part(base, rank=0, size=2, generation=0, events=[
        _ev("Allreduce", 1 << 20, 500.0, "ring"),
        _ev("Allreduce", 1 << 20, 900.0, "rd")] * 3)
    out = str(tmp_path / "cache.json")
    tune.cache_from_trace(obs.part_paths(base), world_size=2,
                          cache_path_override=out, quantize=False)
    data = json.loads(open(out).read())
    assert data["table"]["allreduce"] == [[0, "ring"]]


# ---------------- bench_record knob stamping --------------------------


def test_bench_record_stamps_knob_env(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_COLL_QUANT", "force")
    monkeypatch.setenv("MPI4JAX_TPU_HIER", "deny")
    rec = obs.bench_record(op="allreduce", nbytes=1024, seconds=1e-4)
    assert rec["knobs"]["MPI4JAX_TPU_COLL_QUANT"] == "force"
    assert rec["knobs"]["MPI4JAX_TPU_HIER"] == "deny"
    assert rec["knobs"]["MPI4JAX_TPU_PLAN"] == "0"
    assert rec["knobs"]["MPI4JAX_TPU_URING"] == "auto"
    # an explicit knobs= override wins (the --knob-grid driver stamps
    # the combination it forced on the sub-job)
    rec2 = obs.bench_record(op="allreduce", nbytes=1024, seconds=1e-4,
                            knobs={"X": "1"})
    assert rec2["knobs"] == {"X": "1"}


# ---------------- schedule compiler: model consultation ---------------


EVN, PLN = _load_analysis()


def _grad_events(n, k=6, shape=(65536,)):
    ev = {r: [EVN.CommEvent(r, i, "allreduce", reduce_op="SUM",
                            dtype="float32", shape=shape)
              for i in range(k)] for r in range(n)}
    return ev, {(0,): tuple(range(n))}


def test_plan_consults_model_for_buckets(monkeypatch, tmp_path):
    ev, comms = _grad_events(2)
    base = PLN.compile_schedules(ev, comms)
    assert base.bucket_bytes == PLN.DEFAULT_BUCKET_BYTES
    assert base.model == ""
    m = _model.CostModel(world_size=2)
    for b in _model.BUCKET_LADDER:
        m.add_sample("allreduce", "ring", b, 500e-6 + b / 1e9)
    modeled = PLN.compile_schedules(ev, comms, cost_model=m)
    assert modeled.bucket_bytes != base.bucket_bytes
    assert "bucket_bytes" in modeled.model
    assert any("cost model consulted" in r for r in modeled.reasons)
    # explicit env knob beats the model (operator intent wins)
    monkeypatch.setenv("MPI4JAX_TPU_PLAN_BUCKET_KB", "256")
    pinned = PLN.compile_schedules(ev, comms, cost_model=m)
    assert pinned.bucket_bytes == 256 * 1024
    assert "bucket_bytes" not in pinned.model


def test_plan_model_via_env_knob_only(monkeypatch, tmp_path):
    """Without MPI4JAX_TPU_TUNE_MODEL the compiler never probes the
    disk — golden plans stay byte-stable whatever ~/.cache holds."""
    ev, comms = _grad_events(2)
    m = _model.CostModel(world_size=2)
    for b in _model.BUCKET_LADDER:
        m.add_sample("allreduce", "ring", b, 500e-6 + b / 1e9)
    mp = tmp_path / "model.json"
    _model.save_model(m, path=str(mp))
    assert PLN.compile_schedules(ev, comms).model == ""
    monkeypatch.setenv("MPI4JAX_TPU_TUNE_MODEL", str(mp))
    assert "bucket_bytes" in PLN.compile_schedules(ev, comms).model
    # an unreadable model degrades soft, never fails the compile
    monkeypatch.setenv("MPI4JAX_TPU_TUNE_MODEL", str(tmp_path / "no.json"))
    with pytest.warns(UserWarning, match="unusable cost model"):
        assert PLN.compile_schedules(ev, comms).proved


# ---------------- elastic-safe plans: re-derivation -------------------


def _ring_events(n, rounds=3, shape=(128 * 1024,)):
    events = {}
    for rank in range(n):
        evs = []
        for k in range(rounds):
            evs.append(EVN.CommEvent(rank, 2 * k, "send",
                                     dest=(rank + 1) % n, tag=k,
                                     dtype="float32", shape=shape))
            evs.append(EVN.CommEvent(rank, 2 * k + 1, "recv",
                                     source=(rank - 1 + n) % n, tag=k,
                                     dtype="float32", shape=shape))
        events[rank] = evs
    return events, {(0,): tuple(range(n))}


def test_events_from_plan_round_trips_cache_key():
    ev, comms = _ring_events(3)
    plan = PLN.compile_schedules(ev, comms)
    assert plan.proved and plan.rewritten
    ev2, comms2 = PLN.events_from_plan(plan)
    assert EVN.schedule_cache_key(ev2, 3) == plan.cache_key
    assert comms2 == comms


def test_recompile_plan_reproves_and_signature_checks():
    ev, comms = _ring_events(2)
    plan = PLN.compile_schedules(ev, comms)
    fresh = PLN.recompile_plan(plan)
    assert fresh.proved
    assert fresh.cache_key == plan.cache_key
    assert fresh.world_size == plan.world_size
    # a tampered stored plan (wrong schedule under the claimed key)
    # fails the signature check the reinstall path enforces
    plan.ranks[0].ops[0].tag = 99
    assert PLN.recompile_plan(plan).cache_key != plan.cache_key


def test_bundle_round_trip_and_size_lookup(tmp_path):
    plans = {}
    for n in (3, 2):
        ev, comms = _ring_events(n)
        plans[n] = PLN.compile_schedules(ev, comms)
    bp = tmp_path / "bundle.json"
    PLN.save_bundle(plans, str(bp))
    loaded = PLN.load_bundle(str(bp))
    assert sorted(loaded) == [2, 3]
    assert PLN.load_plan_for_size(str(bp), 2).world_size == 2
    assert PLN.load_plan_for_size(str(bp), 7) is None
    # single-plan files answer only their own size
    sp = tmp_path / "single.json"
    PLN.save_plan(plans[3], str(sp))
    assert PLN.load_plan_for_size(str(sp), 3).world_size == 3
    assert PLN.load_plan_for_size(str(sp), 2) is None
    # version drift invalidates instead of misexecuting
    data = json.loads(bp.read_text())
    data["version"] = 99
    bp.write_text(json.dumps(data))
    with pytest.raises(ValueError, match="version"):
        PLN.load_bundle(str(bp))
