"""Corpus differential gate for rank-symbolic analysis.

For every program in the verify-corpus manifest, extract the real
schedules through :func:`check_program` (virtual world, no processes)
at every world size in 2..8 the program runs at, then pin the symbolic
verdict byte-identical to the concrete one: finding JSON, cache keys,
and compiled plans (proved verdict, reasons, plan diff).  Programs the
symbolic model does not cover (sub-communicators, wildcards) must
raise :class:`Uncanonicalizable` — the sound-fallback half of the
contract.

Skipped where ``import mpi4jax_tpu`` is unavailable (old-jax
containers); the jax-free half of the gate — synthetic families plus
the golden-plan replay in test_verify_scale.py — runs everywhere.
"""

import json
import os
import sys

import pytest

try:
    import mpi4jax_tpu  # noqa: F401  (jax version gate)
except Exception as err:  # pragma: no cover - old-jax containers
    pytest.skip(f"mpi4jax_tpu not importable here: {err}",
                allow_module_level=True)

from mpi4jax_tpu import analysis
from mpi4jax_tpu.analysis import _match, _plan, _symbolic

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROGS = os.path.join(REPO, "tests", "world_programs")
MANIFEST = os.path.join(PROGS, "golden_plans", "manifest.json")

with open(MANIFEST) as fh:
    _MANIFEST = json.load(fh)


def _entries():
    for entry in _MANIFEST["programs"]:
        yield pytest.param(entry, id=f"{entry['program']}-np{entry['np']}")


def _normalize_env(monkeypatch):
    # mirror tools/verify_corpus.py: plan-shaping knobs cleared so the
    # comparison runs under the documented defaults
    for knob in ("MPI4JAX_TPU_PROGRESS_THREAD",
                 "MPI4JAX_TPU_COALESCE_BYTES",
                 "MPI4JAX_TPU_PLAN_BUCKET_KB", "MPI4JAX_TPU_PLAN",
                 "MPI4JAX_TPU_FAULT", "MPI4JAX_TPU_ANALYZE_SYMBOLIC"):
        monkeypatch.delenv(knob, raising=False)


@pytest.mark.parametrize("entry", list(_entries()))
def test_corpus_symbolic_matches_concrete(entry, monkeypatch):
    """The differential gate, on real extracted schedules: every np in
    2..8 where the program itself runs clean under the virtual world."""
    _normalize_env(monkeypatch)
    path = os.path.join(PROGS, entry["program"])
    base_np = int(entry["np"])
    tried = 0
    for np_ in range(base_np, 9):
        if np_ != base_np and np_ % base_np:
            continue  # corpus programs assume their np's divisors hold
        try:
            report = analysis.check_program(path, np_)
        except Exception:
            continue  # program does not support this world size
        if np_ != base_np and any(f.kind == "analysis_timeout"
                                  for f in report.findings):
            continue
        tried += 1
        sch, comms = report.events, report.comms
        conc = analysis._dedupe(_match.match_schedules(sch, comms))
        try:
            part = _symbolic.partition_schedules(sch, comms)
        except _symbolic.Uncanonicalizable:
            # sound fallback: the dispatcher must agree it is concrete
            stats = {}
            findings, part = _symbolic.verify_schedules(sch, comms,
                                                        stats=stats)
            assert part is None or stats["mode"] == "concrete"
            assert ([f.to_json() for f in analysis._dedupe(findings)]
                    == [f.to_json() for f in conc])
            continue
        try:
            sym = analysis._dedupe(_symbolic.match_schedules_symbolic(
                sch, comms, part))
        except _symbolic.FallbackNeeded:
            continue  # honest fallback; concrete path owns the verdict
        assert ([f.to_json() for f in sym]
                == [f.to_json() for f in conc]), \
            f"symbolic/concrete drift at np={np_}"
        ws = len(sch)
        pc = _plan.compile_schedules(sch, comms, world_size=ws,
                                     findings=conc)
        ps = _plan.compile_schedules(sch, comms, world_size=ws,
                                     findings=conc, symmetry=part)
        assert ps.proved == pc.proved, f"proved drift at np={np_}"
        assert ps.reasons == pc.reasons
        assert ps.cache_key == pc.cache_key
        assert not _plan.diff_plans(pc, ps), f"plan drift at np={np_}"
    assert tried >= 1, "program never ran — gate lost its teeth"


def test_corpus_symbolic_off_is_bitforbit(monkeypatch):
    """MPI4JAX_TPU_ANALYZE_SYMBOLIC=off pins the concrete report JSON
    bit-for-bit on a representative golden program."""
    _normalize_env(monkeypatch)
    entry = next(e for e in _MANIFEST["programs"]
                 if e.get("golden"))
    path = os.path.join(PROGS, entry["program"])
    ref = analysis.check_program(path, int(entry["np"])).to_json()
    monkeypatch.setenv("MPI4JAX_TPU_ANALYZE_SYMBOLIC", "off")
    off = analysis.check_program(path, int(entry["np"])).to_json()
    assert json.dumps(off, sort_keys=True) \
        == json.dumps(ref, sort_keys=True)
