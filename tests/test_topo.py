"""Topology subsystem unit tests: fake-host parsing, Topology shape /
fingerprint / leg split, the numpy schedule simulators, the
topology-keyed tune cache, the config knobs, and the obs tier split —
all process-local (no sockets, no launcher; the live-world coverage is
tests/world/test_topology.py)."""

import json
import os
import pathlib
import sys
import types

import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parents[1]


def _load_pkg_modules():
    """topo / tune / obs._stats / utils.config without the package
    __init__ (its jax gate blocks old-jax containers; every module
    here is jax-free by design).  The fallback loads them under an
    ALIAS root instead of registering a bare ``mpi4jax_tpu`` in
    sys.modules — a leaked synthetic package would make other test
    modules' import-gate probes succeed spuriously in-process."""
    try:
        from mpi4jax_tpu import topo, tune
        from mpi4jax_tpu.obs import _stats
        from mpi4jax_tpu.utils import config

        return topo, tune, _stats, config
    except ImportError:
        import importlib

        alias = "m4j_topo_tests_pkg"
        if alias not in sys.modules:
            pkg = types.ModuleType(alias)
            pkg.__path__ = [str(REPO / "mpi4jax_tpu")]
            sys.modules[alias] = pkg
        topo = importlib.import_module(alias + ".topo")
        tune = importlib.import_module(alias + ".tune")
        _stats = importlib.import_module(alias + ".obs._stats")
        config = importlib.import_module(alias + ".utils.config")
        return topo, tune, _stats, config


topo, tune, _stats, config = _load_pkg_modules()


# ---------------- parse_fake_hosts ----------------

def test_parse_fake_hosts_groups_and_bare_tokens():
    labels = topo.parse_fake_hosts("r0,r1|r2,r3", 4)
    assert labels == ["fake-host-0", "fake-host-0",
                      "fake-host-1", "fake-host-1"]
    assert topo.parse_fake_hosts("0 , 1 | 2", 3) == [
        "fake-host-0", "fake-host-0", "fake-host-1"]


def test_parse_fake_hosts_unlisted_and_out_of_range():
    # unlisted ranks keep their real host (None); a spec written for a
    # larger world stays valid on a shrunk one (out-of-range ignored)
    assert topo.parse_fake_hosts("r0|r2", 4) == [
        "fake-host-0", None, "fake-host-1", None]
    assert topo.parse_fake_hosts("r0,r1|r2", 2) == [
        "fake-host-0", "fake-host-0"]


def test_parse_fake_hosts_rejects_garbage_and_duplicates():
    assert topo.parse_fake_hosts("", 4) is None
    assert topo.parse_fake_hosts(None, 4) is None
    with pytest.raises(ValueError):
        topo.parse_fake_hosts("r0,banana", 4)
    with pytest.raises(ValueError):
        topo.parse_fake_hosts("r0|r0", 4)


# ---------------- Topology ----------------

def _fp(host, fake=None, tpu=0):
    return {"v": 1, "host": host, "boot_id": "b", "fake": fake,
            "tpu_chips": tpu}


def test_topology_islands_leaders_and_ordering():
    t = topo.Topology([_fp("a"), _fp("a"), _fp("b"), _fp("b"), _fp("a")])
    assert t.islands == [[0, 1, 4], [2, 3]]
    assert t.island_of == [0, 0, 1, 1, 0]
    assert t.leaders == [0, 2]
    assert t.multi and t.n_islands == 2
    assert t.leader(4) == 0 and t.leader(3) == 2
    # dense island ids ordered by leader rank (the native invariant)
    assert t.leaders == sorted(t.leaders)


def test_topology_fake_overrides_real_host():
    t = topo.Topology([_fp("same", "fake-host-0"), _fp("same", "fake-host-0"),
                       _fp("same", "fake-host-1")])
    assert t.islands == [[0, 1], [2]]


def test_topology_link_classes_and_tiers():
    t = topo.Topology([_fp("a", tpu=4), _fp("a", tpu=4),
                       _fp("b"), _fp("b")])
    assert t.tiers == ["ici", "ici", "shm", "shm"]
    assert t.link(0, 0) == "self"
    assert t.link(0, 1) == "ici"
    assert t.link(2, 3) == "shm"
    assert t.link(1, 2) == "tcp"


def test_topology_fingerprint_keys_on_shape_not_names():
    a = topo.Topology([_fp("hostA"), _fp("hostA"), _fp("hostB"), _fp("hostB")])
    b = topo.Topology([_fp("other1"), _fp("other1"),
                       _fp("other2"), _fp("other2")])
    c = topo.Topology([_fp("x"), _fp("x"), _fp("x"), _fp("y")])  # 3+1
    assert a.fingerprint() == b.fingerprint()
    assert a.fingerprint() != c.fingerprint()
    assert len(a.fingerprint()) == 12
    flat = topo.Topology([_fp("x")] * 4)
    assert not flat.multi
    assert flat.fingerprint() != a.fingerprint()


def test_topology_leg_bytes_and_render():
    t = topo.Topology([_fp("a")] * 4 + [_fp("b")] * 4)
    legs = t.leg_bytes("hring", 1000)
    # intra: 2 * nbytes * (k-1) per island; inter: 2 * (L-1) * nbytes
    assert legs == {"intra": 2 * 1000 * 6, "inter": 2 * 1000}
    # htree's leader leg is recursive doubling: every butterfly
    # participant ships the FULL payload per round (+ the fold pair)
    assert t.leg_bytes("htree", 1000)["inter"] == 2 * 1000  # L=2: 2*1
    t4 = topo.Topology([_fp(h) for h in "aabbccdd" for _ in (0,)][:8])
    assert t4.n_islands == 4
    assert t4.leg_bytes("htree", 1000)["inter"] == 4 * 2 * 1000  # 4*log2(4)
    t3 = topo.Topology([_fp("a"), _fp("b"), _fp("c")])
    # L=3: pof2=2 (2*1 rounds... 2*log2(2)=2) + fold pair 2 -> 4
    assert t3.leg_bytes("htree", 1000)["inter"] == 4 * 1000
    flatlegs = t.leg_bytes("ring", 1000)
    assert flatlegs["intra"] == 0 and flatlegs["inter"] == 2 * 7 * 1000
    out = t.render()
    assert "island0[r0 r1 r2 r3" in out and "inter=tcp" in out
    d = t.describe()
    assert d["islands"] == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert d["fingerprint"] == t.fingerprint()


# ---------------- schedule simulators ----------------

def test_flat_simulators_match_numpy_on_exact_ints():
    rng = np.random.RandomState(0)
    for n in (1, 2, 3, 4, 5, 8):
        vals = [rng.randint(-50, 50, 97).astype(np.float32)
                for _ in range(n)]
        want = np.sum(np.stack(vals), axis=0)
        assert np.array_equal(topo.simulate_ring_sum(vals), want), n
        assert np.array_equal(topo.simulate_rd_sum(vals), want), n


def test_hier_simulators_are_close_and_deterministic():
    rng = np.random.RandomState(1)
    vals = [rng.randn(513).astype(np.float32) for _ in range(6)]
    islands = [[0, 1, 2, 3], [4, 5]]
    for fn in (topo.simulate_hring_sum, topo.simulate_htree_sum):
        got = fn(vals, islands)
        want = np.sum(np.stack(vals).astype(np.float64), axis=0)
        assert np.allclose(got, want, rtol=1e-4, atol=1e-4)
        # deterministic: same inputs, same bits
        assert np.array_equal(got, fn(vals, islands))


def test_hier_simulator_single_island_is_member_fold():
    vals = [np.float32([1e8]), np.float32([1.0]), np.float32([-1e8])]
    # sequential member-order fold: (1e8 + 1) - 1e8 == 0 in f32
    got = topo.simulate_hring_sum(vals, [[0, 1, 2]])
    assert got[0] == np.float32(np.float32(1e8 + 1.0) - 1e8)


def test_hier_simulator_intra_ring_is_the_ring_association():
    # intra="ring" (the ICI-leg data plane) folds each island with the
    # ring reduce-scatter association, NOT the sequential member fold
    rng = np.random.RandomState(7)
    vals = [rng.randn(513).astype(np.float32) for _ in range(5)]
    islands = [[0, 1, 2], [3, 4]]
    got = topo.simulate_hring_sum(vals, islands, intra="ring")
    # phase 1 of the schedule == per-island simulate_ring_sum
    isl0 = topo.simulate_ring_sum([vals[r] for r in islands[0]])
    isl1 = topo.simulate_ring_sum([vals[r] for r in islands[1]])
    want = topo.simulate_ring_sum([isl0, isl1])
    assert np.array_equal(got, want)
    assert np.allclose(got, np.sum(np.stack(vals), axis=0),
                       rtol=1e-4, atol=1e-4)
    with pytest.raises(ValueError):
        topo.simulate_hring_sum(vals, islands, intra="banana")


def test_simulate_ici_q_sum_bound_and_determinism():
    rng = np.random.RandomState(11)
    vals = [rng.randn(700).astype(np.float32) * 3 for _ in range(6)]
    islands = [[0, 1, 2, 3], [4, 5]]
    got = topo.simulate_ici_q_sum(vals, islands)
    exact = np.sum(np.stack(vals).astype(np.float64), axis=0)
    denom = max(float(np.max(np.abs(exact))), 1e-6)
    err = float(np.max(np.abs(got.astype(np.float64) - exact))) / denom
    assert err < 5e-2, err  # the documented int8 wire bound
    assert got.dtype == np.float32
    assert np.array_equal(got, topo.simulate_ici_q_sum(vals, islands))


# ---------------- topology-keyed tune cache ----------------

def test_cache_path_topology_suffix(monkeypatch):
    monkeypatch.delenv("MPI4JAX_TPU_TUNE_CACHE", raising=False)
    monkeypatch.setenv("XDG_CACHE_HOME", "/tmp/xdgtest")
    assert tune.cache_path(8).endswith("tune_8.json")
    assert tune.cache_path(8, "abc123").endswith("tune_8_abc123.json")


def test_save_load_cache_topology_stamp(tmp_path, monkeypatch):
    monkeypatch.delenv("MPI4JAX_TPU_TUNE_CACHE", raising=False)
    p = tmp_path / "tune_4_deadbeef.json"
    table = {"allreduce": [(0, "tree"), (65536, "hring")]}
    tune.save_cache(4, table, path=str(p), topo_fingerprint="deadbeef")
    data = json.loads(p.read_text())
    assert data["topology"] == "deadbeef"
    try:
        loaded = tune.load_cache(4, path=str(p), topo_fingerprint="deadbeef")
        assert loaded["allreduce"][1] == (65536, "hring")
        with pytest.raises(ValueError):
            tune.load_cache(4, path=str(p), topo_fingerprint="00000000")
    finally:
        tune._cache_table = None
        tune._cache_origin = None
        tune._cache_loaded_for = None


def test_install_topology_flips_defaults_and_legacy_fallback(
        tmp_path, monkeypatch):
    monkeypatch.delenv("MPI4JAX_TPU_TUNE_CACHE", raising=False)
    monkeypatch.delenv("MPI4JAX_TPU_COLL_ALGO", raising=False)
    monkeypatch.setenv("XDG_CACHE_HOME", str(tmp_path))
    t_multi = topo.Topology([_fp("a"), _fp("a"), _fp("b"), _fp("b")])
    t_flat = topo.Topology([_fp("a")] * 4)
    try:
        tune.install(4, topology=t_multi)
        assert tune.get_algorithm("allreduce", 16 << 20) == "hring"
        assert tune.get_algorithm("allreduce", 1024) == "tree"
        assert "defaults:topology" in tune.sources()
        # a flat rediscovery (elastic shrink emptied an island)
        # restores the flat defaults
        tune.install(4, topology=t_flat)
        assert tune.get_algorithm("allreduce", 16 << 20) == "ring"
        # legacy fallback: only an un-keyed tune_4.json on disk — a
        # multi-island install still loads it
        legacy = {"version": 1, "world_size": 4, "table":
                  {"allreduce": [[0, "rd"]]}, "measurements": []}
        os.makedirs(tmp_path / "mpi4jax_tpu", exist_ok=True)
        (tmp_path / "mpi4jax_tpu" / "tune_4.json").write_text(
            json.dumps(legacy))
        tune.install(4, topology=t_multi)
        assert tune.get_algorithm("allreduce", 16 << 20) == "rd"
        assert "tune_4.json" in (tune._cache_origin or "")
        # ...but a topology-KEYED cache wins over the legacy one
        keyed = dict(legacy)
        keyed["table"] = {"allreduce": [[0, "htree"]]}
        keyed["topology"] = t_multi.fingerprint()
        (tmp_path / "mpi4jax_tpu" /
         f"tune_4_{t_multi.fingerprint()}.json").write_text(
            json.dumps(keyed))
        tune._cache_table = None
        tune._cache_loaded_for = None
        tune.install(4, topology=t_multi)
        assert tune.get_algorithm("allreduce", 16 << 20) == "htree"
    finally:
        tune._cache_table = None
        tune._cache_origin = None
        tune._cache_loaded_for = None
        tune._topo_multi = False


def test_check_algo_accepts_hier_names():
    assert tune._check_algo("hring") == "hring"
    assert tune._check_algo("htree", "allgather") == "htree"
    assert tune.ALGO_CODES["hring"] == 7 and tune.ALGO_CODES["htree"] == 8
    assert tune.HIER_ALGOS == {"hring", "htree"}
    with pytest.raises(ValueError):
        tune._check_algo("hband")


def test_hier_leg_events_carry_no_tuning_signal():
    # a hierarchical collective's per-leg event is labeled with the LEG
    # algorithm (e.g. ring on the leader tier) but times only that leg:
    # the tuner must ignore it, and use the whole-op record instead
    leg = {"name": "Allreduce", "src": "native", "algo": "ring",
           "bytes": 1 << 20, "dur_us": 10.0, "tier": "inter"}
    whole = {"name": "Allreduce", "src": "native", "algo": "hring",
             "bytes": 1 << 20, "dur_us": 50.0}
    m = tune.measurements_from_events([leg, whole])
    assert "ring" not in m.get("allreduce", {}).get(1 << 20, {})
    assert m["allreduce"][1 << 20]["hring"] == pytest.approx(50e-6)


# ---------------- config knobs ----------------

def test_topo_and_hier_knob_parsers(monkeypatch):
    monkeypatch.delenv("MPI4JAX_TPU_TOPO", raising=False)
    monkeypatch.delenv("MPI4JAX_TPU_HIER", raising=False)
    assert config.topo_mode() == "auto"
    assert config.hier_mode() == "allow"
    monkeypatch.setenv("MPI4JAX_TPU_TOPO", "off")
    assert config.topo_mode() == "off"
    monkeypatch.setenv("MPI4JAX_TPU_HIER", "force")
    assert config.hier_mode() == "force"
    monkeypatch.setenv("MPI4JAX_TPU_TOPO", "maybe")
    with pytest.raises(ValueError):
        config.topo_mode()
    monkeypatch.setenv("MPI4JAX_TPU_HIER", "sometimes")
    with pytest.raises(ValueError):
        config.hier_mode()
    monkeypatch.setenv("MPI4JAX_TPU_FAKE_HOSTS", "r0|r1")
    assert config.fake_hosts_spec() == "r0|r1"


def test_ici_leg_knob_parser(monkeypatch):
    monkeypatch.delenv("MPI4JAX_TPU_ICI_LEG", raising=False)
    assert config.ici_leg_mode() == "auto"
    for v in ("auto", "off", "force"):
        monkeypatch.setenv("MPI4JAX_TPU_ICI_LEG", v)
        assert config.ici_leg_mode() == v
    monkeypatch.setenv("MPI4JAX_TPU_ICI_LEG", "on")  # typo: abort loudly
    with pytest.raises(ValueError, match="MPI4JAX_TPU_ICI_LEG"):
        config.ici_leg_mode()
    monkeypatch.delenv("MPI4JAX_TPU_ICI_LEG", raising=False)
    assert config.knob_env()["MPI4JAX_TPU_ICI_LEG"] == "auto"


# ---------------- the ICI data-plane leg (process-local) ----------------


def _ici_leg_mod():
    import importlib

    return importlib.import_module(topo.__name__ + "._ici_leg")


def test_ici_leg_eligibility_gating(monkeypatch):
    leg = _ici_leg_mod()
    monkeypatch.delenv("MPI4JAX_TPU_HIER", raising=False)
    monkeypatch.delenv("MPI4JAX_TPU_PLAN", raising=False)
    t_ici = topo.Topology([_fp("a", tpu=4), _fp("a", tpu=4),
                           _fp("b", tpu=4), _fp("b", tpu=4)])
    t_shm = topo.Topology([_fp("a"), _fp("a"), _fp("b"), _fp("b")])
    t_flat = topo.Topology([_fp("a", tpu=4)] * 4)
    # auto: every multi-member island must be fully ici-tier
    assert leg.eligible(t_ici, mode="auto")
    assert not leg.eligible(t_shm, mode="auto")
    # force skips ONLY the tier check (the off-TPU tier-1 axis)
    assert leg.eligible(t_shm, mode="force")
    # off / no topology / flat world: never
    assert not leg.eligible(t_ici, mode="off")
    assert not leg.eligible(None, mode="force")
    assert not leg.eligible(t_flat, mode="force")
    # hier deny must keep degrading to the flat twins
    monkeypatch.setenv("MPI4JAX_TPU_HIER", "deny")
    assert not leg.eligible(t_ici, mode="force")
    monkeypatch.delenv("MPI4JAX_TPU_HIER", raising=False)
    # plan execution owns the schedule: the leg steps aside
    monkeypatch.setenv("MPI4JAX_TPU_PLAN", "/tmp/plan.json")
    assert not leg.eligible(t_ici, mode="force")


def test_ici_leg_status_and_backend(monkeypatch):
    leg = _ici_leg_mod()
    monkeypatch.setenv("MPI4JAX_TPU_ICI_LEG", "force")
    st = topo.ici_leg_status()
    assert st["mode"] == "force"
    assert st["backend"] in ("pallas", "numpy")
    assert st["backend"] == leg.ici_leg_backend()
    assert st["active"] is False  # no handle given
    monkeypatch.delenv("MPI4JAX_TPU_ICI_LEG", raising=False)
    assert topo.ici_leg_status()["mode"] == "auto"


def test_joint_ici_combos_need_the_leg():
    _joint = tune._submodule("_joint")
    base = dict(multi_island=True, quant_mode="allow", hier_mode="allow")
    # without the leg (the 3-kwarg legacy call shape): +ici excluded
    legless = _joint.eligible_combos("allreduce", **base)
    assert not any("ici" in c for c in legless)
    with_leg = _joint.eligible_combos("allreduce", ici_leg=True, **base)
    for c in ("hring+ici", "htree+ici", "hring+q+ici", "htree+q+ici"):
        assert c in with_leg
    # quant deny drops the +q+ici composites but keeps the exact +ici
    qdeny = _joint.eligible_combos("allreduce", ici_leg=True,
                                   multi_island=True, quant_mode="deny",
                                   hier_mode="allow")
    assert "hring+ici" in qdeny and "hring+q+ici" not in qdeny
    assert _joint.combo_algo("hring+q+ici") == "hring"
    assert _joint.combo_gates("htree+q+ici") == {
        "MPI4JAX_TPU_COLL_QUANT": "force",
        "MPI4JAX_TPU_ICI_LEG": "force"}
    assert _joint.combo_gates("hring+ici") == {
        "MPI4JAX_TPU_ICI_LEG": "force"}


# ---------------- obs: tier split ----------------

def test_stats_split_intra_vs_inter_bytes():
    events = [
        # whole-op record: NO tier (never double-counted)
        {"name": "Allreduce", "src": "native", "ts_us": 0.0,
         "dur_us": 100.0, "wait_us": 0.0, "dispatch_us": 0.0,
         "bytes": 1000, "peer": -1, "tag": 0, "algo": "hring"},
        {"name": "Reduce", "src": "native", "ts_us": 1.0, "dur_us": 30.0,
         "wait_us": 0.0, "dispatch_us": 0.0, "bytes": 1000, "peer": 0,
         "tag": 0, "algo": "shm", "tier": "intra"},
        {"name": "Allreduce", "src": "native", "ts_us": 2.0,
         "dur_us": 50.0, "wait_us": 0.0, "dispatch_us": 0.0,
         "bytes": 1000, "peer": -1, "tag": 0, "algo": "ring",
         "tier": "inter"},
        {"name": "Bcast", "src": "native", "ts_us": 3.0, "dur_us": 20.0,
         "wait_us": 0.0, "dispatch_us": 0.0, "bytes": 1000, "peer": 0,
         "tag": 0, "algo": "shm", "tier": "intra"},
    ]
    stats = _stats.summarize(events)
    assert stats["tier_bytes"] == {"intra": 2000, "inter": 1000}
    tiers = {(r["op"], r.get("tier")) for r in stats["per_op"]}
    assert ("Allreduce", None) in tiers or ("Allreduce", "inter") in tiers
    # the whole-op hring row and the inter-leg ring row never merge
    hring_rows = [r for r in stats["per_op"] if r["algo"] == "hring"]
    assert hring_rows and "tier" not in hring_rows[0]
    inter_rows = [r for r in stats["per_op"] if r.get("tier") == "inter"]
    assert inter_rows and inter_rows[0]["algo"] == "ring"
    # rendering includes the tier column only when legs are present
    table = _stats.render_table(stats)
    assert "tier" in table.splitlines()[0]


def test_stats_without_tier_events_schema_unchanged():
    events = [{"name": "Send", "src": "native", "ts_us": 0.0,
               "dur_us": 5.0, "wait_us": 0.0, "dispatch_us": 0.0,
               "bytes": 64, "peer": 1, "tag": 7, "algo": None}]
    stats = _stats.summarize(events)
    assert "tier_bytes" not in stats
    assert all("tier" not in r for r in stats["per_op"])
    assert "tier" not in _stats.render_table(stats).splitlines()[0]


# ---------------- analysis invariance (needs jax) ----------------

def _jax_ok():
    # the shim above registers a bare package module, so "import
    # mpi4jax_tpu" succeeding is not enough — the analysis trace needs
    # the real op layer, which needs the gated jax version
    try:
        import jax

        parts = []
        for piece in jax.__version__.split(".")[:3]:
            parts.append(int("".join(c for c in piece if c.isdigit()) or 0))
        return tuple(parts) >= (0, 6, 0)
    except Exception:
        return False


@pytest.mark.skipif(not _jax_ok(), reason="needs jax >= 0.6")
def test_hier_algo_keeps_plain_allreduce_schedule_signature():
    """Hierarchical routing is INVISIBLE to the static verifier: a
    forced hring allreduce extracts the same per-rank schedule (and
    cache key) as the plain one, so every golden plan and verified
    corpus stays byte-identical."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    import mpi4jax_tpu as m4j
    from mpi4jax_tpu import analysis

    def plain(x, comm):
        return m4j.allreduce(x, op=m4j.SUM, comm=comm)

    def hier(x, comm):
        return m4j.allreduce(x, op=m4j.SUM, comm=comm, algo="hring")

    rp = analysis.check(plain, jnp.ones((4,), jnp.float32), world_size=4)
    rh = analysis.check(hier, jnp.ones((4,), jnp.float32), world_size=4)
    assert rp.ok and rh.ok
    assert rp.schedules == rh.schedules
    assert rp.cache_key == rh.cache_key


# ---------------- the alltoall family (MoE expert exchange) ----------------

def test_check_algo_accepts_alltoall_family():
    assert tune._check_algo("qalltoall", "alltoall") == "qalltoall"
    assert tune._check_algo("halltoall", "alltoall") == "halltoall"
    assert tune._check_algo("hqalltoall", "alltoall") == "hqalltoall"
    assert tune.ALGO_CODES["qalltoall"] == 9
    assert tune.ALGO_CODES["halltoall"] == 10
    assert tune.ALGO_CODES["hqalltoall"] == 11
    assert tune.A2A_ALGOS == {"qalltoall", "halltoall", "hqalltoall"}
    assert tune.A2A_QUANT == {"qalltoall", "hqalltoall"}
    assert tune.A2A_HIER == {"halltoall", "hqalltoall"}
    # the degrade chain: one gate axis at a time
    assert tune.HIER_FLAT_TWIN["halltoall"] == "ring"
    assert tune.HIER_FLAT_TWIN["hqalltoall"] == "qalltoall"
    # family names are alltoall-only; the allreduce twins stay theirs
    with pytest.raises(ValueError):
        tune._check_algo("qalltoall", "allreduce")
    with pytest.raises(ValueError):
        tune._check_algo("qring", "alltoall")


def test_alltoall_simulators_permutation_and_quant_bound():
    rng = np.random.RandomState(3)
    n = 5
    base = (rng.randn(n, n, 97) * 4).astype(np.float32)
    inputs = [base[r] for r in range(n)]
    want = [base[:, r] for r in range(n)]  # alltoall IS this transpose
    # halltoall is a pure permutation: bit-identical to the flat exchange
    got_h = topo.simulate_halltoall(inputs)
    assert all(np.array_equal(g, w) for g, w in zip(got_h, want))
    # qalltoall: own chunk exact, off-rank chunks int8-bounded
    got_q = topo.simulate_qalltoall(inputs)
    for r in range(n):
        assert np.array_equal(got_q[r][r], want[r][r])
        err = np.max(np.abs(got_q[r] - want[r]))
        assert 0 < err < np.max(np.abs(base)) / 127.0 + 1e-6
    # hqalltoall on a 3+2 split: intra chunks exact, cross bounded,
    # and deterministic
    islands = [[0, 1, 2], [3, 4]]
    got_hq = topo.simulate_hqalltoall(inputs, islands)
    again = topo.simulate_hqalltoall(inputs, islands)
    for r in range(n):
        assert np.array_equal(got_hq[r], again[r])
        my = islands[0] if r in islands[0] else islands[1]
        for s in range(n):
            if s in my:
                assert np.array_equal(got_hq[r][s], want[r][s]), (r, s)
            else:
                assert not np.array_equal(got_hq[r][s], want[r][s])
                assert np.max(np.abs(got_hq[r][s] - want[r][s])) < (
                    np.max(np.abs(base)) / 127.0 + 1e-6)
    # single island degenerates to the exact permutation
    one = topo.simulate_hqalltoall(inputs, [[0, 1, 2, 3, 4]])
    assert all(np.array_equal(g, w) for g, w in zip(one, want))


def test_leg_bytes_alltoall_family_geometry():
    t = topo.Topology([_fp("a")] * 4 + [_fp("b")] * 4)
    n, chunk = 8, 1000
    nbytes = n * chunk
    flat = t.leg_bytes("alltoall", nbytes)
    assert flat == {"intra": 0, "inter": n * (n - 1) * chunk}
    # halltoall: direct intra chunks + cross-chunk staging hops stay
    # intra; only the cross blocks cross the leader tier
    h = t.leg_bytes("halltoall", nbytes)
    assert h["intra"] == (2 * 4 * 3 * chunk        # direct, both islands
                          + 2 * (3 * 4 + 4 * 3) * chunk)  # staging
    assert h["inter"] == 2 * 4 * 4 * chunk
    # hqalltoall: same geometry, leader blocks through the codec
    hq = t.leg_bytes("hqalltoall", nbytes)
    assert hq["intra"] == h["intra"]
    assert hq["inter"] == 2 * topo._quant_wire_bytes(4 * 4 * chunk)
    assert hq["inter"] < h["inter"]
    # flat quantized: every off-rank chunk is a codec frame
    q = t.leg_bytes("qalltoall", nbytes)
    assert q == {"intra": 0,
                 "inter": n * (n - 1) * topo._quant_wire_bytes(chunk)}
    # codec arithmetic matches the native formula 4*ceil(count/256)+count
    assert topo._quant_wire_bytes(1024) == 256 + 4 * 1
    assert topo._quant_wire_bytes(1028) == 257 + 4 * 2
    # single island: everything is intra
    tf = topo.Topology([_fp("a")] * 4)
    assert tf.leg_bytes("qalltoall", 4000)["inter"] == 0
    assert tf.leg_bytes("alltoall", 4000)["inter"] == 0


def test_alltoall_leg_events_carry_no_tuning_signal():
    # hierarchical alltoall's per-leg events (intra shm leg, inter ring/
    # qalltoall leg) are labeled with the LEG algorithm and a tier: the
    # tuner must read only the tier-less whole-op record
    legs = [
        {"name": "Alltoall", "src": "native", "algo": "shm",
         "bytes": 4096, "dur_us": 5.0, "tier": "intra"},
        {"name": "Alltoall", "src": "native", "algo": "qalltoall",
         "bytes": 8192, "wire_bytes": 2176, "dur_us": 20.0,
         "tier": "inter"},
    ]
    whole = {"name": "Alltoall", "src": "native", "algo": "hqalltoall",
             "bytes": 1 << 15, "dur_us": 60.0}
    m = tune.measurements_from_events(legs + [whole])
    a2a = m.get("alltoall", {})
    assert all("shm" not in by_algo for by_algo in a2a.values())
    assert all("qalltoall" not in by_algo for by_algo in a2a.values())
    assert a2a[1 << 15]["hqalltoall"] == pytest.approx(60e-6)


def test_stats_alltoall_quant_rows_carry_wire_bytes():
    events = [
        # flat qalltoall whole-op record: packed wire, no tier
        {"name": "Alltoall", "src": "native", "ts_us": 0.0,
         "dur_us": 40.0, "wait_us": 0.0, "dispatch_us": 0.0,
         "bytes": 8192, "wire_bytes": 2176, "peer": -1, "tag": 0,
         "algo": "qalltoall"},
        # hqalltoall legs: tier split, quantized leader leg
        {"name": "Alltoall", "src": "native", "ts_us": 1.0,
         "dur_us": 10.0, "wait_us": 0.0, "dispatch_us": 0.0,
         "bytes": 4096, "peer": -1, "tag": 0, "algo": "shm",
         "tier": "intra"},
        {"name": "Alltoall", "src": "native", "ts_us": 2.0,
         "dur_us": 30.0, "wait_us": 0.0, "dispatch_us": 0.0,
         "bytes": 8192, "wire_bytes": 2176, "peer": -1, "tag": 0,
         "algo": "qalltoall", "tier": "inter"},
        {"name": "Alltoall", "src": "native", "ts_us": 3.0,
         "dur_us": 60.0, "wait_us": 0.0, "dispatch_us": 0.0,
         "bytes": 1 << 15, "peer": -1, "tag": 0, "algo": "hqalltoall"},
    ]
    stats = _stats.summarize(events)
    assert stats["tier_bytes"] == {"intra": 4096, "inter": 8192}
    rows = {(r["algo"], r.get("tier")): r for r in stats["per_op"]}
    flatq = rows[("qalltoall", None)]
    assert flatq["wire_bytes"] == 2176
    assert flatq["compression"] == pytest.approx(8192 / 2176, rel=1e-3)
    # the whole-op hqalltoall row is exact-payload (its compression
    # lives on the leader-leg row), and never merges with its legs
    assert "wire_bytes" not in rows[("hqalltoall", None)]
    assert rows[("qalltoall", "inter")]["wire_bytes"] == 2176
