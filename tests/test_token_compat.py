"""Explicit-token compat API: reference signatures `res, token = op(...)`."""

import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m4j
from mpi4jax_tpu.compat import token_api

N = 8


@pytest.fixture(scope="module")
def mesh():
    return m4j.make_mesh(N)


def test_token_chain_matches_reference_style(mesh):
    x = jnp.arange(N, dtype=jnp.float32)

    def step(v):
        token = token_api.create_token(v)
        a, token = token_api.allreduce(v, op=m4j.SUM, token=token)
        b, token = token_api.sendrecv(a, shift=1, token=token)
        token = token_api.barrier(token=token)
        c, token = token_api.allgather(b, token=token)
        return c.sum() + b

    out = m4j.spmd(step, mesh=mesh)(x)
    s = np.sum(np.arange(N))
    np.testing.assert_allclose(np.asarray(out), N * s + s)


def test_token_api_starts_chain_without_token(mesh):
    x = jnp.ones((N,), jnp.float32)

    def step(v):
        res, token = token_api.allreduce(v, op=m4j.SUM)
        assert token is not None
        return res

    out = m4j.spmd(step, mesh=mesh)(x)
    np.testing.assert_allclose(np.asarray(out), N)


def test_all_ops_present():
    for name in (
        "allgather allreduce alltoall barrier bcast gather recv reduce "
        "scan scatter send sendrecv create_token"
    ).split():
        assert hasattr(token_api, name), name
