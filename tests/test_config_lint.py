"""Config-registry lint: every MPI4JAX_TPU_* knob read anywhere in the
tree must be declared in ``utils/config.py``'s ``KNOBS`` registry (and
documented in its module docstring), and every registered knob must
actually be read somewhere — no silent env vars, no stale registry rows.

PR 1 and PR 2 each added knobs by hand; this enforces the discipline.
Stdlib-only on purpose (``config.py`` is loaded standalone), so the lint
runs even where jax itself cannot import.
"""

import importlib.util
import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PREFIX = "MPI4JAX_TPU_"

# lines that READ env: python os.environ/getenv forms + C/C++ getenv
_READ_RE = re.compile(
    r"(os\.environ|getenv|environ\.get|secure_getenv)"
)
_KNOB_RE = re.compile(r"MPI4JAX_TPU_[A-Z0-9_]+")

# knob-shaped strings that are not knobs (doc prefixes, format templates)
_NOT_KNOBS = {PREFIX.rstrip("_"), PREFIX}


def _load_config():
    spec = importlib.util.spec_from_file_location(
        "m4j_config_lint", os.path.join(REPO, "mpi4jax_tpu", "utils",
                                        "config.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _source_files(*roots, exts):
    for root in roots:
        base = os.path.join(REPO, root)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", "_native")]
            for name in filenames:
                if name.endswith(exts):
                    yield os.path.join(dirpath, name)


def _knobs_in(line):
    return {k for k in _KNOB_RE.findall(line) if k not in _NOT_KNOBS}


def test_every_env_read_is_registered():
    config = _load_config()
    registered = set(config.KNOBS)
    offenders = []
    for path in _source_files("mpi4jax_tpu", "native",
                              exts=(".py", ".cc", ".h")):
        with open(path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, 1):
                if not _READ_RE.search(line):
                    continue
                for knob in _knobs_in(line) - registered:
                    rel = os.path.relpath(path, REPO)
                    offenders.append(f"{rel}:{lineno}: {knob}")
    assert not offenders, (
        "env knobs read but not registered in utils/config.py KNOBS:\n  "
        + "\n  ".join(sorted(offenders))
    )


def test_every_registered_knob_is_used():
    config = _load_config()
    used = set()
    for path in _source_files("mpi4jax_tpu", "native", "tests",
                              "benchmarks", "examples",
                              exts=(".py", ".cc", ".h")):
        if path.endswith(os.path.join("utils", "config.py")):
            continue
        with open(path, encoding="utf-8") as f:
            for line in f:
                used |= _knobs_in(line)
    stale = set(config.KNOBS) - used
    assert not stale, (
        "knobs registered in utils/config.py KNOBS but never read "
        f"anywhere: {sorted(stale)}"
    )


def test_every_registered_knob_is_documented():
    config = _load_config()
    path = os.path.join(REPO, "mpi4jax_tpu", "utils", "config.py")
    with open(path, encoding="utf-8") as f:
        docstring = f.read().split('"""')[1]
    missing = [k for k in config.KNOBS if k not in docstring]
    assert not missing, (
        "knobs in KNOBS but not documented in the config.py module "
        f"docstring: {sorted(missing)}"
    )
