"""Ops must work under a user's raw shard_map with the default
check_vma=True — including on invarying (replicated/constant) operands."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import mpi4jax_tpu as m4j

N = 8


@pytest.fixture(scope="module")
def mesh():
    return m4j.make_mesh(N)


def test_ops_in_checked_shard_map(mesh):
    comm = m4j.MeshComm("mpi")

    def step(x):
        # varying operand
        a = m4j.allreduce(x, op=m4j.SUM, comm=comm)
        # invarying (constant) operand — requires internal pcast
        c = m4j.allreduce(jnp.float32(1.0), op=m4j.SUM, comm=comm)
        b = m4j.bcast(x, 2, comm=comm)
        r = m4j.reduce(x, m4j.MAX, 0, comm=comm)
        s = m4j.scan(x, m4j.SUM, comm=comm)
        g = m4j.sendrecv(x, shift=1, comm=comm)
        m4j.barrier(comm=comm)
        return a + c + b + r + s + g

    f = jax.jit(
        jax.shard_map(
            step, mesh=mesh, in_specs=P("mpi"), out_specs=P("mpi")
        )
    )
    out = f(jnp.arange(N, dtype=jnp.float32))
    assert np.all(np.isfinite(np.asarray(out)))


def test_allgather_alltoall_checked(mesh):
    comm = m4j.MeshComm("mpi")

    def step(x):
        g = m4j.allgather(x, comm=comm)  # (N, 1)
        t = m4j.alltoall(g, comm=comm)
        sc = m4j.scatter(g, 0, comm=comm)
        return (g.sum() + t.sum() + sc.sum()).reshape(1)

    f = jax.jit(
        jax.shard_map(
            step, mesh=mesh, in_specs=P("mpi"), out_specs=P("mpi")
        )
    )
    out = f(jnp.arange(N, dtype=jnp.float32))
    assert out.shape == (N,)
