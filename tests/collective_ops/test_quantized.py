"""Quantized int8 allreduce vs the exact collective."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m4j

N = 8


@pytest.fixture(scope="module")
def mesh():
    return m4j.make_mesh(N)


@pytest.mark.parametrize("shape", [(257,), (8, 33), (4, 4, 5)])
def test_quantized_matches_exact(mesh, shape):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(N, *shape).astype(np.float32))
    exact = m4j.spmd(
        lambda v: m4j.allreduce(v, op=m4j.SUM), mesh=mesh
    )(x)
    approx = m4j.spmd(
        lambda v: m4j.allreduce(v, op=m4j.SUM, compression="int8"),
        mesh=mesh,
    )(x)
    e = np.asarray(exact)
    a = np.asarray(approx)
    denom = np.maximum(np.abs(e), 1e-3)
    assert np.median(np.abs(a - e) / denom) < 2e-2
    assert np.max(np.abs(a - e)) < 0.2 * np.max(np.abs(e))


def test_quantized_bf16(mesh):
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(N, 64).astype(np.float32)).astype(jnp.bfloat16)
    out = m4j.spmd(
        lambda v: m4j.allreduce(v, op=m4j.SUM, compression="int8"),
        mesh=mesh,
    )(x)
    assert out.dtype == jnp.bfloat16


def test_quantized_rejects_non_sum(mesh):
    x = jnp.ones((N,), jnp.float32)
    with pytest.raises(NotImplementedError):
        m4j.spmd(
            lambda v: m4j.allreduce(v, op=m4j.MAX, compression="int8"),
            mesh=mesh,
        )(x)
