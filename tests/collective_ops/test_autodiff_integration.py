"""Autodiff integration parity (reference test_allreduce.py:228-325 and
test_sendrecv.py:175-211): custom_vjp composed around the collectives, and
jacfwd/jacrev through sendrecv."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m4j

N = 8


@pytest.fixture(scope="module")
def mesh():
    return m4j.make_mesh(N)


def test_custom_vjp_around_allreduce(mesh):
    # distributed expectation <x> with a custom gradient estimator wrapping
    # the framework allreduce (the reference's NetKet-derived pattern)
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(N * 4).astype(np.float32))

    def run(theta):
        @jax.custom_vjp
        def expect(th):
            def per_rank(ws):
                local = jnp.sum(ws * th)
                return m4j.allreduce(local, op=m4j.SUM)[None] / w.size

            return m4j.spmd(per_rank, mesh=mesh)(w).reshape(N)[0]

        def fwd(th):
            return expect(th), None

        def bwd(_, ct):
            # analytic: d<w*th>/dth = mean(w), computed distributed
            def per_rank(ws):
                return m4j.allreduce(jnp.sum(ws), op=m4j.SUM)[None] / w.size

            mw = m4j.spmd(per_rank, mesh=mesh)(w).reshape(N)[0]
            return (ct * mw,)

        expect.defvjp(fwd, bwd)
        return expect(theta)

    val, grad = jax.value_and_grad(run)(jnp.float32(2.0))
    np.testing.assert_allclose(
        float(val), 2.0 * np.mean(np.asarray(w)), rtol=1e-5
    )
    np.testing.assert_allclose(
        float(grad), np.mean(np.asarray(w)), rtol=1e-5
    )


def test_jacfwd_and_jacrev_sendrecv(mesh):
    # reference: jacfwd raises / jacrev works for sendrecv; here both work
    f = m4j.spmd(
        lambda v: m4j.sendrecv(2.0 * v, shift=1), mesh=mesh
    )
    x = jnp.arange(N, dtype=jnp.float32)
    jf = jax.jacfwd(f)(x)
    jr = jax.jacrev(f)(x)
    expected = np.zeros((N, N), np.float32)
    for i in range(N):
        expected[(i + 1) % N, i] = 2.0
    np.testing.assert_allclose(np.asarray(jf), expected)
    np.testing.assert_allclose(np.asarray(jr), expected)


def test_grad_through_scan_of_collectives(mesh):
    # collectives inside lax.scan must differentiate (control-flow effects)
    def roll_loss(x):
        def per_rank(v):
            def body(c, _):
                c = m4j.sendrecv(c, shift=1) + v
                return c, None

            out, _ = jax.lax.scan(body, v, None, length=3)
            return m4j.allreduce((out * out).sum(), op=m4j.SUM)[None]

        return m4j.spmd(per_rank, mesh=mesh)(x).reshape(N)[0]

    g = jax.grad(roll_loss)(jnp.arange(N, dtype=jnp.float32))
    assert g.shape == (N,)
    assert np.all(np.isfinite(np.asarray(g)))
    # finite-difference check
    x0 = jnp.arange(N, dtype=jnp.float32)
    eps = 1e-2
    e0 = np.zeros(N, np.float32)
    e0[3] = eps
    fd = (roll_loss(x0 + e0) - roll_loss(x0 - e0)) / (2 * eps)
    np.testing.assert_allclose(float(fd), float(g[3]), rtol=2e-2)
