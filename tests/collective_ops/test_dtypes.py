"""Dtype coverage for the mesh tier: bfloat16 (TPU-native), float16,
complex, and 64-bit-free integer paths through every reduction family."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m4j

N = 8


@pytest.fixture(scope="module")
def mesh():
    return m4j.make_mesh(N)


@pytest.mark.parametrize(
    "dtype", [jnp.bfloat16, jnp.float16, jnp.float32, jnp.int32, jnp.uint16]
)
def test_allreduce_sum_dtypes(mesh, dtype):
    x = jnp.ones((N, 4), dtype)
    out = m4j.spmd(lambda v: m4j.allreduce(v, op=m4j.SUM), mesh=mesh)(x)
    assert out.dtype == dtype
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float64), N, rtol=1e-2
    )


def test_allreduce_complex(mesh):
    x = jnp.full((N, 2), 1 + 2j, jnp.complex64)
    out = m4j.spmd(lambda v: m4j.allreduce(v, op=m4j.SUM), mesh=mesh)(x)
    assert out.dtype == jnp.complex64
    np.testing.assert_allclose(np.asarray(out), N * (1 + 2j))


def test_sendrecv_bfloat16(mesh):
    x = jnp.arange(N, dtype=jnp.bfloat16)
    out = m4j.spmd(lambda v: m4j.sendrecv(v, shift=1), mesh=mesh)(x)
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), np.roll(np.arange(N), 1)
    )


def test_scan_bfloat16(mesh):
    x = jnp.ones((N, 2), jnp.bfloat16)
    out = m4j.spmd(lambda v: m4j.scan(v, m4j.SUM), mesh=mesh)(x)
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32).reshape(N, 2)[:, 0],
        np.arange(1, N + 1),
    )


def test_allgather_preserves_dtype(mesh):
    for dtype in (jnp.bfloat16, jnp.int8, jnp.bool_):
        x = jnp.ones((N, 2), dtype)
        out = m4j.spmd(lambda v: m4j.allgather(v), mesh=mesh)(x)
        assert out.dtype == dtype
