"""Pallas RDMA ring collectives vs XLA builtin collectives.

Mirrors the reference's identity-based per-op testing style
(/root/reference/tests/collective_ops/test_allreduce.py:13-32) but checks the
DMA path against the XLA collective path — both run on the 8-device CPU mesh,
the DMA kernels under Pallas TPU interpret mode.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax, shard_map
from jax.sharding import PartitionSpec as P

from mpi4jax_tpu.ops import pallas_collectives as pc
from mpi4jax_tpu.ops._mesh_impl import ring_perm

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs >=4 devices"
)


def _mesh(n=4):
    return jax.make_mesh((n,), ("x",))


def _smap(fn, mesh, in_specs=P("x"), out_specs=P("x")):
    return jax.jit(
        shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    )


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16, jnp.int32])
@pytest.mark.parametrize("shift", [1, -1, 2])
def test_ring_shift_matches_ppermute(dtype, shift):
    mesh = _mesh()
    n = 4
    x = jnp.arange(n * 8 * 128).reshape(n * 8, 128).astype(dtype)
    got = _smap(lambda v: pc.ring_shift(v, "x", shift), mesh)(x)
    want = _smap(
        lambda v: lax.ppermute(v, "x", ring_perm(n, shift)), mesh
    )(x)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_all_gather_matches_lax():
    mesh = _mesh()
    x = jnp.arange(4 * 6 * 32, dtype=jnp.float32).reshape(4 * 6, 32)
    got = _smap(
        lambda v: pc.all_gather(v, "x"), mesh, out_specs=P(None, "x")
    )(x)
    want = _smap(
        lambda v: lax.all_gather(v, "x", axis=0, tiled=False),
        mesh,
        out_specs=P(None, "x"),
    )(x)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_reduce_scatter_matches_psum_chunk():
    mesh = _mesh()
    n = 4
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(n * n * 3, 16), np.float32)

    def rs(v):
        return pc.reduce_scatter_sum(v, "x")

    got = _smap(rs, mesh)(x)

    def ref(v):
        full = lax.psum(v, "x")
        c = v.shape[0] // n
        return lax.dynamic_slice_in_dim(
            full, lax.axis_index("x") * c, c, axis=0
        )

    want = _smap(ref, mesh)(x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


@pytest.mark.parametrize(
    "shape",
    [(4 * 8, 32), (2048, 4), (7, 5), (3,), ()],
    ids=["butterfly", "ring-rs-ag", "odd", "tiny", "scalar"],
)
def test_allreduce_matches_psum(shape):
    mesh = _mesh()
    rng = np.random.RandomState(1)
    full = (4,) + shape
    x = jnp.asarray(rng.randn(*full), np.float32)
    got = _smap(lambda v: pc.allreduce_sum(v[0], "x")[None], mesh)(x)
    want = _smap(lambda v: lax.psum(v[0], "x")[None], mesh)(x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )


def test_allreduce_grad_matches_psum_grad():
    mesh = _mesh()
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(4 * 4, 8), np.float32)
    w = jnp.asarray(rng.randn(4 * 4, 8), np.float32)

    def loss_pc(v, w):
        return jnp.sum(pc.allreduce_sum(v, "x") * w)

    def loss_ref(v, w):
        return jnp.sum(lax.psum(v, "x") * w)

    def gradder(loss):
        def f(v, w):
            g = jax.grad(loss)(v, w)
            return g

        return _smap(f, mesh, in_specs=(P("x"), P("x")))

    got = gradder(loss_pc)(x, w)
    want = gradder(loss_ref)(x, w)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_butterfly_allreduce_8dev():
    """log2(8)=3 XOR exchanges; payload small enough for the butterfly."""
    mesh = jax.make_mesh((8,), ("x",))
    rng = np.random.RandomState(10)
    x = jnp.asarray(rng.randn(8 * 64), np.float32)
    assert 64 <= pc.BUTTERFLY_MAX_ELEMS
    got = _smap(lambda v: pc.allreduce_sum(v, "x"), mesh)(x)
    want = _smap(lambda v: lax.psum(v, "x"), mesh)(x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )


def test_ring_shift2_both_directions():
    mesh = _mesh()
    x = jnp.arange(4 * 8 * 16, dtype=jnp.float32).reshape(4 * 8, 16)

    def f(v):
        a, b = pc.ring_shift2(v, 2.0 * v, "x")
        return a + b

    got = _smap(f, mesh)(x)

    def ref(v):
        a = lax.ppermute(v, "x", ring_perm(4, 1))
        b = lax.ppermute(2.0 * v, "x", ring_perm(4, -1))
        return a + b

    want = _smap(ref, mesh)(x)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_bidirectional_allreduce_matches_psum():
    """Payloads over BIDIR_MIN_ELEMS take the split two-direction ring."""
    mesh = _mesh()
    rng = np.random.RandomState(9)
    assert 4 * 4096 >= pc.BIDIR_MIN_ELEMS
    x = jnp.asarray(rng.randn(4 * 4096 + 4 * 3), np.float32)  # odd: pads
    got = _smap(lambda v: pc.allreduce_sum(v, "x"), mesh)(x)
    want = _smap(lambda v: lax.psum(v, "x"), mesh)(x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("count", [4 * 4096, 4 * 4096 + 37, 513, 3],
                         ids=["aligned", "odd", "small-odd", "tiny"])
def test_fused_ring_allreduce_matches_numpy_twin(count):
    """The ICI data plane's bit-exactness contract: the fused
    double-buffered ring kernel folds with EXACTLY the numpy
    ``simulate_ring_sum`` association (which is also the off-pallas
    backend of ``topo/_ici_leg.py``) — every device, every byte."""
    from mpi4jax_tpu import topo

    mesh = _mesh()
    rng = np.random.RandomState(count)
    rows = rng.randn(4, count).astype(np.float32) * 3
    got = _smap(
        lambda v: pc.fused_ring_allreduce_sum(v.reshape(-1), "x")[None],
        mesh,
    )(jnp.asarray(rows))
    want = topo.simulate_ring_sum([rows[r] for r in range(4)])
    for r in range(4):
        np.testing.assert_array_equal(np.asarray(got)[r], want), r


def test_fused_ring_allreduce_grad_is_itself():
    # d(sum_r x_r)/dx = the same allreduce of the cotangents
    mesh = _mesh()
    rng = np.random.RandomState(21)
    x = jnp.asarray(rng.randn(4 * 600), np.float32)
    w = jnp.asarray(rng.randn(4 * 600), np.float32)

    def make(ar):
        def f(v, w):
            return jax.grad(lambda v: jnp.sum(ar(v) * w))(v)

        return _smap(f, mesh, in_specs=(P("x"), P("x")))

    got = make(lambda v: pc.fused_ring_allreduce_sum(v, "x"))(x, w)
    want = make(lambda v: lax.psum(v, "x"))(x, w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_large_allreduce_dispatches_to_fused_ring():
    # the dispatch arm: bandwidth-bound payloads on n > 2 ride the
    # fused kernel, so allreduce_sum must be bit-identical to it there
    mesh = _mesh()
    rng = np.random.RandomState(23)
    x = jnp.asarray(rng.randn(4 * 4096 + 8), np.float32)
    via_dispatch = _smap(lambda v: pc.allreduce_sum(v, "x"), mesh)(x)
    direct = _smap(lambda v: pc.fused_ring_allreduce_sum(v, "x"), mesh)(x)
    np.testing.assert_array_equal(np.asarray(via_dispatch),
                                  np.asarray(direct))


def test_ring_shift2_grad():
    mesh = _mesh()
    rng = np.random.RandomState(11)
    x = jnp.asarray(rng.randn(4 * 4, 8), np.float32)
    w1 = jnp.asarray(rng.randn(4 * 4, 8), np.float32)
    w2 = jnp.asarray(rng.randn(4 * 4, 8), np.float32)

    def make(step):
        def f(v, w1, w2):
            return jax.grad(
                lambda v: jnp.sum(sum(jnp.multiply(o, w)
                                      for o, w in zip(step(v), (w1, w2))))
            )(v)

        return _smap(f, mesh, in_specs=(P("x"), P("x"), P("x")))

    got = make(lambda v: pc.ring_shift2(v, 3.0 * v, "x"))(x, w1, w2)
    want = make(
        lambda v: (
            lax.ppermute(v, "x", ring_perm(4, 1)),
            lax.ppermute(3.0 * v, "x", ring_perm(4, -1)),
        )
    )(x, w1, w2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)


@pytest.mark.parametrize("periodic", [True, False])
def test_halo_exchange_rdma_matches_ppermute(monkeypatch, periodic):
    from mpi4jax_tpu.parallel.grid import ProcessGrid
    from mpi4jax_tpu.parallel.halo import halo_exchange

    grid = ProcessGrid((2, 4))
    rng = np.random.RandomState(12)
    ny, nx = 2 * 6, 4 * 6
    a = jnp.asarray(rng.randn(ny, nx), np.float32)
    b = jnp.asarray(rng.randn(ny, nx), np.float32)

    def run():
        def f(a, b):
            return halo_exchange((a, b), grid, halo=1, periodic=periodic)

        return jax.jit(
            shard_map(
                f,
                mesh=grid.mesh,
                in_specs=(P(*grid.axes),) * 2,
                out_specs=(P(*grid.axes),) * 2,
            )
        )(a, b)

    base = run()
    monkeypatch.setenv("MPI4JAX_TPU_PALLAS_COLLECTIVES", "1")
    rdma = run()
    for g, w in zip(rdma, base):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


def test_ring_shift_n_matches_sequential():
    mesh = _mesh()
    x = jnp.arange(4 * 4 * 8, dtype=jnp.float32).reshape(4 * 4, 8)

    def f(v):
        a, b, c = pc.ring_shift_n((v, 2.0 * v, v + 1.0), "x")
        return a + b + c

    got = _smap(f, mesh)(x)
    perm = ring_perm(4, 1)
    want = _smap(
        lambda v: sum(lax.ppermute(p, "x", perm)
                      for p in (v, 2.0 * v, v + 1.0)),
        mesh,
    )(x)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_ring_shift_n_grad_matches_ppermute():
    mesh = _mesh()
    rng = np.random.RandomState(14)
    x = jnp.asarray(rng.randn(4 * 4, 8), np.float32)
    w = jnp.asarray(rng.randn(4 * 4, 8), np.float32)

    def make(shifter):
        def f(v, w):
            def loss(v):
                a, b = shifter(v)
                return jnp.sum(a * w) + jnp.sum(b * (2.0 * w))

            return jax.grad(loss)(v)

        return _smap(f, mesh, in_specs=(P("x"), P("x")))

    perm = ring_perm(4, 1)
    got = make(lambda v: pc.ring_shift_n((v, v * v), "x"))(x, w)
    want = make(
        lambda v: (lax.ppermute(v, "x", perm),
                   lax.ppermute(v * v, "x", perm))
    )(x, w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)


def test_ring_attention_rdma_matches_xla(monkeypatch):
    """Ring attention's k/v rotation rides ring_shift_n under the flag and
    must agree with the ppermute ring bit-for-bit."""
    from mpi4jax_tpu.parallel.ring import ring_attention

    mesh = _mesh()
    rng = np.random.RandomState(13)
    b, t, h, d = 2, 4 * 8, 2, 16
    q, k, v = (jnp.asarray(rng.randn(b, t, h, d), np.float32)
               for _ in range(3))

    def run():
        return jax.jit(
            shard_map(
                lambda q, k, v: ring_attention(
                    q, k, v, axis="x", causal=True, impl="xla"
                ),
                mesh=mesh,
                in_specs=(P(None, "x"),) * 3,
                out_specs=P(None, "x"),
            )
        )(q, k, v)

    base = run()
    monkeypatch.setenv("MPI4JAX_TPU_PALLAS_COLLECTIVES", "1")
    rdma = run()
    np.testing.assert_array_equal(np.asarray(rdma), np.asarray(base))


@pytest.mark.parametrize("nmesh", [(4,), (2, 4)])
def test_alltoall_direct_matches_lax(nmesh):
    axes = ("a", "b")[: len(nmesh)]
    mesh = jax.make_mesh(nmesh, axes)
    axis = axes[-1]
    n = nmesh[-1]
    rng = np.random.RandomState(15)
    total = int(np.prod(nmesh))
    x = jnp.asarray(rng.randn(total * n * 3, 8), np.float32)
    spec = P(tuple(axes))

    got = jax.jit(
        shard_map(
            lambda v: pc.alltoall(v.reshape(n, -1, 8), axis).reshape(v.shape),
            mesh=mesh, in_specs=spec, out_specs=spec,
        )
    )(x)
    want = jax.jit(
        shard_map(
            lambda v: lax.all_to_all(
                v.reshape(n, -1, 8), axis, split_axis=0, concat_axis=0
            ).reshape(v.shape),
            mesh=mesh, in_specs=spec, out_specs=spec,
        )
    )(x)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_alltoall_direct_complex():
    """The spectral FFT's slab transpose moves complex64 — byte-exact
    through the DMA path."""
    mesh = _mesh()
    rng = np.random.RandomState(17)
    x = jnp.asarray(
        rng.randn(4 * 4, 8) + 1j * rng.randn(4 * 4, 8), np.complex64
    )
    got = _smap(
        lambda v: pc.alltoall(v.reshape(4, -1, 8), "x").reshape(v.shape),
        mesh,
    )(x)
    want = _smap(
        lambda v: lax.all_to_all(
            v.reshape(4, -1, 8), "x", split_axis=0, concat_axis=0
        ).reshape(v.shape),
        mesh,
    )(x)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_spectral_fft_rdma_matches(monkeypatch):
    """End-to-end: the distributed FFT's alltoall transposes ride the
    direct RDMA kernel under the flag, same spectrum either way."""
    from mpi4jax_tpu.models import spectral

    n = 16
    rng = np.random.RandomState(18)
    u = jnp.asarray(rng.randn(n, n, n), np.float32)
    mesh = jax.make_mesh((4,), ("x",))

    def run():
        return jax.jit(
            shard_map(
                lambda v: spectral.ifft3(spectral.fft3(v, axis="x"),
                                         axis="x").real,
                mesh=mesh, in_specs=P("x"), out_specs=P("x"),
            )
        )(u)

    base = run()
    monkeypatch.setenv("MPI4JAX_TPU_PALLAS_COLLECTIVES", "1")
    rdma = run()
    np.testing.assert_allclose(
        np.asarray(rdma), np.asarray(base), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(np.asarray(rdma), np.asarray(u),
                               rtol=1e-4, atol=1e-4)


def test_alltoall_direct_grad():
    mesh = _mesh()
    rng = np.random.RandomState(16)
    x = jnp.asarray(rng.randn(4 * 4, 6), np.float32)
    w = jnp.asarray(rng.randn(4 * 4, 6), np.float32)

    def make(op):
        def f(v, w):
            return jax.grad(
                lambda v: jnp.sum(op(v.reshape(4, -1, 6)) * w.reshape(4, -1, 6))
            )(v)

        return _smap(f, mesh, in_specs=(P("x"), P("x")))

    got = make(lambda v: pc.alltoall(v, "x"))(x, w)
    want = make(
        lambda v: lax.all_to_all(v, "x", split_axis=0, concat_axis=0)
    )(x, w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)


def test_ring_shift_of():
    assert pc.ring_shift_of(ring_perm(8, 1), 8) == 1
    assert pc.ring_shift_of(ring_perm(8, -1), 8) == 7
    assert pc.ring_shift_of(ring_perm(8, 3), 8) == 3
    assert pc.ring_shift_of([(0, 1)], 8) is None
    assert pc.ring_shift_of([(i, i) for i in range(8)], 8) is None
    # not a uniform shift
    assert pc.ring_shift_of([(0, 1), (1, 0), (2, 3), (3, 2)], 4) is None


def test_multidim_mesh_ring_shift():
    """On a 2-D mesh the DMA target must be the *global* logical id — the
    neighbor on the ring axis within this device's row/column."""
    mesh = jax.make_mesh((2, 4), ("a", "b"))
    x = jnp.arange(8 * 8 * 16, dtype=jnp.float32).reshape(8 * 8, 16)

    for axis in ("a", "b"):
        got = jax.jit(
            shard_map(
                lambda v: pc.ring_shift(v, axis),
                mesh=mesh,
                in_specs=P(("a", "b")),
                out_specs=P(("a", "b")),
            )
        )(x)
        n = mesh.shape[axis]
        want = jax.jit(
            shard_map(
                lambda v: lax.ppermute(v, axis, ring_perm(n, 1)),
                mesh=mesh,
                in_specs=P(("a", "b")),
                out_specs=P(("a", "b")),
            )
        )(x)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_multidim_mesh_allreduce_matches_psum():
    mesh = jax.make_mesh((2, 4), ("a", "b"))
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(8 * 4, 8), np.float32)
    for axis in ("a", "b"):
        got = jax.jit(
            shard_map(
                lambda v: pc.allreduce_sum(v, axis),
                mesh=mesh,
                in_specs=P(("a", "b")),
                out_specs=P(("a", "b")),
            )
        )(x)
        want = jax.jit(
            shard_map(
                lambda v: lax.psum(v, axis),
                mesh=mesh,
                in_specs=P(("a", "b")),
                out_specs=P(("a", "b")),
            )
        )(x)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5
        )


def test_ring_shift_grad_is_inverse_shift():
    """Transpose flows the cotangent backward along the message edge —
    the reference sendrecv's source/dest swap (sendrecv.py:390-409)."""
    mesh = _mesh()
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(4 * 4, 8), np.float32)
    w = jnp.asarray(rng.randn(4 * 4, 8), np.float32)

    def make(shifter):
        def f(v, w):
            return jax.grad(
                lambda v: jnp.sum(shifter(v) * w)
            )(v)

        return _smap(f, mesh, in_specs=(P("x"), P("x")))

    got = make(lambda v: pc.ring_shift(v, "x", 1))(x, w)
    want = make(lambda v: lax.ppermute(v, "x", ring_perm(4, 1)))(x, w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_all_gather_grad_matches_lax():
    mesh = _mesh()
    rng = np.random.RandomState(6)
    x = jnp.asarray(rng.randn(4 * 4, 8), np.float32)

    def make(gatherer):
        def f(v):
            return jax.grad(lambda v: jnp.sum(gatherer(v) ** 2))(v)

        return _smap(f, mesh)

    got = make(lambda v: pc.all_gather(v, "x"))(x)
    want = make(
        lambda v: lax.all_gather(v, "x", axis=0, tiled=False)
    )(x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)


def test_fwd_mode_raises():
    mesh = _mesh()
    x = jnp.ones((4 * 4, 8), np.float32)

    def f(v):
        return jax.jvp(
            lambda v: pc.ring_shift(v, "x", 1), (v,), (v,)
        )[1]

    with pytest.raises(TypeError):
        _smap(f, mesh)(x)


def test_mesh_tier_routing(monkeypatch):
    """With the flag set, the public mesh-tier ops ride the DMA path and
    still produce identical results."""
    monkeypatch.setenv("MPI4JAX_TPU_PALLAS_COLLECTIVES", "1")
    from mpi4jax_tpu.ops import _mesh_impl as m
    from mpi4jax_tpu.ops.reduce_ops import SUM

    mesh = _mesh()
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(4 * 8, 16), np.float32)

    got = _smap(lambda v: m.allreduce(v, SUM, "x"), mesh)(x)
    want = _smap(lambda v: lax.psum(v, "x"), mesh)(x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)

    got = _smap(
        lambda v: m.sendrecv(v, ring_perm(4, 1), "x"), mesh
    )(x)
    want = _smap(
        lambda v: lax.ppermute(v, "x", ring_perm(4, 1)), mesh
    )(x)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
