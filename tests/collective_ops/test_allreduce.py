"""allreduce identity tests on the 8-device mesh.

Mirrors the reference test strategy (SURVEY.md §4.2): closed-form identities
(sum == x * size), input non-mutation, scalars, jit, vmap, grad,
linear_transpose and double-transpose.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m4j

N = 8


@pytest.fixture(scope="module")
def mesh():
    return m4j.make_mesh(N)


def run_spmd(fn, *args, mesh=None, **kw):
    return m4j.spmd(fn, mesh=mesh, **kw)(*args)


def test_allreduce_sum(mesh):
    x = jnp.arange(N * 3, dtype=jnp.float32).reshape(N, 3)
    out = m4j.spmd(lambda v: m4j.allreduce(v, op=m4j.SUM), mesh=mesh)(x)
    expected = np.tile(np.sum(np.asarray(x), axis=0), (N, 1))
    np.testing.assert_allclose(np.asarray(out), expected)
    # input unchanged
    np.testing.assert_allclose(np.asarray(x), np.arange(N * 3).reshape(N, 3))


def test_allreduce_jit(mesh):
    x = jnp.ones((N, 4), jnp.float32)
    f = jax.jit(m4j.spmd(lambda v: m4j.allreduce(v, op=m4j.SUM), mesh=mesh))
    out = f(x)
    np.testing.assert_allclose(np.asarray(out), N)


@pytest.mark.parametrize(
    "op,np_fn",
    [
        (m4j.SUM, np.sum),
        (m4j.PROD, np.prod),
        (m4j.MAX, np.max),
        (m4j.MIN, np.min),
    ],
)
def test_allreduce_ops(mesh, op, np_fn):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.uniform(0.5, 1.5, (N, 5)).astype(np.float32))
    out = m4j.spmd(lambda v: m4j.allreduce(v, op=op), mesh=mesh)(x)
    expected = np.tile(np_fn(np.asarray(x), axis=0), (N, 1))
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5)


@pytest.mark.parametrize("op_name", ["LAND", "LOR", "LXOR"])
def test_allreduce_logical(mesh, op_name):
    op = m4j.as_reduce_op(op_name)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.rand(N, 6) > 0.5)
    out = m4j.spmd(lambda v: m4j.allreduce(v, op=op), mesh=mesh)(x)
    ref = {
        "LAND": np.all(np.asarray(x), axis=0),
        "LOR": np.any(np.asarray(x), axis=0),
        "LXOR": np.sum(np.asarray(x), axis=0) % 2 == 1,
    }[op_name]
    np.testing.assert_array_equal(np.asarray(out), np.tile(ref, (N, 1)))


@pytest.mark.parametrize("op_name", ["BAND", "BOR", "BXOR"])
def test_allreduce_bitwise(mesh, op_name):
    op = m4j.as_reduce_op(op_name)
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randint(0, 255, (N, 4)).astype(np.uint8))
    out = m4j.spmd(lambda v: m4j.allreduce(v, op=op), mesh=mesh)(x)
    np_fn = {
        "BAND": np.bitwise_and.reduce,
        "BOR": np.bitwise_or.reduce,
        "BXOR": np.bitwise_xor.reduce,
    }[op_name]
    np.testing.assert_array_equal(
        np.asarray(out), np.tile(np_fn(np.asarray(x), axis=0), (N, 1))
    )


def test_allreduce_scalar(mesh):
    x = jnp.arange(N, dtype=jnp.float32)
    out = m4j.spmd(
        lambda v: m4j.allreduce(v[0], op=m4j.SUM)[None], mesh=mesh
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.sum(np.arange(N)))


def test_allreduce_bool_sum_raises(mesh):
    x = jnp.ones((N,), jnp.bool_)
    with pytest.raises(TypeError, match="not defined for boolean"):
        m4j.spmd(lambda v: m4j.allreduce(v, op=m4j.SUM), mesh=mesh)(x)


def test_allreduce_vmap(mesh):
    x = jnp.arange(N * 2 * 3, dtype=jnp.float32).reshape(N, 2, 3)

    def step(v):  # v: (2, 3) local; vmap over leading batch
        return jax.vmap(lambda row: m4j.allreduce(row, op=m4j.SUM))(v)

    out = m4j.spmd(step, mesh=mesh)(x)
    expected = np.tile(np.asarray(x).sum(axis=0), (N, 1, 1)).reshape(N, 2, 3)
    np.testing.assert_allclose(np.asarray(out), expected)


def test_allreduce_grad(mesh):
    x = jnp.arange(N, dtype=jnp.float32)

    def loss(v):
        summed = m4j.spmd(
            lambda u: m4j.allreduce(u * u, op=m4j.SUM), mesh=mesh
        )(v)
        return summed.sum()

    g = jax.grad(loss)(x)
    # d/dx_i sum_r sum_j x_j^2 (replicated N times) = 2 * N * x_i
    np.testing.assert_allclose(np.asarray(g), 2 * N * np.asarray(x))


def test_allreduce_jvp(mesh):
    x = jnp.arange(N, dtype=jnp.float32)
    t = jnp.ones((N,), jnp.float32)
    f = m4j.spmd(lambda u: m4j.allreduce(u, op=m4j.SUM), mesh=mesh)
    y, ty = jax.jvp(f, (x,), (t,))
    np.testing.assert_allclose(np.asarray(y), np.sum(np.arange(N)))
    np.testing.assert_allclose(np.asarray(ty), N)


def test_allreduce_transpose_identity(mesh):
    # reference: double transpose of allreduce == allreduce
    # (tests/collective_ops/test_allreduce.py:105-138 there)
    x = jnp.arange(N, dtype=jnp.float32)
    f = m4j.spmd(lambda u: m4j.allreduce(u, op=m4j.SUM), mesh=mesh)
    (xt,) = jax.linear_transpose(f, x)(jnp.ones((N,), jnp.float32))
    # transpose of "replicate-sum" applied to ones = N ones per shard summed
    np.testing.assert_allclose(np.asarray(xt), N)

    def double_transpose(v):
        def t1(u):
            return jax.linear_transpose(f, x)(u)[0]

        return jax.linear_transpose(t1, jnp.ones((N,), jnp.float32))(v)[0]

    dt = double_transpose(x)
    np.testing.assert_allclose(np.asarray(dt), np.asarray(f(x)))


def test_allreduce_token_chain(mesh):
    x = jnp.arange(N, dtype=jnp.float32)

    def step(v):
        token = m4j.create_token(v)
        a, token = m4j.allreduce(v, op=m4j.SUM, token=token)
        b, token = m4j.allreduce(a, op=m4j.MAX, token=token)
        return b

    out = m4j.spmd(step, mesh=mesh)(x)
    np.testing.assert_allclose(np.asarray(out), np.sum(np.arange(N)))


def test_allreduce_inside_fori_loop(mesh):
    # ordering/effects must compose with lax control flow (SURVEY.md §7
    # hard part 1)
    x = jnp.ones((N,), jnp.float32)

    def step(v):
        def body(_, acc):
            return m4j.allreduce(acc, op=m4j.SUM) / N
        return jax.lax.fori_loop(0, 3, body, v)

    out = jax.jit(m4j.spmd(step, mesh=mesh))(x)
    np.testing.assert_allclose(np.asarray(out), 1.0)
