"""Identity tests for allgather/alltoall/bcast/reduce/scan/scatter/gather/
barrier on the 8-device mesh (reference pattern: SURVEY.md §4.2 — eager+jit,
closed-form expectations, input non-mutation)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m4j

N = 8


@pytest.fixture(scope="module")
def mesh():
    return m4j.make_mesh(N)


# ---- allgather ---------------------------------------------------------


def test_allgather(mesh):
    x = jnp.arange(N * 3, dtype=jnp.float32).reshape(N, 3)
    out = m4j.spmd(lambda v: m4j.allgather(v), mesh=mesh)(x)
    # each rank returns (N, 3); stacked across ranks -> (N*N, 3)
    out = np.asarray(out).reshape(N, N, 3)
    for r in range(N):
        np.testing.assert_allclose(out[r], np.asarray(x))


def test_allgather_scalar(mesh):
    x = jnp.arange(N, dtype=jnp.int32)
    out = m4j.spmd(lambda v: m4j.allgather(v[0]), mesh=mesh)(x)
    np.testing.assert_array_equal(
        np.asarray(out).reshape(N, N)[0], np.arange(N)
    )


# ---- alltoall ----------------------------------------------------------


def test_alltoall(mesh):
    # rank r's input row j is 100*r + j; after alltoall, rank r's row j must
    # be rank j's row r: 100*j + r.
    x = jnp.asarray(
        [[100 * r + j for j in range(N)] for r in range(N)], dtype=jnp.int32
    ).reshape(N * N)
    out = m4j.spmd(
        lambda v: m4j.alltoall(v.reshape(N, 1)).reshape(N), mesh=mesh
    )(x)
    out = np.asarray(out).reshape(N, N)
    for r in range(N):
        np.testing.assert_array_equal(
            out[r], np.array([100 * j + r for j in range(N)])
        )


def test_alltoall_bad_leading_axis(mesh):
    x = jnp.ones((N, 3, 2), jnp.float32)
    with pytest.raises(ValueError, match="leading axis"):
        m4j.spmd(lambda v: m4j.alltoall(v), mesh=mesh)(x)


# ---- bcast -------------------------------------------------------------


@pytest.mark.parametrize("root", [0, 3, 7])
def test_bcast(mesh, root):
    x = jnp.arange(N * 2, dtype=jnp.float32).reshape(N, 2)
    out = m4j.spmd(lambda v: m4j.bcast(v, root), mesh=mesh)(x)
    out = np.asarray(out).reshape(N, 2)
    for r in range(N):
        np.testing.assert_allclose(out[r], np.asarray(x)[root])


def test_bcast_bool(mesh):
    x = jnp.asarray([[r % 2 == 0] for r in range(N)])
    out = m4j.spmd(lambda v: m4j.bcast(v, 1), mesh=mesh)(x)
    assert out.dtype == jnp.bool_
    np.testing.assert_array_equal(np.asarray(out), [[False]] * N)


# ---- reduce ------------------------------------------------------------


@pytest.mark.parametrize("root", [0, 5])
def test_reduce(mesh, root):
    x = jnp.arange(N * 2, dtype=jnp.float32).reshape(N, 2)
    out = m4j.spmd(lambda v: m4j.reduce(v, m4j.SUM, root), mesh=mesh)(x)
    out = np.asarray(out).reshape(N, 2)
    np.testing.assert_allclose(out[root], np.asarray(x).sum(axis=0))
    for r in range(N):
        if r != root:
            # non-root ranks keep their input (reference contract)
            np.testing.assert_allclose(out[r], np.asarray(x)[r])


def test_reduce_max(mesh):
    x = jnp.arange(N, dtype=jnp.float32)
    out = m4j.spmd(lambda v: m4j.reduce(v, m4j.MAX, 2), mesh=mesh)(x)
    out = np.asarray(out)
    assert out[2] == N - 1
    assert out[0] == 0.0


# ---- scan --------------------------------------------------------------


@pytest.mark.parametrize(
    "op,np_acc",
    [
        (m4j.SUM, np.cumsum),
        (m4j.MAX, np.maximum.accumulate),
        (m4j.MIN, np.minimum.accumulate),
        (m4j.PROD, np.cumprod),
    ],
)
def test_scan(mesh, op, np_acc):
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.uniform(0.5, 1.5, (N, 3)).astype(np.float32))
    out = m4j.spmd(lambda v: m4j.scan(v, op), mesh=mesh)(x)
    expected = np_acc(np.asarray(x), axis=0)
    np.testing.assert_allclose(
        np.asarray(out).reshape(N, 3), expected, rtol=1e-5
    )


def test_scan_int(mesh):
    x = jnp.ones((N, 1), jnp.int32)
    out = m4j.spmd(lambda v: m4j.scan(v, m4j.SUM), mesh=mesh)(x)
    np.testing.assert_array_equal(
        np.asarray(out).ravel(), np.arange(1, N + 1)
    )


# ---- scatter / gather --------------------------------------------------


@pytest.mark.parametrize("root", [0, 4])
def test_scatter(mesh, root):
    # every rank passes the same (N, 2) buffer; rank j receives row j
    base = np.arange(N * 2, dtype=np.float32).reshape(N, 2)
    x = jnp.asarray(np.tile(base, (N, 1)))  # global (N*N, 2)
    out = m4j.spmd(lambda v: m4j.scatter(v, root), mesh=mesh)(x)
    out = np.asarray(out).reshape(N, 2)
    np.testing.assert_allclose(out, base)


def test_scatter_gather_roundtrip(mesh):
    base = np.arange(N * 3, dtype=np.float32).reshape(N, 3)
    x = jnp.asarray(np.tile(base, (N, 1)))

    def step(v):
        mine = m4j.scatter(v, 0)
        return m4j.gather(mine, 0)

    out = m4j.spmd(step, mesh=mesh)(x)
    out = np.asarray(out).reshape(N, N, 3)
    for r in range(N):
        np.testing.assert_allclose(out[r], base)


def test_gather(mesh):
    x = jnp.arange(N, dtype=jnp.int32)
    out = m4j.spmd(lambda v: m4j.gather(v, 0), mesh=mesh)(x)
    np.testing.assert_array_equal(
        np.asarray(out).reshape(N, N)[0], np.arange(N)
    )


# ---- barrier -----------------------------------------------------------


def test_barrier(mesh):
    def step(v):
        m4j.barrier()
        return v

    out = m4j.spmd(step, mesh=mesh)(jnp.arange(N, dtype=jnp.float32))
    np.testing.assert_allclose(np.asarray(out), np.arange(N))


def test_barrier_token(mesh):
    def step(v):
        token = m4j.create_token(v)
        token = m4j.barrier(token=token)
        y, token = m4j.allreduce(v, op=m4j.SUM, token=token)
        return y

    out = m4j.spmd(step, mesh=mesh)(jnp.arange(N, dtype=jnp.float32))
    np.testing.assert_allclose(np.asarray(out), np.sum(np.arange(N)))


# ---- sendrecv / permute ------------------------------------------------


def test_sendrecv_ring(mesh):
    x = jnp.arange(N, dtype=jnp.float32)
    out = m4j.spmd(lambda v: m4j.sendrecv(v, shift=1), mesh=mesh)(x)
    np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(N), 1))


def test_sendrecv_mesh_accepts_default_tags(mesh):
    # tag=0 / matching tags are the no-op spelling and must keep working
    # on the mesh tier; a non-default tag is rejected loudly
    import pytest

    x = jnp.arange(N, dtype=jnp.float32)
    out = m4j.spmd(lambda v: m4j.sendrecv(v, shift=1, tag=0), mesh=mesh)(x)
    np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(N), 1))
    with pytest.raises(ValueError, match="world-tier only"):
        m4j.spmd(lambda v: m4j.sendrecv(v, shift=1, tag=3), mesh=mesh)(x)


def test_sendrecv_ring_backward(mesh):
    x = jnp.arange(N, dtype=jnp.float32)
    out = m4j.spmd(lambda v: m4j.sendrecv(v, shift=-1), mesh=mesh)(x)
    np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(N), -1))


def test_sendrecv_nowrap_zero_fill(mesh):
    x = jnp.ones((N,), jnp.float32)
    out = m4j.spmd(
        lambda v: m4j.sendrecv(v, shift=1, wrap=False), mesh=mesh
    )(x)
    out = np.asarray(out)
    assert out[0] == 0.0  # rank 0 has no source
    np.testing.assert_allclose(out[1:], 1.0)


def test_sendrecv_explicit_perm(mesh):
    x = jnp.arange(N, dtype=jnp.int32)
    perm = [(0, 7), (7, 0)]
    out = m4j.spmd(lambda v: m4j.permute(v, perm), mesh=mesh)(x)
    out = np.asarray(out)
    assert out[7] == 0 and out[0] == 7
    np.testing.assert_array_equal(out[1:7], 0)


def test_sendrecv_transpose_swaps_direction(mesh):
    # reference: transpose of sendrecv swaps source and dest
    # (sendrecv.py:390-409 there); ppermute's transpose is the inverse perm.
    x = jnp.arange(N, dtype=jnp.float32)
    f = m4j.spmd(lambda v: m4j.sendrecv(v, shift=1), mesh=mesh)
    (ct,) = jax.linear_transpose(f, x)(jnp.arange(N, dtype=jnp.float32))
    np.testing.assert_allclose(np.asarray(ct), np.roll(np.arange(N), -1))


def test_sendrecv_jvp(mesh):
    # improvement over the reference (which forbids fwd-mode, sendrecv.py:150)
    x = jnp.arange(N, dtype=jnp.float32)
    f = m4j.spmd(lambda v: m4j.sendrecv(v, shift=2), mesh=mesh)
    y, ty = jax.jvp(f, (x,), (2 * x,))
    np.testing.assert_allclose(np.asarray(ty), 2 * np.asarray(y))


def test_send_recv_raise_in_mesh(mesh):
    x = jnp.ones((N,), jnp.float32)
    with pytest.raises(NotImplementedError, match="SPMD"):
        m4j.spmd(lambda v: m4j.send(v, 0), mesh=mesh)(x)
    with pytest.raises(NotImplementedError, match="SPMD"):
        m4j.spmd(lambda v: m4j.recv(v, 0), mesh=mesh)(x)


# ---- validation --------------------------------------------------------


def test_static_int_validation(mesh):
    x = jnp.arange(N, dtype=jnp.float32)
    with pytest.raises(TypeError, match="static"):
        m4j.spmd(lambda v: m4j.bcast(v, jnp.int32(0)), mesh=mesh)(x)


def test_traced_root_error_message(mesh):
    x = jnp.arange(N, dtype=jnp.float32)

    def step(v):
        r = jax.lax.axis_index("mpi")  # traced
        return m4j.bcast(v, r)

    with pytest.raises(TypeError, match="static"):
        m4j.spmd(step, mesh=mesh)(x)
