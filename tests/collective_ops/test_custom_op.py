"""User-defined reduction operators (MPI_Op_create parity — the
reference accepts arbitrary mpi4py Op handles, utils.py:133-152 there)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m4j

N = 8

absmax = m4j.custom_op(
    "ABSMAX", lambda a, b: jnp.maximum(jnp.abs(a), jnp.abs(b)))
# non-commutative-looking but associative: keep the lexicographically
# larger of two packed (key, payload) pairs — exercises the stack-reduce
first_nonzero = m4j.custom_op(
    "FIRSTNZ", lambda a, b: jnp.where(a != 0, a, b),
    reduce=lambda s: jax.lax.reduce(
        s, jnp.zeros((), s.dtype),
        lambda a, b: jnp.where(a != 0, a, b), (0,)),
)


@pytest.fixture(scope="module")
def mesh():
    return m4j.make_mesh(N)


def test_custom_allreduce(mesh):
    x = jnp.arange(N * 4, dtype=jnp.float32) - 16.0  # mixed signs
    out = m4j.spmd(lambda v: m4j.allreduce(v, op=absmax), mesh=mesh)(x)
    expect = np.abs(np.asarray(x).reshape(N, 4)).max(axis=0)
    np.testing.assert_allclose(np.asarray(out)[:4], expect)
    assert out.dtype == x.dtype


def test_custom_reduce_and_scan(mesh):
    x = jnp.arange(N * 2, dtype=jnp.float32) - 7.0
    out = m4j.spmd(lambda v: m4j.reduce(v, op=absmax, root=0), mesh=mesh)(x)
    expect = np.abs(np.asarray(x).reshape(N, 2)).max(axis=0)
    np.testing.assert_allclose(np.asarray(out)[:2], expect)

    sc = m4j.spmd(lambda v: m4j.scan(v, op=absmax), mesh=mesh)(x)
    raw = np.asarray(x).reshape(N, 2)
    # MPI inclusive scan: rank 0's prefix is its RAW contribution (no
    # combine applied); combines start at rank 1
    expect = np.empty_like(raw)
    expect[0] = raw[0]
    for r in range(1, N):
        expect[r] = np.maximum(np.abs(expect[r - 1]), np.abs(raw[r]))
    np.testing.assert_allclose(np.asarray(sc).reshape(N, 2), expect)


def test_custom_with_explicit_stack_reduce(mesh):
    x = jnp.asarray([0.0, 3.0] * N, jnp.float32).reshape(-1)[: N * 2]
    x = jnp.where(jnp.arange(N * 2) < 6, 0.0, x)  # leading zeros
    out = m4j.spmd(
        lambda v: m4j.allreduce(v, op=first_nonzero), mesh=mesh)(x)
    rows = np.asarray(x).reshape(N, 2)
    expect = np.zeros(2, np.float32)
    for j in range(2):
        nz = rows[:, j][rows[:, j] != 0]
        expect[j] = nz[0] if nz.size else 0.0
    np.testing.assert_allclose(np.asarray(out)[:2], expect)


def test_custom_under_jit_and_vmap(mesh):
    x = jnp.arange(N * 4, dtype=jnp.float32) - 10.0
    f = jax.jit(m4j.spmd(lambda v: m4j.allreduce(v, op=absmax), mesh=mesh))
    np.testing.assert_allclose(
        np.asarray(f(x))[:4],
        np.abs(np.asarray(x).reshape(N, 4)).max(axis=0))


def test_custom_name_rules():
    with pytest.raises(ValueError, match="built-in"):
        m4j.custom_op("SUM", lambda a, b: a + b)
    with pytest.raises(TypeError):
        m4j.custom_op("", lambda a, b: a + b)
    # identity is name-based (stable across processes, like the
    # reference's pointer-keyed handles within one job)
    a1 = m4j.custom_op("SAME", jnp.maximum)
    a2 = m4j.custom_op("SAME", jnp.maximum)
    assert a1 == a2 and hash(a1) == hash(a2)
    # ...so one name can never mean two different functions (a silent
    # jit-cache collision otherwise)
    with pytest.raises(ValueError, match="different"):
        m4j.custom_op("SAME", jnp.minimum)
    # but re-creating with identical code (same lambda in a loop) is fine
    for _ in range(2):
        m4j.custom_op("LOOPED", lambda a, b: jnp.maximum(a, b))

    # factory closures share a code object but differ in captures —
    # still rejected (they are semantically different functions)
    def make(n):
        return lambda a, b: a + b * n

    m4j.custom_op("SCALED", make(2))
    with pytest.raises(ValueError, match="different"):
        m4j.custom_op("SCALED", make(3))
    # a differing reduce= or domain under one name is likewise rejected
    with pytest.raises(ValueError, match="different"):
        m4j.custom_op("SCALED", make(2), domain="numeric")

    # default-argument captures (the n=n late-binding idiom) and
    # cross-type captures (2 vs 2.0) are semantic differences too
    def make_d(n):
        return lambda a, b, n=n: a + b * n

    m4j.custom_op("DEFCAP", make_d(2))
    with pytest.raises(ValueError, match="different"):
        m4j.custom_op("DEFCAP", make_d(3))
    with pytest.raises(ValueError, match="different"):
        m4j.custom_op("SCALED", make(2.0))


def test_custom_not_differentiable(mesh):
    x = jnp.arange(N * 2, dtype=jnp.float32)

    def loss(v):
        return m4j.spmd(
            lambda u: m4j.allreduce(u, op=absmax), mesh=mesh)(v).sum()

    # abs/max compose of jax primitives — grad works mechanically, but
    # the op itself advertises non-differentiability like every non-SUM
    # builtin; just assert the flag (the reference raises in its JVP for
    # non-SUM, allreduce.py:192-195 there)
    assert not absmax.differentiable
