"""Checkpoint layer: atomic commits, torn-save immunity, exotic-dtype
round-trips, and mismatch diagnostics.

Loads ``utils/checkpoint.py`` through a PRIVATE package shim (not the
real ``mpi4jax_tpu`` name), so these tests run — without orbax, and
regardless of the package's jax version gate — in any container, and
never perturb how other tests see the real package import.
"""

import importlib
import os
import subprocess
import sys
import types

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SHIM = "m4j_ckpt_shim"


def _checkpoint():
    if _SHIM not in sys.modules:
        pkg = types.ModuleType(_SHIM)
        pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
        sys.modules[_SHIM] = pkg
    return importlib.import_module(f"{_SHIM}.utils.checkpoint")


def _bf16():
    try:
        import ml_dtypes

        return ml_dtypes.bfloat16
    except ImportError:
        pytest.skip("ml_dtypes not installed")


def _tree(bf16):
    return {
        "w": np.arange(12, dtype=np.float32).reshape(3, 4),
        "nested": [np.ones((5,), bf16) * 1.5,
                   {"bias": np.float64(2.25),
                    "ints": np.arange(4, dtype=np.int64)}],
        "tup": (np.array(True), np.zeros((2, 0), np.float32)),
    }


def _assert_trees_equal(a, b):
    ck = _checkpoint()
    la, _ = ck._flatten(a)
    lb, _ = ck._flatten(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        assert x.dtype == y.dtype, (x.dtype, y.dtype)
        assert x.shape == y.shape
        assert np.array_equal(x.reshape(-1).view(np.uint8),
                              y.reshape(-1).view(np.uint8))


# ---- single-file API ------------------------------------------------


def test_roundtrip_bf16_and_nested_pytree_no_orbax(tmp_path):
    """The npz fallback round-trips bf16 leaves (numpy alone loses the
    dtype), nested dict/list/tuple structure, 0-d scalars, bools, and
    empty arrays — no orbax, no jax requirement."""
    ck = _checkpoint()
    tree = _tree(_bf16())
    path = str(tmp_path / "state.npz")  # force the orbax-less fallback
    ck.save(path, tree)
    out = ck.restore(path, like=tree)
    _assert_trees_equal(tree, out)
    assert isinstance(out["nested"][1], dict)
    assert isinstance(out["tup"], tuple)


def test_none_subtrees_and_jax_free_bf16_restore(tmp_path):
    """jax-parity details of the fallback paths: ``None`` is an empty
    subtree (not a leaf), and a bf16 checkpoint restores in a process
    that never imported jax/ml_dtypes (the dtype registry is pulled in
    lazily)."""
    ck = _checkpoint()
    tree = {"a": np.arange(3.0), "gap": None,
            "b": np.ones(2, _bf16())}
    path = str(tmp_path / "s.npz")
    ck.save(path, tree)
    out = ck.restore(path, like=tree)
    assert out["gap"] is None
    assert np.array_equal(out["a"], tree["a"])
    # restore in a fresh interpreter with jax BLOCKED and ml_dtypes
    # unimported: the module loads standalone (synthetic parent, the
    # obs/_recorder pattern — utils/__init__ itself imports jax), the
    # pure-python tree walk handles the None subtree, and the bf16
    # dtype name resolves through the lazy ml_dtypes import
    utils_dir = os.path.join(REPO, "mpi4jax_tpu", "utils")
    code = (
        "import importlib.util, os, sys, types\n"
        "import numpy as np\n"
        "assert 'ml_dtypes' not in sys.modules\n"
        "sys.modules['jax'] = None  # force the genuinely jax-free path\n"
        "parent = types.ModuleType('m4jutils')\n"
        f"parent.__path__ = [{utils_dir!r}]\n"
        "sys.modules['m4jutils'] = parent\n"
        "spec = importlib.util.spec_from_file_location(\n"
        f"    'm4jutils.checkpoint', os.path.join({utils_dir!r},\n"
        "    'checkpoint.py'))\n"
        "ck = importlib.util.module_from_spec(spec)\n"
        "sys.modules['m4jutils.checkpoint'] = ck\n"
        "spec.loader.exec_module(ck)\n"
        "like = {'a': np.zeros(3), 'gap': None, 'b': np.zeros(2)}\n"
        f"out = ck.restore({path!r}, like=like)\n"
        "assert out['gap'] is None\n"
        "assert out['b'].dtype.name == 'bfloat16', out['b'].dtype\n"
        "print('jaxfree-bf16-ok')\n"
    )
    res = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         capture_output=True, text=True, timeout=60)
    assert res.returncode == 0, res.stderr
    assert "jaxfree-bf16-ok" in res.stdout


def test_restore_mismatched_tree_errors(tmp_path):
    ck = _checkpoint()
    tree = {"a": np.zeros((2, 3), np.float32), "b": np.ones(4)}
    path = str(tmp_path / "s")
    ck.save(path, tree)
    with pytest.raises(ValueError, match="holds 2 leaves .* has 1"):
        ck.restore(path, like={"a": np.zeros((2, 3))})
    with pytest.raises(ValueError, match=r"leaf 0 has shape \(2, 3\)"):
        ck.restore(path, like={"a": np.zeros((9,)), "b": np.ones(4)})


def test_legacy_format1_files_still_read(tmp_path):
    """Files written by the pre-elastic checkpoint stub (plain leaf_<i>
    arrays, no descriptor) keep restoring."""
    ck = _checkpoint()
    path = str(tmp_path / "old.npz")
    np.savez(path, leaf_0=np.arange(3.0), leaf_1=np.ones((2, 2)))
    like = [np.zeros(3), np.zeros((2, 2))]
    out = ck.restore(path, like=like)
    assert np.array_equal(out[0], np.arange(3.0))


def test_kill_during_single_file_save_keeps_previous(tmp_path):
    """A process killed between writing the tmp payload and the atomic
    rename must leave the previous checkpoint byte-intact (the
    satellite fix: the stub wrote the target path directly)."""
    ck = _checkpoint()
    path = str(tmp_path / "state.npz")  # the atomic npz path under test
    v1 = {"a": np.arange(4.0)}
    ck.save(path, v1)
    code = (
        "import importlib, os, sys, types\n"
        "import numpy as np\n"
        f"pkg = types.ModuleType({_SHIM!r})\n"
        f"pkg.__path__ = [os.path.join({REPO!r}, 'mpi4jax_tpu')]\n"
        f"sys.modules[{_SHIM!r}] = pkg\n"
        f"ck = importlib.import_module('{_SHIM}.utils.checkpoint')\n"
        "os.replace = lambda *a: os._exit(9)  # the kill point\n"
        f"ck.save({path!r}, {{'a': np.full(4, 7.0)}})\n"
    )
    res = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         capture_output=True, text=True, timeout=60)
    assert res.returncode == 9, res.stderr
    out = ck.restore(path, like=v1)
    assert np.array_equal(out["a"], np.arange(4.0)), "previous " \
        "checkpoint was corrupted by the killed save"


# ---- sharded committed API ------------------------------------------


def test_sharded_roundtrip_and_generation_stamp(tmp_path, monkeypatch):
    ck = _checkpoint()
    monkeypatch.setenv("MPI4JAX_TPU_GENERATION", "3")
    tree = _tree(_bf16())
    d = ck.save_sharded(tree, step=7, directory=str(tmp_path))
    assert os.path.exists(os.path.join(d, "manifest.json"))
    out, step, manifest = ck.restore_sharded(tree,
                                             directory=str(tmp_path))
    assert step == 7
    assert manifest["generation"] == 3
    assert manifest["replicated"] is True
    _assert_trees_equal(tree, out)


def test_latest_step_ignores_uncommitted_directories(tmp_path):
    ck = _checkpoint()
    tree = {"a": np.arange(3.0)}
    ck.save_sharded(tree, step=4, directory=str(tmp_path))
    # an interrupted save: shard present, no manifest
    torn = ck.step_dir(str(tmp_path), 9)
    os.makedirs(torn)
    open(os.path.join(torn, "shard0of1.npz"), "wb").close()
    assert ck.committed_steps(str(tmp_path)) == [4]
    assert ck.latest_step(str(tmp_path)) == 4
    _, step, _ = ck.restore_sharded(tree, directory=str(tmp_path))
    assert step == 4


@pytest.mark.parametrize("crash_point", ["after_shard", "mid_commit"])
def test_kill_during_sharded_save_never_tears(tmp_path, crash_point):
    """A kill at EITHER seam of the commit protocol — before the
    manifest exists, or after its tmp file is written but before the
    rename — leaves the previous committed step fully restorable and
    the interrupted step invisible."""
    ck = _checkpoint()
    tree = {"a": np.arange(6.0), "b": np.ones((2, 2), np.float32)}
    ck.save_sharded(tree, step=2, directory=str(tmp_path))
    code = (
        "import importlib, os, sys, types\n"
        "import numpy as np\n"
        f"pkg = types.ModuleType({_SHIM!r})\n"
        f"pkg.__path__ = [os.path.join({REPO!r}, 'mpi4jax_tpu')]\n"
        f"sys.modules[{_SHIM!r}] = pkg\n"
        f"ck = importlib.import_module('{_SHIM}.utils.checkpoint')\n"
        "ck.save_sharded({'a': np.zeros(6), 'b': np.zeros((2, 2), "
        "np.float32)}, step=3, "
        f"directory={str(tmp_path)!r}, _crash_point={crash_point!r})\n"
    )
    res = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         capture_output=True, text=True, timeout=60)
    assert res.returncode == 137, res.stderr
    assert ck.latest_step(str(tmp_path)) == 2
    out, step, _ = ck.restore_sharded(tree, directory=str(tmp_path))
    assert step == 2
    _assert_trees_equal(tree, out)


def test_restore_onto_shrunk_world_requires_replicated(tmp_path):
    """Shard-count vs world-size mismatch: replicated checkpoints
    restore anywhere; truly sharded state refuses with an actionable
    message."""
    ck = _checkpoint()
    tree = {"a": np.arange(3.0)}

    class FakeComm:
        def __init__(self, rank, size):
            self._r, self._s = rank, size

        def rank(self):
            return self._r

        def size(self):
            return self._s

    # nshards=1 (saved single-process, replicated) -> restores at size 2
    ck.save_sharded(tree, step=1, directory=str(tmp_path / "rep"))
    out, _, _ = ck.restore_sharded(tree, directory=str(tmp_path / "rep"),
                                   comm=FakeComm(1, 2))
    _assert_trees_equal(tree, out)

    ck.save_sharded(tree, step=1, directory=str(tmp_path / "nonrep"),
                    replicated=False)
    with pytest.raises(ValueError, match="resharding is not"):
        ck.restore_sharded(tree, directory=str(tmp_path / "nonrep"),
                           comm=FakeComm(1, 2))
