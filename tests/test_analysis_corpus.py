"""Analyzer verdicts over the tests/world_programs/ corpus.

The known-good programs verify CLEAN and the known-bad ones produce the
expected finding kind — all through ``analysis.check_program`` (virtual
world: one thread per rank), with no processes spawned and no live
communication created.  These are the same programs the multi-process
world tier runs for real; the analyzer catches the bad ones in
milliseconds instead of a runtime deadline.
"""

import os

import pytest

try:
    import mpi4jax_tpu  # noqa: F401  (jax version gate)
    from mpi4jax_tpu import analysis
except Exception as err:  # pragma: no cover - old-jax containers
    pytest.skip(f"mpi4jax_tpu not importable here: {err}",
                allow_module_level=True)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROGRAMS = os.path.join(REPO, "tests", "world_programs")


def _check(name, np_, timeout_s=300):
    return analysis.check_program(
        os.path.join(PROGRAMS, name), np_, timeout_s=timeout_s)


# ---- known-good: full program runs with real values, zero findings ----

@pytest.mark.parametrize("name,np_", [
    ("basic_ops.py", 2),
    ("basic_ops.py", 3),
    ("subcomm_ops.py", 4),
])
def test_known_good_verifies_clean(name, np_):
    report = _check(name, np_)
    assert report.ok, report.format_table()
    # every rank communicated and the virtual world saw it
    assert all(len(v) > 0 for v in report.schedules.values())


def test_full_ops_verifies_clean():
    # dtype sweeps + autodiff + vmap + custom ops + quantized allreduce:
    # the virtual world must execute all of it with correct values
    report = _check("full_ops.py", 2)
    assert report.ok, report.format_table()


# ---- known-bad: expected finding kind, rank pair, equation named ------

def test_tag_mismatch_flagged():
    report = _check("tag_mismatch.py", 2)
    assert not report.ok
    f = next(f for f in report.findings if f.kind == "tag_mismatch")
    assert set(f.ranks) == {0, 1}
    assert any("tag_mismatch.py:" in s for s in f.sites), f.sites
    assert f.severity == "error"


def test_broken_chain_flags_token_violation():
    report = _check("broken_chain.py", 2)
    assert "token_violation" in report.kinds(), report.format_table()
    f = next(f for f in report.findings if f.kind == "token_violation")
    assert any("broken_chain.py:" in s for s in f.sites), f.sites


def test_ordering_order_critical_calibrated_to_engine(monkeypatch):
    # ordering.py's bidirectional raw send/recv exchange moves a few
    # bytes per message.  With the async progress engine on (the
    # default) such sends are detached buffered sends — they cannot
    # rendezvous-block, so the exchange is NOT order-critical and the
    # analyzer must no longer cry wolf about it.
    monkeypatch.delenv("MPI4JAX_TPU_PROGRESS_THREAD", raising=False)
    report = _check("ordering.py", 2)
    assert report.ok, report.format_table()

    # with the engine off, every send writes inline and the historic
    # conservative model applies: the same exchange IS order-critical
    monkeypatch.setenv("MPI4JAX_TPU_PROGRESS_THREAD", "0")
    report = _check("ordering.py", 2)
    assert not report.ok
    f = next(f for f in report.findings
             if f.kind == "order_critical_exchange")
    assert set(f.ranks) == {0, 1}
    assert any("ordering.py:" in s for s in f.sites), f.sites
    # and nothing ERROR-severity: the program does match
    assert not report.errors, report.format_table()


@pytest.mark.parametrize("mode,kind", [
    ("opcode", "collective_mismatch"),
    ("reduce_op", "reduce_op_mismatch"),
    ("dtype", "dtype_mismatch"),
])
def test_shm_schedule_mismatch_modes(mode, kind, monkeypatch):
    monkeypatch.setenv("MISMATCH_MODE", mode)
    report = _check("shm_schedule_mismatch.py", 2)
    assert kind in report.kinds(), report.format_table()
    f = next(f for f in report.findings if f.kind == kind)
    assert set(f.ranks) == {0, 1}


# ---- no processes, no live comm ---------------------------------------

def test_no_processes_and_no_native_comm(monkeypatch):
    """The virtual world must never touch the native transport or fork."""
    from mpi4jax_tpu.runtime import bridge

    def _boom(*a, **k):  # pragma: no cover - the assertion is the point
        raise AssertionError("analysis touched the native transport")

    monkeypatch.setattr(bridge, "get_lib", _boom)
    monkeypatch.setattr(bridge, "comm_init", _boom)
    import subprocess

    monkeypatch.setattr(subprocess, "Popen", _boom)
    report = _check("tag_mismatch.py", 2)
    assert "tag_mismatch" in report.kinds()
