"""Unit tests for the N-rank match simulation (analysis/_match.py).

Loaded standalone (no package import, no jax): the matcher is pure
Python by design, so these run — and the matching rules stay pinned —
even on hosts whose jax predates the package minimum.
"""

import importlib.util
import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "mpi4jax_tpu", "analysis")


def _load():
    """Load _events/_match standalone under a private package name."""
    if "m4j_an._match" in sys.modules:
        return sys.modules["m4j_an._events"], sys.modules["m4j_an._match"]
    pkg = types.ModuleType("m4j_an")
    pkg.__path__ = [PKG]
    sys.modules["m4j_an"] = pkg
    mods = {}
    for name in ("_events", "_match"):
        spec = importlib.util.spec_from_file_location(
            f"m4j_an.{name}", os.path.join(PKG, f"{name}.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[f"m4j_an.{name}"] = mod
        spec.loader.exec_module(mod)
        mods[name] = mod
    return mods["_events"], mods["_match"]


EV, MT = _load()
WORLD2 = {(0,): (0, 1)}


def _send(r, i, dest, tag=0, dtype="float32", shape=(4,)):
    return EV.CommEvent(r, i, "send", dest=dest, tag=tag, dtype=dtype,
                        shape=shape, site=f"prog.py:{10 + i}")


def _recv(r, i, source, tag=0, dtype="float32", shape=(4,)):
    return EV.CommEvent(r, i, "recv", source=source, tag=tag, dtype=dtype,
                        shape=shape, site=f"prog.py:{10 + i}")


def _coll(r, i, kind="allreduce", **kw):
    kw.setdefault("dtype", "float32")
    kw.setdefault("shape", (8,))
    if kind in ("allreduce", "reduce", "scan"):
        kw.setdefault("reduce_op", "SUM")
    return EV.CommEvent(r, i, kind, **kw)


def kinds(findings):
    return sorted({f.kind for f in findings})


def test_clean_pair_and_ring():
    out = MT.match_schedules(
        {0: [_send(0, 0, dest=1)], 1: [_recv(1, 0, source=0)]}, WORLD2)
    assert out == []
    world3 = {(0,): (0, 1, 2)}
    ring = {r: [EV.CommEvent(r, 0, "sendrecv", dest=(r + 1) % 3,
                             source=(r - 1) % 3, sendtag=0, recvtag=0,
                             dtype="f32", shape=(4,))]
            for r in range(3)}
    assert MT.match_schedules(ring, world3) == []


def test_tag_mismatch_names_rank_pair_and_sites():
    out = MT.match_schedules(
        {0: [_send(0, 0, dest=1, tag=5)],
         1: [_recv(1, 0, source=0, tag=7)]}, WORLD2)
    assert kinds(out) == ["tag_mismatch"]
    f = out[0]
    assert f.ranks == (0, 1)
    assert len(f.sites) == 2 and "prog.py:10" in f.sites[0]
    assert f.severity == "error"


def test_dtype_and_shape_mismatch():
    out = MT.match_schedules(
        {0: [_send(0, 0, dest=1, dtype="float32")],
         1: [_recv(1, 0, source=0, dtype="int32")]}, WORLD2)
    assert kinds(out) == ["dtype_mismatch"]
    out = MT.match_schedules(
        {0: [_send(0, 0, dest=1, shape=(4,))],
         1: [_recv(1, 0, source=0, shape=(8,))]}, WORLD2)
    assert kinds(out) == ["shape_mismatch"]


def test_collective_divergence_kinds():
    out = MT.match_schedules(
        {0: [_coll(0, 0, "allreduce")],
         1: [_coll(1, 0, "bcast", root=1)]}, WORLD2)
    assert kinds(out) == ["collective_mismatch"]
    out = MT.match_schedules(
        {0: [_coll(0, 0, reduce_op="SUM")],
         1: [_coll(1, 0, reduce_op="MAX")]}, WORLD2)
    assert kinds(out) == ["reduce_op_mismatch"]
    out = MT.match_schedules(
        {0: [_coll(0, 0, "bcast", root=0)],
         1: [_coll(1, 0, "bcast", root=1)]}, WORLD2)
    assert kinds(out) == ["root_mismatch"]


def test_deadlock_cycle_detected():
    out = MT.match_schedules(
        {0: [_recv(0, 0, source=1), _send(0, 1, dest=1)],
         1: [_recv(1, 0, source=0), _send(1, 1, dest=0)]}, WORLD2)
    assert "deadlock" in kinds(out)
    dead = next(f for f in out if f.kind == "deadlock")
    assert set(dead.ranks) == {0, 1}


def test_unmatched_send_and_recv():
    out = MT.match_schedules(
        {0: [_send(0, 0, dest=1)], 1: []}, WORLD2)
    assert kinds(out) == ["unmatched_send"]
    out = MT.match_schedules(
        {0: [], 1: [_recv(1, 0, source=0)]}, WORLD2)
    assert kinds(out) == ["unmatched_recv"]


def test_wildcard_starvation_and_scan_skip():
    any_src = EV.ANY_SOURCE
    out = MT.match_schedules(
        {0: [_send(0, 0, dest=1, tag=3)],
         1: [_recv(1, 0, source=any_src, tag=3),
             _recv(1, 1, source=any_src, tag=3)]}, WORLD2)
    assert kinds(out) == ["wildcard_starvation"]
    # a concrete-tag wildcard must skip an incompatible head and match
    # the compatible peer (transport regression: wildcard_recv.py §4)
    world3 = {(0,): (0, 1, 2)}
    out = MT.match_schedules(
        {0: [_send(0, 0, dest=2, tag=7)],
         1: [_send(1, 0, dest=2, tag=5)],
         2: [_recv(2, 0, source=any_src, tag=5),
             _recv(2, 1, source=any_src, tag=7)]}, world3)
    assert out == []


BIG = (64 * 1024,)  # f32[64Ki] = 256 KB: above any detach threshold


def test_order_critical_exchange_fires_only_on_blocking_cycles():
    # bidirectional raw send/recv with payloads past the buffered-send
    # threshold -> warning (both directions can rendezvous-block)
    out = MT.match_schedules(
        {0: [_send(0, 0, dest=1, shape=BIG), _recv(0, 1, source=1, shape=BIG)],
         1: [_recv(1, 0, source=0, shape=BIG), _send(1, 1, dest=0, shape=BIG)]},
        WORLD2)
    assert kinds(out) == ["order_critical_exchange"]
    assert out[0].severity == "warning"
    # one-directional traffic stays clean (basic_ops shape)
    out = MT.match_schedules(
        {0: [_send(0, 0, dest=1)], 1: [_recv(1, 0, source=0)]}, WORLD2)
    assert out == []


def test_order_critical_exchange_respects_buffered_send_threshold():
    # with the async progress engine on (the default), sends at or below
    # max(32 KB, MPI4JAX_TPU_COALESCE_BYTES) are detached buffered sends:
    # a small bidirectional exchange cannot rendezvous-block and is no
    # longer flagged (PR 5 made the match model's buffering real)
    small = {0: [_send(0, 0, dest=1), _recv(0, 1, source=1)],
             1: [_recv(1, 0, source=0), _send(1, 1, dest=0)]}
    assert MT.match_schedules(small, WORLD2) == []
    # one small direction alone already breaks the cycle
    mixed = {0: [_send(0, 0, dest=1, shape=BIG),
                 _recv(0, 1, source=1)],
             1: [_recv(1, 0, source=0, shape=BIG), _send(1, 1, dest=0)]}
    assert MT.match_schedules(mixed, WORLD2) == []
    # explicit threshold 0 restores the historic conservative model
    # (the engine-off MPI4JAX_TPU_PROGRESS_THREAD=0 behavior)
    out = MT.order_critical_findings(
        {r: list(v) for r, v in small.items()}, WORLD2,
        detach_threshold=0)
    assert kinds(out) == ["order_critical_exchange"]
    # unknown payload sizes stay conservative
    unk = {0: [EV.CommEvent(0, 0, "send", dest=1, tag=0),
               EV.CommEvent(0, 1, "recv", source=1, tag=0)],
           1: [EV.CommEvent(1, 0, "recv", source=0, tag=0),
               EV.CommEvent(1, 1, "send", dest=0, tag=0)]}
    assert "order_critical_exchange" in kinds(MT.match_schedules(unk, WORLD2))
    # a small FIRST send must not mask a later above-threshold send on
    # the same direction: ANY blocking send per direction counts
    masked = {0: [_send(0, 0, dest=1), _recv(0, 1, source=1),
                  _send(0, 2, dest=1, shape=BIG),
                  _recv(0, 3, source=1, shape=BIG)],
              1: [_recv(1, 0, source=0), _send(1, 1, dest=0),
                  _recv(1, 2, source=0, shape=BIG),
                  _send(1, 3, dest=0, shape=BIG)]}
    assert "order_critical_exchange" in \
        kinds(MT.match_schedules(masked, WORLD2))


def test_collective_straggler():
    out = MT.match_schedules(
        {0: [_coll(0, 0)], 1: []}, WORLD2)
    assert kinds(out) == ["collective_mismatch"]
    f = out[0]
    assert 0 in f.ranks and 1 in f.ranks


def test_subcomm_local_rank_translation():
    # comm (0, 1, 0) has members (world 2, world 3); local 0 <-> world 2
    comms = {(0,): (0, 1, 2, 3), (0, 1, 0): (2, 3)}
    sub = (0, 1, 0)
    out = MT.match_schedules(
        {0: [], 1: [],
         2: [EV.CommEvent(2, 0, "send", comm=sub, dest=1, tag=0,
                          dtype="f32", shape=(2,))],
         3: [EV.CommEvent(3, 0, "recv", comm=sub, source=0, tag=0,
                          dtype="f32", shape=(2,))]}, comms)
    assert out == []


def test_report_json_round_trip():
    out = MT.match_schedules(
        {0: [_send(0, 0, dest=1, tag=5)],
         1: [_recv(1, 0, source=0, tag=7)]}, WORLD2)
    rep = EV.Report(world_size=2, target="prog.py", findings=out)
    data = rep.to_json()
    assert data["ok"] is False
    assert data["findings"][0]["kind"] == "tag_mismatch"
    assert data["findings"][0]["ranks"] == [0, 1]
    table = rep.format_table()
    assert "tag_mismatch" in table and "prog.py:10" in table
