"""Infra-layer tests (reference parity: test_validation / test_jax_compat /
test_has_cuda / flush, SURVEY.md §2.6)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mpi4jax_tpu as m4j
from mpi4jax_tpu.utils import config, dtypes, jax_compat, validation


def test_config_truthiness():
    assert config.parse_bool("1") and config.parse_bool("TRUE")
    assert not config.parse_bool("0") and not config.parse_bool("off")
    with pytest.raises(ValueError):
        config.parse_bool("maybe", name="X")


def test_flag_env(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_DEBUG", "yes")
    assert config.debug_enabled()
    monkeypatch.setenv("MPI4JAX_TPU_DEBUG", "0")
    assert not config.debug_enabled()


def test_dtype_wire_codes_unique_and_supported():
    codes = [dtypes.wire_code(d) for d in dtypes.SUPPORTED_DTYPES]
    assert len(set(codes)) == len(codes)
    assert dtypes.wire_code(jnp.bfloat16) == 10  # native/tpucomm.h contract
    with pytest.raises(TypeError):
        dtypes.wire_code(np.dtype("datetime64[s]"))


def test_validation_static_int():
    assert validation.check_static_int("root", np.int64(3)) == 3
    with pytest.raises(TypeError, match="integer"):
        validation.check_static_int("root", 1.5)
    with pytest.raises(TypeError, match="bool"):
        validation.check_static_int("root", True)


def test_validation_range():
    with pytest.raises(TypeError, match="out of range"):
        validation.check_in_range("dest", 9, 4)


def test_jax_version_parse():
    assert jax_compat._parse("0.9.0") == (0, 9, 0)
    assert jax_compat._parse("0.10.1.dev2") >= (0, 10, 1)


def test_reduce_op_coercion():
    assert m4j.as_reduce_op("sum") is m4j.SUM
    assert m4j.as_reduce_op(m4j.MAX) is m4j.MAX
    with pytest.raises(TypeError):
        m4j.as_reduce_op(42)


def test_reduce_op_dtype_domains():
    with pytest.raises(TypeError):
        m4j.BAND.check_dtype(jnp.float32)
    m4j.BAND.check_dtype(jnp.uint8)
    m4j.LAND.check_dtype(jnp.bool_)
    with pytest.raises(TypeError):
        m4j.SUM.check_dtype(jnp.bool_)


def test_has_ici_support_runs():
    assert isinstance(m4j.has_ici_support(), bool)


def test_flush_runs():
    # the atexit barrier must be callable at any time
    from mpi4jax_tpu import _flush

    _flush()


def test_comm_context_stack():
    comm = m4j.MeshComm("foo")
    assert m4j.current_comm() is None
    with comm:
        assert m4j.current_comm() is comm
        inner = m4j.MeshComm("bar")
        with inner:
            assert m4j.current_comm() is inner
        assert m4j.current_comm() is comm
    assert m4j.current_comm() is None


def test_mesh_comm_hashable():
    a, b = m4j.MeshComm("x"), m4j.MeshComm("x")
    assert a == b and hash(a) == hash(b)
    assert m4j.MeshComm(("x", "y")) != a


def test_explicit_token_ordering_is_in_jit_cache_key():
    # the ordering mode is a jax config state in the jit cache key: a
    # function traced in one mode must retrace (not silently reuse the
    # cached program) when called in the other
    from mpi4jax_tpu.ops import _world_impl

    traces = []

    @jax.jit
    def f(x):
        traces.append(_world_impl._ordered_now())
        return x + 1

    f(jnp.zeros(2))
    with m4j.explicit_token_ordering():
        assert not _world_impl._ordered_now()
        f(jnp.zeros(2))
    assert _world_impl._ordered_now()
    f(jnp.zeros(2))  # cached ordered trace — no third retrace
    assert traces == [True, False]


def test_explicit_token_ordering_effect_selection():
    # primitives bind the unordered effect inside the context, ordered
    # outside — checked at the jaxpr level, no transport needed
    from mpi4jax_tpu.ops import _world_impl
    from mpi4jax_tpu.runtime.transport import WorldComm
    from mpi4jax_tpu.utils.effects import (
        comm_effect, unordered_comm_effect,
    )

    comm = WorldComm(rank=0, size=2, coord="127.0.0.1:45999")

    def prog(x):
        return _world_impl.allreduce(x, m4j.SUM, comm)

    ordered_jaxpr = jax.make_jaxpr(prog)(jnp.zeros(2))
    assert comm_effect in ordered_jaxpr.effects
    with m4j.explicit_token_ordering():
        unordered_jaxpr = jax.make_jaxpr(prog)(jnp.zeros(2))
    assert unordered_comm_effect in unordered_jaxpr.effects
    assert comm_effect not in unordered_jaxpr.effects
