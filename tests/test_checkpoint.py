"""Checkpoint save/restore roundtrip, including a model-state resume."""

import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4j
from mpi4jax_tpu.utils import checkpoint


def test_roundtrip(tmp_path):
    tree = {
        "a": jnp.arange(6.0).reshape(2, 3),
        "nested": [jnp.ones((4,), jnp.int32), {"b": jnp.float32(3.5)}],
    }
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, tree)
    out = checkpoint.restore(path, like=tree)
    for a, b in zip(
        __import__("jax").tree.leaves(tree), __import__("jax").tree.leaves(out)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_solver_resume(tmp_path):
    # checkpoint mid-run, resume, and match the uninterrupted trajectory
    import jax

    from mpi4jax_tpu.models.shallow_water import ShallowWater, SWParams
    from mpi4jax_tpu.parallel.grid import ProcessGrid

    grid = ProcessGrid((2, 4))
    model = ShallowWater(grid, (16, 32), SWParams(dx=5e3, dy=5e3))
    s0 = model.init()
    step = model.step_fn(5, first=True)
    cont = model.step_fn(5, first=False)

    mid = step(s0)
    full = cont(mid)

    path = str(tmp_path / "sw")
    checkpoint.save(path, mid._asdict())
    restored = type(mid)(**checkpoint.restore(path, like=mid._asdict()))
    resumed = cont(restored)
    np.testing.assert_allclose(
        model.interior(resumed.h), model.interior(full.h), rtol=1e-6
    )
