"""Fused Pallas shallow-water step vs the XLA slice-stencil step.

Same stencils, same boundary-mask ordering — results must agree to f32
reassociation tolerance, bootstrap (Euler) step included.  Runs the
kernel under the Pallas TPU interpreter on the CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi4jax_tpu.models.shallow_water import ShallowWater, SWParams
from mpi4jax_tpu.parallel.grid import ProcessGrid


def _model(ny=32, nx=64):
    grid = ProcessGrid((1, 1), devices=jax.devices()[:1])
    return ShallowWater(grid, (ny, nx), SWParams(dx=5e3, dy=5e3))


def _advance(model, impl, n_steps, **kw):
    state = model.init()
    state = model.step_fn(1, first=True, impl=impl, **kw)(state)
    if n_steps > 1:
        state = model.step_fn(n_steps - 1, first=False, impl=impl, **kw)(
            state)
    return state


@pytest.mark.parametrize("n_steps", [1, 12])
def test_fused_step_matches_xla(n_steps):
    model = _model()
    ref = _advance(model, "xla", n_steps)
    got = _advance(model, "pallas", n_steps)
    for name, a, b in zip(ref._fields, got, ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6,
            err_msg=f"field {name} after {n_steps} steps",
        )


def test_fused_step_tile_edge_cases():
    """Domain heights that are not multiples of the row tile, and domains
    smaller than one window, still match."""
    for ny, nx in [(16, 32), (22, 32), (48, 32)]:
        model = _model(ny, nx)
        ref = _advance(model, "xla", 3)
        got = _advance(model, "pallas", 3)
        for a, b in zip(got, ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6,
                err_msg=f"domain ({ny},{nx})",
            )


@pytest.mark.parametrize("tile_rows,fuse", [(16, 1), (16, 2), (32, 2),
                                            (24, 3)])
def test_fused_step_multi_tile(tile_rows, fuse):
    """Force ntiles >= 2 so the clamped interior halo index maps and the
    cross-tile halo consistency under temporal blocking actually run (the
    tuned defaults pad the small CI domains into a single tile)."""
    model = _model(ny=70, nx=32)  # nyp=72 -> >= 3 tiles at T=16/32
    ref = _advance(model, "xla", 7)
    got = _advance(model, "pallas", 7, tile_rows=tile_rows, fuse=fuse)
    for name, a, b in zip(ref._fields, got, ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6,
            err_msg=f"field {name} tile_rows={tile_rows} fuse={fuse}",
        )


def test_fused_step_conserves_mass():
    model = _model()
    s0 = model.init()
    s1 = model.step_fn(1, first=True, impl="pallas")(s0)
    s1 = model.step_fn(20, first=False, impl="pallas")(s1)
    m0 = float(jnp.sum(model.interior(s0.h)))
    m1 = float(jnp.sum(model.interior(s1.h)))
    assert abs(m1 - m0) / abs(m0) < 1e-5


def test_pallas_impl_rejects_decomposed_grid():
    grid = ProcessGrid((2, 4))
    model = ShallowWater(grid, (16, 32), SWParams(dx=5e3, dy=5e3))
    with pytest.raises(ValueError, match="1x1 grid"):
        model.step_fn(1, impl="pallas")
