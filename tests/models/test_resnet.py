"""DP residual CNN: trains, and DP run matches single-device numerics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m4j
from mpi4jax_tpu.models import resnet

CFG = resnet.ResNetConfig(
    stages=(1, 1), widths=(8, 16), n_classes=4, in_channels=3, groups=4,
)
N = 8
B, HW = 16, 8


def data():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(B, HW, HW, 3).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 4, (B,)).astype(np.int32))
    return x, y


def test_dp_training_reduces_loss():
    mesh = m4j.make_mesh(N)
    params = resnet.init_params(CFG, seed=0)
    step = resnet.make_dp_train_step(CFG, mesh, lr=0.05)
    x, y = data()
    losses = []
    for _ in range(6):
        loss, params = step(params, x, y)
        losses.append(float(loss))
    assert np.all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


def test_dp_matches_single_device():
    x, y = data()
    params = resnet.init_params(CFG, seed=0)

    mesh8 = m4j.make_mesh(N)
    step8 = resnet.make_dp_train_step(CFG, mesh8, lr=0.05)
    l8, p8 = step8(params, x, y)

    mesh1 = m4j.make_mesh(1, devices=jax.devices()[:1])
    step1 = resnet.make_dp_train_step(CFG, mesh1, lr=0.05)
    l1, p1 = step1(params, x, y)

    np.testing.assert_allclose(float(l8), float(l1), rtol=1e-5)
    flat8 = jax.tree.leaves(p8)
    flat1 = jax.tree.leaves(p1)
    for a, b in zip(flat8, flat1):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        )


def test_imagenet_stem_trains():
    # the downsampling stem (7x7/2 conv + 3x3/2 avg pool): forward shape
    # halves twice before stage 1, and the pool's backward is exercised
    cfg = resnet.ResNetConfig(
        stages=(1,), widths=(8,), n_classes=3, groups=4, stem="imagenet"
    )
    mesh = m4j.make_mesh(1, devices=jax.devices()[:1])
    params = resnet.init_params(cfg, seed=0)
    assert params["stem"].shape[:2] == (7, 7)
    step = resnet.make_dp_train_step(cfg, mesh, lr=0.05)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(4, 32, 32, 3).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 3, (4,)).astype(np.int32))
    losses = []
    for _ in range(4):
        loss, params = step(params, x, y)
        losses.append(float(loss))
    assert np.all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


def test_bottleneck_resnet50_family_trains():
    # the BASELINE-named family: bottleneck blocks with 4x expansion
    # (resnet50_config() = stages (3,4,6,3); here a 2-stage miniature —
    # same block math, test-sized)
    cfg = resnet.ResNetConfig(
        stages=(1, 1), widths=(8, 16), n_classes=3, groups=4,
        block="bottleneck",
    )
    mesh = m4j.make_mesh(1, devices=jax.devices()[:1])
    params = resnet.init_params(cfg, seed=0)
    # 1x1 reduce / 3x3 / 1x1 expand + projection on the widened skip
    blk = params["stages"][0][0]
    assert blk["conv1"].shape[:2] == (1, 1)
    assert blk["conv3"].shape == (1, 1, 8, 32)
    assert blk["proj"].shape == (1, 1, 8, 32)
    assert params["head"].shape[0] == 16 * 4
    step = resnet.make_dp_train_step(cfg, mesh, lr=0.05)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(4, 16, 16, 3).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 3, (4,)).astype(np.int32))
    losses = []
    for _ in range(4):
        loss, params = step(params, x, y)
        losses.append(float(loss))
    assert np.all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses
    # the canonical config is the real ResNet-50 shape
    full = resnet.resnet50_config()
    assert full.stages == (3, 4, 6, 3) and full.block == "bottleneck"


def test_bf16_compute_close_to_f32():
    cfg32 = resnet.ResNetConfig(
        stages=(1,), widths=(8,), n_classes=3, groups=4, stem="small"
    )
    cfg16 = cfg32._replace(dtype="bfloat16")
    params = resnet.init_params(cfg32, seed=0)
    rng = np.random.RandomState(2)
    # scale the head so logits are O(1) (groupnorm washes out input
    # scale): a vacuous tolerance would otherwise pass even if the
    # bf16 path returned zeros
    params = dict(params, head=params["head"] * 100.0)
    x = jnp.asarray(rng.randn(2, 8, 8, 3).astype(np.float32))
    a = np.asarray(resnet.forward(params, x, cfg32))
    b = np.asarray(resnet.forward(params, x, cfg16))
    assert np.abs(a).max() > 0.1, a
    np.testing.assert_allclose(a, b, rtol=0.15, atol=0.05 * np.abs(a).max())
