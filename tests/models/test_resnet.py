"""DP residual CNN: trains, and DP run matches single-device numerics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m4j
from mpi4jax_tpu.models import resnet

CFG = resnet.ResNetConfig(
    stages=(1, 1), widths=(8, 16), n_classes=4, in_channels=3, groups=4
)
N = 8
B, HW = 16, 8


def data():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(B, HW, HW, 3).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 4, (B,)).astype(np.int32))
    return x, y


def test_dp_training_reduces_loss():
    mesh = m4j.make_mesh(N)
    params = resnet.init_params(CFG, seed=0)
    step = resnet.make_dp_train_step(CFG, mesh, lr=0.05)
    x, y = data()
    losses = []
    for _ in range(6):
        loss, params = step(params, x, y)
        losses.append(float(loss))
    assert np.all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


def test_dp_matches_single_device():
    x, y = data()
    params = resnet.init_params(CFG, seed=0)

    mesh8 = m4j.make_mesh(N)
    step8 = resnet.make_dp_train_step(CFG, mesh8, lr=0.05)
    l8, p8 = step8(params, x, y)

    mesh1 = m4j.make_mesh(1, devices=jax.devices()[:1])
    step1 = resnet.make_dp_train_step(CFG, mesh1, lr=0.05)
    l1, p1 = step1(params, x, y)

    np.testing.assert_allclose(float(l8), float(l1), rtol=1e-5)
    flat8 = jax.tree.leaves(p8)
    flat1 = jax.tree.leaves(p1)
    for a, b in zip(flat8, flat1):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        )
