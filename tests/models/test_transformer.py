"""GPT with dp x tp x sp: trains, and the decomposed run matches 1-device."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import mpi4jax_tpu as m4j
from mpi4jax_tpu.models.transformer import GPT, GPTConfig, init_params

CFG = GPTConfig(
    vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=32
)
B, T = 4, 32


def make_model(shape):
    n = int(np.prod(shape))
    devices = np.array(jax.devices()[:n]).reshape(shape)
    mesh = Mesh(devices, ("dp", "tp", "sp"))
    model = GPT(CFG, mesh)
    params = init_params(CFG, tp=shape[1], seed=0)
    opt_state = model.init_opt_state(params)
    step = model.train_step_fn(opt_state)
    return model, params, opt_state, step


def tokens():
    rng = np.random.RandomState(0)
    return jnp.asarray(rng.randint(0, CFG.vocab, (B, T)).astype(np.int32))


def test_training_reduces_loss():
    _, params, opt_state, step = make_model((2, 2, 2))
    toks = tokens()
    losses = []
    for _ in range(8):
        loss, params, opt_state = step(params, opt_state, toks)
        losses.append(float(loss))
    assert np.all(np.isfinite(losses))
    assert losses[-1] < losses[0] - 0.1, losses


@pytest.mark.parametrize("shape", [(2, 2, 2), (1, 4, 2), (2, 1, 4)])
def test_decomposition_invariance(shape):
    # the same data + params must give the same first-step loss on any mesh
    _, p1, s1, step1 = make_model((1, 1, 1))
    toks = tokens()
    l_ref, p1b, _ = step1(p1, s1, toks)

    modelN, pN, sN, stepN = make_model(shape)
    # tp-sharded weights were initialized with the same global values only
    # when tp matches; regenerate the 1-dev model with matching tp blocks
    if shape[1] != 1:
        from mpi4jax_tpu.models.transformer import GPTParams, TP_FIELDS

        # reshape tp=1 params into tp=k blocks (same underlying values)
        def reblock(f, arr):
            if f not in TP_FIELDS:
                return arr
            tp = shape[1]
            full = arr[:, 0]
            if f == "w_qkv":
                # last dim layout is (3, heads, head_dim): split by heads
                L, d, _ = full.shape
                h, hd = CFG.n_heads, CFG.d_model // CFG.n_heads
                w = full.reshape(L, d, 3, h, hd)
                blocks = jnp.split(w, tp, axis=3)
                return jnp.stack(
                    [b.reshape(L, d, 3 * (h // tp) * hd) for b in blocks],
                    axis=1,
                )
            if f in ("w1", "b1"):  # column-sharded: split last (ff) dim
                return jnp.stack(jnp.split(full, tp, axis=-1), axis=1)
            # w_o / w2: row-sharded — split the first feature dim
            return jnp.stack(jnp.split(full, tp, axis=1), axis=1)

        pN = GPTParams(
            **{f: reblock(f, getattr(p1, f)) for f in GPTParams._fields}
        )
        sN = modelN.init_opt_state(pN)
    else:
        pN = p1
        sN = modelN.init_opt_state(pN)

    l_N, _, _ = stepN(pN, sN, toks)
    np.testing.assert_allclose(float(l_N), float(l_ref), rtol=2e-4)


def test_qkv_tp_split_is_consistent():
    # sanity: with tp>1 the column split of w_qkv must keep q/k/v blocks per
    # head group; n_heads % tp == 0 enforced
    with pytest.raises(ValueError):
        init_params(GPTConfig(n_heads=3), tp=2)
