"""Shallow-water solver: stability, conservation, and — the strongest
correctness check — bitwise-comparable results between a 1-device and an
8-device decomposition of the same problem."""

import jax.numpy as jnp
import numpy as np
import pytest

import jax
import mpi4jax_tpu as m4j
from mpi4jax_tpu.models.shallow_water import ShallowWater, SWParams
from mpi4jax_tpu.parallel.grid import ProcessGrid

NY, NX = 24, 48
PARAMS = SWParams(dx=5e3, dy=5e3)


def run_model(grid_shape, n_steps):
    n = int(np.prod(grid_shape))
    grid = ProcessGrid(grid_shape, devices=jax.devices()[:n])
    model = ShallowWater(grid, (NY, NX), PARAMS)
    state = model.init()
    state = model.step_fn(n_steps, first=True)(state)
    return model, state


def test_finite_and_nontrivial():
    model, state = run_model((2, 4), 10)
    h = model.interior(state.h)
    assert np.all(np.isfinite(h))
    assert h.std() > 0  # jet + perturbation evolve


def test_mass_conservation():
    model, state0 = run_model((2, 4), 0)
    m0 = model.total_mass(state0)
    state = model.step_fn(20, first=True)(state0)
    m1 = model.total_mass(state)
    assert abs(m1 - m0) / abs(m0) < 1e-5


def test_decomposition_invariance():
    # 1 device vs 8 devices must produce the same trajectory
    model1, s1 = run_model((1, 1), 10)
    model8, s8 = run_model((2, 4), 10)
    h1 = model1.interior(s1.h)
    h8 = model8.interior(s8.h)
    np.testing.assert_allclose(h1, h8, rtol=2e-5, atol=2e-5)
    u1 = model1.interior(s1.u)
    u8 = model8.interior(s8.u)
    np.testing.assert_allclose(u1, u8, rtol=2e-4, atol=2e-4)


def test_longer_run_stable():
    model, state = run_model((2, 4), 100)
    h = model.interior(state.h)
    assert np.all(np.isfinite(h))
    # surface stays within physically plausible bounds around DEPTH=100
    assert 50 < h.mean() < 150
