"""Distributed FFT/Poisson vs numpy ground truth."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import mpi4jax_tpu as m4j
from mpi4jax_tpu.models import spectral

N = 8
X, Y, Z = 16, 16, 8


@pytest.fixture(scope="module")
def mesh():
    return m4j.make_mesh(N, axis="fft")


def _sharded(fn, mesh, x, out_dim=0):
    return jax.jit(
        jax.shard_map(
            fn, mesh=mesh, in_specs=P("fft"), out_specs=P("fft"),
            check_vma=False,
        )
    )(x)


def test_fft3_roundtrip(mesh):
    rng = np.random.RandomState(0)
    f = rng.randn(X, Y, Z).astype(np.float32)

    def roundtrip(local):
        s = spectral.fft3(local, axis="fft")
        return spectral.ifft3(s, axis="fft").real

    out = _sharded(roundtrip, mesh, jnp.asarray(f))
    np.testing.assert_allclose(np.asarray(out), f, rtol=1e-4, atol=1e-4)


def test_fft3_matches_numpy(mesh):
    rng = np.random.RandomState(1)
    f = rng.randn(X, Y, Z).astype(np.float32)
    expected = np.fft.fftn(f)  # (X, Y, Z)

    def fwd(local):
        # output (X, Y_local, Z) y-sharded; out_specs P("fft") concats on
        # dim 0 → we transpose so the sharded dim leads
        s = spectral.fft3(local, axis="fft")
        return s.transpose(1, 0, 2)  # (Y_local, X, Z)

    out = _sharded(fwd, mesh, jnp.asarray(f))  # (Y, X, Z)
    got = np.asarray(out).transpose(1, 0, 2)
    np.testing.assert_allclose(got, expected, rtol=2e-3, atol=2e-2)


def test_poisson(mesh):
    # manufactured solution: u = sin(x)cos(2y)sin(z); f = ∇²u = -(1+4+1) u
    nx, ny, nz = X, Y, Z
    xs = np.linspace(0, 2 * np.pi, nx, endpoint=False)
    ys = np.linspace(0, 2 * np.pi, ny, endpoint=False)
    zs = np.linspace(0, 2 * np.pi, nz, endpoint=False)
    xx, yy, zz = np.meshgrid(xs, ys, zs, indexing="ij")
    u_true = np.sin(xx) * np.cos(2 * yy) * np.sin(zz)
    f = -6.0 * u_true

    def solve(local):
        return spectral.poisson_solve(
            local, axis="fft", shape=(nx, ny, nz)
        )

    u = _sharded(solve, mesh, jnp.asarray(f.astype(np.float32)))
    np.testing.assert_allclose(
        np.asarray(u), u_true, rtol=1e-3, atol=1e-3
    )
