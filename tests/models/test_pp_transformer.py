"""Pipelined GPT: matches the unpipelined forward and trains."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import mpi4jax_tpu as m4j
from mpi4jax_tpu.models import pp_transformer as ppm
from mpi4jax_tpu.models.transformer import GPTConfig, _layernorm

CFG = GPTConfig(
    vocab=32, d_model=16, n_heads=4, n_layers=4, d_ff=32, max_seq=16
)
M, Bmb, T = 3, 2, 16  # microbatches


def dense_loss(params, tokens, targets, mask):
    """Reference forward with the same weights, no pipeline."""
    x = params.wte[tokens] + params.wpe[:T][None]
    pp, ls = params.w_qkv.shape[:2]
    for s in range(pp):
        for l in range(ls):
            layer = tuple(
                getattr(params, f)[s, l]
                for f in ("ln1", "ln2", "w_qkv", "w_o", "w1", "b1", "w2",
                          "b2")
            )
            l1, l2, wq, wo, a1, c1, a2, c2 = layer
            y = ppm._causal_attention(_layernorm(x, l1), wq, wo, CFG.n_heads)
            x = x + y
            h = jax.nn.gelu(_layernorm(x, l2) @ a1 + c1)
            x = x + (h @ a2 + c2)
    logits = _layernorm(x, params.lnf) @ params.wte.T
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.sum(nll * mask) / jnp.sum(mask)


def toks():
    rng = np.random.RandomState(0)
    return jnp.asarray(rng.randint(0, CFG.vocab, (M, Bmb, T)).astype(np.int32))


def make(pp):
    mesh = Mesh(np.array(jax.devices()[:pp]).reshape(pp), ("pp",))
    model = ppm.PPGPT(CFG, mesh)
    params = ppm.init_params(CFG, pp=pp, seed=0)
    return model, params


@pytest.mark.parametrize("pp", [4, 2, 1])
def test_pp_loss_matches_dense(pp):
    model, params = make(pp)
    step = model.train_step_fn(lr=0.0)
    tokens = toks()
    loss, _ = step(params, tokens)

    targets = jnp.concatenate(
        [tokens[..., 1:], jnp.zeros_like(tokens[..., :1])], axis=-1
    )
    mask = jnp.concatenate(
        [jnp.ones(tokens[..., 1:].shape, jnp.float32),
         jnp.zeros(tokens[..., :1].shape, jnp.float32)], axis=-1,
    )
    # flatten microbatches for the dense reference
    expected = dense_loss(
        params,
        tokens.reshape(M * Bmb, T),
        targets.reshape(M * Bmb, T),
        mask.reshape(M * Bmb, T),
    )
    np.testing.assert_allclose(float(loss), float(expected), rtol=2e-5)


def test_pp_training_reduces_loss():
    model, params = make(4)
    step = model.train_step_fn(lr=0.1)
    tokens = toks()
    losses = []
    for _ in range(6):
        loss, params = step(params, tokens)
        losses.append(float(loss))
    assert np.all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses
