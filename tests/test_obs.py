"""Observability subsystem (mpi4jax_tpu/obs): recorder ring semantics,
numpy-compatible percentiles, clock-offset merge ordering, the Chrome
trace schema, the profile CLI, the tuner's --from-trace backend, and —
against the real native transport on a size-1 loopback comm (no
sockets) — the event ring's overflow accounting and the test-enforced
guarantee that a disabled recorder performs NO ring writes.

Everything here runs under CPU-only tier-1: the pure-Python half is
loaded standalone (the package __init__ gates on the jax version; the
obs package is documented stdlib-importable), and the native half
drives a transport-only build of tpucomm.cc through ctypes directly.
"""

import ctypes
import importlib.util
import json
import os
import pathlib
import shutil
import subprocess
import sys

import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parents[1]


def _load_pkg(name, init_path, search_dir):
    spec = importlib.util.spec_from_file_location(
        name, init_path, submodule_search_locations=[str(search_dir)])
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _load_obs():
    try:
        from mpi4jax_tpu import obs

        return obs
    except ImportError:
        return _load_pkg("m4j_obs_test", REPO / "mpi4jax_tpu/obs/__init__.py",
                         REPO / "mpi4jax_tpu/obs")


def _load_file(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


obs = _load_obs()


def _ev(name, ts_us, dur_us=5.0, wait_us=1.0, nbytes=64, peer=-1, tag=0,
        algo=None, src="native"):
    return {"name": name, "src": src, "ts_us": float(ts_us),
            "dur_us": float(dur_us), "wait_us": float(wait_us),
            "bytes": nbytes, "peer": peer, "tag": tag, "algo": algo}


# ---------------- recorder ring ----------------


def test_ring_overflow_keeps_newest_with_exact_drop_count():
    r = obs.Recorder(16)
    for i in range(41):
        r.append({"i": i})
    kept = [e["i"] for e in r.snapshot()]
    assert kept == list(range(25, 41))  # newest 16, oldest first
    assert r.dropped == 25  # exact, not approximate


def test_ring_no_overflow_reports_zero_drops():
    r = obs.Recorder(16)
    for i in range(7):
        r.append({"i": i})
    assert [e["i"] for e in r.snapshot()] == list(range(7))
    assert r.dropped == 0


# ---------------- percentile math ----------------


def test_percentiles_match_numpy_on_fixed_corpus():
    rng = np.random.RandomState(7)
    corpus = list(rng.gamma(2.0, 50.0, size=211))  # latency-shaped
    for q in (0, 12.5, 50, 90, 95, 99, 99.9, 100):
        assert obs.percentile(corpus, q) == pytest.approx(
            float(np.percentile(corpus, q)), abs=1e-9), q
    # degenerate corpora
    assert obs.percentile([], 50) == 0.0
    assert obs.percentile([3.5], 99) == 3.5


def test_stats_aggregates_per_op_peer_algo():
    events = [
        _ev("Allreduce", 0, dur_us=100, wait_us=40, nbytes=1024, algo="ring"),
        _ev("Allreduce", 200, dur_us=300, wait_us=60, nbytes=1024,
            algo="ring"),
        _ev("Send", 400, dur_us=10, wait_us=0, nbytes=64, peer=1, tag=7),
    ]
    stats = obs.summarize(events, dropped={"native": 3})
    rows = {(r["op"], r["algo"]): r for r in stats["per_op"]}
    ar = rows[("Allreduce", "ring")]
    assert ar["count"] == 2
    assert ar["bytes"] == 2048
    assert ar["p50_us"] == pytest.approx(200.0)
    assert ar["wait_frac"] == pytest.approx(0.25)  # 100us wait / 400us
    assert ar["eff_GBps"] == pytest.approx(2048 / 400e-6 / 1e9, rel=1e-3)
    assert rows[("Send", "-")]["peer"] == 1
    assert stats["dropped"] == {"native": 3}


def test_stats_keeps_native_and_ops_views_of_one_call_separate():
    """The native ring and the ops-layer span record the SAME call from
    two vantage points — they must aggregate as separate rows, never
    double-count (src is part of the grouping key)."""
    events = [
        _ev("Send", 100, dur_us=10, wait_us=2, nbytes=64, peer=1,
            src="native"),
        _ev("Send", 99, dur_us=30, wait_us=0, nbytes=64, peer=1,
            src="ops"),
    ]
    stats = obs.summarize(events)
    rows = {r["src"]: r for r in stats["per_op"]}
    assert set(rows) == {"native", "ops"}
    assert rows["native"]["count"] == 1 and rows["ops"]["count"] == 1
    assert rows["native"]["bytes"] == 64  # not 128: no double-count
    assert rows["native"]["wait_frac"] == pytest.approx(0.2)


# ---------------- clock-offset merge ----------------


def test_clock_offset_merge_orders_two_rank_sequence():
    """Rank 1's local clock runs 5 ms ahead; the recorded offsets must
    put its events back into true order in the merged timeline."""
    rec = obs._recorder
    # rank 0: true clock, no offset
    rec.start(lib=None, rank=0, size=2, clock_offset_s=0.0)
    rec.record_span("Send", 1.000100, 10e-6, peer=1, nbytes=64, tag=7)
    rec.record_span("Barrier", 1.000300, 5e-6)
    part0 = {"rank": 0, "size": 2, "dropped": rec.dropped(),
             "events": rec.events()}
    # rank 1: its unix clock reads 5 ms ahead of true; the alignment
    # handshake estimated -5 ms for it
    rec.start(lib=None, rank=1, size=2, clock_offset_s=-0.005)
    rec.record_span("Recv", 1.005150, 10e-6, peer=0, nbytes=64, tag=7)
    rec.record_span("Barrier", 1.005320, 5e-6)
    part1 = {"rank": 1, "size": 2, "dropped": rec.dropped(),
             "events": rec.events()}
    rec.stop()

    merged = obs.merge_parts([part1, part0])
    assert obs.validate_chrome_trace(merged) == []
    spans = [(e["name"], e["pid"]) for e in merged["traceEvents"]
             if e["ph"] == "X" and e.get("cat") != "phase"]
    assert spans == [("Send", 0), ("Recv", 1), ("Barrier", 0),
                     ("Barrier", 1)], spans
    # without the offset the recv (local 1.005150) would sort after
    # EVERY rank-0 event — prove the alignment actually moved it
    recv = next(e for e in merged["traceEvents"]
                if e["ph"] == "X" and e["name"] == "Recv")
    assert recv["ts"] == pytest.approx(1.000150 * 1e6, abs=1.0)


def test_chrome_trace_export_and_validation():
    events = [_ev("Allreduce", 100, dur_us=50, wait_us=20, nbytes=4096,
                  algo="rd"),
              _ev("Send", 200, dur_us=8, wait_us=0, peer=2, tag=5,
                  src="ops")]
    trace = obs.merge_parts([{"rank": 0, "size": 1, "events": events,
                              "dropped": {"native": 0}}])
    assert obs.validate_chrome_trace(trace) == []
    spans = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    ar = next(e for e in spans if e["name"] == "Allreduce")
    assert ar["args"]["bytes"] == 4096
    assert ar["args"]["algo"] == "rd"
    assert ar["args"]["wait_us"] == pytest.approx(20.0)
    assert ar["tid"] == 0  # native transport thread
    # the wait/wire phase split renders as nested child slices
    names = {e["name"] for e in spans}
    assert {"wait", "wire"} <= names
    wait = next(e for e in spans if e["name"] == "wait")
    assert wait["dur"] == pytest.approx(20.0)
    # ops-layer spans land on their own thread row, no phase children
    send = next(e for e in spans if e["name"] == "Send")
    assert send["tid"] == 1
    # validator actually rejects malformed traces
    assert obs.validate_chrome_trace({"traceEvents": [{"ph": "X"}]})
    assert obs.validate_chrome_trace([1, 2])
    assert obs.validate_chrome_trace(
        {"traceEvents": [{"name": "x", "ph": "X", "pid": 0, "tid": 0,
                          "ts": 1.0, "dur": -4.0}]})


def test_serving_phase_label_roundtrips_chrome_to_canonical(tmp_path):
    """The serving plane's ``phase`` field (prefill/decode/kv_xfer) is
    additive: labeled spans carry it through stats grouping, the Chrome
    export, and the chrome->canonical loader; unlabeled events keep the
    exact pre-serving schema (no phase key anywhere)."""
    labeled = _ev("serve.decode", 100, dur_us=40, wait_us=0, nbytes=256,
                  src="ops")
    labeled["phase"] = "decode"
    plain = _ev("Allreduce", 200, dur_us=50, wait_us=10, nbytes=4096,
                algo="rd")
    # stats: phase splits the group key and lands on the row — only
    # for labeled spans
    two_phases = dict(labeled, phase="prefill")
    stats = obs.summarize([labeled, plain, two_phases])
    rows = {r.get("phase", "-"): r for r in stats["per_op"]
            if r["op"] == "serve.decode"}
    assert set(rows) == {"decode", "prefill"}
    flat = next(r for r in stats["per_op"] if r["op"] == "Allreduce")
    assert "phase" not in flat
    # chrome export carries it in args; the loader restores it
    trace = obs.merge_parts([{"rank": 0, "size": 1,
                              "events": [labeled, plain]}])
    assert obs.validate_chrome_trace(trace) == []
    path = tmp_path / "trace.json"
    path.write_text(json.dumps(trace))
    events, _ = obs.load_events(str(path))
    by_name = {e["name"]: e for e in events}
    assert by_name["serve.decode"]["phase"] == "decode"
    assert "phase" not in by_name["Allreduce"]
    # part-file round trip preserves it too (parts store canonical form)
    base = str(tmp_path / "part.json")
    obs.write_part(base, rank=0, size=1, events=[labeled])
    loaded, _ = obs.load_events(obs.part_paths(base)[0])
    assert loaded[0]["phase"] == "decode"


# ---------------- dump files + profile CLI ----------------


def _write_two_rank_parts(base):
    events0 = [_ev("Allreduce", 100 + 300 * i, dur_us=100 + i, nbytes=1024,
                   algo="tree") for i in range(4)]
    events0 += [_ev("Allreduce", 2000 + 300 * i, dur_us=40 + i, nbytes=1024,
                    algo="rd") for i in range(4)]
    events0 += [_ev("Allreduce", 4000 + 9000 * i, dur_us=8000 + i,
                    nbytes=1 << 20, algo="tree") for i in range(3)]
    events0 += [_ev("Allreduce", 40000 + 9000 * i, dur_us=2500 + i,
                    nbytes=1 << 20, algo="ring") for i in range(3)]
    events0 += [_ev("Allgather", 80000, dur_us=60, nbytes=4096, algo="ring")]
    obs.write_part(base, rank=0, size=3, events=events0,
                   dropped={"native": 0, "ops": 0})
    obs.write_part(base, rank=1, size=3, events=events0,
                   dropped={"native": 2, "ops": 0})
    return obs.part_paths(base)


def test_load_events_rejects_future_part_version(tmp_path):
    path = tmp_path / "future.rank0.json"
    path.write_text(json.dumps({"version": 99, "rank": 0, "size": 2,
                                "events": [], "dropped": {}}))
    with pytest.raises(ValueError, match="version"):
        obs.load_part(str(path))
    # the fallback loader must not quietly read a future format with
    # v1 semantics either (profile report's error path relies on this)
    with pytest.raises(ValueError, match="version"):
        obs.load_events(str(path))


def test_part_dump_roundtrip_and_rank_globbing(tmp_path):
    base = str(tmp_path / "out.json")
    parts = _write_two_rank_parts(base)
    assert [obs.load_part(p)["rank"] for p in parts] == [0, 1]
    part = obs.load_part(parts[1])
    assert part["size"] == 3 and part["dropped"]["native"] == 2
    events, world = obs.load_events(parts[0])
    assert world == 3 and len(events) == 15


def test_profile_cli_report_and_merge(tmp_path, capsys):
    profile = _load_file("m4j_profile_test", REPO / "mpi4jax_tpu/profile.py")
    base = str(tmp_path / "out.json")
    parts = _write_two_rank_parts(base)
    assert profile.main(["merge", "--out", base, *parts]) == 0
    merged = json.load(open(base))
    assert obs.validate_chrome_trace(merged) == []
    assert merged["otherData"]["world_size"] == 3
    # report renders the per-op/per-algo table from the same recordings
    assert profile.main(["report", *parts]) == 0
    out = capsys.readouterr().out
    assert "Allreduce" in out and "ring" in out and "p99_us" in out
    assert "2 dropped" in out
    # report also reads the merged trace, and --json emits obs.stats
    assert profile.main(["report", base, "--json"]) == 0
    stats = json.loads(capsys.readouterr().out)
    assert stats["schema"] == obs.STATS_SCHEMA
    assert any(r["op"] == "Allgather" for r in stats["per_op"])
    # bad input fails loudly, not silently
    assert profile.main(["report", str(tmp_path / "missing.json")]) == 2


# ---------------- tuner feedback (--from-trace) ----------------


def _load_tune():
    try:
        from mpi4jax_tpu import tune

        return tune
    except ImportError:
        return _load_file("m4j_tune_obs_test",
                          REPO / "mpi4jax_tpu/tune/__init__.py")


def test_from_trace_derives_loadable_algorithm_cache(tmp_path):
    tune = _load_tune()
    base = str(tmp_path / "out.json")
    parts = _write_two_rank_parts(base)
    cache = str(tmp_path / "tune_cache.json")
    written = tune.cache_from_trace(parts, cache_path_override=cache)
    assert written == cache
    data = json.load(open(cache))
    assert data["world_size"] == 3
    assert data["transport"] == "tcp:from-trace"
    # the best MEDIAN observed algorithm wins per size bucket: rd at
    # 1 KB; at 1 MB ring wins AND its recorded wire share dominates
    # (dur >> wait), so the row is promoted to the quantized twin —
    # the wire is the bottleneck there, shrinking frames is the lever
    assert data["table"]["allreduce"] == [[0, "rd"], [1 << 20, "qring"]]
    assert data["table"]["allgather"] == [[0, "ring"]]
    assert any(m["source"] == "trace" for m in data["measurements"])
    promo = [m for m in data["measurements"]
             if m.get("source") == "trace:quant-promotion"]
    assert promo and promo[0]["promoted_from"] == "ring"
    assert promo[0]["wire_frac"] >= tune.QUANT_PROMOTE_WIRE_FRAC
    # exactly what bridge.comm_init loads at communicator creation
    loaded = tune.load_cache(3, path=cache)
    assert loaded["allreduce"] == [(0, "rd"), (1 << 20, "qring")]
    # the exact-only escape hatch (tune --from-trace --no-quantize)
    cache2 = str(tmp_path / "tune_cache_exact.json")
    tune.cache_from_trace(parts, cache_path_override=cache2,
                          quantize=False)
    data2 = json.load(open(cache2))
    assert data2["table"]["allreduce"] == [[0, "rd"], [1 << 20, "ring"]]


def test_from_trace_rejects_recordings_without_tcp_signal(tmp_path):
    tune = _load_tune()
    base = str(tmp_path / "shm.json")
    # an arena-served run: every collective is labeled shm — no TCP
    # algorithm evidence, must refuse rather than write a noise cache
    obs.write_part(base, rank=0, size=2,
                   events=[_ev("Allreduce", 0, nbytes=1024, algo="shm")],
                   dropped={})
    with pytest.raises(ValueError, match="no TCP-path collective"):
        tune.cache_from_trace(obs.part_paths(base))


def test_bench_record_is_field_compatible():
    rec = obs.bench_record(op="allreduce", nbytes=1 << 20, seconds=0.002,
                           ranks=4, tier="world", algo="ring", reps=10)
    # the canonical keys every benchmark artifact and report shares
    assert rec["op"] == "allreduce" and rec["bytes"] == 1 << 20
    assert rec["seconds"] == 0.002 and rec["us"] == pytest.approx(2000.0)
    assert rec["eff_GBps_per_chip"] == pytest.approx(
        1.5 * (1 << 20) / 0.002 / 1e9, rel=1e-3)
    assert rec["ranks"] == 4 and rec["algo"] == "ring" and rec["reps"] == 10
    solo = obs.bench_record(op="memcpy", nbytes=100, seconds=1.0)
    assert solo["eff_GBps_per_chip"] == pytest.approx(100 / 1e9)


# ---------------- native event ring (real transport, no sockets) -----


@pytest.fixture(scope="module")
def native_lib(tmp_path_factory):
    """Transport-only build of native/tpucomm.cc, driven via ctypes on a
    size-1 comm — the self-delivery path needs no sockets, so this runs
    under CPU-only tier-1 in any container with a C++ toolchain."""
    cxx = os.environ.get("CXX", "g++")
    if shutil.which(cxx) is None:
        pytest.skip(f"no C++ compiler ({cxx}) available")
    so = tmp_path_factory.mktemp("obs_native") / "libtpucomm_obs.so"
    src = REPO / "native" / "tpucomm.cc"
    res = subprocess.run(
        [cxx, "-O1", "-std=c++17", "-fPIC", "-Wall", "-pthread", "-shared",
         "-o", str(so), str(src), "-lrt"],
        capture_output=True, text=True, timeout=300,
    )
    assert res.returncode == 0, f"native build failed:\n{res.stderr[-2000:]}"
    lib = ctypes.CDLL(str(so))
    lib.tpucomm_init.restype = ctypes.c_int64
    lib.tpucomm_init.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                 ctypes.c_char_p]
    h = lib.tpucomm_init(0, 1, 47299, b"")
    assert h > 0, "size-1 comm init failed"
    yield lib, h
    lib.tpucomm_finalize(ctypes.c_int64(h))


def _native_mod():
    try:
        from mpi4jax_tpu.obs import _native

        return _native
    except ImportError:
        return _load_file("m4j_obs_native_test",
                          REPO / "mpi4jax_tpu/obs/_native.py")


def _self_send_recv(lib, h, tag):
    buf = np.arange(8.0)
    out = np.empty_like(buf)
    p = lambda a: a.ctypes.data_as(ctypes.c_void_p)  # noqa: E731
    rc = lib.tpucomm_send(ctypes.c_int64(h), p(buf),
                          ctypes.c_int64(buf.nbytes), 0, tag)
    assert rc == 0
    rc = lib.tpucomm_recv(ctypes.c_int64(h), p(out),
                          ctypes.c_int64(out.nbytes), 0, tag)
    assert rc == 0
    np.testing.assert_array_equal(out, buf)


def test_native_disabled_fast_path_writes_nothing(native_lib):
    """THE zero-cost contract: with recording off, transport ops perform
    no event-ring writes at all (test-enforced)."""
    lib, h = native_lib
    nat = _native_mod()
    assert nat.available(lib)
    nat.disable(lib)
    for tag in range(20, 25):
        _self_send_recv(lib, h, tag)
    buf = np.arange(8.0)
    out = np.empty_like(buf)
    lib.tpucomm_allreduce(ctypes.c_int64(h),
                          buf.ctypes.data_as(ctypes.c_void_p),
                          out.ctypes.data_as(ctypes.c_void_p),
                          ctypes.c_int64(8), 12, 0)
    held, dropped = nat.counts(lib)
    assert held == 0 and dropped == 0
    assert nat.drain(lib) == []


def test_native_ring_records_ops_with_fields(native_lib):
    lib, h = native_lib
    nat = _native_mod()
    nat.enable(lib, 64)
    _self_send_recv(lib, h, 42)
    buf = np.arange(8.0)
    out = np.empty_like(buf)
    rc = lib.tpucomm_allreduce(ctypes.c_int64(h),
                               buf.ctypes.data_as(ctypes.c_void_p),
                               out.ctypes.data_as(ctypes.c_void_p),
                               ctypes.c_int64(8), 12, 0)  # f64 SUM
    assert rc == 0
    events = nat.drain(lib)
    nat.disable(lib)
    names = [e["name"] for e in events]
    assert names == ["Send", "Recv", "Allreduce"]
    send = events[0]
    assert send["peer"] == 0 and send["tag"] == 42 and send["bytes"] == 64
    assert 0 <= send["wait_s"] <= send["dur_s"]
    ar = events[2]
    assert ar["bytes"] == 64 and ar["peer"] == -1
    assert all(e["t"] <= n["t"] for e, n in zip(events, events[1:]))


def test_native_events_carry_dispatch_phase(native_lib):
    """Every drained event reports queue_s (the post -> native-start
    dispatch delay); a detached self-send (queued on the progress
    engine) records a positive one, and the phases always fit inside
    the op: queue + wait <= dur."""
    lib, h = native_lib
    nat = _native_mod()
    nat.enable(lib, 64)
    for tag in range(60, 64):
        _self_send_recv(lib, h, tag)
    events = nat.drain(lib)
    nat.disable(lib)
    assert events and all("queue_s" in e for e in events)
    for e in events:
        assert 0.0 <= e["queue_s"] <= e["dur_s"] + 1e-12, e
        assert e["queue_s"] + e["wait_s"] <= e["dur_s"] + 1e-9, e
    sends = [e for e in events if e["name"] == "Send"]
    assert any(e["queue_s"] > 0.0 for e in sends), (
        "no queued (detached) send recorded a dispatch delay")


def test_stats_and_trace_carry_dispatch_split():
    """dispatch_us flows from canonical events into obs.stats rows
    (dispatch_frac) and the Chrome trace (args + a nested dispatch
    phase slice ahead of wait/wire)."""
    ev = _ev("Send", 100, dur_us=50, wait_us=10, peer=1)
    ev["dispatch_us"] = 15.0
    stats = obs.summarize([ev])
    row = stats["per_op"][0]
    assert row["dispatch_frac"] == pytest.approx(0.3)
    assert row["wait_frac"] == pytest.approx(0.2)
    trace = obs.merge_parts([{"rank": 0, "size": 1, "events": [ev],
                              "dropped": {}}])
    assert obs.validate_chrome_trace(trace) == []
    spans = {e["name"]: e for e in trace["traceEvents"] if e["ph"] == "X"}
    assert spans["Send"]["args"]["dispatch_us"] == pytest.approx(15.0)
    assert spans["dispatch"]["dur"] == pytest.approx(15.0)
    assert spans["wait"]["ts"] == pytest.approx(spans["dispatch"]["ts"]
                                                + 15.0)
    assert spans["wire"]["dur"] == pytest.approx(25.0)


def test_native_ring_overflow_keeps_newest_exact_drops(native_lib):
    lib, h = native_lib
    nat = _native_mod()
    nat.enable(lib, 16)
    total = 30  # 15 send+recv pairs
    for i in range(total // 2):
        _self_send_recv(lib, h, 1000 + i)
    held, dropped = nat.counts(lib)
    assert held == 16
    assert dropped == total - 16  # exact drop accounting
    events = nat.drain(lib)
    assert len(events) == 16
    # the kept events are the NEWEST 16, oldest-first
    tags = [e["tag"] for e in events]
    assert tags == [1000 + (total - 16 + i) // 2 for i in range(16)]
    # drain clears held events but the drop counter survives
    held2, dropped2 = nat.counts(lib)
    assert held2 == 0 and dropped2 == total - 16
    nat.disable(lib)


def test_native_partial_drain_counts_undelivered_as_dropped(native_lib):
    """A drain whose buffer is smaller than the held count (events can
    arrive between the count probe and the drain) must COUNT what it
    discards — the exact-drop-accounting contract."""
    lib, h = native_lib
    nat = _native_mod()
    nat.enable(lib, 32)
    for i in range(5):
        _self_send_recv(lib, h, 300 + i)  # 10 events held
    buf = (nat.TpuObsEvent * 4)()
    got = lib.tpucomm_obs_drain(buf, ctypes.c_int64(4))
    assert got == 4
    # the 4 delivered are the NEWEST, oldest-first
    assert [buf[i].tag for i in range(4)] == [303, 303, 304, 304]
    held, dropped = nat.counts(lib)
    assert held == 0
    assert dropped == 6  # the 6 undelivered events were counted
    nat.disable(lib)


def test_native_disable_after_enable_stops_recording(native_lib):
    lib, h = native_lib
    nat = _native_mod()
    nat.enable(lib, 16)
    _self_send_recv(lib, h, 7)
    nat.disable(lib)
    _self_send_recv(lib, h, 8)
    held, dropped = nat.counts(lib)
    assert held == 0 and dropped == 0


# ------- end-to-end: launcher --trace over the real transport --------
#
# The launcher runs as a plain FILE and the ranks import the runtime
# through a parent-package shim that skips mpi4jax_tpu/__init__.py, so
# this full multi-process path — comm init, clock-alignment handshake,
# native recording, per-rank dump at exit, launcher merge — runs under
# CPU-only tier-1 even where the package's jax-version gate blocks the
# normal import (the ops layer is not involved at bridge level).

_RANK_PROG = r"""
import os, sys, types
REPO = %r
sys.path.insert(0, REPO)
pkg = types.ModuleType("mpi4jax_tpu")
pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
sys.modules["mpi4jax_tpu"] = pkg
import numpy as np
from mpi4jax_tpu.runtime import bridge, transport

c = transport.get_world_comm()
h = c.handle  # comm init: transport mesh + obs install (TRACE is set)
r, n = c.rank(), c.size()
out = bridge.allreduce(h, np.arange(1024.0), 0)  # SUM
assert abs(float(out[1]) - n) < 1e-9, out[1]
got = bridge.sendrecv(h, np.full(8, float(r)), (8,), np.float64,
                      (r - 1) %% n, (r + 1) %% n, 5)
assert float(got[0]) == float((r - 1) %% n), got
big = bridge.allreduce(h, np.ones(1 << 18), 0)  # 2 MB: ring territory
assert abs(float(big[0]) - n) < 1e-9
bridge.barrier(h)
print("bridge_trace OK", flush=True)
"""


@pytest.mark.parametrize("np_", [3])
def test_launch_trace_end_to_end_bridge_level(tmp_path, np_):
    repo = str(REPO)
    # prebuild the native lib once so the ranks don't compile 3x
    pre = subprocess.run(
        [sys.executable, "-c",
         "import sys, types, os; sys.path.insert(0, %r);"
         "pkg = types.ModuleType('mpi4jax_tpu');"
         "pkg.__path__ = [os.path.join(%r, 'mpi4jax_tpu')];"
         "sys.modules['mpi4jax_tpu'] = pkg;"
         "from mpi4jax_tpu.runtime import bridge; bridge.get_lib();"
         "print('prebuilt')" % (repo, repo)],
        capture_output=True, text=True, timeout=300,
    )
    assert pre.returncode == 0, pre.stderr[-2000:]

    prog = tmp_path / "bridge_trace_prog.py"
    prog.write_text(_RANK_PROG % repo)
    out = tmp_path / "trace.json"
    # a stale part from an earlier, wider run at the same path must not
    # leak into this run's merge (the launcher clears them pre-spawn)
    stale = tmp_path / "trace.json.rank7.json"
    stale.write_text(json.dumps({"version": 1, "rank": 7, "size": 8,
                                 "events": [], "dropped": {}}))
    env = dict(os.environ)
    env["MPI4JAX_TPU_DISABLE_SHM"] = "1"  # record real TCP algorithms
    res = subprocess.run(
        [sys.executable, str(REPO / "mpi4jax_tpu/runtime/launch.py"),
         "-n", str(np_), "--port", "46610", "--trace", str(out),
         str(prog)],
        capture_output=True, text=True, timeout=300, env=env, cwd=repo,
    )
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("bridge_trace OK") == np_
    assert "[obs] recording written to" in res.stderr
    assert f"merged {np_}/{np_} rank recording(s)" in res.stderr, \
        res.stderr[-2000:]

    assert not stale.exists(), "stale pre-run part survived the launcher"
    parts = obs.part_paths(str(out))
    assert len(parts) == np_
    merged = json.loads(out.read_text())
    assert obs.validate_chrome_trace(merged) == []
    assert merged["otherData"]["world_size"] == np_
    spans = [e for e in merged["traceEvents"]
             if e["ph"] == "X" and e.get("cat") != "phase"]
    assert {e["pid"] for e in spans} == set(range(np_))  # EVERY rank
    ar = [e for e in spans if e["name"] == "Allreduce"]
    assert len(ar) >= 2 * np_  # small + big per rank
    assert all(e["args"]["bytes"] > 0 for e in ar)
    assert any(e["args"].get("algo") in ("ring", "rd", "tree")
               for e in ar), [e["args"] for e in ar[:4]]
    sr = [e for e in spans if e["name"] == "Sendrecv"]
    assert any(e["args"]["peer"] >= 0 for e in sr)
    # cross-rank alignment: every rank recorded a clock offset field
    for p in parts:
        assert "clock_offset_us" in obs.load_part(p)
    # wait/transfer split present in the merged timeline
    assert any(e.get("cat") == "phase" and e["name"] == "wait"
               for e in merged["traceEvents"])

    # the recorded run feeds the tuner: a loadable cache comes out
    tune = _load_tune()
    cache = str(tmp_path / "cache.json")
    tune.cache_from_trace(parts, cache_path_override=cache)
    data = json.load(open(cache))
    assert data["world_size"] == np_
    assert all(e[1] in ("ring", "rd", "tree", "qring", "qrd")
               for op in data["table"] for e in data["table"][op])
