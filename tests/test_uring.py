"""Zero-copy transport floor (io_uring submission backend): knob
parsing, the resolved-status export, the obs ``syscalls`` field, and
the pre-uring layout probe.

Unit tier: a transport-only build of ``native/tpucomm.cc`` driven over
size-1 self-delivery (no sockets) plus subprocess probes that pin the
per-process env resolution (`MPI4JAX_TPU_URING` is read once per
process, like every native knob).  The multi-process equivalence and
failure-semantics coverage lives in ``tests/world/test_uring.py``.
"""

import ctypes
import importlib.util
import os
import pathlib
import shutil
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parents[1]


def _load_file(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _config_mod():
    try:
        from mpi4jax_tpu.utils import config

        return config
    except ImportError:
        return _load_file("m4j_uring_config", REPO / "mpi4jax_tpu/utils/config.py")


def _native_mod():
    try:
        from mpi4jax_tpu.obs import _native

        return _native
    except ImportError:
        return _load_file("m4j_uring_obs_native",
                          REPO / "mpi4jax_tpu/obs/_native.py")


# ---------------- knob parser (Python mirror) ------------------------


def test_uring_mode_defaults_to_auto(monkeypatch):
    config = _config_mod()
    monkeypatch.delenv("MPI4JAX_TPU_URING", raising=False)
    assert config.uring_mode() == "auto"
    monkeypatch.setenv("MPI4JAX_TPU_URING", "  ")
    assert config.uring_mode() == "auto"


@pytest.mark.parametrize("value", ["auto", "0", "1"])
def test_uring_mode_accepts_the_documented_values(monkeypatch, value):
    config = _config_mod()
    monkeypatch.setenv("MPI4JAX_TPU_URING", value)
    assert config.uring_mode() == value


@pytest.mark.parametrize("value", ["on", "yes", "2", "true", "uring"])
def test_uring_mode_is_loud_on_malformed(monkeypatch, value):
    # the native parser exits(2) on the same values (pinned below); the
    # mirror must never quietly read them as "auto"
    config = _config_mod()
    monkeypatch.setenv("MPI4JAX_TPU_URING", value)
    with pytest.raises(ValueError, match="MPI4JAX_TPU_URING"):
        config.uring_mode()


def test_uring_knob_is_registered():
    config = _config_mod()
    assert "MPI4JAX_TPU_URING" in config.KNOBS


# ---------------- layout probe (pre-uring .so) -----------------------


class _PreUringLib:
    """A loaded-library stand-in with every pre-uring symbol but no
    ``tpucomm_uring_status`` — the shape of a stale prebuilt .so."""

    tpucomm_obs_enable = tpucomm_obs_counts = tpucomm_obs_drain = None
    tpucomm_obs_clock = tpucomm_execute = None
    tpucomm_quant_packed_bytes = tpucomm_set_topology = None


def test_pre_uring_library_reads_as_syscalls_unavailable():
    nat = _native_mod()
    assert not nat.syscalls_available(_PreUringLib())
    assert not nat.syscalls_available(None)


def test_pre_uring_library_reads_as_uring_unavailable(monkeypatch):
    # bridge.uring_status() must report None (caller renders it as
    # unavailable) instead of misparsing the old layout
    try:
        from mpi4jax_tpu.runtime import bridge
    except ImportError:
        pytest.skip("package gate: bridge needs the package import")
    monkeypatch.setattr(bridge, "_lib", _PreUringLib())
    assert bridge.uring_status() is None
    assert bridge.syscall_count() is None


# ---------------- native resolution (real build, subprocess env) -----


@pytest.fixture(scope="module")
def native_so(tmp_path_factory):
    cxx = os.environ.get("CXX", "g++")
    if shutil.which(cxx) is None:
        pytest.skip(f"no C++ compiler ({cxx}) available")
    so = tmp_path_factory.mktemp("uring_native") / "libtpucomm_uring.so"
    res = subprocess.run(
        [cxx, "-O1", "-std=c++17", "-fPIC", "-Wall", "-pthread", "-shared",
         "-o", str(so), str(REPO / "native" / "tpucomm.cc"), "-lrt"],
        capture_output=True, text=True, timeout=300,
    )
    assert res.returncode == 0, f"native build failed:\n{res.stderr[-2000:]}"
    return so


_STATUS_SRC = (
    "import ctypes, sys\n"
    "lib = ctypes.CDLL(sys.argv[1])\n"
    "lib.tpucomm_uring_status.restype = ctypes.c_char_p\n"
    "print('status=' + lib.tpucomm_uring_status().decode())\n"
)


def _status(so, env_extra):
    env = {**os.environ, **env_extra}
    return subprocess.run([sys.executable, "-c", _STATUS_SRC, str(so)],
                          capture_output=True, text=True, timeout=60,
                          env=env)


def test_native_status_off_when_disabled(native_so):
    res = _status(native_so, {"MPI4JAX_TPU_URING": "0"})
    assert res.returncode == 0, res.stderr
    assert "status=off" in res.stdout


def test_native_status_resolves_on_or_unavailable(native_so):
    # auto: the probe decides; both outcomes are legal, a bare guess or
    # a parse artifact is not
    res = _status(native_so, {"MPI4JAX_TPU_URING": "auto"})
    assert res.returncode == 0, res.stderr
    line = [l for l in res.stdout.splitlines() if l.startswith("status=")]
    assert line, res.stdout
    status = line[0][len("status="):]
    assert status.startswith("on") or status.startswith("unavailable("), status


def test_native_parser_exits_loudly_on_malformed(native_so):
    res = _status(native_so, {"MPI4JAX_TPU_URING": "yes"})
    assert res.returncode == 2, (res.returncode, res.stdout, res.stderr)
    assert "cannot parse MPI4JAX_TPU_URING" in res.stderr


_SYSCALLS_SRC = (
    "import ctypes, sys\n"
    "import numpy as np\n"
    "lib = ctypes.CDLL(sys.argv[1])\n"
    "lib.tpucomm_init.restype = ctypes.c_int64\n"
    "lib.tpucomm_init.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int,"
    " ctypes.c_char_p]\n"
    "lib.tpucomm_syscall_count.restype = ctypes.c_int64\n"
    "h = lib.tpucomm_init(0, 1, 47317, b'')\n"
    "assert h > 0\n"
    "lib.tpucomm_obs_enable(1, ctypes.c_int64(64))\n"
    "buf = np.arange(8.0)\n"
    "out = np.empty_like(buf)\n"
    "p = lambda a: a.ctypes.data_as(ctypes.c_void_p)\n"
    "assert lib.tpucomm_send(h, p(buf), ctypes.c_int64(64), 0, 7) == 0\n"
    "assert lib.tpucomm_recv(h, p(out), ctypes.c_int64(64), 0, 7) == 0\n"
    "print('counter=%d' % lib.tpucomm_syscall_count())\n"
    "print('ok')\n"
)


def test_native_syscall_counter_exported(native_so):
    res = subprocess.run([sys.executable, "-c", _SYSCALLS_SRC, str(native_so)],
                         capture_output=True, text=True, timeout=60,
                         env={**os.environ})
    assert res.returncode == 0, res.stderr[-1500:]
    assert "ok" in res.stdout
    # self-delivery moves no socket bytes; the counter exists and is
    # monotone (>= 0 — ring setup may have counted its own syscalls)
    count = int(res.stdout.split("counter=")[1].split()[0])
    assert count >= 0


def test_drained_events_carry_syscalls_field(native_so):
    """A uring-generation .so stamps every obs event with a syscalls
    count, and the Python drain exposes it; the same drain against a
    pre-uring library omits the key entirely (gated above)."""
    nat = _native_mod()
    lib = ctypes.CDLL(str(native_so))
    lib.tpucomm_init.restype = ctypes.c_int64
    lib.tpucomm_init.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                 ctypes.c_char_p]
    h = lib.tpucomm_init(0, 1, 47321, b"")
    assert h > 0
    try:
        assert nat.available(lib) and nat.syscalls_available(lib)
        nat.enable(lib, 64)
        import numpy as np

        buf = np.arange(8.0)
        out = np.empty_like(buf)
        p = lambda a: a.ctypes.data_as(ctypes.c_void_p)  # noqa: E731
        assert lib.tpucomm_send(ctypes.c_int64(h), p(buf),
                                ctypes.c_int64(buf.nbytes), 0, 3) == 0
        assert lib.tpucomm_recv(ctypes.c_int64(h), p(out),
                                ctypes.c_int64(out.nbytes), 0, 3) == 0
        events = nat.drain(lib)
        nat.disable(lib)
        assert events, "no events recorded"
        assert all("syscalls" in e for e in events)
        # self-delivery touches no socket: the counts are exact zeros
        assert all(e["syscalls"] == 0 for e in events), events
    finally:
        lib.tpucomm_finalize(ctypes.c_int64(h))
