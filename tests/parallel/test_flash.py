"""Unit tests for the Pallas flash-attention block kernels (ops/flash.py).

Exercised in interpret mode on CPU; the same code path compiles for TPU.
The block kernel is validated against a dense einsum reference including
traced global offsets (the ring-step case) and partial causal masking.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi4jax_tpu.ops.flash import _flash_fwd_block, pick_block

BH, TQ, TK, D = 3, 32, 48, 16


def _dense_block(q, k, v, q_off, k_off, scale, causal):
    s = jnp.einsum("btd,bsd->bts", q, k).astype(jnp.float32) * scale
    if causal:
        rows = q_off + np.arange(TQ)[:, None]
        cols = k_off + np.arange(TK)[None, :]
        s = jnp.where(jnp.asarray(cols <= rows)[None], s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.maximum(m, -1e30)  # fully-masked rows
    p = jnp.exp(s - m)
    return jnp.einsum("bts,bsd->btd", p, v.astype(jnp.float32)), m, \
        jnp.sum(p, axis=-1, keepdims=True)


@pytest.mark.parametrize("causal,q_off,k_off", [
    (False, 0, 0),
    (True, 0, 0),       # diagonal block
    (True, 64, 0),      # k fully in the past -> unmasked
    (True, 16, 32),     # partial overlap, some rows fully masked
])
def test_flash_block_matches_dense(causal, q_off, k_off):
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(BH, TQ, D).astype(np.float32) * 0.4)
    k = jnp.asarray(rng.randn(BH, TK, D).astype(np.float32) * 0.4)
    v = jnp.asarray(rng.randn(BH, TK, D).astype(np.float32) * 0.4)
    scale = 0.25

    o, m, l = jax.jit(
        lambda a, b, c, qo, ko: _flash_fwd_block(
            a, b, c, qo, ko, scale=scale, causal=causal,
            block_q=16, block_k=16, interpret=True)
    )(q, k, v, jnp.int32(q_off), jnp.int32(k_off))
    o_ref, m_ref, l_ref = _dense_block(q, k, v, q_off, k_off, scale, causal)

    np.testing.assert_allclose(np.asarray(m), np.asarray(m_ref), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(l), np.asarray(l_ref),
                               rtol=1e-5, atol=1e-6)
    # unnormalized partials: compare where any key is visible
    np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_block_bf16_inputs():
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(BH, TQ, D).astype(np.float32)).astype(
        jnp.bfloat16)
    k = jnp.asarray(rng.randn(BH, TK, D).astype(np.float32)).astype(
        jnp.bfloat16)
    v = jnp.asarray(rng.randn(BH, TK, D).astype(np.float32)).astype(
        jnp.bfloat16)
    o, m, l = _flash_fwd_block(
        q, k, v, jnp.int32(0), jnp.int32(0), scale=0.25, causal=False,
        block_q=32, block_k=16, interpret=True)
    assert o.dtype == jnp.float32  # partials always accumulate in f32
    o_ref, m_ref, l_ref = _dense_block(
        q.astype(jnp.float32), k.astype(jnp.float32),
        v.astype(jnp.float32), 0, 0, 0.25, False)
    np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref),
                               rtol=2e-2, atol=2e-2)


def test_pick_block():
    assert pick_block(256, 128) == 128
    assert pick_block(96, 128) == 96
    assert pick_block(48, 32) == 24
    assert pick_block(7, 128) == 7
