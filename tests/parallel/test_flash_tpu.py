"""Compiled-path (Mosaic) flash/RDMA kernel checks — run only on a real
TPU backend; the CPU suite covers the same code paths in interpret mode
(test_flash.py, test_pallas_collectives.py).

These exist so a TPU-equipped CI run catches Mosaic-only regressions
(tile alignment, VMEM budgets) that interpret mode cannot see — the
round-1 failure class (VERDICT.md r1 weak: kernels passed interpret
tests and failed Mosaic on hardware).
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest

on_tpu = jax.default_backend() == "tpu"
pytestmark = pytest.mark.skipif(
    not on_tpu, reason="needs a real TPU backend (Mosaic compile path)"
)


def _mesh1():
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:1]), ("sp",))


def test_flash_compiled_matches_dense():
    from jax.sharding import PartitionSpec as P

    from mpi4jax_tpu.ops.flash import ring_flash_attention

    B, T, H, D = 2, 1024, 4, 128
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(i), (B, T, H, D), jnp.bfloat16)
        for i in range(3)
    )
    fa = jax.shard_map(
        partial(ring_flash_attention, axis="sp", causal=True,
                interpret=False),
        mesh=_mesh1(), in_specs=(P(None, "sp"),) * 3,
        out_specs=P(None, "sp"), check_vma=False)
    out = np.asarray(jax.jit(fa)(q, k, v), dtype=np.float32)

    qf, kf, vf = (np.asarray(x, dtype=np.float32) for x in (q, k, v))
    s = np.einsum("bqhd,bkhd->bhqk", qf, kf) / np.sqrt(D)
    s = np.where(np.tril(np.ones((T, T), bool)), s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bkhd->bqhd", p, vf)
    np.testing.assert_allclose(out, ref, atol=2e-2)


def test_flash_compiled_grads_finite():
    from jax.sharding import PartitionSpec as P

    from mpi4jax_tpu.ops.flash import ring_flash_attention

    B, T, H, D = 2, 512, 4, 128
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(i), (B, T, H, D), jnp.bfloat16)
        for i in range(3)
    )
    fa = jax.shard_map(
        partial(ring_flash_attention, axis="sp", causal=True,
                interpret=False),
        mesh=_mesh1(), in_specs=(P(None, "sp"),) * 3,
        out_specs=P(None, "sp"), check_vma=False)
    g = jax.jit(jax.grad(
        lambda a, b, c: jnp.sum(fa(a, b, c).astype(jnp.float32)),
        argnums=(0, 1, 2)))(q, k, v)
    for arr in g:
        assert np.all(np.isfinite(np.asarray(arr, dtype=np.float32)))


def test_rdma_loopback_compiled():
    from jax.sharding import PartitionSpec as P

    from mpi4jax_tpu.ops.pallas_collectives import ring_shift, ring_shift2

    x = jnp.arange(8 * 128, dtype=jnp.float32).reshape(8, 128)
    sm = jax.shard_map(
        lambda v: ring_shift(v, "r", 1, interpret=False),
        mesh=jax.sharding.Mesh(np.array(jax.devices()[:1]), ("r",)),
        in_specs=P("r"), out_specs=P("r"), check_vma=False)
    out = np.asarray(jax.jit(sm)(x))
    np.testing.assert_allclose(out, np.asarray(x))  # size-1 ring: identity

    sm2 = jax.shard_map(
        lambda v: ring_shift2(v, v + 1.0, "r", interpret=False)[0],
        mesh=jax.sharding.Mesh(np.array(jax.devices()[:1]), ("r",)),
        in_specs=P("r"), out_specs=P("r"), check_vma=False)
    out2 = np.asarray(jax.jit(sm2)(x))
    np.testing.assert_allclose(out2, np.asarray(x))


def test_sw_fused_compiled_matches_xla():
    from mpi4jax_tpu.models.shallow_water import ShallowWater, SWParams
    from mpi4jax_tpu.parallel.grid import ProcessGrid

    grid = ProcessGrid((1, 1), devices=jax.devices()[:1])
    model = ShallowWater(grid, (256, 512), SWParams(dx=5e3, dy=5e3))

    def advance(impl, **kw):
        s = model.init()
        s = model.step_fn(1, first=True, impl=impl, **kw)(s)
        return model.step_fn(6, first=False, impl=impl, **kw)(s)

    ref = advance("xla")
    got = advance("pallas", tile_rows=128, fuse=2)
    for a, b in zip(got, ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-5)
