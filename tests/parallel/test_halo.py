"""Halo-exchange correctness: reconstruct a global array's neighbor strips.

The reference validates its halo pattern implicitly through the
shallow-water solver; here we check exchange against a numpy ground truth
on a 4x2 grid (8 virtual devices).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m4j
from mpi4jax_tpu.parallel.grid import ProcessGrid
from mpi4jax_tpu.parallel.halo import halo_exchange

GX, GY = 4, 2
H = 1
LOC = (6, 4)  # interior block per rank


def make_global():
    rng = np.random.RandomState(0)
    return rng.rand(GX * LOC[0], GY * LOC[1]).astype(np.float32)


def pad_blocks(g):
    """Split global into per-rank blocks padded with zero ghost rings."""
    blocks = []
    for i in range(GX):
        row = []
        for j in range(GY):
            b = g[
                i * LOC[0] : (i + 1) * LOC[0], j * LOC[1] : (j + 1) * LOC[1]
            ]
            row.append(np.pad(b, H))
        blocks.append(row)
    return blocks


@pytest.mark.parametrize("periodic", [True, False])
def test_halo_exchange_2d(periodic):
    g = make_global()
    blocks = pad_blocks(g)
    grid = ProcessGrid((GX, GY))

    # shard_map input: global array of stacked padded blocks
    stacked = np.stack(
        [blocks[i][j] for i in range(GX) for j in range(GY)]
    ).reshape(GX, GY, LOC[0] + 2 * H, LOC[1] + 2 * H)
    xin = jnp.asarray(stacked)

    def step(b):
        b = b.reshape(LOC[0] + 2 * H, LOC[1] + 2 * H)
        out = halo_exchange(b, grid, halo=H, periodic=periodic)
        return out.reshape(1, 1, LOC[0] + 2 * H, LOC[1] + 2 * H)

    from jax.sharding import PartitionSpec as P

    out = jax.jit(
        jax.shard_map(
            step,
            mesh=grid.mesh,
            in_specs=P(*grid.axes),
            out_specs=P(*grid.axes),
        )
    )(xin)
    out = np.asarray(out)

    gp = np.pad(g, H, mode="wrap" if periodic else "constant")
    for i in range(GX):
        for j in range(GY):
            got = out[i, j]
            want = gp[
                i * LOC[0] : (i + 1) * LOC[0] + 2 * H,
                j * LOC[1] : (j + 1) * LOC[1] + 2 * H,
            ].copy()
            if not periodic:
                # physical-boundary ghosts keep their prior (zero) values
                pass
            # corners are not exchanged diagonally in a 2-pass exchange of
            # axis 0 then axis 1 — axis-1 pass propagates the already-updated
            # axis-0 ghosts, so corners ARE correct. Compare everything.
            np.testing.assert_allclose(got, want, err_msg=f"block {i},{j}")


def test_halo_multifield():
    g1, g2 = make_global(), make_global() + 1
    grid = ProcessGrid((GX, GY))
    b1 = pad_blocks(g1)
    b2 = pad_blocks(g2)
    s1 = np.stack([b1[i][j] for i in range(GX) for j in range(GY)])
    s2 = np.stack([b2[i][j] for i in range(GX) for j in range(GY)])
    shp = (GX, GY, LOC[0] + 2 * H, LOC[1] + 2 * H)

    def step(a, b):
        a = a.reshape(shp[2:])
        b = b.reshape(shp[2:])
        a2, b2_ = halo_exchange((a, b), grid, halo=H, periodic=True)
        return a2.reshape(1, 1, *shp[2:]), b2_.reshape(1, 1, *shp[2:])

    from jax.sharding import PartitionSpec as P

    o1, o2 = jax.jit(
        jax.shard_map(
            step,
            mesh=grid.mesh,
            in_specs=P(*grid.axes),
            out_specs=P(*grid.axes),
        )
    )(jnp.asarray(s1.reshape(shp)), jnp.asarray(s2.reshape(shp)))
    g1p = np.pad(g1, H, mode="wrap")
    g2p = np.pad(g2, H, mode="wrap")
    np.testing.assert_allclose(
        np.asarray(o1)[1, 1],
        g1p[LOC[0] : 2 * LOC[0] + 2 * H, LOC[1] : 2 * LOC[1] + 2 * H],
    )
    np.testing.assert_allclose(
        np.asarray(o2)[2, 0],
        g2p[2 * LOC[0] : 3 * LOC[0] + 2 * H, 0 : LOC[1] + 2 * H],
    )
