"""Ring and Ulysses attention vs a dense single-device reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import mpi4jax_tpu as m4j
from mpi4jax_tpu.parallel.ring import ring_attention
from mpi4jax_tpu.parallel.ulysses import ulysses_attention

N = 8
B, T, H, D = 2, 64, 8, 16  # T_global = 64 -> 8 per rank


def dense_attention(q, k, v, causal):
    scale = 1.0 / np.sqrt(D)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(B, T, H, D).astype(np.float32) * 0.3)
    return mk(), mk(), mk()


@pytest.fixture(scope="module")
def mesh():
    return m4j.make_mesh(N, axis="sp")


def _run_sharded(fn, mesh, *args):
    spec = P(None, "sp")  # shard the sequence axis (dim 1)
    return jax.jit(
        jax.shard_map(
            fn, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False
        )
    )(*args)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(qkv, mesh, causal, impl):
    q, k, v = qkv
    expected = dense_attention(q, k, v, causal)
    got = _run_sharded(
        lambda a, b_, c: ring_attention(
            a, b_, c, axis="sp", causal=causal, impl=impl
        ),
        mesh, q, k, v,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), rtol=2e-4, atol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(qkv, mesh, causal):
    q, k, v = qkv
    expected = dense_attention(q, k, v, causal)
    got = _run_sharded(
        lambda a, b_, c: ulysses_attention(
            a, b_, c, axis="sp", causal=causal
        ),
        mesh, q, k, v,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), rtol=2e-4, atol=2e-5
    )


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_ring_attention_grad(qkv, mesh, impl):
    q, k, v = qkv

    def loss_ring(a, b_, c):
        out = _run_sharded(
            lambda x, y, z: ring_attention(
                x, y, z, axis="sp", causal=True, impl=impl
            ),
            mesh, a, b_, c,
        )
        return (out * out).sum()

    def loss_dense(a, b_, c):
        out = dense_attention(a, b_, c, True)
        return (out * out).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gd), rtol=5e-3, atol=5e-4
        )
