"""DP / TP / pipeline strategy tests (reference embodiments: SURVEY.md §2.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import mpi4jax_tpu as m4j
from mpi4jax_tpu.parallel import dp, tp
from mpi4jax_tpu.parallel.pipeline import pipeline_apply

N = 8


@pytest.fixture(scope="module")
def mesh():
    return m4j.make_mesh(N)


def test_dp_replicated_loss_grad(mesh):
    # grad of the wrapped loss == grad of the global-batch loss
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(4).astype(np.float32))
    xs = jnp.asarray(rng.randn(N * 2, 4).astype(np.float32))
    ys = jnp.asarray(rng.randn(N * 2).astype(np.float32))

    def local_loss(w_, x, y):
        return jnp.mean((x @ w_ - y) ** 2)

    def dp_grad(w_):
        def per_rank(x, y):
            _, g = dp.value_and_synced_grad(local_loss)(w_, x, y)
            return g[None]

        gs = m4j.spmd(per_rank, mesh=mesh)(xs, ys)
        return gs.reshape(N, 4)[0]

    g_dp = dp_grad(w)
    g_full = jax.grad(
        lambda w_: jnp.mean(
            jnp.stack([
                jnp.mean((xs[i * 2:(i + 1) * 2] @ w_ - ys[i * 2:(i + 1) * 2]) ** 2)
                for i in range(N)
            ])
        )
    )(w)
    np.testing.assert_allclose(np.asarray(g_dp), np.asarray(g_full), rtol=1e-5)


def test_tp_column_row_pair(mesh):
    # column-parallel -> row-parallel == dense two-layer matmul
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(3, 16).astype(np.float32))
    w1 = jnp.asarray(rng.randn(16, 32).astype(np.float32))
    w2 = jnp.asarray(rng.randn(32, 8).astype(np.float32))
    dense = jnp.maximum(x @ w1, 0) @ w2

    def per_rank(x_rep):
        r = jax.lax.axis_index("mpi")
        # static shards would come from a checkpoint loader; here slice
        # dynamically for the test via lax.dynamic_slice
        step1 = 32 // N
        w1_shard = jax.lax.dynamic_slice(w1, (0, r * step1), (16, step1))
        w2_shard = jax.lax.dynamic_slice(w2, (r * (32 // N), 0), (32 // N, 8))
        h = jnp.maximum(tp.column_parallel(x_rep, w1_shard), 0)
        return tp.row_parallel(h, w2_shard)[None]

    out = m4j.spmd(per_rank, mesh=mesh, in_specs=P(), out_specs=P("mpi"))(x)
    np.testing.assert_allclose(
        np.asarray(out[0]), np.asarray(dense), rtol=1e-4, atol=1e-4
    )


def test_tp_transpose_matvec(mesh):
    # the reference's distributed-matvec + linear_transpose identity
    # (test_allreduce_matvec.py:43-66 there): A column-split, transpose of
    # the sharded matvec equals the dense transpose matvec
    rng = np.random.RandomState(2)
    a = rng.randn(6, N * 2).astype(np.float32)
    x = rng.randn(N * 2).astype(np.float32)

    def matvec(x_shards):
        def per_rank(xs):
            r = jax.lax.axis_index("mpi")
            a_shard = jax.lax.dynamic_slice(
                jnp.asarray(a), (0, r * 2), (6, 2)
            )
            return m4j.allreduce(a_shard @ xs, op=m4j.SUM)[None]

        return m4j.spmd(per_rank, mesh=mesh)(x_shards).reshape(N, 6)[0]

    np.testing.assert_allclose(
        np.asarray(matvec(jnp.asarray(x))), a @ x, rtol=1e-4, atol=1e-4
    )
    ct = rng.randn(6).astype(np.float32)
    (xt,) = jax.linear_transpose(matvec, jnp.asarray(x))(jnp.asarray(ct))
    np.testing.assert_allclose(np.asarray(xt), a.T @ ct, rtol=1e-4, atol=1e-4)


def test_pipeline_matches_sequential(mesh):
    # N stages, each y = relu(x @ w_s); pipeline == sequential composition
    rng = np.random.RandomState(3)
    d = 8
    ws = rng.randn(N, d, d).astype(np.float32) * 0.4
    m = 5  # microbatches
    xs = rng.randn(m, 2, d).astype(np.float32)

    seq = jnp.asarray(xs)
    for s in range(N):
        seq = jnp.maximum(seq @ ws[s], 0)

    def per_rank(w_all, mb):
        r = jax.lax.axis_index("mpi")
        w_mine = jax.lax.dynamic_index_in_dim(w_all, r, 0, keepdims=False)
        out = pipeline_apply(
            lambda w, x: jnp.maximum(x @ w, 0), w_mine, mb, axis="mpi"
        )
        return out[None]

    out = m4j.spmd(
        per_rank, mesh=mesh, in_specs=(P(), P()), out_specs=P("mpi")
    )(jnp.asarray(ws), jnp.asarray(xs))
    # outputs valid on the last stage
    np.testing.assert_allclose(
        np.asarray(out[N - 1]), np.asarray(seq), rtol=1e-4, atol=1e-4
    )
    # other stages masked to zero
    assert float(np.abs(np.asarray(out[0])).max()) == 0.0
