"""Accuracy harness gate: DP GPT-2 training steps with int8-quantized
gradient allreduce must track the exact-SUM loss within the documented
relative bound (docs/usage.md § Quantized collectives).

The harness replays the NATIVE qring/qrd wire arithmetic through the
numpy simulators (bit-identical to the library — tests/test_quant.py
pins that), so this runs deterministically under CPU-only tier-1 with
no transport."""

import importlib.util
import pathlib
import sys

import pytest

pytest.importorskip("jax")

REPO = pathlib.Path(__file__).resolve().parents[1]


def _load_harness():
    spec = importlib.util.spec_from_file_location(
        "m4j_quant_accuracy_harness",
        REPO / "benchmarks" / "quant_accuracy.py")
    mod = importlib.util.module_from_spec(spec)
    sys.modules["m4j_quant_accuracy_harness"] = mod
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.parametrize("algo", ["auto", "qring", "qrd"])
def test_quantized_gradient_training_tracks_exact_loss(algo):
    harness = _load_harness()
    lines = []
    summary = harness.run_harness(steps=6, nshards=3, algo=algo,
                                  seed=0, emit=lines.append)
    assert summary["within_bound"], summary
    assert summary["max_rel_diff"] < summary["bound"]
    # every step emitted a record, and the exact run really trained
    # (the bound means nothing against a frozen model)
    assert len(lines) == 6 + 1
    assert summary["final_loss_exact"] != pytest.approx(
        float(__import__("json").loads(lines[0])["loss_exact"]), abs=1e-6)


def test_harness_is_deterministic():
    harness = _load_harness()
    s1 = harness.run_harness(steps=3, nshards=2, algo="qrd", seed=1,
                             emit=lambda _: None)
    s2 = harness.run_harness(steps=3, nshards=2, algo="qrd", seed=1,
                             emit=lambda _: None)
    assert s1 == s2
