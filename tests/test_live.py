"""Live re-tuning subsystem (mpi4jax_tpu/live): the drift detector's
flag/no-flag behavior on contended vs quiescent phases, the epoch
rendezvous' agreement and reentrancy properties against a fake bridge
(two simulated ranks in lockstep), the controller's candidate-table
build (baseline overlay -> winner flip), the strict LIVE_* knob
parsers, the serving retune-flag consumption, and — against the real
native transport on a size-1 loopback comm — the two-consumer obs-ring
contract: the peek cursor never steals events from the destructive
drain, so a run with an armed controller still dumps a byte-complete
trace.

No ranks, no sockets (except the loopback self-sends the native ring
tests use); loads under an ALIAS package name like test_serving.py
does, so old-jax containers run everything."""

import ctypes
import importlib
import json
import os
import pathlib
import shutil
import subprocess
import sys
import types

import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

try:
    from mpi4jax_tpu import live, tune
    from mpi4jax_tpu.live import _controller, _drift, _swap
    from mpi4jax_tpu.obs import _native as obs_native
    from mpi4jax_tpu.utils import config
except ImportError:
    _ALIAS = "m4j_lv"
    if _ALIAS not in sys.modules:
        _pkg = types.ModuleType(_ALIAS)
        _pkg.__path__ = [str(REPO / "mpi4jax_tpu")]
        sys.modules[_ALIAS] = _pkg
    live = importlib.import_module(_ALIAS + ".live")
    tune = importlib.import_module(_ALIAS + ".tune")
    _controller = importlib.import_module(_ALIAS + ".live._controller")
    _drift = importlib.import_module(_ALIAS + ".live._drift")
    _swap = importlib.import_module(_ALIAS + ".live._swap")
    obs_native = importlib.import_module(_ALIAS + ".obs._native")
    config = importlib.import_module(_ALIAS + ".utils.config")

_model = tune._submodule("_model")


def _ev(op="Allreduce", nbytes=262144, dur_s=1e-4, algo="ring"):
    return {"name": op, "src": "native", "ts_us": 0.0,
            "dur_us": dur_s * 1e6, "wait_us": 0.0, "dispatch_us": 0.0,
            "bytes": int(nbytes), "peer": -1, "tag": 0, "algo": algo}


def _baseline_model(tmp_path=None):
    """ring predicted fast, rd a known modest alternative."""
    m = _model.CostModel(world_size=2, source="test")
    m.add_sample("allreduce", "ring", 1024, 1e-6)
    m.add_sample("allreduce", "ring", 262144, 1e-5)
    m.add_sample("allreduce", "rd", 1024, 5e-6)
    m.add_sample("allreduce", "rd", 262144, 1e-4)
    return m


# ---------------- knobs ----------------


def test_live_knob_defaults(monkeypatch):
    for k in ("MPI4JAX_TPU_LIVE", "MPI4JAX_TPU_LIVE_WINDOW",
              "MPI4JAX_TPU_LIVE_DRIFT_PCT",
              "MPI4JAX_TPU_LIVE_COOLDOWN_OPS"):
        monkeypatch.delenv(k, raising=False)
    assert config.live_mode() == "off"
    assert config.live_window() == 256
    assert config.live_drift_pct() == 30.0
    assert config.live_cooldown_ops() == 64


def test_live_knob_parsers_are_strict_and_loud(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_LIVE", "auto")
    assert config.live_mode() == "auto"
    monkeypatch.setenv("MPI4JAX_TPU_LIVE", "yes")
    with pytest.raises(ValueError, match="MPI4JAX_TPU_LIVE"):
        config.live_mode()
    monkeypatch.setenv("MPI4JAX_TPU_LIVE_WINDOW", "0")
    with pytest.raises(ValueError, match="MPI4JAX_TPU_LIVE_WINDOW"):
        config.live_window()
    monkeypatch.setenv("MPI4JAX_TPU_LIVE_DRIFT_PCT", "-3")
    with pytest.raises(ValueError, match="MPI4JAX_TPU_LIVE_DRIFT_PCT"):
        config.live_drift_pct()
    monkeypatch.setenv("MPI4JAX_TPU_LIVE_COOLDOWN_OPS", "many")
    with pytest.raises(ValueError,
                       match="MPI4JAX_TPU_LIVE_COOLDOWN_OPS"):
        config.live_cooldown_ops()


# ---------------- drift detector ----------------


def test_contention_phase_is_flagged():
    """A quiescent-calibrated model + a contended phase -> exactly the
    drifted (op, band, algo) is flagged, with the right direction.

    Two-phase: the first crossing only arms suspicion (the window
    straddles the onset); a fresh post-onset window confirms."""
    det = _drift.DriftDetector(_baseline_model(), drift_pct=30.0,
                               min_samples=6)
    det.observe([_ev(dur_s=8e-5) for _ in range(8)])  # 8x the model
    assert det.drifts() == []          # phase 1: suspect, window cleared
    det.observe([_ev(dur_s=8e-5) for _ in range(8)])  # pure post-onset
    found = det.drifts()
    assert len(found) == 1
    d = found[0]
    assert (d.op, d.band, d.algo) == ("allreduce", 262144, "ring")
    assert d.deviation_pct > 30.0 and d.samples == 8
    assert d.predicted_s == pytest.approx(1e-5)


def test_transient_spike_never_confirms():
    """A suspect whose FRESH window comes back inside the threshold was
    a transient, not a regime change — suspicion is dropped and the key
    can re-arm later (no sticky state)."""
    det = _drift.DriftDetector(_baseline_model(), drift_pct=30.0,
                               min_samples=6)
    det.observe([_ev(dur_s=8e-5) for _ in range(8)])   # spike
    assert det.drifts() == []                          # armed
    det.observe([_ev(dur_s=1e-5) for _ in range(8)])   # back to normal
    assert det.drifts() == []                          # disarmed
    # a genuine regime change afterwards still takes two phases
    det.observe([_ev(dur_s=8e-5) for _ in range(8)])
    assert det.drifts() == []
    det.observe([_ev(dur_s=8e-5) for _ in range(8)])
    assert len(det.drifts()) == 1


def test_quiescent_run_raises_zero_flags():
    """Timings matching the model (within the threshold) never flag —
    the ZERO-swap guarantee's detector half."""
    det = _drift.DriftDetector(_baseline_model(), drift_pct=30.0,
                               min_samples=6)
    det.observe([_ev(dur_s=1.1e-5) for _ in range(50)])     # +10%
    det.observe([_ev(nbytes=1024, dur_s=0.9e-6) for _ in range(50)])
    assert det.drifts() == []
    assert det.events_used == 100


def test_faster_than_predicted_also_drifts():
    det = _drift.DriftDetector(_baseline_model(), drift_pct=30.0,
                               min_samples=6)
    det.observe([_ev(dur_s=1e-6) for _ in range(8)])  # 10x faster
    assert det.drifts() == []                         # armed
    det.observe([_ev(dur_s=1e-6) for _ in range(8)])
    found = det.drifts()
    assert len(found) == 1 and found[0].deviation_pct < -30.0


def test_detector_needs_min_samples_and_a_model():
    det = _drift.DriftDetector(None, drift_pct=30.0, min_samples=6)
    det.observe([_ev(dur_s=1.0) for _ in range(8)])
    assert det.drifts() == []                    # no model, no drift
    det.set_model(_baseline_model())
    det2 = _drift.DriftDetector(_baseline_model(), min_samples=6)
    det2.observe([_ev(dur_s=1.0) for _ in range(5)])
    assert det2.drifts() == []                   # below min_samples
    assert det.drifts() == []                    # armed only
    det.observe([_ev(dur_s=1.0) for _ in range(8)])
    assert len(det.drifts()) == 1


def test_detector_applies_tuner_event_filter():
    """Events the offline fit ignores (shm, per-leg tiers, ops spans,
    unknown algos) never feed drift — the model could not have learned
    them, so there is nothing to drift FROM."""
    det = _drift.DriftDetector(_baseline_model(), min_samples=2)
    shm = _ev(dur_s=1.0)
    shm["algo"] = "shm"
    tiered = _ev(dur_s=1.0)
    tiered["tier"] = "intra"
    span = _ev(dur_s=1.0)
    span["src"] = "ops"
    unseen = _ev(dur_s=1.0)
    unseen["algo"] = None
    det.observe([shm, tiered, span, unseen] * 4)
    assert det.events_used == 0 and det.drifts() == []


# ---------------- swap protocol (fake bridge) ----------------


class FakeBridge:
    """Two lockstep instances sharing ``channel`` emulate a 2-rank
    bcast: rank 0 appends its buffer, rank 1 reads in order."""

    def __init__(self, rank, channel):
        self.rank = rank
        self.channel = channel
        self._read = 0
        self.staged = []
        self.commits = []
        self.proto = None        # set for the reentrancy test
        self.stage_ok = True

    def coll_epoch(self):
        return self.commits[-1][1] if self.commits else 0

    def bcast(self, handle, buf, root):
        # a real bcast re-enters the boundary hook; emulate that
        if self.proto is not None:
            self.proto.on_boundary(handle)
        if self.rank == 0:
            self.channel.append(np.array(buf, copy=True))
            return buf
        out = self.channel[self._read]
        self._read += 1
        return out

    def stage_coll_table(self, coded):
        if not self.stage_ok:
            return False
        self.staged.append(coded)
        return True

    def commit_coll_tables(self, handle, epoch):
        self.commits.append((int(handle), int(epoch)))
        return True


def _pair(period=4):
    chan = []
    b0, b1 = FakeBridge(0, chan), FakeBridge(1, chan)
    p0 = _swap.SwapProtocol(b0, 7, 0, 2, period)
    p1 = _swap.SwapProtocol(b1, 7, 1, 2, period)
    b0.proto, b1.proto = p0, p1
    return (b0, p0), (b1, p1)


def _drive(p0, p1, n, handle=7):
    for _ in range(n):
        p0.on_boundary(handle)
        p1.on_boundary(handle)


def test_steady_state_is_header_only_and_swap_free():
    (b0, p0), (b1, p1) = _pair(period=4)
    _drive(p0, p1, 20)
    assert p0.boundaries == p1.boundaries == 20
    assert p0.epoch == p1.epoch == 0
    assert b0.commits == b1.commits == []
    # 5 rendezvous, each exactly ONE header bcast (16 bytes), no payload
    assert len(b0.channel) == 5
    assert all(c.nbytes == 16 and c[1] == 0 for c in b0.channel)


def test_proposal_commits_on_both_ranks_at_same_boundary():
    (b0, p0), (b1, p1) = _pair(period=4)
    _drive(p0, p1, 2)
    ep = p0.propose({"tables": {"0": [[0, 2]]},
                     "named": {"allreduce": [[0, "rd"]]},
                     "report": {"changes": ["allreduce@0: ring -> rd"],
                                "note": "test"}})
    assert ep == 1
    _drive(p0, p1, 2)                      # boundary 4: rendezvous
    assert p0.epoch == p1.epoch == 1
    assert b0.staged == b1.staged == [{0: [(0, 2)]}]
    assert b0.commits == b1.commits == [(7, 1)]
    assert [s["boundary"] for s in p0.swaps] \
        == [s["boundary"] for s in p1.swaps] == [4]
    assert not p0.pending()
    # cooldown accounting restarts at the swap boundary
    _drive(p0, p1, 3)
    assert p0.boundaries_since_swap() == 3


def test_rendezvous_bcasts_do_not_advance_the_boundary_clock():
    """The rendezvous' own bcasts re-enter the hook (FakeBridge.bcast
    calls on_boundary, like the real bridge); the _in_rv guard must
    keep them out of the counter or ranks desynchronize."""
    (b0, p0), (b1, p1) = _pair(period=2)
    p0.propose({"tables": {"0": [[0, 3]]}, "named": {}, "report": {}})
    _drive(p0, p1, 10)
    # exactly the 10 application collectives counted, nothing else
    assert p0.boundaries == p1.boundaries == 10
    assert p0.epoch == p1.epoch == 1


def test_off_comm_collectives_are_invisible():
    (b0, p0), (b1, p1) = _pair(period=4)
    for _ in range(9):
        p0.on_boundary(12345)              # some sub-comm's handle
    assert p0.boundaries == 0 and b0.channel == []


def test_newer_proposal_supersedes_unserved_one():
    (b0, p0), (b1, p1) = _pair(period=4)
    p0.propose({"tables": {"0": [[0, 2]]}, "named": {}, "report": {}})
    ep2 = p0.propose({"tables": {"0": [[0, 3]]}, "named": {},
                      "report": {}})
    _drive(p0, p1, 4)
    assert p0.epoch == p1.epoch == ep2 == 2
    assert b1.staged == [{0: [(0, 3)]}]    # only the latest installed
    assert len(b0.commits) == 1


def test_commit_failure_is_loud_not_silent():
    (b0, p0), (b1, p1) = _pair(period=2)
    b1.stage_ok = False                    # rank 1 cannot stage
    p0.propose({"tables": {"0": [[0, 2]]}, "named": {}, "report": {}})
    with pytest.raises(RuntimeError, match="stage_coll_table"):
        _drive(p0, p1, 2)


# ---------------- controller candidate build ----------------


class FakeSwap:
    def __init__(self):
        self.proposed = []
        self.epoch = 0

    def pending(self):
        return False

    def boundaries_since_swap(self):
        return 10**9

    def propose(self, payload):
        self.proposed.append(payload)
        self.epoch += 1
        return self.epoch


def test_candidate_overlay_flips_drifted_winner(tmp_path, monkeypatch):
    """The tentpole decision: observed ring timings overlay the
    baseline, alternatives keep their baseline predictions, and the
    ladder's winner at the drifted band flips ring -> rd."""
    mp = tmp_path / "model.json"
    mp.write_text(json.dumps(_baseline_model().to_json()))
    monkeypatch.setenv("MPI4JAX_TPU_TUNE_MODEL", str(mp))
    ctrl = _controller.Controller(
        None, 7, 0, 2, FakeSwap(), window=64, drift_pct=30.0,
        cooldown_ops=8)
    assert ctrl.status()["baseline"].startswith("model-file")
    slow_ring = [_ev(dur_s=5e-4) for _ in range(10)]   # 50x the model
    ctrl._events.extend(slow_ring)
    ctrl._detector.observe(slow_ring)
    assert ctrl._detector.drifts() == []     # phase 1: suspect only
    ctrl._events.extend(slow_ring)
    ctrl._detector.observe(slow_ring)        # pure post-onset window
    drifts = ctrl._detector.drifts()
    assert drifts
    tables, changes = ctrl._candidate(drifts)
    assert "allreduce" in tables
    assert _controller._lookup(tables["allreduce"], 262144) == "rd"
    assert "allreduce@262144: ring -> rd" in changes
    payload = ctrl._payload(tables, changes)
    coded = payload["tables"][str(tune.OP_KIND["allreduce"])]
    assert [0, tune.ALGO_CODES["ring"]] not in \
        [e for e in coded if e[0] >= 262144]
    # after the commit lands, the candidate IS the current table:
    # proposing it again would be a no-op (convergence, not flapping)
    ctrl.note_commit({"named": payload["named"]})
    ctrl._events.extend(slow_ring)
    ctrl._detector.observe(slow_ring)
    tables2, _ = ctrl._candidate(ctrl._detector.drifts() or drifts)
    assert "allreduce" not in tables2


def test_candidate_respects_quant_deny(tmp_path, monkeypatch):
    m = _baseline_model()
    m.add_sample("allreduce", "qring", 262144, 1e-7)  # tempting, lossy
    mp = tmp_path / "model.json"
    mp.write_text(json.dumps(m.to_json()))
    monkeypatch.setenv("MPI4JAX_TPU_TUNE_MODEL", str(mp))
    monkeypatch.setenv("MPI4JAX_TPU_COLL_QUANT", "deny")
    ctrl = _controller.Controller(
        None, 7, 0, 2, FakeSwap(), window=64, drift_pct=30.0,
        cooldown_ops=8)
    slow_ring = [_ev(dur_s=5e-4) for _ in range(10)]
    ctrl._events.extend(slow_ring)
    ctrl._detector.observe(slow_ring)
    assert ctrl._detector.drifts() == []     # phase 1: suspect only
    ctrl._events.extend(slow_ring)
    ctrl._detector.observe(slow_ring)
    tables, _ = ctrl._candidate(ctrl._detector.drifts())
    assert _controller._lookup(tables["allreduce"], 262144) == "rd"


# ---------------- serving retune flag ----------------


def test_consume_retune_resets_flag_and_counts():
    sched = types.SimpleNamespace(retune_requested=True)
    before = live.status()["retune_requests"]
    assert live.consume_retune(sched) is True
    assert sched.retune_requested is False
    assert live.status()["retune_requests"] == before + 1
    # idle flag: nothing consumed, nothing counted
    assert live.consume_retune(sched) is False
    assert live.status()["retune_requests"] == before + 1


# ---------------- native ring: the two-consumer contract ----------------


@pytest.fixture(scope="session")
def native_lib(tmp_path_factory):
    cxx = os.environ.get("CXX", "g++")
    if shutil.which(cxx) is None:
        pytest.skip(f"no C++ compiler ({cxx}) available")
    so = tmp_path_factory.mktemp("live_native") / "libtpucomm_live.so"
    src = REPO / "native" / "tpucomm.cc"
    res = subprocess.run(
        [cxx, "-O1", "-std=c++17", "-fPIC", "-Wall", "-pthread",
         "-shared", "-o", str(so), str(src), "-lrt"],
        capture_output=True, text=True, timeout=300,
    )
    assert res.returncode == 0, f"native build failed:\n{res.stderr[-2000:]}"
    lib = ctypes.CDLL(str(so))
    lib.tpucomm_init.restype = ctypes.c_int64
    lib.tpucomm_init.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                 ctypes.c_char_p]
    h = lib.tpucomm_init(0, 1, 47319, b"")
    assert h > 0, "size-1 comm init failed"
    yield lib, h
    lib.tpucomm_finalize(ctypes.c_int64(h))


def _self_send_recv(lib, h, tag):
    buf = np.arange(8.0)
    out = np.empty_like(buf)
    p = lambda a: a.ctypes.data_as(ctypes.c_void_p)  # noqa: E731
    assert lib.tpucomm_send(ctypes.c_int64(h), p(buf),
                            ctypes.c_int64(buf.nbytes), 0, tag) == 0
    assert lib.tpucomm_recv(ctypes.c_int64(h), p(out),
                            ctypes.c_int64(out.nbytes), 0, tag) == 0


def test_peek_consumer_leaves_drain_byte_complete(native_lib):
    """THE two-consumer contract: an armed live controller (peek
    cursor) interleaved with recording must not cost the end-of-run
    trace a single event."""
    lib, h = native_lib
    assert obs_native.peek_available(lib)
    obs_native.enable(lib, 64)
    cursor, peeked = 0, []
    for tag in range(70, 75):
        _self_send_recv(lib, h, tag)
        got, cursor, skipped = obs_native.peek(lib, cursor)
        assert skipped == 0
        peeked.extend(got)
    drained = obs_native.drain(lib)
    obs_native.disable(lib)
    # the follower saw every event AND the drain still owns every event
    assert len(peeked) == len(drained) == 10
    assert [(e["name"], e["tag"]) for e in peeked] \
        == [(e["name"], e["tag"]) for e in drained]


def test_peek_cursor_survives_destructive_drain(native_lib):
    """The double-consumption hazard the cursor fixes: a drain between
    two peeks must neither replay old events nor lose new ones."""
    lib, h = native_lib
    obs_native.enable(lib, 64)
    _self_send_recv(lib, h, 80)
    _self_send_recv(lib, h, 81)
    got, cursor, skipped = obs_native.peek(lib, 0)
    assert len(got) == 4 and cursor == 4 and skipped == 0
    assert len(obs_native.drain(lib)) == 4        # destructive consumer
    _self_send_recv(lib, h, 82)
    got, cursor, skipped = obs_native.peek(lib, cursor)
    obs_native.disable(lib)
    # exactly the two NEW events, no replay, no gap
    assert [e["tag"] for e in got] == [82, 82]
    assert cursor == 6 and skipped == 0


def test_peek_reports_overflow_as_skipped(native_lib):
    lib, h = native_lib
    obs_native.enable(lib, 16)
    for tag in range(90, 110):                    # 40 events, cap 16
        _self_send_recv(lib, h, tag)
    got, cursor, skipped = obs_native.peek(lib, 0, max_events=64)
    obs_native.disable(lib)
    assert len(got) == 16 and skipped == 24 and cursor == 40
    assert [e["tag"] for e in got] == \
        [tag for tag in range(102, 110) for _ in (0, 1)]
