"""Static verifier API (`analysis.check`): jaxpr-level extraction.

Exercises the tentpole's abstract path: a function is traced once per
simulated rank (no values, no comm), the closed jaxpr — including
scan/cond/while/pjit sub-jaxprs — is walked into per-rank schedules, and
the match simulation reports the findings.
"""

import warnings

import pytest

try:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    import mpi4jax_tpu as m4j
    from mpi4jax_tpu import analysis
except Exception as err:  # pragma: no cover - old-jax containers
    pytest.skip(f"mpi4jax_tpu not importable here: {err}",
                allow_module_level=True)


def test_clean_spmd_with_scan_and_nested_jit():
    def fn(x, comm):
        @jax.jit
        def inner(v):
            return m4j.allreduce(v, op=m4j.SUM, comm=comm)

        def body(c, _):
            return inner(c), None

        y, _ = jax.lax.scan(body, x, None, length=3)
        return m4j.sendrecv(y, shift=1, comm=comm)

    report = analysis.check(fn, jnp.ones((4,), jnp.float32), world_size=3)
    assert report.ok, report.format_table()
    # scan unrolled: 3 allreduces + 1 sendrecv per rank
    assert all(len(v) == 4 for v in report.schedules.values())


def test_rank_divergent_reduce_op_flagged():
    def fn(x):
        comm = m4j.get_default_comm()
        op = m4j.SUM if comm.rank() == 0 else m4j.MAX
        return m4j.allreduce(x, op=op, comm=comm)

    report = analysis.check(fn, jnp.ones((2,), jnp.float32), world_size=2)
    assert "reduce_op_mismatch" in report.kinds()
    f = next(f for f in report.findings if f.kind == "reduce_op_mismatch")
    assert set(f.ranks) == {0, 1}
    assert any("eqn" in s or ".py:" in s for s in f.sites), f.sites


def test_unpaired_send_flagged():
    def fn(x, comm):
        if comm.rank() == 0:
            m4j.send(x, dest=1, comm=comm)
        return x

    report = analysis.check(fn, jnp.ones((2,), jnp.float32), world_size=2)
    assert "unmatched_send" in report.kinds()


def test_deadlock_by_recv_order():
    def fn(x, comm):
        peer = 1 - comm.rank()
        got = m4j.recv(jnp.zeros_like(x), source=peer, comm=comm)
        m4j.send(got, dest=peer, comm=comm)
        return got

    report = analysis.check(fn, jnp.ones((2,), jnp.float32), world_size=2)
    assert "deadlock" in report.kinds()


def test_forked_token_chain_flagged():
    def fn(x, comm):
        with m4j.explicit_token_ordering():
            def f(v):
                t1 = m4j.create_token(v)
                rogue = m4j.create_token()
                a, _ = m4j.allreduce(v, op=m4j.SUM, comm=comm, token=t1)
                b, _ = m4j.allreduce(v, op=m4j.SUM, comm=comm,
                                     token=rogue)
                return a + b

            return jax.jit(f)(x)

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        report = analysis.check(fn, jnp.ones((2,), jnp.float32),
                                world_size=2)
    assert "token_violation" in report.kinds()


def test_threaded_token_chain_clean():
    def fn(x, comm):
        with m4j.explicit_token_ordering():
            def f(v):
                t = m4j.create_token(v)
                a, t = m4j.allreduce(v, op=m4j.SUM, comm=comm, token=t)
                b, t = m4j.allreduce(a, op=m4j.SUM, comm=comm, token=t)
                return b

            return jax.jit(f)(x)

    report = analysis.check(fn, jnp.ones((2,), jnp.float32), world_size=2)
    assert report.ok, report.format_table()


def test_cond_divergence_warns():
    def fn(x, comm):
        def f(v):
            return jax.lax.cond(
                v.sum() > 0,
                lambda u: m4j.allreduce(u, op=m4j.SUM, comm=comm),
                lambda u: u * 2.0,
                v,
            )

        return jax.jit(f)(x)

    report = analysis.check(fn, jnp.ones((2,), jnp.float32), world_size=2)
    assert "control_divergence" in report.kinds()


def test_while_comm_warns():
    def fn(x, comm):
        def f(v):
            return jax.lax.while_loop(
                lambda c: c.sum() < 10,
                lambda c: m4j.allreduce(c, op=m4j.SUM, comm=comm),
                v,
            )

        return jax.jit(f)(x)

    report = analysis.check(fn, jnp.ones((2,), jnp.float32), world_size=2)
    assert "comm_in_while" in report.kinds()


def test_vmap_and_grad_schedules_extracted():
    def fn(x, comm):
        def ar(v):
            return m4j.allreduce(v, op=m4j.SUM, comm=comm)

        batched = jax.vmap(ar)(jnp.stack([x, x]))
        g = jax.grad(lambda v: ar(v).sum())(x)
        return batched.sum() + g.sum()

    report = analysis.check(fn, jnp.ones((3,), jnp.float32), world_size=2)
    assert report.ok, report.format_table()
    assert all(len(v) >= 1 for v in report.schedules.values())


def test_abstract_comm_never_touches_native():
    comm = analysis.AbstractComm(0, 4)
    with pytest.raises(analysis.AnalysisError):
        comm.handle


def test_schedule_signatures_cover_every_world_primitive():
    """Every world primitive must export its schedule signature — a new
    op without one would be invisible to the verifier."""
    from jax._src import core as jcore

    from mpi4jax_tpu.ops import _world_impl as wi

    prims = [v for v in vars(wi).values()
             if isinstance(v, jcore.Primitive)
             and v.name.startswith("mpi4jax_tpu_")]
    prims += list(wi._token_variants.values())
    assert len(prims) >= 14
    for p in prims:
        assert wi.schedule_signature(p.name) is not None, p.name
