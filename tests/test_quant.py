"""Quantized collective wire formats (qring/qrd): the native codec
against its documented numpy reference (bit-identical — the accuracy
harness and the docs lean on the reference being the REAL format), the
schedule simulators' error bounds, the tune-layer gating, and the
observability wire_bytes/compression plumbing.

Runs under CPU-only tier-1: the native half drives a transport-only
build of tpucomm.cc through ctypes on a size-1 comm (no sockets), the
rest is pure Python loaded through the package or standalone.
"""

import ctypes
import importlib.util
import os
import pathlib
import shutil
import subprocess
import sys

import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parents[1]


def _load_file(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _load_quantized():
    try:
        from mpi4jax_tpu.ops import quantized

        return quantized
    except ImportError:
        # the module's jax imports are lazy; the numpy reference and
        # simulators work standalone
        import types

        pkg = types.ModuleType("m4j_q_pkg")
        pkg.__path__ = [str(REPO / "mpi4jax_tpu")]
        sys.modules.setdefault("m4j_q_pkg", pkg)
        return _load_file("m4j_quantized_test",
                          REPO / "mpi4jax_tpu/ops/quantized.py")


def _load_tune():
    try:
        from mpi4jax_tpu import tune

        return tune
    except ImportError:
        return _load_file("m4j_tune_quant_test",
                          REPO / "mpi4jax_tpu/tune/__init__.py")


q = _load_quantized()
tune = _load_tune()


# ---------------- native codec vs the numpy reference ----------------


@pytest.fixture(scope="module")
def native_lib(tmp_path_factory):
    cxx = os.environ.get("CXX", "g++")
    if shutil.which(cxx) is None:
        pytest.skip(f"no C++ compiler ({cxx}) available")
    so = tmp_path_factory.mktemp("quant_native") / "libtpucomm_quant.so"
    src = REPO / "native" / "tpucomm.cc"
    res = subprocess.run(
        [cxx, "-O2", "-std=c++17", "-fPIC", "-Wall", "-pthread", "-shared",
         "-o", str(so), str(src), "-lrt"],
        capture_output=True, text=True, timeout=300,
    )
    assert res.returncode == 0, f"native build failed:\n{res.stderr[-2000:]}"
    lib = ctypes.CDLL(str(so))
    lib.tpucomm_quant_packed_bytes.restype = ctypes.c_int64
    lib.tpucomm_quant_packed_bytes.argtypes = [ctypes.c_int64]
    lib.tpucomm_quant_pack.restype = ctypes.c_int
    lib.tpucomm_quant_unpack.restype = ctypes.c_int
    return lib


def _p(a):
    return a.ctypes.data_as(ctypes.c_void_p)


def _native_pack(lib, x, dtype_code):
    out = np.zeros(int(lib.tpucomm_quant_packed_bytes(x.size)), np.int8)
    rc = lib.tpucomm_quant_pack(_p(x), ctypes.c_int64(x.size), dtype_code,
                                _p(out))
    assert rc == 0
    return out


def test_packed_bytes_formula_and_block_sync(native_lib):
    """The wire layout is ceil(n/QUANT_BLOCK) f32 scales + n int8 codes;
    the native kQuantBlock and the Python QUANT_BLOCK must agree (the
    packed size at one-past-a-block boundary detects any drift)."""
    for n in (0, 1, q.QUANT_BLOCK - 1, q.QUANT_BLOCK, q.QUANT_BLOCK + 1,
              7 * q.QUANT_BLOCK + 13, 1 << 20):
        nb = (n + q.QUANT_BLOCK - 1) // q.QUANT_BLOCK
        assert native_lib.tpucomm_quant_packed_bytes(n) == (
            4 * nb + n if n > 0 else 0), n


@pytest.mark.parametrize("n", [1, 5, 255, 256, 257, 1000, 4096, 100_001])
def test_native_pack_bit_identical_to_reference(native_lib, n):
    rng = np.random.RandomState(n)
    for x in (
        (rng.randn(n) * rng.choice([1e-4, 1.0, 1e4])).astype(np.float32),
        np.zeros(n, np.float32),                       # all-zero blocks
        np.full(n, -3.25, np.float32),                 # constant
    ):
        packed = _native_pack(native_lib, x, 11)       # TPU_F32
        scales, codes = q.quant_pack_ref(x)
        ref = np.concatenate([scales.view(np.int8), codes])
        np.testing.assert_array_equal(packed, ref)
        # the whole-frame reference (what the ICI leg ships on the
        # leader tier) is the same bytes — three codecs, one format
        np.testing.assert_array_equal(q.quant_pack_wire_ref(x), ref)
        # unpack round-trips exactly the reference's dequantization
        back = np.empty(n, np.float32)
        rc = native_lib.tpucomm_quant_unpack(_p(packed), ctypes.c_int64(n),
                                             11, _p(back))
        assert rc == 0
        np.testing.assert_array_equal(back, q.quant_unpack_ref(scales,
                                                               codes))


def test_native_pack_error_bound(native_lib):
    """|dequant - x| <= blockwise absmax/254 (half a quantization step)."""
    rng = np.random.RandomState(0)
    x = (rng.randn(10_000) * 7).astype(np.float32)
    packed = _native_pack(native_lib, x, 11)
    back = np.empty(x.size, np.float32)
    assert native_lib.tpucomm_quant_unpack(_p(packed),
                                           ctypes.c_int64(x.size), 11,
                                           _p(back)) == 0
    for b in range((x.size + q.QUANT_BLOCK - 1) // q.QUANT_BLOCK):
        blk = slice(b * q.QUANT_BLOCK, min(x.size, (b + 1) * q.QUANT_BLOCK))
        bound = np.max(np.abs(x[blk])) / 127.0 * 0.5 + 1e-9
        assert np.max(np.abs(back[blk] - x[blk])) <= bound, b


def test_native_pack_rejects_integer_dtypes(native_lib):
    x = np.arange(16, dtype=np.int32)
    out = np.zeros(64, np.int8)
    assert native_lib.tpucomm_quant_pack(_p(x), ctypes.c_int64(16), 3,
                                         _p(out)) != 0  # TPU_I32


def test_native_pack_bf16(native_lib):
    """bf16 payloads convert through f32 exactly like the reference."""
    rng = np.random.RandomState(2)
    f = (rng.randn(777) * 3).astype(np.float32)
    bits = f.view(np.uint32)
    bf = ((bits + 0x7FFF + ((bits >> 16) & 1)) >> 16).astype(np.uint16)
    packed = _native_pack(native_lib, bf, 10)          # TPU_BF16
    f_from_bf = (bf.astype(np.uint32) << 16).view(np.float32)
    scales, codes = q.quant_pack_ref(f_from_bf)
    np.testing.assert_array_equal(
        packed, np.concatenate([scales.view(np.int8), codes]))


def test_wire_ref_matches_reference_layout():
    # pure numpy, no native build needed: the frame is scale bytes then
    # codes, nothing else (the ICI leg's _unpack_fold depends on it)
    rng = np.random.RandomState(3)
    for n in (1, q.QUANT_BLOCK - 1, q.QUANT_BLOCK, q.QUANT_BLOCK + 1, 1000):
        x = (rng.randn(n) * 5).astype(np.float32)
        scales, codes = q.quant_pack_ref(x)
        nb = (n + q.QUANT_BLOCK - 1) // q.QUANT_BLOCK
        wire = q.quant_pack_wire_ref(x)
        assert wire.shape == (4 * nb + n,) and wire.dtype == np.int8
        np.testing.assert_array_equal(
            wire, np.concatenate([scales.view(np.int8), codes]))


def _pallas_codec_ok():
    """The in-kernel codec needs the gated jax AND an importable Pallas
    TPU backend (interpret mode runs it on CPU)."""
    try:
        import jax

        parts = []
        for piece in jax.__version__.split(".")[:3]:
            parts.append(int("".join(c for c in piece if c.isdigit()) or 0))
        if tuple(parts) < (0, 6, 0):
            return False
        from mpi4jax_tpu.ops import pallas_collectives  # noqa: F401

        return True
    except Exception:
        return False


@pytest.mark.skipif(not _pallas_codec_ok(),
                    reason="needs jax >= 0.6 with Pallas")
@pytest.mark.parametrize("n", [1, 255, 256, 257, 1000, 4096])
def test_pallas_pack_bit_identical_to_reference(n):
    """The cross-ISA contract extended to the THIRD codec: the Pallas
    in-kernel pack (interpret mode here; the leader leg of the ICI data
    plane on a slice) emits byte-identical wire frames to
    ``quant_pack_ref``/``tpucomm_quant_pack``."""
    import jax.numpy as jnp

    from mpi4jax_tpu.ops import pallas_collectives as pc

    rng = np.random.RandomState(n)
    for x in (
        (rng.randn(n) * rng.choice([1e-4, 1.0, 1e4])).astype(np.float32),
        np.zeros(n, np.float32),
        np.full(n, -3.25, np.float32),
    ):
        wire = np.asarray(pc.quant_pack_pallas(jnp.asarray(x),
                                               interpret=True))
        np.testing.assert_array_equal(wire, q.quant_pack_wire_ref(x))


# ---------------- schedule simulators (accuracy-harness backbone) -----


@pytest.mark.parametrize("size", [2, 3, 4, 5, 8])
@pytest.mark.parametrize("sim", ["qring", "qrd"])
def test_simulators_track_exact_sum(size, sim):
    rng = np.random.RandomState(size)
    n = 3 * q.QUANT_BLOCK + 17
    parts = [(rng.randn(n) * (r + 1)).astype(np.float32)
             for r in range(size)]
    exact = np.sum(np.stack(parts), axis=0, dtype=np.float64)
    fn = q.simulate_qring_sum if sim == "qring" else q.simulate_qrd_sum
    got = fn(parts)
    denom = max(np.max(np.abs(exact)), 1e-6)
    assert np.max(np.abs(got - exact)) / denom < 3e-2, sim


def test_simulators_are_deterministic():
    rng = np.random.RandomState(9)
    parts = [rng.randn(1000).astype(np.float32) for _ in range(3)]
    assert np.array_equal(q.simulate_qring_sum(parts),
                          q.simulate_qring_sum(parts))
    assert np.array_equal(q.simulate_qrd_sum(parts),
                          q.simulate_qrd_sum(parts))


def test_simulator_size_one_is_identity():
    x = np.arange(7, dtype=np.float32)
    np.testing.assert_array_equal(q.simulate_qring_sum([x]), x)
    np.testing.assert_array_equal(q.simulate_qrd_sum([x]), x)


# ---------------- tune-layer gating ----------------


def test_quantized_algorithm_maps_to_twin():
    # defaults: tree below 64KB -> qrd, ring above -> qring
    assert tune.quantized_algorithm(1024) == "qrd"
    assert tune.quantized_algorithm(16 << 20) == "qring"
    assert tune.QUANT_TWIN["tree"] == "qrd"
    assert tune.EXACT_TWIN["qring"] == "ring"


def test_qalgos_rejected_for_allgather():
    with pytest.raises(ValueError, match="allreduce-only"):
        tune.set_algorithm("allgather", "qring")
    with pytest.raises(ValueError, match="allreduce-only"):
        tune._validate_table({"allgather": [(0, "qrd")]})
    # allreduce rows are legal and win the merge
    tune.set_algorithm("allreduce", "qring", min_bytes=1 << 20)
    try:
        assert tune.get_algorithm("allreduce", 4 << 20) == "qring"
        assert tune.quantized_algorithm(4 << 20) == "qring"
    finally:
        tune.clear_overrides()


def test_env_bare_quantized_name_governs_allreduce_only(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_COLL_ALGO", "qring")
    table = tune.decision_table()
    assert table["allreduce"] == [(0, "qring")]
    # allgather keeps its normal selection (no quantized schedule)
    assert all(a not in tune.QUANT_ALGOS for _, a in table["allgather"])


def test_wire_fractions_and_promotion_gates(monkeypatch, tmp_path):
    import json

    def ev(algo, nbytes, dur_us, wait_us):
        return {"name": "Allreduce", "src": "native", "ts_us": 0.0,
                "dur_us": dur_us, "wait_us": wait_us, "dispatch_us": 0.0,
                "bytes": nbytes, "peer": -1, "tag": 0, "algo": algo}

    # wire-bound ring at 1 MB, wait-bound ring at 128 KB
    events = ([ev("ring", 1 << 20, 1000.0, 10.0)] * 3
              + [ev("ring", 128 << 10, 1000.0, 900.0)] * 3
              + [ev("rd", 1 << 20, 5000.0, 10.0)] * 3
              + [ev("rd", 128 << 10, 5000.0, 10.0)] * 3)
    fr = tune.wire_fractions_from_events(events)
    assert fr["allreduce"][1 << 20]["ring"] > 0.9
    assert fr["allreduce"][128 << 10]["ring"] < 0.2

    try:
        from mpi4jax_tpu import obs
    except ImportError:
        obs = _load_file(
            "m4j_obs_quant_test",
            REPO / "mpi4jax_tpu/obs/__init__.py")
    base = str(tmp_path / "rec.json")
    # write a part so cache_from_trace's loader path is exercised
    import types  # noqa: F401

    part = {"version": 1, "rank": 0, "size": 2, "clock_offset_us": 0.0,
            "dropped": {}, "events": events}
    p0 = f"{base}.rank0.json"
    with open(p0, "w") as f:
        json.dump(part, f)
    cache = str(tmp_path / "cache.json")
    tune.cache_from_trace([p0], cache_path_override=cache)
    table = json.load(open(cache))["table"]["allreduce"]
    # 128 KB: wait-bound -> stays exact; 1 MB: wire-bound -> promoted
    assert table == [[0, "ring"], [1 << 20, "qring"]]

    # deny gate: no promotion when int8 wire formats are vetoed
    monkeypatch.setenv("MPI4JAX_TPU_COLL_QUANT", "deny")
    cache2 = str(tmp_path / "cache2.json")
    tune.cache_from_trace([p0], cache_path_override=cache2)
    table2 = json.load(open(cache2))["table"]["allreduce"]
    assert all(a not in tune.QUANT_ALGOS for _, a in table2)


# ---------------- obs wire_bytes / compression plumbing ----------------


def _load_obs():
    try:
        from mpi4jax_tpu import obs

        return obs
    except ImportError:
        return _load_file("m4j_obs_quant_test2",
                          REPO / "mpi4jax_tpu/obs/__init__.py")


def test_stats_compression_column_only_when_it_differs():
    obs = _load_obs()
    exact = {"name": "Allreduce", "src": "native", "ts_us": 0.0,
             "dur_us": 100.0, "wait_us": 0.0, "dispatch_us": 0.0,
             "bytes": 4096, "peer": -1, "tag": 0, "algo": "ring"}
    quant = dict(exact, algo="qring", wire_bytes=1088, ts_us=200.0)
    stats = obs.summarize([exact, quant])
    rows = {r["algo"]: r for r in stats["per_op"]}
    assert "compression" not in rows["ring"]
    assert rows["qring"]["wire_bytes"] == 1088
    assert rows["qring"]["compression"] == pytest.approx(4096 / 1088,
                                                         rel=1e-3)
    # eff_GBps stays LOGICAL bytes over wall time for both rows
    assert rows["qring"]["eff_GBps"] == rows["ring"]["eff_GBps"]
    # the rendered table gains the column only because a quantized row
    # is present
    table = obs.render_table(stats)
    assert "compression" in table
    table_exact = obs.render_table(obs.summarize([exact]))
    assert "compression" not in table_exact


def test_trace_and_chrome_roundtrip_carry_wire_bytes(tmp_path):
    obs = _load_obs()
    quant = {"name": "Allreduce", "src": "native", "ts_us": 10.0,
             "dur_us": 50.0, "wait_us": 5.0, "dispatch_us": 0.0,
             "bytes": 4096, "wire_bytes": 1088, "peer": -1, "tag": 0,
             "algo": "qring"}
    trace = obs.merge_parts([{"rank": 0, "size": 2, "events": [quant],
                              "dropped": {}}])
    assert obs.validate_chrome_trace(trace) == []
    span = next(e for e in trace["traceEvents"]
                if e.get("ph") == "X" and e.get("cat") == "native")
    assert span["args"]["wire_bytes"] == 1088
    assert span["args"]["bytes"] == 4096
    # load_events maps the chrome span back to a canonical event
    import json

    path = tmp_path / "merged.json"
    path.write_text(json.dumps(trace))
    events, _ = obs.load_events(str(path))
    assert events[0]["wire_bytes"] == 1088


def test_native_drain_defaults_wire_bytes_to_logical(native_lib):
    """Exact ops drain with wire_bytes == bytes (schema compatibility:
    every consumer may default the field)."""
    nat = _load_file("m4j_obs_native_quant_test",
                     REPO / "mpi4jax_tpu/obs/_native.py")
    assert nat.available(native_lib)
    lib = native_lib
    lib.tpucomm_init.restype = ctypes.c_int64
    lib.tpucomm_init.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                 ctypes.c_char_p]
    h = lib.tpucomm_init(0, 1, 47423, b"")
    assert h > 0
    try:
        nat.enable(lib, 64)
        x = np.arange(64.0, dtype=np.float32)
        out = np.empty_like(x)
        rc = lib.tpucomm_allreduce(ctypes.c_int64(h), _p(x), _p(out),
                                   ctypes.c_int64(64), 11, 0)
        assert rc == 0
        events = nat.drain(lib)
        nat.disable(lib)
        assert events and all(e["wire_bytes"] == e["bytes"]
                              for e in events)
    finally:
        lib.tpucomm_finalize(ctypes.c_int64(h))


# ---------------- packed one-leg scale transport (Python schedule) ----


def test_pack_scales_bitcast_roundtrip():
    jax = pytest.importorskip("jax")
    if not hasattr(q, "_pack_scales"):
        pytest.skip("standalone quantized module")
    import jax.numpy as jnp

    rng = np.random.RandomState(4)
    codes = jnp.asarray(rng.randint(-127, 128, (3, 40)), jnp.int8)
    scales = jnp.asarray(rng.rand(3).astype(np.float32) * 1e-3)
    packed = q._pack_scales(codes, scales)
    assert packed.shape == (3, 44) and packed.dtype == jnp.int8
    q2, s2 = q._unpack_scales(packed)
    np.testing.assert_array_equal(np.asarray(q2), np.asarray(codes))
    # the bitcast preserves the EXACT scale bits — this is what makes
    # the one-leg schedule bit-compatible with the old two-leg one
    np.testing.assert_array_equal(np.asarray(s2), np.asarray(scales))


# ------- one-leg packed schedule ≡ historic two-leg schedule ----------
#
# The satellite contract: packing the scales into the payload halves the
# round count with BIT-COMPATIBLE results.  A little oracle runs the
# per-rank schedule for every virtual rank, resolving each collective
# leg when all ranks have posted to it — no mesh, no transport, works on
# any jax.


class _Stop(Exception):
    pass


def _run_world(xs, body):
    """Execute ``body(x_r, size, alltoall, allgather)`` for every rank
    of a virtual world, resolving collective legs in program order."""
    n = len(xs)
    resolved = []  # [(kind, [out_r, ...])]
    while True:
        posted = [None] * n
        kinds = [None] * n
        outs = [None] * n
        stopped = False
        for r in range(n):
            counter = {"i": 0}

            def leg(kind, arr, r=r):
                i = counter["i"]
                counter["i"] += 1
                if i < len(resolved):
                    rkind, router = resolved[i]
                    assert rkind == kind, "ranks diverged on leg order"
                    return router[r]
                posted[r] = np.asarray(arr)
                kinds[r] = kind
                raise _Stop()

            try:
                outs[r] = body(
                    xs[r], n,
                    lambda rows, r=r: leg("alltoall", rows, r),
                    lambda row, r=r: leg("allgather", row, r))
            except _Stop:
                stopped = True
        if not stopped:
            return outs
        assert all(k == kinds[0] for k in kinds), kinds
        if kinds[0] == "alltoall":
            router = [np.stack([posted[j][r] for j in range(n)])
                      for r in range(n)]
        else:
            router = [np.stack(posted)] * n
        resolved.append((kinds[0], router))


def _two_leg_schedule(x, size, alltoall, allgather):
    """The historic schedule: separate alltoall/allgather legs for the
    scales (four collective legs total)."""
    import jax.numpy as jnp

    orig_dtype = x.dtype
    flat, pad = q._pad_to(x, size)
    chunks = flat.reshape(size, -1)
    codes, scale = q._quantize(chunks)
    q_t = alltoall(codes)
    s_t = alltoall(scale.reshape(size, 1))
    partial = q_t.astype(jnp.float32) * s_t
    mine = jnp.sum(partial, axis=0)
    q2, s2 = q._quantize(mine[None])
    q_all = allgather(q2[0])
    s_all = allgather(s2[0])
    full = (q_all.astype(jnp.float32) * s_all[:, None]).reshape(-1)
    if pad:
        full = full[:-pad]
    return full.reshape(x.shape).astype(orig_dtype)


def test_packed_schedule_bit_compatible_with_two_leg():
    pytest.importorskip("jax")
    if not hasattr(q, "_quantized_schedule"):
        pytest.skip("standalone quantized module")
    import jax.numpy as jnp

    rng = np.random.RandomState(11)
    n = 3
    xs = [jnp.asarray((rng.randn(257) * (r + 1)).astype(np.float32))
          for r in range(n)]
    packed_out = _run_world(xs, q._quantized_schedule)
    two_leg_out = _run_world(xs, _two_leg_schedule)
    for r in range(n):
        np.testing.assert_array_equal(np.asarray(packed_out[r]),
                                      np.asarray(two_leg_out[r]))
    # and both approximate the exact sum within the documented bound
    exact = np.sum(np.stack([np.asarray(x) for x in xs]), axis=0,
                   dtype=np.float64)
    denom = max(np.max(np.abs(exact)), 1e-6)
    assert np.max(np.abs(np.asarray(packed_out[0]) - exact)) / denom < 3e-2
