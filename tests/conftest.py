"""Test harness: virtual 8-device CPU mesh.

The reference runs its suite twice — single process and ``mpirun -np 2``
(/root/reference/docs/developers.rst).  Here the primary tier is SPMD over a
mesh, so the suite runs once over an 8-device *virtual CPU mesh*
(xla_force_host_platform_device_count), which exercises every collective
path the way 8 TPU chips would; world-tier tests spawn real subprocesses via
the launcher.
"""

import os

# Must happen before the first JAX backend initialization.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    )
os.environ.setdefault("XLA_PYTHON_CLIENT_PREALLOCATE", "false")

import jax

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:  # pragma: no cover - backend already initialized
    pass


def pytest_report_header(config):
    import mpi4jax_tpu

    return [
        f"mpi4jax_tpu {mpi4jax_tpu.__version__} | jax {jax.__version__} | "
        f"devices: {len(jax.devices())} x {jax.devices()[0].platform}"
    ]
