"""Unit tests for the collective algorithm engine's selection logic
(mpi4jax_tpu/tune): defaults, env/API override layering, bucket lookup,
and the persistent cache round-trip.  Pure stdlib — the tune package is
importable without jax or the native transport, and these tests load it
standalone when the full package import is unavailable."""

import importlib.util
import json
import os
import pathlib

import pytest

REPO = pathlib.Path(__file__).resolve().parents[1]


def _load_tune():
    try:
        from mpi4jax_tpu import tune

        return tune
    except ImportError:
        # the package __init__ gates on the jax version; the engine
        # itself is stdlib-only and documented standalone-importable
        spec = importlib.util.spec_from_file_location(
            "m4j_tune_standalone", REPO / "mpi4jax_tpu/tune/__init__.py"
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod


tune = _load_tune()


@pytest.fixture(autouse=True)
def _clean_engine_state(monkeypatch):
    monkeypatch.delenv("MPI4JAX_TPU_COLL_ALGO", raising=False)
    monkeypatch.delenv("MPI4JAX_TPU_TUNE_CACHE", raising=False)
    tune._cache_table = None
    tune._cache_origin = None
    for op in tune.OPS:
        tune._overrides[op].clear()
    yield
    tune._cache_table = None
    tune._cache_origin = None
    for op in tune.OPS:
        tune._overrides[op].clear()


def test_defaults_mirror_builtin_heuristic():
    assert tune.get_algorithm("allreduce", 1024) == "tree"
    assert tune.get_algorithm("allreduce", 64 * 1024) == "ring"
    assert tune.get_algorithm("allreduce", 16 << 20) == "ring"
    assert tune.get_algorithm("allgather", 1024) == "ring"
    assert tune.sources() == ["defaults"]


def test_env_force_all_ops(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_COLL_ALGO", "ring")
    assert tune.get_algorithm("allreduce", 16) == "ring"
    assert tune.get_algorithm("allgather", 16 << 20) == "ring"
    assert "env:MPI4JAX_TPU_COLL_ALGO" in tune.sources()


def test_env_per_op(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_COLL_ALGO", "allreduce=rd,allgather=tree")
    assert tune.get_algorithm("allreduce", 16 << 20) == "rd"
    assert tune.get_algorithm("allgather", 64) == "tree"


def test_env_invalid_raises(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_COLL_ALGO", "warp-drive")
    with pytest.raises(ValueError, match="unknown collective algorithm"):
        tune.get_algorithm("allreduce", 64)
    monkeypatch.setenv("MPI4JAX_TPU_COLL_ALGO", "teleport=ring")
    with pytest.raises(ValueError, match="unknown collective op"):
        tune.get_algorithm("allreduce", 64)


def test_api_override_and_clear():
    tune.set_algorithm("allreduce", "rd")
    assert tune.get_algorithm("allreduce", 16 << 20) == "rd"
    assert "api" in tune.sources()
    # bucketed override: the default tree keeps the small end
    tune.clear_overrides()
    tune.set_algorithm("allreduce", "rd", min_bytes=1 << 20)
    assert tune.get_algorithm("allreduce", 1024) == "tree"
    assert tune.get_algorithm("allreduce", 2 << 20) == "rd"
    tune.clear_overrides()
    assert tune.get_algorithm("allreduce", 16 << 20) == "ring"


def test_env_beats_api_override(monkeypatch):
    tune.set_algorithm("allreduce", "tree")
    monkeypatch.setenv("MPI4JAX_TPU_COLL_ALGO", "allreduce=ring")
    assert tune.get_algorithm("allreduce", 64) == "ring"


def test_algo_name_aliases():
    tune.set_algorithm("allreduce", "recursive_doubling")
    assert tune.get_algorithm("allreduce", 64) == "rd"
    with pytest.raises(ValueError):
        tune.set_algorithm("allreduce", "shm")  # report-only, not forcible


def test_cache_round_trip(tmp_path):
    p = tmp_path / "tune_4.json"
    table = {"allreduce": [(0, "rd"), (1 << 20, "ring")],
             "allgather": [(0, "ring")]}
    meas = [{"op": "allreduce", "bytes": 1024, "algo": "rd",
             "seconds": 1e-5}]
    written = tune.save_cache(4, table, meas, path=str(p))
    assert written == str(p)
    loaded = tune.load_cache(4, path=str(p))
    assert loaded == {"allreduce": [(0, "rd"), (1048576, "ring")],
                      "allgather": [(0, "ring")]}
    # the loaded cache layers under overrides/env
    assert tune.get_algorithm("allreduce", 1024) == "rd"
    assert tune.get_algorithm("allreduce", 2 << 20) == "ring"
    assert any(s.startswith("cache:") for s in tune.sources())
    data = json.loads(p.read_text())
    assert data["version"] == tune.CACHE_VERSION
    assert data["world_size"] == 4
    assert data["measurements"] == meas


def test_cache_malformed_rejected(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"version": 1, "table": {"allreduce": [[0]]}}))
    with pytest.raises(ValueError, match="malformed"):
        tune.load_cache(4, path=str(p))
    p.write_text(json.dumps({"version": 99, "table": {}}))
    with pytest.raises(ValueError, match="version"):
        tune.load_cache(4, path=str(p))
    p.write_text(json.dumps({"hello": 1}))
    with pytest.raises(ValueError, match="table"):
        tune.load_cache(4, path=str(p))
    with pytest.raises(FileNotFoundError):
        tune.load_cache(4, path=str(tmp_path / "missing.json"))


def test_cache_world_size_mismatch_rejected(tmp_path):
    p = tmp_path / "tune_4.json"
    tune.save_cache(4, {"allreduce": [(0, "rd")]}, path=str(p))
    with pytest.raises(ValueError, match="world size"):
        tune.load_cache(32, path=str(p))
    assert tune._cache_table is None  # nothing half-loaded
    assert tune.load_cache(4, path=str(p))  # the measured size loads


def test_default_algorithm_ignores_overrides():
    tune.set_algorithm("allreduce", "rd")
    assert tune.default_algorithm("allreduce", 1024) == "tree"
    assert tune.default_algorithm("allreduce", 16 << 20) == "ring"
    assert tune.default_algorithm("allgather", 64) == "ring"


def test_cache_path_knob(monkeypatch, tmp_path):
    monkeypatch.setenv("MPI4JAX_TPU_TUNE_CACHE", str(tmp_path / "x.json"))
    assert tune.cache_path(8) == str(tmp_path / "x.json")
    monkeypatch.delenv("MPI4JAX_TPU_TUNE_CACHE")
    monkeypatch.setenv("XDG_CACHE_HOME", str(tmp_path))
    assert tune.cache_path(8) == str(tmp_path / "mpi4jax_tpu" / "tune_8.json")


def test_entries_from_measurements():
    assert tune.entries_from_measurements({}) == []
    assert tune.entries_from_measurements(
        {1024: "tree", 65536: "ring", 262144: "ring"}
    ) == [(0, "tree"), (65536, "ring")]
    assert tune.entries_from_measurements(
        {1024: "rd", 65536: "ring", 262144: "rd"}
    ) == [(0, "rd"), (65536, "ring"), (262144, "rd")]


def test_describe_shape():
    info = tune.describe()
    assert set(info) == {"sources", "table", "picks"}
    for op in tune.OPS:
        assert info["picks"][op]["1KB"] in ("ring", "rd", "tree")
        assert info["picks"][op]["16MB"] in ("ring", "rd", "tree")
