"""Accuracy harness gate: expert-parallel MoE training steps with
int8-quantized dispatch/combine must track the exact-wire loss within
the documented relative bound (docs/usage.md § MoE expert parallelism).

The harness replays the NATIVE qalltoall codec arithmetic through a jnp
twin; the twin is bit-pinned here against ``ops/quantized.py``'s
reference codec (which tests/test_quant.py pins against the real
library), so this runs deterministically under CPU-only tier-1 with no
transport."""

import importlib.util
import pathlib
import sys

import numpy as np
import pytest

pytest.importorskip("jax")

REPO = pathlib.Path(__file__).resolve().parents[1]


def _load(name, relpath):
    spec = importlib.util.spec_from_file_location(name, REPO / relpath)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _load_harness():
    return _load("m4j_moe_accuracy_harness", "benchmarks/moe_accuracy.py")


def _load_codec():
    return _load("m4j_moe_accuracy_codec", "mpi4jax_tpu/ops/quantized.py")


@pytest.mark.parametrize("n", [3, 256, 513, 1030])
def test_jnp_codec_twin_matches_reference_bitwise(n):
    # the harness's qdq IS the wire arithmetic only if it matches the
    # reference codec bit for bit (the reference is itself pinned
    # against the native library by tests/test_quant.py)
    harness = _load_harness()
    q = _load_codec()
    rng = np.random.RandomState(7)
    for scale in (1.0, 1e-3, 40.0):
        x = (rng.randn(n) * scale).astype(np.float32)
        scales, codes = q.quant_pack_ref(x)
        want = q.quant_unpack_ref(scales, codes)
        got = np.asarray(harness.qdq_vals(x))
        assert np.array_equal(got, want), (
            f"n={n} scale={scale}: jnp codec twin diverges from the "
            f"reference (maxdiff {np.max(np.abs(got - want))})")
    # all-zero blocks: scale 0, exact zeros back
    z = np.zeros(n, np.float32)
    assert np.array_equal(np.asarray(harness.qdq_vals(z)), z)


def test_quantized_moe_training_tracks_exact_loss():
    harness = _load_harness()
    lines = []
    summary = harness.run_harness(steps=6, nshards=4, seed=0,
                                  emit=lines.append)
    assert summary["within_bound"], summary
    assert summary["max_rel_diff"] < summary["bound"]
    # every step emitted a record, and the exact run really trained
    # (the bound means nothing against a frozen model)
    assert len(lines) == 6 + 1
    assert summary["final_loss_exact"] != pytest.approx(
        float(__import__("json").loads(lines[0])["loss_exact"]), abs=1e-6)


def test_harness_is_deterministic():
    harness = _load_harness()
    s1 = harness.run_harness(steps=3, nshards=3, seed=1,
                             emit=lambda _: None)
    s2 = harness.run_harness(steps=3, nshards=3, seed=1,
                             emit=lambda _: None)
    assert s1 == s2
