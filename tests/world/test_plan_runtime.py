"""Plan-on vs plan-off equivalence of schedule-plan execution.

Three layers, mirroring the PR 5 coalescing suite's structure:

- bridge level (runs in ANY container — the ranks never import jax): a
  2-rank pipeline executes through the PlanRunner (ticketed posting,
  hoisted recv posts, deferred sends) and its received-bytes digests
  are bit-identical to the direct path, with the runner reporting the
  overlap it achieved and zero signature mismatches;
- package level (needs jax >= 0.6): ``world_programs/
  false_serialization.py`` under the launcher with MPI4JAX_TPU_PLAN
  pointing at its verified compiled plan vs ``MPI4JAX_TPU_PLAN=0``
  produces identical per-rank digests — and ``launch --plan`` wires the
  whole flow (compile, prove, install) by itself;
- failure injection: a hang injected on a send INSIDE a concurrency
  group (a deferred posted send) still trips the transport deadline and
  tears the job down detectably with the plan armed.
"""

import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
PROGRAMS = os.path.join(REPO, "tests", "world_programs")


def _port(slot):
    return 45400 + (os.getpid() * 5 + slot * 13) % 900


def _digests(stdout, marker):
    return sorted(re.findall(marker + r" (r\d+ [0-9a-f]{64})", stdout))


# ---- bridge level: runs everywhere (parent-package shim, no jax) ----

_BRIDGE_PROG = r"""
import hashlib, os, sys, types
REPO = %r
sys.path.insert(0, REPO)
pkg = types.ModuleType("mpi4jax_tpu")
pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
sys.modules["mpi4jax_tpu"] = pkg
import numpy as np
from mpi4jax_tpu.analysis import _events, _plan
from mpi4jax_tpu.runtime import bridge, planrt, transport

c = transport.get_world_comm()
h, r, n = c.handle, c.rank(), c.size()
nxt, prv = (r + 1) %% n, (r - 1 + n) %% n
ROUNDS, SHAPE = 4, (128 * 1024,)   # 512 KB f32: past the detach threshold

events = {}
for rank in range(n):
    evs = []
    for k in range(ROUNDS):
        evs.append(_events.CommEvent(rank, 2 * k, "send",
                                     dest=(rank + 1) %% n, tag=k,
                                     dtype="float32", shape=SHAPE))
        evs.append(_events.CommEvent(rank, 2 * k + 1, "recv",
                                     source=(rank - 1 + n) %% n, tag=k,
                                     dtype="float32", shape=SHAPE))
    events[rank] = evs
comms = {(0,): tuple(range(n))}

rt = None
if os.environ.get("USE_PLAN") == "1":
    plan = _plan.compile_schedules(events, comms)
    assert plan.proved, plan.reasons
    assert plan.rewritten, plan.format()
    assert planrt.install(h, plan, r), "planrt.install refused"
    rt = planrt.get(c)
    assert rt is not None

digest = hashlib.sha256()
for k in range(ROUNDS):
    out_data = np.arange(SHAPE[0], dtype=np.float32) + 1000 * r + k
    if rt is not None:
        assert rt.run_send(out_data, nxt, k), "send not handled"
        got = rt.run_recv(SHAPE, np.float32, prv, k)
        assert got is not None, "recv not handled"
    else:
        bridge.send(h, out_data, nxt, k)
        got = bridge.recv(h, SHAPE, np.float32, prv, k)
    assert got[0] == 1000 * prv + k, (r, k, got[0])
    digest.update(got.tobytes())

if rt is not None:
    rt.flush()
    assert rt.stats["mismatches"] == 0, rt.stats
    assert rt.stats["hoisted_recvs"] > 0, rt.stats
    assert rt.stats["deferred_sends"] > 0, rt.stats
bridge.barrier(h)
print("bridge_plan digest r%%d %%s" %% (r, digest.hexdigest()), flush=True)
print("bridge_plan OK", flush=True)
"""


def _run_bridge_prog(tmp_path, port, env_extra):
    prog = tmp_path / "bridge_plan.py"
    prog.write_text(_BRIDGE_PROG % REPO)
    env = dict(os.environ)
    env["MPI4JAX_TPU_DISABLE_SHM"] = "1"  # ticketed posts ride TCP
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "mpi4jax_tpu/runtime/launch.py"),
         "-n", "3", "--port", str(port), str(prog)],
        capture_output=True, text=True, timeout=180, env=env, cwd=REPO,
    )


def test_bridge_level_plan_execution_bit_identical(tmp_path):
    res_on = _run_bridge_prog(tmp_path, _port(0), {"USE_PLAN": "1"})
    assert res_on.returncode == 0, res_on.stderr + res_on.stdout
    assert res_on.stdout.count("bridge_plan OK") == 3
    res_off = _run_bridge_prog(tmp_path, _port(1), {"USE_PLAN": "0"})
    assert res_off.returncode == 0, res_off.stderr + res_off.stdout
    d_on = _digests(res_on.stdout, "bridge_plan digest")
    d_off = _digests(res_off.stdout, "bridge_plan digest")
    assert d_on == d_off and len(d_on) == 3, (d_on, d_off)


def test_bridge_level_plan_with_engine_off_still_bit_identical(tmp_path):
    # MPI4JAX_TPU_PROGRESS_THREAD=0: posts execute inline, tickets are
    # pre-completed — the plan degrades to serialized execution, never
    # to different results
    res = _run_bridge_prog(tmp_path, _port(2), {
        "USE_PLAN": "1", "MPI4JAX_TPU_PROGRESS_THREAD": "0"})
    assert res.returncode == 0, res.stderr + res.stdout
    res_off = _run_bridge_prog(tmp_path, _port(3), {"USE_PLAN": "0"})
    assert res_off.returncode == 0, res_off.stderr + res_off.stdout
    assert _digests(res.stdout, "bridge_plan digest") == \
        _digests(res_off.stdout, "bridge_plan digest")


# ---- package level: the real ops layer under the launcher ----------


def _jax_at_least_min():
    try:
        import jax

        parts = []
        for piece in jax.__version__.split(".")[:3]:
            parts.append(int("".join(c for c in piece if c.isdigit()) or 0))
        return tuple(parts) >= (0, 6, 0)
    except Exception:
        return False


needs_package = pytest.mark.skipif(
    not _jax_at_least_min(), reason="package gate: needs jax >= 0.6")


def _run_launcher(args, env_extra, timeout=300):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.runtime.launch", *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )


def _emit_plan(tmp_path, prog, np_):
    plan_path = tmp_path / "plan.json"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.analyze", prog,
         "--np", str(np_), "--emit-plan", str(plan_path)],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO,
    )
    assert res.returncode == 0, res.stderr + res.stdout
    return str(plan_path)


@needs_package
def test_false_serialization_plan_on_off_bit_identical(tmp_path):
    prog = os.path.join(PROGRAMS, "false_serialization.py")
    plan_path = _emit_plan(tmp_path, prog, 3)
    res_on = _run_launcher(["-n", "3", "--port", str(_port(4)), prog],
                           {"MPI4JAX_TPU_PLAN": plan_path})
    assert res_on.returncode == 0, res_on.stderr + res_on.stdout
    assert "plan execution disabled" not in res_on.stderr, res_on.stderr
    res_off = _run_launcher(["-n", "3", "--port", str(_port(5)), prog],
                            {"MPI4JAX_TPU_PLAN": "0"})
    assert res_off.returncode == 0, res_off.stderr + res_off.stdout
    d_on = _digests(res_on.stdout, "false_serialization digest")
    d_off = _digests(res_off.stdout, "false_serialization digest")
    assert d_on == d_off and len(d_on) == 3, (d_on, d_off)


@needs_package
def test_launch_plan_flag_compiles_and_installs(tmp_path):
    prog = os.path.join(PROGRAMS, "false_serialization.py")
    res = _run_launcher(
        ["-n", "3", "--port", str(_port(6)), "--plan", prog], {})
    assert res.returncode == 0, res.stderr + res.stdout
    assert "--plan: verified plan" in res.stderr, res.stderr[-2000:]
    assert res.stdout.count("false_serialization OK") == 3


@needs_package
def test_fault_inside_concurrency_group_still_detected(tmp_path):
    # the 2nd logical send of rank 1 hangs INSIDE a plan concurrency
    # group (a deferred posted send on the progress thread): the
    # progress-based deadline must still trip and tear the job down
    prog = os.path.join(PROGRAMS, "false_serialization.py")
    plan_path = _emit_plan(tmp_path, prog, 3)
    res = _run_launcher(
        ["-n", "3", "--port", str(_port(7)), "--timeout", "120", prog],
        {"MPI4JAX_TPU_PLAN": plan_path,
         "MPI4JAX_TPU_FAULT": "rank=1,point=send,after=1,action=hang",
         "MPI4JAX_TPU_TIMEOUT_S": "6"})
    assert res.returncode != 0
    blob = res.stderr
    assert "timed out" in blob or "deadline" in blob or "rank 1" in blob, \
        blob[-2000:]


@needs_package
def test_bucketed_dp_grad_plan_on(tmp_path):
    # bucketed vs per-leaf gradient sync asserts bit-identity inside the
    # program; run it with the plan armed so the bucketed allreduces
    # execute under the runner's cursor too
    prog = os.path.join(PROGRAMS, "bucketed_dp_grad.py")
    plan_path = _emit_plan(tmp_path, prog, 2)
    res = _run_launcher(["-n", "2", "--port", str(_port(8)), prog],
                        {"MPI4JAX_TPU_PLAN": plan_path})
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("bucketed_dp_grad OK") == 2
