"""World-tier harness: a per-test hard deadline.

Every test here drives real multi-process jobs; a transport regression
that hangs one (a stuck launcher wait, a subprocess call missing its
``timeout=``) must fail THAT test fast instead of eating the whole
suite's global wall-clock budget.  SIGALRM fires in the main thread, so
it interrupts even a blocking ``subprocess`` wait — and before failing
the test it SIGKILLs every descendant process, because unwinding a
``subprocess.run`` kills only the direct child (the launcher), which
then can never reap its ranks (a deliberately hung fault-injected rank
would survive as a permanent orphan).

``MPI4JAX_TPU_TEST_TIMEOUT_S`` overrides the per-test budget (0 turns
the backstop off); the default comfortably exceeds every individual
test's own subprocess timeouts, so it only fires on a genuine hang.
"""

import os
import signal

import pytest

_BUDGET_S = float(os.environ.get("MPI4JAX_TPU_TEST_TIMEOUT_S", "600"))


def _descendant_pids():
    """All live descendants of this process, children before parents
    (stdlib /proc walk — psutil is not a test dependency)."""
    children = {}
    try:
        for pid in os.listdir("/proc"):
            if not pid.isdigit():
                continue
            try:
                with open(f"/proc/{pid}/stat") as f:
                    fields = f.read().rsplit(")", 1)[1].split()
                children.setdefault(int(fields[1]), []).append(int(pid))
            except (OSError, IndexError, ValueError):
                continue
    except OSError:
        return []
    out = []
    stack = [os.getpid()]
    while stack:
        for child in children.get(stack.pop(), []):
            out.append(child)
            stack.append(child)
    return out[::-1]  # deepest first


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    if _BUDGET_S <= 0 or not hasattr(signal, "SIGALRM"):
        yield
        return

    def _fire(signum, frame):
        for pid in _descendant_pids():
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
        raise TimeoutError(
            f"world test exceeded the {_BUDGET_S:.0f} s hard deadline "
            "(tests/world/conftest.py; override with "
            "MPI4JAX_TPU_TEST_TIMEOUT_S) — a multi-process job hung "
            "instead of failing fast; all descendant processes were "
            "SIGKILLed"
        )

    old = signal.signal(signal.SIGALRM, _fire)
    signal.setitimer(signal.ITIMER_REAL, _BUDGET_S)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, old)
