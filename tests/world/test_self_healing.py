"""Self-healing links, multi-process: exactly-once replay under injected
transient faults, heartbeat detection of idle dead links, CRC-caught
wire corruption, retry-budget exhaustion escalating through the elastic
path, and ``MPI4JAX_TPU_RETRY=0`` pinning the historic wire bit-for-bit.

Everything here is bridge-level (parent-package shim, no jax import,
the ``test_uring_world.py`` pattern), so the whole module runs in any
container.  The uring legs probe the resolved native status first and
SKIP visibly when the kernel lacks io_uring.

The contract under test (docs/sharp-bits.md § Self-healing links): with
``MPI4JAX_TPU_RETRY`` armed, a transient link fault is healed IN PLACE
— reconnect, gap replay, seq dedup — and the run's results are
bit-identical to a fault-free run; what cannot heal (budget exhausted,
unreplayable frame) escalates loudly through poison -> abort -> elastic,
and the launcher post-mortem names the failed link while reporting
transient-recovered ranks distinctly from dead ones.
"""

import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
PROGRAMS = os.path.join(REPO, "tests", "world_programs")
LAUNCHER = os.path.join(REPO, "mpi4jax_tpu", "runtime", "launch.py")

_port = [48300]  # own range (uring_world counts in 47400+)

# the armed layer plus fast, test-friendly backoff
ARMED = {
    "MPI4JAX_TPU_RETRY": "4",
    "MPI4JAX_TPU_RETRY_BACKOFF_MS": "50",
}
RESET_AT_5 = {"MPI4JAX_TPU_FAULT": "rank=0,point=send,after=5,action=reset"}
TCP = {"MPI4JAX_TPU_DISABLE_SHM": "1"}


def run_launcher(program, np_, timeout=120, env_extra=None, extra_args=()):
    _port[0] += np_ + 5
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("MPI4JAX_TPU_TIMEOUT_S", "30")
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [
            sys.executable, LAUNCHER, "-n", str(np_),
            "--port", str(_port[0]), *extra_args,
            os.path.join(PROGRAMS, program),
        ],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )


def heal_lines(stdout):
    """``{rank: (digest, reconnects, dup_dropped, crc_errors, replayed)}``
    from heal_ops.py's report lines."""
    out = {}
    for m in re.finditer(
            r"heal_ops (\d+) digest (\S+) reconnects (\d+) "
            r"dup_dropped (\d+) crc_errors (\d+) replayed (\d+)", stdout):
        out[int(m.group(1))] = (m.group(2), int(m.group(3)),
                                int(m.group(4)), int(m.group(5)),
                                int(m.group(6)))
    return out


_uring_status_cache = []


def _require_uring():
    """SKIP visibly when the kernel lacks io_uring (probe in a fresh
    subprocess: the knob is resolved once per process)."""
    if not _uring_status_cache:
        code = (
            "import sys, types, os; sys.path.insert(0, %r)\n"
            "pkg = types.ModuleType('mpi4jax_tpu')\n"
            "pkg.__path__ = [os.path.join(%r, 'mpi4jax_tpu')]\n"
            "sys.modules['mpi4jax_tpu'] = pkg\n"
            "from mpi4jax_tpu.runtime import bridge\n"
            "print('status=' + str(bridge.uring_status()))\n"
            % (REPO, REPO)
        )
        res = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=300, env={**os.environ, "MPI4JAX_TPU_URING": "auto"},
            cwd=REPO,
        )
        status = "probe-failed"
        for line in res.stdout.splitlines():
            if line.startswith("status="):
                status = line[len("status="):]
        _uring_status_cache.append(status)
    status = _uring_status_cache[0]
    if not status.startswith("on"):
        pytest.skip(f"io_uring leg skipped: native status is {status!r} "
                    "on this kernel (poll path still covered)")


def _baseline(env_extra):
    """Fault-free digests under the same knobs (minus fault/slack)."""
    env = {k: v for k, v in env_extra.items()
           if k not in ("MPI4JAX_TPU_FAULT",
                        "MPI4JAX_TPU_RETRY_REPLAY_SLACK")}
    res = run_launcher("heal_ops.py", 2, env_extra=env)
    assert res.returncode == 0, res.stderr[-800:]
    lines = heal_lines(res.stdout)
    assert set(lines) == {0, 1}, res.stdout
    assert all(v[1] == 0 for v in lines.values()), (
        f"fault-free run recovered something: {lines}")
    return lines[0][0], lines[1][0]


# ---------------- RETRY=0 pins today's path ----------------


def test_retry_disarmed_is_bit_identical_to_unset():
    # MPI4JAX_TPU_RETRY=0 (and unset) both run the historic wire: same
    # digests, no link layer anywhere in stderr, zero counters
    d_unset = _baseline({**TCP})
    res = run_launcher("heal_ops.py", 2, env_extra={
        **TCP, "MPI4JAX_TPU_RETRY": "0"})
    assert res.returncode == 0, res.stderr[-800:]
    lines = heal_lines(res.stdout)
    assert (lines[0][0], lines[1][0]) == d_unset
    assert "self-heal" not in res.stderr
    assert all(v[1:] == (0, 0, 0, 0) for v in lines.values())


def test_retry_disarmed_fault_still_fails_loudly():
    # unarmed + injected reset: the historic escalation (no retry layer
    # to absorb it) — the job must die loudly, never hang or corrupt
    res = run_launcher("heal_ops.py", 2, env_extra={
        **TCP, **RESET_AT_5, "MPI4JAX_TPU_TIMEOUT_S": "5"})
    assert res.returncode != 0
    assert "fault injection: reset" in res.stderr
    assert "self-heal" not in res.stderr  # disarmed: nothing retried
    assert "post-mortem" in res.stderr, res.stderr[-800:]


# ---------------- exactly-once heal, digest-identical ----------------


@pytest.mark.parametrize("uring", ["0", "1"])
def test_reset_mid_coalesced_heals_bit_identical(uring):
    # the acceptance scenario: engine on (small sends ride coalesced
    # container frames), transient reset mid-run; the armed layer
    # reconnects, replays the gap, dedups — and the digests match the
    # fault-free run bit-for-bit on both ranks
    if uring == "1":
        _require_uring()
    env = {**TCP, **ARMED, "MPI4JAX_TPU_URING": uring,
           "MPI4JAX_TPU_PROGRESS_THREAD": "1"}
    want = _baseline(env)
    res = run_launcher("heal_ops.py", 2, env_extra={**env, **RESET_AT_5})
    assert res.returncode == 0, res.stderr[-800:]
    lines = heal_lines(res.stdout)
    assert (lines[0][0], lines[1][0]) == want, res.stderr[-800:]
    assert "fault injection: reset" in res.stderr
    assert re.search(r"self-heal: link to r\d+ recovered", res.stderr)
    assert all(v[1] >= 1 for v in lines.values())  # both sides reconnect
    # the launcher reports the heal as a transient, NOT a rank death
    assert "healed in-place" in res.stderr, res.stderr[-800:]
    assert "not rank deaths" in res.stderr


def test_reset_mid_zc_send_heals_bit_identical():
    # 128 KB payloads: above the MSG_ZEROCOPY floor (64 KB), below the
    # replay-retention ceiling (256 KB) — a reset mid-ZC-send must
    # replay the whole frame and land bit-identical digests
    _require_uring()
    env = {**TCP, **ARMED, "MPI4JAX_TPU_URING": "1",
           "HEAL_OPS_N": "16384"}
    want = _baseline(env)
    res = run_launcher("heal_ops.py", 2, env_extra={**env, **RESET_AT_5})
    assert res.returncode == 0, res.stderr[-800:]
    lines = heal_lines(res.stdout)
    assert (lines[0][0], lines[1][0]) == want, res.stderr[-800:]
    assert re.search(r"self-heal: link to r\d+ recovered", res.stderr)
    # at least one side held the in-flight ZC frame and replayed it
    # (the peer may have had nothing in its gap)
    assert any(v[4] >= 1 for v in lines.values()), lines


def test_replay_slack_duplicates_are_dropped():
    # deliberate replay overlap: the sender re-sends frames the
    # receiver already delivered; the seq dedup must DROP them (the
    # exactly-once half of the contract) and the digests stay identical
    env = {**TCP, **ARMED}
    want = _baseline(env)
    res = run_launcher("heal_ops.py", 2, env_extra={
        **env, **RESET_AT_5, "MPI4JAX_TPU_RETRY_REPLAY_SLACK": "2"})
    assert res.returncode == 0, res.stderr[-800:]
    lines = heal_lines(res.stdout)
    assert (lines[0][0], lines[1][0]) == want
    assert any(v[2] >= 2 for v in lines.values()), (
        f"replay slack produced no dropped duplicates: {lines}")


def test_corrupt_frame_detected_by_crc_and_healed():
    # a flipped header byte must NEVER parse: the CRC32C catches it,
    # the receiver forces a reconnect, and the replayed frame lands
    # bit-identical — no silent corruption, ever
    env = {**TCP, **ARMED}
    want = _baseline(env)
    res = run_launcher("heal_ops.py", 2, env_extra={
        **env,
        "MPI4JAX_TPU_FAULT": "rank=0,point=send,after=5,action=corrupt"})
    assert res.returncode == 0, res.stderr[-800:]
    lines = heal_lines(res.stdout)
    assert (lines[0][0], lines[1][0]) == want
    assert "header CRC mismatch" in res.stderr, res.stderr[-800:]
    assert any(v[3] >= 1 for v in lines.values())  # crc_errors counted


def test_delay_fault_is_transparent():
    # a transient stall below the deadline needs no recovery at all:
    # digests identical, nothing reconnected
    env = {**TCP, **ARMED}
    want = _baseline(env)
    res = run_launcher("heal_ops.py", 2, env_extra={
        **env,
        "MPI4JAX_TPU_FAULT": "rank=0,point=send,after=5,action=delay,"
                             "ms=300"})
    assert res.returncode == 0, res.stderr[-800:]
    lines = heal_lines(res.stdout)
    assert (lines[0][0], lines[1][0]) == want
    assert all(v[1] == 0 for v in lines.values())


def test_heartbeat_heals_idle_link_under_shm():
    # shm arena on: traffic rides the rings, so a reset lands on the
    # IDLE TCP link underneath — only the progress thread's heartbeats
    # can find it.  The idle window between the phases is where the
    # ping fails, the link heals, and phase 2 runs on the new epoch.
    env = {
        **ARMED,
        "MPI4JAX_TPU_DISABLE_SHM": "0",
        "MPI4JAX_TPU_PROGRESS_THREAD": "1",
        "MPI4JAX_TPU_HEARTBEAT_S": "0.2",
        "HEAL_OPS_SLEEP_S": "1.5",
    }
    want = _baseline(env)
    res = run_launcher("heal_ops.py", 2, env_extra={**env, **RESET_AT_5})
    assert res.returncode == 0, res.stderr[-800:]
    lines = heal_lines(res.stdout)
    assert (lines[0][0], lines[1][0]) == want
    assert "heartbeat send failed" in res.stderr, res.stderr[-800:]
    assert all(v[1] >= 1 for v in lines.values())


# ---------------- budget exhaustion escalates ----------------


def test_budget_exhaustion_escalates_to_elastic_shrink():
    # a peer that actually DIED is not a transient: the survivors honor
    # the retry budget, declare the link DEAD, and escalate through the
    # PR 9 path — poison, abort, elastic shrink — finishing with the
    # uninterrupted run's exact digest, while the launcher post-mortem
    # names the failed link (and reports no bogus "healed" ranks)
    import tempfile

    with tempfile.TemporaryDirectory(prefix="m4j_heal_base_") as ckpt:
        base = run_launcher("elastic_train.py", 3, env_extra={
            **TCP, "MPI4JAX_TPU_CKPT_DIR": ckpt})
    assert base.returncode == 0, base.stderr[-800:]
    want = set(re.findall(r"elastic_train digest r\d+ (\w+)", base.stdout))
    assert len(want) == 1

    with tempfile.TemporaryDirectory(prefix="m4j_heal_ckpt_") as ckpt:
        res = run_launcher("elastic_train.py", 3, timeout=180, env_extra={
            **TCP,
            "MPI4JAX_TPU_RETRY": "2",
            "MPI4JAX_TPU_RETRY_BACKOFF_MS": "50",
            "MPI4JAX_TPU_TIMEOUT_S": "8",
            "MPI4JAX_TPU_CKPT_DIR": ckpt,
            "MPI4JAX_TPU_FAULT": "rank=1,point=send,after=10,action=exit",
        }, extra_args=("--elastic",))
    assert res.returncode == 0, res.stderr[-800:]
    assert "completed after recovery" in res.stderr, res.stderr[-800:]
    # the budget was honored, then exhausted, then escalated — loudly
    assert re.search(r"self-heal: link to r1 DEAD after \d+ attempt",
                     res.stderr), res.stderr[-800:]
    assert "escalating (poison -> abort -> elastic)" in res.stderr
    # the post-mortem names the link, and nothing is called "healed"
    assert re.search(r"failed link\(s\): rank \d+ -> rank 1", res.stderr)
    assert "healed in-place" not in res.stderr
    got = set(re.findall(r"elastic_train digest r\d+ (\w+)", res.stdout))
    assert want <= got, f"survivor digests diverged: {want} vs {got}"
