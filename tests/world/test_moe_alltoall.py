"""Alltoall family: quantized / hierarchical equivalence for the MoE
expert exchange.

All bridge-level through the launcher-as-file + the world programs'
parent-package shim, so the whole suite runs in ANY container (no jax
import inside the ranks) — the same pattern as the topology suite.

- ``moe_alltoall_ops.py`` at np=4 (2x2 islands) and np=6 (uneven 4+2),
  shm on and off: forced ring/qalltoall/halltoall/hqalltoall x
  {f32, bf16, i32} bit-compared against the flat default and the numpy
  codec simulators (``topo.simulate_qalltoall`` /
  ``simulate_halltoall`` / ``simulate_hqalltoall``), own-chunk /
  intra-island exactness, int8 error bound, global rank-consistency
  cross-check, i32 degrade;
- ``MPI4JAX_TPU_COLL_QUANT=deny`` degrades qalltoall -> ring and
  hqalltoall -> halltoall (exact bits); ``=force`` upgrades the default
  and forced-ring paths to the quantized wire;
- ``MPI4JAX_TPU_HIER=deny`` degrades hqalltoall to the flat quantized
  exchange;
- a non-contiguous interleaved partition exercises the island-block ->
  world-rank reorder of the hierarchical schedule.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
PROGRAMS = os.path.join(REPO, "tests", "world_programs")

_port = [47340]


def _launch(np_, fake_hosts, expect_islands, *, timeout=300,
            env_extra=None):
    _port[0] += np_ + 5
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("MPI4JAX_TPU_COLL_ALGO", None)
    env.pop("MPI4JAX_TPU_COLL_QUANT", None)
    env.pop("MPI4JAX_TPU_HIER", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["TOPO_EXPECT_ISLANDS"] = expect_islands
    env.setdefault("MPI4JAX_TPU_TIMEOUT_S", "120")
    if env_extra:
        env.update(env_extra)
    # launcher as a FILE: the rank programs use the parent-package
    # shim, and `-m` would import the package (jax gate) in the
    # launcher process
    return subprocess.run(
        [sys.executable,
         os.path.join(REPO, "mpi4jax_tpu", "runtime", "launch.py"),
         "-n", str(np_), "--port", str(_port[0]),
         "--fake-hosts", fake_hosts,
         os.path.join(PROGRAMS, "moe_alltoall_ops.py")],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )


@pytest.mark.parametrize("np_,fake,expect,shm", [
    (4, "r0,r1|r2,r3", "0,0,1,1", "on"),
    (4, "r0,r1|r2,r3", "0,0,1,1", "off"),
    (6, "r0,r1,r2,r3|r4,r5", "0,0,0,0,1,1", "on"),
    (6, "r0,r1,r2,r3|r4,r5", "0,0,0,0,1,1", "off"),
])
def test_alltoall_family_equivalence(np_, fake, expect, shm):
    env = {"MPI4JAX_TPU_DISABLE_SHM": "1" if shm == "off" else ""}
    res = _launch(np_, fake, expect, env_extra=env)
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("moe_alltoall_ops OK") == np_


def test_noncontiguous_islands():
    # islands need not be contiguous rank ranges: the hierarchical
    # alltoall's member-order compaction and (island, member) ->
    # world-rank unpack are exercised by an interleaved partition
    res = _launch(4, "r0,r2|r1,r3", "0,1,0,1")
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("moe_alltoall_ops OK") == 4


@pytest.mark.parametrize("np_,fake,expect", [
    (4, "r0,r1|r2,r3", "0,0,1,1"),
    (6, "r0,r1,r2,r3|r4,r5", "0,0,0,0,1,1"),
])
def test_quant_deny_gate(np_, fake, expect):
    # deny degrades qalltoall -> ring and hqalltoall -> halltoall; the
    # program switches every quantized expectation to exact bits
    res = _launch(np_, fake, expect,
                  env_extra={"MPI4JAX_TPU_COLL_QUANT": "deny"})
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("moe_alltoall_ops OK") == np_


def test_quant_force_gate():
    # force upgrades the AUTO default and forced ring to qalltoall and
    # forced halltoall to hqalltoall — the program's simulator
    # expectations switch to the quantized twins (i32 stays exact:
    # the dtype is codec-ineligible)
    res = _launch(4, "r0,r1|r2,r3", "0,0,1,1",
                  env_extra={"MPI4JAX_TPU_COLL_QUANT": "force"})
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("moe_alltoall_ops OK") == 4


def test_hier_deny_gate():
    # deny degrades hqalltoall to the flat quantized exchange (the
    # quant axis survives — one gate per axis)
    res = _launch(4, "r0,r1|r2,r3", "0,0,1,1",
                  env_extra={"MPI4JAX_TPU_HIER": "deny"})
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("moe_alltoall_ops OK") == 4


def _jax_at_least_min():
    try:
        import jax

        parts = []
        for piece in jax.__version__.split(".")[:3]:
            parts.append(int("".join(c for c in piece if c.isdigit()) or 0))
        return tuple(parts) >= (0, 6, 0)
    except Exception:
        return False


@pytest.mark.parametrize("shm", ["on", "off"])
def test_moe_ops_live_uneven_islands(shm):
    # the verify-corpus MoE program (router + rank-sharded experts,
    # exact + quantized + forced-hierarchical dispatch) run LIVE on an
    # uneven 3+1 island partition, shm on and off.  Package-level
    # program: needs jax >= 0.6 like the other full-ops axes; the
    # static-verifier + golden-plan coverage of the same program runs
    # everywhere via make verify-corpus.
    if not _jax_at_least_min():
        pytest.skip("package gate: needs jax >= 0.6")
    _port[0] += 9
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["MPI4JAX_TPU_TIMEOUT_S"] = "120"
    env["MPI4JAX_TPU_DISABLE_SHM"] = "1" if shm == "off" else ""
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.runtime.launch",
         "-n", "4", "--port", str(_port[0]),
         "--fake-hosts", "r0,r1,r2|r3",
         os.path.join(PROGRAMS, "moe_ops.py")],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("moe_ops OK") == 4
