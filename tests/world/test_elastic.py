"""Elastic worlds: deterministic end-to-end recovery tests.

Bridge level (runs in ANY container — the ranks use the parent-package
shim, no jax): a 3-rank DP training job whose rank 1 is killed by
``MPI4JAX_TPU_FAULT`` shrinks to np=2 (or respawns, per policy),
resumes from the last committed checkpoint, and finishes with the EXACT
state digest of an uninterrupted run; the continuous-batching serving
harness keeps answering requests across the same injected death.  The
launcher exits 0 and its post-mortem names the recovery outcome.

Package level (jax >= the package gate): the DP GPT-2 acceptance
scenario over the real ops layer, with the documented loss-parity
bound.
"""

import json
import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
PROGRAMS = os.path.join(REPO, "tests", "world_programs")
LAUNCHER = os.path.join(REPO, "mpi4jax_tpu", "runtime", "launch.py")


def _port(slot):
    return 45700 + (os.getpid() * 7 + slot * 13) % 900


def _run(prog, np_, port, env_extra, *args, elastic=True, timeout=240,
         prog_args=()):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["MPI4JAX_TPU_DISABLE_SHM"] = "1"  # deterministic TCP fault points
    env.update(env_extra)
    argv = [sys.executable, LAUNCHER, "-n", str(np_), "--port", str(port)]
    argv += list(args)
    if elastic:
        argv.append("--elastic")
    argv.append(os.path.join(PROGRAMS, prog))
    argv += [str(a) for a in prog_args]
    return subprocess.run(argv, capture_output=True, text=True,
                          timeout=timeout, env=env, cwd=REPO)


def _digests(stdout, marker):
    return sorted(set(re.findall(marker + r" (?:r\d+ )?([0-9a-f]{64})",
                                 stdout)))


FAULT_EXIT = {"MPI4JAX_TPU_FAULT": "rank=1,point=send,after=14,action=exit",
              "MPI4JAX_TPU_TIMEOUT_S": "8"}


# ---- bridge level: training recovery (shrink) ----------------------


def test_shrink_recovery_matches_uninterrupted_run(tmp_path):
    """The acceptance scenario at the bridge level: rank 1 dies
    mid-job, the world shrinks 3 -> 2, training resumes from the last
    committed checkpoint, and the final state digest is BIT-IDENTICAL
    to an uninterrupted 3-rank run (the program's gradient sync is
    world-size invariant by construction)."""
    clean = _run("elastic_train.py", 3, _port(0),
                 {"MPI4JAX_TPU_CKPT_DIR": str(tmp_path / "clean")},
                 prog_args=(12,))
    assert clean.returncode == 0, clean.stderr[-2000:]
    assert clean.stdout.count("elastic_train OK") == 3
    d_clean = _digests(clean.stdout, "elastic_train digest")
    assert len(d_clean) == 1, clean.stdout

    fault = _run("elastic_train.py", 3, _port(1),
                 {**FAULT_EXIT,
                  "MPI4JAX_TPU_CKPT_DIR": str(tmp_path / "fault")},
                 prog_args=(12,))
    assert fault.returncode == 0, fault.stderr[-2000:]
    # two survivors finish; the dead rank prints nothing
    assert fault.stdout.count("elastic_train OK") == 2
    assert _digests(fault.stdout, "elastic_train digest") == d_clean
    # the recovery post-mortem names the outcome (satellite): the
    # generation reached, the slots lost, and the resume step
    assert "completed after recovery" in fault.stderr
    assert "generation 1" in fault.stderr
    assert "lost rank slot(s) [1]" in fault.stderr
    assert re.search(r"resumed from step \d+", fault.stderr), \
        fault.stderr[-800:]
    # survivors really did restore a COMMITTED mid-job checkpoint
    assert re.search(r"resuming from step [1-9]\d*", fault.stderr)


def test_respawn_recovery_all_ranks_finish(tmp_path):
    """respawn policy: the dead slot's program restarts (possibly
    dying again — the fault spec rides the environment), the world
    rebuilds at full size every time, and all 3 ranks finish with the
    uninterrupted digest."""
    res = _run("elastic_train.py", 3, _port(2),
               {**FAULT_EXIT,
                "MPI4JAX_TPU_CKPT_DIR": str(tmp_path / "resp")},
               "--elastic-policy", "respawn", prog_args=(12,))
    assert res.returncode == 0, res.stderr[-2000:]
    assert res.stdout.count("elastic_train OK") == 3
    assert len(_digests(res.stdout, "elastic_train digest")) == 1
    assert "policy respawn" in res.stderr
    assert "completed after recovery" in res.stderr
    # a respawned-and-finished slot is a death, not a loss — the
    # post-mortem must not claim slots were lost when all ranks finished
    assert "(respawned)" in res.stderr
    assert "lost rank slot(s)" not in res.stderr


def test_rank_failure_surfaces_as_exception(tmp_path):
    """MPI4JAX_TPU_ELASTIC turns the bridge's hard abort into a
    catchable RankFailure: a rank that handles it itself exits
    cleanly instead of being os._exit(1)'d."""
    prog = tmp_path / "catch.py"
    prog.write_text(
        "import os, sys, types\n"
        f"REPO = {REPO!r}\n"
        "sys.path.insert(0, REPO)\n"
        "pkg = types.ModuleType('mpi4jax_tpu')\n"
        "pkg.__path__ = [os.path.join(REPO, 'mpi4jax_tpu')]\n"
        "sys.modules['mpi4jax_tpu'] = pkg\n"
        "import numpy as np\n"
        "from mpi4jax_tpu.elastic import RankFailure\n"
        "from mpi4jax_tpu.runtime import bridge, transport\n"
        "c = transport.get_world_comm()\n"
        # comm creation itself is in the try block: the after=0 recv
        # fault fires inside the topology-discovery allgather at init
        # (comm_init's first collective), and the failure must surface
        # as a catchable RankFailure from WHEREVER the transport first
        # touches the dead peer
        "if c.rank() == 0:\n"
        "    try:\n"
        "        h = c.handle\n"
        "        bridge.recv(h, (4,), np.float64, 1, 7)\n"
        "        print('UNREACHABLE', flush=True)\n"
        "    except RankFailure as e:\n"
        "        print(f'caught RankFailure op={e.op}', flush=True)\n"
        # stay up long enough for the launcher to process rank 1's
        # death while this rank is alive: a survivor that handles the
        # failure itself and winds down is a completed job, not a
        # zero-survivor loss
        "    import time; time.sleep(2)\n"
        "else:\n"
        "    h = c.handle\n"
    )
    env = {"MPI4JAX_TPU_FAULT": "rank=1,point=recv,after=0,action=exit",
           "MPI4JAX_TPU_TIMEOUT_S": "6"}
    res = subprocess.run(
        [sys.executable, LAUNCHER, "-n", "2", "--port", str(_port(3)),
         "--elastic", str(prog)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "MPI4JAX_TPU_DISABLE_SHM": "1", **env}, cwd=REPO)
    assert res.returncode == 0, res.stderr[-1500:]
    assert "caught RankFailure op=" in res.stdout
    assert "UNREACHABLE" not in res.stdout


# ---- bridge level: plans survive recovery (elastic-safe plans) -----


def test_shrink_reproves_and_keeps_the_plan(tmp_path):
    """The elastic-safe-plans acceptance scenario: a PLANNED job (every
    step's gradient allreduces run through an installed proved plan,
    signature-checked per op) loses rank 1 mid-job, shrinks 3 -> 2, and
    ``bridge.rebuild`` re-derives + re-PROVES the plan for the new
    world inside recovery — the job finishes with the plan still
    active, zero signature mismatches, and the EXACT digest of an
    uninterrupted planned run (instead of silently dropping to the
    unplanned path, the pre-PR-12 behavior)."""
    clean = _run("elastic_plan.py", 3, _port(10),
                 {"MPI4JAX_TPU_CKPT_DIR": str(tmp_path / "clean")},
                 prog_args=(10,))
    assert clean.returncode == 0, clean.stderr[-2000:]
    assert clean.stdout.count("elastic_plan OK") == 3
    assert "plan_active=1" in clean.stdout
    d_clean = _digests(clean.stdout, "elastic_plan digest")
    assert len(d_clean) == 1, clean.stdout

    fault = _run("elastic_plan.py", 3, _port(11),
                 {"MPI4JAX_TPU_FAULT":
                      "rank=1,point=send,after=30,action=exit",
                  "MPI4JAX_TPU_TIMEOUT_S": "8",
                  "MPI4JAX_TPU_CKPT_DIR": str(tmp_path / "fault")},
                 prog_args=(10,))
    assert fault.returncode == 0, fault.stderr[-2000:]
    # both survivors finish WITH the plan active and clean signatures
    assert fault.stdout.count("elastic_plan OK") == 2
    assert fault.stdout.count("np=2 plan_active=1 mismatches=0") == 2, \
        fault.stdout
    # recovery really did re-derive + re-prove (not reuse the np=3 plan)
    assert "re-proved plan" in fault.stderr, fault.stderr[-2000:]
    assert "np=2" in fault.stderr
    assert "overlap preserved across recovery" in fault.stderr
    assert "completed after recovery" in fault.stderr
    # bit-identical trajectory: the MAX sync is world-size invariant
    assert _digests(fault.stdout, "elastic_plan digest") == d_clean


# ---- bridge level: serving recovery --------------------------------


def test_serving_survives_rank_death():
    """Continuous batching across an injected worker death: every
    request completes, transcripts match an uninterrupted run exactly
    (in-flight iterations are retried, never committed twice), and the
    job exits 0."""
    clean = _run("elastic_serve.py", 3, _port(4), {}, prog_args=(10,))
    assert clean.returncode == 0, clean.stderr[-2000:]
    assert "elastic_serve OK nreq=10 recoveries=0" in clean.stdout
    d_clean = _digests(clean.stdout, "elastic_serve digest")

    fault = _run("elastic_serve.py", 3, _port(5),
                 {"MPI4JAX_TPU_FAULT":
                      "rank=1,point=recv,after=9,action=exit",
                  "MPI4JAX_TPU_TIMEOUT_S": "8"},
                 prog_args=(10,))
    assert fault.returncode == 0, fault.stderr[-2000:]
    assert "elastic_serve OK nreq=10 recoveries=1" in fault.stdout, \
        fault.stdout
    assert _digests(fault.stdout, "elastic_serve digest") == d_clean
    assert "retrying" in fault.stderr  # in-flight requests were retried


@pytest.mark.parametrize("plane", ["toy", "v2"])
def test_serving_frontend_death_releases_survivors(plane):
    """The rank-0 caveat, made orderly (satellite regression): when the
    FRONTEND dies, the worker promoted to rank 0 broadcasts STOP before
    raising its 'became the frontend' error, so the other survivors
    return from serve_worker instead of hanging in a headless bcast
    until the transport deadline.  Both serving planes share the
    contract."""
    res = _run("serve_frontend_death.py", 3, _port(20 if plane == "toy"
                                                   else 21),
               {"MPI4JAX_TPU_FAULT":
                    "rank=0,point=send,after=12,action=exit",
                "MPI4JAX_TPU_TIMEOUT_S": "8"},
               prog_args=(plane,))
    assert res.returncode == 0, res.stdout + res.stderr[-2000:]
    # exactly one survivor was promoted (and raised only after the
    # release); every other survivor exited its loop normally
    assert res.stdout.count("fd promoted clean") == 1, res.stdout
    assert res.stdout.count("fd worker done") == 1, res.stdout
    assert "fault did not fire" not in res.stdout


# ---- bridge level: serving v2 (disaggregated, KV cache) ------------


def test_serving_v2_commit_point_fault_retry_bit_identical():
    """The commit-point invariant on the v2 plane: a rank killed
    between prefill hand-off (the KV ship) and decode commit forces a
    recovery that drops all rank-local KV and re-prefills every
    in-flight request — and the completed transcripts are BYTE-
    IDENTICAL to an uninterrupted run (the toy adapter is exactly
    prefix-consistent, so a retried iteration cannot drift)."""
    args = ("--fake-hosts", "r0,r1|r2,r3")
    clean = _run("serve_v2.py", 4, _port(22), {}, *args,
                 prog_args=(12, "disagg", "toy"))
    assert clean.returncode == 0, clean.stderr[-2000:]
    assert "serve_v2 OK nreq=12 recoveries=0 mode=disagg" in clean.stdout
    d_clean = _digests(clean.stdout, "serve_v2 digest")
    assert len(d_clean) == 1, clean.stdout

    # rank 1 is the (sole) prefill rank on this mesh: its sends are the
    # KV ships to the decode island — the 15th send dies between a
    # hand-off and the frontend's commit
    fault = _run("serve_v2.py", 4, _port(23), FAULT_EXIT, *args,
                 prog_args=(12, "disagg", "toy"))
    assert fault.returncode == 0, fault.stderr[-2000:]
    assert "serve_v2 OK nreq=12 recoveries=1" in fault.stdout, fault.stdout
    assert _digests(fault.stdout, "serve_v2 digest") == d_clean
    assert "re-prefilling" in fault.stderr  # the KV-drop recovery path


@pytest.mark.parametrize("shm", ["0", "1"])
def test_serving_v2_disagg_bit_consistent_with_colocated(shm):
    """Disaggregated placement is a pure routing choice: the same
    prompts produce byte-identical transcripts whether prefill and
    decode are colocated or split across the 2-island mesh, with the
    shm arena on or off (the KV wire is exact by default)."""
    digests = {}
    for i, mode in enumerate(("colocated", "disagg")):
        res = _run("serve_v2.py", 4, _port(24 + 2 * i + int(shm)),
                   {"MPI4JAX_TPU_DISABLE_SHM": shm},
                   "--fake-hosts", "r0,r1|r2,r3",
                   prog_args=(12, mode, "gpt"))
        assert res.returncode == 0, res.stderr[-2000:]
        assert f"mode={mode}" in res.stdout, res.stdout
        d = _digests(res.stdout, "serve_v2 digest")
        assert len(d) == 1, res.stdout
        digests[mode] = d[0]
    assert digests["colocated"] == digests["disagg"], digests


# ---- obs: recordings carry the world generation --------------------


def test_obs_parts_carry_generation(tmp_path):
    """Recordings dumped after a recovery are stamped with the new
    world generation, and the merged trace surfaces the per-rank
    generations."""
    trace = tmp_path / "trace.json"
    res = _run("elastic_train.py", 3, _port(6),
               {**FAULT_EXIT,
                "MPI4JAX_TPU_CKPT_DIR": str(tmp_path / "ck")},
               "--trace", str(trace), prog_args=(12,))
    assert res.returncode == 0, res.stderr[-2000:]
    parts = sorted(tmp_path.glob("trace.json.rank*.json"))
    assert len(parts) == 2, parts  # the two survivors dumped
    gens = set()
    for p in parts:
        part = json.loads(p.read_text())
        gens.add(int(part.get("generation", -1)))
    assert gens == {1}, gens
    merged = json.loads(trace.read_text())
    assert merged["otherData"].get("generations"), merged["otherData"]
    assert set(merged["otherData"]["generations"].values()) == {1}


# ---- package level: the DP GPT-2 acceptance scenario ---------------


def _jax_at_least_min():
    try:
        import jax

        parts = []
        for piece in jax.__version__.split(".")[:3]:
            parts.append(int("".join(c for c in piece if c.isdigit()) or 0))
        return tuple(parts) >= (0, 6, 0)
    except Exception:
        return False


needs_package = pytest.mark.skipif(
    not _jax_at_least_min(), reason="package gate: needs jax >= 0.6")

#: documented loss-parity bound (docs/elasticity.md): the recovered
#: run reshards the global batch over fewer ranks, so only float
#: reassociation separates it from the uninterrupted trajectory
LOSS_REL_BOUND = 1e-2


@needs_package
def test_gpt_dp_elastic_loss_parity(tmp_path):
    """np=3 DP GPT-2 training, rank 1 killed mid-job, shrink to np=2,
    resume from the last committed step: the final full-batch loss
    matches an uninterrupted run within the documented bound."""
    clean = _run("gpt_dp_elastic.py", 3, _port(7),
                 {"MPI4JAX_TPU_CKPT_DIR": str(tmp_path / "clean")},
                 timeout=420, prog_args=(8,))
    assert clean.returncode == 0, clean.stderr[-2500:]
    m = re.search(r"final_loss ([0-9.]+)", clean.stdout)
    assert m, clean.stdout
    loss_clean = float(m.group(1))

    fault = _run("gpt_dp_elastic.py", 3, _port(8),
                 {"MPI4JAX_TPU_CKPT_DIR": str(tmp_path / "fault"),
                  "MPI4JAX_TPU_FAULT":
                      "rank=1,point=send,after=60,action=exit",
                  "MPI4JAX_TPU_TIMEOUT_S": "10"},
                 timeout=420, prog_args=(8,))
    assert fault.returncode == 0, fault.stderr[-2500:]
    assert "completed after recovery" in fault.stderr
    m = re.search(r"final_loss ([0-9.]+)", fault.stdout)
    assert m, fault.stdout
    loss_fault = float(m.group(1))
    rel = abs(loss_fault - loss_clean) / max(abs(loss_clean), 1e-9)
    assert rel <= LOSS_REL_BOUND, (loss_clean, loss_fault, rel)


@needs_package
def test_schedules_stay_valid_at_shrunk_sizes():
    """Dense renumbering keeps the verifier's contract: a rank-symmetric
    program's schedule verifies clean at np=3 AND at the shrunk np=2 —
    nothing about a recovered world invalidates static analysis."""
    import jax.numpy as jnp

    import mpi4jax_tpu as m4j
    from mpi4jax_tpu import analysis

    def program(x):
        y = m4j.allreduce(x, op=m4j.SUM)
        return m4j.allgather(y)

    for np_ in (3, 2):
        report = analysis.check(program, jnp.arange(4.0), world_size=np_)
        assert report.ok, report.format_table()
