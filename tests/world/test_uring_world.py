"""Zero-copy transport floor, multi-process: digest equivalence of the
uring and poll submission paths, the fault-injection matrix under
``MPI4JAX_TPU_URING=1``, and elastic shrink-under-load on the uring leg.

Everything here is bridge-level (parent-package shim, no jax import),
so the whole module runs in any container.  The uring legs probe the
resolved native status first and SKIP with a visible notice when the
kernel lacks io_uring — never silently green on the poll path.
"""

import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
PROGRAMS = os.path.join(REPO, "tests", "world_programs")
LAUNCHER = os.path.join(REPO, "mpi4jax_tpu", "runtime", "launch.py")

URING_ON = {"MPI4JAX_TPU_URING": "1"}
URING_OFF = {"MPI4JAX_TPU_URING": "0"}


def _port(slot):
    return 47400 + (os.getpid() * 7 + slot * 17) % 500


_uring_status_cache = []


def _uring_status():
    """The RESOLVED native uring state in a fresh subprocess (the knob
    is read once per process, so the probe must not run in-process)."""
    if _uring_status_cache:
        return _uring_status_cache[0]
    code = (
        "import sys, types, os; sys.path.insert(0, %r)\n"
        "pkg = types.ModuleType('mpi4jax_tpu')\n"
        "pkg.__path__ = [os.path.join(%r, 'mpi4jax_tpu')]\n"
        "sys.modules['mpi4jax_tpu'] = pkg\n"
        "from mpi4jax_tpu.runtime import bridge\n"
        "print('status=' + str(bridge.uring_status()))\n" % (REPO, REPO)
    )
    res = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=300, env={**os.environ, "MPI4JAX_TPU_URING": "auto"},
        cwd=REPO,
    )
    status = "probe-failed"
    for line in res.stdout.splitlines():
        if line.startswith("status="):
            status = line[len("status="):]
    _uring_status_cache.append(status)
    return status


def _require_uring():
    status = _uring_status()
    if not status.startswith("on"):
        pytest.skip(f"io_uring leg skipped: native status is {status!r} "
                    "on this kernel (poll path still covered)")


# ---- digest equality: mixed send/recv/allreduce program, on vs off --

_MIXED_PROG = r"""
import hashlib, os, sys, types
REPO = %r
sys.path.insert(0, REPO)
pkg = types.ModuleType("mpi4jax_tpu")
pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
sys.modules["mpi4jax_tpu"] = pkg
import numpy as np
from mpi4jax_tpu.runtime import bridge, transport

c = transport.get_world_comm()
h, r, n = c.handle, c.rank(), c.size()
digest = hashlib.sha256()
for round_ in range(3):
    # small-send burst (coalesced containers / staged uring frames)
    for peer in range(n):
        if peer == r:
            continue
        for i in range(16):
            m = 5 + (i %% 3) * 200
            bridge.send(h, np.arange(m, dtype=np.int32) + 7000 * r + i,
                        peer, 900 * round_ + i)
    for peer in range(n):
        if peer == r:
            continue
        for i in range(16):
            m = 5 + (i %% 3) * 200
            got = bridge.recv(h, (m,), np.int32, peer, 900 * round_ + i)
            assert got[0] == 7000 * peer + i, (peer, i, got[0])
            digest.update(got.tobytes())
    # mid-size detached sends (> coalesce threshold: writev batch path)
    mid = np.arange(3000, dtype=np.float64) * (r + 1) + round_
    for peer in range(n):
        if peer != r:
            bridge.send(h, mid, peer, 7000 + round_)
    for peer in range(n):
        if peer != r:
            got = bridge.recv(h, (3000,), np.float64, peer, 7000 + round_)
            digest.update(got.tobytes())
    # sendrecv ring + small and larger allreduce (chunked transfers on
    # the uring leg; the zero-copy gate lives past the kernel's
    # buffering ceiling and is pinned by the cyclic-sends test below)
    got = bridge.sendrecv(h, np.arange(64.0) + r, (64,), np.float64,
                          (r - 1) %% n, (r + 1) %% n, 31 + round_)
    digest.update(got.tobytes())
    out = bridge.allreduce(h, np.ones(8) * (r + 1), 0)
    digest.update(out.tobytes())
    big = bridge.allreduce(h, np.arange(70000, dtype=np.float32) + r, 0)
    digest.update(big.tobytes())
bridge.barrier(h)
print("uring_mixed digest r%%d %%s" %% (r, digest.hexdigest()), flush=True)
print("uring_mixed OK", flush=True)
"""


def _run_mixed(tmp_path, port, env_extra):
    prog = tmp_path / "uring_mixed.py"
    prog.write_text(_MIXED_PROG % REPO)
    env = dict(os.environ)
    env["MPI4JAX_TPU_DISABLE_SHM"] = "1"  # the floor under test is TCP
    env["MPI4JAX_TPU_TIMEOUT_S"] = "60"
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, LAUNCHER, "-n", "3", "--port", str(port),
         str(prog)],
        capture_output=True, text=True, timeout=240, env=env, cwd=REPO,
    )


def _digests(stdout, marker):
    return sorted(re.findall(marker + r" (r\d+ [0-9a-f]{64})", stdout))


def test_uring_on_off_digest_equality(tmp_path):
    """THE escape-hatch contract: a mixed send/recv/sendrecv/allreduce
    program produces bit-identical per-rank digests with the uring
    submission backend on and off (URING=0 is the poll path)."""
    _require_uring()
    res_off = _run_mixed(tmp_path, _port(0), URING_OFF)
    assert res_off.returncode == 0, res_off.stderr[-2000:] + res_off.stdout
    assert res_off.stdout.count("uring_mixed OK") == 3
    res_on = _run_mixed(tmp_path, _port(1), URING_ON)
    assert res_on.returncode == 0, res_on.stderr[-2000:] + res_on.stdout
    assert res_on.stdout.count("uring_mixed OK") == 3
    d_off = _digests(res_off.stdout, "uring_mixed digest")
    d_on = _digests(res_on.stdout, "uring_mixed digest")
    assert d_off == d_on and len(d_off) == 3, (d_off, d_on)


_CYCLIC_LARGE_PROG = r"""
import hashlib, os, sys, types
REPO = %r
sys.path.insert(0, REPO)
pkg = types.ModuleType("mpi4jax_tpu")
pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
sys.modules["mpi4jax_tpu"] = pkg
import numpy as np
from mpi4jax_tpu.runtime import bridge, transport

c = transport.get_world_comm()
h, r, n = c.handle, c.rank(), c.size()
nxt, prv = (r + 1) %% n, (r - 1 + n) %% n
digest = hashlib.sha256()
for k in range(4):
    # every rank sends BEFORE anyone receives: completion relies on the
    # kernel buffering the payload, exactly like the poll path's write
    out = np.arange(128 * 1024, dtype=np.float32) + 1000 * r + k
    bridge.send(h, out, nxt, k)
    got = bridge.recv(h, (128 * 1024,), np.float32, prv, k)
    assert got[0] == 1000 * prv + k, (r, k, got[0])
    digest.update(got.tobytes())
bridge.barrier(h)
print("uring_cyclic digest r%%d %%s" %% (r, digest.hexdigest()), flush=True)
print("uring_cyclic OK", flush=True)
"""


def test_large_cyclic_sends_keep_buffered_completion(tmp_path):
    """The MSG_ZEROCOPY completion-envelope contract: a 3-rank ring of
    512 KiB sends where every rank sends before anyone receives — the
    poll path completes each send once the kernel buffers the payload,
    and the uring path must do the same (a zero-copy send's buffer
    release waits on the RECEIVER, so ZC engaging below the kernel's
    buffering ceiling would turn this into a rendezvous deadlock).
    Runs with the progress engine off (inline blocking sends, the worst
    case) and no deadline armed, so a regression hangs rather than
    degrades."""
    _require_uring()

    def run(port, env_extra):
        prog = tmp_path / "uring_cyclic.py"
        prog.write_text(_CYCLIC_LARGE_PROG % REPO)
        env = dict(os.environ)
        env["MPI4JAX_TPU_DISABLE_SHM"] = "1"
        env["MPI4JAX_TPU_PROGRESS_THREAD"] = "0"
        env.pop("MPI4JAX_TPU_TIMEOUT_S", None)  # unarmed: hang = bug
        env.update(env_extra)
        return subprocess.run(
            [sys.executable, LAUNCHER, "-n", "3", "--port", str(port),
             str(prog)],
            capture_output=True, text=True, timeout=120, env=env, cwd=REPO,
        )

    res_on = run(_port(8), URING_ON)
    assert res_on.returncode == 0, res_on.stderr[-2000:] + res_on.stdout
    assert res_on.stdout.count("uring_cyclic OK") == 3
    res_off = run(_port(9), URING_OFF)
    assert res_off.returncode == 0, res_off.stderr[-2000:] + res_off.stdout
    d_on = _digests(res_on.stdout, "uring_cyclic digest")
    d_off = _digests(res_off.stdout, "uring_cyclic digest")
    assert d_on == d_off and len(d_on) == 3, (d_on, d_off)


def test_coalesced_wire_survives_batched_writes(tmp_path):
    """Pin the coalesced-frame wire format across the drain-loop write
    batching: the poll path (URING=0, where the container now leaves in
    ONE write) still delivers every burst message with its tag and
    bytes intact — the receive-side splitter parses the same wire
    bytes it always did."""
    res = _run_mixed(tmp_path, _port(2), {**URING_OFF,
                                          "MPI4JAX_TPU_COALESCE_BYTES":
                                          "4096"})
    assert res.returncode == 0, res.stderr[-2000:] + res.stdout
    assert res.stdout.count("uring_mixed OK") == 3


# ---- failure semantics on the uring path ----------------------------

# bridge-level sendrecv ring (parent-package shim, no jax), the shape
# the PR 2 fault matrix injects into
_FAULT_PROG = r"""
import os, sys, types
REPO = %r
sys.path.insert(0, REPO)
pkg = types.ModuleType("mpi4jax_tpu")
pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
sys.modules["mpi4jax_tpu"] = pkg
import numpy as np
from mpi4jax_tpu.runtime import bridge, transport

c = transport.get_world_comm()
h, r, n = c.handle, c.rank(), c.size()
base = np.arange(8, dtype=np.float64)
for i in range(6):
    got = bridge.sendrecv(h, base + r + i, (8,), np.float64,
                          (r - 1) %% n, (r + 1) %% n, 40 + i)
    np.testing.assert_allclose(got, base + (r - 1) %% n + i)
print("fault_prog OK", flush=True)
"""


def _run_fault(tmp_path, np_, port, env_extra, timeout=120, args=(),
               program=None):
    prog = program
    if prog is None:
        prog = tmp_path / "uring_fault.py"
        prog.write_text(_FAULT_PROG % REPO)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["MPI4JAX_TPU_DISABLE_SHM"] = "1"
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, LAUNCHER, "-n", str(np_), "--port", str(port),
         *args, str(prog)],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )


@pytest.mark.parametrize("point", ["send", "recv"])
@pytest.mark.parametrize("action", ["hang", "exit", "close"])
def test_fault_matrix_under_uring(tmp_path, point, action):
    """The PR 2 fault-injection matrix on the uring submission path:
    every (action, point) still tears the job down detectably, with
    deadlines measured from post time (the hang cases name the timeout)
    and poison/EOF propagation intact (the exit/close cases)."""
    _require_uring()
    slot = {"hang": 0, "exit": 1, "close": 2}[action] * 2 + \
        {"send": 0, "recv": 1}[point] + 3
    env = {
        **URING_ON,
        "MPI4JAX_TPU_TIMEOUT_S": "3",
        "MPI4JAX_TPU_FAULT":
            f"rank=1,point={point},after=2,action={action}",
    }
    res = _run_fault(tmp_path, 2, _port(slot), env)
    assert res.returncode != 0
    assert res.stdout.count("fault_prog OK") < 2
    assert "post-mortem" in res.stderr, res.stderr[-900:]
    if action == "hang":
        # the progress deadline (anchored at post time on the engine
        # queue) fires and names the configured knob's value
        assert "timed out after 3 s" in res.stderr, res.stderr[-900:]
    else:
        # crash / partition: detected through the dead socket or the
        # injected exit itself, with the injection named
        assert ("fault injection" in res.stderr
                or "returned error code" in res.stderr), res.stderr[-900:]


def test_poison_tears_down_in_one_deadline_under_uring(tmp_path):
    """A hang inside a coalesced burst with the uring leg armed: the
    receivers starve, the post-time deadline fires, and the poison
    frame tears the group down within ~2x the deadline — not the sum of
    per-rank timeouts."""
    _require_uring()
    import time

    prog = tmp_path / "uring_mixed.py"
    prog.write_text(_MIXED_PROG % REPO)
    env = dict(os.environ)
    env.update({
        **URING_ON,
        "MPI4JAX_TPU_DISABLE_SHM": "1",
        "MPI4JAX_TPU_TIMEOUT_S": "4",
        "MPI4JAX_TPU_FAULT": "rank=0,point=send,after=20,action=hang",
    })
    t0 = time.monotonic()
    res = subprocess.run(
        [sys.executable, LAUNCHER, "-n", "3", "--port", str(_port(9)),
         str(prog)],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO,
    )
    dt = time.monotonic() - t0
    assert res.returncode != 0
    assert "timed out" in res.stderr, res.stderr[-1200:]
    assert dt < 45, f"teardown took {dt:.1f}s for a 4s deadline"


def test_elastic_shrink_under_load_uring(tmp_path):
    """The PR 9 shrink-under-load scenario with the uring backend
    armed: rank 1 dies mid-stream, survivors recover through
    tpucomm_shrink, and training finishes from the committed checkpoint
    (recovery post-mortem names the outcome)."""
    _require_uring()
    env = {
        **URING_ON,
        "MPI4JAX_TPU_FAULT": "rank=1,point=send,after=14,action=exit",
        "MPI4JAX_TPU_TIMEOUT_S": "8",
        "MPI4JAX_TPU_CKPT_DIR": str(tmp_path / "ckpt"),
    }
    res = _run_fault(tmp_path, 3, _port(11), env, timeout=240,
                     args=("--elastic",),
                     program=os.path.join(PROGRAMS, "elastic_train.py"))
    assert res.returncode == 0, res.stderr[-2000:]
    assert res.stdout.count("elastic_train OK") == 2
    assert "completed after recovery" in res.stderr
    assert "generation 1" in res.stderr
