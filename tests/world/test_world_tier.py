"""World-tier integration: run the per-rank programs under the launcher.

The reference runs its suite twice (pytest / mpirun -np 2 pytest,
docs/developers.rst there); here the multi-process half is driven from
pytest via the bundled launcher, at np=2 and np=4.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
PROGRAMS = os.path.join(REPO, "tests", "world_programs")

_port = [44100]


def run_launcher(program, np_, timeout=180, env_extra=None, extra_args=(),
                 prog_args=(), prog_dir=None):
    _port[0] += np_ + 3  # unique ports per invocation
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # ranks don't need virtual devices
    env["JAX_PLATFORMS"] = "cpu"
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [
            sys.executable, "-m", "mpi4jax_tpu.runtime.launch",
            "-n", str(np_), "--port", str(_port[0]), *extra_args,
            os.path.join(prog_dir or PROGRAMS, program), *prog_args,
        ],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )


@pytest.mark.parametrize("np_", [2, 4])
def test_basic_ops(np_):
    res = run_launcher("basic_ops.py", np_)
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("basic_ops OK") == np_


def test_neighbor_exchange():
    # one-op bidirectional ring/chain exchange at np=3 — the smallest
    # ring where pairwise bidirectional scheduling deadlocks
    res = run_launcher("neighbor_ops.py", 3)
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("neighbor_ops OK") == 3


def test_shm_chunked_pieces():
    # 1 MB slots against 4-6 MB payloads: every collective exercises its
    # multi-piece loop (incl. scatter/alltoall divided-slot budgets)
    res = run_launcher(
        "shm_chunked.py", 2, timeout=300,
        # pin the arena ON: the whole-suite tcp axis (DISABLE_SHM=1 in
        # CI env) must not turn the shm tests into trivial TCP reruns
        env_extra={"MPI4JAX_TPU_SHM_MB": "1", "MPI4JAX_TPU_DISABLE_SHM": ""},
    )
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("shm_chunked OK") == 2


def test_shm_ring_stub_path():
    # 4 KB rings force every payload over 1 KB (ring/4) through the
    # stub-in-ring + TCP-payload path — ordering spine and large-message
    # degradation both exercised by the full op battery
    res = run_launcher(
        "full_ops.py", 2, timeout=300,
        env_extra={"MPI4JAX_TPU_SHM_RING_KB": "4",
                   "MPI4JAX_TPU_DISABLE_SHM": ""},
    )
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("full_ops OK") == 2


def test_shm_p2p_disabled_axis():
    # p2p kill switch: collectives stay on the arena, point-to-point
    # falls back to TCP — numerics identical
    res = run_launcher(
        "full_ops.py", 2, timeout=300,
        env_extra={"MPI4JAX_TPU_DISABLE_SHM_P2P": "1",
                   "MPI4JAX_TPU_DISABLE_SHM": ""},
    )
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("full_ops OK") == 2


def test_shm_disabled_tcp_path():
    # collectives fall back to the framed TCP schedules under the shm
    # kill switch — numerics must be identical (CI axis for the arena)
    res = run_launcher(
        "full_ops.py", 2, timeout=300,
        env_extra={"MPI4JAX_TPU_DISABLE_SHM": "1"},
    )
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("full_ops OK") == 2


@pytest.mark.parametrize("np_,shm", [(4, "on"), (4, "off"), (3, "off")])
def test_coll_algo_equivalence(np_, shm):
    # cross-algorithm equivalence (ring/rd/tree x {f32,i32,bf16} x
    # {SUM,MAX} vs the default path), under the arena and under
    # DISABLE_SHM=1; np=3 exercises the non-power-of-two rd fold
    env = {"MPI4JAX_TPU_DISABLE_SHM": "1" if shm == "off" else ""}
    res = run_launcher("coll_algo_ops.py", np_, timeout=300, env_extra=env)
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("coll_algo_ops OK") == np_


def test_coll_algo_forced_ring_axis():
    # the forced-`ring` suite axis (mirror of the DISABLE_SHM=1 axis):
    # the full op battery must hold with every allreduce/allgather
    # forced onto the ring schedules over TCP
    res = run_launcher(
        "full_ops.py", 4, timeout=300,
        env_extra={"MPI4JAX_TPU_COLL_ALGO": "ring",
                   "MPI4JAX_TPU_DISABLE_SHM": "1"},
    )
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("full_ops OK") == 4


def test_tune_cli_smoke(tmp_path):
    # the offline autotuner end to end: the CLI sweeps algorithms at
    # np=4, writes a well-formed cache, and a SUBSEQUENT run loads and
    # honors it (algo_report prints the engine's live picks, and debug
    # tracing names the algorithm on the wire)
    import json

    cache = tmp_path / "tune_4.json"
    _port[0] += 9
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("MPI4JAX_TPU_COLL_ALGO", None)
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.tune", "--np", "4",
         "--port", str(_port[0]), "--sizes", "1024,262144",
         "--repeats", "3", "--cache", str(cache)],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO,
    )
    assert res.returncode == 0, res.stderr + res.stdout
    assert cache.exists(), res.stdout

    data = json.loads(cache.read_text())
    assert data["version"] == 1 and data["world_size"] == 4
    for op in ("allreduce", "allgather"):
        entries = data["table"][op]
        assert entries and entries[0][0] == 0
        assert all(e[1] in ("ring", "rd", "tree", "qring", "qrd")
                   for e in entries)
    assert data["measurements"], "tuner wrote no measurements"

    # round-trip through the loader, then honor-check on a live job
    from mpi4jax_tpu import tune

    try:
        table = tune.load_cache(4, path=str(cache))
    finally:
        # don't leak this cache into the pytest process's own engine state
        tune._cache_table = None
        tune._cache_origin = None
    expected = {}
    for nbytes in (1024, 262144):
        algo = "auto"
        for mb, name in table["allreduce"]:
            if nbytes >= mb:
                algo = name
        expected[nbytes] = algo
    res = run_launcher(
        "algo_report.py", 4, timeout=180,
        env_extra={"MPI4JAX_TPU_TUNE_CACHE": str(cache),
                   "MPI4JAX_TPU_DISABLE_SHM": "1",
                   "MPI4JAX_TPU_DEBUG": "1",
                   "ALGO_REPORT_SIZES": "1024,262144"},
    )
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("algo_report OK") == 4
    for nbytes, algo in expected.items():
        assert res.stdout.count(f"allreduce@{nbytes}={algo}") == 4, (
            res.stdout
        )
    assert res.stdout.count("sources=defaults+cache:") == 4
    # the native trace line names the algorithm that ran
    assert "algo " + expected[262144] in res.stderr, res.stderr[-2000:]


def test_foreign_launcher_env_adoption():
    # an mpirun-shaped environment (OMPI_COMM_WORLD_RANK/SIZE) with no
    # MPI4JAX_TPU_* vars must be adopted as the world job description —
    # the drop-in path for `mpirun -n 2 python prog.py` users
    # (reference README.rst:73-77)
    _port[0] += 7
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["MPI4JAX_TPU_COORD"] = f"127.0.0.1:{_port[0]}"
    procs = []
    for rank in range(2):
        e = dict(env)
        e["OMPI_COMM_WORLD_RANK"] = str(rank)
        e["OMPI_COMM_WORLD_SIZE"] = "2"
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(PROGRAMS, "basic_ops.py")],
            env=e, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        ))
    outs = [p.communicate(timeout=180) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, err + out
        assert "basic_ops OK" in out


@pytest.mark.parametrize("np_", [2, 4])
def test_mpi4py_comm_adoption(np_, tmp_path):
    # WorldComm.from_mpi (VERDICT r4 #6): plain processes holding
    # (simulated) mpi4py comms hand them over; bootstrap rides mpi4py,
    # data rides the native transport.  Covers COMM_WORLD, a
    # Split-derived subgroup, and composition with the framework's own
    # split.  Reference bar: any MPI.Comm as op param (utils.py:80-127).
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("MPI4JAX_TPU_COORD", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["FAKE_MPI_DIR"] = str(tmp_path)
    env["FAKE_MPI_SIZE"] = str(np_)
    procs = []
    for rank in range(np_):
        e = dict(env)
        e["FAKE_MPI_RANK"] = str(rank)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(PROGRAMS, "mpi_adopt.py")],
            env=e, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        ))
    outs = [p.communicate(timeout=240) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, err + out
        assert "mpi_adopt OK" in out


def test_foreign_launcher_jobid_port_derivation():
    # two concurrent mpirun jobs on one host must not collide on the
    # rendezvous port (ADVICE r4): with no MPI4JAX_TPU_COORD set, the
    # default derives from the launcher's job-unique token — same jobid
    # -> same port (ranks rendezvous), different jobid -> different port
    import mpi4jax_tpu.runtime.transport as tr

    def coord_for(jobid):
        saved = dict(os.environ)
        for var in ("OMPI_MCA_ess_base_jobid", "PMIX_NAMESPACE",
                    "SLURM_JOB_ID", "PMI_JOBID", "PBS_JOBID", "LSB_JOBID",
                    "MPI4JAX_TPU_COORD"):
            os.environ.pop(var, None)
        if jobid is not None:
            os.environ["OMPI_MCA_ess_base_jobid"] = jobid
        try:
            return tr._default_coord()
        finally:
            os.environ.clear()
            os.environ.update(saved)

    assert coord_for("12345") == coord_for("12345")
    assert coord_for("12345") != coord_for("12346")
    assert coord_for(None) == "127.0.0.1:49817"

    # end to end: both ranks derive the same port from the jobid alone
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("MPI4JAX_TPU_COORD", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["OMPI_MCA_ess_base_jobid"] = str(os.getpid())
    procs = []
    for rank in range(2):
        e = dict(env)
        e["OMPI_COMM_WORLD_RANK"] = str(rank)
        e["OMPI_COMM_WORLD_SIZE"] = "2"
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(PROGRAMS, "basic_ops.py")],
            env=e, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        ))
    outs = [p.communicate(timeout=180) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, err + out
        assert "basic_ops OK" in out


@pytest.mark.parametrize("np_", [2, 4])
def test_full_ops(np_):
    # the mesh tier's identity battery (dtype sweep, double transpose,
    # vmap, autodiff) executed as a world program — the analog of the
    # reference running its whole suite again under mpirun -np 2
    # (mpi-tests.yml:74-90 there)
    res = run_launcher("full_ops.py", np_, timeout=300)
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("full_ops OK") == np_


def test_multihost_hosts_list():
    # non-loopback host table: rank 1 listens on the 127.0.0.2 alias and
    # rank 0 dials it there (the pod/DCN layout exercised via the local
    # alias range; previously the hosts plumbing had no caller — weak #5)
    res = run_launcher(
        "basic_ops.py", 2, extra_args=("--hosts", "127.0.0.1,127.0.0.2")
    )
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("basic_ops OK") == 2


def test_hosts_list_length_mismatch():
    res = run_launcher(
        "basic_ops.py", 2, extra_args=("--hosts", "127.0.0.1")
    )
    assert res.returncode != 0
    assert "2 ranks" in res.stderr


def test_staged_eager_dispatch():
    # forced staged-eager (the callback-less-backend path, e.g. the axon
    # tunnel): eager ops stage through device_get/device_put + the
    # native transport; jit ops still lower normally on cpu ranks
    res = run_launcher(
        "basic_ops.py", 2, env_extra={"MPI4JAX_TPU_STAGED_EAGER": "1"}
    )
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("basic_ops OK") == 2


@pytest.mark.parametrize("ffi", ["on", "off"])
def test_ffi_fast_path(ffi):
    # native custom calls used when available; callback fallback under the
    # kill switch — identical numerics either way.  The "on" case clears
    # the var explicitly so the test holds under a CI job that forces
    # callbacks mode globally ("" parses as false in utils/config.py).
    env = ({"MPI4JAX_TPU_DISABLE_FFI": "1"} if ffi == "off"
           else {"MPI4JAX_TPU_DISABLE_FFI": ""})
    res = run_launcher("ffi_path.py", 2, env_extra=env)
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count(f"ffi_path OK (ffi={ffi})") == 2


def test_vmap_ops():
    res = run_launcher("vmap_ops.py", 2)
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("vmap_ops OK") == 2


def test_ordering():
    res = run_launcher("ordering.py", 2)
    assert res.returncode == 0, res.stderr + res.stdout


@pytest.mark.parametrize("np_,grid,size", [
    (1, (1, 1), (64, 128)), (2, (1, 2), (64, 128)),
    (4, (2, 2), (64, 128)), (6, (2, 3), (66, 126)),
])
def test_sw_world_matches_mesh_solver(np_, grid, size):
    # the world-tier per-rank solver (explicit sendrecv halos over the
    # native transport — the reference's mpirun shape) must reproduce
    # the mesh-tier SPMD solver bit-for-nearly-bit; covers the
    # self-wrap (np=1), two-rank-ring (gx=2 periodic), and the >= 3
    # periodic ring whose naive pairwise schedule deadlocked (the
    # uniform-shift fix)
    res = run_launcher(
        "sw_world_rank.py", np_, timeout=300,
        prog_dir=os.path.join(REPO, "benchmarks"),
        prog_args=("--grid", str(grid[0]), str(grid[1]),
                   "--size", str(size[0]), str(size[1]),
                   "--days", "0.02", "--check"),
    )
    assert res.returncode == 0, res.stderr + res.stdout
    assert "sw_world CHECK OK" in res.stdout


@pytest.mark.parametrize("mode", ["fresh_token", "no_token"])
def test_broken_token_chain_fails_at_trace_time(mode):
    # chain guard (VERDICT r4 #8): a deliberately broken chain in
    # explicit-token mode dies at TRACE time under strict mode, never
    # reaching the transport (beats the reference, which can only
    # document the footgun — docs/sharp-bits.rst:6-34 there)
    res = run_launcher(
        "broken_chain.py", 2, timeout=120,
        env_extra={"MPI4JAX_TPU_STRICT_TOKENS": "1", "BROKEN_MODE": mode},
    )
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("CAUGHT AT TRACE TIME") == 2
    assert "UNREACHABLE" not in res.stdout


def test_mesh_world_composition():
    # tier composition: np=2 world ranks, each owning a 4-virtual-device
    # mesh — mesh psum inside shard_map + world ops in the same jitted
    # step, plus the asymmetric-chain torture (SURVEY §7 hard part 4)
    res = run_launcher(
        "mesh_world.py", 2, timeout=300,
        env_extra={"XLA_FLAGS": "--xla_force_host_platform_device_count=4"},
    )
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("mesh_world OK") == 2


def test_subcomm_ops():
    # split/dup sub-communicators on a 2x2 rank grid (reference analog:
    # arbitrary mpi4py comms, comm.py:4-11 + sharp-bits there)
    res = run_launcher("subcomm_ops.py", 4)
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("subcomm_ops OK") == 4


def test_status_ops():
    # status introspection on recv/sendrecv (reference
    # test_sendrecv.py:29-61): eager, jit, ANY_TAG, split tags, short
    # messages
    res = run_launcher("status_ops.py", 2)
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("status_ops OK") == 2


@pytest.mark.parametrize("seed", [0, 7, 23])
def test_fuzz_ops(seed):
    # randomized matched-op program, replayed against numpy — exercises
    # framing, eager/writer concurrency, self-queue, and wildcards in
    # combination (the generative big sibling of the ordering tortures)
    res = run_launcher("fuzz_ops.py", 2,
                       env_extra={"FUZZ_SEED": str(seed)})
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("fuzz_ops OK") == 2


@pytest.mark.parametrize("seed", [3, 11])
def test_fuzz_ops_ring_boundary(seed):
    # same generative program against 4 KB p2p rings: payloads flip
    # between inline frames and stub+TCP constantly (inline cutoff
    # ring/4 = 1 KB sits inside the fuzz size range), and ring wrap
    # happens every few messages — the r5 rings' nastiest regime
    res = run_launcher("fuzz_ops.py", 2,
                       env_extra={"FUZZ_SEED": str(seed), "FUZZ_OPS": "80",
                                  "MPI4JAX_TPU_SHM_RING_KB": "4",
                                  "MPI4JAX_TPU_DISABLE_SHM": ""})
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("fuzz_ops OK") == 2


def test_wildcard_recv():
    # ANY_SOURCE receives at np=4, incl. mixed wildcard/directed ordering
    # (the reference's default recv source, recv.py:45 there)
    res = run_launcher("wildcard_recv.py", 4)
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("wildcard_recv OK") == 4


def test_autodiff():
    res = run_launcher("autodiff.py", 2)
    assert res.returncode == 0, res.stderr + res.stdout


def test_abort_fail_fast():
    res = run_launcher("abort.py", 2, timeout=120)
    assert res.returncode != 0
    assert "UNREACHABLE" not in res.stdout
    assert "returned error code" in res.stderr


@pytest.mark.parametrize("mode", ["opcode", "reduce_op", "dtype"])
def test_shm_schedule_mismatch_aborts(mode):
    # the arena's per-op opword cross-check: ranks disagreeing on which
    # collective comes next — or on its dtype or reduce op at equal byte
    # counts (ADVICE r4 low) — must abort with a diagnostic naming both
    # opwords, not hang in a barrier or reduce divergently in silence
    res = run_launcher("shm_schedule_mismatch.py", 2, timeout=120,
                       env_extra={"MISMATCH_MODE": mode,
                                  "MPI4JAX_TPU_DISABLE_SHM": ""})
    assert res.returncode != 0
    assert res.stdout.count("warmup ok") == 2
    assert "UNREACHABLE" not in res.stdout
    assert ("schedule mismatch" in res.stderr
            or "returned error code" in res.stderr), res.stderr[-800:]


def test_tag_mismatch_aborts():
    res = run_launcher("tag_mismatch.py", 2, timeout=120)
    assert res.returncode != 0
    assert "UNREACHABLE\n" not in res.stdout
    assert "order violation" in res.stderr or "returned error code" in res.stderr


def test_flush_exit_no_deadlock():
    # reference regression: pending async comm at teardown must not hang
    res = run_launcher("flush_exit.py", 2, timeout=120)
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("dispatched, exiting") == 2


def test_debug_log_format():
    res = run_launcher(
        "ordering.py", 2, env_extra={"MPI4JAX_TPU_DEBUG": "1"}
    )
    assert res.returncode == 0, res.stderr + res.stdout
    # reference format: "r<rank> | <id8> | <Op> ..." with timing on exit
    import re

    lines = [l for l in res.stderr.splitlines() if re.match(r"^r\d+ \| ", l)]
    assert any("Send" in l for l in lines), res.stderr[:2000]
    assert any(
        re.search(r"done with code 0 \(\d+\.\d+ s\)", l) for l in lines
    )


def _jax_at_least_min():
    # the observability world tests are the only ones that import the
    # package IN-PROCESS (trace validation + cache loading), so they
    # skip cleanly where the package gate blocks the import instead of
    # failing alongside the subprocess-only tests
    try:
        import jax

        parts = []
        for piece in jax.__version__.split(".")[:3]:
            parts.append(int("".join(c for c in piece if c.isdigit()) or 0))
        return tuple(parts) >= (0, 6, 0)
    except Exception:
        return False


@pytest.mark.skipif(not _jax_at_least_min(),
                    reason="package gate: needs jax >= 0.6")
def test_trace_records_and_merges_perfetto_timeline(tmp_path):
    """The observability acceptance path end to end: `launch --trace`
    on a 3-rank full-ops program produces one merged Perfetto-loadable
    trace with per-op spans from EVERY rank (bytes, peer/algorithm,
    wait/transfer phases); `profile report` renders the table from the
    same recordings; `tune --from-trace` derives a loadable algorithm
    cache from them."""
    import json

    from mpi4jax_tpu import obs, tune

    out = tmp_path / "trace.json"
    res = run_launcher(
        "full_ops.py", 3, timeout=600,
        extra_args=("--trace", str(out)),
        # TCP path: shm-arena events carry algo=shm, which is honest but
        # useless to the tuner; the acceptance run records real algorithms
        env_extra={"MPI4JAX_TPU_DISABLE_SHM": "1"},
    )
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("full_ops OK") == 3
    assert "merged 3/3 rank recording(s)" in res.stderr, res.stderr[-2000:]

    parts = obs.part_paths(str(out))
    assert len(parts) == 3, parts
    merged = json.loads(out.read_text())
    assert obs.validate_chrome_trace(merged) == []
    spans = [e for e in merged["traceEvents"]
             if e["ph"] == "X" and e.get("cat") != "phase"]
    assert {e["pid"] for e in spans} == {0, 1, 2}  # every rank present
    native_ar = [e for e in spans
                 if e["name"] == "Allreduce" and e["cat"] == "native"]
    assert native_ar, "no native allreduce spans recorded"
    assert all(e["args"]["bytes"] > 0 for e in native_ar)
    assert any(e["args"].get("algo") in ("ring", "rd", "tree")
               for e in native_ar), native_ar[:3]
    sends = [e for e in spans if e["name"] == "Send" and e["cat"] == "native"]
    assert any(e["args"]["peer"] >= 0 for e in sends)
    # the ops layer contributes labeled spans on its own thread row
    assert any(e["cat"] == "ops" and e["args"]["bytes"] > 0 for e in spans)
    # the wait/transfer split renders as nested phase slices
    phase_names = {e["name"] for e in merged["traceEvents"]
                   if e.get("cat") == "phase"}
    assert "wait" in phase_names

    # profile report renders the per-op/per-algo table from the dumps
    env = dict(os.environ)
    env.setdefault("PYTHONPATH", REPO)
    env["JAX_PLATFORMS"] = "cpu"
    rep = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.profile", "report", *parts],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO,
    )
    assert rep.returncode == 0, rep.stderr
    assert "Allreduce" in rep.stdout and "wait_frac" in rep.stdout

    # tune --from-trace: recorded real-run timings -> loadable cache
    cache = tmp_path / "cache_from_trace.json"
    tn = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.tune",
         "--from-trace", f"{out}.rank*.json", "--cache", str(cache)],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO,
    )
    assert tn.returncode == 0, tn.stderr + tn.stdout
    data = json.loads(cache.read_text())
    assert data["world_size"] == 3
    assert all(e[1] in ("ring", "rd", "tree")
               for op in data["table"] for e in data["table"][op])
    try:
        table = tune.load_cache(3, path=str(cache))  # what comm_init loads
        assert table
    finally:
        tune._cache_table = None
        tune._cache_origin = None
