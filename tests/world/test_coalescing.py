"""Async progress engine: coalescing equivalence and fault injection.

Three layers of proof that small-send coalescing is transparent:

- bridge level (runs in ANY container — the ranks never import jax):
  a burst program's received bytes digest bit-identically with the
  engine + coalescing on vs fully off;
- package level (needs jax >= 0.6, like the other in-process world
  tests): ``world_programs/coalesce_ops.py`` under the launcher with
  coalescing on/off produces identical per-rank digests, and the SAME
  program verifies clean under the static analyzer unchanged —
  coalescing is invisible to the match model because buffered sends
  already are its semantics;
- failure injection: a fault landing on a send INSIDE a coalesced run
  (after=N counts logical sends, not wire frames) still tears the job
  down detectably, with the engine queue armed.
"""

import hashlib
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
PROGRAMS = os.path.join(REPO, "tests", "world_programs")

COALESCE_ON = {"MPI4JAX_TPU_PROGRESS_THREAD": "1",
               "MPI4JAX_TPU_COALESCE_BYTES": "4096"}
COALESCE_OFF = {"MPI4JAX_TPU_PROGRESS_THREAD": "0",
                "MPI4JAX_TPU_COALESCE_BYTES": "0"}

# ---- bridge level: runs everywhere (parent-package shim, no jax) ----

_BRIDGE_PROG = r"""
import hashlib, os, sys, types
REPO = %r
sys.path.insert(0, REPO)
pkg = types.ModuleType("mpi4jax_tpu")
pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
sys.modules["mpi4jax_tpu"] = pkg
import numpy as np
from mpi4jax_tpu.runtime import bridge, transport

c = transport.get_world_comm()
h, r, n = c.handle, c.rank(), c.size()
digest = hashlib.sha256()
for round_ in range(3):
    for peer in range(n):
        if peer == r:
            continue
        for i in range(24):
            m = 3 + (i %% 4) * 61
            bridge.send(h, np.arange(m, dtype=np.int32) + 10000 * r + i,
                        peer, 1000 * round_ + i)
    for peer in range(n):
        if peer == r:
            continue
        for i in range(24):
            m = 3 + (i %% 4) * 61
            got = bridge.recv(h, (m,), np.int32, peer, 1000 * round_ + i)
            assert got[0] == 10000 * peer + i, (peer, i, got[0])
            digest.update(got.tobytes())
    out = bridge.allreduce(h, np.ones(8), 0)
    assert abs(float(out[0]) - n) < 1e-9
    digest.update(out.tobytes())
bridge.barrier(h)
print("bridge_coalesce digest r%%d %%s" %% (r, digest.hexdigest()),
      flush=True)
print("bridge_coalesce OK", flush=True)
"""


def _port(slot):
    # pid-derived, slot-separated: fixed ports collide with lingering
    # sockets from neighbouring launcher tests on busy CI hosts
    return 46900 + (os.getpid() * 7 + slot * 11) % 800


def _run_bridge_prog(tmp_path, port, env_extra):
    prog = tmp_path / "bridge_coalesce.py"
    prog.write_text(_BRIDGE_PROG % REPO)
    env = dict(os.environ)
    env["MPI4JAX_TPU_DISABLE_SHM"] = "1"  # coalescing rides the TCP path
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "mpi4jax_tpu/runtime/launch.py"),
         "-n", "3", "--port", str(port), str(prog)],
        capture_output=True, text=True, timeout=180, env=env, cwd=REPO,
    )


def _digests(stdout, marker):
    # regex, not line starts: the launcher merges rank stdout streams,
    # which can interleave another rank's partial line ahead of ours
    import re

    return sorted(re.findall(marker + r" (r\d+ [0-9a-f]{64})", stdout))


def test_bridge_level_coalescing_bit_identical(tmp_path):
    res_on = _run_bridge_prog(tmp_path, _port(0), COALESCE_ON)
    assert res_on.returncode == 0, res_on.stderr + res_on.stdout
    assert res_on.stdout.count("bridge_coalesce OK") == 3
    res_off = _run_bridge_prog(tmp_path, _port(1), COALESCE_OFF)
    assert res_off.returncode == 0, res_off.stderr + res_off.stdout
    d_on = _digests(res_on.stdout, "bridge_coalesce digest")
    d_off = _digests(res_off.stdout, "bridge_coalesce digest")
    assert d_on == d_off and len(d_on) == 3, (d_on, d_off)


def test_bridge_level_fault_at_coalesced_boundary(tmp_path):
    """A crash injected on the 30th LOGICAL send of rank 0 — inside a
    coalesced run (24-message bursts merge into container frames) —
    must fail the job loudly with the queue armed, exactly like the
    uncoalesced wire would."""
    env = dict(COALESCE_ON)
    env["MPI4JAX_TPU_FAULT"] = "rank=0,point=send,after=30,action=exit"
    env["MPI4JAX_TPU_TIMEOUT_S"] = "6"
    res = _run_bridge_prog(tmp_path, _port(2), env)
    assert res.returncode != 0
    assert "fault injection" in res.stderr, res.stderr[-2000:]
    # the launcher's post-mortem names the injected rank as first-failing
    assert "rank 0" in res.stderr, res.stderr[-1500:]


# ---- package level: the real ops layer + the static verifier --------


def _jax_at_least_min():
    try:
        import jax

        parts = []
        for piece in jax.__version__.split(".")[:3]:
            parts.append(int("".join(c for c in piece if c.isdigit()) or 0))
        return tuple(parts) >= (0, 6, 0)
    except Exception:
        return False


needs_package = pytest.mark.skipif(
    not _jax_at_least_min(), reason="package gate: needs jax >= 0.6")


def _run_launcher(np_, port, env_extra, timeout=300):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["MPI4JAX_TPU_DISABLE_SHM"] = "1"
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.runtime.launch",
         "-n", str(np_), "--port", str(port),
         os.path.join(PROGRAMS, "coalesce_ops.py")],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )


@needs_package
def test_coalesce_ops_bit_identical_on_off():
    res_on = _run_launcher(2, _port(3) + 800, COALESCE_ON)
    assert res_on.returncode == 0, res_on.stderr + res_on.stdout
    assert res_on.stdout.count("coalesce_ops OK") == 2
    res_off = _run_launcher(2, _port(4) + 800, COALESCE_OFF)
    assert res_off.returncode == 0, res_off.stderr + res_off.stdout
    d_on = _digests(res_on.stdout, "coalesce_ops digest")
    d_off = _digests(res_off.stdout, "coalesce_ops digest")
    assert d_on == d_off and len(d_on) == 2, (d_on, d_off)


@needs_package
def test_coalesce_ops_verifies_clean_unchanged():
    """The analyzer's verdict is knob-independent: the burst program
    passes the static verifier with zero findings — coalescing never
    changes the schedule the match model sees."""
    from mpi4jax_tpu import analysis

    report = analysis.check_program(
        os.path.join(PROGRAMS, "coalesce_ops.py"), 2)
    assert report.ok, report.format_table()
    assert all(len(v) > 0 for v in report.schedules.values())


@needs_package
def test_coalesce_ops_fault_hang_trips_deadline():
    """action=hang at a coalesced boundary: the unsent container frame
    leaves the receivers starved, and the progress deadline (measured
    from post time with the queue armed) must tear the job down."""
    # rank 0 is the burst sender in the chain topology; after=30 lands
    # inside its second-round burst (24 sends + ring/collective frames)
    env = dict(COALESCE_ON)
    env["MPI4JAX_TPU_FAULT"] = "rank=0,point=send,after=30,action=hang"
    env["MPI4JAX_TPU_TIMEOUT_S"] = "5"
    res = _run_launcher(2, _port(5) + 800, env, timeout=240)
    assert res.returncode != 0
    assert ("MPI4JAX_TPU_TIMEOUT_S" in res.stderr
            or "timed out" in res.stderr), res.stderr[-2500:]
