"""Topology subsystem: discovery, hierarchical equivalence, elastic
rediscovery.

All bridge-level through the launcher-as-file + the world programs'
parent-package shim, so the whole suite runs in ANY container (no jax
import inside the ranks) — the same pattern as the coalescing and
elastic bridge tests.

- ``topo_ops.py`` at np=4 (2x2 islands) and np=6 (uneven 4+2), shm on
  and off: hring/htree x {f32, bf16} x {SUM, MAX} bit-compared against
  the flat default and the numpy schedule simulators
  (``topo.simulate_hring_sum``), rank consistency, hierarchical
  allgather/bcast/reduce, discovery + native-map assertions;
- ``MPI4JAX_TPU_HIER=deny`` runs the same program with the
  hierarchical default degraded (the program's flat-vs-hring pair
  still holds: forced hring degrades to ring bit-for-bit);
- ``MPI4JAX_TPU_ICI_LEG=force`` runs it with the ICI data-plane leg
  active (exact, and composed with ``COLL_QUANT=force`` for the
  in-kernel int8 wire), parity against ``simulate_hring_sum(...,
  intra="ring")`` / ``simulate_ici_q_sum``; ``off`` must be inert;
- elastic: a rank death that EMPTIES an island shrinks np=3 (2+1) to
  np=2 and the rebuilt world re-discovers a clean flat topology.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
PROGRAMS = os.path.join(REPO, "tests", "world_programs")

# pid-mixed base (the test_sanitizers.py idiom): concurrent pytest
# processes land in disjoint port windows instead of all racing for
# one fixed base — the forced-ICI-leg launch was flaking on exactly
# that collision.  Per-launch strides below keep launches within one
# process apart; the 43200–44000 window is unused by the other world
# suites.
_port = [43200 + (os.getpid() * 41) % 600]


def _launch(program, np_, fake_hosts, expect_islands, *, timeout=300,
            env_extra=None, extra_args=()):
    _port[0] += np_ + 5
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("MPI4JAX_TPU_COLL_ALGO", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["TOPO_EXPECT_ISLANDS"] = expect_islands
    env.setdefault("MPI4JAX_TPU_TIMEOUT_S", "120")
    if env_extra:
        env.update(env_extra)
    # launcher as a FILE: the rank programs use the parent-package
    # shim, and `-m` would import the package (jax gate) in the
    # launcher process
    return subprocess.run(
        [sys.executable,
         os.path.join(REPO, "mpi4jax_tpu", "runtime", "launch.py"),
         "-n", str(np_), "--port", str(_port[0]),
         "--fake-hosts", fake_hosts, *extra_args,
         os.path.join(PROGRAMS, program)],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )


@pytest.mark.parametrize("np_,fake,expect,shm", [
    (4, "r0,r1|r2,r3", "0,0,1,1", "on"),
    (4, "r0,r1|r2,r3", "0,0,1,1", "off"),
    (6, "r0,r1,r2,r3|r4,r5", "0,0,0,0,1,1", "on"),
    (6, "r0,r1,r2,r3|r4,r5", "0,0,0,0,1,1", "off"),
])
def test_hier_equivalence(np_, fake, expect, shm):
    env = {"MPI4JAX_TPU_DISABLE_SHM": "1" if shm == "off" else ""}
    res = _launch("topo_ops.py", np_, fake, expect, env_extra=env)
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("topo_ops OK") == np_


@pytest.mark.parametrize("np_,fake,expect,quant", [
    (4, "r0,r1|r2,r3", "0,0,1,1", False),
    (6, "r0,r1,r2,r3|r4,r5", "0,0,0,0,1,1", False),
    (4, "r0,r1|r2,r3", "0,0,1,1", True),
])
def test_ici_leg_forced_equivalence(np_, fake, expect, quant):
    # MPI4JAX_TPU_ICI_LEG=force routes every f32 SUM hring/htree
    # through the ICI data plane (topo/_ici_leg.py — the Pallas fused
    # ring's numpy twin in a jax-less container): the program's
    # simulator expectation switches to intra="ring" and every exact
    # row must stay bit-identical to the native paths.  With
    # COLL_QUANT=force on top, the leader leg exchanges the in-kernel
    # int8 wire frames and parity is against simulate_ici_q_sum.
    env = {"MPI4JAX_TPU_ICI_LEG": "force"}
    if quant:
        env["MPI4JAX_TPU_COLL_QUANT"] = "force"
    res = _launch("topo_ops.py", np_, fake, expect, env_extra=env)
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("topo_ops OK") == np_


def test_ici_leg_off_is_inert():
    # the explicit off mode must leave the native schedules untouched
    res = _launch("topo_ops.py", 4, "r0,r1|r2,r3", "0,0,1,1",
                  env_extra={"MPI4JAX_TPU_ICI_LEG": "off"})
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("topo_ops OK") == 4


def test_noncontiguous_islands():
    # islands need not be contiguous rank ranges: the allgather's
    # island-block -> world-rank reorder and the leader ordering
    # (dense ids by lowest member) are exercised by an interleaved
    # partition
    res = _launch("topo_ops.py", 4, "r0,r2|r1,r3", "0,1,0,1")
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("topo_ops OK") == 4


def test_hier_deny_gate():
    # deny degrades the hierarchical default (and forced hring) to the
    # flat twins: the equivalence program still holds — every forced
    # hring IS a ring — except the default-pick assertion, which the
    # program skips when COLL_ALGO is exported
    res = _launch(
        "topo_ops.py", 4, "r0,r1|r2,r3", "0,0,1,1",
        env_extra={"MPI4JAX_TPU_HIER": "deny",
                   # the default-table assertion doesn't apply under
                   # deny; the program skips it when COLL_ALGO is set
                   "MPI4JAX_TPU_COLL_ALGO": "allreduce=ring"})
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("topo_ops OK") == 4


def test_full_ops_hier_force_axis():
    # the full op battery under MPI4JAX_TPU_HIER=force on a 2x2
    # partition: every allreduce/allgather upgrades to a hierarchical
    # twin and every large bcast/reduce routes through the leaders —
    # numerics must hold end to end (the forced-ring axis's sibling).
    # Package-level program: needs jax >= 0.6 like the other full-ops
    # axes; skip cleanly elsewhere.
    if not _jax_at_least_min():
        pytest.skip("package gate: needs jax >= 0.6")
    _port[0] += 11
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["MPI4JAX_TPU_HIER"] = "force"
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.runtime.launch",
         "-n", "4", "--port", str(_port[0]),
         "--fake-hosts", "r0,r1|r2,r3",
         os.path.join(PROGRAMS, "full_ops.py")],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("full_ops OK") == 4


def _jax_at_least_min():
    try:
        import jax

        parts = []
        for piece in jax.__version__.split(".")[:3]:
            parts.append(int("".join(c for c in piece if c.isdigit()) or 0))
        return tuple(parts) >= (0, 6, 0)
    except Exception:
        return False


_ELASTIC_PROG = r"""
import os, sys, types
REPO = %r
sys.path.insert(0, REPO)
pkg = types.ModuleType("mpi4jax_tpu")
pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
sys.modules["mpi4jax_tpu"] = pkg
import numpy as np
from mpi4jax_tpu import elastic, topo, tune
from mpi4jax_tpu.runtime import bridge, transport

comm = transport.get_world_comm()
t = comm.topology()
assert t is not None and t.multi, t
assert t.islands == [[0, 1], [2]], t.islands
assert comm.coll_algo("allreduce", 16 << 20) == "hring"

x = np.arange(70000, dtype=np.float32)
done = False
for step in range(6):
    try:
        if comm.rank() == 2 and step == 3:
            os._exit(17)  # island 1's only member dies mid-run
        out = bridge.allreduce(comm.handle, x + step, 0)
        assert np.array_equal(out, (x + step) * comm.size())
        if step >= 4:
            done = True
    except elastic.RankFailure:
        rec = elastic.recover(comm)
        # rank 2 WAS island 1: its death empties the island and the
        # rebuilt np=2 world must re-discover a clean FLAT topology
        t2 = comm.topology()
        assert t2 is not None and not t2.multi, t2
        assert t2.islands == [[0, 1]], t2.islands
        assert bridge.topo_info(comm.handle) == ([0, 0], 1)
        # flat map = flat defaults again (hring would degrade anyway);
        # both survivors share fake-host-0, so the rebuilt WORLD gets
        # the arena back ("shm") unless the suite's tcp axis is on
        assert comm.coll_algo("allreduce", 16 << 20) in ("shm", "ring")
        assert "defaults:topology" not in tune.sources()
        out = bridge.allreduce(comm.handle, x + 99, 0)
        assert np.array_equal(out, (x + 99) * 2), "post-shrink allreduce"
        done = True
        break
assert done
print("topo_elastic OK", comm.rank(), flush=True)
"""


def test_elastic_island_death_rediscovers_flat():
    """np=3 as islands [r0,r1]|[r2]: killing rank 2 empties island 1;
    the survivors shrink to np=2 and re-discover a flat single-island
    topology (sub-comms torn down, native map reinstalled, defaults
    back to flat)."""
    import tempfile

    _port[0] += 23
    with tempfile.TemporaryDirectory(prefix="m4j_topo_elastic_") as td:
        prog = os.path.join(td, "prog.py")
        with open(prog, "w") as f:
            f.write(_ELASTIC_PROG % REPO)
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["MPI4JAX_TPU_TIMEOUT_S"] = "15"
        res = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "mpi4jax_tpu", "runtime", "launch.py"),
             "-n", "3", "--port", str(_port[0]), "--elastic",
             "--fake-hosts", "r0,r1|r2", prog],
            capture_output=True, text=True, timeout=300, env=env, cwd=REPO,
        )
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("topo_elastic OK") == 2, res.stdout
    assert "generation 1" in res.stderr, res.stderr[-2000:]
