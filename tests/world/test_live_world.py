"""World-tier live re-tuning: a mid-run epoch swap must land on every
rank at the same collective boundary, and — for agreement-free exact
ops (int32 SUM) — must not change a single result bit.

The program is pkg-stub loaded (bridge-level, no jax import), so this
axis runs in every container.  The harness runs the same op sequence
twice — live armed with a mid-run proposal, and live off — and pins:

- every rank reports the SAME nonzero epoch (the rendezvous agreement
  property, here on real sockets rather than the match simulator);
- the swapped run's result digests are bit-identical to the live-off
  run's (int32 SUM is exact under every algorithm the table can name,
  so a swap that changed results would be a dispatch bug, not fp
  reassociation);
- the live-off run reports epoch 0 and zero swaps (the off = bit-for-
  bit guarantee's world half).
"""

import os
import re
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_port = [46900]

_PROG = r"""
import hashlib, os, sys, types
REPO = %r
sys.path.insert(0, REPO)
pkg = types.ModuleType("mpi4jax_tpu")
pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
sys.modules["mpi4jax_tpu"] = pkg
import numpy as np
from mpi4jax_tpu import live
from mpi4jax_tpu.runtime import bridge, transport

comm = transport.get_world_comm()
rank, size = comm.rank(), comm.size()
h = comm.handle

dig = hashlib.sha256()
x = (np.arange(4096, dtype=np.int32) %% 977) + 1
for step in range(30):
    out = bridge.allreduce(h, x + step, 0)  # SUM
    assert out[0] == (x[0] + step) * size, (step, out[0])
    dig.update(out.tobytes())
    if step == 9 and rank == 0 and live.armed():
        # flip every allreduce to recursive doubling mid-run; the
        # rendezvous installs it on all ranks a few boundaries later
        live.propose({"allreduce": [(0, "rd")]}, note="world-test")
st = live.status()
swaps = len(st.get("swaps", []))
print("live_swap rank %%d epoch %%d swaps %%d digest %%s"
      %% (rank, st.get("epoch", 0), swaps, dig.hexdigest()), flush=True)
"""


def _run(np_, live_on):
    _port[0] += np_ + 3
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    # force the TCP path so the installed table actually dispatches
    # (the same-host shm arena would shadow the algorithm choice)
    env["MPI4JAX_TPU_DISABLE_SHM"] = "1"
    env["MPI4JAX_TPU_LIVE_COOLDOWN_OPS"] = "8"   # rendezvous every 2
    with tempfile.TemporaryDirectory(prefix="m4j_live_world_") as td:
        prog = os.path.join(td, "prog.py")
        with open(prog, "w") as f:
            f.write(_PROG % REPO)
        # launcher as a FILE (the test_topology.py idiom): `-m` would
        # import the package, and with it the jax version gate
        args = [sys.executable,
                os.path.join(REPO, "mpi4jax_tpu", "runtime", "launch.py"),
                "-n", str(np_), "--port", str(_port[0])]
        if live_on:
            args.append("--live")           # the launcher flag axis
        args.append(prog)
        return subprocess.run(args, capture_output=True, text=True,
                              timeout=180, env=env, cwd=REPO)


_LINE_RE = re.compile(
    r"live_swap rank (\d+) epoch (\d+) swaps (\d+) digest ([0-9a-f]{64})")


def _parse(stdout, np_):
    rows = {int(r): (int(e), int(s), d)
            for r, e, s, d in _LINE_RE.findall(stdout)}
    assert sorted(rows) == list(range(np_)), stdout
    return rows


def test_mid_run_swap_same_epoch_and_bit_identical_digests():
    np_ = 2
    live_run = _run(np_, live_on=True)
    assert live_run.returncode == 0, live_run.stderr + live_run.stdout
    off_run = _run(np_, live_on=False)
    assert off_run.returncode == 0, off_run.stderr + off_run.stdout
    live_rows = _parse(live_run.stdout, np_)
    off_rows = _parse(off_run.stdout, np_)

    # agreement: every rank took the swap, at the same epoch
    epochs = {e for e, _, _ in live_rows.values()}
    assert epochs == {1}, live_rows
    assert all(s == 1 for _, s, _ in live_rows.values()), live_rows
    # the commit really happened mid-run (rank 0 logs the boundary)
    assert "[live] epoch 1 committed" in live_run.stderr, \
        live_run.stderr[-2000:]

    # exactness: int32 SUM digests identical across ranks AND across
    # the swapped vs never-swapped runs
    digests = {d for _, _, d in live_rows.values()}
    assert len(digests) == 1, live_rows
    assert digests == {d for _, _, d in off_rows.values()}, \
        (live_rows, off_rows)

    # live off: no epoch, no swaps — bit-for-bit pre-live behavior
    assert all(e == 0 and s == 0 for e, s, _ in off_rows.values()), \
        off_rows
