"""Failure detection & teardown: transport deadlines, abort propagation,
the launcher watchdog, and connect deadlines — every path driven by the
deterministic fault injector (``MPI4JAX_TPU_FAULT``).

The contract under test (docs/sharp-bits.md § Hangs, timeouts, and
teardown): with ``MPI4JAX_TPU_TIMEOUT_S`` set, one wedged rank makes
every peer exit nonzero — naming the stuck rank — and the launcher reap
the whole group within roughly 2x the configured deadline; with the
knob unset, peer *death* is still detected immediately via the dead
socket (the historic behavior).
"""

import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
PROGRAMS = os.path.join(REPO, "tests", "world_programs")

_port = [45500]  # own range: test_world_tier.py counts up from 44100


def run_launcher(program, np_, timeout=180, env_extra=None, extra_args=()):
    _port[0] += np_ + 3
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [
            sys.executable, "-m", "mpi4jax_tpu.runtime.launch",
            "-n", str(np_), "--port", str(_port[0]), *extra_args,
            os.path.join(PROGRAMS, program),
        ],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )

# keep p2p on the framed TCP path: the shm rings have their own bounded
# waits (also capped by the knob), but the wording asserted below is the
# TCP transport's
TCP = {"MPI4JAX_TPU_DISABLE_SHM": "1"}


def test_hung_rank_trips_deadline_and_reaps_group():
    # the acceptance scenario: rank 1 hangs at its 3rd recv; rank 0's
    # next recv from it must trip the 3 s progress deadline, name the
    # stuck peer, and the launcher must reap the hung rank — all well
    # inside 2x the deadline plus process startup
    t0 = time.monotonic()
    res = run_launcher("fault_ops.py", 2, timeout=90, env_extra={
        **TCP,
        "MPI4JAX_TPU_TIMEOUT_S": "3",
        "MPI4JAX_TPU_FAULT": "rank=1,point=recv,after=2,action=hang",
    })
    dt = time.monotonic() - t0
    assert res.returncode != 0
    assert "fault_ops OK" not in res.stdout
    assert "timed out after 3 s" in res.stderr, res.stderr[-800:]
    assert "recv header from 1" in res.stderr, res.stderr[-800:]
    assert "post-mortem" in res.stderr, res.stderr[-800:]
    assert dt < 40, f"teardown took {dt:.1f}s for a 3s deadline"


def test_hung_rank_shm_path_also_bounded():
    # same wedge under the default same-host arena: the job deadline
    # caps the shm ring/barrier waits too, so the group still tears
    # down promptly (the knob bounds the job, not just one transport)
    t0 = time.monotonic()
    res = run_launcher("fault_ops.py", 2, timeout=90, env_extra={
        "MPI4JAX_TPU_DISABLE_SHM": "",
        "MPI4JAX_TPU_TIMEOUT_S": "3",
        "MPI4JAX_TPU_FAULT": "rank=1,point=recv,after=2,action=hang",
    })
    dt = time.monotonic() - t0
    assert res.returncode != 0
    assert "fault_ops OK" not in res.stdout
    assert "timed out" in res.stderr, res.stderr[-800:]
    assert dt < 40, f"teardown took {dt:.1f}s for a 3s deadline"


def test_killed_rank_detected_without_deadline():
    # knob unset: a crashed rank (simulated by action=exit, code 17) is
    # still detected immediately through the dead socket — the historic
    # fail-fast path, now with the launcher's post-mortem naming the
    # first failure
    t0 = time.monotonic()
    res = run_launcher("fault_ops.py", 2, timeout=90, env_extra={
        **TCP,
        "MPI4JAX_TPU_FAULT": "rank=1,point=send,after=2,action=exit",
    })
    dt = time.monotonic() - t0
    assert res.returncode != 0
    assert "fault_ops OK" not in res.stdout
    # the launcher may notice either casualty first: the crashed rank
    # (code 17) or the peer that aborted on the dead socket — both get
    # named, and the injected crash is visible either way
    assert "post-mortem: rank" in res.stderr, res.stderr[-800:]
    assert "fault injection" in res.stderr, res.stderr[-800:]
    assert dt < 40, f"EOF detection took {dt:.1f}s"


def test_partitioned_rank_fails_both_sides():
    # action=close shuts every socket of rank 1 down mid-schedule (a
    # yanked cable): both sides of the partition must abort
    res = run_launcher("fault_ops.py", 2, timeout=90, env_extra={
        **TCP,
        "MPI4JAX_TPU_FAULT": "rank=1,point=send,after=2,action=close",
    })
    assert res.returncode != 0
    assert "fault_ops OK" not in res.stdout
    assert "returned error code" in res.stderr, res.stderr[-800:]


def test_abort_poisons_waiting_third_rank():
    # abort propagation: rank 1 hangs; rank 2 (2 s deadline) times out
    # first and aborts; rank 0 — blocked on rank 2 with a 60 s deadline
    # — must fail via rank 2's poison frame (naming it, carrying the
    # root-cause text) within seconds, NOT its own 60 s deadline.
    # Ranks are spawned directly (no launcher) so no reaper can race
    # the poison delivery; per-rank env carries different deadlines.
    port = 46300 + os.getpid() % 500
    base = dict(os.environ)
    base.pop("XLA_FLAGS", None)
    base.update({
        **TCP,
        "MPI4JAX_TPU_SIZE": "3",
        "MPI4JAX_TPU_COORD": f"127.0.0.1:{port}",
        "MPI4JAX_TPU_FAULT": "rank=1,point=recv,after=1,action=hang",
        "FAULT_OPS_ROUNDS": "8",
        "JAX_PLATFORMS": "cpu",
    })
    deadlines = {0: "60", 1: "60", 2: "2"}
    procs = {}
    for r in range(3):
        env = dict(base)
        env["MPI4JAX_TPU_RANK"] = str(r)
        env["MPI4JAX_TPU_TIMEOUT_S"] = deadlines[r]
        procs[r] = subprocess.Popen(
            [sys.executable, os.path.join(PROGRAMS, "fault_ops.py")],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        )
    try:
        t0 = time.monotonic()
        out0, err0 = procs[0].communicate(timeout=45)
        dt0 = time.monotonic() - t0
        out2, err2 = procs[2].communicate(timeout=45)
    finally:
        procs[1].kill()  # rank 1 is deliberately hung
        procs[1].communicate()
    assert procs[2].returncode != 0
    assert "timed out" in err2 and "from 1" in err2, err2[-600:]
    assert procs[0].returncode != 0
    assert "rank 2 aborted the job" in err0, err0[-600:]
    assert "timed out" in err0  # the poison carried rank 2's root cause
    assert dt0 < 30, f"poison took {dt0:.1f}s to beat a 60s deadline"


def test_launcher_watchdog_reaps_wedged_job():
    t0 = time.monotonic()
    res = run_launcher("hang_forever.py", 2, timeout=90,
                       extra_args=("--timeout", "3"))
    dt = time.monotonic() - t0
    assert res.returncode == 124, res.returncode
    assert "watchdog" in res.stderr, res.stderr[-600:]
    assert "post-mortem" in res.stderr
    assert dt < 40, f"watchdog reap took {dt:.1f}s for a 3s budget"


def test_launcher_watchdog_quiet_on_healthy_job():
    res = run_launcher("fault_ops.py", 2, timeout=90,
                       extra_args=("--timeout", "80"))
    assert res.returncode == 0, res.stderr + res.stdout
    assert res.stdout.count("fault_ops OK") == 2
    assert "watchdog" not in res.stderr


def test_launcher_sigterm_forwards_and_reaps(tmp_path):
    # scheduler preemption: SIGTERM to the launcher must take the whole
    # rank group down (exit 143) with zero orphans
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["HANG_PID_DIR"] = str(tmp_path)
    env["MPI4JAX_TPU_LAUNCH_GRACE_S"] = "2"
    p = subprocess.Popen(
        [sys.executable, "-m", "mpi4jax_tpu.runtime.launch", "-n", "2",
         "--port", str(46200 + os.getpid() % 500),
         os.path.join(PROGRAMS, "hang_forever.py")],
        env=env, cwd=REPO,
    )
    try:
        deadline = time.monotonic() + 30
        while len(list(tmp_path.glob("pid_*"))) < 2:
            assert time.monotonic() < deadline, "ranks never spawned"
            assert p.poll() is None, "launcher died before spawning"
            time.sleep(0.1)
        pids = [int(f.read_text()) for f in tmp_path.glob("pid_*")]
        p.send_signal(signal.SIGTERM)
        p.wait(timeout=30)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()
    assert p.returncode == 143, p.returncode
    time.sleep(0.5)
    orphans = []
    for pid in pids:
        try:
            os.kill(pid, 0)
            orphans.append(pid)
        except ProcessLookupError:
            pass
    assert not orphans, f"orphan ranks survived SIGTERM: {orphans}"


def test_launcher_sigint_escalates_past_ignoring_ranks(tmp_path):
    # Ctrl-C: ranks that ignore SIGINT must still be reaped after the
    # grace period (SIGINT -> grace -> SIGTERM -> SIGKILL), exit 130
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["HANG_PID_DIR"] = str(tmp_path)
    env["HANG_IGNORE_SIGINT"] = "1"
    env["MPI4JAX_TPU_LAUNCH_GRACE_S"] = "1"
    p = subprocess.Popen(
        [sys.executable, "-m", "mpi4jax_tpu.runtime.launch", "-n", "2",
         "--port", str(46250 + os.getpid() % 500),
         os.path.join(PROGRAMS, "hang_forever.py")],
        env=env, cwd=REPO,
    )
    try:
        deadline = time.monotonic() + 30
        while len(list(tmp_path.glob("pid_*"))) < 2:
            assert time.monotonic() < deadline, "ranks never spawned"
            assert p.poll() is None, "launcher died before spawning"
            time.sleep(0.1)
        pids = [int(f.read_text()) for f in tmp_path.glob("pid_*")]
        p.send_signal(signal.SIGINT)
        p.wait(timeout=30)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()
    assert p.returncode == 130, p.returncode
    time.sleep(0.5)
    orphans = []
    for pid in pids:
        try:
            os.kill(pid, 0)
            orphans.append(pid)
        except ProcessLookupError:
            pass
    assert not orphans, f"orphan ranks survived Ctrl-C: {orphans}"


def test_connect_deadline_reports_last_errno():
    # a rank whose lower peer never exists: the bootstrap dial must give
    # up within the configured deadline reporting the last errno, not
    # spin silently
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.update({
        "MPI4JAX_TPU_RANK": "1",
        "MPI4JAX_TPU_SIZE": "2",
        "MPI4JAX_TPU_COORD": f"127.0.0.1:{46350 + os.getpid() % 500}",
        "MPI4JAX_TPU_CONNECT_TIMEOUT_S": "2",
        "JAX_PLATFORMS": "cpu",
    })
    t0 = time.monotonic()
    res = subprocess.run(
        [sys.executable, os.path.join(PROGRAMS, "fault_ops.py")],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    dt = time.monotonic() - t0
    assert res.returncode != 0
    assert "cannot reach rank 0" in res.stderr, res.stderr[-600:]
    assert "within 2 s" in res.stderr, res.stderr[-600:]
    assert dt < 30, f"connect gave up after {dt:.1f}s for a 2s deadline"


def test_connect_hang_bounds_accept_side():
    # rank 1 wedged before dialing: with the connect knob set, rank 0's
    # accept side times out too instead of waiting forever
    t0 = time.monotonic()
    res = run_launcher("fault_ops.py", 2, timeout=90, env_extra={
        **TCP,
        "MPI4JAX_TPU_CONNECT_TIMEOUT_S": "2",
        "MPI4JAX_TPU_FAULT": "rank=1,point=connect,after=0,action=hang",
    })
    dt = time.monotonic() - t0
    assert res.returncode != 0
    assert "no higher rank dialed within 2 s" in res.stderr, (
        res.stderr[-600:])
    assert dt < 40, f"accept gave up after {dt:.1f}s for a 2s deadline"


def test_malformed_fault_spec_fails_loudly():
    # a typo'd injection spec must stop the job, not silently inject
    # nothing and fake a green failure test
    res = run_launcher("fault_ops.py", 2, timeout=90, env_extra={
        **TCP, "MPI4JAX_TPU_FAULT": "rank=1,point=typo,action=hang",
    })
    assert res.returncode != 0
    assert "malformed MPI4JAX_TPU_FAULT" in res.stderr, res.stderr[-600:]


def test_deadline_armed_job_still_passes():
    # the knob on a healthy job changes nothing: full rounds complete
    # under both transports with the deadline armed
    for extra in (TCP, {"MPI4JAX_TPU_DISABLE_SHM": ""}):
        res = run_launcher("fault_ops.py", 2, timeout=90, env_extra={
            **extra, "MPI4JAX_TPU_TIMEOUT_S": "30",
        })
        assert res.returncode == 0, res.stderr + res.stdout
        assert res.stdout.count("fault_ops OK") == 2
