"""Native transport under ThreadSanitizer / AddressSanitizer+UBSan.

Slow tier: builds ``native/tpucomm.cc`` with ``make tsan`` / ``make asan``
(transport-only — no jaxlib headers, no XLA in the loop) and runs a
2-rank loopback pair under each build, failing on ANY sanitizer report.

The rank processes drive the sanitized library through raw ctypes (no
jax import: the sanitizer runtimes would otherwise drown the report in
uninstrumented-interpreter noise), exercising the hot concurrency paths:
bootstrap accept/dial, framed send/recv both directions, allreduce (the
algorithm engine's threaded fan-in), and barrier — in a loop, with the
shm arena on (its lock-free rings are exactly what tsan is for) and off.
"""

import os
import shutil
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")
SO_DIR = os.path.join(REPO, "mpi4jax_tpu", "runtime", "_native")

_RANK_SRC = r"""
import ctypes, os, sys
import numpy as np

so = os.environ["SAN_SO"]
rank = int(os.environ["SAN_RANK"])
size = 2
port = int(os.environ["SAN_PORT"])

lib = ctypes.CDLL(so)
lib.tpucomm_init.restype = ctypes.c_int64
lib.tpucomm_init.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int,
                             ctypes.c_char_p]
h = lib.tpucomm_init(rank, size, port, b"")
assert h > 0, "tpucomm_init failed"

F32, SUM = 11, 0  # wire codes (tpucomm.h)
n = 1024
buf = np.arange(n, dtype=np.float32) + rank
out = np.zeros_like(buf)
for it in range(20):
    # p2p both directions (framed path + shm rings when arena is on)
    if rank == 0:
        lib.tpucomm_send(h, buf.ctypes.data_as(ctypes.c_void_p),
                         buf.nbytes, 1, it)
        rc = lib.tpucomm_recv(h, out.ctypes.data_as(ctypes.c_void_p),
                              out.nbytes, 1, it)
    else:
        rc = lib.tpucomm_recv(h, out.ctypes.data_as(ctypes.c_void_p),
                              out.nbytes, 0, it)
        lib.tpucomm_send(h, buf.ctypes.data_as(ctypes.c_void_p),
                         buf.nbytes, 0, it)
    assert rc == 0, f"recv failed at iter {it}"
    assert out[3] == 3.0 + (1 - rank), out[3]
    # collective fan-in + barrier
    rc = lib.tpucomm_allreduce(
        h, buf.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p), n, F32, SUM)
    assert rc == 0, f"allreduce failed at iter {it}"
    assert out[1] == 3.0, out[1]  # (1+0) + (1+1)
    assert lib.tpucomm_barrier(h) == 0
lib.tpucomm_finalize(h)
print("san-rank-ok", rank, flush=True)
"""

_REPORT_MARKERS = (
    "WARNING: ThreadSanitizer",
    "ERROR: AddressSanitizer",
    "ERROR: LeakSanitizer",
    "runtime error:",          # UBSan
    "SUMMARY: ThreadSanitizer",
    "SUMMARY: AddressSanitizer",
    "SUMMARY: UndefinedBehaviorSanitizer",
)


def _preload_path(libname):
    gcc = shutil.which("g++") or shutil.which("gcc")
    if gcc is None:
        pytest.skip("no C++ toolchain")
    path = subprocess.run(
        [gcc, f"-print-file-name={libname}"],
        capture_output=True, text=True,
    ).stdout.strip()
    if not path or not os.path.isabs(path) or not os.path.exists(path):
        pytest.skip(f"{libname} not installed")
    return path


def _run_pair(so_path, preload, san_env, port, extra_env):
    env = {
        **os.environ,
        "SAN_SO": so_path,
        "SAN_PORT": str(port),
        "LD_PRELOAD": preload,
        **san_env,
        **extra_env,
    }
    procs = []
    for rank in range(2):
        env_r = {**env, "SAN_RANK": str(rank)}
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _RANK_SRC],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env_r,
        ))
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            p.kill()
            out, err = p.communicate()
            pytest.fail(f"sanitized rank hung: {out[-500:]} {err[-500:]}")
        outs.append((p.returncode, out, err))
    for rank, (rc, out, err) in enumerate(outs):
        blob = out + err
        for marker in _REPORT_MARKERS:
            assert marker not in blob, (
                f"sanitizer report from rank {rank}:\n{blob[-4000:]}"
            )
        assert rc == 0, (
            f"rank {rank} exited {rc} (sanitizer exitcode=66 means a "
            f"report fired):\n{(out + err)[-2000:]}"
        )
        assert f"san-rank-ok {rank}" in out, out


def _build(target):
    res = subprocess.run(
        ["make", "-C", NATIVE, target], capture_output=True, text=True,
    )
    assert res.returncode == 0, f"make {target} failed:\n{res.stderr[-2000:]}"


def _uring_status_of(so, preload, san_env):
    """The sanitized build's RESOLVED uring state, probed in a fresh
    subprocess (the knob resolves once per process)."""
    code = (
        "import ctypes\n"
        "lib = ctypes.CDLL(%r)\n"
        "lib.tpucomm_uring_status.restype = ctypes.c_char_p\n"
        "print('status=' + lib.tpucomm_uring_status().decode())\n" % so
    )
    res = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120,
        env={**os.environ, "LD_PRELOAD": preload, **san_env,
             "MPI4JAX_TPU_URING": "1"},
    )
    for line in res.stdout.splitlines():
        if line.startswith("status="):
            return line[len("status="):]
    return "probe-failed: " + (res.stderr or res.stdout)[-200:]


def _uring_env(uring, so, preload, san_env):
    """Env for a sanitized uring leg — skips VISIBLY (never silently
    green on the poll path) when the kernel lacks io_uring."""
    if uring == "1":
        status = _uring_status_of(so, preload, san_env)
        if not status.startswith("on"):
            pytest.skip(f"io_uring leg skipped: sanitized build reports "
                        f"{status!r} on this kernel (URING=0 leg still "
                        "covered)")
    return {"MPI4JAX_TPU_URING": uring}


@pytest.mark.parametrize("uring", ["0", "1"])
@pytest.mark.parametrize("shm", ["on", "off"])
def test_tsan_loopback_pair(shm, uring):
    _build("tsan")
    preload = _preload_path("libtsan.so")
    so = os.path.join(SO_DIR, "libtpucomm_tsan.so")
    san = {"TSAN_OPTIONS": "exitcode=66 halt_on_error=0"}
    extra = {"MPI4JAX_TPU_JOBID": f"tsan{shm}{uring}{os.getpid()}",
             **_uring_env(uring, so, preload, san)}
    if shm == "off":
        extra["MPI4JAX_TPU_DISABLE_SHM"] = "1"
    _run_pair(
        so, preload, san,
        46200 + (os.getpid() + (7 if shm == "on" else 0)
                 + (29 if uring == "1" else 0)) % 900,
        extra,
    )


# ---- async progress engine under TSan ------------------------------
#
# The progress thread is the first truly concurrent writer the
# transport has had (descriptors cross the lock-free submission queue,
# completions cross a futex, coalesced frames are assembled off the
# posting thread), so it gets its own sanitized battery: a slow
# loopback pingpong with send BURSTS (forcing the coalescing path) plus
# a 3-rank allreduce/barrier loop, queue armed, failing on any report.

_ENGINE_RANK_SRC = r"""
import ctypes, os, sys
import numpy as np

so = os.environ["SAN_SO"]
rank = int(os.environ["SAN_RANK"])
size = int(os.environ["SAN_SIZE"])
port = int(os.environ["SAN_PORT"])

lib = ctypes.CDLL(so)
lib.tpucomm_init.restype = ctypes.c_int64
lib.tpucomm_init.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int,
                             ctypes.c_char_p]
h = lib.tpucomm_init(rank, size, port, b"")
assert h > 0, "tpucomm_init failed"

F32, SUM = 11, 0  # wire codes (tpucomm.h)
n = 256
buf = np.arange(n, dtype=np.float32) + rank
out = np.zeros_like(buf)
p = lambda a: a.ctypes.data_as(ctypes.c_void_p)
dest = (rank + 1) % size
src = (rank - 1 + size) % size
for it in range(12):
    # burst of detached small sends: the engine queues them and the
    # progress thread coalesces adjacent ones into container frames
    for i in range(8):
        rc = lib.tpucomm_send(h, p(buf), buf.nbytes, dest, it * 8 + i)
        assert rc == 0, f"send failed at iter {it}.{i}"
    for i in range(8):
        rc = lib.tpucomm_recv(h, p(out), out.nbytes, src, it * 8 + i)
        assert rc == 0, f"recv failed at iter {it}.{i}"
    assert out[3] == 3.0 + src, out[3]
    rc = lib.tpucomm_allreduce(
        h, p(buf), p(out), n, F32, SUM)
    assert rc == 0, f"allreduce failed at iter {it}"
    assert out[0] == sum(range(size)), out[0]
    # quantized wire formats under the engine: forced qring (chunked
    # codec frames + the TLS scratch the progress thread owns) and qrd
    # (whole-buffer packed exchanges).  On an arena comm (shm on) they
    # are exact no-ops; on TCP the result is approximate.
    QRING, QRD = 5, 6
    nq = 3000  # several codec blocks, uneven chunks at size 3
    qbuf = (np.arange(nq, dtype=np.float32) % 17 - 8) * (rank + 1)
    qout = np.zeros_like(qbuf)
    expect = (np.arange(nq, dtype=np.float64) % 17 - 8) * sum(
        r + 1 for r in range(size))
    for algo in (QRING, QRD):
        rc = lib.tpucomm_allreduce_algo(
            h, p(qbuf), p(qout), nq, F32, SUM, algo)
        assert rc == 0, f"quantized allreduce failed at iter {it}"
        denom = max(abs(expect).max(), 1e-6)
        assert abs(qout - expect).max() / denom < 3e-2, algo
    assert lib.tpucomm_barrier(h) == 0
lib.tpucomm_finalize(ctypes.c_int64(h))
print("san-rank-ok", rank, flush=True)
"""


def _run_group(src, n_ranks, so_path, preload, san_env, port, extra_env):
    env = {
        **os.environ,
        "SAN_SO": so_path,
        "SAN_PORT": str(port),
        "SAN_SIZE": str(n_ranks),
        "LD_PRELOAD": preload,
        **san_env,
        **extra_env,
    }
    procs = []
    for rank in range(n_ranks):
        env_r = {**env, "SAN_RANK": str(rank)}
        procs.append(subprocess.Popen(
            [sys.executable, "-c", src],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env_r,
        ))
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            out, err = p.communicate()
            pytest.fail(f"sanitized rank hung: {out[-500:]} {err[-500:]}")
        outs.append((p.returncode, out, err))
    for rank, (rc, out, err) in enumerate(outs):
        blob = out + err
        for marker in _REPORT_MARKERS:
            assert marker not in blob, (
                f"sanitizer report from rank {rank}:\n{blob[-4000:]}"
            )
        assert rc == 0, (
            f"rank {rank} exited {rc} (sanitizer exitcode=66 means a "
            f"report fired):\n{(out + err)[-2000:]}"
        )
        assert f"san-rank-ok {rank}" in out, out


@pytest.mark.parametrize("uring", ["0", "1"])
@pytest.mark.parametrize("shm", ["on", "off"])
def test_tsan_progress_engine_three_ranks(shm, uring):
    _build("tsan")
    preload = _preload_path("libtsan.so")
    so = os.path.join(SO_DIR, "libtpucomm_tsan.so")
    san = {"TSAN_OPTIONS": "exitcode=66 halt_on_error=0"}
    extra = {
        "MPI4JAX_TPU_JOBID": f"tsaneng{shm}{uring}{os.getpid()}",
        "MPI4JAX_TPU_PROGRESS_THREAD": "1",
        "MPI4JAX_TPU_COALESCE_BYTES": "4096",
        **_uring_env(uring, so, preload, san),
    }
    if shm == "off":
        # TCP path: this is where detached sends coalesce on the wire
        extra["MPI4JAX_TPU_DISABLE_SHM"] = "1"
    _run_group(
        _ENGINE_RANK_SRC, 3, so, preload, san,
        48200 + (os.getpid() + (13 if shm == "on" else 0)
                 + (31 if uring == "1" else 0)) % 900,
        extra,
    )


# ---- hierarchical collectives under TSan ---------------------------
#
# The topology subsystem adds a third concurrency shape: one engine op
# fans out across THREE communicators (the world op runs intra-island
# shm reduces, leader-tier TCP rounds, and intra bcasts on sub-comms
# borrowing the world's sockets, with per-leg observability events
# appended from whichever thread executes).  A three-rank two-island
# (r0,r1 | r2) loop drives forced hring/htree allreduces plus
# hierarchically routed bcasts, queue armed, shm on and off — 0
# reports required.

_HIER_RANK_SRC = r"""
import ctypes, os, sys
import numpy as np

so = os.environ["SAN_SO"]
rank = int(os.environ["SAN_RANK"])
size = int(os.environ["SAN_SIZE"])
port = int(os.environ["SAN_PORT"])

lib = ctypes.CDLL(so)
lib.tpucomm_init.restype = ctypes.c_int64
lib.tpucomm_init.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int,
                             ctypes.c_char_p]
lib.tpucomm_split.restype = ctypes.c_int64
lib.tpucomm_split.argtypes = [ctypes.c_int64, ctypes.c_int, ctypes.c_int]
lib.tpucomm_set_topology.restype = ctypes.c_int
lib.tpucomm_set_topology.argtypes = [
    ctypes.c_int64, ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
    ctypes.c_int64, ctypes.c_int64]
h = lib.tpucomm_init(rank, size, port, b"")
assert h > 0, "tpucomm_init failed"

# islands r0,r1 | r2 (MPI4JAX_TPU_FAKE_HOSTS in the env governs the
# arena gating; this mirrors it for the native map)
islands = [0, 0, 1]
intra_h = lib.tpucomm_split(h, islands[rank], rank)
lead_h = lib.tpucomm_split(h, 0 if rank in (0, 2) else -1, rank)
arr = (ctypes.c_int32 * size)(*islands)
rc = lib.tpucomm_set_topology(
    h, arr, size, intra_h if rank < 2 else 0, lead_h if rank != 1 else 0)
assert rc == 0, f"set_topology failed rc={rc}"

F32, SUM = 11, 0
HRING, HTREE = 7, 8
n = 3000
p = lambda a: a.ctypes.data_as(ctypes.c_void_p)
buf = (np.arange(n, dtype=np.float32) % 13) * (rank + 1)
expect = (np.arange(n, dtype=np.float32) % 13) * sum(
    r + 1 for r in range(size))
out = np.zeros_like(buf)
big = np.zeros(70000, np.float32)
for it in range(12):
    for algo in (HRING, HTREE):
        rc = lib.tpucomm_allreduce_algo(h, p(buf), p(out), n, F32, SUM,
                                        algo)
        assert rc == 0, f"hier allreduce failed at iter {it}"
        assert np.array_equal(out, expect), f"iter {it} algo {algo}"
    # >= 64 KiB bcast routes hierarchically (leader tier + islands)
    if rank == 1:
        big[:] = np.arange(70000, dtype=np.float32) + it
    rc = lib.tpucomm_bcast(h, p(big), ctypes.c_int64(big.nbytes), 1)
    assert rc == 0
    assert big[7] == 7.0 + it, big[7]
    assert lib.tpucomm_barrier(h) == 0

# forced-qalltoall burst (the MoE dispatch wire): the int8 codec packs
# and unpacks concurrently with the progress/writer threads; own-rank
# chunk stays exact, every chunk inside the codec error bound
QA2A = 9
cnt = 700
lib.tpucomm_alltoall_algo.restype = ctypes.c_int
lib.tpucomm_alltoall_algo.argtypes = [
    ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
    ctypes.c_int, ctypes.c_int]
base = np.stack([
    np.stack([(np.arange(cnt, dtype=np.float32) % 7 - 3) * (s + 1 + 2 * d)
              for d in range(size)])
    for s in range(size)])
sx = base[rank].copy()
rx = np.zeros_like(sx)
want = base[:, rank]
bound = np.max(np.abs(base)) / 127.0 * 0.5 + 1e-6
for it in range(8):
    rc = lib.tpucomm_alltoall_algo(h, p(sx), p(rx), cnt, F32, QA2A)
    assert rc == 0, f"qalltoall failed at iter {it}"
    assert np.array_equal(rx[rank], want[rank]), f"own chunk iter {it}"
    assert np.max(np.abs(rx - want)) <= bound, f"codec bound iter {it}"
    assert lib.tpucomm_barrier(h) == 0

lib.tpucomm_finalize(ctypes.c_int64(intra_h))
lib.tpucomm_finalize(ctypes.c_int64(lead_h))
lib.tpucomm_finalize(ctypes.c_int64(h))
print("san-rank-ok", rank, flush=True)
"""


@pytest.mark.parametrize("shm", ["on", "off"])
def test_tsan_hier_two_islands_three_ranks(shm):
    _build("tsan")
    preload = _preload_path("libtsan.so")
    so = os.path.join(SO_DIR, "libtpucomm_tsan.so")
    extra = {
        "MPI4JAX_TPU_JOBID": f"tsanhier{shm}{os.getpid()}",
        "MPI4JAX_TPU_PROGRESS_THREAD": "1",
        # the virtual partition is what grants the intra-island arena
        # while withholding the world one
        "MPI4JAX_TPU_FAKE_HOSTS": "r0,r1|r2",
    }
    if shm == "off":
        extra["MPI4JAX_TPU_DISABLE_SHM"] = "1"
    _run_group(
        _HIER_RANK_SRC, 3, so, preload,
        {"TSAN_OPTIONS": "exitcode=66 halt_on_error=0"},
        48500 + (os.getpid() + (17 if shm == "on" else 0)) % 900,
        extra,
    )


# ---- elastic shrink under load (TSan) ------------------------------
#
# The recovery bootstrap is the second lifecycle the transport's
# threads cross (engine shutdown + socket close + a fresh dial/accept
# mesh while the survivors' writer/progress threads wind down), so it
# gets a sanitized battery too: a 3-rank engine-armed load loop whose
# rank 1 vanishes mid-stream; the survivors detect the failure on a
# live op, abort-propagate, tpucomm_shrink into a 2-rank world at a
# re-derived port, and run the SAME load to completion — 0 reports
# required.

_SHRINK_RANK_SRC = r"""
import ctypes, os, sys
import numpy as np

so = os.environ["SAN_SO"]
rank = int(os.environ["SAN_RANK"])
size = int(os.environ["SAN_SIZE"])
port = int(os.environ["SAN_PORT"])

lib = ctypes.CDLL(so)
lib.tpucomm_init.restype = ctypes.c_int64
lib.tpucomm_init.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int,
                             ctypes.c_char_p]
lib.tpucomm_shrink.restype = ctypes.c_int64
lib.tpucomm_shrink.argtypes = [ctypes.c_int64, ctypes.c_int,
                               ctypes.c_int, ctypes.c_int,
                               ctypes.c_char_p]
h = lib.tpucomm_init(rank, size, port, b"")
assert h > 0, "tpucomm_init failed"

F32, SUM = 11, 0
n = 256
p = lambda a: a.ctypes.data_as(ctypes.c_void_p)

def load_iter(h, rank, size, it, must=False):
    '''One iteration of engine-armed load; returns False on the first
    transport failure (must=False) or asserts success (must=True).'''
    buf = np.arange(n, dtype=np.float32) + rank
    out = np.zeros_like(buf)
    dest = (rank + 1) % size
    src = (rank - 1 + size) % size
    for i in range(6):
        rc = lib.tpucomm_send(h, p(buf), buf.nbytes, dest, it * 8 + i)
        if rc:
            assert not must, f"send failed post-shrink at {it}.{i}"
            return False
    for i in range(6):
        rc = lib.tpucomm_recv(h, p(out), out.nbytes, src, it * 8 + i)
        if rc:
            assert not must, f"recv failed post-shrink at {it}.{i}"
            return False
    rc = lib.tpucomm_allreduce(h, p(buf), p(out), n, F32, SUM)
    if rc:
        assert not must, f"allreduce failed post-shrink at {it}"
        return False
    assert out[0] == sum(range(size)), out[0]
    rc = lib.tpucomm_barrier(h)
    if rc:
        assert not must, f"barrier failed post-shrink at {it}"
        return False
    return True

failed = False
for it in range(8):
    if rank == 1 and it == 3:
        # the injected death: vanish mid-stream with the mesh live
        # (peers see a reset on their next op touching this rank)
        print("san-rank-ok", rank, flush=True)
        os._exit(0)
    if not load_iter(h, rank, size, it):
        failed = True
        break

assert failed, "survivors must observe the rank death"
lib.tpucomm_abort_all()
new_rank = {0: 0, 2: 1}[rank]
h2 = lib.tpucomm_shrink(h, new_rank, 2, port + 7, b"")
assert h2 > 0, "tpucomm_shrink bootstrap failed"
for it in range(6):
    load_iter(h2, new_rank, 2, 100 + it, must=True)
lib.tpucomm_finalize(ctypes.c_int64(h2))
print("san-rank-ok", rank, flush=True)
"""


@pytest.mark.parametrize("shm", ["on", "off"])
def test_tsan_shrink_under_load_three_ranks(shm):
    _build("tsan")
    preload = _preload_path("libtsan.so")
    so = os.path.join(SO_DIR, "libtpucomm_tsan.so")
    extra = {
        "MPI4JAX_TPU_JOBID": f"tsanshr{shm}{os.getpid()}",
        "MPI4JAX_TPU_PROGRESS_THREAD": "1",
        "MPI4JAX_TPU_COALESCE_BYTES": "4096",
        # bound every wait: a survivor parked on the dead rank's
        # socket (or the shm barrier, shm=on) must fail over, not hang
        "MPI4JAX_TPU_TIMEOUT_S": "10",
        "MPI4JAX_TPU_CONNECT_TIMEOUT_S": "30",
    }
    if shm == "off":
        extra["MPI4JAX_TPU_DISABLE_SHM"] = "1"
    _run_group(
        _SHRINK_RANK_SRC, 3, so, preload,
        {"TSAN_OPTIONS": "exitcode=66 halt_on_error=0"},
        48400 + (os.getpid() + (19 if shm == "on" else 0)) % 900,
        extra,
    )


# ---- self-healing reconnect (TSan + ASan) --------------------------
#
# The reconnect path is a fourth lifecycle the threads cross: an
# injected RST mid-stream, the victim thread parking the fd while a
# fresh dial races the peer's accept, the hello exchange, gap replay
# from the retain ring, and seq dedup on the receiver — all while the
# progress thread (engine legs) keeps polling the same link set.  A
# 2-rank armed pair heals an injected reset and finishes the SAME
# deterministic load, 0 reports required.  The shm-on leg resets the
# idle TCP link under the arena and recovers it via heartbeats.

_HEAL_RANK_SRC = r"""
import ctypes, os, sys, time
import numpy as np

so = os.environ["SAN_SO"]
rank = int(os.environ["SAN_RANK"])
size = 2
port = int(os.environ["SAN_PORT"])

lib = ctypes.CDLL(so)
lib.tpucomm_init.restype = ctypes.c_int64
lib.tpucomm_init.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int,
                             ctypes.c_char_p]
h = lib.tpucomm_init(rank, size, port, b"")
assert h > 0, "tpucomm_init failed"

F32, SUM = 11, 0  # wire codes (tpucomm.h)
n = 1024
buf = np.arange(n, dtype=np.float32) + rank
out = np.zeros_like(buf)
p = lambda a: a.ctypes.data_as(ctypes.c_void_p)

# phase 1: p2p pingpong — the injected reset lands here (point=send
# counts transmissions) and the armed layer must heal it in place
for it in range(12):
    if rank == 0:
        lib.tpucomm_send(h, p(buf), buf.nbytes, 1, it)
        rc = lib.tpucomm_recv(h, p(out), out.nbytes, 1, it)
    else:
        rc = lib.tpucomm_recv(h, p(out), out.nbytes, 0, it)
        lib.tpucomm_send(h, p(buf), buf.nbytes, 0, it)
    assert rc == 0, f"recv failed at iter {it}"
    assert out[3] == 3.0 + (1 - rank), out[3]

# shm-on leg: park the wire so the heartbeat (not an op) finds the
# reset link and heals it before phase 2
sleep_s = float(os.environ.get("SAN_SLEEP_S", "0"))
if sleep_s > 0:
    time.sleep(sleep_s)

# phase 2: collectives over the healed link
for it in range(8):
    rc = lib.tpucomm_allreduce(h, p(buf), p(out), n, F32, SUM)
    assert rc == 0, f"allreduce failed at iter {it}"
    assert out[1] == 3.0, out[1]
    assert lib.tpucomm_barrier(h) == 0

cnt = (ctypes.c_int64 * 6)()
lib.tpucomm_link_counters(*[ctypes.byref(cnt, 8 * i) for i in range(6)])
assert cnt[1] >= 1, f"no reconnect recorded (counters {list(cnt)})"
lib.tpucomm_finalize(ctypes.c_int64(h))
print("san-rank-ok", rank, flush=True)
"""


def _heal_env(shm, uring, so, preload, san, tag):
    extra = {
        "MPI4JAX_TPU_JOBID": f"{tag}{shm}{uring}{os.getpid()}",
        "MPI4JAX_TPU_RETRY": "4",
        "MPI4JAX_TPU_RETRY_BACKOFF_MS": "50",
        "MPI4JAX_TPU_TIMEOUT_S": "60",
        "MPI4JAX_TPU_FAULT": "rank=0,point=send,after=5,action=reset",
        **_uring_env(uring, so, preload, san),
    }
    if shm == "off":
        extra["MPI4JAX_TPU_DISABLE_SHM"] = "1"
    else:
        # shm traffic can't be reset, so the fault lands on the idle
        # TCP link underneath — only heartbeats can find it
        extra["MPI4JAX_TPU_PROGRESS_THREAD"] = "1"
        extra["MPI4JAX_TPU_HEARTBEAT_S"] = "0.2"
        extra["SAN_SLEEP_S"] = "2.0"
    return extra


@pytest.mark.parametrize("uring", ["0", "1"])
@pytest.mark.parametrize("shm", ["on", "off"])
def test_tsan_self_heal_reconnect(shm, uring):
    _build("tsan")
    preload = _preload_path("libtsan.so")
    so = os.path.join(SO_DIR, "libtpucomm_tsan.so")
    san = {"TSAN_OPTIONS": "exitcode=66 halt_on_error=0"}
    _run_group(
        _HEAL_RANK_SRC, 2, so, preload, san,
        48700 + (os.getpid() + (23 if shm == "on" else 0)
                 + (41 if uring == "1" else 0)) % 400,
        _heal_env(shm, uring, so, preload, san, "tsanheal"),
    )


@pytest.mark.parametrize("uring", ["0", "1"])
@pytest.mark.parametrize("shm", ["on", "off"])
def test_asan_self_heal_reconnect(shm, uring):
    _build("asan")
    preload = _preload_path("libasan.so")
    so = os.path.join(SO_DIR, "libtpucomm_asan.so")
    san = {
        "ASAN_OPTIONS": "exitcode=66 detect_leaks=0 halt_on_error=1",
        "UBSAN_OPTIONS": "halt_on_error=1 print_stacktrace=1",
    }
    _run_group(
        _HEAL_RANK_SRC, 2, so, preload, san,
        49100 + (os.getpid() + (23 if shm == "on" else 0)
                 + (41 if uring == "1" else 0)) % 400,
        _heal_env(shm, uring, so, preload, san, "asanheal"),
    )


@pytest.mark.parametrize("uring", ["0", "1"])
@pytest.mark.parametrize("shm", ["on", "off"])
def test_asan_loopback_pair(shm, uring):
    _build("asan")
    preload = _preload_path("libasan.so")
    so = os.path.join(SO_DIR, "libtpucomm_asan.so")
    san = {
        "ASAN_OPTIONS": "exitcode=66 detect_leaks=0 halt_on_error=1",
        "UBSAN_OPTIONS": "halt_on_error=1 print_stacktrace=1",
    }
    extra = {"MPI4JAX_TPU_JOBID": f"asan{shm}{uring}{os.getpid()}",
             **_uring_env(uring, so, preload, san)}
    if shm == "off":
        extra["MPI4JAX_TPU_DISABLE_SHM"] = "1"
    _run_pair(
        so, preload, san,
        47200 + (os.getpid() + (7 if shm == "on" else 0)
                 + (37 if uring == "1" else 0)) % 900,
        extra,
    )


# ---- live re-tuning under TSan -------------------------------------
#
# The live controller is a NEW concurrent reader of the transport's
# state: its thread walks the obs ring via tpucomm_obs_peek while op
# threads append, and a mid-run epoch commit promotes staged decision
# tables (engine quiesced, comm lock held) while the dispatch path
# reads them per call.  A 2-rank pair runs the full Python stack
# (bridge + armed controller) against the sanitized .so, proposes a
# swap mid-loop, and requires 0 reports — shm on and off.

_LIVE_RANK_SRC = r"""
import os, sys, types
REPO = os.environ["SAN_REPO"]
sys.path.insert(0, REPO)
pkg = types.ModuleType("mpi4jax_tpu")
pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
sys.modules["mpi4jax_tpu"] = pkg
import numpy as np
from mpi4jax_tpu import live
from mpi4jax_tpu.runtime import bridge

rank = int(os.environ["SAN_RANK"])
port = int(os.environ["SAN_PORT"])
h = bridge.comm_init(rank, 2, "127.0.0.1:%d" % port)
assert live.armed(), "controller must arm under MPI4JAX_TPU_LIVE=auto"
x = np.arange(2048, dtype=np.int32)
for it in range(60):
    out = bridge.allreduce(h, x + it, 0)  # SUM
    assert out[0] == 2 * it, (it, out[0])
    if it == 20 and rank == 0:
        live.propose({"allreduce": [(0, "rd")]}, note="tsan")
st = live.status()
assert st["epoch"] >= 1, st
assert st["errors"] == 0, st
bridge.comm_finalize(h)
print("san-rank-ok", rank, flush=True)
"""


def _live_env(shm, tag):
    extra = {
        "SAN_REPO": REPO,
        "MPI4JAX_TPU_NATIVE_LIB": os.path.join(
            SO_DIR, "libtpucomm_tsan.so"),
        "MPI4JAX_TPU_JOBID": f"{tag}{shm}{os.getpid()}",
        "MPI4JAX_TPU_LIVE": "auto",
        "MPI4JAX_TPU_LIVE_WINDOW": "64",
        "MPI4JAX_TPU_LIVE_COOLDOWN_OPS": "8",
    }
    if shm == "off":
        extra["MPI4JAX_TPU_DISABLE_SHM"] = "1"
    return extra


@pytest.mark.parametrize("shm", ["on", "off"])
def test_tsan_live_retune_pair(shm):
    _build("tsan")
    preload = _preload_path("libtsan.so")
    so = os.path.join(SO_DIR, "libtpucomm_tsan.so")
    san = {"TSAN_OPTIONS": "exitcode=66 halt_on_error=0"}
    _run_group(
        _LIVE_RANK_SRC, 2, so, preload, san,
        49500 + (os.getpid() + (19 if shm == "on" else 0)) % 400,
        _live_env(shm, "tsanlive"),
    )
