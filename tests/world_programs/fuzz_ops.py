"""Randomized matched-op fuzz for the world-tier transport.

Both ranks generate the SAME seeded random program — a sequence of
collectives and matched point-to-point pairs with varying payloads,
tags, and dtypes — and verify every result against a pure-numpy replay.
A transport bug (framing, ordering, eager/writer races, self-queue,
wildcard matching) surfaces as a numeric mismatch or a fail-fast abort.

Run under the launcher with -n 2 and FUZZ_SEED set:
    python -m mpi4jax_tpu.runtime.launch -n 2 tests/world_programs/fuzz_ops.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)  # f64/i64 payloads stay 64-bit

import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4j

SEED = int(os.environ.get("FUZZ_SEED", "0"))
N_OPS = int(os.environ.get("FUZZ_OPS", "40"))
DTYPES = [np.float32, np.float64, np.int32, np.int8]


def main():
    comm = m4j.get_default_comm()
    rank, size = comm.rank(), comm.size()
    assert size == 2, "run with -n 2"
    other = 1 - rank

    rng = np.random.RandomState(SEED)  # identical stream on both ranks

    for step in range(N_OPS):
        kind = rng.choice(
            ["allreduce", "allgather", "sendrecv", "p2p", "bcast",
             "alltoall", "self", "wild"])
        dtype = DTYPES[rng.randint(len(DTYPES))]
        n = int(rng.randint(1, 2000))
        tag = int(rng.randint(0, 50))
        base = rng.randint(-50, 50, size=(2, n)).astype(dtype)
        mine = jnp.asarray(base[rank])

        if kind == "allreduce":
            out = m4j.allreduce(mine, op=m4j.SUM, comm=comm)
            np.testing.assert_allclose(
                np.asarray(out), base.sum(axis=0), err_msg=f"step {step}")
        elif kind == "allgather":
            out = m4j.allgather(mine, comm=comm)
            np.testing.assert_allclose(
                np.asarray(out), base, err_msg=f"step {step}")
        elif kind == "sendrecv":
            out = m4j.sendrecv(mine, source=other, dest=other, sendtag=tag,
                               recvtag=tag, comm=comm)
            np.testing.assert_allclose(
                np.asarray(out), base[other], err_msg=f"step {step}")
        elif kind == "p2p":
            sender = int(rng.randint(2))
            if rank == sender:
                m4j.send(mine, dest=other, tag=tag, comm=comm)
            else:
                st = m4j.Status()
                out = m4j.recv(mine, source=other, tag=tag, status=st,
                               comm=comm)
                np.testing.assert_allclose(
                    np.asarray(out), base[other], err_msg=f"step {step}")
                assert st.Get_count(dtype) == n, (step, st)
        elif kind == "bcast":
            root = int(rng.randint(2))
            out = m4j.bcast(mine, root=root, comm=comm)
            np.testing.assert_allclose(
                np.asarray(out), base[root], err_msg=f"step {step}")
        elif kind == "alltoall":
            block = rng.randint(-50, 50, size=(2, 2, n)).astype(dtype)
            out = m4j.alltoall(jnp.asarray(block[rank]), comm=comm)
            np.testing.assert_allclose(
                np.asarray(out), block[:, rank], err_msg=f"step {step}")
        elif kind == "self":
            m4j.send(mine, dest=rank, tag=tag, comm=comm)
            out = m4j.recv(mine, source=rank, tag=tag, comm=comm)
            np.testing.assert_allclose(
                np.asarray(out), base[rank], err_msg=f"step {step}")
        elif kind == "wild":
            sender = int(rng.randint(2))
            if rank == sender:
                m4j.send(mine, dest=other, tag=tag, comm=comm)
            else:
                st = m4j.Status()
                out = m4j.recv(mine, source=m4j.ANY_SOURCE, tag=tag,
                               status=st, comm=comm)
                np.testing.assert_allclose(
                    np.asarray(out), base[other], err_msg=f"step {step}")
                assert st.Get_source() == other, (step, st)

    m4j.barrier(comm=comm)
    print(f"fuzz_ops OK (rank {rank}, seed {SEED}, {N_OPS} ops)")


if __name__ == "__main__":
    main()
