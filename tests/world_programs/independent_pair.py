"""Independent small-transfer pairs: coalescing + deferral territory.

Two ranks exchange bursts of small messages on disjoint tags.  Every
send fits the buffered-send threshold, so (a) the recalibrated
``order_critical_exchange`` must NOT fire — a small bidirectional
exchange cannot rendezvous-block — and (b) the execution plan marks the
adjacent same-peer sends for coalescing and groups the independent ops.
Values are tag-addressed so any cross-delivery asserts immediately;
bit-identical with the plan on or off.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4j

BURST = 3
MSG = 64  # f32: 256 B, always below the coalesce/detach thresholds


def main():
    comm = m4j.get_default_comm()
    rank, size = comm.rank(), comm.size()
    assert size == 2, "run at np = 2"
    peer = 1 - rank

    zero = jnp.zeros((MSG,), jnp.float32)
    for round_ in range(2):
        base = 100 * round_
        # a burst of adjacent small sends to ONE peer: the plan's
        # coalesce marks, the engine's one-frame merge
        for i in range(BURST):
            m4j.send(jnp.full((MSG,), float(10 * rank + i + base)),
                     dest=peer, tag=base + i, comm=comm)
        for i in range(BURST):
            got = m4j.recv(zero, source=peer, tag=base + i, comm=comm)
            np.testing.assert_allclose(
                np.asarray(got), float(10 * peer + i + base))

    print(f"rank {rank}: independent_pair OK", flush=True)


if __name__ == "__main__":
    main()
