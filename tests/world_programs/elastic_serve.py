"""Bridge-level continuous-batching serving rank program (no jax, so
it runs in ANY container via the parent-package shim).

Rank 0 is the frontend: it submits a stream of requests — some only
after serving already started (continuous batching) — and drains them
through ``mpi4jax_tpu.elastic.serving``.  The toy decode function is a
deterministic function of the row contents ONLY, so the completed
transcripts are independent of world size and of how many times an
iteration was retried: a run that loses a rank mid-stream must print
the EXACT digest of an uninterrupted run, with every request completed.

Usage (under the launcher): elastic_serve.py [nreq]
"""

import hashlib
import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)
pkg = types.ModuleType("mpi4jax_tpu")
pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
sys.modules["mpi4jax_tpu"] = pkg

import numpy as np  # noqa: E402

from mpi4jax_tpu.elastic import serving  # noqa: E402
from mpi4jax_tpu.runtime import transport  # noqa: E402

NREQ = int(sys.argv[1]) if len(sys.argv) > 1 else 10


def decode_fn(toks, lengths, start, stop):
    """Next token per row: a pure function of the row's tokens."""
    out = np.zeros(stop - start, np.int32)
    for i in range(start, stop):
        n = int(lengths[i])
        row = toks[i, :n].astype(np.int64)
        out[i - start] = int((row.sum() * 31 + n * 7 + int(row[-1])) % 997)
    return out


def main():
    comm = transport.get_world_comm()
    _ = comm.handle  # connect the mesh before the first broadcast
    if comm.rank() != 0:
        serving.serve_worker(comm, decode_fn)
        print("elastic_serve worker done", flush=True)
        return

    server = serving.Server(comm, decode_fn, max_batch=4)
    for i in range(NREQ // 2):
        server.submit([i + 1, 2 * i + 1], max_new=3 + (i % 3))
    iters = 0
    while server.active or len(server.completed) < NREQ:
        # continuous batching: the second half of the stream arrives
        # while the first half is already decoding
        if iters == 2:
            for i in range(NREQ // 2, NREQ):
                server.submit([i + 1, 2 * i + 1], max_new=3 + (i % 3))
        server.step()
        iters += 1
        if iters > 500:
            raise RuntimeError("serving did not drain")
    server.stop()

    digest = hashlib.sha256()
    for r in sorted(server.completed, key=lambda r: r.id):
        assert r.done and len(r.generated) >= 3, (r.id, r.tokens)
        digest.update(repr((r.id, r.tokens)).encode())
    print(f"elastic_serve digest {digest.hexdigest()}", flush=True)
    print(f"elastic_serve OK nreq={len(server.completed)} "
          f"recoveries={server.recoveries}", flush=True)


if __name__ == "__main__":
    main()
