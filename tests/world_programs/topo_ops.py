"""Cross-algorithm equivalence for the hierarchical (topology-aware)
schedules — the hring/htree sibling of ``coll_algo_ops.py``.

Run under the launcher with ``MPI4JAX_TPU_FAKE_HOSTS`` partitioning the
ranks into islands (the test drives 2x2 at np=4 and uneven 4+2 at
np=6, shm on and off).  Asserts:

- discovery: the Topology matches the partition, the WORLD arena is
  withheld, each multi-member island's intra sub-comm has one exactly
  when shm is enabled, and the native layer reports the installed map;
- hring/htree x {f32, bf16} x {SUM, MAX} vs the flat default path:
  association-free cases (MAX, integer-valued floats) bit-identical;
  f32 SUM additionally bit-identical to the numpy schedule simulators
  (``topo.simulate_hring_sum`` — ONE simulator covers shm on and off,
  because both native intra paths fold in island member order);
  bf16 SUM inside the documented fp tolerance;
- rank consistency: every rank holds identical bits after a
  hierarchical allreduce (phase 3 broadcasts the leader's bytes);
- allgather under hring/htree: pure data movement, bit-for-bit,
  including the island-block -> world-rank reorder on non-contiguous
  partitions;
- large bcast/reduce route hierarchically (>= 64 KiB) with flat-equal
  results (exact payloads);
- MPI4JAX_TPU_HIER=deny degrades hring to the flat ring bit-for-bit.

Under ``MPI4JAX_TPU_ICI_LEG=force`` the same battery asserts the ICI
data-plane leg instead: f32 SUM routes through ``topo/_ici_leg.py``
(the Pallas fused ring's numpy twin in a jax-less container — the
identical association by contract), so the simulator expectation
switches to ``intra="ring"``; with ``MPI4JAX_TPU_COLL_QUANT=force`` on
top, to ``topo.simulate_ici_q_sum`` (and the flat-default comparison
loosens to the int8 error bound — quantization is lossy by design).
Everything else (integer/MAX/bf16 rows, allgather, bcast/reduce) is
ineligible for the leg and must stay bit-identical to the native
paths.

Bridge-level with the parent-package shim (no jax import): runs in ANY
container, like the coalescing bridge programs.
"""

import os
import sys
import types

REPO = os.path.join(os.path.dirname(__file__), "..", "..")
sys.path.insert(0, REPO)
pkg = types.ModuleType("mpi4jax_tpu")
pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
sys.modules["mpi4jax_tpu"] = pkg

import numpy as np  # noqa: E402

from mpi4jax_tpu import topo, tune  # noqa: E402
from mpi4jax_tpu.runtime import bridge, transport  # noqa: E402

# wire codes (native/tpucomm.h)
F32, BF16, I32 = 11, 10, 3
SUM, MAX = 0, 2


def f32_to_bf16_bits(a32):
    bits = a32.view(np.uint32)
    rounded = bits + np.uint32(0x7FFF) + ((bits >> np.uint32(16))
                                          & np.uint32(1))
    return (rounded >> np.uint32(16)).astype(np.uint16)


def bf16_bits_to_f32(b):
    return (b.astype(np.uint32) << 16).view(np.float32)


def main():
    comm = transport.get_world_comm()
    rank, size = comm.rank(), comm.size()
    h = comm.handle
    shm_on = os.environ.get("MPI4JAX_TPU_DISABLE_SHM", "") in ("", "0")

    # ---- discovery assertions -------------------------------------
    t = comm.topology()
    assert t is not None and t.multi, f"expected a multi-island map, got {t}"
    expect = [int(x) for x in os.environ["TOPO_EXPECT_ISLANDS"].split(",")]
    assert t.island_of == expect, (t.island_of, expect)
    active, _, _ = bridge.shm_info(h)
    assert not active, "world arena must be withheld under FAKE_HOSTS"
    info = bridge.topo_info(h)
    assert info == (expect, t.n_islands), info
    # the intra sub-comm's arena follows the shm axis (registered by
    # the bridge; probe through the cached handles)
    subs = bridge._topo_handles.get(int(h), [])
    my_members = t.island(rank)
    if len(my_members) > 1:
        intra_active, _, _ = bridge.shm_info(subs[0])
        assert intra_active == shm_on, (intra_active, shm_on)
    if (not os.environ.get("MPI4JAX_TPU_COLL_ALGO")
            and not os.environ.get("MPI4JAX_TPU_COLL_QUANT")):
        # (a forced quant gate upgrades the default table to the
        # quantized twins — the quant suite owns those assertions)
        assert comm.coll_algo("allreduce", 16 << 20) == "hring"
        assert comm.coll_algo("allreduce", 1024) == "tree"

    deny = os.environ.get("MPI4JAX_TPU_HIER", "allow").strip() == "deny"
    leg = os.environ.get("MPI4JAX_TPU_ICI_LEG", "").strip() == "force"
    legq = leg and (os.environ.get("MPI4JAX_TPU_COLL_QUANT", "").strip()
                    == "force")

    rng = np.random.RandomState(5)
    for count in (3, 513, 70000):  # < n_islands, odd small, > 64KB f32
        base_f = rng.randn(size, count).astype(np.float32) * 2
        base_i = rng.randint(-900, 900, size=(size, count)).astype(np.int32)
        base_x = base_i.astype(np.float32)  # integer-valued: exact SUM
        bf_bits = f32_to_bf16_bits(base_f)

        for algo in ("hring", "htree"):
            code = tune.ALGO_CODES[algo]
            # exact cases: bit-identical to the flat default path
            for dcode, base, op in ((I32, base_i, SUM), (F32, base_x, SUM),
                                    (F32, base_f, MAX),
                                    (BF16, bf_bits, MAX)):
                if legq and dcode == F32 and op == SUM:
                    # the quantized leg is lossy by design: this row is
                    # covered by the simulate_ici_q_sum parity below
                    continue
                x = base[rank].copy()
                ref = np.empty_like(x)
                bridge.allreduce_raw(h, x, ref, dcode, op)
                out = np.empty_like(x)
                bridge.allreduce_raw(h, x, out, dcode, op, algo=code)
                assert np.array_equal(out, ref), (
                    f"{algo} dcode={dcode} op={op} count={count}: not "
                    "bit-identical to the flat default")

            # f32 SUM on random floats: bit-parity with the simulator
            # (under MPI4JAX_TPU_HIER=deny the forced code DEGRADES to
            # its flat twin — the degrade contract is asserted below
            # instead, and here against the flat ring simulator)
            x = base_f[rank].copy()
            out = np.empty_like(x)
            bridge.allreduce_raw(h, x, out, F32, SUM, algo=code)
            if deny:
                if algo == "hring":
                    want = topo.simulate_ring_sum(
                        [base_f[r] for r in range(size)])
                    assert np.array_equal(out, want), (
                        f"denied {algo}: not the flat ring")
            else:
                parts = [base_f[r] for r in range(size)]
                if legq:
                    want = topo.simulate_ici_q_sum(parts, t.islands)
                else:
                    sim_fn = (topo.simulate_hring_sum if algo == "hring"
                              else topo.simulate_htree_sum)
                    want = sim_fn(parts, t.islands,
                                  intra="ring" if leg else "member")
                assert np.array_equal(out, want), (
                    f"{algo} count={count} leg={leg} q={legq}: native "
                    f"diverges from the numpy simulator (maxdiff "
                    f"{np.max(np.abs(out - want))})")
            # ...and within fp tolerance of the flat default (the int8
            # error bound when the quantized leg is forced)
            ref = np.empty_like(x)
            bridge.allreduce_raw(h, x, ref, F32, SUM)
            if legq:
                denom = max(float(np.max(np.abs(ref))), 1e-6)
                err = float(np.max(np.abs(out - ref))) / denom
                assert err < 5e-2, f"{algo} quant leg rel err {err:.2e}"
            else:
                assert np.allclose(out, ref, rtol=1e-5, atol=1e-5 * size)
            # rank consistency: every rank holds the same bits
            rows = bridge.allgather(h, out, size)
            for r in range(size):
                assert np.array_equal(rows[r], out), (
                    f"{algo} count={count}: rank {r} diverged")

            # bf16 SUM: error-bound vs f64 + rank consistency
            xb = bf_bits[rank].copy()
            outb = np.empty_like(xb)
            bridge.allreduce_raw(h, xb, outb, BF16, SUM, algo=code)
            exact = np.sum(bf16_bits_to_f32(bf_bits).astype(np.float64),
                           axis=0)
            denom = max(np.max(np.abs(exact)), 1e-6)
            err = np.max(np.abs(bf16_bits_to_f32(outb) - exact)) / denom
            assert err < 4e-2, f"{algo} bf16 SUM rel err {err:.2e}"
            rows = bridge.allgather(h, outb, size)
            for r in range(size):
                assert np.array_equal(rows[r], outb), f"{algo} bf16 diverged"

        # allgather: pure data movement — bit-for-bit under both
        xg = (base_i[rank, :count] + 13 * rank).astype(np.int32)
        ref = bridge.allgather(h, xg, size)
        for algo in ("hring", "htree"):
            got = bridge.allgather(h, xg, size,
                                   algo=tune.ALGO_CODES[algo])
            assert np.array_equal(got, ref), f"allgather {algo}"

    # ---- hierarchical bcast / reduce routing (>= 64 KiB) -----------
    big = np.arange(70000, dtype=np.float32)
    buf = big.copy() if rank == 1 else np.zeros_like(big)
    got = bridge.bcast(h, buf, 1)
    assert np.array_equal(got, big), "hier bcast payload wrong"
    xr = np.full(70000, float(rank + 1), np.float32)
    root = size - 1
    outr = bridge.reduce(h, xr, SUM, root)
    if rank == root:
        assert np.all(outr == sum(range(1, size + 1))), outr[:4]
    else:
        assert np.all(outr == rank + 1), "non-root reduce buf must stay input"

    # ---- deny gate: hring degrades to the flat ring bit-for-bit ----
    # (same process: the native gate is read per dispatch via the env
    # at startup, so drive the degrade through a FLAT-vs-forced pair
    # instead — forced ring vs forced hring on integer floats)
    xi = base_x[rank][:513].copy()
    a = np.empty_like(xi)
    b = np.empty_like(xi)
    bridge.allreduce_raw(h, xi, a, F32, SUM, algo=tune.ALGO_CODES["ring"])
    bridge.allreduce_raw(h, xi, b, F32, SUM, algo=tune.ALGO_CODES["hring"])
    if legq:
        # the quantized leg handles the forced hring: integer payloads
        # survive only to the int8 error bound
        denom = max(float(np.max(np.abs(a))), 1e-6)
        assert float(np.max(np.abs(a - b))) / denom < 5e-2, "quant leg hring"
    else:
        assert np.array_equal(a, b), "exact-int hring != ring"

    print(f"topo_ops OK (shm={int(shm_on)})", flush=True)


if __name__ == "__main__":
    main()
