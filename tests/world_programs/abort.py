"""Fail-fast behavior: one rank dies; the peer's pending recv must abort
the process with the transport error message instead of hanging (reference:
abort-on-error subprocess test, test_common.py:59-87 there)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp

import mpi4jax_tpu as m4j


def main():
    comm = m4j.get_default_comm()
    rank = comm.rank()
    # establish the mesh before rank 0 bails (init needs all ranks)
    m4j.barrier(comm=comm)
    if rank == 0:
        # "clean" early exit (code 0 so the launcher doesn't reap the peer
        # first): the peer's pending recv must then fail on the dead socket
        os._exit(0)
    m4j.recv(jnp.zeros((1,), jnp.float32), source=0, comm=comm)
    print("UNREACHABLE", flush=True)


if __name__ == "__main__":
    main()
