"""Package-level elastic DP GPT-2 training rank program (needs the real
ops layer, i.e. jax >= the package gate).

The acceptance scenario (ISSUE 9): an np=3 DP training job over the
tiny GPT-2 from ``benchmarks/quant_accuracy.py``, synchronized with
``parallel.dp.sync_gradients`` through the world-tier transport,
checkpointed every 2 steps via the elastic training loop.  A run whose
rank 1 is killed mid-job shrinks to np=2, resumes from the last
committed checkpoint, reshards the global batch (6 rows — divisible by
3 and 2, so the synced gradient stays the global mean), and its final
full-batch loss must match an uninterrupted run within the documented
bound (|rel diff| <= 1e-2, from float reassociation only; see
docs/elasticity.md).

Usage (under the launcher): gpt_dp_elastic.py [steps]
Checkpoint directory: MPI4JAX_TPU_CKPT_DIR.
"""

import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

import mpi4jax_tpu  # noqa: E402,F401  (the real package: ops layer)
from mpi4jax_tpu.elastic import training  # noqa: E402
from mpi4jax_tpu.parallel import dp  # noqa: E402
from mpi4jax_tpu.runtime import transport  # noqa: E402

_spec = importlib.util.spec_from_file_location(
    "m4j_qa_model", os.path.join(REPO, "benchmarks", "quant_accuracy.py"))
_qa = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_qa)

STEPS = int(sys.argv[1]) if len(sys.argv) > 1 else 8
VOCAB, D_MODEL, N_LAYER, N_HEAD, SEQ = 64, 32, 2, 4, 16
GLOBAL_BATCH = 6  # divisible by np=3 AND the shrunk np=2


def global_batch(step):
    rng = np.random.RandomState(1000 + step)
    data = rng.randint(0, VOCAB, size=(GLOBAL_BATCH, SEQ + 1))
    return data[:, :-1], data[:, 1:]


def batch_fn(step, rank, size):
    tok, tgt = global_batch(step)
    per = GLOBAL_BATCH // size
    lo = rank * per
    return tok[lo:lo + per], tgt[lo:lo + per]


def loss_fn(params, tok, tgt):
    import jax.numpy as jnp

    return _qa.gpt2_loss(params, jnp.asarray(tok), jnp.asarray(tgt),
                         N_LAYER, N_HEAD)


def main():
    comm = transport.get_world_comm()
    params = _qa.gpt2_init(np.random.RandomState(0), VOCAB, D_MODEL,
                           N_LAYER, N_HEAD, SEQ)
    step_fn = dp.elastic_step_fn(loss_fn, lr=0.05, batch_fn=batch_fn)
    params = training.run(step_fn, params, steps=STEPS, save_every=2)
    # the verdict metric: the FULL-batch loss at the final parameters,
    # on deterministic data — directly comparable across world shapes
    tok, tgt = global_batch(STEPS)
    final = float(loss_fn(params, tok, tgt))
    print(f"gpt_dp_elastic final_loss {final:.6f}", flush=True)
    print("gpt_dp_elastic OK", flush=True)


if __name__ == "__main__":
    main()
