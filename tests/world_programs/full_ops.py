"""Full per-op identity battery for the world tier (run under the
launcher) — the multi-process twin of the mesh tier's coverage, matching
the reference's dual-mode CI where the *entire* suite runs again under
``mpirun -np 2`` (reference .github/workflows/mpi-tests.yml:74-90,
docs/developers.rst:16-28 there).

Covers, per op: dtype sweep (bf16/f16/f32/f64/ints/bool/complex),
identity vs closed form, double-transpose ≡ identity (reference
test_allreduce.py:105-138), vmap, and grad/jvp where the op supports
autodiff.  Any assertion failure exits nonzero -> failed job.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4j

REDUCE_DTYPES = [
    jnp.bfloat16, jnp.float16, jnp.float32, jnp.float64,
    jnp.int8, jnp.int16, jnp.int32, jnp.int64,
    jnp.uint8, jnp.uint16, jnp.uint32, jnp.uint64,
]
MOVE_DTYPES = REDUCE_DTYPES + [jnp.bool_, jnp.complex64, jnp.complex128]


def _mk(dtype, rank, n=4):
    if dtype == jnp.bool_:
        return jnp.asarray([True, False, True, bool(rank % 2)])
    if jnp.issubdtype(dtype, jnp.complexfloating):
        return (jnp.arange(n) + 1j * (rank + 1)).astype(dtype)
    return (jnp.arange(n, dtype=jnp.float64) + rank).astype(dtype)


def _f64(a):
    a = np.asarray(a)
    return a.astype(np.complex128 if np.iscomplexobj(a) else np.float64)


def check_allreduce_dtypes(comm, rank, size):
    for dtype in REDUCE_DTYPES:
        x = _mk(dtype, rank)
        out = m4j.allreduce(x, op=m4j.SUM, comm=comm)
        assert out.dtype == x.dtype, (dtype, out.dtype)
        expect = np.arange(4) * size + sum(range(size))
        np.testing.assert_allclose(_f64(out), expect, rtol=1e-2)
        out = m4j.allreduce(x, op=m4j.MAX, comm=comm)
        np.testing.assert_allclose(_f64(out), np.arange(4) + size - 1,
                                   rtol=1e-2)
    # complex SUM / PROD
    for dtype in (jnp.complex64, jnp.complex128):
        x = jnp.full((3,), 1 + 1j, dtype)
        out = m4j.allreduce(x, op=m4j.SUM, comm=comm)
        np.testing.assert_allclose(_f64(out), size * (1 + 1j))
        out = m4j.allreduce(x, op=m4j.PROD, comm=comm)
        np.testing.assert_allclose(_f64(out), (1 + 1j) ** size)
    # bool logical ops
    mine = jnp.asarray([rank == 0, True, False])
    lor = m4j.allreduce(mine, op=m4j.LOR, comm=comm)
    np.testing.assert_array_equal(np.asarray(lor), [True, True, False])
    land = m4j.allreduce(mine, op=m4j.LAND, comm=comm)
    np.testing.assert_array_equal(np.asarray(land), [size == 1, True, False])
    # int bitwise
    bits = jnp.asarray([1 << rank, 3], jnp.int32)
    bor = m4j.allreduce(bits, op=m4j.BOR, comm=comm)
    np.testing.assert_array_equal(np.asarray(bor), [(1 << size) - 1, 3])


def check_movement_dtypes(comm, rank, size):
    """allgather / alltoall / bcast / gather / scatter / sendrecv / scan
    across the dtype table."""
    for dtype in MOVE_DTYPES:
        x = _mk(dtype, rank)
        ag = m4j.allgather(x, comm=comm)
        assert ag.shape == (size, 4) and ag.dtype == x.dtype
        for r in range(size):
            np.testing.assert_allclose(_f64(ag[r]), _f64(_mk(dtype, r)),
                                       rtol=1e-2)

        a2a_in = jnp.stack([_mk(dtype, rank)] * size)
        a2a = m4j.alltoall(a2a_in, comm=comm)
        for r in range(size):
            np.testing.assert_allclose(_f64(a2a[r]), _f64(_mk(dtype, r)),
                                       rtol=1e-2)

        b = m4j.bcast(x, root=size - 1, comm=comm)
        np.testing.assert_allclose(_f64(b), _f64(_mk(dtype, size - 1)),
                                   rtol=1e-2)

        g = m4j.gather(x, root=0, comm=comm)
        if rank == 0:
            for r in range(size):
                np.testing.assert_allclose(_f64(g[r]), _f64(_mk(dtype, r)),
                                           rtol=1e-2)

        sc_in = jnp.stack([_mk(dtype, r) for r in range(size)])
        mine = m4j.scatter(sc_in, root=0, comm=comm)
        np.testing.assert_allclose(_f64(mine), _f64(_mk(dtype, rank)),
                                   rtol=1e-2)

        ring = m4j.sendrecv(x, shift=1, comm=comm)
        np.testing.assert_allclose(
            _f64(ring), _f64(_mk(dtype, (rank - 1) % size)), rtol=1e-2)

    # scan on ordered dtypes
    for dtype in (jnp.float32, jnp.float64, jnp.int32, jnp.bfloat16):
        sc = m4j.scan(jnp.ones((2,), dtype) * (rank + 1), op=m4j.SUM,
                      comm=comm)
        np.testing.assert_allclose(_f64(sc), sum(range(1, rank + 2)),
                                   rtol=1e-2)


def check_transpose_identities(comm, rank, size):
    """Reference test_allreduce.py:105-138: linear_transpose of
    allreduce-SUM is identity-shaped, and the double transpose equals the
    original allreduce.  Same for the sendrecv ring (source/dest swap)."""
    x = jnp.arange(4, dtype=jnp.float32) + rank

    def ar(v):
        return m4j.allreduce(v, op=m4j.SUM, comm=comm)

    (xt,) = jax.linear_transpose(ar, x)(jnp.ones((4,), jnp.float32))
    np.testing.assert_allclose(np.asarray(xt), 1.0)

    def double_t(v):
        def t1(u):
            return jax.linear_transpose(ar, x)(u)[0]

        return jax.linear_transpose(t1, jnp.ones((4,), jnp.float32))(v)[0]

    np.testing.assert_allclose(
        np.asarray(double_t(x)), np.asarray(ar(x)), rtol=1e-6)

    def ring(v):
        return m4j.sendrecv(v, shift=1, comm=comm)

    # transpose of shift +1 routes the cotangent back along shift -1:
    # transposing twice restores the original routing
    def ring_double_t(v):
        def t1(u):
            return jax.linear_transpose(ring, x)(u)[0]

        return jax.linear_transpose(t1, x)(v)[0]

    np.testing.assert_allclose(
        np.asarray(ring_double_t(x)), np.asarray(ring(x)), rtol=1e-6)

    # grad through allreduce (SUM-only autodiff, reference
    # allreduce.py:188-218: the transpose lowers to *identity*, so the
    # cotangent passes through unreduced) and through the ring
    g = jax.grad(lambda v: ar(v).sum())(x)
    np.testing.assert_allclose(np.asarray(g), 1.0)
    g = jax.grad(lambda v: (ring(v) * (rank + 1.0)).sum())(x)
    np.testing.assert_allclose(np.asarray(g), float((rank + 1) % size + 1))

    # jvp through allreduce
    _, tang = jax.jvp(ar, (x,), (jnp.ones_like(x),))
    np.testing.assert_allclose(np.asarray(tang), float(size))


def check_vmap(comm, rank, size):
    xb = jnp.stack([jnp.arange(4, dtype=jnp.float32) + rank,
                    jnp.full((4,), float(rank))])

    out = jax.vmap(lambda v: m4j.allreduce(v, op=m4j.SUM, comm=comm))(xb)
    np.testing.assert_allclose(
        np.asarray(out)[0], np.arange(4) * size + sum(range(size)))
    np.testing.assert_allclose(np.asarray(out)[1], sum(range(size)))

    out = jax.vmap(lambda v: m4j.allgather(v, comm=comm))(xb)
    assert out.shape == (2, size, 4)
    for r in range(size):
        np.testing.assert_allclose(np.asarray(out)[1, r], float(r))

    out = jax.vmap(lambda v: m4j.sendrecv(v, shift=1, comm=comm))(xb)
    np.testing.assert_allclose(
        np.asarray(out)[1], float((rank - 1) % size))


def check_custom_op(comm, rank, size):
    """User-defined reduction (MPI_Op_create analog) on the world tier:
    composed from allgather + a local fold."""
    absmax = m4j.custom_op(
        "ABSMAX_W", lambda a, b: jnp.maximum(jnp.abs(a), jnp.abs(b)))
    x = jnp.asarray([float(rank) - 1.5, -float(rank)], jnp.float32)
    out = m4j.allreduce(x, op=absmax, comm=comm)
    expect = np.max(np.abs(np.asarray(
        [[r - 1.5, -r] for r in range(size)], np.float32)), axis=0)
    np.testing.assert_allclose(np.asarray(out), expect)

    red = m4j.reduce(x, op=absmax, root=0, comm=comm)
    if rank == 0:
        np.testing.assert_allclose(np.asarray(red), expect)
    else:
        np.testing.assert_allclose(np.asarray(red), np.asarray(x))

    sc = m4j.scan(x, op=absmax, comm=comm)
    raw = np.asarray([[r - 1.5, -r] for r in range(size)], np.float32)
    want = raw[0]
    for r in range(1, rank + 1):
        want = np.maximum(np.abs(want), np.abs(raw[r]))
    np.testing.assert_allclose(np.asarray(sc), want)


def main():
    comm = m4j.get_default_comm()
    rank, size = comm.rank(), comm.size()
    assert size >= 2, "run under the launcher with -n >= 2"

    # int8-compressed allreduce over the native transport (~1e-2 rel err)
    xq = jnp.linspace(-3.0, 5.0, 257, dtype=jnp.float32) * (rank + 1)
    outq = m4j.allreduce(xq, op=m4j.SUM, compression="int8", comm=comm)
    expectq = np.linspace(-3.0, 5.0, 257) * sum(r + 1 for r in range(size))
    np.testing.assert_allclose(np.asarray(outq), expectq, rtol=5e-2,
                               atol=0.2)

    check_custom_op(comm, rank, size)
    check_allreduce_dtypes(comm, rank, size)
    check_movement_dtypes(comm, rank, size)
    check_transpose_identities(comm, rank, size)
    check_vmap(comm, rank, size)

    # everything again under one jit (effects thread through one program)
    def prog(v):
        a = m4j.allreduce(v, op=m4j.SUM, comm=comm)
        b = m4j.sendrecv(a, shift=1, comm=comm)
        c = m4j.allgather(b, comm=comm)
        return c.sum()

    x = jnp.arange(4, dtype=jnp.float32) + rank
    got = jax.jit(prog)(x)
    expect = (np.arange(4) * size + sum(range(size))).sum() * size
    np.testing.assert_allclose(float(got), expect)

    print(f"rank {rank}: full_ops OK", flush=True)


if __name__ == "__main__":
    main()
