"""Coalescing-equivalence program: bursts of adjacent small sends.

Every rank sends K small messages (mixed sizes, distinct tags) to every
peer back to back — exactly the adjacent-in-posted-order shape the
async progress engine coalesces into single wire frames — then receives
the matching K from every peer in deterministic order and digests every
received byte.  The printed digest must be BIT-IDENTICAL with
coalescing on or off (the receive side splits container frames
transparently: tags, sizes, and per-channel order preserved), and the
schedule must verify clean under ``python -m mpi4jax_tpu.analyze``
unchanged (buffered small sends are already the match model's
semantics).

The send bursts are also the deterministic substrate for the
fault-at-a-coalesced-boundary test: ``MPI4JAX_TPU_FAULT=rank=0,
point=send,after=N,...`` lands on the N-th LOGICAL send regardless of
how many of them the engine merged into one frame.
"""

import hashlib
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4j

K = 24                      # messages per directed pair, per round
SIZES = (3, 17, 64, 251)    # odd sizes exercise sub-frame parsing


def main():
    comm = m4j.get_default_comm()
    rank, size = comm.rank(), comm.size()
    assert size >= 2, "run under the launcher with -n >= 2"

    def send_burst(peer, round_):
        # K adjacent small sends to one peer — the coalescing window
        for i in range(K):
            n = SIZES[i % len(SIZES)]
            payload = jnp.arange(n, dtype=jnp.int32) + (
                10000 * rank + 100 * round_ + i)
            m4j.send(payload, dest=peer, tag=1000 * round_ + i, comm=comm)

    def recv_burst(peer, round_, digest):
        for i in range(K):
            n = SIZES[i % len(SIZES)]
            got = m4j.recv(jnp.zeros(n, jnp.int32), source=peer,
                           tag=1000 * round_ + i, comm=comm)
            expect = np.arange(n, dtype=np.int32) + (
                10000 * peer + 100 * round_ + i)
            np.testing.assert_array_equal(np.asarray(got), expect)
            digest.update(np.asarray(got).tobytes())

    digest = hashlib.sha256()
    for round_ in range(3):
        # chain topology: raw send/recv traffic flows strictly DOWN the
        # rank order (r -> r+1), so no rank pair ever exchanges raw
        # messages in both directions — the analyzer's conservative
        # order_critical_exchange pass proves the schedule clean
        # without leaning on send buffering.  Bidirectional flow rides
        # the reorder-safe combined op (sendrecv ring) below.
        if rank + 1 < size:
            send_burst(rank + 1, round_)
        if rank > 0:
            recv_burst(rank - 1, round_, digest)
        ring = m4j.sendrecv(
            jnp.full(16, float(10 * rank + round_), jnp.float32),
            shift=1, comm=comm)
        np.testing.assert_allclose(
            np.asarray(ring),
            float(10 * ((rank - 1) % size) + round_))
        digest.update(np.asarray(ring).tobytes())
        # a rendezvous collective between rounds: coalesced user frames
        # must never leak into (or past) collective-protocol traffic
        total = m4j.allreduce(jnp.ones(8, jnp.float32), op=m4j.SUM,
                              comm=comm)
        np.testing.assert_allclose(np.asarray(total), float(size))
        digest.update(np.asarray(total).tobytes())

    print(f"coalesce_ops digest r{rank} {digest.hexdigest()}", flush=True)
    print("coalesce_ops OK", flush=True)


if __name__ == "__main__":
    main()
