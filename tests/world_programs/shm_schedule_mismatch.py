"""Shm-arena schedule cross-check: divergent collectives must fail fast.

Rank 0 calls allreduce while rank 1 calls bcast at the same program
position — on the TCP tier this surfaces as a frame mismatch; on the
shm arena the per-rank opword check must turn it into an immediate
"collective schedule mismatch" abort instead of silent corruption or a
barrier-timeout hang.
"""

import os
import sys

sys.path.insert(
    0,
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
)

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

import mpi4jax_tpu as m4j  # noqa: E402

comm = m4j.get_default_comm()
rank = comm.rank()

x = jnp.arange(32.0)
# a matched warm-up proves the arena works before the divergence
out = m4j.allreduce(x, op=m4j.SUM, comm=comm)
assert float(out[1]) == 2.0, out[1]
print(f"warmup ok r{rank}", flush=True)

mode = os.environ.get("MISMATCH_MODE", "opcode")
if mode == "opcode":
    # different collectives at the same program position
    if rank == 0:
        m4j.allreduce(x, op=m4j.SUM, comm=comm)
    else:
        m4j.bcast(x, root=1, comm=comm)
elif mode == "reduce_op":
    # same collective, same bytes, divergent reduce op (SUM vs MAX):
    # caught only because the opword carries the op code (ADVICE r4 low)
    m4j.allreduce(x, op=m4j.SUM if rank == 0 else m4j.MAX, comm=comm)
else:  # dtype: equal byte counts, different element type
    if rank == 0:
        m4j.allreduce(x, op=m4j.SUM, comm=comm)
    else:
        m4j.allreduce(jnp.arange(32, dtype=jnp.int32), op=m4j.SUM,
                      comm=comm)
print("UNREACHABLE", flush=True)
