"""Autodiff parity on the world tier: grad / jvp / linear_transpose /
double-transpose through allreduce(SUM), and transpose-swaps-direction for
sendrecv (reference contracts: allreduce.py:188-218, sendrecv.py:390-409)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4j


def main():
    comm = m4j.get_default_comm()
    rank, size = comm.rank(), comm.size()
    x = jnp.arange(3, dtype=jnp.float32) + 1.0

    f = lambda v: m4j.allreduce(v, op=m4j.SUM, comm=comm)

    # jvp: tangent allreduces along
    y, ty = jax.jvp(f, (x,), (jnp.ones_like(x),))
    np.testing.assert_allclose(np.asarray(y), (np.arange(3) + 1) * size)
    np.testing.assert_allclose(np.asarray(ty), float(size))

    # grad through a scalar loss
    g = jax.grad(lambda v: f(v).sum())(x)
    np.testing.assert_allclose(np.asarray(g), 1.0)

    # linear_transpose: identity per rank (replicated cotangent)
    (ct,) = jax.linear_transpose(f, x)(jnp.ones_like(x))
    np.testing.assert_allclose(np.asarray(ct), 1.0)

    # double transpose == allreduce
    def t1(u):
        return jax.linear_transpose(f, x)(u)[0]

    (dt,) = jax.linear_transpose(t1, jnp.ones_like(x))(x)
    np.testing.assert_allclose(np.asarray(dt), np.asarray(f(x)))

    # sendrecv transpose swaps direction: ring shift +1 transposes to -1
    sr = lambda v: m4j.sendrecv(v, shift=1, comm=comm)
    mine = jnp.asarray([float(rank)])
    (ct,) = jax.linear_transpose(sr, mine)(mine)
    np.testing.assert_allclose(np.asarray(ct), [(rank + 1) % size])

    # jvp through sendrecv (improvement over reference, which raises)
    _, tsr = jax.jvp(sr, (mine,), (mine * 2,))
    np.testing.assert_allclose(np.asarray(tsr), [2.0 * ((rank - 1) % size)])

    # grad through sendrecv composed with allreduce (matvec-like pattern)
    def loss(v):
        moved = m4j.sendrecv(v, shift=1, comm=comm)
        return m4j.allreduce((moved * v).sum(), op=m4j.SUM, comm=comm)

    g = jax.grad(loss)(mine)
    # d/dv_r [sum_s v_{s-1} v_s] = v_{r-1} + v_{r+1}
    np.testing.assert_allclose(
        np.asarray(g), [float((rank - 1) % size + (rank + 1) % size)]
    )

    print(f"rank {rank}: autodiff OK", flush=True)


if __name__ == "__main__":
    main()
