"""neighbor_exchange: rings, chains (walls), eager + jit, np=3.

A 3-ring is the smallest topology where a naive per-neighbor pairing of
the two directions deadlocks — this program is the regression for the
one-op schedule.
"""

import os
import sys

sys.path.insert(
    0,
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
)

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import mpi4jax_tpu as m4j  # noqa: E402

comm = m4j.get_default_comm()
rank, size = comm.rank(), comm.size()
assert size == 3

strip = jnp.full((4,), float(rank), jnp.float32)

# periodic ring, eager
lo, hi = (rank - 1) % size, (rank + 1) % size
f_lo, f_hi = m4j.neighbor_exchange(strip, strip + 100, lo=lo, hi=hi,
                                   comm=comm)
# from_lo = lo's to_hi; from_hi = hi's to_lo
np.testing.assert_allclose(np.asarray(f_lo), lo + 100.0)
np.testing.assert_allclose(np.asarray(f_hi), float(hi))

# chain with walls, inside jit
lo_w = rank - 1 if rank > 0 else None
hi_w = rank + 1 if rank < size - 1 else None


@jax.jit
def step(s):
    a, b = m4j.neighbor_exchange(s, s * 2, lo=lo_w, hi=hi_w, comm=comm)
    return a + b


out = np.asarray(step(strip))
want_lo = 2.0 * (rank - 1) if rank > 0 else 2.0 * rank  # wall passthrough
want_hi = float(rank + 1) if rank < size - 1 else float(rank)
np.testing.assert_allclose(out, want_lo + want_hi)

# explicit-token route (unordered mode)
with m4j.explicit_token_ordering():

    @jax.jit
    def tstep(s):
        token = m4j.create_token(s)
        (a, b), token = m4j.neighbor_exchange(
            s, s + 10, lo=lo, hi=hi, comm=comm, token=token)
        return a + b


    tout = np.asarray(tstep(strip))
    np.testing.assert_allclose(tout, (lo + 10.0) + hi)

print(f"neighbor_ops OK r{rank}", flush=True)
