"""Sub-communicators over the native transport: split/dup (the analog of
the reference's arbitrary-mpi4py-comm support — Split()/Clone(),
comm.py:4-11 + docs/sharp-bits.rst:82-143 there).

Run with -n 4: a 2x2 rank grid, row and column communicators, reductions
and point-to-point inside each, plus dup isolation and opt-out colors.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4j


def main():
    world = m4j.get_default_comm()
    rank, size = world.rank(), world.size()
    assert size == 4, "run with -n 4"

    row_id, col_id = divmod(rank, 2)

    # row communicators: {0,1} and {2,3}
    row = world.split(color=row_id)
    assert row.size() == 2 and row.rank() == col_id, (row, rank)

    # column communicators: {0,2} and {1,3}
    col = world.split(color=col_id)
    assert col.size() == 2 and col.rank() == row_id, (col, rank)

    x = jnp.float32(rank)

    # row-wise sum: ranks (0,1) -> 1, ranks (2,3) -> 5
    got = m4j.allreduce(x, op=m4j.SUM, comm=row)
    assert float(got) == [1.0, 1.0, 5.0, 5.0][rank], (rank, float(got))

    # column-wise sum under jit: (0,2) -> 2, (1,3) -> 4
    got = jax.jit(lambda v: m4j.allreduce(v, op=m4j.SUM, comm=col))(x)
    assert float(got) == [2.0, 4.0, 2.0, 4.0][rank], (rank, float(got))

    # point-to-point within a row: exchange with the row partner
    other = 1 - row.rank()
    res = m4j.sendrecv(
        jnp.full((2,), float(rank)), source=other, dest=other, comm=row
    )
    partner_world_rank = row_id * 2 + other
    np.testing.assert_allclose(np.asarray(res), float(partner_world_rank))

    # allgather on the column comm: stacking order follows sub-rank
    ag = m4j.allgather(x, comm=col)
    np.testing.assert_allclose(
        np.asarray(ag), [float(col_id), float(col_id + 2)]
    )

    # dup: same membership, isolated message space, world results match
    wdup = world.dup()
    assert wdup.size() == size and wdup.rank() == rank
    got = m4j.allreduce(x, op=m4j.SUM, comm=wdup)
    assert float(got) == 6.0, float(got)

    # interleave parent and child comms in one jit program: ordered
    # effects serialize them identically on every rank
    def mixed(v):
        a = m4j.allreduce(v, op=m4j.SUM, comm=row)
        b = m4j.allreduce(a, op=m4j.SUM, comm=world)
        c = m4j.allreduce(b, op=m4j.MAX, comm=col)
        return c

    got = jax.jit(mixed)(x)
    # row sums (1,1,5,5) -> world sum = 12 everywhere -> max = 12
    assert float(got) == 12.0, float(got)

    # key reverses the sub-rank order
    rev = world.split(color=row_id, key=-rank)
    assert rev.rank() == 1 - col_id, (rev, rank)

    # opt-out color: odd ranks get no comm; even ranks form a pair.
    # (Collective: every rank calls split once, at the same point.)
    sub = world.split(color=0 if rank % 2 == 0 else -1)
    if rank % 2:
        assert sub is None, sub
    else:
        assert sub.size() == 2 and sub.rank() == rank // 2, (sub, rank)
        got = m4j.allreduce(x, op=m4j.SUM, comm=sub)
        assert float(got) == 2.0, float(got)

    # distinct sub-comms never collide in the jit cache: same shapes,
    # different comms, different results (hash/eq carry the lineage)
    f = jax.jit(lambda v, c: m4j.allreduce(v, op=m4j.SUM, comm=c),
                static_argnums=1)
    assert float(f(x, row)) == [1.0, 1.0, 5.0, 5.0][rank]
    assert float(f(x, col)) == [2.0, 4.0, 2.0, 4.0][rank]

    print(f"subcomm_ops OK (rank {rank})")


if __name__ == "__main__":
    main()
