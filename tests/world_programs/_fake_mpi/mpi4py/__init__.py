"""Simulated mpi4py package for testing WorldComm.from_mpi without an
MPI installation (none ships in this environment).

Implements the minimal bootstrap surface ``from_mpi`` touches —
``Get_rank``/``Get_size``/``allgather``/``bcast``/``Split`` — with the
collectives exchanged through a shared filesystem rendezvous directory
(env ``FAKE_MPI_DIR``), the way a real harness would use PMI.  Data
correctness of the framework's ops is NOT provided by this shim; it only
lets separate OS processes agree on ranks/hosts/ports, which is all
``from_mpi`` uses mpi4py for.
"""

from . import MPI  # noqa: F401
