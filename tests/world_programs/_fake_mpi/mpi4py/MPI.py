"""File-rendezvous fake of the mpi4py.MPI surface from_mpi bootstraps on."""

import json
import os
import pathlib
import time

_DIR = pathlib.Path(os.environ["FAKE_MPI_DIR"])
_RANK = int(os.environ["FAKE_MPI_RANK"])
_SIZE = int(os.environ["FAKE_MPI_SIZE"])
_TIMEOUT_S = 60.0


class Comm:
    def __init__(self, members, my_index, tag):
        self._members = members  # global ranks, in comm order
        self._idx = my_index
        self._tag = tag
        self._seq = 0

    def Get_rank(self):
        return self._idx

    def Get_size(self):
        return len(self._members)

    def _exchange(self, payload):
        """Allgather ``payload`` (JSON-able) across the comm's members."""
        self._seq += 1
        base = f"{self._tag}_{self._seq}"
        me = _DIR / f"{base}.r{self._members[self._idx]}"
        tmp = me.with_suffix(me.suffix + ".tmp")
        tmp.write_text(json.dumps(payload))
        tmp.rename(me)  # atomic publish
        out = []
        deadline = time.time() + _TIMEOUT_S
        for g in self._members:
            f = _DIR / f"{base}.r{g}"
            while not f.exists():
                if time.time() > deadline:
                    raise TimeoutError(f"fake MPI: waiting for {f}")
                time.sleep(0.01)
            # publish is atomic (rename), so a visible file is complete
            out.append(json.loads(f.read_text()))
        return out

    def allgather(self, x):
        return self._exchange(x)

    def bcast(self, x, root=0):
        return self._exchange(x if self._idx == root else None)[root]

    def Split(self, color, key=0):
        rows = self._exchange([color, key, self._members[self._idx]])
        mine = sorted(
            (k, g) for c, k, g in rows if c == color
        )
        members = [g for _, g in mine]
        idx = members.index(self._members[self._idx])
        return Comm(members, idx, f"{self._tag}s{self._seq}c{color}")


COMM_WORLD = Comm(list(range(_SIZE)), _RANK, "w")
