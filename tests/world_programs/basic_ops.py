"""Per-rank correctness program for world-tier ops (run under the launcher).

Mirrors the reference's per-op identity tests (SURVEY.md §4.2) in the
one-process-per-rank execution model.  Any assertion failure exits nonzero,
which the launcher converts into a failed job.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4j


def main():
    comm = m4j.get_default_comm()
    rank, size = comm.rank(), comm.size()
    assert size >= 2, "run under the launcher with -n >= 2"

    x = jnp.arange(4, dtype=jnp.float32) + rank

    # allreduce: eager + jit
    expected_sum = np.arange(4) * size + sum(range(size))
    out = m4j.allreduce(x, op=m4j.SUM, comm=comm)
    np.testing.assert_allclose(np.asarray(out), expected_sum)
    out = jax.jit(lambda v: m4j.allreduce(v, op=m4j.SUM, comm=comm))(x)
    np.testing.assert_allclose(np.asarray(out), expected_sum)
    # input not mutated
    np.testing.assert_allclose(np.asarray(x), np.arange(4) + rank)

    out = m4j.allreduce(x, op=m4j.MAX, comm=comm)
    np.testing.assert_allclose(np.asarray(out), np.arange(4) + size - 1)

    # allgather
    ag = m4j.allgather(x, comm=comm)
    assert ag.shape == (size, 4)
    for r in range(size):
        np.testing.assert_allclose(np.asarray(ag)[r], np.arange(4) + r)

    # alltoall: row j -> rank j
    a2a_in = jnp.asarray(
        [[100 * rank + j] for j in range(size)], dtype=jnp.int32
    )
    a2a = m4j.alltoall(a2a_in, comm=comm)
    np.testing.assert_array_equal(
        np.asarray(a2a).ravel(), [100 * r + rank for r in range(size)]
    )

    # bcast
    b = jnp.full((3,), float(rank), jnp.float32)
    b = m4j.bcast(b, root=1, comm=comm)
    np.testing.assert_allclose(np.asarray(b), 1.0)

    # reduce: root gets reduction, others passthrough
    red = m4j.reduce(x, op=m4j.SUM, root=0, comm=comm)
    if rank == 0:
        np.testing.assert_allclose(np.asarray(red), expected_sum)
    else:
        np.testing.assert_allclose(np.asarray(red), np.asarray(x))

    # scan (inclusive prefix)
    sc = m4j.scan(jnp.asarray([float(rank + 1)]), op=m4j.SUM, comm=comm)
    np.testing.assert_allclose(
        np.asarray(sc), [sum(range(1, rank + 2))]
    )

    # gather / scatter (rank-dependent gather output: root stacks,
    # non-root gets its input back — reference gather.py:213-226)
    g = m4j.gather(x, root=0, comm=comm)
    if rank == 0:
        assert g.shape == (size, 4), g.shape
        for r in range(size):
            np.testing.assert_allclose(np.asarray(g)[r], np.arange(4) + r)
    else:
        assert g.shape == (4,), g.shape
        np.testing.assert_allclose(np.asarray(g), np.asarray(x))
    sc_in = jnp.tile(jnp.arange(size, dtype=jnp.float32)[:, None], (1, 2))
    mine = m4j.scatter(sc_in, root=0, comm=comm)
    np.testing.assert_allclose(np.asarray(mine), float(rank))

    # barrier
    m4j.barrier(comm=comm)

    # sendrecv ring (jit)
    ring = jax.jit(
        lambda v: m4j.sendrecv(v, shift=1, comm=comm)
    )(x)
    np.testing.assert_allclose(
        np.asarray(ring), np.arange(4) + (rank - 1) % size
    )

    # send / recv pair (true MPMD — impossible on the mesh tier)
    if rank == 0:
        m4j.send(x * 2, dest=1, comm=comm)
    elif rank == 1:
        got = m4j.recv(jnp.zeros_like(x), source=0, comm=comm)
        np.testing.assert_allclose(np.asarray(got), np.arange(4) * 2.0)

    # ops inside lax control flow (effects must thread through scan)
    def body(carry, _):
        carry = m4j.allreduce(carry, op=m4j.SUM, comm=comm) / size
        return carry, None

    looped, _ = jax.jit(
        lambda v: jax.lax.scan(body, v, None, length=3)
    )(jnp.ones((2,), jnp.float32))
    np.testing.assert_allclose(np.asarray(looped), 1.0, rtol=1e-6)

    print(f"rank {rank}: basic_ops OK", flush=True)


if __name__ == "__main__":
    main()
