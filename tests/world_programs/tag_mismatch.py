"""Protocol-error fail-fast: mismatched tags must abort, not hang.

The transport matches messages strictly in order (ordered effects upstream);
a tag mismatch is a program error reported as an abort — the no-silent-
deadlock contract.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp

import mpi4jax_tpu as m4j


def main():
    comm = m4j.get_default_comm()
    rank = comm.rank()
    x = jnp.arange(3, dtype=jnp.float32)
    if rank == 0:
        m4j.send(x, dest=1, tag=5)
    elif rank == 1:
        m4j.recv(x, source=0, tag=7)  # wrong tag -> transport abort
    print("UNREACHABLE-OK" if rank != 1 else "UNREACHABLE", flush=True)


if __name__ == "__main__":
    main()
