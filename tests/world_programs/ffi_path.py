"""Assert the native XLA FFI fast path is actually used on cpu.

The world tier lowers to typed FFI custom calls (native/tpucomm_ffi.cc)
when available — this program checks the lowered module contains the
``tpucomm_*`` custom-call targets (i.e. no silent fallback to the Python
host-callback path), and that results agree with the closed-form
expectations.  Run with ``MPI4JAX_TPU_DISABLE_FFI=1`` the same program
checks the inverse: callbacks only, same numerics.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4j
from mpi4jax_tpu.utils import config


def main():
    comm = m4j.get_default_comm()
    rank, size = comm.rank(), comm.size()

    def program(v):
        y = m4j.allreduce(v, op=m4j.SUM, comm=comm)
        y = m4j.bcast(y, root=0, comm=comm)
        y = m4j.sendrecv(y, shift=1, comm=comm)
        return y

    lowered = jax.jit(program).lower(jnp.ones((4,), jnp.float32))
    text = lowered.as_text()
    ffi_on = not config.ffi_disabled()
    for target in ("tpucomm_allreduce", "tpucomm_bcast", "tpucomm_sendrecv"):
        present = target in text
        assert present == ffi_on, (
            f"{target}: expected {'native ffi call' if ffi_on else 'callback'}"
            f" in lowering, got the opposite\n{text[:3000]}"
        )

    x = jnp.arange(4, dtype=jnp.float32) + rank
    out = jax.jit(program)(x)
    expected = np.arange(4) * size + sum(range(size))  # allreduce(SUM)
    np.testing.assert_allclose(np.asarray(out), expected)

    # shape-changing ops through the native decoders
    ag = m4j.allgather(x, comm=comm)
    for r in range(size):
        np.testing.assert_allclose(np.asarray(ag)[r], np.arange(4) + r)
    g = m4j.gather(x, root=0, comm=comm)
    if rank == 0:
        for r in range(size):
            np.testing.assert_allclose(np.asarray(g)[r], np.arange(4) + r)
    else:
        assert g.shape == x.shape, g.shape
        np.testing.assert_allclose(np.asarray(g), np.asarray(x))
    mine = m4j.scatter(
        jnp.tile(jnp.arange(size, dtype=jnp.float32)[:, None], (1, 3)),
        root=0, comm=comm,
    )
    np.testing.assert_allclose(np.asarray(mine), float(rank))
    sc = m4j.scan(jnp.asarray([rank + 1.0]), op=m4j.SUM, comm=comm)
    np.testing.assert_allclose(np.asarray(sc), [sum(range(1, rank + 2))])
    red = m4j.reduce(x, op=m4j.SUM, root=0, comm=comm)
    if rank == 0:
        np.testing.assert_allclose(np.asarray(red), expected)
    m4j.barrier(comm=comm)

    print(f"rank {rank}: ffi_path OK (ffi={'on' if ffi_on else 'off'})",
          flush=True)


if __name__ == "__main__":
    main()
