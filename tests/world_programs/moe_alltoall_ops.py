"""Cross-algorithm equivalence for the alltoall family — the
qalltoall/halltoall/hqalltoall sibling of ``topo_ops.py``, and the
verification spine of the MoE dispatch/combine path (the expert
exchange IS this alltoall).

Run under the launcher with ``MPI4JAX_TPU_FAKE_HOSTS`` partitioning the
ranks into islands (the test drives 2x2 at np=4 and uneven 4+2 at np=6,
shm on and off).  Asserts:

- discovery: the Topology matches the partition and the default
  decision table picks the flat pairwise exchange for alltoall at every
  size (no quant/hier env set);
- forced ring is bit-identical to the AUTO default;
- ``halltoall`` (exact hierarchical) is a pure permutation: bit-identical
  to the flat exchange on every partition — including uneven islands
  and the non-contiguous interleaved one;
- ``qalltoall`` matches ``topo.simulate_qalltoall`` bit-for-bit (the
  destination dequantizes the SENDER's packed bytes, so parity with the
  shared numpy codec IS the rank-consistency proof), keeps the own-rank
  chunk exact, and stays inside the documented int8 error bound of the
  exact exchange;
- ``hqalltoall`` matches ``topo.simulate_hqalltoall`` bit-for-bit
  (intra-island chunks exact; each cross-island block quantized as ONE
  codec frame on the leader leg, 256-element blocks spanning chunk
  boundaries), plus a global allgather cross-check of every rank's
  output against the simulator;
- bf16 payloads ride the f32 staging (upcast exact, RNE store) with the
  same simulator parity; exact paths move the bf16 bits verbatim;
- int32 is codec-ineligible: forced qalltoall/hqalltoall degrade to the
  exact exchange bit-for-bit on every rank;
- ``MPI4JAX_TPU_COLL_QUANT=deny`` degrades qalltoall -> ring and
  hqalltoall -> halltoall (exact bits); ``=force`` upgrades the default
  AND forced-ring paths to qalltoall and halltoall to hqalltoall
  (simulator parity switches accordingly); ``MPI4JAX_TPU_HIER=deny``
  degrades hqalltoall to the flat quantized exchange.

Bridge-level with the parent-package shim (no jax import): runs in ANY
container, like the coalescing bridge programs.
"""

import os
import sys
import types

REPO = os.path.join(os.path.dirname(__file__), "..", "..")
sys.path.insert(0, REPO)
pkg = types.ModuleType("mpi4jax_tpu")
pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
sys.modules["mpi4jax_tpu"] = pkg

import numpy as np  # noqa: E402

from mpi4jax_tpu import topo, tune  # noqa: E402
from mpi4jax_tpu.runtime import bridge, transport  # noqa: E402

# wire codes (native/tpucomm.h)
F32, BF16, I32 = 11, 10, 3


def f32_to_bf16_bits(a32):
    bits = a32.view(np.uint32)
    rounded = bits + np.uint32(0x7FFF) + ((bits >> np.uint32(16))
                                          & np.uint32(1))
    return (rounded >> np.uint32(16)).astype(np.uint16)


def bf16_bits_to_f32(b):
    return (b.astype(np.uint32) << 16).view(np.float32)


def forced(h, x, name, dtype_code=None):
    out = np.empty_like(x)
    bridge.alltoall_raw(h, x, out, algo=tune.ALGO_CODES[name],
                        dtype_code=dtype_code)
    return out


def main():
    comm = transport.get_world_comm()
    rank, size = comm.rank(), comm.size()
    h = comm.handle
    shm_on = os.environ.get("MPI4JAX_TPU_DISABLE_SHM", "") in ("", "0")

    # ---- discovery + default-table assertions ---------------------
    t = comm.topology()
    assert t is not None and t.multi, f"expected a multi-island map, got {t}"
    expect = [int(x) for x in os.environ["TOPO_EXPECT_ISLANDS"].split(",")]
    assert t.island_of == expect, (t.island_of, expect)
    if (not os.environ.get("MPI4JAX_TPU_COLL_ALGO")
            and not os.environ.get("MPI4JAX_TPU_COLL_QUANT")):
        # alltoall's default is the flat pairwise exchange at EVERY
        # size (the quantized/hierarchical twins are opt-in via the
        # tuner cache or a forced algo)
        assert comm.coll_algo("alltoall", 64) == "ring"
        assert comm.coll_algo("alltoall", 16 << 20) == "ring"

    qmode = os.environ.get("MPI4JAX_TPU_COLL_QUANT", "allow").strip()
    qdeny, qforce = qmode == "deny", qmode == "force"
    hdeny = os.environ.get("MPI4JAX_TPU_HIER", "allow").strip() == "deny"
    islands = t.islands

    rng = np.random.RandomState(11)
    for count in (3, 513, 20000):  # < codec block, odd multi-block, 80KB
        # every rank derives the same base from the shared seed:
        # base[r] is rank r's (size, count) send matrix
        base_f = (rng.randn(size, size, count) * 3).astype(np.float32)
        base_i = rng.randint(-900, 900,
                             size=(size, size, count)).astype(np.int32)
        bf_bits = f32_to_bf16_bits(base_f)
        inputs_f = [base_f[r] for r in range(size)]
        inputs_b = [bf16_bits_to_f32(bf_bits[r]) for r in range(size)]

        sim_h = topo.simulate_halltoall(inputs_f)  # == flat exact
        sim_q = topo.simulate_qalltoall(inputs_f)
        sim_hq = topo.simulate_hqalltoall(inputs_f, islands)

        # ---- f32 -------------------------------------------------
        x = base_f[rank].copy()
        ref = bridge.alltoall(h, x)
        ring = forced(h, x, "ring")
        assert np.array_equal(ring, ref), (
            f"count={count}: forced ring != AUTO default")
        # under COLL_QUANT=force the default (and forced ring) ride
        # the quantized wire; anywhere else the flat exchange is exact
        want_ref = sim_q[rank] if qforce else sim_h[rank]
        assert np.array_equal(ref, want_ref), (
            f"count={count} qforce={qforce}: default path diverges from "
            f"the simulator (maxdiff {np.max(np.abs(ref - want_ref))})")

        out = forced(h, x, "qalltoall")
        if qdeny:
            assert np.array_equal(out, sim_h[rank]), (
                f"count={count}: denied qalltoall is not the exact ring")
        else:
            assert np.array_equal(out, sim_q[rank]), (
                f"count={count}: qalltoall diverges from the simulator "
                f"(maxdiff {np.max(np.abs(out - sim_q[rank]))})")
            assert np.array_equal(out[rank], x[rank]), (
                "qalltoall own chunk must stay exact")
            denom = max(float(np.max(np.abs(sim_h[rank]))), 1e-6)
            err = float(np.max(np.abs(out - sim_h[rank]))) / denom
            assert err < 5e-2, f"qalltoall rel err {err:.2e}"

        out = forced(h, x, "halltoall")
        # exact hierarchical = pure permutation: bit-identical to flat
        # under allow AND deny (the degrade target moves the same
        # bytes); quant force upgrades it to the quantized-leader twin
        want = sim_hq[rank] if (qforce and not hdeny) else sim_h[rank]
        assert np.array_equal(out, want), (
            f"count={count} qforce={qforce}: halltoall diverges "
            f"(maxdiff {np.max(np.abs(out - want))})")

        out = forced(h, x, "hqalltoall")
        if qdeny:
            want, label = sim_h[rank], "halltoall (exact)"
        elif hdeny:
            want, label = sim_q[rank], "flat qalltoall"
        else:
            want, label = sim_hq[rank], "the hqalltoall simulator"
        assert np.array_equal(out, want), (
            f"count={count}: hqalltoall should match {label} "
            f"(maxdiff {np.max(np.abs(out - want))})")
        if not (qdeny or hdeny):
            for s in t.island(rank):
                assert np.array_equal(out[s], base_f[s][rank]), (
                    "hqalltoall intra-island chunk must stay exact")
            # global consistency: every rank's output must be the
            # simulator's row for that rank (the leader quantizes each
            # cross block once; everyone dequantizes the same bytes)
            rows = bridge.allgather(h, out.reshape(-1).copy(), size)
            for r in range(size):
                assert np.array_equal(rows[r], sim_hq[r].reshape(-1)), (
                    f"count={count}: rank {r}'s hqalltoall output "
                    "disagrees with the shared simulator")

        # ---- bf16 (f32 staging: upcast exact, RNE store) ---------
        xb = bf_bits[rank].copy()
        outb = forced(h, xb, "qalltoall", dtype_code=BF16)
        if qdeny:
            assert np.array_equal(outb, bf_bits[:, rank]), (
                "denied bf16 qalltoall must move the bits verbatim")
        else:
            want_bits = f32_to_bf16_bits(
                topo.simulate_qalltoall(inputs_b)[rank])
            assert np.array_equal(outb, want_bits), (
                f"count={count}: bf16 qalltoall diverges from the "
                "simulator (RNE staging contract)")
        outb = forced(h, xb, "hqalltoall", dtype_code=BF16)
        if qdeny:
            want_bits = bf_bits[:, rank]
        elif hdeny:
            want_bits = f32_to_bf16_bits(
                topo.simulate_qalltoall(inputs_b)[rank])
        else:
            want_bits = f32_to_bf16_bits(
                topo.simulate_hqalltoall(inputs_b, islands)[rank])
        assert np.array_equal(outb, want_bits), (
            f"count={count}: bf16 hqalltoall diverges")
        if not qforce:
            outb = forced(h, xb, "halltoall", dtype_code=BF16)
            assert np.array_equal(outb, bf_bits[:, rank]), (
                "bf16 halltoall must move the bits verbatim")

        # ---- int32: codec-ineligible, degrades to exact ----------
        xi = base_i[rank].copy()
        refi = bridge.alltoall(h, xi)
        assert np.array_equal(refi, base_i[:, rank]), "i32 flat exchange"
        for name in ("qalltoall", "halltoall", "hqalltoall"):
            outi = forced(h, xi, name)
            assert np.array_equal(outi, refi), (
                f"i32 {name} must degrade to the exact exchange")

    print(f"moe_alltoall_ops OK (shm={int(shm_on)})", flush=True)


if __name__ == "__main__":
    main()
