"""Strict-ordering torture tests (the reference's core value proposition).

1. "Hot potato" (modeled on the reference's notoken ordering test,
   tests/experimental/test_notoken.py:81-120 there): an asymmetric
   send/recv script between two ranks whose numeric result is wrong under
   ANY reordering of the communication calls.
2. Deadlock-by-construction: send-then-recv on rank 0 vs recv-then-send on
   rank 1 — only correct if program order is execution order
   (test_send_and_recv.py:96-115 there).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4j


def main():
    comm = m4j.get_default_comm()
    rank, size = comm.rank(), comm.size()
    assert size >= 2

    zero = jnp.zeros((1,), jnp.float32)

    # --- hot potato: value accumulates operations in strict sequence ----
    @jax.jit
    def potato_rank0(v):
        # send v, get back 3v+1, send 2*(3v+1), get back final
        m4j.send(v, dest=1, comm=comm)
        v1 = m4j.recv(zero, source=1, comm=comm)
        m4j.send(v1 * 2.0, dest=1, comm=comm)
        v2 = m4j.recv(zero, source=1, comm=comm)
        return v2

    @jax.jit
    def potato_rank1():
        a = m4j.recv(zero, source=0, comm=comm)
        m4j.send(a * 3.0 + 1.0, dest=0, comm=comm)
        b = m4j.recv(zero, source=0, comm=comm)
        m4j.send(b - 5.0, dest=0, comm=comm)
        return b

    if rank == 0:
        out = potato_rank0(jnp.asarray([7.0]))
        # ((7*3+1)*2) - 5 = 39
        np.testing.assert_allclose(np.asarray(out), [39.0])
    elif rank == 1:
        potato_rank1()

    # --- deadlock-by-construction ordering ------------------------------
    if rank == 0:
        m4j.send(jnp.asarray([13.0]), dest=1, comm=comm)
        got = m4j.recv(zero, source=1, comm=comm)
        np.testing.assert_allclose(np.asarray(got), [17.0])
    elif rank == 1:
        got = m4j.recv(zero, source=0, comm=comm)
        np.testing.assert_allclose(np.asarray(got), [13.0])
        m4j.send(jnp.asarray([17.0]), dest=0, comm=comm)

    # --- ordering across nested jits ------------------------------------
    @jax.jit
    def inner(v):
        return m4j.allreduce(v, op=m4j.SUM, comm=comm)

    @jax.jit
    def outer(v):
        a = inner(v)
        b = m4j.allreduce(a, op=m4j.MAX, comm=comm)
        return inner(b)

    out = outer(jnp.asarray([1.0]))
    np.testing.assert_allclose(np.asarray(out), [float(size * size)])

    print(f"rank {rank}: ordering OK", flush=True)


if __name__ == "__main__":
    main()
