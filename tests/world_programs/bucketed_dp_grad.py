"""Bucketed DP gradient synchronization: the plan's bucket marks, live.

A small deep-ish parameter pytree (many small same-dtype leaves — the
shape that drowns in per-op latency) syncs its "gradients" twice: once
per-leaf (the historic schedule) and once bucketed
(``dp.sync_gradients(bucket_bytes=...)``, the fusion the schedule
compiler's ``bucket`` marks describe).  SUM over a concatenation is
elementwise, so both must be BIT-identical — asserted here on every
rank.  The per-leaf section also gives the analyzer the adjacent small
allreduce run its plan marks as a bucket.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4j
from mpi4jax_tpu.parallel import dp

N_LAYERS = 6
LEAF = 512  # f32: 2 KB per leaf — bucketable


def main():
    comm = m4j.get_default_comm()
    rank, size = comm.rank(), comm.size()
    assert size >= 2, "run under the launcher with -n >= 2"

    grads = {
        f"layer{i}": {
            "w": jnp.full((LEAF,), float(rank + i), jnp.float32),
            "b": jnp.arange(LEAF, dtype=jnp.float32) * (rank - i),
        }
        for i in range(N_LAYERS)
    }

    per_leaf = dp.sync_gradients(grads, comm=comm, bucket_bytes=0)
    bucketed = dp.sync_gradients(grads, comm=comm,
                                 bucket_bytes=64 * 1024)

    flat_a = jax.tree.leaves(per_leaf)
    flat_b = jax.tree.leaves(bucketed)
    assert len(flat_a) == len(flat_b) == 2 * N_LAYERS
    for a, b in zip(flat_a, flat_b):
        assert a.shape == b.shape and a.dtype == b.dtype, (a.shape, b.shape)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # spot-check a value against the closed form
    want = np.full((LEAF,), sum(range(size)) / size + 2, np.float32)
    np.testing.assert_allclose(np.asarray(per_leaf["layer2"]["w"]), want)

    print(f"rank {rank}: bucketed_dp_grad OK", flush=True)


if __name__ == "__main__":
    main()
