"""Report the engine's selected collective algorithms from a live comm.

Used by the tuner smoke test: run after ``python -m mpi4jax_tpu.tune``
with ``MPI4JAX_TPU_TUNE_CACHE`` pointing at the written cache, and the
printed picks must match the cache's table — proof the persistent cache
is loaded at comm creation and honored.  Also executes one allreduce so
``MPI4JAX_TPU_DEBUG=1`` runs show the native trace line naming the
algorithm that ran (``Allreduce ... algo <name>``).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from mpi4jax_tpu import tune
from mpi4jax_tpu.runtime import bridge, transport


def main():
    comm = transport.get_world_comm()
    h = comm.handle  # comm creation loads + installs the tune cache
    sizes = [int(s) for s in
             os.environ.get("ALGO_REPORT_SIZES", "1024,16777216").split(",")]
    for nbytes in sizes:
        x = np.ones(max(nbytes // 4, 1), np.float32)
        out = np.empty_like(x)
        bridge.allreduce_raw(h, x, out, 11, 0)  # f32 SUM, engine-selected
        assert np.allclose(out, comm.size())
        print(f"algo_report allreduce@{nbytes}="
              f"{comm.coll_algo('allreduce', nbytes)}", flush=True)
    print(f"algo_report sources={'+'.join(tune.sources())}", flush=True)
    print("algo_report OK", flush=True)


if __name__ == "__main__":
    main()
