"""Frontend-death regression (bridge level, parent-package shim).

Rank 0 — the frontend, which owns the request queue — is killed by
fault injection mid-serve.  The failure model says its in-flight state
is unrecoverable, BUT the survivors must find that out in an orderly
way: the worker promoted to rank 0 by the recovery broadcasts STOP
(releasing every other survivor from the bcast it re-entered) BEFORE
raising its "became the frontend" error.  Before that fix the promoted
worker raised immediately and the other survivors hung in a headless
bcast until the transport deadline.

Success markers (the test asserts both, and exit code 0):
    ``fd promoted clean rN``  — the promoted worker raised AFTER release
    ``fd worker done rN``     — every other survivor returned normally

Usage (under the launcher): serve_frontend_death.py [plane]
with plane = ``toy`` (elastic/serving.py) or ``v2``
(mpi4jax_tpu/serving).
"""

import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)
pkg = types.ModuleType("mpi4jax_tpu")
pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
sys.modules["mpi4jax_tpu"] = pkg

import numpy as np  # noqa: E402

from mpi4jax_tpu import serving as serving_v2  # noqa: E402
from mpi4jax_tpu.elastic import serving as serving_toy  # noqa: E402
from mpi4jax_tpu.runtime import transport  # noqa: E402

PLANE = sys.argv[1] if len(sys.argv) > 1 else "toy"


def decode_fn(toks, lengths, start, stop):
    out = np.zeros(stop - start, np.int32)
    for i in range(start, stop):
        n = int(lengths[i])
        row = toks[i, :n].astype(np.int64)
        out[i - start] = int((row.sum() * 31 + n * 7 + int(row[-1])) % 997)
    return out


def run_frontend(comm):
    """Rank 0: serve until the injected fault kills this process (the
    drain should never finish — the fault fires first)."""
    if PLANE == "toy":
        server = serving_toy.Server(comm, decode_fn, max_batch=4)
        for i in range(8):
            server.submit([i + 1, 2 * i + 1], max_new=6)
        server.run_until_drained()
        server.stop()
    else:
        server = serving_v2.Server(comm, serving_v2.ToyAdapter(),
                                   max_batch=4, chunk_tokens=4)
        for i in range(8):
            assert server.submit([i + 1, 2 * i + 1], max_new=6).admitted
        server.run_until_drained()
        server.stop()
    print("fd frontend drained (fault did not fire?)", flush=True)


def run_worker(comm):
    try:
        if PLANE == "toy":
            serving_toy.serve_worker(comm, decode_fn)
        else:
            serving_v2.serve_worker(comm, serving_v2.ToyAdapter())
        print(f"fd worker done r{comm.rank()}", flush=True)
    except RuntimeError as e:
        assert "became the frontend" in str(e), e
        print(f"fd promoted clean r{comm.rank()}", flush=True)


def main():
    comm = transport.get_world_comm()
    _ = comm.handle
    if comm.rank() == 0:
        run_frontend(comm)
    else:
        run_worker(comm)


if __name__ == "__main__":
    main()
