"""Deterministic traffic for the self-healing link tests.

Bridge-level rank program (no jax import) driven by
``tests/world/test_self_healing.py``: phases of point-to-point
pingpong — where an injected transient fault (``MPI4JAX_TPU_FAULT``)
lands deterministically and the armed link layer must heal in place —
followed by allreduce rounds proving the healed wire still carries
collectives, a digest over everything received, and the process-total
self-healing counters from ``obs.stats()``.

Unlike ``fault_ops.py`` this program loads the package through the
parent-package shim (the pattern ``runtime/diag.py`` established), so
the self-healing tests run even where the package's jax version gate
blocks the full import — the paths under test live entirely in the
native transport and the stdlib-importable obs package.

Env:
    HEAL_OPS_N        payload element count (float64; default 256)
    HEAL_OPS_ROUNDS   pingpong rounds, then the same number of
                      allreduce rounds (default 12)
    HEAL_OPS_SLEEP_S  idle window between the phases (default 0) —
                      the heartbeat test parks the wire here so the
                      progress thread, not an op, finds the dead link
    HEAL_OPS_LIVE_SWAP  when "1" (and the live plane is armed via
                      MPI4JAX_TPU_LIVE=auto), rank 0 proposes a table
                      swap early in phase 2 so the epoch rendezvous
                      lands WHILE the link layer is healing the
                      injected fault — the chaos matrix's swap-during-
                      reconnect cell.  np=2 float64 SUM is a single
                      addition under every algorithm, so the digest
                      contract is unchanged: a swap that altered
                      results would be a dispatch bug
"""

import os
import sys
import types

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
sys.path.insert(0, REPO)
pkg = types.ModuleType("mpi4jax_tpu")
pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
sys.modules["mpi4jax_tpu"] = pkg

import numpy as np  # noqa: E402

from mpi4jax_tpu import obs  # noqa: E402
from mpi4jax_tpu.runtime import bridge, transport  # noqa: E402


def main():
    comm = transport.get_world_comm()
    rank, size = comm.rank(), comm.size()
    assert size == 2, "run under the launcher with -n 2"
    h = comm.handle
    obs.start(lib=bridge.get_lib(), rank=rank, size=size)

    n = int(os.environ.get("HEAL_OPS_N", "256"))
    rounds = int(os.environ.get("HEAL_OPS_ROUNDS", "12"))
    peer = 1 - rank
    x = np.arange(n, dtype=np.float64) + rank
    digest = 0.0

    # phase 1: pingpong — the injected fault lands here (point=send
    # counts transmissions); a mid-frame reset on this traffic is
    # always healable (sent frames <= the retain ceiling are replayed
    # whole, the receiver dedups by seq)
    for it in range(rounds):
        if rank == 0:
            bridge.send(h, x + it, peer, it)
            got = bridge.recv(h, x.shape, x.dtype, peer, it)
        else:
            got = bridge.recv(h, x.shape, x.dtype, peer, it)
            bridge.send(h, x + it, peer, it)
        np.testing.assert_allclose(got, np.arange(n) + peer + it)
        digest += float(got.sum())

    sleep_s = float(os.environ.get("HEAL_OPS_SLEEP_S", "0"))
    if sleep_s > 0:
        import time

        time.sleep(sleep_s)

    live_swap = os.environ.get("HEAL_OPS_LIVE_SWAP", "0") == "1"

    # phase 2: collectives over the healed wire (the one-shot fault
    # has fired by now; these must run exactly as on a fresh link)
    for it in range(rounds):
        out = bridge.allreduce(h, x + it, 0)  # 0 = SUM (tpucomm.h wire code)
        np.testing.assert_allclose(out, (np.arange(n) * 2) + 1 + 2 * it)
        digest += float(out.sum())
        if live_swap and it == 2 and rank == 0:
            from mpi4jax_tpu import live

            if live.armed():
                live.propose({"allreduce": [(0, "rd")]}, note="chaos-swap")

    epoch = 0
    if live_swap:
        from mpi4jax_tpu import live

        epoch = live.status().get("epoch", 0)

    sh = obs.stats().get("self_healing", {})
    # one write() so the two ranks' report lines can't interleave in
    # the launcher's multiplexed stdout
    sys.stdout.write(
        "heal_ops %d digest %r reconnects %d dup_dropped %d "
        "crc_errors %d replayed %d epoch %d\n"
        % (rank, digest, sh.get("reconnects", 0), sh.get("dup_dropped", 0),
           sh.get("crc_errors", 0), sh.get("replayed", 0), epoch))
    sys.stdout.flush()


if __name__ == "__main__":
    main()
