"""Chain guard: a deliberately broken token chain fails fast at TRACE time.

The composition mode's sharpest bit (inherited from the reference's token
design, docs/sharp-bits.rst:6-34 there): a world op binding a fresh token
while other ops chain theirs has UNDEFINED order and deadlocks at run
time.  With MPI4JAX_TPU_STRICT_TOKENS=1 the trace-time chain guard turns
that into an immediate error — the program must die BEFORE any
communication happens (no deadlock, no timeout).

Run under the launcher at np=2 with MPI4JAX_TPU_STRICT_TOKENS=1.
"""

import os
import sys

sys.path.insert(
    0,
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
)

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

import mpi4jax_tpu as m4j  # noqa: E402
from mpi4jax_tpu.compat import token_api as tk  # noqa: E402

comm = m4j.get_default_comm()
rank, size = comm.rank(), comm.size()

mode = os.environ.get("BROKEN_MODE", "fresh_token")

with m4j.explicit_token_ordering():

    @jax.jit
    def bad(x):
        token = tk.create_token(x)
        if mode == "fresh_token":
            # rank 0 threads its chain; both ranks then bind a SECOND op
            # with a fresh UNROOTED token while the first chain is live
            token = tk.send(x, dest=(rank + 1) % size, tag=7, comm=comm,
                            token=token)
            rogue = tk.create_token()          # <- the bug
            got, _ = tk.recv(jnp.zeros_like(x), source=(rank - 1) % size,
                             tag=7, comm=comm, token=rogue)
        else:  # "no_token": a primary-API (tokenless) op amid a chain
            token = tk.send(x, dest=(rank + 1) % size, tag=7, comm=comm,
                            token=token)
            got = m4j.recv(jnp.zeros_like(x), source=(rank - 1) % size,
                           tag=7, comm=comm)   # <- the bug
        return got

    try:
        bad(jnp.arange(4.0))
    except RuntimeError as err:
        assert "UNDEFINED" in str(err), err
        print(f"broken_chain CAUGHT AT TRACE TIME r{rank}", flush=True)
        sys.exit(0)

print("UNREACHABLE", flush=True)
