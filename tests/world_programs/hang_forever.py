"""A rank that never finishes: the launcher watchdog / signal-teardown
target.  Writes its pid to $HANG_PID_DIR (when set) so tests can prove
no orphan survives the reap; $HANG_IGNORE_SIGINT=1 forces the launcher's
SIGINT grace period to escalate to SIGTERM/SIGKILL."""

import os
import signal
import time

if os.environ.get("HANG_IGNORE_SIGINT"):
    signal.signal(signal.SIGINT, signal.SIG_IGN)

piddir = os.environ.get("HANG_PID_DIR")
if piddir:
    with open(os.path.join(piddir, f"pid_{os.getpid()}"), "w") as f:
        f.write(str(os.getpid()))

time.sleep(600)
