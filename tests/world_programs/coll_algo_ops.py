"""Cross-algorithm equivalence for the collective algorithm engine.

Every selectable algorithm (ring / rd / tree) x {f32, i32, bf16} x
{SUM, MAX} must produce results matching the default path bit-for-bit —
except float SUM under ring/rd, whose different reduction-tree
association order is allowed the documented fp tolerance (docs/usage.md
§ Tuning collectives).  Runs under both shm-on and
``MPI4JAX_TPU_DISABLE_SHM=1`` (the test drives both); on an arena comm
the forced algorithms are no-ops (shm wins), so equivalence is exact.

Deliberately bridge-level (numpy in/out, no jit): the engine lives
under every dispatch path, and the bridge is the one that exposes
per-call forcing.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from mpi4jax_tpu import tune
from mpi4jax_tpu.runtime import bridge, transport

# wire codes (native/tpucomm.h): SUM=0, MAX=2
SUM, MAX = 0, 2


def f32_to_bf16_bits(a32):
    """Round-to-nearest-even bf16 bits, mirroring native f32_to_bf16."""
    bits = a32.view(np.uint32)
    rounded = bits + np.uint32(0x7FFF) + ((bits >> np.uint32(16)) & np.uint32(1))
    return (rounded >> np.uint32(16)).astype(np.uint16)


def main():
    comm = transport.get_world_comm()
    rank, size = comm.rank(), comm.size()
    h = comm.handle
    active, _, _ = bridge.shm_info(h)
    rng = np.random.RandomState(7)

    for count in (5, 513, 70000):  # < size, odd small, > 64KB f32 (ring cutoff)
        base_i = rng.randint(-1000, 1000, size=(size, count)).astype(np.int32)
        base_f = rng.randn(size, count).astype(np.float32)
        cases = []
        for op in (SUM, MAX):
            cases.append(("f32", 11, base_f[rank].copy(), op))
            cases.append(("i32", 3, base_i[rank].copy(), op))
            # bf16 payload: truncate the f32 field (exactly representable
            # inputs keep MAX bit-exact; SUM still reassociates)
            bf_bits = f32_to_bf16_bits(base_f)
            cases.append(("bf16", 10, bf_bits[rank].copy(), op))
        for name, dcode, x, op in cases:
            out_def = np.empty_like(x)
            bridge.allreduce_raw(h, x, out_def, dcode, op)  # default path
            for algo in ("ring", "rd", "tree"):
                out = np.empty_like(x)
                bridge.allreduce_raw(h, x, out, dcode, op,
                                     algo=tune.ALGO_CODES[algo])
                if name == "i32" or op == MAX or active:
                    assert np.array_equal(out, out_def), (
                        f"{name} op={op} algo={algo} count={count}: "
                        f"not bit-identical to the default path"
                    )
                else:
                    # float SUM: ring/rd reassociate — documented tolerance
                    if name == "bf16":
                        a = (out.astype(np.uint32) << 16).view(np.float32)
                        b = (out_def.astype(np.uint32) << 16).view(np.float32)
                        tol = dict(rtol=2e-2, atol=2e-2 * size)
                    else:
                        a, b = out, out_def
                        tol = dict(rtol=1e-5, atol=1e-5 * size)
                    assert np.allclose(a, b, **tol), (
                        f"{name} SUM algo={algo} count={count}: "
                        f"outside fp tolerance ({np.max(np.abs(a - b))})"
                    )

        # quantized wire formats (qring / qrd): APPROXIMATE but
        # rank-consistent — every rank must reconstruct bit-identical
        # results, and the native arithmetic must match the documented
        # numpy simulators exactly (ops/quantized.py).  On an arena
        # comm the forced quantized algorithms are no-ops (shm wins):
        # results are bit-identical to the default path.
        from mpi4jax_tpu.ops import quantized as quant

        for qcount in (2, count):  # 2 < size exercises empty chunks
            qbase = rng.randn(size, qcount).astype(np.float32) * 3
            exact64 = np.sum(qbase.astype(np.float64), axis=0)
            for qname, sim in (("qring", quant.simulate_qring_sum),
                               ("qrd", quant.simulate_qrd_sum)):
                xq = qbase[rank].copy()
                outq = np.empty_like(xq)
                bridge.allreduce_raw(h, xq, outq, 11, SUM,
                                     algo=tune.ALGO_CODES[qname])
                if active:
                    ref = np.empty_like(xq)
                    bridge.allreduce_raw(h, qbase[rank].copy(), ref, 11,
                                         SUM)
                    assert np.array_equal(outq, ref), (
                        f"{qname} on an arena comm must be the exact "
                        f"shm path (count={qcount})")
                else:
                    denom = max(np.max(np.abs(exact64)), 1e-6)
                    err = np.max(np.abs(outq - exact64)) / denom
                    assert err < 3e-2, (
                        f"{qname} count={qcount}: rel err {err:.2e} "
                        "outside the documented bound")
                    # bit-parity with the documented reference math
                    simulated = sim([qbase[r] for r in range(size)])
                    assert np.array_equal(outq, simulated), (
                        f"{qname} count={qcount}: native result "
                        "diverges from the numpy simulator")
                # rank consistency: every rank holds the same bits
                rows = bridge.allgather(h, outq, size)
                for r in range(size):
                    assert np.array_equal(rows[r], outq), (
                        f"{qname} count={qcount}: rank {r} diverged")
            # bf16 quantized: error-bound only (store rounding differs
            # per element; the wire math is covered by the f32 parity)
            bfq = f32_to_bf16_bits(qbase)
            outb = np.empty(qcount, np.uint16)
            bridge.allreduce_raw(h, bfq[rank].copy(), outb, 10, SUM,
                                 algo=tune.ALGO_CODES["qring"])
            bf_vals = (outb.astype(np.uint32) << 16).view(np.float32)
            bf_exact = np.sum(
                (bfq.astype(np.uint32) << 16).view(np.float32)
                .astype(np.float64), axis=0)
            if not active:
                denom = max(np.max(np.abs(bf_exact)), 1e-6)
                assert np.max(np.abs(bf_vals - bf_exact)) / denom < 4e-2
            rows = bridge.allgather(h, outb, size)
            for r in range(size):
                assert np.array_equal(rows[r], outb), "bf16 qring diverged"
            # ineligible dtype: a forced quantized code DEGRADES to the
            # exact twin — int32 stays bit-exact
            xi = (qbase[rank] * 100).astype(np.int32)
            outi = np.empty_like(xi)
            bridge.allreduce_raw(h, xi, outi, 3, SUM,
                                 algo=tune.ALGO_CODES["qring"])
            ref_i = np.empty_like(xi)
            bridge.allreduce_raw(h, xi.copy(), ref_i, 3, SUM)
            assert np.array_equal(outi, ref_i), (
                "int32 under forced qring must run the exact twin")

        # allgather: pure data movement — bit-for-bit under every algorithm
        xg = (base_i[rank, :count] + 7 * rank).astype(np.int32)
        ref = bridge.allgather(h, xg, size)
        for algo in ("ring", "rd", "tree"):
            got = bridge.allgather(h, xg, size, algo=tune.ALGO_CODES[algo])
            assert np.array_equal(got, ref), (
                f"allgather algo={algo} count={count}: mismatch"
            )

    # the probe names what ran: on an arena comm everything is "shm",
    # on TCP the engine's table picks must match the Python-side mirror
    for nbytes in (1024, 16 << 20):
        picked = comm.coll_algo("allreduce", nbytes)
        if active:
            assert picked == "shm", picked
        else:
            assert picked == tune.get_algorithm("allreduce", nbytes), picked

    print(f"coll_algo_ops OK (shm={int(active)})", flush=True)


if __name__ == "__main__":
    main()
