"""Quantized allreduce corpus program: ``compression="int8"`` must be
INVISIBLE to the static verifier and the schedule compiler.

The world-tier compression route binds the SAME ``allreduce`` primitive
as the exact collective (only a wire-format param rides along), so the
extracted per-rank schedule, the match simulation, and the compiled
execution plan are identical to an uncompressed program's — pinned by
the verify-corpus golden.  Executed in a virtual world the values are
the exact sums (the analysis executor does not model quantization);
under the real launcher they are the native qring/qrd approximations —
the asserts accept both within the documented error bound.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4j


def main():
    comm = m4j.get_default_comm()
    rank, size = comm.rank(), comm.size()
    weight = sum(r + 1 for r in range(size))

    # exact vs quantized: same primitive, same schedule, different wire
    x = jnp.linspace(-2.0, 3.0, 1030, dtype=jnp.float32) * (rank + 1)
    exact = m4j.allreduce(x, op=m4j.SUM, comm=comm)
    approx = m4j.allreduce(x, op=m4j.SUM, compression="int8", comm=comm)
    expect = np.linspace(-2.0, 3.0, 1030, dtype=np.float64) * weight
    np.testing.assert_allclose(np.asarray(exact), expect, rtol=1e-5,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(approx), expect, rtol=5e-2,
                               atol=0.5)

    # bf16 payload (the 2x-compression dtype)
    xb = x.astype(jnp.bfloat16)
    outb = m4j.allreduce(xb, op=m4j.SUM, compression="int8", comm=comm)
    assert outb.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(outb).astype(np.float32), expect, rtol=6e-2, atol=2.0)

    # quantized gradient synchronization under jax.grad: the backward
    # pass sees the same allreduce signature (transpose = identity)
    def loss(w):
        y = m4j.allreduce(w * w, op=m4j.SUM, compression="int8",
                          comm=comm)
        return jnp.sum(y)

    w0 = jnp.ones((512,), jnp.float32) * (rank + 1)
    g = jax.grad(loss)(w0)
    np.testing.assert_allclose(np.asarray(g),
                               2.0 * (rank + 1) * np.ones(512),
                               rtol=5e-2, atol=0.1)

    # a large payload routes as qring (the bandwidth twin) — still the
    # same schedule signature
    big = jnp.ones((96 * 1024,), jnp.float32) * (rank + 1)
    outg = m4j.allreduce(big, op=m4j.SUM, compression="int8", comm=comm)
    np.testing.assert_allclose(np.asarray(outg),
                               np.full(96 * 1024, float(weight)),
                               rtol=5e-2, atol=0.5)

    print("quant_ops OK", flush=True)


if __name__ == "__main__":
    main()
