"""vmap over world-tier ops, including the shape-changing ones.

The reference batches only allreduce/barrier/sendrecv (SURVEY.md §2.1);
here every op batches: the batch axis rides inside the communicated
payload, so a vmapped collective still issues ONE message.  Each vmapped
result is checked against the per-slice loop of the unbatched op.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4j


def main():
    comm = m4j.get_default_comm()
    rank, size = comm.rank(), comm.size()
    B, N = 3, 4

    x = (
        jnp.arange(B * N, dtype=jnp.float32).reshape(B, N) + 100 * rank
    )

    # allreduce (parity with reference scope) — vmap == loop
    vm = jax.vmap(lambda v: m4j.allreduce(v, op=m4j.SUM, comm=comm))(x)
    loop = jnp.stack(
        [m4j.allreduce(x[i], op=m4j.SUM, comm=comm) for i in range(B)]
    )
    np.testing.assert_allclose(np.asarray(vm), np.asarray(loop))

    # allgather: out (size, N) per slice → vmapped out (B, size, N)
    vm = jax.vmap(lambda v: m4j.allgather(v, comm=comm))(x)
    assert vm.shape == (B, size, N), vm.shape
    loop = jnp.stack([m4j.allgather(x[i], comm=comm) for i in range(B)])
    np.testing.assert_allclose(np.asarray(vm), np.asarray(loop))

    # gather: rank-dependent output — root (B, size, N) stacks, non-root
    # gets its batched input back (reference contract)
    vm = jax.vmap(lambda v: m4j.gather(v, root=0, comm=comm))(x)
    if rank == 0:
        assert vm.shape == (B, size, N), vm.shape
    else:
        assert vm.shape == (B, N), vm.shape
        np.testing.assert_allclose(np.asarray(vm), np.asarray(x))
    loop = jnp.stack([m4j.gather(x[i], root=0, comm=comm) for i in range(B)])
    np.testing.assert_allclose(np.asarray(vm), np.asarray(loop))

    # alltoall: per-slice input (size, 2), batched (B, size, 2)
    a2a_in = (
        jnp.arange(B * size * 2, dtype=jnp.float32).reshape(B, size, 2)
        + 1000 * rank
    )
    vm = jax.vmap(lambda v: m4j.alltoall(v, comm=comm))(a2a_in)
    loop = jnp.stack(
        [m4j.alltoall(a2a_in[i], comm=comm) for i in range(B)]
    )
    np.testing.assert_allclose(np.asarray(vm), np.asarray(loop))

    # scatter: per-slice input (size, 2), out (2,) → batched out (B, 2)
    sc_in = jnp.tile(
        jnp.arange(size, dtype=jnp.float32)[None, :, None], (B, 1, 2)
    ) + jnp.arange(B, dtype=jnp.float32)[:, None, None]
    vm = jax.vmap(lambda v: m4j.scatter(v, root=0, comm=comm))(sc_in)
    assert vm.shape == (B, 2)
    loop = jnp.stack(
        [m4j.scatter(sc_in[i], root=0, comm=comm) for i in range(B)]
    )
    np.testing.assert_allclose(np.asarray(vm), np.asarray(loop))

    # non-zero batch axis: batch on axis 1
    xt = x.T  # (N, B)
    vm = jax.vmap(
        lambda v: m4j.allgather(v, comm=comm), in_axes=1, out_axes=0
    )(xt)
    np.testing.assert_allclose(
        np.asarray(vm),
        np.asarray(
            jnp.stack([m4j.allgather(xt[:, i], comm=comm) for i in range(B)])
        ),
    )

    # vmap ∘ jit with mixed ops
    vm = jax.vmap(
        jax.jit(
            lambda v: m4j.allreduce(
                m4j.bcast(v, root=0, comm=comm), op=m4j.SUM, comm=comm
            )
        )
    )(x)
    assert vm.shape == (B, N)

    # batched send/recv pair: one message carries the whole batch
    if rank == 0:
        jax.vmap(lambda v: m4j.send(v, dest=1, comm=comm))(x)
    elif rank == 1:
        # NB: the dummy must itself be batched (zeros_like inside the
        # vmapped fn would make an unbatched constant and recv once)
        got = jax.vmap(lambda v: m4j.recv(v, source=0, comm=comm))(
            jnp.zeros_like(x)
        )
        np.testing.assert_allclose(
            np.asarray(got),
            np.arange(B * N, dtype=np.float32).reshape(B, N),
        )

    print(f"rank {rank}: vmap_ops OK", flush=True)


if __name__ == "__main__":
    main()
