"""False-serialization pipeline: the schedule compiler's headline shape.

A >= 3-rank ring where every rank sends a large block downstream, does
local compute, then receives the upstream block.  Token order serializes
send -> compute -> recv, but nothing truly depends: the recv's POST can
hoist into the send's callback, so the wire drains during the compute —
the overlap the execution plan (``analyze --optimize`` /
``launch --plan``) unlocks.  At np=2 the ring degenerates into a
bidirectional exchange and the plan must stay unrewritten
(order-critical); run this at np >= 3.

Numeric contract: two pipeline stages, each forwarding ``f(block)``
downstream; every rank checks the exact value that travelled two hops.
Bit-identical with the plan on or off.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4j

BLOCK = 64 * 1024  # f32: 256 KB, past any buffered-send threshold


def main():
    comm = m4j.get_default_comm()
    rank, size = comm.rank(), comm.size()
    assert size >= 3, "run at np >= 3 (np=2 is a bidirectional exchange)"
    nxt, prv = (rank + 1) % size, (rank - 1) % size

    def stage(block, tag):
        m4j.send(block, dest=nxt, tag=tag, comm=comm)
        # local compute between the send and the recv: the window the
        # hoisted recv post overlaps with
        local = jnp.tanh(block[:1024]).sum()
        got = m4j.recv(jnp.zeros((BLOCK,), jnp.float32), source=prv,
                       tag=tag, comm=comm)
        return got, local

    block0 = jnp.full((BLOCK,), float(rank), jnp.float32)
    got1, _ = stage(block0, tag=11)
    np.testing.assert_allclose(np.asarray(got1[:4]), float(prv))

    got2, _ = stage(got1 * 2.0 + 1.0, tag=12)
    two_back = (rank - 2) % size
    np.testing.assert_allclose(np.asarray(got2[:4]), two_back * 2.0 + 1.0)

    import hashlib

    digest = hashlib.sha256(
        np.asarray(got1).tobytes() + np.asarray(got2).tobytes()
    ).hexdigest()
    print(f"false_serialization digest r{rank} {digest}", flush=True)
    print(f"rank {rank}: false_serialization OK", flush=True)


if __name__ == "__main__":
    main()
