"""World tier ON THE ACCELERATOR RUNTIME — the staging-tier evidence run.

A 1-rank world job executed with the TPU runtime (no JAX_PLATFORMS=cpu
pin): every world op moves real device (HBM) buffers through the
HBM→host staging path into the native transport and back — the
structural analog of the reference's GPU bridge staging D2H → MPI → H2D
(mpi_xla_bridge_gpu.pyx:233-251 there).  Exercises every collective,
the p2p ops via MPI-style self-messaging, and Status introspection,
all with device-resident arrays.

Two modes, chosen by backend capability:

* real TPU VM (libtpu): ops run inside ``jit`` via the ordered host
  callback — including ordering inside ``lax.scan`` and ``grad``
  through the staged path;
* axon TPU tunnel: the PJRT plugin implements no host send/recv
  callbacks (``UNIMPLEMENTED`` for pure_callback; a HANG for the
  ordered path), so ops dispatch through the framework's staged-eager
  path (``_world_impl._use_staged_eager``): explicit device_get →
  native transport → device_put per op.  The jit-only sections are
  skipped with a note.

Launched by bench.py; also runnable by hand:
    python -m mpi4jax_tpu.runtime.launch -n 1 --platform tpu,cpu \
        tests/world_programs/tpu_world.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4j
from mpi4jax_tpu.ops import _world_impl


def main():
    dev = jax.devices()[0]
    platform = dev.platform
    assert platform != "cpu", (
        f"this program must run on the accelerator runtime, got {platform}"
    )
    staged = _world_impl._use_staged_eager()

    comm = m4j.get_default_comm()
    rank, size = comm.rank(), comm.size()

    x = jnp.arange(8, dtype=jnp.float32) + rank
    assert dev in x.devices(), (x.devices(), dev)

    # every collective with device-resident buffers (eager: each op is
    # one D2H → transport → H2D staging round)
    ar_sum = m4j.allreduce(x, op=m4j.SUM, comm=comm)
    assert dev in ar_sum.devices(), "result must land back on the accelerator"
    ar_max = m4j.allreduce(x, op=m4j.MAX, comm=comm)
    ag = m4j.allgather(x, comm=comm)
    a2a = m4j.alltoall(jnp.stack([x] * size), comm=comm)
    bc = m4j.bcast(x, root=0, comm=comm)
    red = m4j.reduce(x, op=m4j.SUM, root=0, comm=comm)
    sc = m4j.scan(x, op=m4j.SUM, comm=comm)
    g = m4j.gather(x, root=0, comm=comm)
    mine = m4j.scatter(jnp.stack([x] * size), root=0, comm=comm)
    m4j.barrier(comm=comm)

    expect = np.arange(8) * size + sum(range(size))
    np.testing.assert_allclose(np.asarray(ar_sum), expect)
    np.testing.assert_allclose(np.asarray(ar_max), np.arange(8) + size - 1)
    assert ag.shape == (size, 8)
    assert a2a.shape == (size, 8)
    np.testing.assert_allclose(np.asarray(bc), np.arange(8))
    if rank == 0:
        np.testing.assert_allclose(np.asarray(red), expect)
        assert g.shape == (size, 8)
    np.testing.assert_allclose(
        np.asarray(sc), np.cumsum([np.arange(8) + r for r in range(rank + 1)],
                                  axis=0)[-1])
    np.testing.assert_allclose(np.asarray(mine), np.asarray(x))

    # p2p + Status via self-messaging (reference allows self-sendrecv —
    # its exit-flush regression depends on it, test_common.py:91-114)
    st = m4j.Status()
    out = m4j.sendrecv(x, source=rank, dest=rank, status=st, comm=comm)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))
    assert st.Get_source() == rank and st.Get_count(np.float32) == 8, st

    m4j.send(x * 2, dest=rank, tag=9, comm=comm)
    st2 = m4j.Status()
    out = m4j.recv(x, source=m4j.ANY_SOURCE, status=st2, comm=comm)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 2)
    assert st2.Get_source() == rank and st2.Get_tag() == 9, st2

    if staged:
        # the tunnel compiles no callback programs; the jit-only
        # ordering/autodiff sections need a callback-capable backend
        print("tpu_world: staged-eager dispatch (axon tunnel — no host "
              "callbacks); jit sections skipped", flush=True)
    else:
        # the whole stack under one jit on the TPU runtime: ordered
        # effects must serialize the callbacks inside lax.scan (the
        # reference's fori_loop halo pattern, shallow_water.py:415-420)
        def body(carry, _):
            carry = m4j.allreduce(carry, op=m4j.SUM, comm=comm) / size
            carry = m4j.sendrecv(carry, source=rank, dest=rank, comm=comm)
            return carry, ()

        looped, _ = jax.jit(
            lambda v: jax.lax.scan(body, v, None, length=4)
        )(jnp.ones((4,), jnp.float32))
        np.testing.assert_allclose(np.asarray(looped), 1.0, rtol=1e-6)

        # autodiff through the staged path
        grad = jax.grad(
            lambda v: m4j.allreduce(v, op=m4j.SUM, comm=comm).sum()
        )(x)
        np.testing.assert_allclose(np.asarray(grad), 1.0)

    print(f"tpu_world OK (rank {rank}, platform {platform}, "
          f"staged_eager={staged})", flush=True)


if __name__ == "__main__":
    main()
