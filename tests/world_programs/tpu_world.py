"""World tier ON THE TPU PLATFORM — the staging-tier evidence run.

A 1-rank world job executed with the TPU runtime (no JAX_PLATFORMS=cpu
pin): every world op lowers to the ordered host callback, which on this
platform IS the HBM→host staging path (the structural analog of the
reference's GPU bridge staging D2H → MPI → H2D,
mpi_xla_bridge_gpu.pyx:233-251 there).  Exercises every collective, the
p2p ops via MPI-style self-messaging, Status introspection, ordering
inside lax.scan, and grad — all under jit on the accelerator runtime.

Launched by bench.py with --platform left to the ambient TPU backend;
also runnable by hand:
    python -m mpi4jax_tpu.runtime.launch -n 1 --platform tpu,cpu \
        tests/world_programs/tpu_world.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4j


def main():
    dev = jax.devices()[0]
    platform = dev.platform
    assert platform != "cpu", (
        f"this program must run on the accelerator runtime, got {platform}"
    )

    comm = m4j.get_default_comm()
    rank, size = comm.rank(), comm.size()

    x = jnp.arange(8, dtype=jnp.float32) + rank

    # every collective, eagerly (device buffers staged through the host)
    out = m4j.allreduce(x, op=m4j.SUM, comm=comm)
    expect = np.arange(8) * size + sum(range(size))
    np.testing.assert_allclose(np.asarray(out), expect)
    np.testing.assert_allclose(
        np.asarray(m4j.allreduce(x, op=m4j.MAX, comm=comm)),
        np.arange(8) + size - 1)
    ag = m4j.allgather(x, comm=comm)
    assert ag.shape == (size, 8)
    a2a = m4j.alltoall(jnp.stack([x] * size), comm=comm)
    assert a2a.shape == (size, 8)
    np.testing.assert_allclose(
        np.asarray(m4j.bcast(x, root=0, comm=comm)), np.arange(8))
    red = m4j.reduce(x, op=m4j.SUM, root=0, comm=comm)
    if rank == 0:
        np.testing.assert_allclose(np.asarray(red), expect)
    sc = m4j.scan(x, op=m4j.SUM, comm=comm)
    np.testing.assert_allclose(
        np.asarray(sc), np.cumsum([np.arange(8) + r for r in range(rank + 1)],
                                  axis=0)[-1])
    g = m4j.gather(x, root=0, comm=comm)
    if rank == 0:
        assert g.shape == (size, 8)
    mine = m4j.scatter(jnp.stack([x] * size), root=0, comm=comm)
    np.testing.assert_allclose(np.asarray(mine), np.asarray(x))
    m4j.barrier(comm=comm)

    # p2p + Status via self-messaging (reference allows self-sendrecv —
    # its exit-flush regression depends on it, test_common.py:91-114)
    st = m4j.Status()
    out = m4j.sendrecv(x, source=rank, dest=rank, status=st, comm=comm)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))
    assert st.Get_source() == rank and st.Get_count(np.float32) == 8, st

    m4j.send(x * 2, dest=rank, tag=9, comm=comm)
    st2 = m4j.Status()
    out = m4j.recv(x, source=m4j.ANY_SOURCE, status=st2, comm=comm)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 2)
    assert st2.Get_source() == rank and st2.Get_tag() == 9, st2

    # the whole stack under one jit on the TPU runtime: ordered effects
    # must serialize the callbacks inside lax.scan (the reference's
    # fori_loop halo pattern, shallow_water.py:415-420 there)
    def body(carry, _):
        carry = m4j.allreduce(carry, op=m4j.SUM, comm=comm) / size
        carry = m4j.sendrecv(carry, source=rank, dest=rank, comm=comm)
        return carry, ()

    looped, _ = jax.jit(
        lambda v: jax.lax.scan(body, v, None, length=4)
    )(jnp.ones((4,), jnp.float32))
    np.testing.assert_allclose(np.asarray(looped), 1.0, rtol=1e-6)

    # autodiff through the staged path
    grad = jax.grad(
        lambda v: m4j.allreduce(v, op=m4j.SUM, comm=comm).sum()
    )(x)
    np.testing.assert_allclose(np.asarray(grad), 1.0)

    print(f"tpu_world OK (rank {rank}, platform {platform})", flush=True)


if __name__ == "__main__":
    main()
