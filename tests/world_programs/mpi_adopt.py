"""Adopting an mpi4py communicator: WorldComm.from_mpi end to end.

Launched as N plain processes (no framework launcher, no MPI4JAX_TPU_*
env) with the simulated mpi4py harness on sys.path — the drop-in shape
for users who hold mpi4py comms (reference: any ``MPI.Comm`` as an op
param, utils.py:80-127 there).  Exercises:

1. ``from_mpi(COMM_WORLD)`` — bootstrap via mpi4py only, data over the
   native transport (eager + jitted ops).
2. ``from_mpi(COMM_WORLD.Split(...))`` — a Split-derived subgroup
   becomes its own world; collectives stay inside the group.
3. The adopted world composes with the framework's own ``split``.
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tests", "world_programs", "_fake_mpi"))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from mpi4py import MPI  # noqa: E402  (the simulated harness)

import mpi4jax_tpu as m4j  # noqa: E402
from mpi4jax_tpu.runtime.transport import WorldComm  # noqa: E402

world = WorldComm.from_mpi(MPI.COMM_WORLD)
rank, size = world.rank(), world.size()
assert rank == MPI.COMM_WORLD.Get_rank()
assert size == MPI.COMM_WORLD.Get_size()

# eager op over the adopted comm
out = np.asarray(m4j.allreduce(jnp.arange(4.0) + rank, op=m4j.SUM,
                               comm=world))
np.testing.assert_allclose(
    out, size * np.arange(4.0) + sum(range(size)))

# jitted chain (FFI fast path) with the adopted comm as ambient default
with world:
    @jax.jit
    def step(x):
        y = m4j.allreduce(x, op=m4j.SUM)
        return m4j.bcast(y * 2.0, root=size - 1)

    got = np.asarray(step(jnp.ones(8) * (rank + 1)))
    np.testing.assert_allclose(got, 2.0 * sum(range(1, size + 1)))

# a Split-derived mpi4py subgroup becomes its own world
sub_mpi = MPI.COMM_WORLD.Split(color=rank % 2, key=rank)
sub = WorldComm.from_mpi(sub_mpi)
assert sub.size() == sub_mpi.Get_size()
vals = np.asarray(m4j.allgather(jnp.float32(rank), comm=sub))
np.testing.assert_allclose(vals, np.arange(rank % 2, size, 2, np.float32))

# the adopted world composes with the framework's own split
own_sub = world.split(color=rank // 2, key=rank)
s = np.asarray(m4j.allreduce(jnp.float32(1.0), op=m4j.SUM, comm=own_sub))
np.testing.assert_allclose(s, own_sub.size())

print(f"mpi_adopt OK r{rank}", flush=True)
