"""Bridge-level ELASTIC + PLANNED training rank program (no jax import,
so it runs in ANY container via the parent-package shim).

The elastic-safe-plans acceptance scenario: every step routes K small
MAX allreduces through an installed, proved execution plan (bucket
marks make it a rewritten plan; the runner signature-checks every op).
A registered ``planrt.set_plan_source`` tells recovery how to re-derive
the schedule for ANY world size, so when a rank dies mid-job
``bridge.rebuild`` re-compiles and re-PROVES the plan for the shrunk
world inside the recovery — the job keeps its plan instead of silently
losing it.  The MAX gradient sync is world-size invariant, so the final
state digest must be BIT-IDENTICAL to an uninterrupted planned run.

Usage (under the launcher): elastic_plan.py [steps]
Checkpoint directory: MPI4JAX_TPU_CKPT_DIR (set by the test).
"""

import hashlib
import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)
pkg = types.ModuleType("mpi4jax_tpu")
pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
sys.modules["mpi4jax_tpu"] = pkg

import numpy as np  # noqa: E402

from mpi4jax_tpu.analysis import _events, _plan  # noqa: E402
from mpi4jax_tpu.elastic import training  # noqa: E402
from mpi4jax_tpu.runtime import bridge, planrt, transport  # noqa: E402

STEPS = int(sys.argv[1]) if len(sys.argv) > 1 else 10
K = 4                 # planned allreduces per step (one plan cycle)
SHAPE = (256,)        # f64: 2 KB — bucketable, so the plan is rewritten
_MAX = 2              # native reduce-op code (tpucomm.h)


def make_schedule(n):
    """The per-step schedule for ANY world size: K adjacent small MAX
    allreduces per rank — the shape the compiler marks as a gradient
    bucket (=> a rewritten plan worth keeping across recovery)."""
    events = {
        r: [_events.CommEvent(r, i, "allreduce", reduce_op="MAX",
                              dtype="float64", shape=SHAPE)
            for i in range(K)]
        for r in range(n)
    }
    return events, {(0,): tuple(range(n))}


# HOW recovery re-derives the plan for a shrunk world: rebuild calls
# this with the new size, compiles the schedule fresh, and re-proves it
# before anything may execute — the elastic-safe-plans contract.
planrt.set_plan_source(make_schedule)


def grad(step, j):
    # identical on every rank; MAX-synced, so the result is
    # bit-identical for ANY world size and the trajectory survives a
    # shrink bit-for-bit
    return np.cos(np.arange(SHAPE[0]) * (step + 1) * 0.01 * (j + 1))


def step_fn(state, step, comm):
    rt = planrt.get(comm)
    assert rt is not None and rt.enabled, \
        f"step {step}: no active plan runner on this world"
    g = np.zeros(8)
    for j in range(K):
        payload = grad(step, j)
        out = rt.run_sync(
            "allreduce",
            lambda p=payload: bridge.allreduce(comm.handle, p, _MAX),
            reduce_op="MAX", nbytes=payload.nbytes)
        g = g + out[:8]
    assert rt.stats["mismatches"] == 0, rt.stats
    return state - 0.05 * g


def main():
    comm = transport.get_world_comm()
    n, r = comm.size(), comm.rank()
    events, comms = make_schedule(n)
    plan = _plan.compile_schedules(events, comms)
    assert plan.proved, plan.reasons
    assert plan.rewritten, plan.format()  # bucket marks
    assert planrt.install(comm.handle, plan, r), "planrt.install refused"

    state = training.run(step_fn, np.zeros(8), steps=STEPS, save_every=2)

    rt = planrt.get(comm)
    assert rt is not None and rt.enabled, "plan lost by the end of the job"
    assert rt.stats["mismatches"] == 0, rt.stats
    rt.flush()
    digest = hashlib.sha256(np.asarray(state).tobytes()).hexdigest()
    print(f"elastic_plan digest r{comm.rank()} {digest}", flush=True)
    print(f"elastic_plan OK np={comm.size()} plan_active=1 "
          f"mismatches=0", flush=True)


if __name__ == "__main__":
    main()
