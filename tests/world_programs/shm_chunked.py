"""Shm-arena piece loops: messages far larger than the slot size.

Run with MPI4JAX_TPU_SHM_MB=1 so every collective must traverse its
chunked multi-piece path (slot 1 MB, payloads 4-6 MB), including the
divided-slot budgets of scatter/alltoall.  Values are position-dependent
so any piece misplacement shows up as a wrong element, not a wrong sum.
"""

import os
import sys

sys.path.insert(
    0,
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
)

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import mpi4jax_tpu as m4j  # noqa: E402

assert os.environ.get("MPI4JAX_TPU_SHM_MB") == "1", "run with 1 MB slots"

comm = m4j.get_default_comm()
rank, size = comm.rank(), comm.size()

n = 1_500_000  # 6 MB of f32 per rank
base = jnp.arange(n, dtype=jnp.float32)

# allreduce: 6 pieces through the cooperative path
out = np.asarray(m4j.allreduce(base + rank, op=m4j.SUM, comm=comm))
expect = size * np.arange(n, dtype=np.float32) + sum(range(size))
np.testing.assert_allclose(out, expect, rtol=1e-6)

# bcast: root's position-dependent payload arrives intact
got = np.asarray(m4j.bcast(base * (rank + 1), root=1, comm=comm))
np.testing.assert_allclose(got, 2.0 * np.arange(n, dtype=np.float32))

# allgather: each rank's 4 MB row lands in the right slot of the stack
m = 1_000_000
rows = np.asarray(
    m4j.allgather(jnp.full((m,), float(rank), jnp.float32)
                  + jnp.arange(m, dtype=jnp.float32), comm=comm)
)
for r in range(size):
    np.testing.assert_allclose(
        rows[r], r + np.arange(m, dtype=np.float32)
    )

# alltoall: (size, m) with per-destination markers, divided-slot pieces
x = (jnp.arange(size, dtype=jnp.float32)[:, None] * 10
     + rank
     + jnp.zeros((size, m), jnp.float32))
shuf = np.asarray(m4j.alltoall(x, comm=comm))
for src in range(size):
    np.testing.assert_allclose(
        shuf[src], np.full((m,), rank * 10 + src, np.float32)
    )

# scatter: root row r (position-dependent) reaches rank r
table = (jnp.arange(size, dtype=jnp.float32)[:, None] * 100
         + jnp.arange(m, dtype=jnp.float32)[None, :])
mine = np.asarray(m4j.scatter(table, root=0, comm=comm))
np.testing.assert_allclose(
    mine, rank * 100 + np.arange(m, dtype=np.float32)
)

# race hunt (ADVICE r4 high): a >slot-size allreduce's copy-out from the
# result region happens AFTER its second barrier; an immediately
# following root!=0 bcast/scatter used to write result() BEFORE its
# first barrier, corrupting a slower rank's copy-out.  Staging now goes
# through slot(root); iterate the exact sequence so a regression shows
# up as a wrong element with high probability rather than never.
for trial in range(8):
    red_out = np.asarray(m4j.allreduce(base + rank, op=m4j.SUM, comm=comm))
    b = np.asarray(
        m4j.bcast(base * (rank + 1) + trial, root=size - 1, comm=comm))
    np.testing.assert_allclose(red_out, expect, rtol=1e-6,
                               err_msg=f"allreduce trial {trial}")
    np.testing.assert_allclose(
        b, float(size) * np.arange(n, dtype=np.float32) + trial,
        err_msg=f"bcast trial {trial}")
    red_out2 = np.asarray(m4j.allreduce(base, op=m4j.SUM, comm=comm))
    sc = np.asarray(m4j.scatter(table, root=size - 1, comm=comm))
    np.testing.assert_allclose(red_out2,
                               size * np.arange(n, dtype=np.float32),
                               rtol=1e-6, err_msg=f"allreduce2 trial {trial}")
    np.testing.assert_allclose(
        sc, rank * 100 + np.arange(m, dtype=np.float32),
        err_msg=f"scatter trial {trial}")

# scan + reduce through the same chunked machinery
pre = np.asarray(m4j.scan(base * 0 + (rank + 1), op=m4j.SUM, comm=comm))
np.testing.assert_allclose(pre[:4], sum(range(1, rank + 2)))
red = np.asarray(m4j.reduce(base, op=m4j.SUM, root=0, comm=comm))
if rank == 0:
    np.testing.assert_allclose(red, size * np.arange(n, dtype=np.float32))

print(f"shm_chunked OK r{rank}", flush=True)
