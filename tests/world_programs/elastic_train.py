"""Bridge-level elastic DP training rank program (no jax import, so it
runs in ANY container via the parent-package shim).

Each step allreduce-means a gradient that is IDENTICAL on every rank,
so the parameter trajectory is invariant to the world size — an
elastic run that loses a rank mid-job, shrinks (or respawns), restores
the last committed checkpoint, and finishes must print the EXACT digest
of an uninterrupted run.  That pins the whole recovery pipeline:
RankFailure surfacing, generation announcements, the tpucomm_shrink
bootstrap, and checkpoint commit/restore.

Usage (under the launcher): elastic_train.py [steps]
Checkpoint directory: MPI4JAX_TPU_CKPT_DIR (set by the test).
"""

import hashlib
import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)
pkg = types.ModuleType("mpi4jax_tpu")
pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
sys.modules["mpi4jax_tpu"] = pkg

import numpy as np  # noqa: E402

from mpi4jax_tpu.elastic import training  # noqa: E402
from mpi4jax_tpu.runtime import bridge, transport  # noqa: E402

STEPS = int(sys.argv[1]) if len(sys.argv) > 1 else 12


def grad(step):
    # identical on every rank; synced with a MAX allreduce, whose
    # result is bit-identical for ANY world size (a SUM-mean would
    # round differently at np=3 vs the shrunk np=2: (3g)/3 != g in
    # f64) — so the trajectory survives a shrink bit-for-bit and the
    # final digest must equal an uninterrupted run's
    return np.cos(np.arange(8) * (step + 1) * 0.1)


def step_fn(state, step, comm):
    g = bridge.allreduce(comm.handle, grad(step), 2)  # MAX
    return state - 0.05 * g


def main():
    comm = transport.get_world_comm()
    state = training.run(step_fn, np.zeros(8), steps=STEPS, save_every=2)
    digest = hashlib.sha256(np.asarray(state).tobytes()).hexdigest()
    # one write() per line so the ranks' reports can't interleave in
    # the launcher's multiplexed stdout (print's text + newline are two
    # writes, and a splice between them corrupts the digest token)
    sys.stdout.write(f"elastic_train digest r{comm.rank()} {digest}\n")
    sys.stdout.write("elastic_train OK\n")
    sys.stdout.flush()


if __name__ == "__main__":
    main()
