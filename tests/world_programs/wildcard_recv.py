"""ANY_SOURCE wildcard receive (reference parity: MPI.ANY_SOURCE is the
reference's *default* recv source, recv.py:45 there; libmpi matches the
wildcard natively).  The native transport polls across peer sockets and
takes the first complete frame; the Status reports who actually sent.

Run at -n 4: rank 0 collects from everyone via wildcards — eagerly,
under jit, mixed with directed receives, and with ANY_TAG."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4j


def main():
    comm = m4j.get_default_comm()
    rank, size = comm.rank(), comm.size()
    assert size == 4, "run with -n 4"

    template = jnp.zeros((4,), jnp.float32)

    # --- 1. pure wildcard: rank 0 drains one message from each sender ---
    if rank == 0:
        got = {}
        for _ in range(size - 1):
            status = m4j.Status()
            out = m4j.recv(
                template, source=m4j.ANY_SOURCE, status=status, comm=comm
            )
            src = status.Get_source()
            assert src not in got, f"duplicate source {src}"
            got[src] = np.asarray(out)
            assert status.Get_tag() == 100 + src, status
        assert sorted(got) == [1, 2, 3], got
        for src, val in got.items():
            np.testing.assert_allclose(val, float(src))
        # phase gate: senders must not race ahead, or their next-phase
        # frames would be wildcard-eligible here
        for r in (1, 2, 3):
            m4j.send(template, dest=r, tag=99, comm=comm)
    else:
        m4j.send(template + rank, dest=0, tag=100 + rank, comm=comm)
        m4j.recv(template, source=0, tag=99, comm=comm)  # phase gate

    # --- 2. mixed wildcard/directed ordering: a directed recv must pull
    # from its own socket even when wildcard-eligible frames from other
    # peers are already waiting ---
    if rank == 0:
        # give the sends time to land so wildcard-eligible frames are
        # already queued when the directed recv runs (can't barrier here:
        # barrier frames would queue behind the un-received data frames
        # on these same ordered sockets)
        import time

        time.sleep(0.3)
        status_d = m4j.Status()
        out = m4j.recv(
            template, source=2, tag=m4j.ANY_TAG, status=status_d, comm=comm
        )
        np.testing.assert_allclose(np.asarray(out), 20.0)
        assert status_d.Get_source() == 2 and status_d.Get_tag() == 202
        seen = set()
        for _ in range(2):
            status_w = m4j.Status()
            out = m4j.recv(
                template, source=m4j.ANY_SOURCE, tag=m4j.ANY_TAG,
                status=status_w, comm=comm,
            )
            src = status_w.Get_source()
            seen.add(src)
            np.testing.assert_allclose(np.asarray(out), src * 10.0)
            assert status_w.Get_tag() == 200 + src
        assert seen == {1, 3}, seen
    else:
        m4j.send(template + rank * 10.0, dest=0, tag=200 + rank, comm=comm)

    # --- 3. wildcard under jit (status filled by the ordered callback) ---
    # rank 1 must not send before phase 2 is fully drained, or its
    # phase-3 frame would be wildcard-eligible there: rank 0 posts an
    # explicit go-ahead
    if rank == 0:
        m4j.send(template, dest=1, tag=300, comm=comm)
        status_j = m4j.Status()
        out = jax.jit(
            lambda v: m4j.recv(
                v, source=m4j.ANY_SOURCE, status=status_j, comm=comm
            )
        )(template)
        np.testing.assert_allclose(
            np.asarray(out), float(status_j.Get_source())
        )
        assert status_j.Get_source() in (1, 2, 3), status_j
        assert status_j.Get_count(np.float32) == 4, status_j
    elif rank == 1:
        m4j.recv(template, source=0, tag=300, comm=comm)  # go-ahead
        m4j.send(template + 1.0, dest=0, tag=0, comm=comm)
    # ranks 2, 3 idle in phase 3 (exactly one jit message outstanding)

    # --- 4. concrete-tag wildcard must skip a mismatched self head and
    # match the peer frame instead (regression: the self-queue shortcut
    # used to pop unconditionally and abort on the tag mismatch) ---
    if rank == 0:
        m4j.send(template, dest=3, tag=301, comm=comm)  # phase gate
        m4j.send(template + 7.0, dest=0, tag=7, comm=comm)  # self, tag 7
        status_m = m4j.Status()
        out = m4j.recv(
            template, source=m4j.ANY_SOURCE, tag=5, status=status_m,
            comm=comm,
        )
        np.testing.assert_allclose(np.asarray(out), 5.0)
        assert status_m.Get_source() == 3 and status_m.Get_tag() == 5
        out = m4j.recv(template, source=0, tag=7, comm=comm)  # drain self
        np.testing.assert_allclose(np.asarray(out), 7.0)
    elif rank == 3:
        m4j.recv(template, source=0, tag=301, comm=comm)  # phase gate
        m4j.send(template + 5.0, dest=0, tag=5, comm=comm)

    print(f"wildcard_recv OK (rank {rank})")


if __name__ == "__main__":
    main()
