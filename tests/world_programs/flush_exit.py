"""Exit-deadlock regression: dispatch communication and exit immediately.

Reference analog: pending async MPI at interpreter teardown would hang
without the atexit effects barrier (test_common.py:91-114 there).  Here:
both ranks fire a sendrecv and exit without blocking on the result; the
atexit ``jax.effects_barrier()`` must drain it and the job must end
cleanly.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp

import mpi4jax_tpu as m4j


def main():
    comm = m4j.get_default_comm()
    # fire-and-exit: no block_until_ready, no result use
    m4j.sendrecv(jnp.arange(1000.0), shift=1, comm=comm)
    m4j.allreduce(jnp.ones((1000,)), op=m4j.SUM, comm=comm)
    print(f"rank {comm.rank()}: dispatched, exiting", flush=True)


if __name__ == "__main__":
    main()
