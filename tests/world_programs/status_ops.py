"""Status introspection on recv/sendrecv (reference parity:
tests/collective_ops/test_sendrecv.py:29-61 there — status filled eagerly
and under jit; plus ANY_TAG wildcard, element counts, and split
sendtag/recvtag)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4j


def main():
    comm = m4j.get_default_comm()
    rank, size = comm.rank(), comm.size()
    assert size == 2, "run with -n 2"
    other = 1 - rank

    arr = jnp.ones((3, 2), jnp.float32) * rank

    # sendrecv + status, eager
    status = m4j.Status()
    res = m4j.sendrecv(
        arr, source=other, dest=other, status=status, comm=comm
    )
    np.testing.assert_allclose(np.asarray(res), other)
    assert status.Get_source() == other, status
    assert status.Get_tag() == 0, status
    assert status.Get_count() == arr.size * 4, status
    assert status.Get_count(np.float32) == arr.size, status

    # sendrecv + status under jit
    status2 = m4j.Status()
    res = jax.jit(
        lambda v: m4j.sendrecv(
            v, source=other, dest=other, status=status2, comm=comm
        )
    )(arr)
    np.testing.assert_allclose(np.asarray(res), other)
    assert status2.Get_source() == other, status2
    assert status2.Get_count(np.float32) == arr.size, status2

    # split tags: each rank sends with its own tag; ANY_TAG recv reports it
    status3 = m4j.Status()
    res = m4j.sendrecv(
        arr, source=other, dest=other, sendtag=10 + rank,
        recvtag=m4j.ANY_TAG, status=status3, comm=comm,
    )
    np.testing.assert_allclose(np.asarray(res), other)
    assert status3.Get_tag() == 10 + other, status3

    # recv + status (+ default ANY_TAG), with an explicitly tagged send
    status4 = m4j.Status()
    if rank == 0:
        m4j.send(arr, dest=1, tag=7, comm=comm)
    else:
        out = m4j.recv(arr, source=0, status=status4, comm=comm)
        np.testing.assert_allclose(np.asarray(out), 0.0)
        assert status4.Get_source() == 0, status4
        assert status4.Get_tag() == 7, status4
        assert status4.Get_count(np.float32) == arr.size, status4

    # short message into a larger buffer: count reports actual bytes
    if rank == 0:
        m4j.send(jnp.arange(2, dtype=jnp.float32), dest=1, tag=3, comm=comm)
    else:
        big = jnp.zeros((6,), jnp.float32)
        status5 = m4j.Status()
        out = m4j.recv(big, source=0, tag=3, status=status5, comm=comm)
        np.testing.assert_allclose(np.asarray(out)[:2], [0.0, 1.0])
        assert status5.Get_count(np.float32) == 2, status5

    # reverse-mode AD with asymmetric split tags: the transpose must swap
    # tags along with source/dest (forward matched sendtag(s) ==
    # recvtag(d), so the reversed edge sends with the old recvtag)
    g = jax.grad(
        lambda v: m4j.sendrecv(
            v, source=other, dest=other, sendtag=rank + 1,
            recvtag=other + 1, comm=comm,
        ).sum()
    )(arr)
    np.testing.assert_allclose(np.asarray(g), 1.0)

    # explicit-token compat shim carries status too
    from mpi4jax_tpu.compat import token_api

    status6 = m4j.Status()
    res, tok = token_api.sendrecv(
        arr, source=other, dest=other, status=status6, comm=comm
    )
    np.testing.assert_allclose(np.asarray(res), other)
    assert status6.Get_source() == other, status6

    print(f"status_ops OK (rank {rank})")


if __name__ == "__main__":
    main()
