"""Bridge-level serving-v2 rank program (no jax — parent-package shim).

Drives :mod:`mpi4jax_tpu.serving` end-to-end under the launcher: rank 0
is the frontend (continuous batching — half the stream is submitted
only after decoding started), every other rank runs the v2 worker
loop.  The transcript digest is a pure function of the request
prompts and the adapter, so it must be IDENTICAL across world sizes,
role modes (colocated vs disaggregated), shm on/off, and any number of
mid-stream recoveries — that is the bit-consistency and commit-point
contract the world tests pin.

Usage (under the launcher):
    serve_v2.py [nreq] [roles_mode] [adapter] [max_new]

adapter: ``toy`` (exactly prefix-consistent integer state — the fault
tests) or ``gpt`` (the numpy GPT — float math, no-fault runs).
"""

import hashlib
import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)
pkg = types.ModuleType("mpi4jax_tpu")
pkg.__path__ = [os.path.join(REPO, "mpi4jax_tpu")]
sys.modules["mpi4jax_tpu"] = pkg

from mpi4jax_tpu import serving  # noqa: E402
from mpi4jax_tpu.runtime import transport  # noqa: E402

NREQ = int(sys.argv[1]) if len(sys.argv) > 1 else 10
MODE = sys.argv[2] if len(sys.argv) > 2 else "auto"
ADAPTER = sys.argv[3] if len(sys.argv) > 3 else "toy"
MAX_NEW = int(sys.argv[4]) if len(sys.argv) > 4 else 4


def make_adapter():
    if ADAPTER == "gpt":
        return serving.make_numpy_gpt_adapter(max_seq=96)
    return serving.ToyAdapter()


def prompt_for(i, vocab):
    return [(i * 7 + j * 3 + 1) % vocab for j in range(4 + i % 3)]


def main():
    comm = transport.get_world_comm()
    _ = comm.handle  # connect the mesh before the first broadcast
    adapter = make_adapter()
    if comm.rank() != 0:
        roles = serving.serve_worker(comm, adapter, roles_mode=MODE)
        print(f"serve_v2 worker done r{comm.rank()} "
              f"role={roles.role_of(comm.rank())}", flush=True)
        return

    server = serving.Server(comm, adapter, max_batch=4, chunk_tokens=3,
                            roles_mode=MODE)
    print(f"serve_v2 roles: {server.roles.describe()}", flush=True)
    vocab = adapter.vocab
    for i in range(NREQ // 2):
        assert server.submit(prompt_for(i, vocab),
                             max_new=MAX_NEW + (i % 3)).admitted
    iters = 0
    while server.active or len(server.completed) < NREQ:
        if iters == 2:
            # continuous batching: the second half arrives mid-decode
            for i in range(NREQ // 2, NREQ):
                assert server.submit(prompt_for(i, vocab),
                                     max_new=MAX_NEW + (i % 3)).admitted
        server.step()
        iters += 1
        if iters > 2000:
            raise RuntimeError("serving did not drain")
    server.stop()

    digest = hashlib.sha256()
    for r in sorted(server.completed, key=lambda r: r.id):
        assert r.done and len(r.generated) >= MAX_NEW, (r.id, r.tokens)
        digest.update(repr((r.id, r.tokens)).encode())
    print(f"serve_v2 digest {digest.hexdigest()}", flush=True)
    print(f"serve_v2 OK nreq={len(server.completed)} "
          f"recoveries={server.recoveries} mode={server.roles.mode}",
          flush=True)


if __name__ == "__main__":
    main()
