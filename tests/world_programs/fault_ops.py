"""Deterministic point-to-point traffic for the failure-detection tests.

A ring of sendrecv rounds at the bridge level (no jax import — the
failure paths under test live entirely in the native transport, and a
lean program keeps the detection-latency assertions about the
*transport*, not interpreter startup).  Under ``MPI4JAX_TPU_FAULT`` one
rank hangs / exits / partitions mid-schedule; its peers must abort with
the transport's diagnostics instead of hanging (tests/world/
test_failure_detection.py asserts the teardown latency and wording).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from mpi4jax_tpu.runtime import bridge, transport


def main():
    comm = transport.get_world_comm()
    rank, size = comm.rank(), comm.size()
    assert size >= 2, "run under the launcher with -n >= 2"
    h = comm.handle

    rounds = int(os.environ.get("FAULT_OPS_ROUNDS", "6"))
    peer_hi = (rank + 1) % size
    peer_lo = (rank - 1) % size
    base = np.arange(8, dtype=np.float64)
    for i in range(rounds):
        got = bridge.sendrecv(h, base + rank + i, (8,), np.float64,
                              peer_lo, peer_hi, 40 + i)
        np.testing.assert_allclose(got, base + peer_lo + i)
    print(f"rank {rank}: fault_ops OK", flush=True)


if __name__ == "__main__":
    main()
