"""MoE expert-parallel corpus program: the dispatch/combine alltoalls
must be INVISIBLE to the static verifier and the schedule compiler.

``parallel.moe`` routes every token top-1, ships it to its expert's
rank with one ``alltoall``, and ships the expert outputs home with a
second one.  Quantized dispatch (``compression="int8"``) and forced
schedules (``algo="halltoall"``) bind the SAME ``alltoall`` primitive —
only wire-format / schedule params ride along — so the extracted
per-rank schedule, the match simulation, and the compiled execution
plan are identical to the exact program's, pinned by the verify-corpus
golden.  Executed in a virtual world the values are exact (the analysis
executor does not model quantization); under the real launcher the
quantized runs are the int8 approximations — the asserts accept both
within the documented error bound.

Routing is made deterministic by construction (each token carries a
strong component along its expert's gate direction), so the numpy
reference below agrees with the traced routing on every jax version.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4j
from mpi4jax_tpu.parallel import moe


T, D, DFF = 8, 16, 32  # tokens/rank, d_model, d_ff


def _reference(params, x, size, capacity):
    """Per-token numpy twin of ``moe.moe_ffn``: the exchange never
    changes values, so the reference is local — route, capacity-drop,
    expert FFN, gate-weight."""
    logits = x @ params["w_gate"]
    z = np.exp(logits - logits.max(axis=-1, keepdims=True))
    probs = z / z.sum(axis=-1, keepdims=True)
    idx = np.argmax(probs, axis=-1)
    y = np.zeros_like(x)
    seen = {e: 0 for e in range(size)}
    for t in range(x.shape[0]):
        e = int(idx[t])
        pos = seen[e]
        seen[e] += 1
        if pos >= capacity:
            continue  # dropped: output stays the zero vector
        h = np.maximum(x[t] @ params["w_in"][e] + params["b_in"][e], 0)
        out = h @ params["w_out"][e] + params["b_out"][e]
        y[t] = out * probs[t, e]
    return y


def main():
    comm = m4j.get_default_comm()
    rank, size = comm.rank(), comm.size()

    rng = np.random.RandomState(23)
    # gate with a dominant diagonal: token t of rank r routes to expert
    # (t + r) % size with a wide margin — routing is tie-free on every
    # jax version / precision
    w_gate = (rng.randn(D, size) * 0.01).astype(np.float32)
    for e in range(size):
        w_gate[e, e] += 5.0
    full = {
        "w_gate": w_gate,
        "w_in": (rng.randn(size, D, DFF) * 0.2).astype(np.float32),
        "b_in": (rng.randn(size, DFF) * 0.1).astype(np.float32),
        "w_out": (rng.randn(size, DFF, D) * 0.2).astype(np.float32),
        "b_out": (rng.randn(size, D) * 0.1).astype(np.float32),
    }
    xs = (rng.randn(size, T, D) * 0.1).astype(np.float32)
    for r in range(size):
        for t in range(T):
            xs[r, t, (t + r) % size] += 3.0

    params = {
        "w_gate": jnp.asarray(full["w_gate"]),
        "w_in": jnp.asarray(full["w_in"][rank]),
        "b_in": jnp.asarray(full["b_in"][rank]),
        "w_out": jnp.asarray(full["w_out"][rank]),
        "b_out": jnp.asarray(full["b_out"][rank]),
    }
    x = jnp.asarray(xs[rank])

    # balanced routing, no drops (T/size tokens per expert < capacity)
    cap = moe.expert_capacity(T, size)
    want = _reference(full, xs[rank], size, cap)
    exact = moe.moe_ffn(x, params, comm=comm)
    np.testing.assert_allclose(np.asarray(exact), want, rtol=1e-4,
                               atol=1e-5)

    # quantized dispatch/combine: same primitive, same schedule,
    # different wire — exact in the virtual world, int8-bounded live
    approx = moe.moe_ffn(x, params, comm=comm, compression="int8")
    np.testing.assert_allclose(np.asarray(approx), want, rtol=1e-1,
                               atol=0.2)

    # forced hierarchical schedule: a pure permutation on the wire,
    # bit-identical values to the exact run
    hier = moe.moe_ffn(x, params, comm=comm, algo="halltoall")
    np.testing.assert_allclose(np.asarray(hier), np.asarray(exact),
                               rtol=1e-6, atol=1e-6)

    # tight capacity drops the overflow token per expert: the dropped
    # outputs are exactly zero, the kept ones match the reference
    cap_tight = moe.expert_capacity(T, size, 0.5)
    assert cap_tight < T // size, (cap_tight, T // size)
    want_tight = _reference(full, xs[rank], size, cap_tight)
    tight = moe.moe_ffn(x, params, comm=comm, capacity_factor=0.5)
    np.testing.assert_allclose(np.asarray(tight), want_tight, rtol=1e-4,
                               atol=1e-5)

    print("moe_ops OK", flush=True)


if __name__ == "__main__":
    main()
