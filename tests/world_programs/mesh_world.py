"""Tier composition: world ranks that each own a multi-device mesh.

This is the actual TPU-pod shape — ICI collectives inside ``shard_map``
within a process's device slice, world-tier (DCN/host) ops across
processes — composed in ONE jitted step (SURVEY.md §7 hard part 4:
"mixing ICI collectives with host MPI without deadlock").

Run as np=2 world ranks with a 4-virtual-device CPU mesh per rank:

    XLA_FLAGS=--xla_force_host_platform_device_count=4 \
    python -m mpi4jax_tpu.runtime.launch -n 2 tests/world_programs/mesh_world.py

Composition contract (documented in DESIGN.md):

- JAX refuses ORDERED effects in a multi-device computation, so these
  programs trace inside ``mpi4jax_tpu.explicit_token_ordering()`` —
  world ops bind with the unordered effect and ordering is carried by
  EXPLICIT token chains (the reference's primary L1 token design,
  docs/sharp-bits.rst there).  Every world op must be threaded.
- mesh-tier collectives live inside ``shard_map`` regions and order
  freely within the rank's local device slice;
- world-tier ops sit OUTSIDE ``shard_map`` at the jit level, in the
  token-chain order, identical on every rank.

Phase 2 is the torture variant: an asymmetric send/recv chain
interleaved with mesh collectives inside a scanned jit — a broken token
chain deadlocks or corrupts the potato (the composition analog of the
reference's hot-potato, test_notoken.py:81-120 there).
"""

import os
import sys

sys.path.insert(
    0,
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
)

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402

import mpi4jax_tpu as m4j  # noqa: E402
from mpi4jax_tpu.compat import token_api as tk  # noqa: E402

comm = m4j.get_default_comm()
rank, size = comm.rank(), comm.size()
assert size == 2, "this program composes np=2 world ranks"
ndev = len(jax.devices())
assert ndev >= 4, f"need 4 local devices per rank, have {ndev}"
mesh = Mesh(np.array(jax.devices()[:4]), ("d",))


def local_psum(v):
    return jax.lax.psum(v, "d")


def shard_psum(v):
    return jax.shard_map(
        local_psum, mesh=mesh, in_specs=P("d"), out_specs=P()
    )(v)


with m4j.explicit_token_ordering():
    # -- phase 1: mesh psum + world allreduce in one jitted step ------
    @jax.jit
    def step(x):
        y = shard_psum(x)
        out, _ = tk.allreduce(y, op=m4j.SUM, comm=comm)
        return out

    x = jnp.arange(8.0) + rank
    out = np.asarray(step(x))
    # psum over 4 shards of 2: [0+2+4+6, 1+3+5+7] + 4*rank; world-sum
    # over the 2 ranks adds both rank offsets: [24+4, 32+4]
    np.testing.assert_allclose(out, np.array([28.0, 36.0]))

    # -- phase 2: torture — asymmetric world chain x mesh work --------
    K = 6

    @jax.jit
    def torture(x):
        def body(carry, _):
            token = tk.create_token(carry)
            if rank == 0:
                token = tk.send(carry, dest=1, tag=101, comm=comm,
                                token=token)
                got, token = tk.recv(jnp.zeros_like(carry), source=1,
                                     tag=202, comm=comm, token=token)
            else:
                got, token = tk.recv(jnp.zeros_like(carry), source=0,
                                     tag=101, comm=comm, token=token)
                # local mesh work ON the potato between the two world ops
                got = jnp.tile(shard_psum(got) / 4.0 + 1.0, 4)
                token = tk.send(got, dest=0, tag=202, comm=comm,
                                token=token)
            return got, ()

        out, _ = jax.lax.scan(body, x, None, length=K)
        return out

    t = np.asarray(torture(jnp.ones((8,), jnp.float32)))
    # host replay: each round rank 1 averages the psum back down
    ref = np.ones(8, np.float32)
    for _ in range(K):
        s = ref.reshape(4, 2).sum(axis=0) / 4.0 + 1.0
        ref = np.tile(s, 4)
    np.testing.assert_allclose(t, ref, rtol=1e-6)

    # -- phase 3: world collective chain around mesh regions ----------
    @jax.jit
    def mixed(x):
        a, token = tk.bcast(x, root=0, comm=comm)
        b = shard_psum(a)
        c, token = tk.allgather(b, comm=comm, token=token)
        out, _ = tk.allreduce(jnp.sum(c, axis=0), op=m4j.MAX, comm=comm,
                              token=token)
        return out

    xr = (jnp.arange(8.0) if rank == 0 else jnp.zeros(8))
    got = np.asarray(mixed(xr))
    base = np.arange(8.0).reshape(4, 2).sum(axis=0)  # [12, 16]
    np.testing.assert_allclose(got, 2 * base)

    # -- phase 4: TRAINING through the composition (VERDICT r4 #2) ----
    # cross-slice data-parallel grad: mesh psum + world allreduce in one
    # jitted loss, differentiated end to end.  The token-operand
    # allreduce carries the reference L1 JVP/transpose (SUM, flag-flip
    # identity), so jax.grad flows through both tiers.
    @jax.jit
    def loss_fn(x):
        y = shard_psum(x)                      # (2,) per rank
        z, _ = tk.allreduce(y, op=m4j.SUM, comm=comm)
        return jnp.sum(z * z)

    xg = jnp.arange(8.0) + rank
    g = np.asarray(jax.grad(loss_fn)(xg))
    # z = [28, 36] (phase 1); dL/dz = 2z; allreduce transpose =
    # identity; psum transpose broadcasts back over the 4 shards
    np.testing.assert_allclose(
        g, np.tile(2.0 * np.array([28.0, 36.0]), 4))

    # value_and_grad in the same jitted step, with a world op chained
    # AFTER the differentiated one (token continuity under AD)
    @jax.jit
    def loss2(w, x):
        z, token = tk.allreduce(shard_psum(x) * w, op=m4j.SUM, comm=comm)
        # a non-differentiated MAX op chained after the SUM (its tangent
        # is symbolically zero via stop_gradient — must not raise)
        s, _ = tk.allreduce(jax.lax.stop_gradient(jnp.sum(z)),
                            op=m4j.MAX, comm=comm, token=token)
        return jnp.sum(z) + 0.0 * s

    val, gw = jax.value_and_grad(loss2)(2.0, xg)
    # z = 2*(y0+y1) elementwise; sum(z) = 2*64
    np.testing.assert_allclose(float(val), 128.0)
    # identity-transpose contract (reference allreduce.py:206-218):
    # jax.grad yields the rank-LOCAL partial d(sum z)/dw = sum(y_rank) —
    # cross-rank terms enter when the grad itself is allreduced, the
    # standard DP closing step
    np.testing.assert_allclose(float(gw), 28.0 + 8.0 * rank)
    gw_global, _ = tk.allreduce(jnp.asarray(gw), op=m4j.SUM, comm=comm)
    np.testing.assert_allclose(float(gw_global), 64.0)  # = d/dw of the
    # global loss — matches the single-process value of the same model

    # -- phase 5: double-transpose identity in explicit-token mode ----
    def ar(v):
        out, _ = tk.allreduce(v, op=m4j.SUM, comm=comm)
        return out

    v0 = jnp.arange(4.0) + rank
    t_fn = jax.linear_transpose(ar, v0)
    (ct1,) = t_fn(v0)            # transpose = identity pass, per rank
    np.testing.assert_allclose(np.asarray(ct1), np.asarray(v0))
    tt_fn = jax.linear_transpose(lambda c: t_fn(c)[0], v0)
    (ct2,) = tt_fn(v0)           # transpose(transpose) = allreduce
    np.testing.assert_allclose(
        np.asarray(ct2), 2 * np.arange(4.0) + 1.0)  # sum over 2 ranks

    # sendrecv transpose in explicit-token mode: the cotangent rides
    # the reversed edge (reference sendrecv.py:390-409)
    def ring(v):
        out, _ = tk.sendrecv(
            v, source=(rank - 1) % size, dest=(rank + 1) % size,
            comm=comm)
        return out

    st_fn = jax.linear_transpose(ring, v0)
    (sct,) = st_fn(jnp.full((4,), float(rank + 1)))
    # fwd edge r->r+1; cotangent flows back: this rank receives the
    # cotangent held by the rank it SENT to (rank+1), i.e. rank+2's...
    # value: rank+1's ct payload = (rank+1 % size)+1
    np.testing.assert_allclose(
        np.asarray(sct), float(((rank + 1) % size) + 1))

print(f"mesh_world OK r{rank}", flush=True)
