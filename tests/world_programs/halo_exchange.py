"""Halo exchange on a 1-D ring: the stencil-code communication shape.

Each rank owns a strip and exchanges boundary halos with both
neighbors via ``sendrecv`` (the reorder-safe combined op), then applies
a 3-point stencil whose result depends on both halos.  Two independent
sendrecvs (disjoint channels) form one concurrency group in the
execution plan; values are checked against a numpy reference of the
same global stencil.  Bit-identical with the plan on or off.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4j

STRIP = 4096


def main():
    comm = m4j.get_default_comm()
    rank, size = comm.rank(), comm.size()
    assert size >= 2, "run under the launcher with -n >= 2"

    strip = (jnp.arange(STRIP, dtype=jnp.float32) + rank * STRIP)

    for step in range(2):
        # halo to the right neighbor / from the left, then the mirror —
        # two sendrecvs on disjoint channels (one group in the plan)
        from_left = m4j.sendrecv(strip[-1:], shift=1, comm=comm,
                                 sendtag=20 + step)
        from_right = m4j.sendrecv(strip[:1], shift=-1, comm=comm,
                                  sendtag=40 + step)
        left = jnp.concatenate([from_left, strip[:-1]])
        right = jnp.concatenate([strip[1:], from_right])
        strip = 0.25 * left + 0.5 * strip + 0.25 * right

    # numpy reference over the assembled global ring
    world = np.arange(STRIP * size, dtype=np.float32)
    for _ in range(2):
        world = (0.25 * np.roll(world, 1) + 0.5 * world
                 + 0.25 * np.roll(world, -1))
    mine = world[rank * STRIP:(rank + 1) * STRIP]
    np.testing.assert_allclose(np.asarray(strip), mine, rtol=1e-6)

    print(f"rank {rank}: halo_exchange OK", flush=True)


if __name__ == "__main__":
    main()
