"""Live re-tuning epoch rendezvous — the agreement pattern, proved clean.

Models ``mpi4jax_tpu.live._swap.SwapProtocol`` at the jax op level so the
match simulator can verify the protocol shape: every rank, at every P-th
collective boundary, joins a fixed-size header bcast from rank 0; the
*received* header — not any local state — decides whether a second
(payload) bcast follows.  Because the branch condition is itself the
product of a collective, every rank takes the same branch at the same
boundary: the rendezvous can never split the world.  The analyzer must
find nothing (kinds []).

The divergent variant (epoch_rendezvous_divergent.py) breaks exactly this
invariant — one rank consults local state instead of the header — and must
be flagged.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4j

PERIOD = 4      # rendezvous every 4th collective boundary
STEPS = 16
PROPOSE_AT = 8  # rank 0 has a pending table at this boundary


def main():
    comm = m4j.get_default_comm()
    rank, size = comm.rank(), comm.size()
    assert size >= 2, "run under the launcher with -n >= 2"

    epoch = 0
    installed = None
    x = jnp.arange(8, dtype=jnp.int32) + 1
    for step in range(1, STEPS + 1):
        out = m4j.allreduce(x + step, op=m4j.SUM, comm=comm)
        np.testing.assert_array_equal(
            np.asarray(out), (np.arange(8) + 1 + step) * size)
        if step % PERIOD:
            continue

        # --- header bcast: (proposed_epoch, payload_len), root 0 ---
        if rank == 0 and step == PROPOSE_AT and epoch == 0:
            payload = np.frombuffer(
                json.dumps({"allreduce": [[0, "rd"]]}).encode(),
                dtype=np.uint8)
            hdr = jnp.asarray([epoch + 1, payload.size], dtype=jnp.int32)
        else:
            payload = None
            hdr = jnp.asarray([epoch, 0], dtype=jnp.int32)
        hdr = m4j.bcast(hdr, root=0, comm=comm)
        new_epoch, nbytes = int(hdr[0]), int(hdr[1])
        if new_epoch <= epoch or nbytes <= 0:
            continue

        # --- payload bcast: every rank decided from the SAME header ---
        buf = (jnp.asarray(payload) if rank == 0
               else jnp.zeros((nbytes,), dtype=jnp.uint8))
        buf = m4j.bcast(buf, root=0, comm=comm)
        installed = json.loads(np.asarray(buf).tobytes().decode())
        epoch = new_epoch

    assert epoch == 1, epoch
    assert installed == {"allreduce": [[0, "rd"]]}, installed
    print(f"epoch_rendezvous rank {rank} epoch {epoch}", flush=True)


if __name__ == "__main__":
    main()
