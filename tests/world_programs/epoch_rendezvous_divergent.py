"""Broken epoch rendezvous — payload branch decided from LOCAL state.

The one rule of the live swap protocol (epoch_rendezvous.py, and the real
implementation in ``mpi4jax_tpu.live._swap``) is that the payload-bcast
branch is decided by the *received* header, so every rank takes it
together.  This variant has non-root ranks consult a local "I have seen
no proposal" flag instead: rank 0 proceeds into the payload bcast while
everyone else moves on to the next allreduce.  The analyzer must flag the
split (collective_mismatch) — the native transport would abort here, and
a build without fail-fast would deadlock or silently corrupt the table.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m4j

PERIOD = 4
STEPS = 16
PROPOSE_AT = 8


def main():
    comm = m4j.get_default_comm()
    rank, size = comm.rank(), comm.size()
    assert size >= 2, "run under the launcher with -n >= 2"

    epoch = 0
    local_saw_proposal = False  # the bug: never updated off the wire
    x = jnp.arange(8, dtype=jnp.int32) + 1
    for step in range(1, STEPS + 1):
        m4j.allreduce(x + step, op=m4j.SUM, comm=comm)
        if step % PERIOD:
            continue

        if rank == 0 and step == PROPOSE_AT and epoch == 0:
            payload = np.frombuffer(
                json.dumps({"allreduce": [[0, "rd"]]}).encode(),
                dtype=np.uint8)
            hdr = jnp.asarray([epoch + 1, payload.size], dtype=jnp.int32)
        else:
            payload = None
            hdr = jnp.asarray([epoch, 0], dtype=jnp.int32)
        hdr = m4j.bcast(hdr, root=0, comm=comm)
        new_epoch, nbytes = int(hdr[0]), int(hdr[1])

        # BUG: non-root ranks ignore the header they just received and
        # gate the payload bcast on local state -> rank 0 enters the
        # payload collective alone.
        take = (new_epoch > epoch and nbytes > 0) if rank == 0 \
            else local_saw_proposal
        if not take:
            continue
        buf = (jnp.asarray(payload) if rank == 0
               else jnp.zeros((nbytes,), dtype=jnp.uint8))
        m4j.bcast(buf, root=0, comm=comm)
        epoch = new_epoch

    print("UNREACHABLE" if rank == 0 else "UNREACHABLE-OK", flush=True)


if __name__ == "__main__":
    main()
