# Top-level convenience targets.  The native transport's own build
# lives in native/Makefile (make -C native ...); this file carries the
# repo-wide CI gates.

PY ?= python

# Analyzer + schedule-compiler gate over tests/world_programs/: every
# manifest program must verify with exactly its expected finding kinds,
# compile to a PROVED execution plan, and (where a golden is checked
# in) match it byte-for-byte.  Wired as a tier-1 test
# (tests/test_verify_corpus.py); run it directly after changing the
# analyzer, the planner, or any corpus program.  After an INTENTIONAL
# plan-semantics change: make update-goldens, review the diff, commit.
verify-corpus:
	$(PY) tools/verify_corpus.py

update-goldens:
	$(PY) tools/verify_corpus.py --update-goldens

# Large-np verification gate (tools/scale_harness.py): the committed
# golden plans are re-verified as np-parametric schedule families on
# the 8→512 rank ladder — symbolic quotient vs concrete matcher
# differential, every plan PROVED at 512 via the class-rotation
# prover, simulator oracles and joint-tuner sanity at 512 — and the
# evidence lands in BENCH_verifier_scale.json (review + commit after
# an intentional analyzer/prover change).  Import-light: runs on any
# host, jax or not.  Wired as a tier-1 test
# (tests/test_verify_scale.py, --quick ladder under a wall budget).
verify-scale:
	$(PY) tools/scale_harness.py

# sanitizer builds of the native transport (tests/test_sanitizers.py:
# loopback pairs, the progress engine, the elastic shrink-under-load
# three-rank scenario, and the self-heal reconnect pairs all run
# against these builds — 0 reports required)
tsan asan:
	$(MAKE) -C native $@

# chaos fault matrix for the self-healing link layer: every cell of
# {reset,drop,delay,corrupt} x {URING 0/1} x {shm on/off} x
# {engine on/off} must heal bit-identically or escalate loudly — no
# hangs, no silent corruption (tools/chaos_matrix.py)
chaos:
	$(MAKE) -C native libtpucomm-noffi
	$(PY) tools/chaos_matrix.py

.PHONY: verify-corpus update-goldens verify-scale tsan asan chaos
