/* tpucomm_ffi — typed XLA FFI custom-call handlers for the world tier.
 *
 * The native fast path replacing the Python host-callback hop: each world
 * tier primitive lowers (on the cpu platform) to a stablehlo.custom_call
 * whose handler decodes buffers/attributes here and dispatches straight
 * into the tpucomm transport (tpucomm.cc).  This is the C++ analog of the
 * reference's Cython custom-call decoders
 * (/root/reference/mpi4jax/_src/xla_bridge/mpi_xla_bridge_cpu.pyx:20-209,
 * SURVEY.md §2.3) — scalar params travel as custom-call *attributes*
 * (the modern FFI idiom) instead of operand buffers.
 *
 * Ordering: every handler takes and returns an XLA token, threaded by the
 * framework's ordered CommEffect (ops/_world_impl.py), so program order of
 * world ops is preserved exactly as with the callback path.
 *
 * Fail-fast: a nonzero transport return prints the same diagnostic the
 * Python bridge does ("tpucomm_<Op> returned error code N") and hard-exits,
 * matching runtime/bridge.py::_abort and the reference's abort_on_error →
 * MPI_Abort contract (mpi_xla_bridge.pyx:67-91 there).
 */

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <unistd.h>

#include "xla/ffi/api/ffi.h"

#include "tpucomm.h"

namespace ffi = xla::ffi;

namespace {

/* XLA FFI element type → tpucomm wire code (utils/dtypes.py order). */
int wire_dtype(ffi::DataType dt) {
  switch (dt) {
    case ffi::DataType::PRED: return TPU_BOOL;
    case ffi::DataType::S8:   return TPU_I8;
    case ffi::DataType::S16:  return TPU_I16;
    case ffi::DataType::S32:  return TPU_I32;
    case ffi::DataType::S64:  return TPU_I64;
    case ffi::DataType::U8:   return TPU_U8;
    case ffi::DataType::U16:  return TPU_U16;
    case ffi::DataType::U32:  return TPU_U32;
    case ffi::DataType::U64:  return TPU_U64;
    case ffi::DataType::F16:  return TPU_F16;
    case ffi::DataType::BF16: return TPU_BF16;
    case ffi::DataType::F32:  return TPU_F32;
    case ffi::DataType::F64:  return TPU_F64;
    case ffi::DataType::C64:  return TPU_C64;
    case ffi::DataType::C128: return TPU_C128;
    default:                  return -1;
  }
}

/* Same fail-fast contract as runtime/bridge.py::_abort: diagnostic line on
 * stderr, then hard exit; peers observe dead sockets and abort in turn. */
void check_abort(const char* opname, int rc) {
  if (rc != 0) {
    std::fprintf(stderr, "tpucomm_%s returned error code %d\n", opname, rc);
    std::fflush(stderr);
    _exit(1);
  }
}

ffi::Error bad_dtype() {
  return ffi::Error::InvalidArgument(
      "tpucomm ffi: unsupported element type for reduction");
}

/* ---------------- reductions ---------------- */

ffi::Error AllreduceImpl(ffi::Token, ffi::AnyBuffer x,
                         ffi::Result<ffi::Token>,
                         ffi::Result<ffi::AnyBuffer> out,
                         int64_t comm, int32_t op) {
  int dt = wire_dtype(x.element_type());
  if (dt < 0) return bad_dtype();
  check_abort("Allreduce",
              tpucomm_allreduce(comm, x.untyped_data(), out->untyped_data(),
                                (int64_t)x.element_count(), dt, op));
  return ffi::Error::Success();
}

ffi::Error ReduceImpl(ffi::Token, ffi::AnyBuffer x,
                      ffi::Result<ffi::Token>,
                      ffi::Result<ffi::AnyBuffer> out,
                      int64_t comm, int32_t op, int32_t root) {
  int dt = wire_dtype(x.element_type());
  if (dt < 0) return bad_dtype();
  check_abort("Reduce",
              tpucomm_reduce(comm, x.untyped_data(), out->untyped_data(),
                             (int64_t)x.element_count(), dt, op, root));
  return ffi::Error::Success();
}

ffi::Error ScanImpl(ffi::Token, ffi::AnyBuffer x,
                    ffi::Result<ffi::Token>,
                    ffi::Result<ffi::AnyBuffer> out,
                    int64_t comm, int32_t op) {
  int dt = wire_dtype(x.element_type());
  if (dt < 0) return bad_dtype();
  check_abort("Scan",
              tpucomm_scan(comm, x.untyped_data(), out->untyped_data(),
                           (int64_t)x.element_count(), dt, op));
  return ffi::Error::Success();
}

/* ---------------- data movement ---------------- */

ffi::Error BcastImpl(ffi::Token, ffi::AnyBuffer x,
                     ffi::Result<ffi::Token>,
                     ffi::Result<ffi::AnyBuffer> out,
                     int64_t comm, int32_t root) {
  /* in-place collective on the output (bridge.py::bcast copies first;
   * under jit the operand is usually aliased onto the result) */
  if (out->untyped_data() != x.untyped_data())
    std::memcpy(out->untyped_data(), x.untyped_data(), x.size_bytes());
  check_abort("Bcast", tpucomm_bcast(comm, out->untyped_data(),
                                     (int64_t)out->size_bytes(), root));
  return ffi::Error::Success();
}

ffi::Error AllgatherImpl(ffi::Token, ffi::AnyBuffer x,
                         ffi::Result<ffi::Token>,
                         ffi::Result<ffi::AnyBuffer> out,
                         int64_t comm) {
  check_abort("Allgather",
              tpucomm_allgather(comm, x.untyped_data(),
                                (int64_t)x.size_bytes(),
                                out->untyped_data()));
  return ffi::Error::Success();
}

ffi::Error GatherImpl(ffi::Token, ffi::AnyBuffer x,
                      ffi::Result<ffi::Token>,
                      ffi::Result<ffi::AnyBuffer> out,
                      int64_t comm, int32_t root) {
  /* rank-dependent result (bridge.py::gather): root's out is the full
   * (size, ...) stack; non-root's out is x-shaped and gets the input
   * back (exact reference contract, gather.py:213-226 there; the native
   * call ignores recvbuf off-root) */
  if (tpucomm_rank(comm) != root && out->untyped_data() != x.untyped_data())
    std::memcpy(out->untyped_data(), x.untyped_data(),
                (size_t)x.size_bytes());
  check_abort("Gather",
              tpucomm_gather(comm, x.untyped_data(), (int64_t)x.size_bytes(),
                             out->untyped_data(), root));
  return ffi::Error::Success();
}

ffi::Error ScatterImpl(ffi::Token, ffi::AnyBuffer x,
                       ffi::Result<ffi::Token>,
                       ffi::Result<ffi::AnyBuffer> out,
                       int64_t comm, int32_t root) {
  check_abort("Scatter",
              tpucomm_scatter(comm, x.untyped_data(), out->untyped_data(),
                              (int64_t)out->size_bytes(), root));
  return ffi::Error::Success();
}

ffi::Error AlltoallImpl(ffi::Token, ffi::AnyBuffer x,
                        ffi::Result<ffi::Token>,
                        ffi::Result<ffi::AnyBuffer> out,
                        int64_t comm) {
  int64_t rows = x.dimensions()[0];
  int64_t chunk = rows ? (int64_t)x.size_bytes() / rows : 0;
  check_abort("Alltoall", tpucomm_alltoall(comm, x.untyped_data(),
                                           out->untyped_data(), chunk));
  return ffi::Error::Success();
}

/* ---------------- point-to-point / sync ---------------- */

ffi::Error BarrierImpl(ffi::Token, ffi::Result<ffi::Token>,
                       ffi::Result<ffi::AnyBuffer> out, int64_t comm) {
  check_abort("Barrier", tpucomm_barrier(comm));
  std::memset(out->untyped_data(), 0, out->size_bytes());
  return ffi::Error::Success();
}

ffi::Error SendImpl(ffi::Token, ffi::AnyBuffer x,
                    ffi::Result<ffi::Token>,
                    ffi::Result<ffi::AnyBuffer> out,
                    int64_t comm, int32_t dest, int32_t tag) {
  check_abort("Send", tpucomm_send(comm, x.untyped_data(),
                                   (int64_t)x.size_bytes(), dest, tag));
  std::memset(out->untyped_data(), 0, out->size_bytes());
  return ffi::Error::Success();
}

ffi::Error RecvImpl(ffi::Token, ffi::AnyBuffer /* shape carrier */,
                    ffi::Result<ffi::Token>,
                    ffi::Result<ffi::AnyBuffer> out,
                    int64_t comm, int32_t source, int32_t tag) {
  check_abort("Recv", tpucomm_recv(comm, out->untyped_data(),
                                   (int64_t)out->size_bytes(), source, tag));
  return ffi::Error::Success();
}

ffi::Error Shift2Impl(ffi::Token, ffi::AnyBuffer x,
                      ffi::Result<ffi::Token>,
                      ffi::Result<ffi::AnyBuffer> out,
                      int64_t comm, int32_t lo, int32_t hi, int32_t tag) {
  /* x/out: (2, ...) stacked strips — [to_lo|to_hi] in, [from_lo|from_hi]
   * out; see tpucomm_shift2 */
  check_abort("Shift2",
              tpucomm_shift2(comm, x.untyped_data(), out->untyped_data(),
                             (int64_t)x.size_bytes() / 2, lo, hi, tag));
  return ffi::Error::Success();
}

ffi::Error SendrecvImpl(ffi::Token, ffi::AnyBuffer x,
                        ffi::Result<ffi::Token>,
                        ffi::Result<ffi::AnyBuffer> out,
                        int64_t comm, int32_t source, int32_t dest,
                        int32_t tag) {
  check_abort("Sendrecv",
              tpucomm_sendrecv(comm, x.untyped_data(),
                               (int64_t)x.size_bytes(), dest,
                               out->untyped_data(),
                               (int64_t)out->size_bytes(), source, tag));
  return ffi::Error::Success();
}

/* ---------------- token-operand variants (explicit-token mode) ------
 *
 * Same transport calls, but the ordering token is a real uint32 scalar
 * OPERAND and RESULT (the reference's L1 wire format, allreduce.py:
 * 101-104 there) instead of an XLA token: in explicit-token mode the
 * data edge THROUGH the call is the ordering contract, and it must
 * survive every XLA pass — these replace the ~150 us/op Python host
 * callback with the ~1 us native path (docs/benchmarks.md, dispatch
 * profile). */

void relay_token(const ffi::AnyBuffer& tok,
                 ffi::Result<ffi::AnyBuffer>& tok_out) {
  if (tok_out->untyped_data() != tok.untyped_data())
    std::memcpy(tok_out->untyped_data(), tok.untyped_data(),
                (size_t)tok.size_bytes());
}

ffi::Error AllreduceTokImpl(ffi::AnyBuffer x, ffi::AnyBuffer tok,
                            ffi::Result<ffi::AnyBuffer> out,
                            ffi::Result<ffi::AnyBuffer> tok_out,
                            int64_t comm, int32_t op) {
  relay_token(tok, tok_out);
  int dt = wire_dtype(x.element_type());
  if (dt < 0) return bad_dtype();
  check_abort("Allreduce",
              tpucomm_allreduce(comm, x.untyped_data(), out->untyped_data(),
                                (int64_t)x.element_count(), dt, op));
  return ffi::Error::Success();
}

ffi::Error ReduceTokImpl(ffi::AnyBuffer x, ffi::AnyBuffer tok,
                         ffi::Result<ffi::AnyBuffer> out,
                         ffi::Result<ffi::AnyBuffer> tok_out,
                         int64_t comm, int32_t op, int32_t root) {
  relay_token(tok, tok_out);
  int dt = wire_dtype(x.element_type());
  if (dt < 0) return bad_dtype();
  check_abort("Reduce",
              tpucomm_reduce(comm, x.untyped_data(), out->untyped_data(),
                             (int64_t)x.element_count(), dt, op, root));
  return ffi::Error::Success();
}

ffi::Error ScanTokImpl(ffi::AnyBuffer x, ffi::AnyBuffer tok,
                       ffi::Result<ffi::AnyBuffer> out,
                       ffi::Result<ffi::AnyBuffer> tok_out,
                       int64_t comm, int32_t op) {
  relay_token(tok, tok_out);
  int dt = wire_dtype(x.element_type());
  if (dt < 0) return bad_dtype();
  check_abort("Scan",
              tpucomm_scan(comm, x.untyped_data(), out->untyped_data(),
                           (int64_t)x.element_count(), dt, op));
  return ffi::Error::Success();
}

ffi::Error BcastTokImpl(ffi::AnyBuffer x, ffi::AnyBuffer tok,
                        ffi::Result<ffi::AnyBuffer> out,
                        ffi::Result<ffi::AnyBuffer> tok_out,
                        int64_t comm, int32_t root) {
  relay_token(tok, tok_out);
  if (out->untyped_data() != x.untyped_data())
    std::memcpy(out->untyped_data(), x.untyped_data(), x.size_bytes());
  check_abort("Bcast", tpucomm_bcast(comm, out->untyped_data(),
                                     (int64_t)out->size_bytes(), root));
  return ffi::Error::Success();
}

ffi::Error AllgatherTokImpl(ffi::AnyBuffer x, ffi::AnyBuffer tok,
                            ffi::Result<ffi::AnyBuffer> out,
                            ffi::Result<ffi::AnyBuffer> tok_out,
                            int64_t comm) {
  relay_token(tok, tok_out);
  check_abort("Allgather",
              tpucomm_allgather(comm, x.untyped_data(),
                                (int64_t)x.size_bytes(),
                                out->untyped_data()));
  return ffi::Error::Success();
}

ffi::Error GatherTokImpl(ffi::AnyBuffer x, ffi::AnyBuffer tok,
                         ffi::Result<ffi::AnyBuffer> out,
                         ffi::Result<ffi::AnyBuffer> tok_out,
                         int64_t comm, int32_t root) {
  relay_token(tok, tok_out);
  if (tpucomm_rank(comm) != root && out->untyped_data() != x.untyped_data())
    std::memcpy(out->untyped_data(), x.untyped_data(),
                (size_t)x.size_bytes());
  check_abort("Gather",
              tpucomm_gather(comm, x.untyped_data(), (int64_t)x.size_bytes(),
                             out->untyped_data(), root));
  return ffi::Error::Success();
}

ffi::Error ScatterTokImpl(ffi::AnyBuffer x, ffi::AnyBuffer tok,
                          ffi::Result<ffi::AnyBuffer> out,
                          ffi::Result<ffi::AnyBuffer> tok_out,
                          int64_t comm, int32_t root) {
  relay_token(tok, tok_out);
  check_abort("Scatter",
              tpucomm_scatter(comm, x.untyped_data(), out->untyped_data(),
                              (int64_t)out->size_bytes(), root));
  return ffi::Error::Success();
}

ffi::Error AlltoallTokImpl(ffi::AnyBuffer x, ffi::AnyBuffer tok,
                           ffi::Result<ffi::AnyBuffer> out,
                           ffi::Result<ffi::AnyBuffer> tok_out,
                           int64_t comm) {
  relay_token(tok, tok_out);
  int64_t rows = x.dimensions()[0];
  int64_t chunk = rows ? (int64_t)x.size_bytes() / rows : 0;
  check_abort("Alltoall", tpucomm_alltoall(comm, x.untyped_data(),
                                           out->untyped_data(), chunk));
  return ffi::Error::Success();
}

ffi::Error BarrierTokImpl(ffi::AnyBuffer tok,
                          ffi::Result<ffi::AnyBuffer> out,
                          ffi::Result<ffi::AnyBuffer> tok_out,
                          int64_t comm) {
  relay_token(tok, tok_out);
  check_abort("Barrier", tpucomm_barrier(comm));
  std::memset(out->untyped_data(), 0, out->size_bytes());
  return ffi::Error::Success();
}

ffi::Error SendTokImpl(ffi::AnyBuffer x, ffi::AnyBuffer tok,
                       ffi::Result<ffi::AnyBuffer> out,
                       ffi::Result<ffi::AnyBuffer> tok_out,
                       int64_t comm, int32_t dest, int32_t tag) {
  relay_token(tok, tok_out);
  check_abort("Send", tpucomm_send(comm, x.untyped_data(),
                                   (int64_t)x.size_bytes(), dest, tag));
  std::memset(out->untyped_data(), 0, out->size_bytes());
  return ffi::Error::Success();
}

ffi::Error RecvTokImpl(ffi::AnyBuffer /* shape carrier */, ffi::AnyBuffer tok,
                       ffi::Result<ffi::AnyBuffer> out,
                       ffi::Result<ffi::AnyBuffer> tok_out,
                       int64_t comm, int32_t source, int32_t tag) {
  relay_token(tok, tok_out);
  check_abort("Recv", tpucomm_recv(comm, out->untyped_data(),
                                   (int64_t)out->size_bytes(), source, tag));
  return ffi::Error::Success();
}

ffi::Error Shift2TokImpl(ffi::AnyBuffer x, ffi::AnyBuffer tok,
                         ffi::Result<ffi::AnyBuffer> out,
                         ffi::Result<ffi::AnyBuffer> tok_out,
                         int64_t comm, int32_t lo, int32_t hi, int32_t tag) {
  relay_token(tok, tok_out);
  check_abort("Shift2",
              tpucomm_shift2(comm, x.untyped_data(), out->untyped_data(),
                             (int64_t)x.size_bytes() / 2, lo, hi, tag));
  return ffi::Error::Success();
}

ffi::Error SendrecvTokImpl(ffi::AnyBuffer x, ffi::AnyBuffer tok,
                           ffi::Result<ffi::AnyBuffer> out,
                           ffi::Result<ffi::AnyBuffer> tok_out,
                           int64_t comm, int32_t source, int32_t dest,
                           int32_t tag) {
  relay_token(tok, tok_out);
  check_abort("Sendrecv",
              tpucomm_sendrecv(comm, x.untyped_data(),
                               (int64_t)x.size_bytes(), dest,
                               out->untyped_data(),
                               (int64_t)out->size_bytes(), source, tag));
  return ffi::Error::Success();
}

}  // namespace

/* Handler symbols, loaded by runtime/bridge.py via ctypes and registered
 * with jax.ffi.register_ffi_target (≙ the reference's
 * xla_client.register_custom_call_target loop, xla_bridge/__init__.py:26-31
 * there). */

#define TPUCOMM_BIND() ffi::Ffi::Bind().Arg<ffi::Token>()

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommAllreduceFfi, AllreduceImpl,
    TPUCOMM_BIND().Arg<ffi::AnyBuffer>()
        .Ret<ffi::Token>().Ret<ffi::AnyBuffer>()
        .Attr<int64_t>("comm").Attr<int32_t>("op"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommReduceFfi, ReduceImpl,
    TPUCOMM_BIND().Arg<ffi::AnyBuffer>()
        .Ret<ffi::Token>().Ret<ffi::AnyBuffer>()
        .Attr<int64_t>("comm").Attr<int32_t>("op").Attr<int32_t>("root"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommScanFfi, ScanImpl,
    TPUCOMM_BIND().Arg<ffi::AnyBuffer>()
        .Ret<ffi::Token>().Ret<ffi::AnyBuffer>()
        .Attr<int64_t>("comm").Attr<int32_t>("op"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommBcastFfi, BcastImpl,
    TPUCOMM_BIND().Arg<ffi::AnyBuffer>()
        .Ret<ffi::Token>().Ret<ffi::AnyBuffer>()
        .Attr<int64_t>("comm").Attr<int32_t>("root"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommAllgatherFfi, AllgatherImpl,
    TPUCOMM_BIND().Arg<ffi::AnyBuffer>()
        .Ret<ffi::Token>().Ret<ffi::AnyBuffer>()
        .Attr<int64_t>("comm"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommGatherFfi, GatherImpl,
    TPUCOMM_BIND().Arg<ffi::AnyBuffer>()
        .Ret<ffi::Token>().Ret<ffi::AnyBuffer>()
        .Attr<int64_t>("comm").Attr<int32_t>("root"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommScatterFfi, ScatterImpl,
    TPUCOMM_BIND().Arg<ffi::AnyBuffer>()
        .Ret<ffi::Token>().Ret<ffi::AnyBuffer>()
        .Attr<int64_t>("comm").Attr<int32_t>("root"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommAlltoallFfi, AlltoallImpl,
    TPUCOMM_BIND().Arg<ffi::AnyBuffer>()
        .Ret<ffi::Token>().Ret<ffi::AnyBuffer>()
        .Attr<int64_t>("comm"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommBarrierFfi, BarrierImpl,
    TPUCOMM_BIND()
        .Ret<ffi::Token>().Ret<ffi::AnyBuffer>()
        .Attr<int64_t>("comm"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommSendFfi, SendImpl,
    TPUCOMM_BIND().Arg<ffi::AnyBuffer>()
        .Ret<ffi::Token>().Ret<ffi::AnyBuffer>()
        .Attr<int64_t>("comm").Attr<int32_t>("dest").Attr<int32_t>("tag"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommRecvFfi, RecvImpl,
    TPUCOMM_BIND().Arg<ffi::AnyBuffer>()
        .Ret<ffi::Token>().Ret<ffi::AnyBuffer>()
        .Attr<int64_t>("comm").Attr<int32_t>("source").Attr<int32_t>("tag"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommShift2Ffi, Shift2Impl,
    TPUCOMM_BIND().Arg<ffi::AnyBuffer>()
        .Ret<ffi::Token>().Ret<ffi::AnyBuffer>()
        .Attr<int64_t>("comm").Attr<int32_t>("lo").Attr<int32_t>("hi")
        .Attr<int32_t>("tag"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommSendrecvFfi, SendrecvImpl,
    TPUCOMM_BIND().Arg<ffi::AnyBuffer>()
        .Ret<ffi::Token>().Ret<ffi::AnyBuffer>()
        .Attr<int64_t>("comm").Attr<int32_t>("source").Attr<int32_t>("dest")
        .Attr<int32_t>("tag"));

/* token-operand variants: (data..., u32 token) -> (out, u32 token') */
#define TPUCOMM_TOK_BIND() \
  ffi::Ffi::Bind().Arg<ffi::AnyBuffer>().Arg<ffi::AnyBuffer>() \
      .Ret<ffi::AnyBuffer>().Ret<ffi::AnyBuffer>()

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommAllreduceTokFfi, AllreduceTokImpl,
    TPUCOMM_TOK_BIND().Attr<int64_t>("comm").Attr<int32_t>("op"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommReduceTokFfi, ReduceTokImpl,
    TPUCOMM_TOK_BIND().Attr<int64_t>("comm").Attr<int32_t>("op")
        .Attr<int32_t>("root"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommScanTokFfi, ScanTokImpl,
    TPUCOMM_TOK_BIND().Attr<int64_t>("comm").Attr<int32_t>("op"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommBcastTokFfi, BcastTokImpl,
    TPUCOMM_TOK_BIND().Attr<int64_t>("comm").Attr<int32_t>("root"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommAllgatherTokFfi, AllgatherTokImpl,
    TPUCOMM_TOK_BIND().Attr<int64_t>("comm"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommGatherTokFfi, GatherTokImpl,
    TPUCOMM_TOK_BIND().Attr<int64_t>("comm").Attr<int32_t>("root"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommScatterTokFfi, ScatterTokImpl,
    TPUCOMM_TOK_BIND().Attr<int64_t>("comm").Attr<int32_t>("root"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommAlltoallTokFfi, AlltoallTokImpl,
    TPUCOMM_TOK_BIND().Attr<int64_t>("comm"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommBarrierTokFfi, BarrierTokImpl,
    ffi::Ffi::Bind().Arg<ffi::AnyBuffer>()
        .Ret<ffi::AnyBuffer>().Ret<ffi::AnyBuffer>()
        .Attr<int64_t>("comm"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommSendTokFfi, SendTokImpl,
    TPUCOMM_TOK_BIND().Attr<int64_t>("comm").Attr<int32_t>("dest")
        .Attr<int32_t>("tag"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommRecvTokFfi, RecvTokImpl,
    TPUCOMM_TOK_BIND().Attr<int64_t>("comm").Attr<int32_t>("source")
        .Attr<int32_t>("tag"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommShift2TokFfi, Shift2TokImpl,
    TPUCOMM_TOK_BIND().Attr<int64_t>("comm").Attr<int32_t>("lo")
        .Attr<int32_t>("hi").Attr<int32_t>("tag"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TpucommSendrecvTokFfi, SendrecvTokImpl,
    TPUCOMM_TOK_BIND().Attr<int64_t>("comm").Attr<int32_t>("source")
        .Attr<int32_t>("dest").Attr<int32_t>("tag"));
